"""Chaos acceptance test (ISSUE 2): a seeded fault-ridden training run —
injected loader IO errors, one NaN step (divergence rollback), one simulated
SIGTERM (preemption save + mid-epoch resume) — must reach the SAME final
TrainState digest as a clean run of the same seed, with every recovery event
visible in the telemetry metrics.jsonl and `mgproto-telemetry summarize`.

Fast, CPU, fully seeded: runs in tier-1 under the `chaos` marker.
"""

import json
import os

import numpy as np
import pytest
from PIL import Image

from mgproto_tpu.cli.train import run_training
from mgproto_tpu.config import DataConfig, tiny_test_config
from mgproto_tpu.resilience import preemption
from mgproto_tpu.resilience.chaos import ChaosPlan, ChaosState
from mgproto_tpu.utils.checkpoint import (
    find_latest_checkpoint,
    list_checkpoints,
    load_metadata,
    pytree_digest,
)

pytestmark = pytest.mark.chaos


def _make_folder(root, num_classes=4, per_class=6, size=40, seed=0):
    rng = np.random.RandomState(seed)
    for c in range(num_classes):
        d = os.path.join(root, f"{c:03d}.class_{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.randint(0, 255, size=(size, size, 3), dtype=np.uint8)
            arr = np.clip(arr * 0.3 + c * 50, 0, 255)
            Image.fromarray(arr.astype(np.uint8)).save(
                os.path.join(d, f"img_{i}.jpg")
            )


@pytest.fixture(scope="module")
def data_root(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("chaos_data"))
    _make_folder(os.path.join(root, "train"))  # 24 imgs -> 3 steps @ batch 8
    _make_folder(os.path.join(root, "test"), per_class=3, seed=1)
    return root


def _cfg(data_root, model_dir):
    import dataclasses

    cfg = tiny_test_config()
    return cfg.replace(
        data=DataConfig(
            train_dir=os.path.join(data_root, "train"),
            test_dir=os.path.join(data_root, "test"),
            train_push_dir=os.path.join(data_root, "train"),
            train_batch_size=8,
            test_batch_size=8,
            train_push_batch_size=8,
            num_workers=2,
        ),
        # no push (orthogonal machinery; keeps the chaos run tight), prune
        # tail still runs — 2 epochs x 3 steps, global steps 0..5
        schedule=dataclasses.replace(cfg.schedule, push_start=99),
        model_dir=model_dir,
    )


def test_chaos_run_converges_to_clean_state(data_root, tmp_path):
    # -------------------------------------------------------------- clean run
    clean_state, clean_accu = run_training(
        _cfg(data_root, str(tmp_path / "clean")), telemetry=False
    )
    clean_digest = pytree_digest(clean_state)

    # -------------------------------------------------------------- chaos run
    # one ChaosState across BOTH invocations: its one-shot bookkeeping is the
    # fault schedule's memory (a rollback replay / resume must not re-inject)
    chaos = ChaosState(ChaosPlan(
        seed=0,
        loader_io_rate=0.3,          # transient: heals on first retry
        loader_io_fail_attempts=1,
        nan_at_step=3,               # epoch 1, batch 0 -> divergence rollback
        preempt_at_step=4,           # epoch 1 -> preemption save + marker
    ))
    cfg = _cfg(data_root, str(tmp_path / "chaos"))
    telem1 = str(tmp_path / "telem1")
    state1, _ = run_training(
        cfg,
        target_accu=-1.0,            # save every epoch (rollback anchors)
        telemetry_dir=telem1,
        max_bad_steps=1,             # roll back on the first bad step
        divergence_check_every=1,
        chaos=chaos,
    )
    handler = preemption.get_handler()
    assert handler.requested(), "chaos preemption never fired"

    # the preempted invocation left a marker + a mid-epoch preempt checkpoint
    marker = preemption.read_marker(cfg.model_dir)
    assert marker is not None and marker["epoch"] == 1
    latest = find_latest_checkpoint(cfg.model_dir)
    meta = load_metadata(latest)
    assert meta["stage"] == "preempt" and meta["epoch"] == 1
    assert 0 < meta["batch_in_epoch"] < 3  # genuinely mid-epoch

    # recovery events visible in the telemetry snapshots (acceptance)
    snapshots = [
        json.loads(l)
        for l in open(os.path.join(telem1, "metrics.jsonl"))
    ]
    last = snapshots[-1]["metrics"]

    def total(name):
        return sum(
            s["value"] for s in last.get(name, {}).get("series", [])
        )

    assert total("train_skipped_steps_total") >= 1   # the NaN step
    assert total("train_rollbacks_total") == 1
    assert total("preemption_saves_total") == 1
    assert total("resilience_retries_total") >= 1    # loader IO healing
    assert total("loader_sentinel_rows_total") == 0  # transient, not dropped
    assert total("chaos_injections_total") >= 3

    # ... and in the summarize subcommand's output (text + json)
    from mgproto_tpu.cli.telemetry import render_table, summarize

    summary = summarize(telem1)
    res = summary["resilience"]
    assert res["train_rollbacks_total"] == 1
    assert res["preemption_saves_total"] == 1
    assert res["train_skipped_steps_total"] >= 1
    table = render_table(summary)
    assert "resilience (recovery events)" in table
    assert "preemption_saves_total" in table

    # ------------------------------------------------------------ resumed run
    state2, accu2 = run_training(
        cfg,
        resume="auto",
        target_accu=-1.0,
        telemetry_dir=str(tmp_path / "telem2"),
        max_bad_steps=1,
        divergence_check_every=1,
        chaos=chaos,
    )
    assert not preemption.get_handler().requested()
    assert preemption.read_marker(cfg.model_dir) is None  # resume cleared it

    # the headline acceptance: bit-exact convergence with the clean run
    assert pytree_digest(state2) == clean_digest
    assert accu2 == pytest.approx(clean_accu)
    assert int(state2.step) == int(clean_state.step) == 6

    # the chaos model_dir ends with a complete stage trajectory
    stages = {c[1] for c in list_checkpoints(cfg.model_dir)}
    assert {"nopush", "preempt", "prune"} <= stages


def test_clean_run_resume_auto_reports_complete(data_root, tmp_path):
    """A finished run resumed with --resume auto short-circuits on the prune
    checkpoint (guard rails around the new mid-epoch resume logic)."""
    cfg = _cfg(data_root, str(tmp_path / "run"))
    state, accu = run_training(cfg, target_accu=-1.0, telemetry=False)
    state2, accu2 = run_training(
        cfg, resume="auto", target_accu=-1.0, telemetry=False
    )
    assert accu2 == pytest.approx(accu)
    assert pytree_digest(state2) == pytree_digest(state)
