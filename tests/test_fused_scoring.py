"""Fused Pallas scoring kernel vs the unfused XLA path (interpret mode on CPU).

The fused kernel must reproduce ops/gaussian.py + ops/pooling.py exactly:
same top-T values, same indices (incl. lowest-index tie-breaks), and the same
feature gradient as differentiating through the unfused density + top_k."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mgproto_tpu.ops.fused_scoring import score_pool
from mgproto_tpu.ops.gaussian import diag_gaussian_log_prob

B, HW, D, C, K, T = 3, 49, 16, 5, 4, 6


def _setup(seed=0):
    rng = np.random.default_rng(seed)
    feat = jnp.asarray(rng.normal(size=(B, HW, D)).astype(np.float32))
    feat = feat / jnp.linalg.norm(feat, axis=-1, keepdims=True)
    means = jnp.asarray(rng.normal(size=(C, K, D)).astype(np.float32))
    sigmas = jnp.full((C, K, D), 0.4, jnp.float32)
    return feat, means, sigmas


def _unfused(feat, means, sigmas):
    lp = diag_gaussian_log_prob(feat.reshape(-1, D), means, sigmas)
    lp = lp.reshape(B, HW, C * K).transpose(0, 2, 1)  # [B, P, HW]
    vals, idx = jax.lax.top_k(lp, T)
    return vals, idx


def test_forward_matches_unfused():
    feat, means, sigmas = _setup()
    vals_f, idx_f = score_pool(feat, means, sigmas, T, 1e-10, True)
    vals_u, idx_u = _unfused(feat, means, sigmas)
    np.testing.assert_allclose(np.asarray(vals_f), np.asarray(vals_u), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(idx_f), np.asarray(idx_u))


def test_forward_tie_break_lowest_index():
    # identical patches -> tied densities; both paths must pick low indices
    feat = jnp.ones((1, 8, D), jnp.float32) / np.sqrt(D)
    rng = np.random.default_rng(1)
    means = jnp.asarray(rng.normal(size=(1, 2, D)).astype(np.float32))
    sigmas = jnp.full((1, 2, D), 0.4, jnp.float32)
    _, idx = score_pool(feat, means, sigmas, 3, 1e-10, True)
    np.testing.assert_array_equal(np.asarray(idx[0, :, :]), [[0, 1, 2], [0, 1, 2]])


def test_gradient_matches_unfused():
    feat, means, sigmas = _setup(2)
    w = jnp.asarray(
        np.random.default_rng(3).normal(size=(B, C * K, T)).astype(np.float32)
    )

    def loss_fused(f):
        vals, _ = score_pool(f, means, sigmas, T, 1e-10, True)
        return jnp.sum(vals * w)

    def loss_unfused(f):
        vals, _ = _unfused(f, means, sigmas)
        return jnp.sum(vals * w)

    gf = jax.grad(loss_fused)(feat)
    gu = jax.grad(loss_unfused)(feat)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gu), rtol=1e-4, atol=1e-5)


def test_prototype_gradients_are_zero():
    """The kernel's contract: prototypes are EM-trained constants
    (reference model.py:264-265 detaches them in compute_log_prob)."""
    feat, means, sigmas = _setup(4)

    def loss(m, s):
        vals, _ = score_pool(feat, m, s, T, 1e-10, True)
        return jnp.sum(vals)

    gm, gs = jax.grad(loss, argnums=(0, 1))(means, sigmas)
    assert float(jnp.abs(gm).max()) == 0.0
    assert float(jnp.abs(gs).max()) == 0.0


def test_padding_is_inert():
    """P not a multiple of the tile and T not a multiple of 8: padded slots
    must never leak into results."""
    rng = np.random.default_rng(5)
    feat = jnp.asarray(rng.normal(size=(2, 10, 8)).astype(np.float32))
    means = jnp.asarray(rng.normal(size=(3, 1, 8)).astype(np.float32))  # P=3
    sigmas = jnp.full((3, 1, 8), 0.4, jnp.float32)
    vals, idx = score_pool(feat, means, sigmas, 5, 1e-10, True)
    assert vals.shape == (2, 3, 5) and idx.shape == (2, 3, 5)
    assert np.all(np.isfinite(np.asarray(vals)))
    assert np.asarray(idx).min() >= 0 and np.asarray(idx).max() < 10


def test_train_step_fused_matches_unfused():
    """End-to-end: one Trainer step with fused_scoring on/off must agree."""
    import dataclasses

    from mgproto_tpu.config import tiny_test_config
    from mgproto_tpu.engine.train import Trainer

    def run(fused):
        cfg = tiny_test_config()
        cfg = cfg.replace(
            model=dataclasses.replace(cfg.model, fused_scoring=fused)
        )
        tr = Trainer(cfg, steps_per_epoch=2)
        st = tr.init_state(jax.random.PRNGKey(0))
        imgs = jax.random.uniform(jax.random.PRNGKey(1), (4, 32, 32, 3))
        lbls = jnp.array([0, 1, 2, 3])
        st, m = tr.train_step(st, imgs, lbls, use_mine=True, update_gmm=True)
        return st, m

    s0, m0 = run(False)
    s1, m1 = run(True)
    np.testing.assert_allclose(float(m1.loss), float(m0.loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(s1.gmm.means), np.asarray(s0.gmm.means), rtol=1e-4, atol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(s1.memory.length), np.asarray(s0.memory.length)
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(s0.params), jax.tree_util.tree_leaves(s1.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_fused_scoring_auto_resolution():
    """fused_scoring=None resolves per backend/mesh: off on CPU (this test's
    backend), on only for TPU with an unsharded class axis; explicit
    True/False is always honored (config.py:ModelConfig.fused_scoring)."""
    import dataclasses

    from mgproto_tpu.config import tiny_test_config
    from mgproto_tpu.engine.train import Trainer
    from mgproto_tpu.parallel import ShardedTrainer, make_mesh

    def with_fused(value):
        cfg = tiny_test_config()
        return cfg.replace(
            model=dataclasses.replace(cfg.model, fused_scoring=value)
        )

    assert jax.default_backend() == "cpu"  # conftest pins the CPU backend
    assert Trainer(with_fused(None), steps_per_epoch=1)._fused is False
    assert Trainer(with_fused(True), steps_per_epoch=1)._fused is True
    assert Trainer(with_fused(False), steps_per_epoch=1)._fused is False

    # class-sharded mesh: auto must stay on the XLA path (SPMD cannot
    # partition a pallas_call over the class axis); explicit True wins
    devices = jax.devices()[:4]
    mesh = make_mesh(data=2, model=2, devices=devices)
    assert ShardedTrainer(
        with_fused(None), steps_per_epoch=1, mesh=mesh
    )._fused is False
    assert ShardedTrainer(
        with_fused(True), steps_per_epoch=1, mesh=mesh
    )._fused is True


@pytest.mark.slow
def test_fused_step_partitions_over_data_sharded_mesh():
    """A forced-fused train step must execute AND preserve numerics under a
    data-sharded mesh (the TPU-pod data-parallel layout where the auto
    default keeps fused ON — parallel/trainer.py only falls back to the XLA
    path for class-sharded meshes). Interpret-mode pallas on the virtual CPU
    mesh; the same partitioning question on real Mosaic is covered by the
    on-hardware suite when a chip is available."""
    import dataclasses

    from mgproto_tpu.config import tiny_test_config
    from mgproto_tpu.engine.train import Trainer
    from mgproto_tpu.parallel import ShardedTrainer, make_mesh

    cfg = tiny_test_config().replace(
        model=dataclasses.replace(tiny_test_config().model, fused_scoring=True)
    )
    mesh = make_mesh(data=8, model=1, devices=jax.devices()[:8])
    sharded = ShardedTrainer(cfg, steps_per_epoch=1, mesh=mesh)
    single = Trainer(cfg, steps_per_epoch=1)
    assert sharded._fused and single._fused

    state0 = single.init_state(jax.random.PRNGKey(0))
    imgs = np.random.RandomState(0).rand(
        16, cfg.model.img_size, cfg.model.img_size, 3
    ).astype(np.float32)
    lbls = np.random.RandomState(1).randint(
        0, cfg.model.num_classes, size=(16,)
    ).astype(np.int32)

    s_sh, m_sh = sharded.train_step(
        sharded.prepare(state0), imgs, lbls,
        use_mine=True, update_gmm=True, warm=False,
    )
    s_1, m_1 = single.train_step(
        state0, jnp.asarray(imgs), jnp.asarray(lbls),
        use_mine=True, update_gmm=True, warm=False,
    )
    np.testing.assert_allclose(
        float(jax.device_get(m_sh.loss)), float(m_1.loss), rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(jax.device_get(s_sh.gmm.means)), np.asarray(s_1.gmm.means),
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(s_sh.memory.length)),
        np.asarray(s_1.memory.length),
    )
