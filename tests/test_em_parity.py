"""EM reference-parity: measure (not just claim) how closely the vmapped
all-class `em_update` tracks the reference's per-class-loop EM.

The oracle reimplements the reference `update_GMM` semantics fresh in torch
(/root/reference/model.py:277-401 + main.py:223-229): python loop over
classes; per class, `num_em_loop` rounds of E-step → smoothed responsibilities
→ one torch-Adam step on the responsibility-weighted NLL + diversity cost,
where the Adam instance holds the FULL [C,K,d] means tensor (so zero-grad
classes still drift under moment decay — the documented optimizer artifact,
core/em.py:12-19) → tau-momentum priors.

Known, deliberate deviations measured here (core/em.py docstring):
  * ours takes ONE Adam step per EM round for ALL classes vs the reference's
    one step per (class, round) — different Adam step counts / bias
    correction;
  * ours pins inactive classes' means exactly; the reference lets them drift.

The test quantifies both: trajectories must agree to ~1e-2 while the means
move ~100x that, and priors must track tightly."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from mgproto_tpu.config import EMConfig
from mgproto_tpu.core.em import em_update, make_mean_optimizer
from mgproto_tpu.core.memory import init_memory
from mgproto_tpu.core.mgproto import GMMState

C, K, D, N = 3, 4, 6, 32
SIGMA = 1.0 / np.sqrt(2.0 * np.pi)
ROUNDS = 10
CFG = EMConfig(num_em_loop=3, alpha=0.1, tau=0.99, diversity_lambda=1.0,
               mean_lr=3e-3, update_interval=1)


def _synthetic_bank(rng):
    """Per class: N feats drawn near K/2 cluster centers on the unit sphere."""
    feats = np.zeros((C, N, D), np.float32)
    for c in range(C):
        centers = rng.normal(size=(K // 2, D))
        centers /= np.linalg.norm(centers, axis=-1, keepdims=True)
        for i in range(N):
            v = centers[i % len(centers)] + 0.15 * rng.normal(size=D)
            feats[c, i] = v / np.linalg.norm(v)
    return feats


def _init_means(rng):
    m = rng.uniform(size=(C, K, D)).astype(np.float32)
    return m / np.linalg.norm(m, axis=-1, keepdims=True)


def _torch_reference_em(feats, means0, priors0, rounds):
    """Reference update_GMM semantics, written fresh (see module docstring)."""
    torch = pytest.importorskip("torch")
    eps = 1e-10
    means = torch.tensor(means0, dtype=torch.float64, requires_grad=True)
    opt = torch.optim.Adam([means], lr=CFG.mean_lr)
    priors = torch.tensor(priors0, dtype=torch.float64)
    x_all = torch.tensor(feats, dtype=torch.float64)
    sigma = torch.full((K, D), SIGMA, dtype=torch.float64)

    def log_density(x, mu):
        # reference _estimate_log_prob (model.py:323-336); var holds the STD
        quad = (((x[:, None, :] - mu[None]) / (sigma + eps)) ** 2).sum(-1)
        log_sig = torch.log(sigma + eps).sum(-1)
        return -0.5 * D * np.log(2 * np.pi) - log_sig[None, :] - 0.5 * quad

    eye = 1.0 - torch.eye(K, dtype=torch.float64)
    for _ in range(rounds):
        for c in range(C):
            pi_old = priors[c].clone()
            x = x_all[c]
            for _i in range(CFG.num_em_loop):
                with torch.no_grad():
                    weighted = log_density(x, means[c]) + torch.log(pi_old + eps)
                    log_resp = weighted - torch.logsumexp(
                        weighted, dim=1, keepdim=True
                    )
                resp = torch.exp(log_resp)
                resp = (resp + CFG.alpha) / (resp + CFG.alpha).sum(1, keepdim=True)
                pi_unnorm = resp.sum(0) + eps

                ll = log_density(x, means[c]) + torch.log(pi_old + eps)
                weighted_nll = -(resp * ll).sum(1).mean(0)
                mu = means[c]
                pd = ((mu[:, None, :] - mu[None, :, :]) ** 2).sum(-1)
                diversity = (torch.exp(-pd) * eye).sum() / eye.sum()
                loss = weighted_nll + CFG.diversity_lambda * diversity
                opt.zero_grad()
                loss.backward()
                opt.step()  # updates the WHOLE [C,K,d] tensor (torch Adam)

                pi_new = pi_unnorm / x.shape[0]
                pi_old = CFG.tau * pi_old + (1.0 - CFG.tau) * pi_new
            priors[c] = pi_old.detach()
    return means.detach().numpy(), priors.numpy()


def _ours_em(feats, means0, priors0, rounds):
    gmm = GMMState(
        means=jnp.asarray(means0),
        sigmas=jnp.full((C, K, D), SIGMA, jnp.float32),
        priors=jnp.asarray(priors0),
        keep=jnp.ones((C, K), bool),
    )
    mem = init_memory(C, N, D)
    mem = mem._replace(
        feats=jnp.asarray(feats),
        length=jnp.full((C,), N, mem.length.dtype),
        updated=jnp.ones((C,), bool),
    )
    tx = make_mean_optimizer(CFG)
    opt_state = tx.init(gmm.means)
    for _ in range(rounds):
        gmm, mem, opt_state, _aux = em_update(gmm, mem, opt_state, tx, CFG)
        mem = mem._replace(updated=jnp.ones((C,), bool))  # re-touch all
    return np.asarray(gmm.means), np.asarray(gmm.priors)


def test_em_update_tracks_reference_trajectory():
    rng = np.random.RandomState(0)
    feats = _synthetic_bank(rng)
    means0 = _init_means(rng)
    priors0 = np.full((C, K), 1.0 / K, np.float32)

    ref_means, ref_priors = _torch_reference_em(feats, means0, priors0, ROUNDS)
    got_means, got_priors = _ours_em(feats, means0, priors0, ROUNDS)

    # Measured deviation profile (this test's reason to exist): the reference
    # applies the optimizer to every class's slice at every per-class step —
    # 3 gradient steps PLUS ~3*(C-1) momentum-decay applications per class per
    # round — so its means move ~1.5x further per round than ours (3 gradient
    # steps, exact pinning elsewhere). Direction is the modeling content and
    # must agree tightly; magnitude differs by that bookkeeping factor.
    ref_d = (ref_means - means0).reshape(-1)
    got_d = (got_means - means0).reshape(-1)
    movement = np.abs(ref_d).mean()
    assert movement > 5e-3, f"oracle barely moved ({movement:.2e}): bad setup"

    cos = ref_d @ got_d / (np.linalg.norm(ref_d) * np.linalg.norm(got_d))
    assert cos > 0.95, f"displacement direction diverged: cosine={cos:.4f}"
    for c in range(C):
        for k in range(K):
            a = (ref_means - means0)[c, k]
            b = (got_means - means0)[c, k]
            ck = a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12)
            assert ck > 0.9, f"proto ({c},{k}) direction cosine {ck:.3f}"

    ratio = np.abs(got_d).mean() / movement
    assert 0.4 < ratio < 1.1, f"movement ratio {ratio:.3f} out of family"
    gap = np.abs(got_means - ref_means).mean()
    assert gap < 0.5 * movement, (
        f"means diverged from reference: gap={gap:.3e} vs movement={movement:.3e}"
    )

    # priors ride the identical E-step/smoothing/momentum math: tight
    np.testing.assert_allclose(got_priors, ref_priors, atol=5e-3)
    np.testing.assert_allclose(got_priors.sum(-1), 1.0, atol=0.05)


def test_em_inactive_classes_pinned_vs_reference_drift():
    """Measures the ONE deliberate deviation: with class 0 never touched,
    ours pins its means bit-exactly; the reference's Adam-moment decay drifts
    them (core/em.py:12-19)."""
    rng = np.random.RandomState(1)
    feats = _synthetic_bank(rng)
    means0 = _init_means(rng)
    priors0 = np.full((C, K), 1.0 / K, np.float32)

    gmm = GMMState(
        means=jnp.asarray(means0),
        sigmas=jnp.full((C, K, D), SIGMA, jnp.float32),
        priors=jnp.asarray(priors0),
        keep=jnp.ones((C, K), bool),
    )
    mem = init_memory(C, N, D)
    updated = jnp.asarray([False, True, True])
    mem = mem._replace(
        feats=jnp.asarray(feats),
        length=jnp.full((C,), N, mem.length.dtype),
        updated=updated,
    )
    tx = make_mean_optimizer(CFG)
    opt_state = tx.init(gmm.means)
    for _ in range(5):
        gmm, mem, opt_state, aux = em_update(gmm, mem, opt_state, tx, CFG)
        mem = mem._replace(updated=updated)
    assert int(aux.num_active) == 2
    np.testing.assert_array_equal(np.asarray(gmm.means[0]), means0[0])
    assert np.abs(np.asarray(gmm.means[1]) - means0[1]).mean() > 1e-3
    np.testing.assert_allclose(np.asarray(gmm.priors[0]), priors0[0])
