"""EM reference-parity: measure (not just claim) how closely the vmapped
all-class `em_update` tracks the reference's per-class-loop EM.

The oracle reimplements the reference `update_GMM` semantics fresh in torch
(/root/reference/model.py:277-401 + main.py:223-229): python loop over
classes; per class, `num_em_loop` rounds of E-step → smoothed responsibilities
→ one torch-Adam step on the responsibility-weighted NLL + diversity cost,
where the Adam instance holds the FULL [C,K,d] means tensor (so zero-grad
classes still drift under moment decay — the documented optimizer artifact,
core/em.py:12-19) → tau-momentum priors.

Known, deliberate deviations measured here (core/em.py docstring):
  * ours takes ONE Adam step per EM round for ALL classes vs the reference's
    one step per (class, round) — different Adam step counts / bias
    correction;
  * ours pins inactive classes' means exactly; the reference lets them drift.

The test quantifies both: trajectories must agree to ~1e-2 while the means
move ~100x that, and priors must track tightly."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from mgproto_tpu.config import EMConfig
from mgproto_tpu.core.em import em_update, make_mean_optimizer
from mgproto_tpu.core.memory import init_memory
from mgproto_tpu.core.mgproto import GMMState

C, K, D, N = 3, 4, 6, 32
SIGMA = 1.0 / np.sqrt(2.0 * np.pi)
ROUNDS = 10
CFG = EMConfig(num_em_loop=3, alpha=0.1, tau=0.99, diversity_lambda=1.0,
               mean_lr=3e-3, update_interval=1)


def _synthetic_bank(rng):
    """Per class: N feats drawn near K/2 cluster centers on the unit sphere."""
    feats = np.zeros((C, N, D), np.float32)
    for c in range(C):
        centers = rng.normal(size=(K // 2, D))
        centers /= np.linalg.norm(centers, axis=-1, keepdims=True)
        for i in range(N):
            v = centers[i % len(centers)] + 0.15 * rng.normal(size=D)
            feats[c, i] = v / np.linalg.norm(v)
    return feats


def _init_means(rng):
    m = rng.uniform(size=(C, K, D)).astype(np.float32)
    return m / np.linalg.norm(m, axis=-1, keepdims=True)


def _torch_reference_em(feats, means0, priors0, rounds, schedule=None):
    """Reference update_GMM semantics, written fresh (see module docstring).

    `schedule`: optional per-round boolean activity arrays [C]; an inactive
    class is skipped entirely (reference model.py:283 `continue`). Default:
    every class active every round."""
    torch = pytest.importorskip("torch")
    eps = 1e-10
    means = torch.tensor(means0, dtype=torch.float64, requires_grad=True)
    opt = torch.optim.Adam([means], lr=CFG.mean_lr)
    priors = torch.tensor(priors0, dtype=torch.float64)
    x_all = torch.tensor(feats, dtype=torch.float64)
    sigma = torch.full((K, D), SIGMA, dtype=torch.float64)

    def log_density(x, mu):
        # reference _estimate_log_prob (model.py:323-336); var holds the STD
        quad = (((x[:, None, :] - mu[None]) / (sigma + eps)) ** 2).sum(-1)
        log_sig = torch.log(sigma + eps).sum(-1)
        return -0.5 * D * np.log(2 * np.pi) - log_sig[None, :] - 0.5 * quad

    eye = 1.0 - torch.eye(K, dtype=torch.float64)
    for r in range(rounds):
        for c in range(C):
            if schedule is not None and not schedule[r][c]:
                continue
            pi_old = priors[c].clone()
            x = x_all[c]
            for _i in range(CFG.num_em_loop):
                with torch.no_grad():
                    weighted = log_density(x, means[c]) + torch.log(pi_old + eps)
                    log_resp = weighted - torch.logsumexp(
                        weighted, dim=1, keepdim=True
                    )
                resp = torch.exp(log_resp)
                resp = (resp + CFG.alpha) / (resp + CFG.alpha).sum(1, keepdim=True)
                pi_unnorm = resp.sum(0) + eps

                ll = log_density(x, means[c]) + torch.log(pi_old + eps)
                weighted_nll = -(resp * ll).sum(1).mean(0)
                mu = means[c]
                pd = ((mu[:, None, :] - mu[None, :, :]) ** 2).sum(-1)
                diversity = (torch.exp(-pd) * eye).sum() / eye.sum()
                loss = weighted_nll + CFG.diversity_lambda * diversity
                opt.zero_grad()
                loss.backward()
                opt.step()  # updates the WHOLE [C,K,d] tensor (torch Adam)

                pi_new = pi_unnorm / x.shape[0]
                pi_old = CFG.tau * pi_old + (1.0 - CFG.tau) * pi_new
            priors[c] = pi_old.detach()
    return means.detach().numpy(), priors.numpy()


def _ours_em(feats, means0, priors0, rounds):
    gmm = GMMState(
        means=jnp.asarray(means0),
        sigmas=jnp.full((C, K, D), SIGMA, jnp.float32),
        priors=jnp.asarray(priors0),
        keep=jnp.ones((C, K), bool),
    )
    mem = init_memory(C, N, D)
    mem = mem._replace(
        feats=jnp.asarray(feats),
        length=jnp.full((C,), N, mem.length.dtype),
        updated=jnp.ones((C,), bool),
    )
    tx = make_mean_optimizer(CFG)
    opt_state = tx.init(gmm.means)
    for _ in range(rounds):
        gmm, mem, opt_state, _aux = em_update(gmm, mem, opt_state, tx, CFG)
        mem = mem._replace(updated=jnp.ones((C,), bool))  # re-touch all
    return np.asarray(gmm.means), np.asarray(gmm.priors)


def test_em_update_tracks_reference_trajectory():
    rng = np.random.RandomState(0)
    feats = _synthetic_bank(rng)
    means0 = _init_means(rng)
    priors0 = np.full((C, K), 1.0 / K, np.float32)

    ref_means, ref_priors = _torch_reference_em(feats, means0, priors0, ROUNDS)
    got_means, got_priors = _ours_em(feats, means0, priors0, ROUNDS)

    # Measured deviation profile (this test's reason to exist): the reference
    # applies the optimizer to every class's slice at every per-class step —
    # 3 gradient steps PLUS ~3*(C-1) momentum-decay applications per class per
    # round — so its means move ~1.5x further per round than ours (3 gradient
    # steps, exact pinning elsewhere). Direction is the modeling content and
    # must agree tightly; magnitude differs by that bookkeeping factor.
    ref_d = (ref_means - means0).reshape(-1)
    got_d = (got_means - means0).reshape(-1)
    movement = np.abs(ref_d).mean()
    assert movement > 5e-3, f"oracle barely moved ({movement:.2e}): bad setup"

    cos = ref_d @ got_d / (np.linalg.norm(ref_d) * np.linalg.norm(got_d))
    assert cos > 0.95, f"displacement direction diverged: cosine={cos:.4f}"
    for c in range(C):
        for k in range(K):
            a = (ref_means - means0)[c, k]
            b = (got_means - means0)[c, k]
            ck = a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12)
            assert ck > 0.9, f"proto ({c},{k}) direction cosine {ck:.3f}"

    ratio = np.abs(got_d).mean() / movement
    assert 0.4 < ratio < 1.1, f"movement ratio {ratio:.3f} out of family"
    gap = np.abs(got_means - ref_means).mean()
    assert gap < 0.5 * movement, (
        f"means diverged from reference: gap={gap:.3e} vs movement={movement:.3e}"
    )

    # priors ride the identical E-step/smoothing/momentum math: tight
    np.testing.assert_allclose(got_priors, ref_priors, atol=5e-3)
    np.testing.assert_allclose(got_priors.sum(-1), 1.0, atol=0.05)


def test_em_inactive_classes_pinned_vs_reference_drift():
    """Measures the ONE deliberate deviation: with class 0 never touched,
    ours pins its means bit-exactly; the reference's Adam-moment decay drifts
    them (core/em.py:12-19)."""
    rng = np.random.RandomState(1)
    feats = _synthetic_bank(rng)
    means0 = _init_means(rng)
    priors0 = np.full((C, K), 1.0 / K, np.float32)

    gmm = GMMState(
        means=jnp.asarray(means0),
        sigmas=jnp.full((C, K, D), SIGMA, jnp.float32),
        priors=jnp.asarray(priors0),
        keep=jnp.ones((C, K), bool),
    )
    mem = init_memory(C, N, D)
    updated = jnp.asarray([False, True, True])
    mem = mem._replace(
        feats=jnp.asarray(feats),
        length=jnp.full((C,), N, mem.length.dtype),
        updated=updated,
    )
    tx = make_mean_optimizer(CFG)
    opt_state = tx.init(gmm.means)
    for _ in range(5):
        gmm, mem, opt_state, aux = em_update(gmm, mem, opt_state, tx, CFG)
        mem = mem._replace(updated=updated)
    assert int(aux.num_active) == 2
    np.testing.assert_array_equal(np.asarray(gmm.means[0]), means0[0])
    assert np.abs(np.asarray(gmm.means[1]) - means0[1]).mean() > 1e-3
    np.testing.assert_allclose(np.asarray(gmm.priors[0]), priors0[0])


def _ours_em_reference_mode(feats, means0, priors0, rounds, schedule=None):
    """Drive em_update in reference mode. `schedule`: optional per-call [C]
    activity arrays (mirrors _torch_reference_em's parameter)."""
    cfg = EMConfig(num_em_loop=CFG.num_em_loop, alpha=CFG.alpha, tau=CFG.tau,
                   diversity_lambda=CFG.diversity_lambda, mean_lr=CFG.mean_lr,
                   update_interval=1, reference_stepping=True)
    gmm = GMMState(
        means=jnp.asarray(means0),
        sigmas=jnp.full((C, K, D), SIGMA, jnp.float32),
        priors=jnp.asarray(priors0),
        keep=jnp.ones((C, K), bool),
    )
    mem = init_memory(C, N, D)
    mem = mem._replace(
        feats=jnp.asarray(feats),
        length=jnp.full((C,), N, mem.length.dtype),
    )
    tx = make_mean_optimizer(cfg)
    opt_state = tx.init(gmm.means)
    aux = None
    for r in range(rounds):
        touch = (jnp.ones((C,), bool) if schedule is None
                 else jnp.asarray(schedule[r]))
        mem = mem._replace(updated=touch)
        gmm, mem, opt_state, aux = em_update(gmm, mem, opt_state, tx, cfg)
    return np.asarray(gmm.means), np.asarray(gmm.priors), aux


def test_em_reference_stepping_matches_oracle_tightly():
    """reference_stepping=True must reproduce the torch bookkeeping itself —
    per-(class, round) Adam steps on the shared tensor — so the trajectory
    agreement is an order tighter than the default path's (which this file's
    first test bounds at cosine>0.95 / gap<0.5*movement)."""
    rng = np.random.RandomState(0)
    feats = _synthetic_bank(rng)
    means0 = _init_means(rng)
    priors0 = np.full((C, K), 1.0 / K, np.float32)

    ref_means, ref_priors = _torch_reference_em(feats, means0, priors0, ROUNDS)
    got_means, got_priors, aux = _ours_em_reference_mode(
        feats, means0, priors0, ROUNDS
    )
    assert int(aux.num_active) == C

    ref_d = (ref_means - means0).reshape(-1)
    got_d = (got_means - means0).reshape(-1)
    movement = np.abs(ref_d).mean()
    assert movement > 5e-3

    cos = ref_d @ got_d / (np.linalg.norm(ref_d) * np.linalg.norm(got_d))
    assert cos > 0.999, f"reference mode diverged: cosine={cos:.5f}"
    # magnitude now matches too (the default path's documented ~0.4-1.1
    # ratio band collapses to ~1)
    ratio = np.abs(got_d).mean() / movement
    assert 0.95 < ratio < 1.05, f"movement ratio {ratio:.4f}"
    gap = np.abs(got_means - ref_means).mean()
    assert gap < 0.05 * movement, f"gap={gap:.2e} vs movement={movement:.2e}"
    np.testing.assert_allclose(got_priors, ref_priors, atol=1e-3)


def test_em_reference_stepping_reproduces_inactive_drift():
    """The torch zero-grad moment-decay drift — which the default path
    deliberately pins away — must come BACK in reference mode.

    Drift requires nonzero Adam moments: a NEVER-active class has zero
    moments (zero grad forever → m stays 0) and does not move even in torch.
    The drifting scenario is active-then-inactive: class 0 runs EM in call 1
    (accumulating moments), then goes untouched — in torch its means keep
    moving during every other class's step while its priors stay frozen."""
    torch = pytest.importorskip("torch")
    del torch
    rng = np.random.RandomState(1)
    feats = _synthetic_bank(rng)
    means0 = _init_means(rng)
    priors0 = np.full((C, K), 1.0 / K, np.float32)
    # per-call activity: all on for call 0, class 0 off afterwards
    schedule = [np.array([True, True, True])] + [
        np.array([False, True, True])
    ] * 4

    ref_means, ref_priors = _torch_reference_em(
        feats, means0, priors0, 5, schedule=schedule
    )
    got_means, got_priors, aux = _ours_em_reference_mode(
        feats, means0, priors0, 5, schedule=schedule
    )
    assert int(aux.num_active) == 2

    # class 0 kept moving AFTER its last active call (drift, not pinning):
    # the oracle's endpoint differs from its state right after call 0
    ref_means_after_1, _ = _torch_reference_em(
        feats, means0, priors0, 1, schedule=schedule
    )
    drift_while_inactive = np.abs(ref_means[0] - ref_means_after_1[0]).mean()
    assert drift_while_inactive > 1e-5, "oracle did not drift: bad setup"
    np.testing.assert_allclose(
        got_means[0], ref_means[0], atol=5e-4,
        err_msg="inactive-class trajectory does not match torch",
    )
    # priors of the inactive class froze after its last active call, in both
    np.testing.assert_allclose(got_priors[0], ref_priors[0], atol=1e-3)
    np.testing.assert_allclose(got_priors, ref_priors, atol=1e-3)


def test_em_reference_stepping_inside_jitted_train_step():
    """The sequential path must compile and run inside the production jitted
    step (lax.cond + class scan + shared Adam state all under one jit)."""
    import dataclasses

    from mgproto_tpu.config import tiny_test_config
    from mgproto_tpu.engine.train import Trainer

    cfg = tiny_test_config()
    cfg = cfg.replace(em=dataclasses.replace(cfg.em, reference_stepping=True))
    tr = Trainer(cfg, steps_per_epoch=4)
    state = tr.init_state(jax.random.PRNGKey(0))
    from conftest import prefill_full_memory

    state = prefill_full_memory(state)
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(
        rng.rand(4, cfg.model.img_size, cfg.model.img_size, 3), jnp.float32
    )
    lbls = jnp.asarray(rng.randint(0, cfg.model.num_classes, 4), jnp.int32)
    m0 = np.asarray(state.gmm.means).copy()
    state, m = tr.train_step(state, imgs, lbls, use_mine=True, update_gmm=True)
    assert np.isfinite(float(m.loss))
    assert int(m.em_active) == cfg.model.num_classes
    assert np.abs(np.asarray(state.gmm.means) - m0).mean() > 1e-5
