"""Multi-tenant serving (ISSUE 17): one shared trunk, many MGProto heads.

The isolation story, each piece tested at its own layer:

  * admission — a tenant at quota sheds ITS OWN tail (typed
    `tenant_quota`), never another tenant's queued work, and `pop_batch`
    round-robins batch slots across lanes; with zero or one lane the pop
    path is the original FIFO (single-tenant parity at the unit level —
    the committed `evidence/load_test_baseline.json` regenerating
    byte-identical is the end-to-end proof);
  * directory — mounting a head costs head bytes + gate construction on
    a REAL clock (no trunk compiles: the engine's AOT key never sees the
    head), fair-share quota math, tenant-scoped blue/green that fails
    closed per tenant;
  * engine — per-request gating through the addressed tenant's head,
    typed `tenant_unmounted` reject for traffic at a missing head;
  * chaos — the MGPROTO_CHAOS_TENANT_* knobs parse from env and drive
    deterministically;
  * the tier-1 drill — `load_test.py --tenants N` under a quota storm
    with poisoned traffic, a sabotaged swap and a mid-storm mount, gated
    by `mgproto-telemetry check --tenants` whose verdicts re-derive from
    raw counts (tamper vectors prove the re-derivation bites);
  * lints — the serving/ walk reaches tenants.py BY CONSTRUCTION
    (violation-detection cases prove the walk bites, per lint policy).
"""

import dataclasses as dc
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

import jax

pytestmark = [pytest.mark.tenants, pytest.mark.serving]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EVIDENCE = os.path.join(REPO, "evidence")
sys.path.insert(0, os.path.join(REPO, "scripts"))

from load_test import run_load_test  # noqa: E402

from mgproto_tpu.config import tiny_test_config
from mgproto_tpu.engine.train import Trainer
from mgproto_tpu.resilience import chaos as chaos_mod
from mgproto_tpu.serving import metrics as sm
from mgproto_tpu.serving.admission import (
    SHED_TENANT_QUOTA,
    AdmissionQueue,
)
from mgproto_tpu.serving.calibration import Calibration, calibrate
from mgproto_tpu.serving.engine import (
    OUTCOME_ABSTAIN,
    OUTCOME_PREDICT,
    OUTCOME_REJECT,
    ServingEngine,
)
from mgproto_tpu.serving.tenants import (
    REASON_TENANT_UNMOUNTED,
    SWAP_COMMITTED,
    TenantDirectory,
    head_fingerprint,
    head_nbytes,
)
from mgproto_tpu.telemetry.registry import (
    MetricRegistry,
    set_current_registry,
)


@pytest.fixture(autouse=True)
def fresh_registry():
    prev = set_current_registry(MetricRegistry())
    yield
    set_current_registry(prev)


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_test_config()
    trainer = Trainer(cfg, steps_per_epoch=1)
    state = trainer.init_state(jax.random.PRNGKey(0))
    return cfg, trainer, state


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _calib(seed=3, n=200):
    rng = np.random.RandomState(seed)
    scores = rng.randn(n) - 2.0
    logits = rng.randn(n, 4)
    return Calibration.from_scores(scores, logits, f"fp-{seed}")


def _id_batches(cfg, n_batches=2, bs=4, seed=0):
    rng = np.random.RandomState(seed)
    return [
        (
            rng.rand(bs, cfg.model.img_size, cfg.model.img_size, 3).astype(
                np.float32
            ),
            rng.randint(0, cfg.model.num_classes, (bs,)).astype(np.int32),
        )
        for _ in range(n_batches)
    ]


def _payloads(cfg, n=4, seed=7):
    rng = np.random.RandomState(seed)
    return [
        rng.rand(cfg.model.img_size, cfg.model.img_size, 3).astype(np.float32)
        for _ in range(n)
    ]


# ------------------------------------------------------- tenant admission
class TestTenantAdmission:
    def test_no_tenant_path_is_plain_fifo(self):
        """Single-tenant parity at the unit level: without tenant ids the
        queue is the original bounded FIFO — no lanes, no quota checks."""
        q = AdmissionQueue(capacity=8, clock=FakeClock())
        for i in range(5):
            req, shed = q.submit(i, request_id=f"r{i}")
            assert shed is None and req.tenant is None
        assert q.tenant_depths() == {}
        assert [r.request_id for r in q.pop_batch(5)] == [
            f"r{i}" for i in range(5)
        ]

    def test_one_lane_pop_is_fifo(self):
        q = AdmissionQueue(capacity=8, clock=FakeClock())
        for i in range(4):
            q.submit(i, request_id=f"a{i}", tenant="a", quota=8)
        assert [r.request_id for r in q.pop_batch(4)] == [
            f"a{i}" for i in range(4)
        ]

    def test_quota_sheds_own_tail_only(self):
        q = AdmissionQueue(capacity=16, clock=FakeClock())
        q.submit("b0", request_id="b0", tenant="b", quota=8)
        for i in range(2):
            _, shed = q.submit(f"a{i}", request_id=f"a{i}", tenant="a",
                               quota=2)
            assert shed is None
        req, shed = q.submit("a2", request_id="a2", tenant="a", quota=2)
        assert shed == SHED_TENANT_QUOTA and req.request_id == "a2"
        # b's queued entry was never a candidate, and b can still submit
        assert q.tenant_depths() == {"a": 2, "b": 1}
        _, shed = q.submit("b1", request_id="b1", tenant="b", quota=8)
        assert shed is None
        assert sm.counter(sm.TENANT_SHED).value(
            tenant="a", reason=SHED_TENANT_QUOTA
        ) == 1

    def test_quota_deadline_aware_within_share(self):
        """At quota the tenant's own EXPIRED entries free room first —
        the newcomer is only shed when the share is full of live work."""
        clock = FakeClock()
        q = AdmissionQueue(capacity=16, clock=clock)
        q.submit("a0", request_id="a0", tenant="a", quota=2, deadline_s=0.5)
        q.submit("a1", request_id="a1", tenant="a", quota=2, deadline_s=10.0)
        clock.advance(1.0)  # a0 is now past its deadline
        req, shed = q.submit("a2", request_id="a2", tenant="a", quota=2,
                             deadline_s=10.0)
        assert shed is None
        shed_ids = {r.request_id for r in q.drain_shed()}
        assert shed_ids == {"a0"}
        assert q.tenant_depths() == {"a": 2}

    def test_pop_batch_fair_share_round_robins_lanes(self):
        q = AdmissionQueue(capacity=16, clock=FakeClock())
        for i in range(4):
            q.submit(f"a{i}", request_id=f"a{i}", tenant="a", quota=8)
        for i in range(2):
            q.submit(f"b{i}", request_id=f"b{i}", tenant="b", quota=8)
        got = [r.request_id for r in q.pop_batch(4)]
        assert got == ["a0", "b0", "a1", "b1"]
        # the leftovers stay queued, FIFO within the lane
        assert [r.request_id for r in q.pop_batch(4)] == ["a2", "a3"]


# ------------------------------------------------------- tenant directory
class TestTenantDirectory:
    def test_mount_reports_head_cost_on_real_clock(self):
        """The marginal cost of a tenant: head bytes + mount seconds (on
        the REAL clock — the drill's virtual clock reports 0.0 by
        construction, so the wall-time bound lives here)."""
        calib = _calib()
        d = TenantDirectory()
        rep = d.mount("t0", calib)
        assert rep.head_bytes == head_nbytes(calib) > 0
        assert rep.head_fingerprint == head_fingerprint(calib)
        assert len(rep.head_fingerprint) == 64
        assert 0.0 <= rep.mount_seconds < 0.2
        assert d.tenants() == ["t0"] and len(d) == 1
        assert sm.gauge(sm.TENANTS_MOUNTED).value() == 1.0
        with pytest.raises(ValueError, match="already mounted"):
            d.mount("t0", calib)

    def test_head_identity_is_the_calibration(self):
        a, b = _calib(seed=1), _calib(seed=2)
        assert head_fingerprint(a) != head_fingerprint(b)
        assert head_fingerprint(a) == head_fingerprint(_calib(seed=1))
        assert head_fingerprint(None) == "" and head_nbytes(None) == 0

    def test_quota_fair_share_math(self):
        d = TenantDirectory()
        d.mount("big", _calib(1), quota_weight=3.0)
        d.mount("small", _calib(2), quota_weight=1.0)
        assert d.quota_for("big", 32) == 24
        assert d.quota_for("small", 32) == 8
        assert d.quota_for("ghost", 32) is None
        d.mount("tiny", _calib(3), quota_weight=0.001)
        assert d.quota_for("tiny", 32) == 1  # floor: always admits one
        with pytest.raises(ValueError, match="quota_weight"):
            d.mount("bad", _calib(4), quota_weight=0.0)

    def test_unmount(self):
        d = TenantDirectory()
        d.mount("t0", _calib())
        assert d.unmount("t0") is True
        assert d.unmount("t0") is False
        assert d.tenants() == [] and d.gate_for("t0") is None

    def test_capture_config_needs_num_classes(self):
        from mgproto_tpu.online.capture import CaptureConfig

        d = TenantDirectory()
        with pytest.raises(ValueError, match="num_classes"):
            d.mount("t0", _calib(), capture_config=CaptureConfig())

    def test_swap_fails_closed_per_tenant(self):
        d = TenantDirectory()
        d.mount("a", _calib(1))
        d.mount("b", _calib(2))
        old_gate = d.gate_for("a")
        old_fp = d.head_for("a").head_fingerprint
        # an operator pushes a head with no trust data: REFUSED, the old
        # head keeps serving, tenant b never notices
        rep = d.swap("a", None)
        assert rep.ok is False and rep.reason == "uncalibrated"
        assert d.gate_for("a") is old_gate
        assert d.head_for("a").head_fingerprint == old_fp
        # a good head commits — for that one tenant
        new = _calib(9)
        rep = d.swap("b", new)
        assert rep.ok is True and rep.reason == SWAP_COMMITTED
        assert rep.head_fingerprint == head_fingerprint(new)
        assert d.head_for("b").head_fingerprint == head_fingerprint(new)
        assert d.gate_for("a") is old_gate  # untouched either way
        # a swap aimed at nobody is an outcome, not a crash
        assert d.swap("ghost", new).reason == "not_mounted"

    def test_chaos_bad_swap_knob_strips_the_staged_head(self):
        d = TenantDirectory()
        d.mount("a", _calib(1))
        chaos_mod.install(chaos_mod.ChaosPlan(tenant_bad_swap=1))
        try:
            rep = d.swap("a", _calib(9))  # a GOOD head, sabotaged in flight
            assert rep.ok is False and rep.reason == "uncalibrated"
            rep = d.swap("a", _calib(9))  # budget spent: commits
            assert rep.ok is True
            from mgproto_tpu.resilience import metrics as rm

            assert rm.counter(rm.CHAOS_INJECTIONS).value(
                kind="tenant_bad_swap"
            ) == 1
        finally:
            chaos_mod.set_active(None)


# ------------------------------------------------------------ chaos knobs
class TestTenantChaosKnobs:
    def test_plan_from_env_parses_tenant_knobs(self):
        plan = chaos_mod.plan_from_env({
            "MGPROTO_CHAOS_TENANT_STORM_AT": "5",
            "MGPROTO_CHAOS_TENANT_BAD_SWAP": "2",
            "MGPROTO_CHAOS_TENANT_POISON_RATE": "0.25",
        })
        assert plan.tenant_storm_at == 5
        assert plan.tenant_bad_swap == 2
        assert plan.tenant_poison_rate == 0.25
        assert chaos_mod.plan_from_env({}) is None  # zero-overhead default

    def test_storm_and_poison_fire_deterministically(self):
        state = chaos_mod.install(chaos_mod.ChaosPlan(
            seed=7, tenant_storm_at=5, tenant_poison_rate=0.25,
        ))
        try:
            assert not state.tenant_storm_due(4)
            assert state.tenant_storm_due(5)
            assert state.tenant_storm_due(6)
            hits = [state.tenant_poison_due(i) for i in range(400)]
            again = [state.tenant_poison_due(i) for i in range(400)]
            assert hits == again  # per-index deterministic
            assert 0.15 < sum(hits) / len(hits) < 0.35
        finally:
            chaos_mod.set_active(None)

    def test_bad_swap_budget_counts_down(self):
        state = chaos_mod.install(chaos_mod.ChaosPlan(tenant_bad_swap=2))
        try:
            assert state.tenant_bad_swap_due()
            assert state.tenant_bad_swap_due()
            assert not state.tenant_bad_swap_due()
        finally:
            chaos_mod.set_active(None)


# --------------------------------------------------- engine-level gating
class TestPerTenantGating:
    def test_requests_gate_through_their_tenants_head(self, setup):
        """Two tenants, one trunk: the strict tenant's traffic abstains
        while the lax tenant's identical traffic predicts — gating is a
        property of the ADDRESSED head, not of the shared executable."""
        cfg, trainer, state = setup
        calib = calibrate(trainer, state, _id_batches(cfg))
        d = TenantDirectory()
        d.mount("strict", dc.replace(calib, threshold_log_px=1e9))
        d.mount("lax", dc.replace(calib, threshold_log_px=-1e9))
        eng = ServingEngine.from_live(
            trainer, state, calibration=calib, buckets=(2,), tenants=d
        )
        eng.warmup()
        pay = _payloads(cfg, 2)
        eng.submit(pay[0], request_id="s", tenant="strict")
        eng.submit(pay[1], request_id="l", tenant="lax")
        got = {r.request_id: r for r in eng.process_pending()}
        assert got["s"].outcome == OUTCOME_ABSTAIN
        assert got["l"].outcome == OUTCOME_PREDICT
        assert got["s"].tenant == "strict" and got["l"].tenant == "lax"
        assert sm.counter(sm.TENANT_REQUESTS).value(
            tenant="strict", outcome=OUTCOME_ABSTAIN
        ) == 1

    def test_unmounted_tenant_rejected_typed(self, setup):
        cfg, trainer, state = setup
        calib = calibrate(trainer, state, _id_batches(cfg))
        d = TenantDirectory()
        d.mount("real", calib)
        eng = ServingEngine.from_live(
            trainer, state, calibration=calib, buckets=(2,), tenants=d
        )
        eng.warmup()
        resps = eng.submit(_payloads(cfg, 1)[0], request_id="g",
                           tenant="ghost")
        assert len(resps) == 1
        assert resps[0].outcome == OUTCOME_REJECT
        assert resps[0].reason == REASON_TENANT_UNMOUNTED


# --------------------------------------------------------- the tier-1 drill
DRILL = dict(
    seed=5,
    phases=((0.5, 40.0), (1.0, 40.0), (0.5, 40.0)),
    replicas=2,
    buckets=(1, 2, 4),
    deadline_ms=100.0,
    service_ms=4.0,
    linger_ms=20.0,
    heartbeat_timeout_s=0.25,
    tenants=3,
)


@pytest.fixture(scope="module")
def drill_result():
    return run_load_test(**DRILL)


class TestTenantDrill:
    def test_every_request_answered_once_typed(self, drill_result):
        overall = drill_result["overall"]
        assert overall["zero_dropped"] is True
        assert overall["answered"] == overall["submitted"]
        assert drill_result["steady_state_recompiles"] == 0

    def test_quota_storm_sheds_only_its_own_tenant(self, drill_result):
        t = drill_result["tenants"]
        per = t["per_tenant"]
        storm = per[t["storm_tenant"]]
        assert storm["shed_by_reason"].get(SHED_TENANT_QUOTA, 0) > 0
        for name, row in per.items():
            if name == t["storm_tenant"]:
                continue
            assert row["shed_by_reason"] == {}, name
            assert set(row["outcomes"]) <= {"predict", "abstain"}, name

    def test_poison_breaches_only_the_storm_tenant(self, drill_result):
        t = drill_result["tenants"]
        assert t["poison_injected"] > 0
        per = t["per_tenant"]
        assert per[t["storm_tenant"]]["drift_breaches"] > 0
        for name, row in per.items():
            if name != t["storm_tenant"]:
                assert row["drift_breaches"] == 0, name

    def test_bad_swap_fails_closed_good_commits_mid_storm(self, drill_result):
        t = drill_result["tenants"]
        by_tenant = {s["tenant"]: s for s in t["swaps"]}
        bad = by_tenant[t["storm_tenant"]]
        assert bad["ok"] is False and bad["reason"] == "uncalibrated"
        good = next(s for s in t["swaps"]
                    if s["tenant"] != t["storm_tenant"])
        assert good["ok"] is True and good["reason"] == "committed"
        assert good["head_fingerprint"]

    def test_mid_storm_mount_costs_head_bytes_zero_trunk_compiles(
        self, drill_result
    ):
        t = drill_result["tenants"]
        mid = [m for m in t["mounts"] if m["during_storm"]]
        assert len(mid) == 1
        assert mid[0]["trunk_compiles_delta"] == 0
        assert mid[0]["aot_misses_delta"] == 0
        assert mid[0]["head_bytes"] > 0
        # the joined tenant served real traffic after mounting
        assert t["per_tenant"][mid[0]["tenant"]]["submitted"] > 0

    def test_tenant_ledger_covers_all_traffic(self, drill_result):
        t = drill_result["tenants"]
        total = sum(r["submitted"] for r in t["per_tenant"].values())
        assert total == drill_result["overall"]["submitted"]

    def test_gate_suite_passes_on_the_drill(self, drill_result):
        from mgproto_tpu.cli.telemetry import tenant_gates

        res = tenant_gates(drill_result)
        assert res["ok"] is True and res["failed"] == 0
        assert res["checked"] == 19

    def test_drill_is_deterministic(self):
        small = dict(DRILL)
        small.update(phases=((0.3, 40.0), (0.5, 40.0), (0.3, 40.0)))
        assert run_load_test(**small) == run_load_test(**small)

    def test_single_tenant_run_has_no_tenant_plane(self):
        r = run_load_test(seed=3, phases=((0.3, 60.0),), replicas=1,
                          buckets=(1, 2), deadline_ms=100.0, service_ms=4.0,
                          linger_ms=20.0, heartbeat_timeout_s=0.25)
        assert "tenants" not in r
        assert r["overall"].get("shed_by_reason", {}).get(
            SHED_TENANT_QUOTA
        ) is None

    def test_tenant_mode_rejects_bad_combinations(self):
        with pytest.raises(ValueError, match="tenants"):
            run_load_test(seed=0, phases=((0.3, 40.0),), tenants=1)


# ------------------------------------------------------ committed evidence
class TestTenantEvidence:
    PATH = os.path.join(EVIDENCE, "tenant_baseline.json")

    def _record(self):
        with open(self.PATH) as f:
            return json.loads(f.readline())

    def test_committed_schema(self):
        rec = self._record()
        assert rec["load_test"] is True and rec["virtual_clock"] is True
        t = rec["tenants"]
        for key in ("count", "storm_tenant", "per_tenant", "mounts",
                    "swaps", "poison_injected", "storm_at", "aot"):
            assert key in t, key
        for row in t["per_tenant"].values():
            assert {"submitted", "outcomes", "shed_by_reason", "quota",
                    "head_fingerprint", "head_bytes",
                    "drift_breaches"} <= set(row)

    def test_committed_evidence_gates_clean(self):
        from mgproto_tpu.cli.telemetry import check_main

        assert check_main(["--tenants", self.PATH]) == 0

    @pytest.mark.parametrize("mutate,expect", [
        (lambda t, r: t["per_tenant"][t["storm_tenant"]]["outcomes"]
         .__setitem__("predict", 10 ** 6),
         "tenants.ledger_consistent"),
        (lambda t, r: t["per_tenant"][t["storm_tenant"]]
         .__setitem__("shed_by_reason", {}),
         "tenants.shed_ledger_consistent"),
        (lambda t, r: r["overall"].__setitem__(
            "submitted", r["overall"]["submitted"] + 1),
         "tenants.covers_all_traffic"),
        (lambda t, r: t["swaps"].__setitem__(0, {
            "tenant": t["storm_tenant"], "ok": True,
            "reason": "committed", "head_fingerprint": "x"}),
         "tenants.bad_swap_fail_closed"),
        (lambda t, r: [m for m in t["mounts"] if m["during_storm"]][0]
         .__setitem__("trunk_compiles_delta", 1),
         "tenants.mount_zero_trunk_compiles"),
        (lambda t, r: min(
            (row for n, row in t["per_tenant"].items()
             if n != t["storm_tenant"]), key=lambda x: x["submitted"]
        ).__setitem__("drift_breaches", 3),
         "tenants.quiet_drift_silent"),
        (lambda t, r: r.__setitem__("steady_state_recompiles", 2),
         "tenants.zero_steady_recompiles"),
    ])
    def test_tampered_evidence_fails_the_right_gate(
        self, tmp_path, mutate, expect
    ):
        """The gate verdicts re-derive from raw counts: cooking any one
        ledger (while leaving the others untouched) trips its gate."""
        from mgproto_tpu.cli.telemetry import check_main, tenant_gates

        rec = self._record()
        mutate(rec["tenants"], rec)
        res = tenant_gates(rec)
        failed = [row["key"] for row in res["rows"] if not row["ok"]]
        assert expect in failed
        bad = tmp_path / "tampered.json"
        bad.write_text(json.dumps(rec))
        assert check_main(["--tenants", str(bad)]) == 1

    def test_gate_suite_rejects_a_non_tenant_record(self, tmp_path):
        from mgproto_tpu.cli.telemetry import check_main

        with open(os.path.join(EVIDENCE, "load_test_baseline.json")) as f:
            rec = json.loads(f.readline())
        bad = tmp_path / "plain.json"
        bad.write_text(json.dumps(rec))
        assert check_main(["--tenants", str(bad)]) == 1


# ------------------------------------------------------- telemetry summary
class TestTenantsSummarySection:
    def test_section_silent_until_a_tenant_mounts(self):
        from mgproto_tpu.cli.telemetry import _tenants_section

        reg = MetricRegistry()
        set_current_registry(reg)
        sm.register_serving_metrics(reg)
        # pre-registered but never exercised: a single-tenant fleet's
        # summary must not grow a tenants section
        assert _tenants_section(reg.snapshot()) is None

    def test_section_renders_the_multi_tenant_story(self):
        from mgproto_tpu.cli.telemetry import _tenants_section

        reg = MetricRegistry()
        set_current_registry(reg)
        sm.register_serving_metrics(reg)
        d = TenantDirectory()
        d.mount("t0", _calib(1))
        d.mount("t1", _calib(2))
        for outcome, n in (("predict", 5), ("abstain", 1)):
            for _ in range(n):
                reg.counter(sm.TENANT_REQUESTS).inc(
                    tenant="t0", outcome=outcome
                )
                reg.histogram(sm.TENANT_REQUEST_SECONDS).observe(
                    0.008, tenant="t0"
                )
        reg.counter(sm.TENANT_SHED).inc(
            4, tenant="t0", reason=SHED_TENANT_QUOTA
        )
        d.swap("t1", _calib(9))
        sec = _tenants_section(reg.snapshot())
        assert sec["mounted"] == 2.0 and sec["mount_total"] == 2.0
        assert sec["requests_by_tenant"] == {"t0": 6.0}
        assert sec["outcomes_by_tenant"]["t0"] == {
            "predict": 5.0, "abstain": 1.0
        }
        assert sec["shed_by_tenant"] == {
            "t0": {SHED_TENANT_QUOTA: 4.0}
        }
        assert sec["swaps_by_tenant"]["t1"] == {"committed": 1.0}
        assert sec["head_bytes_by_tenant"]["t0"] > 0
        lat = sec["latency_by_tenant"]["t0"]
        assert lat["count"] == 6 and lat["p99_ms"] == pytest.approx(
            8.0, rel=0.3
        )

    def test_all_tenant_metrics_preregistered_with_help(self):
        reg = MetricRegistry()
        sm.register_serving_metrics(reg)
        snap = reg.snapshot()
        for name in (sm.TENANT_REQUESTS, sm.TENANT_REQUEST_SECONDS,
                     sm.TENANT_SHED, sm.TENANT_MOUNTS, sm.TENANT_UNMOUNTS,
                     sm.TENANT_SWAPS, sm.TENANTS_MOUNTED,
                     sm.TENANT_QUEUE_DEPTH, sm.TENANT_HEAD_BYTES,
                     sm.TENANT_MOUNT_SECONDS):
            assert name in snap, name
            assert snap[name].get("help"), name


# ------------------------------------------------------------------- lints
def _load_script(name):
    path = os.path.join(REPO, "scripts", name)
    spec = importlib.util.spec_from_file_location(name[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_pkg_module(root, pkg, name, source):
    d = os.path.join(root, "mgproto_tpu", pkg)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, name), "w") as f:
        f.write(source)


def test_sleep_lint_walk_reaches_tenants_module(tmp_path):
    """tenants.py lives in serving/, which the lint walks BY CONSTRUCTION
    — the violation case proves the walk actually bites there."""
    lint = _load_script("check_no_blocking_sleep.py")
    assert lint.offenders(REPO) == []
    _write_pkg_module(
        str(tmp_path), "serving", "tenants_bad.py",
        "import time\n\ndef mount():\n    time.sleep(1)\n",
    )
    found = lint.offenders(str(tmp_path))
    assert len(found) == 1 and found[0][0].endswith(
        os.path.join("serving", "tenants_bad.py")
    )
    assert lint.main([str(tmp_path)]) == 1


def test_guarded_collectives_lint_walk_reaches_tenants_module(tmp_path):
    lint = _load_script("check_guarded_collectives.py")
    assert lint.offenders(REPO) == []
    _write_pkg_module(
        str(tmp_path), "serving", "tenants_bad.py",
        "from jax.experimental import multihost_utils\n",
    )
    found = lint.offenders(str(tmp_path))
    assert len(found) == 1 and found[0][0].endswith(
        os.path.join("serving", "tenants_bad.py")
    )
    assert lint.main([str(tmp_path)]) == 1
