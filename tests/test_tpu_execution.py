"""On-hardware execution checks (skipped off-TPU; CI proves lowering only —
tests/test_tpu_lowering.py — and interpret-mode numerics; THIS file is the
proof the Mosaic kernel actually executes and agrees on a real chip).

Run on a TPU host:  MGPROTO_TEST_TPU=1 python -m pytest tests/test_tpu_execution.py
(the flag stops conftest.py from pinning the suite to the virtual CPU mesh)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

requires_tpu = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="needs a real TPU backend"
)


def _flagship_shapes():
    """R34-CUB flagship head shapes (reference settings.py:1-5, 14x14 latent
    grid per models/resnet.py conv_info): B=8 keeps the density matrix
    [B*196, 2000] real while the test stays seconds-fast."""
    rng = np.random.RandomState(0)
    b, hw, d, c, k, t = 8, 196, 64, 200, 10, 20
    feat = rng.normal(size=(b, hw, d)).astype(np.float32)
    feat /= np.linalg.norm(feat, axis=-1, keepdims=True)
    means = rng.normal(size=(c, k, d)).astype(np.float32)
    means /= np.linalg.norm(means, axis=-1, keepdims=True)
    sigmas = np.full((c, k, d), 1.0 / np.sqrt(2 * np.pi), np.float32)
    return jnp.asarray(feat), jnp.asarray(means), jnp.asarray(sigmas), t


@requires_tpu
def test_fused_kernel_matches_unfused_on_device():
    """Mosaic execution == XLA matmul+top_k numerics at flagship shapes
    (values bit-domain f32; indices may differ only where densities tie)."""
    from mgproto_tpu.ops.fused_scoring import score_pool
    from mgproto_tpu.ops.gaussian import diag_gaussian_log_prob

    feat, means, sigmas, t = _flagship_shapes()
    b, hw, d = feat.shape

    def full_densities(f):
        lp = diag_gaussian_log_prob(f.reshape(-1, d), means, sigmas)
        return lp.reshape(b, hw, -1).transpose(0, 2, 1)  # [B, P, HW]

    vals_f, idx_f = jax.jit(
        lambda f: score_pool(f, means, sigmas, t, 1e-10, False)
    )(feat)
    vals_u, _ = jax.jit(lambda f: jax.lax.top_k(full_densities(f), t))(feat)
    np.testing.assert_allclose(
        np.asarray(vals_f), np.asarray(vals_u), rtol=1e-5, atol=1e-5
    )
    # indices: ties may legally reorder between implementations, so validate
    # idx_f by GATHERING the densities it points at — they must reproduce the
    # returned values (catches correct-values-garbage-indices regressions,
    # which would corrupt push projection and mining)
    lp_full = np.asarray(jax.jit(full_densities)(feat))
    gathered = np.take_along_axis(lp_full, np.asarray(idx_f), axis=-1)
    np.testing.assert_allclose(
        np.asarray(vals_f), gathered, rtol=1e-5, atol=1e-5
    )


def _backward_parity(interpret: bool):
    """Shared by the TPU test and the CPU (interpret-mode) regression test.

    Gradient ROUTING follows the selected indices, and near-equal densities
    at the top-T boundary may legally swap between the kernel and XLA top_k
    (both selections are valid within float error), which makes elementwise
    gradient comparison at T < HW inherently tie-fragile. Running with
    T = HW selects every patch, so the gradient is selection-independent and
    compares the VJP math + kernel numerics alone; the strict forward test
    above covers top-T selection values."""
    from mgproto_tpu.ops.fused_scoring import score_pool
    from mgproto_tpu.ops.gaussian import diag_gaussian_log_prob

    feat, means, sigmas, _ = _flagship_shapes()
    b, hw, d = feat.shape
    t = hw

    def loss_fused(f):
        v, _ = score_pool(f, means, sigmas, t, 1e-10, interpret)
        return jnp.sum(v)

    def loss_unfused(f):
        lp = diag_gaussian_log_prob(f.reshape(-1, d), means, sigmas)
        v, _ = jax.lax.top_k(lp.reshape(b, hw, -1).transpose(0, 2, 1), t)
        return jnp.sum(v)

    g_f = np.asarray(jax.jit(jax.grad(loss_fused))(feat))
    g_u = np.asarray(jax.jit(jax.grad(loss_unfused))(feat))
    # Tolerance root-caused (ISSUE 15, the PR-14 remat rationale): at
    # T=HW the gradient sums C*K=2000 per-prototype terms per element,
    # and the kernel's VMEM-tiled VJP accumulates them in a different
    # ORDER than XLA's unfused reduce. Measured against a float64 oracle,
    # the unfused f32 gradient is exact at these shapes while the fused
    # kernel differs by up to ~2e-3 relative on small elements / ~1.5e-3
    # absolute — pure f32 reassociation rounding, which scales with the
    # LARGEST summed terms (|g| reaches ~6.8e3 here), not with the
    # possibly-cancelled element value. A fixed atol=1e-4 sat below that
    # noise floor; the atol is therefore leaf-scaled to the gradient's
    # own magnitude.
    np.testing.assert_allclose(
        g_f, g_u, rtol=1e-4, atol=1e-6 * float(np.abs(g_u).max())
    )


def test_fused_backward_parity_interpret_cpu():
    _backward_parity(interpret=jax.default_backend() != "tpu")


@requires_tpu
def test_fused_kernel_backward_matches_on_device():
    _backward_parity(interpret=False)


@requires_tpu
def test_full_train_step_runs_on_device():
    """One bf16 fused-scoring train step on the chip: finite loss."""
    from mgproto_tpu.config import tiny_test_config
    from mgproto_tpu.engine.train import Trainer

    import dataclasses

    cfg = tiny_test_config()
    cfg = cfg.replace(
        model=dataclasses.replace(
            cfg.model, compute_dtype="bfloat16", fused_scoring=True
        )
    )
    trainer = Trainer(cfg, steps_per_epoch=2)
    state = trainer.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(
        rng.rand(4, cfg.model.img_size, cfg.model.img_size, 3), jnp.float32
    )
    labels = jnp.asarray(rng.randint(0, cfg.model.num_classes, 4), jnp.int32)
    state, m = trainer.train_step(
        state, imgs, labels, use_mine=True, update_gmm=True, warm=False
    )
    assert np.isfinite(float(jax.device_get(m.loss)))


@requires_tpu
def test_fused_scoring_auto_resolves_on_tpu():
    """fused_scoring=None must pick the Pallas path on a real TPU backend
    (config.py:ModelConfig.fused_scoring; the CPU-side half of this contract
    lives in tests/test_fused_scoring.py::test_fused_scoring_auto_resolution)."""
    from mgproto_tpu.config import tiny_test_config
    from mgproto_tpu.engine.train import Trainer

    assert Trainer(tiny_test_config(), steps_per_epoch=1)._fused is True
