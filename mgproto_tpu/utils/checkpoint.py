"""Checkpoint / resume for the full functional train state.

The reference saves only `state_dict` when test accuracy clears a threshold
(reference utils/save.py:5-12) — optimizer state is dropped and there is no
resume path (reference main.py:31-33 even deletes the model dir on restart;
SURVEY.md §5.3-5.4). Here a checkpoint is the WHOLE `TrainState` pytree
(params, batch_stats, GMM, memory bank, all three optimizer states, step), so
training resumes bit-exactly, via orbax.

Filename convention keeps the reference's readable encoding
(`{epoch}{stage}{accuracy}` e.g. `104nopush0.8224`, reference utils/save.py:9)
as a directory name per checkpoint.

Preemption-safety (ISSUE 2 tentpole): every save is ATOMIC — the pytree is
written to `<name>.tmp`, an integrity manifest (leaf paths/shapes/dtypes +
step) is added, and only then is the directory renamed into place, so a
SIGKILL mid-save can never leave a half-written checkpoint where
`find_latest_checkpoint` would pick it up. Restores verify the manifest
against the restore target BEFORE orbax runs (a structure mismatch fails
with a readable diff, not an orbax stack trace) and against the restored
step AFTER. Writes retry through `resilience.retry` (transient FS errors on
preemptible fleets), and `apply_retention` keeps the last N + best-accuracy
checkpoints so long runs don't fill the disk.

Sharded coordinated checkpoints (ISSUE 9 tentpole): the replicated format
above funnels the whole state through one host — a bandwidth wall and a
single point of failure at pod scale. With `sharded=True` (the multi-host
default; `--ckpt_format` is the escape hatch) every process writes ONLY the
array shards it owns (`Shard.replica_id == 0` dedupes replicated leaves) as
`shard_<pid>.npz` + `shard_<pid>.idx.json` into the checkpoint directory, a
cross-host barrier (`parallel.multihost.checkpoint_barrier`) confirms all
hosts finished, and host 0 alone publishes the global COMMIT marker — the
one and only publish point. Every listing here treats a sharded directory
without COMMIT as ABSENT, so a crash at any mid-save moment can never
produce a half-checkpoint that `--resume auto` would trust. Restore is
ELASTIC: shards are reassembled per leaf on the host and placed against the
RESTORE TARGET's shardings (`jax.make_array_from_callback`), so a
checkpoint committed on N chips restores bit-exactly onto an M-chip mesh
(counted in `elastic_restores_total` when N != M).
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional, Tuple

import jax

_NAME_RE = re.compile(r"^(\d+)([a-z_]+)(\d+\.\d+)$")

MANIFEST_FILE = "mgproto_manifest.json"
MANIFEST_FORMAT = 1
TMP_SUFFIX = ".tmp"
COMMIT_FILE = "COMMIT"
_SHARD_NPZ = "shard_{pid:05d}.npz"
_SHARD_IDX = "shard_{pid:05d}.idx.json"


def _checkpointer():
    # orbax import kept lazy: it is needed only when actually checkpointing,
    # not by every consumer of the utils package
    import orbax.checkpoint as ocp

    return ocp.Checkpointer(ocp.PyTreeCheckpointHandler())


def checkpoint_name(epoch: int, stage: str, accuracy: float) -> str:
    """`{epoch}{stage}{acc:.4f}` (reference utils/save.py:9 filename scheme)."""
    return f"{epoch}{stage}{accuracy:.4f}"


def parse_checkpoint_name(name: str) -> Optional[Tuple[int, str, float]]:
    m = _NAME_RE.match(name)
    if not m:
        return None
    return int(m.group(1)), m.group(2), float(m.group(3))


def _tree_manifest(host_state: Any) -> dict:
    """Integrity manifest for a HOST pytree: every leaf's keypath, shape and
    dtype, plus the scalar step when the tree carries one. Cheap to build
    (metadata only) and cheap to verify — corruption of the pytree
    STRUCTURE (wrong aux_loss, truncated write, version skew) is caught
    before orbax ever runs."""
    import numpy as np

    leaves = []
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(host_state)[0]:
        arr = np.asarray(leaf)
        leaves.append({
            "path": jax.tree_util.keystr(keypath),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        })
    step = getattr(host_state, "step", None)
    return {
        "format": MANIFEST_FORMAT,
        "num_leaves": len(leaves),
        "step": None if step is None else int(np.asarray(step)),
        "leaves": leaves,
    }


def _tree_manifest_meta(state: Any) -> dict:
    """Sharded-save manifest: same schema as `_tree_manifest` but built from
    leaf METADATA only — at pod scale the leaves are not fully addressable
    and must never be materialized on one host. Records the saving mesh's
    size so an elastic restore can tell it changed."""
    import numpy as np

    leaves = []
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:  # plain python scalar leaf; NEVER asarray a jax
            dtype = np.asarray(leaf).dtype  # Array here — not addressable
        leaves.append({
            "path": jax.tree_util.keystr(keypath),
            "shape": list(shape),
            "dtype": str(dtype),
        })
    step = getattr(state, "step", None)
    return {
        "format": MANIFEST_FORMAT,
        "sharded": True,
        "num_hosts": jax.process_count(),
        "num_devices": jax.device_count(),
        "num_leaves": len(leaves),
        # step is replicated, so its addressable shard exists on every host
        "step": None if step is None else _scalar_value(step),
        "leaves": leaves,
    }


def _scalar_value(leaf: Any) -> int:
    """A replicated scalar's host value, read from a LOCAL shard — a plain
    device_get of a global array spanning other hosts' devices raises."""
    import numpy as np

    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        return int(np.asarray(leaf.addressable_shards[0].data))
    return int(jax.device_get(leaf))


def load_manifest(path: str) -> Optional[dict]:
    """The checkpoint's manifest, or None when absent (pre-manifest save).
    Raises CheckpointIntegrityError on an unreadable/wrong-format manifest
    (a torn write — the checkpoint must not be trusted)."""
    mpath = os.path.join(path, MANIFEST_FILE)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointIntegrityError(f"unreadable manifest in {path}: {e}")
    if manifest.get("format") != MANIFEST_FORMAT or "leaves" not in manifest:
        raise CheckpointIntegrityError(
            f"manifest in {path} has unknown format {manifest.get('format')!r}"
        )
    return manifest


class CheckpointIntegrityError(RuntimeError):
    """Manifest missing/corrupt or mismatching the restore target."""


def _verify_manifest(manifest: dict, target: Any, path: str) -> None:
    import numpy as np

    want = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(target)[0]:
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:  # lazy: asarray would fetch a global jax.Array
            dtype = np.asarray(leaf).dtype
        want[jax.tree_util.keystr(keypath)] = (shape, str(dtype))
    got = {e["path"]: (tuple(e["shape"]), e["dtype"])
           for e in manifest["leaves"]}
    if got == want:
        return
    missing = sorted(set(want) - set(got))[:3]
    extra = sorted(set(got) - set(want))[:3]
    diff = sorted(
        k for k in set(got) & set(want) if got[k] != want[k]
    )[:3]
    detail = []
    if missing:
        detail.append(f"missing from checkpoint: {missing}")
    if extra:
        detail.append(f"unexpected in checkpoint: {extra}")
    for k in diff:
        detail.append(f"{k}: checkpoint {got[k]} vs target {want[k]}")
    raise CheckpointIntegrityError(
        f"checkpoint {path} does not match the restore target "
        f"({len(got)} vs {len(want)} leaves); " + "; ".join(detail)
    )


def _host_chunks(leaf: Any):
    """The (global_index, host_array) chunks THIS process must persist for
    one leaf. jax Arrays contribute exactly their `replica_id == 0`
    addressable shards — across all processes that is a non-overlapping
    exact cover of the global array, so replicated leaves are written once
    (by whichever host owns replica 0) and sharded leaves are written where
    they live. Plain host leaves are written whole by the primary host."""
    import numpy as np

    if isinstance(leaf, jax.Array):
        for s in leaf.addressable_shards:
            if s.replica_id == 0:
                yield s.index, np.asarray(s.data)
        return
    from mgproto_tpu.parallel.multihost import is_primary_host

    if is_primary_host():
        arr = np.asarray(leaf)
        yield tuple(slice(None) for _ in arr.shape), arr


def _index_to_json(index, shape) -> list:
    """A shard's global index as [[start, stop], ...] (per dimension)."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, _ = sl.indices(int(dim))
        out.append([int(start), int(stop)])
    return out


def _index_from_json(spans) -> tuple:
    return tuple(slice(int(a), int(b)) for a, b in spans)


def _spans_intersect(a, b) -> bool:
    """Whether two [(start, stop), ...] rectangles overlap (per-dim open
    interval test; scalars — empty span tuples — always intersect)."""
    return all(s1 < e2 and s2 < e1 for (s1, e1), (s2, e2) in zip(a, b))


def _write_host_shards(path: str, state: Any, pid: int) -> None:
    """Persist this process's chunks of every leaf as one npz + one index
    sidecar, each atomic (tmp+rename), the sidecar LAST — restores iterate
    sidecars, so a torn npz-without-sidecar is invisible."""
    import numpy as np

    arrays: Dict[str, Any] = {}
    chunks = []
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    for leaf_i, (_keypath, leaf) in enumerate(flat):
        for chunk_i, (index, data) in enumerate(_host_chunks(leaf)):
            key = f"c{leaf_i}_{chunk_i}"
            arrays[key] = data
            chunks.append({
                "leaf": leaf_i,
                "key": key,
                "index": _index_to_json(index, _global_shape(leaf)),
            })
    npz = os.path.join(path, _SHARD_NPZ.format(pid=pid))
    idx = os.path.join(path, _SHARD_IDX.format(pid=pid))
    with open(npz + TMP_SUFFIX, "wb") as f:
        np.savez(f, **arrays)
    os.replace(npz + TMP_SUFFIX, npz)
    _atomic_json(idx, {"process": pid, "chunks": chunks})


def _global_shape(leaf: Any):
    import numpy as np

    return tuple(getattr(leaf, "shape", np.shape(leaf)))


def _atomic_json(path: str, payload: dict) -> None:
    tmp = path + TMP_SUFFIX
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _save_sharded(
    path: str, state: Any, name: str, metadata: Optional[dict]
) -> None:
    """One attempt of the coordinated sharded save protocol. Called in
    lockstep by EVERY process (the barriers keep retries aligned). All
    writes land in a `<name>.tmp` STAGING directory (invisible to every
    listing), so overwriting an existing checkpoint of the same name —
    repeated preempt saves of one epoch — never destroys the committed
    original until its replacement is fully committed:

      1. host 0 clears any stale staging directory at this name
      2. barrier — all hosts see a clean staging dir
      3. every host writes its shard npz + index sidecar into staging
      4. barrier — all shard files visible on the shared FS
      5. host 0 writes manifest + metadata, then the COMMIT marker, all
         in staging (the chaos checkpoint-failure knob injects a
         simulated crash just before the commit)
      6. barrier, then host 0 alone SWAPS staging into place (removing
         any previous same-name checkpoint at the last instant)
      7. barrier, then EVERY host verifies from the shared FS that THIS
         attempt published: staging gone AND COMMIT present (a stale
         same-name checkpoint's COMMIT alone can't fake success — only
         the swap removes staging), then one final barrier so no host
         starts a retry (clearing staging) before every peer has read
         the outcome — a failed commit raises on all hosts consistently,
         never on host 0 alone
    """
    import time

    from mgproto_tpu.parallel.multihost import (
        checkpoint_barrier,
        is_primary_host,
    )
    from mgproto_tpu.resilience.chaos import get_active

    primary = is_primary_host()
    staging = path + TMP_SUFFIX
    if primary:
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging, exist_ok=True)
    checkpoint_barrier(f"{name}.begin")
    os.makedirs(staging, exist_ok=True)
    _write_host_shards(staging, state, jax.process_index())
    checkpoint_barrier(f"{name}.shards")
    commit_error: Optional[Exception] = None
    if primary:
        try:
            _atomic_json(os.path.join(staging, MANIFEST_FILE),
                         _tree_manifest_meta(state))
            if metadata is not None:
                _atomic_json(
                    os.path.join(staging, "mgproto_meta.json"), metadata
                )
            chaos = get_active()
            if chaos is not None and chaos.checkpoint_should_fail():
                # simulated crash after the shard writes, before the commit
                raise IOError(
                    f"chaos: injected checkpoint write failure ({name})"
                )
            _atomic_json(os.path.join(staging, COMMIT_FILE), {
                "committed_at": time.time(),
                "num_hosts": jax.process_count(),
                "num_devices": jax.device_count(),
            })
        except Exception as e:  # join the barrier first; raise after
            commit_error = e
    checkpoint_barrier(f"{name}.commit")
    if commit_error is None and primary:
        try:
            # the swap: the only moment the previous committed checkpoint
            # of this name ceases to exist, microseconds before its fully
            # committed replacement appears (two syscalls — the same window
            # the replicated format's rename publish accepts)
            if os.path.isdir(path):
                shutil.rmtree(path)
            os.rename(staging, path)
        except Exception as e:  # join the publish barrier first — a swap
            commit_error = e  # failure must not strand peers in it
    checkpoint_barrier(f"{name}.publish")
    # every host verifies from the SHARED FS, not from local exception
    # state: when a same-name checkpoint was already committed by an
    # earlier save (repeated preempt saves of one epoch), `path/COMMIT`
    # alone cannot distinguish this attempt's commit from the stale one —
    # but a failed attempt always leaves its staging directory behind (the
    # swap is the only thing that removes it), so staging-present means
    # this attempt did not publish. All hosts agree, so retry_call's next
    # attempt re-enters in lockstep and the barriers stay aligned.
    failure: Optional[Exception] = commit_error
    if failure is None and (
        os.path.isdir(staging)
        or not os.path.exists(os.path.join(path, COMMIT_FILE))
    ):
        failure = IOError(
            f"sharded checkpoint {path} was not committed by the primary "
            "host; treating the save as failed on every host"
        )
    # second agreement point: nobody starts the next attempt (which clears
    # staging, the failure signal above) until every host has finished
    # reading this attempt's outcome
    checkpoint_barrier(f"{name}.verified")
    if failure is not None:
        raise failure


def _shard_sidecars(path: str) -> List[str]:
    """This checkpoint directory's shard index sidecars, in process order."""
    try:
        names = os.listdir(path)
    except OSError:
        return []
    return sorted(
        os.path.join(path, n) for n in names
        if n.startswith("shard_") and n.endswith(".idx.json")
    )


def has_shard_files(path: str) -> bool:
    """True when `path` holds per-host shard artifacts (a sharded-protocol
    save, committed or not)."""
    try:
        names = os.listdir(path)
    except OSError:
        return False
    return any(
        n.startswith("shard_") and (n.endswith(".npz") or n.endswith(".idx.json"))
        for n in names
    )


def is_committed(path: str) -> bool:
    """True when the sharded protocol's COMMIT marker exists (irrelevant for
    replicated-format saves, whose publish point is the directory rename)."""
    return os.path.exists(os.path.join(path, COMMIT_FILE))


def _restore_sharded(path: str, target: Any, manifest: dict) -> Any:
    """Elastic restore of a committed sharded checkpoint onto `target`'s
    topology. Each leaf is reassembled on the host from the saved chunk
    cover (the shared FS holds all shard files) — but each process reads
    ONLY the chunks intersecting its own addressable spans of the target —
    then placed against the TARGET leaf's sharding via
    `jax.make_array_from_callback`; the callback slices the assembled
    array per addressable shard, so the checkpoint's device/host count
    never constrains the restore mesh. An exact-cover check over the
    needed region catches torn/missing chunks before anything is placed.
    Counted in `elastic_restores_total` when the topology changed."""
    import numpy as np

    if not is_committed(path):
        raise CheckpointIntegrityError(
            f"sharded checkpoint {path} has no COMMIT marker (crashed "
            "mid-save); it must not be restored"
        )
    flat, treedef = jax.tree_util.tree_flatten(target)
    # the spans THIS process actually needs: the union of the target
    # leaf's addressable shard indices (`make_array_from_callback` only
    # ever asks for those). On an N-host pod each host then reads only the
    # chunk bytes its own placement touches instead of N full copies of
    # the checkpoint flowing through the shared FS — the single-host
    # funnel the sharded format exists to avoid. Replicated leaves are
    # needed whole everywhere; sharded leaves only where they will live.
    needed: Dict[int, list] = {}
    for leaf_i, leaf in enumerate(flat):
        shape = _global_shape(leaf)
        if isinstance(leaf, jax.Array):
            needed[leaf_i] = [
                sp for sp in {
                    tuple(map(tuple, _index_to_json(s.index, shape)))
                    for s in leaf.addressable_shards
                }
            ]
        else:
            needed[leaf_i] = [tuple((0, int(d)) for d in shape)]
    # per-leaf chunk lists from every process's sidecar, intersected with
    # the needed spans; an npz holding nothing this host needs is never
    # opened, and npz zip members are read per-key
    per_leaf: Dict[int, list] = {i: [] for i in range(len(flat))}
    for sidecar in _shard_sidecars(path):
        with open(sidecar) as f:
            idx = json.load(f)
        wanted = []
        for chunk in idx["chunks"]:
            leaf_i = int(chunk["leaf"])
            spans = tuple((int(a), int(b)) for a, b in chunk["index"])
            if any(_spans_intersect(spans, n) for n in needed[leaf_i]):
                wanted.append(
                    (leaf_i, chunk["key"], _index_from_json(chunk["index"]))
                )
        if not wanted:
            continue
        npz = np.load(sidecar[: -len(".idx.json")] + ".npz")
        for leaf_i, key, index in wanted:
            per_leaf[leaf_i].append((index, npz[key]))
    restored = []
    for leaf_i, leaf in enumerate(flat):
        shape = _global_shape(leaf)
        dtype = np.dtype(manifest["leaves"][leaf_i]["dtype"])
        # one buffer PER NEEDED SPAN (the target's addressable shard
        # rectangles), never the global array: host restore memory stays
        # proportional to the host's own shards — allocating the full
        # global leaf on every host would be the single-host funnel this
        # format exists to avoid, and at bank scale would OOM every host
        # simultaneously. Saved chunks never overlap (replica_id==0 is a
        # partition of the global array), so per-span filled-element
        # counting is an exact cover check over the needed region.
        buffers: Dict[tuple, Any] = {}
        filled: Dict[tuple, int] = {}
        for span in needed[leaf_i]:
            buffers[span] = np.empty([b - a for a, b in span], dtype)
            filled[span] = 0
        for index, data in per_leaf[leaf_i]:
            cspan = tuple(
                sl.indices(int(dim))[:2] for sl, dim in zip(index, shape)
            )
            for span in needed[leaf_i]:
                if not _spans_intersect(cspan, span):
                    continue
                inter = tuple(
                    (max(cs, ns), min(ce, ne))
                    for (cs, ce), (ns, ne) in zip(cspan, span)
                )
                bsl = tuple(
                    slice(a - ns, b - ns)
                    for (a, b), (ns, _) in zip(inter, span)
                )
                csl = tuple(
                    slice(a - cs, b - cs)
                    for (a, b), (cs, _) in zip(inter, cspan)
                )
                buffers[span][bsl] = data[csl]
                filled[span] += int(
                    np.prod([b - a for a, b in inter], dtype=np.int64)
                )
        total_needed = got = 0
        for span in needed[leaf_i]:
            total_needed += int(
                np.prod([b - a for a, b in span], dtype=np.int64)
            )
            got += filled[span]
        if got != total_needed:
            raise CheckpointIntegrityError(
                f"sharded checkpoint {path}: leaf {leaf_i} "
                f"({manifest['leaves'][leaf_i]['path']}) chunks cover "
                f"{got} of {total_needed} needed elements"
            )
        if isinstance(leaf, jax.Array):
            def _shard_data(idx, _b=buffers, _shape=shape):
                # idx comes from the same sharding the needed spans were
                # computed from, so normalization makes it an exact key
                key = tuple(
                    sl.indices(int(dim))[:2]
                    for sl, dim in zip(idx, _shape)
                )
                return _b[key]

            restored.append(jax.make_array_from_callback(
                shape, leaf.sharding, _shard_data
            ))
        else:
            restored.append(buffers[tuple((0, int(d)) for d in shape)])
    saved_devices = int(manifest.get("num_devices", jax.device_count()))
    saved_hosts = int(manifest.get("num_hosts", jax.process_count()))
    if (saved_devices, saved_hosts) != (
        jax.device_count(), jax.process_count()
    ):
        from mgproto_tpu.obs.flightrec import record_event
        from mgproto_tpu.resilience import metrics as _m

        _m.counter(_m.ELASTIC_RESTORES).inc()
        record_event(
            "elastic_restore", path=path,
            saved_devices=saved_devices, saved_hosts=saved_hosts,
            restore_devices=jax.device_count(),
            restore_hosts=jax.process_count(),
        )
    return jax.tree_util.tree_unflatten(treedef, restored)


def save_checkpoint(
    ckpt_dir: str,
    state: Any,
    name: str,
    metadata: Optional[dict] = None,
    retries: int = 2,
    sharded: Optional[bool] = None,
) -> str:
    """Write `state` (any pytree of arrays) to `ckpt_dir/name`, atomically.

    `sharded=None` resolves by process count: multi-host runs use the
    coordinated per-host shard protocol (`_save_sharded` — COMMIT marker is
    the publish point), single-process runs the replicated orbax format
    (tmp+rename is the publish point). Explicit True/False is always
    honored (`--ckpt_format`). Either way a kill at ANY mid-save moment
    leaves nothing any listing here trusts. Failed attempts (counted in
    `checkpoint_write_failures_total`) are retried with backoff — under the
    sharded protocol every process retries in lockstep, so the barriers
    stay aligned."""
    from mgproto_tpu.resilience import metrics as _m
    from mgproto_tpu.resilience.chaos import get_active
    from mgproto_tpu.resilience.retry import retry_call

    if sharded is None:
        sharded = jax.process_count() > 1
    path = os.path.abspath(os.path.join(ckpt_dir, name))
    tmp = path + TMP_SUFFIX

    def _write_sharded() -> None:
        try:
            _save_sharded(path, state, name, metadata)
        except Exception:
            _m.counter(_m.CKPT_WRITE_FAILURES).inc()
            raise

    def _write() -> None:
        try:
            from mgproto_tpu.parallel.multihost import (
                checkpoint_barrier,
                is_primary_host,
            )

            # replicated escape hatch under multi-host: ONE writer (the
            # state must be fully replicated to be addressable on host 0);
            # every host joins the publish barrier, then verifies the
            # rename landed — a primary-side failure raises on ALL hosts,
            # so retry_call's attempts stay in lockstep and the barriers
            # aligned (same shape as the sharded commit step)
            write_error: Optional[Exception] = None
            if is_primary_host():
                try:
                    if os.path.isdir(tmp):
                        shutil.rmtree(tmp)
                    host_state = jax.device_get(state)
                    _checkpointer().save(tmp, host_state, force=True)
                    with open(os.path.join(tmp, MANIFEST_FILE), "w") as f:
                        json.dump(_tree_manifest(host_state), f)
                    if metadata is not None:
                        with open(
                            os.path.join(tmp, "mgproto_meta.json"), "w"
                        ) as f:
                            json.dump(metadata, f)
                    chaos = get_active()
                    if chaos is not None and chaos.checkpoint_should_fail():
                        # simulated kill between tmp write and publish rename
                        raise IOError(
                            f"chaos: injected checkpoint write failure "
                            f"({name})"
                        )
                    if os.path.isdir(path):
                        shutil.rmtree(path)  # force=True overwrite semantics
                    os.rename(tmp, path)
                except Exception as e:  # join the barrier first; raise after
                    write_error = e
                    try:
                        # the tmp dir doubles as the cross-host failure
                        # signal: with a stale same-name checkpoint already
                        # at `path`, peers cannot tell this attempt's
                        # publish from the old one — tmp-present can. A
                        # successful rename removed it; guarantee it exists
                        # on any failure (orbax may fail before creating
                        # it). If even this write fails, peers fall back to
                        # the barrier timeout.
                        os.makedirs(tmp, exist_ok=True)
                    except OSError:
                        pass
            checkpoint_barrier(f"{name}.publish")
            failure: Optional[Exception] = write_error
            if failure is None and (
                os.path.isdir(tmp) or not os.path.isdir(path)
            ):
                failure = IOError(
                    f"checkpoint {path} was not published by the primary "
                    "host; treating the save as failed on every host"
                )
            # agreement before retry: the next attempt's rmtree(tmp) clears
            # the failure signal peers just read
            checkpoint_barrier(f"{name}.verified")
            if failure is not None:
                raise failure
        except Exception:
            _m.counter(_m.CKPT_WRITE_FAILURES).inc()
            raise

    # a barrier timeout is NOT retryable: the dead peer cannot join the
    # retry's fresh barriers either — each attempt would burn another full
    # timeout window (and re-write the PEER_LOST marker) before the exit
    # the pod launcher is waiting on. Propagate failure agreement at once.
    from mgproto_tpu.parallel.multihost import BarrierTimeoutError

    retry_call(_write_sharded if sharded else _write, retries=retries,
               base_delay=0.1, max_delay=2.0, scope="checkpoint",
               no_retry_on=(BarrierTimeoutError,))
    return path


def restore_checkpoint(path: str, target: Any) -> Any:
    """Restore a pytree with the structure/shardings of `target`.

    `target` is a concrete state (e.g. a fresh `Trainer.init_state(...)`);
    restored arrays adopt its dtypes and shardings, so a restore into a
    sharded state lands directly on the mesh.

    When the checkpoint carries a manifest it is verified against `target`
    BEFORE orbax runs (structure mismatches fail readably) and against the
    restored step AFTER (a truncated array payload cannot masquerade as a
    clean resume point).

    A sharded-protocol checkpoint (manifest `sharded: true`) dispatches to
    the elastic reassembly path instead of orbax — restored leaves land
    directly on `target`'s shardings, whatever mesh the save ran on."""
    path = os.path.abspath(path)
    manifest = load_manifest(path)
    if manifest is not None:
        _verify_manifest(manifest, target, path)
    if manifest is not None and manifest.get("sharded"):
        restored = _restore_sharded(path, target, manifest)
    elif manifest is None and has_shard_files(path):
        # shard files but no manifest: a save that crashed before the
        # manifest write — never feed it to orbax's opaque error path
        raise CheckpointIntegrityError(
            f"{path} holds uncommitted shard files and no manifest "
            "(crashed mid-save); it cannot be restored"
        )
    else:
        restored = _checkpointer().restore(path, item=target)
    if manifest is not None and manifest.get("step") is not None:
        restored_step = getattr(restored, "step", None)
        if restored_step is not None:
            got = _scalar_value(restored_step)
            if got != int(manifest["step"]):
                raise CheckpointIntegrityError(
                    f"checkpoint {path}: restored step {got} != manifest "
                    f"step {manifest['step']}"
                )
    return restored


def pytree_digest(tree: Any) -> str:
    """sha256 over a pytree's structure + exact leaf bytes. Two states with
    the same digest stepped identically stay identical — the equality the
    chaos tests assert between a fault-ridden run and a clean one."""
    import hashlib

    import numpy as np

    host = jax.device_get(tree)
    leaves, treedef = jax.tree_util.tree_flatten(host)
    h = hashlib.sha256(str(treedef).encode())
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(f"{arr.shape}{arr.dtype}".encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def load_metadata(path: str) -> Optional[dict]:
    meta = os.path.join(path, "mgproto_meta.json")
    if not os.path.exists(meta):
        return None
    with open(meta) as f:
        return json.load(f)


def save_state_w_condition(
    ckpt_dir: str,
    state: Any,
    epoch: int,
    stage: str,
    accuracy: float,
    target_accuracy: float,
    metadata: Optional[dict] = None,
    sharded: Optional[bool] = None,
) -> Optional[str]:
    """Parity with reference utils/save.py:5-12: save only when accuracy
    clears the threshold; name encodes epoch/stage/accuracy. `sharded`
    forwards to `save_checkpoint` (the `--ckpt_format` plumbing) — the
    accuracy gate is host-symmetric (the test pass is SPMD), so under
    multi-host every process takes the same save/skip branch and the
    coordinated protocol's barriers stay aligned.

    The comparison is non-strict at the boundary (save when accuracy ==
    target): the default target of 0.0 means "keep every stage
    checkpoint", and an early epoch that evaluates to exactly 0.0
    accuracy must still leave its stage checkpoint behind — resume and
    the full-schedule e2e both read the stage set, not the accuracy. At
    the reference's real thresholds (0.6/0.7) ties are measure-zero, so
    parity is unaffected where it matters."""
    if accuracy < target_accuracy:
        return None
    meta = dict(metadata or {})
    meta.update(epoch=epoch, stage=stage, accuracy=accuracy)
    return save_checkpoint(
        ckpt_dir, state, checkpoint_name(epoch, stage, accuracy),
        metadata=meta, sharded=sharded,
    )


# Within one epoch the reference saves nopush, then push, then prune
# (reference main.py:255/281/287) — resume must pick the latest STAGE, not the
# highest accuracy (push/prune typically dip). "preempt" checkpoints are
# taken MID-epoch, before that epoch's nopush save, so they order first.
_STAGE_ORDER = {"preempt": -1, "nopush": 0, "push": 1, "prune": 2}


def _manifest_state(path: str) -> str:
    """'ok' (valid manifest), 'missing' (pre-manifest legacy save), or
    'bad' (torn/corrupt manifest — never trust the checkpoint).

    A sharded-protocol directory (manifest says so, or shard files are
    present) is 'bad' until its COMMIT marker exists: the marker is that
    format's one publish point, so a mid-save crash — before OR after the
    manifest write — can never leave a checkpoint any listing trusts."""
    try:
        manifest = load_manifest(path)
    except CheckpointIntegrityError:
        return "bad"
    sharded = bool((manifest or {}).get("sharded")) or has_shard_files(path)
    if sharded and not is_committed(path):
        return "bad"
    return "ok" if manifest is not None else "missing"


def list_checkpoints(ckpt_dir: str, require_manifest: bool = False):
    """All parseable checkpoints in `ckpt_dir` as (epoch, stage, acc, path),
    ordered by (epoch, stage progression). In-flight `.tmp` saves and
    checkpoints with a CORRUPT manifest are always skipped;
    `require_manifest=True` additionally skips legacy manifest-less saves
    (the strict listing `find_latest_checkpoint` resumes from)."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        if name.endswith(TMP_SUFFIX):
            continue  # unpublished (interrupted) save
        parsed = parse_checkpoint_name(name)
        if not parsed or not os.path.isdir(os.path.join(ckpt_dir, name)):
            continue
        mstate = _manifest_state(os.path.join(ckpt_dir, name))
        if mstate == "bad" or (require_manifest and mstate != "ok"):
            continue
        out.append((*parsed, os.path.join(ckpt_dir, name)))
    out.sort(key=lambda t: (t[0], _STAGE_ORDER.get(t[1], -2), t[2]))
    return out


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Highest-epoch checkpoint path (the resume point the reference lacks)."""
    ckpts = list_checkpoints(ckpt_dir)
    return ckpts[-1][3] if ckpts else None


def find_latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """The newest checkpoint SAFE to resume from: latest by (epoch, stage)
    among checkpoints with a verified-parseable manifest; `.tmp` leftovers
    and torn saves never qualify. The `--resume auto` and rollback entry
    point."""
    ckpts = list_checkpoints(ckpt_dir, require_manifest=True)
    return ckpts[-1][3] if ckpts else None


def apply_retention(
    ckpt_dir: str, keep_last: int, keep_best: int = 1
) -> List[str]:
    """Delete old checkpoints, keeping the newest `keep_last` by (epoch,
    stage) order plus the `keep_best` highest-accuracy ones (the eval
    artifacts the reference's threshold saves were for). `keep_last <= 0`
    disables retention. Returns the deleted paths.

    Trust and deletion are two sides of one listing: `list_checkpoints`
    skips uncommitted sharded directories, so retention can never count
    them toward `keep_last` — and in particular can never delete the last
    COMMITTED checkpoint in favor of a half-written one. Those orphaned
    shard directories (a crashed save that a later same-name save did not
    overwrite) are instead PRUNED here, since nothing can ever resume from
    them and at pod scale each holds a full model's worth of bytes.
    Multi-host: call on the primary host only (cli/train gates it)."""
    if keep_last <= 0:
        return []
    ckpts = list_checkpoints(ckpt_dir)
    keep = {c[3] for c in ckpts[-keep_last:]}
    if keep_best > 0:
        by_acc = sorted(ckpts, key=lambda c: c[2], reverse=True)
        keep.update(c[3] for c in by_acc[:keep_best])
    removed = []
    for c in ckpts:
        if c[3] not in keep:
            shutil.rmtree(c[3], ignore_errors=True)
            removed.append(c[3])
    # orphaned saves: (a) `<name>.tmp` staging dirs of crashed attempts —
    # a live save always clears its own staging before writing, so any
    # still here belongs to a DEAD attempt; (b) bare-name sharded dirs
    # without COMMIT (a crash inside the final swap, or a lost marker) —
    # the trusted listing refused them, nothing can ever resume from them.
    trusted = {c[3] for c in ckpts}
    for name in os.listdir(ckpt_dir):
        path = os.path.join(ckpt_dir, name)
        if not os.path.isdir(path) or path in trusted:
            continue
        if name.endswith(TMP_SUFFIX):
            if parse_checkpoint_name(name[: -len(TMP_SUFFIX)]):
                shutil.rmtree(path, ignore_errors=True)
                removed.append(path)
        elif (
            parse_checkpoint_name(name)
            and has_shard_files(path)
            and not is_committed(path)
        ):
            shutil.rmtree(path, ignore_errors=True)
            removed.append(path)
    return removed


def select_checkpoint(ckpt_dir: str, stage: str = "nopush",
                      policy: str = "best"):
    """(epoch, stage, acc, path) of the requested stage, or None.

    policy='best' — highest test accuracy: how the reference chose its
    released eval checkpoints (eval_purity.py:55 `104nopush0.8224`).
    policy='latest' — highest epoch. One definition for every evidence/eval
    consumer so checkpoint-selection can't silently diverge between them."""
    if policy not in ("best", "latest"):
        raise ValueError(f"unknown policy {policy!r}")
    ckpts = [c for c in list_checkpoints(ckpt_dir) if c[1] == stage]
    if not ckpts:
        return None
    return max(ckpts, key=lambda c: c[2]) if policy == "best" else ckpts[-1]


def adopt_checkpoint_train_config(cfg, path: str, log=None):
    """Return cfg with training-time settings recorded in the checkpoint's
    metadata adopted for restore/eval. The single definition behind
    cli/evaluate, cli/interpret, and the evidence scripts. Adopts:

    - `model.compute_dtype`: evaluating under different numerics silently
      shifts the p(x) scale OoD thresholding rides on;
    - `loss.aux_loss`: proxy-based losses carry a params['proxies'] leaf
      (plus optimizer-state leaves), so a restore target built with the
      wrong aux_loss has a mismatching pytree STRUCTURE and orbax restore
      fails outright;
    - `em.reference_stepping`: resuming a reference-stepping run without
      re-passing the flag would silently switch EM math mid-training (the
      two paths share a pytree structure, so nothing else would catch it).

    Checkpoints predating a metadata key keep cfg's value for it."""
    import dataclasses

    meta = load_metadata(path) or {}
    ckpt_dtype = meta.get("compute_dtype")
    if ckpt_dtype and ckpt_dtype != cfg.model.compute_dtype:
        if log is not None:
            log(
                f"note: checkpoint was trained with compute_dtype="
                f"{ckpt_dtype}; overriding {cfg.model.compute_dtype}"
            )
        cfg = cfg.replace(
            model=dataclasses.replace(cfg.model, compute_dtype=ckpt_dtype)
        )
    ckpt_aux = meta.get("aux_loss")
    if ckpt_aux and ckpt_aux != cfg.loss.aux_loss:
        if log is not None:
            log(
                f"note: checkpoint was trained with aux_loss={ckpt_aux}; "
                f"overriding {cfg.loss.aux_loss}"
            )
        cfg = cfg.replace(
            loss=dataclasses.replace(cfg.loss, aux_loss=ckpt_aux)
        )
    ckpt_ref_em = meta.get("em_reference_stepping")
    if ckpt_ref_em is not None and ckpt_ref_em != cfg.em.reference_stepping:
        if log is not None:
            log(
                f"note: checkpoint was trained with em.reference_stepping="
                f"{ckpt_ref_em}; overriding {cfg.em.reference_stepping}"
            )
        cfg = cfg.replace(
            em=dataclasses.replace(cfg.em, reference_stepping=ckpt_ref_em)
        )
    return cfg
