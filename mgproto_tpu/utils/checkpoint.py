"""Checkpoint / resume for the full functional train state.

The reference saves only `state_dict` when test accuracy clears a threshold
(reference utils/save.py:5-12) — optimizer state is dropped and there is no
resume path (reference main.py:31-33 even deletes the model dir on restart;
SURVEY.md §5.3-5.4). Here a checkpoint is the WHOLE `TrainState` pytree
(params, batch_stats, GMM, memory bank, all three optimizer states, step), so
training resumes bit-exactly, via orbax.

Filename convention keeps the reference's readable encoding
(`{epoch}{stage}{accuracy}` e.g. `104nopush0.8224`, reference utils/save.py:9)
as a directory name per checkpoint.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional, Tuple

import jax

_NAME_RE = re.compile(r"^(\d+)([a-z_]+)(\d+\.\d+)$")


def _checkpointer():
    # orbax import kept lazy: it is needed only when actually checkpointing,
    # not by every consumer of the utils package
    import orbax.checkpoint as ocp

    return ocp.Checkpointer(ocp.PyTreeCheckpointHandler())


def checkpoint_name(epoch: int, stage: str, accuracy: float) -> str:
    """`{epoch}{stage}{acc:.4f}` (reference utils/save.py:9 filename scheme)."""
    return f"{epoch}{stage}{accuracy:.4f}"


def parse_checkpoint_name(name: str) -> Optional[Tuple[int, str, float]]:
    m = _NAME_RE.match(name)
    if not m:
        return None
    return int(m.group(1)), m.group(2), float(m.group(3))


def save_checkpoint(
    ckpt_dir: str,
    state: Any,
    name: str,
    metadata: Optional[dict] = None,
) -> str:
    """Write `state` (any pytree of arrays) to `ckpt_dir/name`."""
    path = os.path.abspath(os.path.join(ckpt_dir, name))
    _checkpointer().save(path, jax.device_get(state), force=True)
    if metadata is not None:
        with open(os.path.join(path, "mgproto_meta.json"), "w") as f:
            json.dump(metadata, f)
    return path


def restore_checkpoint(path: str, target: Any) -> Any:
    """Restore a pytree with the structure/shardings of `target`.

    `target` is a concrete state (e.g. a fresh `Trainer.init_state(...)`);
    restored arrays adopt its dtypes and shardings, so a restore into a
    sharded state lands directly on the mesh.
    """
    return _checkpointer().restore(os.path.abspath(path), item=target)


def load_metadata(path: str) -> Optional[dict]:
    meta = os.path.join(path, "mgproto_meta.json")
    if not os.path.exists(meta):
        return None
    with open(meta) as f:
        return json.load(f)


def save_state_w_condition(
    ckpt_dir: str,
    state: Any,
    epoch: int,
    stage: str,
    accuracy: float,
    target_accuracy: float,
    metadata: Optional[dict] = None,
) -> Optional[str]:
    """Parity with reference utils/save.py:5-12: save only when accuracy
    clears the threshold; name encodes epoch/stage/accuracy."""
    if accuracy <= target_accuracy:
        return None
    meta = dict(metadata or {})
    meta.update(epoch=epoch, stage=stage, accuracy=accuracy)
    return save_checkpoint(
        ckpt_dir, state, checkpoint_name(epoch, stage, accuracy), metadata=meta
    )


# Within one epoch the reference saves nopush, then push, then prune
# (reference main.py:255/281/287) — resume must pick the latest STAGE, not the
# highest accuracy (push/prune typically dip).
_STAGE_ORDER = {"nopush": 0, "push": 1, "prune": 2}


def list_checkpoints(ckpt_dir: str):
    """All parseable checkpoints in `ckpt_dir` as (epoch, stage, acc, path),
    ordered by (epoch, stage progression)."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        parsed = parse_checkpoint_name(name)
        if parsed and os.path.isdir(os.path.join(ckpt_dir, name)):
            out.append((*parsed, os.path.join(ckpt_dir, name)))
    out.sort(key=lambda t: (t[0], _STAGE_ORDER.get(t[1], -1), t[2]))
    return out


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Highest-epoch checkpoint path (the resume point the reference lacks)."""
    ckpts = list_checkpoints(ckpt_dir)
    return ckpts[-1][3] if ckpts else None


def select_checkpoint(ckpt_dir: str, stage: str = "nopush",
                      policy: str = "best"):
    """(epoch, stage, acc, path) of the requested stage, or None.

    policy='best' — highest test accuracy: how the reference chose its
    released eval checkpoints (eval_purity.py:55 `104nopush0.8224`).
    policy='latest' — highest epoch. One definition for every evidence/eval
    consumer so checkpoint-selection can't silently diverge between them."""
    if policy not in ("best", "latest"):
        raise ValueError(f"unknown policy {policy!r}")
    ckpts = [c for c in list_checkpoints(ckpt_dir) if c[1] == stage]
    if not ckpts:
        return None
    return max(ckpts, key=lambda c: c[2]) if policy == "best" else ckpts[-1]


def adopt_checkpoint_train_config(cfg, path: str, log=None):
    """Return cfg with training-time settings recorded in the checkpoint's
    metadata adopted for restore/eval. The single definition behind
    cli/evaluate, cli/interpret, and the evidence scripts. Adopts:

    - `model.compute_dtype`: evaluating under different numerics silently
      shifts the p(x) scale OoD thresholding rides on;
    - `loss.aux_loss`: proxy-based losses carry a params['proxies'] leaf
      (plus optimizer-state leaves), so a restore target built with the
      wrong aux_loss has a mismatching pytree STRUCTURE and orbax restore
      fails outright;
    - `em.reference_stepping`: resuming a reference-stepping run without
      re-passing the flag would silently switch EM math mid-training (the
      two paths share a pytree structure, so nothing else would catch it).

    Checkpoints predating a metadata key keep cfg's value for it."""
    import dataclasses

    meta = load_metadata(path) or {}
    ckpt_dtype = meta.get("compute_dtype")
    if ckpt_dtype and ckpt_dtype != cfg.model.compute_dtype:
        if log is not None:
            log(
                f"note: checkpoint was trained with compute_dtype="
                f"{ckpt_dtype}; overriding {cfg.model.compute_dtype}"
            )
        cfg = cfg.replace(
            model=dataclasses.replace(cfg.model, compute_dtype=ckpt_dtype)
        )
    ckpt_aux = meta.get("aux_loss")
    if ckpt_aux and ckpt_aux != cfg.loss.aux_loss:
        if log is not None:
            log(
                f"note: checkpoint was trained with aux_loss={ckpt_aux}; "
                f"overriding {cfg.loss.aux_loss}"
            )
        cfg = cfg.replace(
            loss=dataclasses.replace(cfg.loss, aux_loss=ckpt_aux)
        )
    ckpt_ref_em = meta.get("em_reference_stepping")
    if ckpt_ref_em is not None and ckpt_ref_em != cfg.em.reference_stepping:
        if log is not None:
            log(
                f"note: checkpoint was trained with em.reference_stepping="
                f"{ckpt_ref_em}; overriding {cfg.em.reference_stepping}"
            )
        cfg = cfg.replace(
            em=dataclasses.replace(cfg.em, reference_stepping=ckpt_ref_em)
        )
    return cfg
