"""Checkpoint / resume for the full functional train state.

The reference saves only `state_dict` when test accuracy clears a threshold
(reference utils/save.py:5-12) — optimizer state is dropped and there is no
resume path (reference main.py:31-33 even deletes the model dir on restart;
SURVEY.md §5.3-5.4). Here a checkpoint is the WHOLE `TrainState` pytree
(params, batch_stats, GMM, memory bank, all three optimizer states, step), so
training resumes bit-exactly, via orbax.

Filename convention keeps the reference's readable encoding
(`{epoch}{stage}{accuracy}` e.g. `104nopush0.8224`, reference utils/save.py:9)
as a directory name per checkpoint.

Preemption-safety (ISSUE 2 tentpole): every save is ATOMIC — the pytree is
written to `<name>.tmp`, an integrity manifest (leaf paths/shapes/dtypes +
step) is added, and only then is the directory renamed into place, so a
SIGKILL mid-save can never leave a half-written checkpoint where
`find_latest_checkpoint` would pick it up. Restores verify the manifest
against the restore target BEFORE orbax runs (a structure mismatch fails
with a readable diff, not an orbax stack trace) and against the restored
step AFTER. Writes retry through `resilience.retry` (transient FS errors on
preemptible fleets), and `apply_retention` keeps the last N + best-accuracy
checkpoints so long runs don't fill the disk.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, List, Optional, Tuple

import jax

_NAME_RE = re.compile(r"^(\d+)([a-z_]+)(\d+\.\d+)$")

MANIFEST_FILE = "mgproto_manifest.json"
MANIFEST_FORMAT = 1
TMP_SUFFIX = ".tmp"


def _checkpointer():
    # orbax import kept lazy: it is needed only when actually checkpointing,
    # not by every consumer of the utils package
    import orbax.checkpoint as ocp

    return ocp.Checkpointer(ocp.PyTreeCheckpointHandler())


def checkpoint_name(epoch: int, stage: str, accuracy: float) -> str:
    """`{epoch}{stage}{acc:.4f}` (reference utils/save.py:9 filename scheme)."""
    return f"{epoch}{stage}{accuracy:.4f}"


def parse_checkpoint_name(name: str) -> Optional[Tuple[int, str, float]]:
    m = _NAME_RE.match(name)
    if not m:
        return None
    return int(m.group(1)), m.group(2), float(m.group(3))


def _tree_manifest(host_state: Any) -> dict:
    """Integrity manifest for a HOST pytree: every leaf's keypath, shape and
    dtype, plus the scalar step when the tree carries one. Cheap to build
    (metadata only) and cheap to verify — corruption of the pytree
    STRUCTURE (wrong aux_loss, truncated write, version skew) is caught
    before orbax ever runs."""
    import numpy as np

    leaves = []
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(host_state)[0]:
        arr = np.asarray(leaf)
        leaves.append({
            "path": jax.tree_util.keystr(keypath),
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        })
    step = getattr(host_state, "step", None)
    return {
        "format": MANIFEST_FORMAT,
        "num_leaves": len(leaves),
        "step": None if step is None else int(np.asarray(step)),
        "leaves": leaves,
    }


def load_manifest(path: str) -> Optional[dict]:
    """The checkpoint's manifest, or None when absent (pre-manifest save).
    Raises CheckpointIntegrityError on an unreadable/wrong-format manifest
    (a torn write — the checkpoint must not be trusted)."""
    mpath = os.path.join(path, MANIFEST_FILE)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointIntegrityError(f"unreadable manifest in {path}: {e}")
    if manifest.get("format") != MANIFEST_FORMAT or "leaves" not in manifest:
        raise CheckpointIntegrityError(
            f"manifest in {path} has unknown format {manifest.get('format')!r}"
        )
    return manifest


class CheckpointIntegrityError(RuntimeError):
    """Manifest missing/corrupt or mismatching the restore target."""


def _verify_manifest(manifest: dict, target: Any, path: str) -> None:
    import numpy as np

    want = {}
    for keypath, leaf in jax.tree_util.tree_flatten_with_path(target)[0]:
        shape = tuple(getattr(leaf, "shape", np.shape(leaf)))
        dtype = str(getattr(leaf, "dtype", np.asarray(leaf).dtype))
        want[jax.tree_util.keystr(keypath)] = (shape, dtype)
    got = {e["path"]: (tuple(e["shape"]), e["dtype"])
           for e in manifest["leaves"]}
    if got == want:
        return
    missing = sorted(set(want) - set(got))[:3]
    extra = sorted(set(got) - set(want))[:3]
    diff = sorted(
        k for k in set(got) & set(want) if got[k] != want[k]
    )[:3]
    detail = []
    if missing:
        detail.append(f"missing from checkpoint: {missing}")
    if extra:
        detail.append(f"unexpected in checkpoint: {extra}")
    for k in diff:
        detail.append(f"{k}: checkpoint {got[k]} vs target {want[k]}")
    raise CheckpointIntegrityError(
        f"checkpoint {path} does not match the restore target "
        f"({len(got)} vs {len(want)} leaves); " + "; ".join(detail)
    )


def save_checkpoint(
    ckpt_dir: str,
    state: Any,
    name: str,
    metadata: Optional[dict] = None,
    retries: int = 2,
) -> str:
    """Write `state` (any pytree of arrays) to `ckpt_dir/name`, atomically.

    The pytree, its integrity manifest, and any metadata all land in
    `<name>.tmp` first; the final rename is the publish point, so a kill at
    ANY earlier moment leaves only a `.tmp` directory that every listing
    here skips. Failed attempts (counted in
    `checkpoint_write_failures_total`) are retried with backoff."""
    from mgproto_tpu.resilience import metrics as _m
    from mgproto_tpu.resilience.chaos import get_active
    from mgproto_tpu.resilience.retry import retry_call

    path = os.path.abspath(os.path.join(ckpt_dir, name))
    tmp = path + TMP_SUFFIX

    def _write() -> None:
        try:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
            host_state = jax.device_get(state)
            _checkpointer().save(tmp, host_state, force=True)
            with open(os.path.join(tmp, MANIFEST_FILE), "w") as f:
                json.dump(_tree_manifest(host_state), f)
            if metadata is not None:
                with open(os.path.join(tmp, "mgproto_meta.json"), "w") as f:
                    json.dump(metadata, f)
            chaos = get_active()
            if chaos is not None and chaos.checkpoint_should_fail():
                # simulated kill between tmp write and publish rename
                raise IOError(f"chaos: injected checkpoint write failure ({name})")
            if os.path.isdir(path):
                shutil.rmtree(path)  # force=True overwrite semantics
            os.rename(tmp, path)
        except Exception:
            _m.counter(_m.CKPT_WRITE_FAILURES).inc()
            raise

    retry_call(_write, retries=retries, base_delay=0.1, max_delay=2.0,
               scope="checkpoint")
    return path


def restore_checkpoint(path: str, target: Any) -> Any:
    """Restore a pytree with the structure/shardings of `target`.

    `target` is a concrete state (e.g. a fresh `Trainer.init_state(...)`);
    restored arrays adopt its dtypes and shardings, so a restore into a
    sharded state lands directly on the mesh.

    When the checkpoint carries a manifest it is verified against `target`
    BEFORE orbax runs (structure mismatches fail readably) and against the
    restored step AFTER (a truncated array payload cannot masquerade as a
    clean resume point)."""
    path = os.path.abspath(path)
    manifest = load_manifest(path)
    if manifest is not None:
        _verify_manifest(manifest, target, path)
    restored = _checkpointer().restore(path, item=target)
    if manifest is not None and manifest.get("step") is not None:
        restored_step = getattr(restored, "step", None)
        if restored_step is not None:
            got = int(jax.device_get(restored_step))
            if got != int(manifest["step"]):
                raise CheckpointIntegrityError(
                    f"checkpoint {path}: restored step {got} != manifest "
                    f"step {manifest['step']}"
                )
    return restored


def pytree_digest(tree: Any) -> str:
    """sha256 over a pytree's structure + exact leaf bytes. Two states with
    the same digest stepped identically stay identical — the equality the
    chaos tests assert between a fault-ridden run and a clean one."""
    import hashlib

    import numpy as np

    host = jax.device_get(tree)
    leaves, treedef = jax.tree_util.tree_flatten(host)
    h = hashlib.sha256(str(treedef).encode())
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(f"{arr.shape}{arr.dtype}".encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def load_metadata(path: str) -> Optional[dict]:
    meta = os.path.join(path, "mgproto_meta.json")
    if not os.path.exists(meta):
        return None
    with open(meta) as f:
        return json.load(f)


def save_state_w_condition(
    ckpt_dir: str,
    state: Any,
    epoch: int,
    stage: str,
    accuracy: float,
    target_accuracy: float,
    metadata: Optional[dict] = None,
) -> Optional[str]:
    """Parity with reference utils/save.py:5-12: save only when accuracy
    clears the threshold; name encodes epoch/stage/accuracy."""
    if accuracy <= target_accuracy:
        return None
    meta = dict(metadata or {})
    meta.update(epoch=epoch, stage=stage, accuracy=accuracy)
    return save_checkpoint(
        ckpt_dir, state, checkpoint_name(epoch, stage, accuracy), metadata=meta
    )


# Within one epoch the reference saves nopush, then push, then prune
# (reference main.py:255/281/287) — resume must pick the latest STAGE, not the
# highest accuracy (push/prune typically dip). "preempt" checkpoints are
# taken MID-epoch, before that epoch's nopush save, so they order first.
_STAGE_ORDER = {"preempt": -1, "nopush": 0, "push": 1, "prune": 2}


def _manifest_state(path: str) -> str:
    """'ok' (valid manifest), 'missing' (pre-manifest legacy save), or
    'bad' (torn/corrupt manifest — never trust the checkpoint)."""
    try:
        manifest = load_manifest(path)
    except CheckpointIntegrityError:
        return "bad"
    return "ok" if manifest is not None else "missing"


def list_checkpoints(ckpt_dir: str, require_manifest: bool = False):
    """All parseable checkpoints in `ckpt_dir` as (epoch, stage, acc, path),
    ordered by (epoch, stage progression). In-flight `.tmp` saves and
    checkpoints with a CORRUPT manifest are always skipped;
    `require_manifest=True` additionally skips legacy manifest-less saves
    (the strict listing `find_latest_checkpoint` resumes from)."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        if name.endswith(TMP_SUFFIX):
            continue  # unpublished (interrupted) save
        parsed = parse_checkpoint_name(name)
        if not parsed or not os.path.isdir(os.path.join(ckpt_dir, name)):
            continue
        mstate = _manifest_state(os.path.join(ckpt_dir, name))
        if mstate == "bad" or (require_manifest and mstate != "ok"):
            continue
        out.append((*parsed, os.path.join(ckpt_dir, name)))
    out.sort(key=lambda t: (t[0], _STAGE_ORDER.get(t[1], -2), t[2]))
    return out


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """Highest-epoch checkpoint path (the resume point the reference lacks)."""
    ckpts = list_checkpoints(ckpt_dir)
    return ckpts[-1][3] if ckpts else None


def find_latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """The newest checkpoint SAFE to resume from: latest by (epoch, stage)
    among checkpoints with a verified-parseable manifest; `.tmp` leftovers
    and torn saves never qualify. The `--resume auto` and rollback entry
    point."""
    ckpts = list_checkpoints(ckpt_dir, require_manifest=True)
    return ckpts[-1][3] if ckpts else None


def apply_retention(
    ckpt_dir: str, keep_last: int, keep_best: int = 1
) -> List[str]:
    """Delete old checkpoints, keeping the newest `keep_last` by (epoch,
    stage) order plus the `keep_best` highest-accuracy ones (the eval
    artifacts the reference's threshold saves were for). `keep_last <= 0`
    disables retention. Returns the deleted paths."""
    if keep_last <= 0:
        return []
    ckpts = list_checkpoints(ckpt_dir)
    keep = {c[3] for c in ckpts[-keep_last:]}
    if keep_best > 0:
        by_acc = sorted(ckpts, key=lambda c: c[2], reverse=True)
        keep.update(c[3] for c in by_acc[:keep_best])
    removed = []
    for c in ckpts:
        if c[3] not in keep:
            shutil.rmtree(c[3], ignore_errors=True)
            removed.append(c[3])
    return removed


def select_checkpoint(ckpt_dir: str, stage: str = "nopush",
                      policy: str = "best"):
    """(epoch, stage, acc, path) of the requested stage, or None.

    policy='best' — highest test accuracy: how the reference chose its
    released eval checkpoints (eval_purity.py:55 `104nopush0.8224`).
    policy='latest' — highest epoch. One definition for every evidence/eval
    consumer so checkpoint-selection can't silently diverge between them."""
    if policy not in ("best", "latest"):
        raise ValueError(f"unknown policy {policy!r}")
    ckpts = [c for c in list_checkpoints(ckpt_dir) if c[1] == stage]
    if not ckpts:
        return None
    return max(ckpts, key=lambda c: c[2]) if policy == "best" else ckpts[-1]


def adopt_checkpoint_train_config(cfg, path: str, log=None):
    """Return cfg with training-time settings recorded in the checkpoint's
    metadata adopted for restore/eval. The single definition behind
    cli/evaluate, cli/interpret, and the evidence scripts. Adopts:

    - `model.compute_dtype`: evaluating under different numerics silently
      shifts the p(x) scale OoD thresholding rides on;
    - `loss.aux_loss`: proxy-based losses carry a params['proxies'] leaf
      (plus optimizer-state leaves), so a restore target built with the
      wrong aux_loss has a mismatching pytree STRUCTURE and orbax restore
      fails outright;
    - `em.reference_stepping`: resuming a reference-stepping run without
      re-passing the flag would silently switch EM math mid-training (the
      two paths share a pytree structure, so nothing else would catch it).

    Checkpoints predating a metadata key keep cfg's value for it."""
    import dataclasses

    meta = load_metadata(path) or {}
    ckpt_dtype = meta.get("compute_dtype")
    if ckpt_dtype and ckpt_dtype != cfg.model.compute_dtype:
        if log is not None:
            log(
                f"note: checkpoint was trained with compute_dtype="
                f"{ckpt_dtype}; overriding {cfg.model.compute_dtype}"
            )
        cfg = cfg.replace(
            model=dataclasses.replace(cfg.model, compute_dtype=ckpt_dtype)
        )
    ckpt_aux = meta.get("aux_loss")
    if ckpt_aux and ckpt_aux != cfg.loss.aux_loss:
        if log is not None:
            log(
                f"note: checkpoint was trained with aux_loss={ckpt_aux}; "
                f"overriding {cfg.loss.aux_loss}"
            )
        cfg = cfg.replace(
            loss=dataclasses.replace(cfg.loss, aux_loss=ckpt_aux)
        )
    ckpt_ref_em = meta.get("em_reference_stepping")
    if ckpt_ref_em is not None and ckpt_ref_em != cfg.em.reference_stepping:
        if log is not None:
            log(
                f"note: checkpoint was trained with em.reference_stepping="
                f"{ckpt_ref_em}; overriding {cfg.em.reference_stepping}"
            )
        cfg = cfg.replace(
            em=dataclasses.replace(cfg.em, reference_stepping=ckpt_ref_em)
        )
    return cfg
