"""Logging / metrics / profiling.

Covers the reference's three observability channels (SURVEY.md §5.5):
file logger with periodic fsync (reference utils/log.py:4-17), wandb scalar
streams (reference train_and_test.py:73-80 — disabled by default there,
main.py:53; here a local JSONL stream with the same keys), and wall-clock
spans (reference train_and_test.py:17,87-89). Adds what the reference lacks:
a `jax.profiler` trace harness for real TPU profiling.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
from typing import Any, Dict, Optional


class Logger:
    """Append-file + stdout logger, fsync every `flush_every` lines
    (reference utils/log.py:4-17 closure, as a class with close())."""

    def __init__(self, log_path: Optional[str], flush_every: int = 10):
        self.path = log_path
        self.flush_every = flush_every
        self._count = 0
        self._f = None
        if log_path:
            os.makedirs(os.path.dirname(os.path.abspath(log_path)), exist_ok=True)
            self._f = open(log_path, "a")

    def log(self, message: str) -> None:
        print(message)
        sys.stdout.flush()
        if self._f is None:
            return
        self._f.write(message + "\n")
        self._count += 1
        if self._count % self.flush_every == 0:
            self._f.flush()
            os.fsync(self._f.fileno())

    __call__ = log

    def close(self) -> None:
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None


class MetricsWriter:
    """JSONL scalar stream — the local stand-in for the reference's wandb
    channel (reference main.py:53-54, train_and_test.py:73-80). One JSON
    object per `write()`, always stamped with step and wall time."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._f = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._f = open(path, "a")

    def write(self, step: int, scalars: Dict[str, Any]) -> None:
        if self._f is None:
            return
        rec = {"step": int(step), "time": time.time()}
        for k, v in scalars.items():
            if isinstance(v, (str, bool, type(None))):
                rec[k] = v
                continue
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                rec[k] = str(v)
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


@contextlib.contextmanager
def timed_span(logger: Logger, name: str):
    """Wall-clock span (reference train_and_test.py:17,87-89 semantics)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        logger.log(f"\t{name} time: \t{time.perf_counter() - t0:.2f}s")


@contextlib.contextmanager
def profiler_trace(logdir: Optional[str]):
    """jax.profiler trace around a block; no-op when logdir is falsy.
    View with TensorBoard / xprof. The reference has no profiler hooks
    (SURVEY.md §5.1) — this is the TPU-native upgrade."""
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
