"""Logging / metrics / profiling — thin wrappers over `mgproto_tpu.telemetry`.

Covers the reference's three observability channels (SURVEY.md §5.5): file
logger with periodic fsync (reference utils/log.py:4-17), wandb scalar
streams (reference train_and_test.py:73-80 — here a local JSONL stream with
the same keys), and wall-clock spans (reference train_and_test.py:17,87-89).

These classes predate the telemetry subsystem and stay for their call sites
and tests; the machinery is telemetry's: the file core is
`telemetry.registry.JsonlWriter` (batched flush+fsync, write-after-close
guard), `MetricsWriter` mirrors every numeric scalar into the process
metric registry (so the run's Prometheus/JSONL snapshots carry loss/acc/...
without new call sites), and `timed_span` records a real tracing span on
the default tracer in addition to its log line. The deeper instrumentation
— step monitors, model health, Chrome traces — lives in `telemetry/`.
"""

from __future__ import annotations

import contextlib
import sys
import time
from typing import Any, Dict, Optional

from mgproto_tpu.telemetry.registry import (
    JsonlWriter,
    MetricRegistry,
    default_registry,
)
from mgproto_tpu.telemetry.tracing import trace_span


class Logger:
    """Append-file + stdout logger, fsync every `flush_every` lines
    (reference utils/log.py:4-17 closure, as a class with close()).
    Logging after `close()` still prints but never touches the closed
    file (the old implementation could raise `ValueError: I/O operation
    on closed file` from late callers, e.g. an exception handler logging
    after the normal shutdown path ran)."""

    def __init__(self, log_path: Optional[str], flush_every: int = 10):
        self.path = log_path
        self._w = JsonlWriter(log_path, flush_every=flush_every)

    def log(self, message: str) -> None:
        print(message)
        sys.stdout.flush()
        self._w.write_line(message)

    __call__ = log

    def close(self) -> None:
        self._w.close()


class MetricsWriter:
    """JSONL scalar stream — the local stand-in for the reference's wandb
    channel (reference main.py:53-54, train_and_test.py:73-80). One JSON
    object per `write()`, always stamped with step and wall time; fsync is
    batched (every `flush_every` writes) like `Logger`, not per line. The
    tradeoff is explicit: a hard kill (no close()) can lose up to
    `flush_every - 1` buffered records — callers streaming at epoch cadence
    who need per-record durability should pass `flush_every=1`.

    Every numeric scalar is also mirrored into the metric registry as a
    `run_<key>` gauge, so telemetry's Prometheus/JSONL sinks see the same
    stream without a second call site."""

    def __init__(
        self,
        path: Optional[str],
        flush_every: int = 10,
        registry: Optional[MetricRegistry] = None,
    ):
        self.path = path
        # None = resolve per write: the process-CURRENT registry, so a
        # TelemetrySession installed after this writer is constructed still
        # receives the mirrored scalars
        self._registry = registry
        self._w = JsonlWriter(path, flush_every=flush_every)

    @property
    def registry(self) -> MetricRegistry:
        return self._registry if self._registry is not None else default_registry()

    def write(self, step: int, scalars: Dict[str, Any]) -> None:
        if self.path is None:
            return
        rec = {"step": int(step), "time": time.time()}
        for k, v in scalars.items():
            if isinstance(v, (str, bool, type(None), dict, list, tuple)):
                rec[k] = v
                continue
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                rec[k] = str(v)
            else:
                try:
                    self.registry.gauge(f"run_{k}").set(rec[k])
                except ValueError:
                    pass  # key not a legal metric name; JSONL still has it
        self._w.write(rec)

    def close(self) -> None:
        self._w.close()


@contextlib.contextmanager
def timed_span(logger: Logger, name: str):
    """Wall-clock span (reference train_and_test.py:17,87-89 semantics).
    Also records a nesting tracing span on the default tracer, so runs
    driven through the classic call sites still produce a Chrome trace."""
    t0 = time.perf_counter()
    with trace_span(name):
        try:
            yield
        finally:
            logger.log(f"\t{name} time: \t{time.perf_counter() - t0:.2f}s")


@contextlib.contextmanager
def profiler_trace(logdir: Optional[str], create_perfetto_link: bool = False):
    """jax.profiler trace around a block; no-op when logdir is falsy.
    View with TensorBoard / xprof. The reference has no profiler hooks
    (SURVEY.md §5.1) — this is the TPU-native upgrade.

    Exception-safe: `stop_trace` runs only if `start_trace` succeeded, and
    a `stop_trace` failure during exception unwind never masks the body's
    exception. `create_perfetto_link=True` passes through to jax (prints a
    Perfetto UI link when the trace closes; older jax without the kwarg
    falls back silently)."""
    if not logdir:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    except TypeError:
        # jax predating the kwarg
        jax.profiler.start_trace(logdir)
    try:
        yield
    except BaseException:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass  # don't mask the body's exception with a stop failure
        raise
    jax.profiler.stop_trace()
