"""Host-side prototype visualization (cv2/matplotlib — stays on CPU).

Behavior-parity with reference utils/helpers.py:38-74 (95th-percentile
connected-component crop) and push.py:202-226 (heatmap overlay + bbox
rendering). These run on numpy arrays pulled off-device; nothing here is
jitted or traced."""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np


def makedir(path: str) -> None:
    os.makedirs(path, exist_ok=True)


def find_high_activation_crop(
    activation_map: np.ndarray, percentile: float = 95
) -> Tuple[int, int, int, int]:
    """Bounding box (y0, y1, x0, x1) of the connected component of
    above-percentile activation that contains the activation peak
    (reference utils/helpers.py:38-74)."""
    import cv2

    threshold = np.percentile(activation_map, percentile)
    mask = (activation_map >= threshold).astype(np.uint8)
    peak_y, peak_x = np.unravel_index(
        np.argmax(activation_map), activation_map.shape
    )
    n_labels, labeled = cv2.connectedComponents(mask, connectivity=8)
    peak_label = labeled[peak_y, peak_x]
    if peak_label != 0:
        mask = (labeled == peak_label).astype(np.uint8)

    ys = np.where(mask.max(axis=1) > 0)[0]
    xs = np.where(mask.max(axis=0) > 0)[0]
    y0 = int(ys[0]) if ys.size else 0
    y1 = int(ys[-1]) if ys.size else 0
    x0 = int(xs[0]) if xs.size else 0
    x1 = int(xs[-1]) if xs.size else 0
    return (y0, y1 + 1, x0, x1 + 1)


def upsample_activation(act: np.ndarray, size_hw: Tuple[int, int]) -> np.ndarray:
    """Bicubic latent-grid -> pixel-grid upsample (reference push.py:208)."""
    import cv2

    return cv2.resize(
        act, dsize=(size_hw[1], size_hw[0]), interpolation=cv2.INTER_CUBIC
    )


def heatmap_overlay(img_rgb01: np.ndarray, act: np.ndarray) -> np.ndarray:
    """0.5*img + 0.3*jet(normalized act) (reference push.py:216-221)."""
    import cv2

    lo, hi = act.min(), act.max()
    rescaled = np.clip((act - lo) / max(hi - lo, 1e-12), 0, 1)
    heatmap = cv2.applyColorMap(np.uint8(255 * rescaled), cv2.COLORMAP_JET)
    heatmap = np.float32(heatmap) / 255
    heatmap = heatmap[..., ::-1]  # BGR -> RGB
    return 0.5 * img_rgb01 + 0.3 * heatmap


def imsave_with_bbox(
    fname: str,
    img_rgb01: np.ndarray,
    y0: int,
    y1: int,
    x0: int,
    x1: int,
    color=(0, 255, 255),
) -> None:
    """Save with a 2px rectangle (reference push.py:234-239)."""
    import cv2
    import matplotlib.pyplot as plt

    img_bgr = cv2.cvtColor(
        np.uint8(255 * np.clip(img_rgb01, 0, 1)), cv2.COLOR_RGB2BGR
    )
    cv2.rectangle(img_bgr, (x0, y0), (x1 - 1, y1 - 1), color, thickness=2)
    plt.imsave(fname, np.float32(img_bgr[..., ::-1]) / 255, vmin=0.0, vmax=1.0)


def imsave(fname: str, img_rgb01: np.ndarray) -> None:
    import matplotlib.pyplot as plt

    plt.imsave(fname, np.clip(img_rgb01, 0, 1), vmin=0.0, vmax=1.0)
