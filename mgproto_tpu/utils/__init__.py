"""Host-side utilities: image normalization, visualization, logging,
checkpointing."""

from mgproto_tpu.utils.checkpoint import (
    CheckpointIntegrityError,
    apply_retention,
    find_latest_checkpoint,
    latest_checkpoint,
    list_checkpoints,
    pytree_digest,
    restore_checkpoint,
    save_checkpoint,
    save_state_w_condition,
)
from mgproto_tpu.utils.log import Logger, MetricsWriter, profiler_trace, timed_span
from mgproto_tpu.utils.images import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    preprocess_input,
    undo_preprocess_input,
)
from mgproto_tpu.utils.vis import (
    find_high_activation_crop,
    heatmap_overlay,
    imsave,
    imsave_with_bbox,
    makedir,
    upsample_activation,
)

__all__ = [
    "CheckpointIntegrityError",
    "apply_retention",
    "find_latest_checkpoint",
    "latest_checkpoint",
    "list_checkpoints",
    "pytree_digest",
    "restore_checkpoint",
    "save_checkpoint",
    "save_state_w_condition",
    "Logger",
    "MetricsWriter",
    "profiler_trace",
    "timed_span",
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "preprocess_input",
    "undo_preprocess_input",
    "find_high_activation_crop",
    "heatmap_overlay",
    "imsave",
    "imsave_with_bbox",
    "makedir",
    "upsample_activation",
]
