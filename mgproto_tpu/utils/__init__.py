"""Host-side utilities: image normalization, visualization, logging."""

from mgproto_tpu.utils.images import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    preprocess_input,
    undo_preprocess_input,
)
from mgproto_tpu.utils.vis import (
    find_high_activation_crop,
    heatmap_overlay,
    imsave,
    imsave_with_bbox,
    makedir,
    upsample_activation,
)

__all__ = [
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "preprocess_input",
    "undo_preprocess_input",
    "find_high_activation_crop",
    "heatmap_overlay",
    "imsave",
    "imsave_with_bbox",
    "makedir",
    "upsample_activation",
]
