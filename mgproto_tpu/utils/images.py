"""Image normalization helpers (reference utils/preprocess.py:3-36).

Arrays are NHWC float32 in [0, 1]; normalization uses the torchvision
ImageNet statistics the pretrained backbones were trained with."""

from __future__ import annotations

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def preprocess_input(x):
    """[0,1] NHWC -> ImageNet-normalized (reference preprocess.py:15-20)."""
    return (x - IMAGENET_MEAN) / IMAGENET_STD


def undo_preprocess_input(x):
    """ImageNet-normalized NHWC -> [0,1] (reference preprocess.py:31-36)."""
    return x * IMAGENET_STD + IMAGENET_MEAN
