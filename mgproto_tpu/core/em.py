"""EM over the memory bank: the only trainer of prototype means and priors.

Reference semantics (/root/reference/model.py:277-401 + main.py:223-229):
per touched class with a FULL queue, run `num_em_loop` rounds of
  E-step:  responsibilities under current means/sigmas and momentum priors;
  M-step ("diversified"): additive-smoothed responsibilities give new priors;
           the MEANS take one Adam step on the responsibility-weighted NLL
           plus a diversity cost (mean off-diagonal exp(-||mu_i - mu_j||^2));
           sigmas are never trained;
  priors:  EMA with tau, written back into the classifier weights.

TPU-native redesign: instead of a 200-iteration python loop with per-class
optimizer stepping (reference model.py:281-298), ALL classes are processed at
once — per-class E-steps vmap over the leading class axis, inactive classes
are masked out of the loss, and ONE Adam step per EM round updates the whole
[C, K, d] means tensor. Deliberate deviation from the reference: inactive
classes' means are pinned exactly (the final jnp.where), whereas torch Adam
lets zero-grad params drift under nonzero moment decay — the drift is an
optimizer artifact, not a modeling choice, so we don't reproduce it by
default. `EMConfig.reference_stepping=True` switches to a reference-exact
sequential path (`_reference_em_update`) that reproduces the torch
bookkeeping — per-(class, round) Adam steps, shared moments, drift included —
measured against a torch oracle in tests/test_em_parity.py.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from mgproto_tpu.config import EMConfig
from mgproto_tpu.core.memory import Memory, clear_updated
from mgproto_tpu.core.mgproto import GMMState
from mgproto_tpu.ops.gaussian import (
    diag_gaussian_log_prob,
    e_step,
    momentum_update,
    pairwise_sq_dists,
)


class EMAux(NamedTuple):
    loss: jax.Array  # final-round masked m-step objective (scalar)
    num_active: jax.Array  # classes that ran EM this call
    log_likelihood: jax.Array  # mean E-step log-likelihood over active classes


def em_health_diagnostics(
    gmm: GMMState,
    memory: Memory,
    collapse_tol: float = 1e-3,
    sigma_floor: float = 1e-3,
    eps: float = 1e-10,
) -> dict:
    """Pure, jittable EM/prototype health diagnostics — the hook point
    telemetry's ModelHealth reads each epoch. Returns scalars only (so the
    output is replicated and host-readable under any mesh sharding):

      prior_entropy_mean/min: per-class mixture-prior entropy in nats over
        the renormalized priors (momentum write-back keeps sums near but not
        exactly 1). Entropy -> 0 means one prototype owns the class — the
        mixture has effectively collapsed to a single mode.
      min_interproto_dist: smallest intra-class distance between prototype
        means, over all classes. -> 0 means duplicate prototypes (the
        diversity cost failing).
      collapse_frac: fraction of intra-class prototype pairs closer than
        `collapse_tol` (euclidean).
      sigma_floor_frac: fraction of sigma entries at or below `sigma_floor`
        — the covariance-floor analogue for this model family (sigmas are
        frozen by design, so nonzero here means a checkpoint/restore or
        future trainable-sigma path drove them degenerate).
      memory_occupancy: mean fill fraction of the per-class queues.
      memory_full_frac: fraction of classes with a full queue (the EM
        eligibility gate).
      memory_updated_frac: fraction of classes touched since the last EM.
    """
    p = gmm.priors / jnp.maximum(
        jnp.sum(gmm.priors, axis=-1, keepdims=True), eps
    )
    ent = -jnp.sum(jnp.where(p > 0, p * jnp.log(p + eps), 0.0), axis=-1)  # [C]

    k = gmm.k_per_class
    if k > 1:
        sq = jax.vmap(pairwise_sq_dists)(gmm.means, gmm.means)  # [C, K, K]
        off = 1.0 - jnp.eye(k)
        sq_off = jnp.where(off > 0, sq, jnp.inf)
        min_d = jnp.sqrt(jnp.maximum(jnp.min(sq_off), 0.0))
        n_pairs = jnp.sum(off) * gmm.num_classes
        collapse = jnp.sum(sq_off < collapse_tol**2) / n_pairs
    else:
        # a 1-component mixture has no pairs to collapse
        min_d = jnp.zeros(())
        collapse = jnp.zeros(())

    cap = memory.capacity
    return {
        "prior_entropy_mean": jnp.mean(ent),
        "prior_entropy_min": jnp.min(ent),
        "min_interproto_dist": min_d,
        "collapse_frac": collapse,
        "sigma_floor_frac": jnp.mean(
            (gmm.sigmas <= sigma_floor).astype(jnp.float32)
        ),
        "memory_occupancy": jnp.mean(memory.length / cap),
        "memory_full_frac": jnp.mean(
            (memory.length == cap).astype(jnp.float32)
        ),
        "memory_updated_frac": jnp.mean(memory.updated.astype(jnp.float32)),
    }


def make_mean_optimizer(cfg: EMConfig) -> optax.GradientTransformation:
    """Adam on the means (reference main.py:223-227; its StepLR is created but
    never stepped — main.py:229 — so the lr is constant)."""
    return optax.adam(cfg.mean_lr)


def _class_objective(
    mu: jax.Array,
    x: jax.Array,
    resp: jax.Array,
    pi_old: jax.Array,
    sigmas: jax.Array,
    lam: float,
    eps: float = 1e-10,
) -> jax.Array:
    """The reference's per-class gmm_loss (model.py:387-393): responsibility-
    weighted NLL + diversity cost. Shapes: mu/sigmas [K,d], x [N,d],
    resp [N,K], pi_old [K]. The ONE definition of the M-step objective —
    vmapped by `_m_step_objective`, sliced by `_reference_em_update` — so the
    two EM modes provably optimize the same loss."""
    ll = diag_gaussian_log_prob(x, mu, sigmas) + jnp.log(pi_old + eps)
    weighted_nll = -jnp.mean(jnp.sum(resp * ll, axis=-1))
    pair = pairwise_sq_dists(mu, mu)
    off = 1.0 - jnp.eye(mu.shape[0])
    diversity = jnp.sum(jnp.exp(-pair) * off) / jnp.sum(off)
    return weighted_nll + lam * diversity


def _m_step_objective(
    means: jax.Array,
    x: jax.Array,
    resp: jax.Array,
    pi_old: jax.Array,
    sigmas: jax.Array,
    active: jax.Array,
    lam: float,
) -> jax.Array:
    """Masked sum over classes of `_class_objective`. Shapes: means/sigmas
    [C,K,d], x [C,N,d], resp [C,N,K], pi_old [C,K], active [C]."""
    per_class = jax.vmap(_class_objective, in_axes=(0, 0, 0, 0, 0, None))(
        means, x, resp, pi_old, sigmas, lam
    )
    return jnp.sum(per_class * active)


def _reference_em_update(
    gmm: GMMState,
    memory: Memory,
    opt_state: optax.OptState,
    mean_tx: optax.GradientTransformation,
    cfg: EMConfig,
    eps: float = 1e-10,
) -> Tuple[GMMState, Memory, optax.OptState, EMAux]:
    """Reference-exact stepping (cfg.reference_stepping=True).

    Reproduces the reference's control flow under jit: a sequential scan over
    classes IN ORDER (model.py:281); per active class, `num_em_loop` rounds of
    E-step → smoothed responsibilities → ONE Adam step whose gradient is
    nonzero only in that class's slice but which updates the WHOLE [C,K,d]
    tensor through the shared optimizer state (torch keeps one Adam over the
    full parameter, main.py:223-227 — zero-grad slices still move under
    moment decay, and the step count advances once per (class, round)) →
    τ-momentum prior write-back for that class. Inactive classes take no
    step of their own but DO drift during other classes' steps — the exact
    torch artifact the default path deliberately removes."""
    c_num, cap, _ = memory.feats.shape
    active = memory.updated & (memory.length == cap)
    x_all = memory.feats
    lam = cfg.diversity_lambda

    def class_step(carry, c):
        means, priors, opt_state = carry
        xc = x_all[c]  # [N, d]
        sig_c = gmm.sigmas[c]  # [K, d]

        def run(args):
            means, priors, opt_state = args

            def em_round(inner, _):
                means, pi_old, opt_state = inner
                ll_c, log_resp = e_step(xc, means[c], sig_c, pi_old)
                resp = jnp.exp(log_resp)
                resp = (resp + cfg.alpha) / jnp.sum(
                    resp + cfg.alpha, axis=-1, keepdims=True
                )
                pi_unnorm = jnp.sum(resp, axis=0) + eps

                def obj(m):
                    # m[c]: only this class's slice carries gradient
                    return _class_objective(
                        m[c], xc, resp, pi_old, sig_c, lam, eps
                    )

                loss, grads = jax.value_and_grad(obj)(means)
                updates, opt_state = mean_tx.update(grads, opt_state, means)
                means = optax.apply_updates(means, updates)
                pi_old = momentum_update(pi_old, pi_unnorm / cap, cfg.tau)
                return (means, pi_old, opt_state), (loss, ll_c)

            (means, pi_old, opt_state), (losses, lls) = jax.lax.scan(
                em_round, (means, priors[c], opt_state), None,
                length=cfg.num_em_loop,
            )
            priors = priors.at[c].set(pi_old)
            return means, priors, opt_state, losses[-1], lls[-1]

        def skip(args):
            means, priors, opt_state = args
            return means, priors, opt_state, jnp.zeros(()), jnp.zeros(())

        means, priors, opt_state, loss, ll = jax.lax.cond(
            active[c], run, skip, (means, priors, opt_state)
        )
        return (means, priors, opt_state), (loss, ll)

    (means, priors, opt_state), (losses, lls) = jax.lax.scan(
        class_step, (gmm.means, gmm.priors, opt_state), jnp.arange(c_num)
    )
    active_f = active.astype(jnp.float32)
    n_active = jnp.maximum(jnp.sum(active_f), 1.0)
    new_gmm = gmm._replace(means=means, priors=priors)
    return (
        new_gmm,
        clear_updated(memory),
        opt_state,
        EMAux(
            loss=jnp.sum(losses * active_f),
            num_active=jnp.sum(active),
            log_likelihood=jnp.sum(lls * active_f) / n_active,
        ),
    )


def em_update(
    gmm: GMMState,
    memory: Memory,
    opt_state: optax.OptState,
    mean_tx: optax.GradientTransformation,
    cfg: EMConfig,
    eps: float = 1e-10,
) -> Tuple[GMMState, Memory, optax.OptState, EMAux]:
    """One full EM call (reference `update_GMM`, model.py:277-301). Jittable;
    call every `update_interval` training steps once the epoch gate is open.
    Dispatches on cfg.reference_stepping (a static config bool): the
    TPU-native vmapped path below, or the reference-exact sequential path."""
    if cfg.reference_stepping:
        return _reference_em_update(gmm, memory, opt_state, mean_tx, cfg, eps)
    c, cap, _ = memory.feats.shape
    active = memory.updated & (memory.length == cap)  # model.py:283,289
    active_f = active.astype(jnp.float32)

    x = memory.feats  # [C, N, d]; full queues only, so no masking needed
    means, priors = gmm.means, gmm.priors
    pi_old = priors  # [C, K] (reference reads them from the last layer)

    loss = jnp.zeros(())
    ll_mean = jnp.zeros(())
    for _ in range(cfg.num_em_loop):
        ll, log_resp = jax.vmap(e_step, in_axes=(0, 0, 0, 0))(
            x, means, gmm.sigmas, pi_old
        )  # ll [C], log_resp [C, N, K] (vmapped e_step squeezes to [N, K])
        resp = jnp.exp(log_resp)
        resp = (resp + cfg.alpha) / jnp.sum(
            resp + cfg.alpha, axis=-1, keepdims=True
        )  # model.py:383
        pi_unnorm = jnp.sum(resp, axis=1) + eps  # [C, K], model.py:385

        loss, grads = jax.value_and_grad(_m_step_objective)(
            means, x, resp, pi_old, gmm.sigmas, active_f, cfg.diversity_lambda
        )
        updates, opt_state = mean_tx.update(grads, opt_state, means)
        means = optax.apply_updates(means, updates)

        pi_new = pi_unnorm / cap  # model.py:399
        pi_old = jnp.where(
            active[:, None], momentum_update(pi_old, pi_new, cfg.tau), pi_old
        )
        ll_mean = jnp.sum(ll * active_f) / jnp.maximum(jnp.sum(active_f), 1)

    new_gmm = gmm._replace(
        means=jnp.where(active[:, None, None], means, gmm.means),
        priors=pi_old,
    )
    return (
        new_gmm,
        clear_updated(memory),
        opt_state,
        EMAux(loss=loss, num_active=jnp.sum(active), log_likelihood=ll_mean),
    )
