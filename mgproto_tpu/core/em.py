"""EM over the memory bank: the only trainer of prototype means and priors.

Reference semantics (/root/reference/model.py:277-401 + main.py:223-229):
per touched class with a FULL queue, run `num_em_loop` rounds of
  E-step:  responsibilities under current means/sigmas and momentum priors;
  M-step ("diversified"): additive-smoothed responsibilities give new priors;
           the MEANS take one Adam step on the responsibility-weighted NLL
           plus a diversity cost (mean off-diagonal exp(-||mu_i - mu_j||^2));
           sigmas are never trained;
  priors:  EMA with tau, written back into the classifier weights.

TPU-native redesign: instead of a 200-iteration python loop with per-class
optimizer stepping (reference model.py:281-298), ALL classes are processed at
once — per-class E-steps vmap over the leading class axis, inactive classes
are masked out of the loss, and ONE Adam step per EM round updates the whole
[C, K, d] means tensor. Deliberate deviation from the reference: inactive
classes' means are pinned exactly (the final jnp.where), whereas torch Adam
lets zero-grad params drift under nonzero moment decay — the drift is an
optimizer artifact, not a modeling choice, so we don't reproduce it.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from mgproto_tpu.config import EMConfig
from mgproto_tpu.core.memory import Memory, clear_updated
from mgproto_tpu.core.mgproto import GMMState
from mgproto_tpu.ops.gaussian import (
    diag_gaussian_log_prob,
    e_step,
    momentum_update,
    pairwise_sq_dists,
)


class EMAux(NamedTuple):
    loss: jax.Array  # final-round masked m-step objective (scalar)
    num_active: jax.Array  # classes that ran EM this call
    log_likelihood: jax.Array  # mean E-step log-likelihood over active classes


def make_mean_optimizer(cfg: EMConfig) -> optax.GradientTransformation:
    """Adam on the means (reference main.py:223-227; its StepLR is created but
    never stepped — main.py:229 — so the lr is constant)."""
    return optax.adam(cfg.mean_lr)


def _m_step_objective(
    means: jax.Array,
    x: jax.Array,
    resp: jax.Array,
    pi_old: jax.Array,
    sigmas: jax.Array,
    active: jax.Array,
    lam: float,
    eps: float = 1e-10,
) -> jax.Array:
    """Masked sum over classes of the reference's per-class gmm_loss
    (model.py:387-393). Shapes: means/sigmas [C,K,d], x [C,N,d],
    resp [C,N,K], pi_old [C,K], active [C]."""
    ll = jax.vmap(diag_gaussian_log_prob)(x, means[:, None], sigmas[:, None])
    # vmap gives [C, N, 1, K]; weighted NLL: sum over K, mean over N
    ll = ll[:, :, 0, :] + jnp.log(pi_old + eps)[:, None, :]  # [C, N, K]
    weighted_nll = -jnp.mean(jnp.sum(resp * ll, axis=-1), axis=-1)  # [C]

    pair = jax.vmap(pairwise_sq_dists)(means, means)  # [C, K, K]
    k = means.shape[1]
    off = 1.0 - jnp.eye(k)
    diversity = jnp.sum(jnp.exp(-pair) * off, axis=(1, 2)) / jnp.sum(off)  # [C]

    per_class = weighted_nll + lam * diversity
    return jnp.sum(per_class * active)


def em_update(
    gmm: GMMState,
    memory: Memory,
    opt_state: optax.OptState,
    mean_tx: optax.GradientTransformation,
    cfg: EMConfig,
    eps: float = 1e-10,
) -> Tuple[GMMState, Memory, optax.OptState, EMAux]:
    """One full EM call (reference `update_GMM`, model.py:277-301). Jittable;
    call every `update_interval` training steps once the epoch gate is open."""
    c, cap, _ = memory.feats.shape
    active = memory.updated & (memory.length == cap)  # model.py:283,289
    active_f = active.astype(jnp.float32)

    x = memory.feats  # [C, N, d]; full queues only, so no masking needed
    means, priors = gmm.means, gmm.priors
    pi_old = priors  # [C, K] (reference reads them from the last layer)

    loss = jnp.zeros(())
    ll_mean = jnp.zeros(())
    for _ in range(cfg.num_em_loop):
        ll, log_resp = jax.vmap(e_step, in_axes=(0, 0, 0, 0))(
            x, means, gmm.sigmas, pi_old
        )  # ll [C], log_resp [C, N, K] (vmapped e_step squeezes to [N, K])
        resp = jnp.exp(log_resp)
        resp = (resp + cfg.alpha) / jnp.sum(
            resp + cfg.alpha, axis=-1, keepdims=True
        )  # model.py:383
        pi_unnorm = jnp.sum(resp, axis=1) + eps  # [C, K], model.py:385

        loss, grads = jax.value_and_grad(_m_step_objective)(
            means, x, resp, pi_old, gmm.sigmas, active_f, cfg.diversity_lambda
        )
        updates, opt_state = mean_tx.update(grads, opt_state, means)
        means = optax.apply_updates(means, updates)

        pi_new = pi_unnorm / cap  # model.py:399
        pi_old = jnp.where(
            active[:, None], momentum_update(pi_old, pi_new, cfg.tau), pi_old
        )
        ll_mean = jnp.sum(ll * active_f) / jnp.maximum(jnp.sum(active_f), 1)

    new_gmm = gmm._replace(
        means=jnp.where(active[:, None, None], means, gmm.means),
        priors=pi_old,
    )
    return (
        new_gmm,
        clear_updated(memory),
        opt_state,
        EMAux(loss=loss, num_active=jnp.sum(active), log_likelihood=ll_mean),
    )
