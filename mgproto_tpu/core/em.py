"""EM over the memory bank: the only trainer of prototype means and priors.

Reference semantics (/root/reference/model.py:277-401 + main.py:223-229):
per touched class with a FULL queue, run `num_em_loop` rounds of
  E-step:  responsibilities under current means/sigmas and momentum priors;
  M-step ("diversified"): additive-smoothed responsibilities give new priors;
           the MEANS take one Adam step on the responsibility-weighted NLL
           plus a diversity cost (mean off-diagonal exp(-||mu_i - mu_j||^2));
           sigmas are never trained;
  priors:  EMA with tau, written back into the classifier weights.

TPU-native redesign: instead of a 200-iteration python loop with per-class
optimizer stepping (reference model.py:281-298), ALL classes are processed at
once — per-class E-steps vmap over the leading class axis, inactive classes
are masked out of the loss, and ONE Adam step per EM round updates the whole
[C, K, d] means tensor. Deliberate deviation from the reference: inactive
classes' means are pinned exactly (the final jnp.where), whereas torch Adam
lets zero-grad params drift under nonzero moment decay — the drift is an
optimizer artifact, not a modeling choice, so we don't reproduce it by
default. `EMConfig.reference_stepping=True` switches to a reference-exact
sequential path (`_reference_em_update`) that reproduces the torch
bookkeeping — per-(class, round) Adam steps, shared moments, drift included —
measured against a torch oracle in tests/test_em_parity.py.

Bank fast path (the post-measurement MFU work, PERF.md): at steady state EM
runs EVERY step, and its bank traffic — not its FLOPs — is what stalls the
step. Two composable levers, both default-path only:

  * COMPACT DIRTY-CLASS EM (`max_active_classes` > 0): a train batch of B
    rows can newly dirty at most B classes, so instead of reducing over all
    C banks, a fixed-width lax.top_k + gather pulls the <=A dirty banks into
    an [A, N, d] slab, E/M runs there, and means/priors scatter back —
    ~C/A x less bank traffic (2.5x at flagship C=200, B=80). If more than A
    classes are dirty (first call after the epoch gate opens), a lax.cond
    falls back to the dense path for that call: both branches are compiled
    once, so the fallback is a counter event, never a recompile.
  * FUSED E-STEP (`fused_estep`, ops/em_kernels.py): responsibilities and
    their sufficient statistics (sum r, sum r x, sum r x^2) computed in one
    VMEM pass; the m-step objective is then evaluated in sufficient-
    statistics form (`_m_step_objective_stats` — exactly the same math as
    `_m_step_objective`, since responsibilities are constants there), so no
    [N, K] intermediate ever reaches HBM, forward or backward.

Equivalence contracts are pinned in tests/test_em_compact.py; the dense path
(`max_active_classes=0`, `fused_estep=False`) is the pre-fast-path behavior
bit-for-bit, and `reference_stepping=True` is untouched.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from mgproto_tpu.config import EMConfig
from mgproto_tpu.core.memory import Memory, clear_updated, memory_push
from mgproto_tpu.core.mgproto import GMMState
from mgproto_tpu.perf.precision import assert_f32_stats
from mgproto_tpu.ops.em_kernels import em_estep_stats
from mgproto_tpu.ops.gaussian import (
    diag_gaussian_log_prob,
    e_step,
    momentum_update,
    pairwise_sq_dists,
    precompute_diag_gaussian,
)


class BankAux(NamedTuple):
    """Scalars the bank phase reports back to the step metrics."""

    num_active: jax.Array  # classes EM touched this call (0 when gated off)
    # dense-fallback flag forwarded from EMAux (telemetry counter)
    compact_fallback: jax.Array


def bank_update(
    gmm: GMMState,
    memory: Memory,
    opt_state: optax.OptState,
    mean_tx: optax.GradientTransformation,
    cfg: EMConfig,
    feats: jax.Array,
    classes: jax.Array,
    valid: jax.Array,
    step: jax.Array,
    update_gmm: jax.Array,
    finite: jax.Array,
    mesh=None,
) -> Tuple[GMMState, Memory, optax.OptState, BankAux]:
    """The BANK PHASE of one train step: memory enqueue + gated EM.

    This is the ONE definition of the phase, shared by the monolithic train
    step and the standalone async bank program (engine/train.py) so the two
    cannot drift: under `--async_bank` the same function is compiled as its
    own program and dispatched one step behind the trunk.

    Gating (reference train_and_test.py:61-63 + the divergence guard):
      * `finite` (the trunk's loss/grad finiteness) freezes BOTH the enqueue
        and EM — a poisoned batch must not touch the bank;
      * EM additionally requires the epoch flag `update_gmm`, the step
        interval phase (`step` is the PRE-increment counter of the batch the
        candidates came from — under the async pipeline that is the
        *previous* batch's counter, keeping the interval phase identical to
        the synchronous schedule), and a non-empty bank.

    All gates are traced scalars under lax.cond: one compiled program,
    zero steady-state recompiles.
    """
    # the f32-statistics invariant (perf/precision.py): under the mixed-
    # precision policy the trunk may run bf16, but the mixture, the bank
    # and the enqueue candidates entering it must still be f32 — checked
    # here at trace time, at the ONE entry both train modes share
    assert_f32_stats(gmm.means, "gmm.means")
    assert_f32_stats(gmm.priors, "gmm.priors")
    assert_f32_stats(memory.feats, "memory bank feats")
    assert_f32_stats(feats, "memory enqueue candidates")
    mem = jax.lax.cond(
        finite,
        lambda m: memory_push(m, feats, classes, valid),
        lambda m: m,
        memory,
    )
    interval_ok = (step % cfg.update_interval) == 0
    do_em = update_gmm & interval_ok & (jnp.sum(mem.length) > 0) & finite

    def run_em(args):
        g, m, o = args
        g, m, o, aux_em = em_update(g, m, o, mean_tx, cfg, mesh=mesh)
        return g, m, o, aux_em.num_active, aux_em.compact_fallback

    def skip_em(args):
        g, m, o = args
        return g, m, o, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)

    gmm, mem, opt_state, num_active, fallback = jax.lax.cond(
        do_em, run_em, skip_em, (gmm, mem, opt_state)
    )
    return gmm, mem, opt_state, BankAux(
        num_active=num_active, compact_fallback=fallback
    )


class EMAux(NamedTuple):
    loss: jax.Array  # final-round masked m-step objective (scalar)
    num_active: jax.Array  # classes that ran EM this call
    log_likelihood: jax.Array  # mean E-step log-likelihood over active classes
    # 1 when compaction was enabled but more classes were dirty than the
    # compact width, so this call took the dense lax.cond branch (telemetry:
    # em_compact_fallback_total); 0 otherwise. Under the class-sharded
    # shard_map path this is the psum'd COUNT of shards whose local slab
    # overflowed its local width this call (each shard contributes 0/1).
    compact_fallback: jax.Array


def em_health_diagnostics(
    gmm: GMMState,
    memory: Memory,
    collapse_tol: float = 1e-3,
    sigma_floor: float = 1e-3,
    eps: float = 1e-10,
) -> dict:
    """Pure, jittable EM/prototype health diagnostics — the hook point
    telemetry's ModelHealth reads each epoch. Returns scalars only (so the
    output is replicated and host-readable under any mesh sharding):

      prior_entropy_mean/min: per-class mixture-prior entropy in nats over
        the renormalized priors (momentum write-back keeps sums near but not
        exactly 1). Entropy -> 0 means one prototype owns the class — the
        mixture has effectively collapsed to a single mode.
      min_interproto_dist: smallest intra-class distance between prototype
        means, over all classes. -> 0 means duplicate prototypes (the
        diversity cost failing).
      collapse_frac: fraction of intra-class prototype pairs closer than
        `collapse_tol` (euclidean).
      sigma_floor_frac: fraction of sigma entries at or below `sigma_floor`
        — the covariance-floor analogue for this model family (sigmas are
        frozen by design, so nonzero here means a checkpoint/restore or
        future trainable-sigma path drove them degenerate).
      memory_occupancy: mean fill fraction of the per-class queues.
      memory_full_frac: fraction of classes with a full queue (the EM
        eligibility gate).
      memory_updated_frac: fraction of classes touched since the last EM.
    """
    p = gmm.priors / jnp.maximum(
        jnp.sum(gmm.priors, axis=-1, keepdims=True), eps
    )
    ent = -jnp.sum(jnp.where(p > 0, p * jnp.log(p + eps), 0.0), axis=-1)  # [C]

    k = gmm.k_per_class
    if k > 1:
        sq = jax.vmap(pairwise_sq_dists)(gmm.means, gmm.means)  # [C, K, K]
        off = 1.0 - jnp.eye(k)
        sq_off = jnp.where(off > 0, sq, jnp.inf)
        min_d = jnp.sqrt(jnp.maximum(jnp.min(sq_off), 0.0))
        n_pairs = jnp.sum(off) * gmm.num_classes
        collapse = jnp.sum(sq_off < collapse_tol**2) / n_pairs
    else:
        # a 1-component mixture has no pairs to collapse
        min_d = jnp.zeros(())
        collapse = jnp.zeros(())

    cap = memory.capacity
    return {
        "prior_entropy_mean": jnp.mean(ent),
        "prior_entropy_min": jnp.min(ent),
        "min_interproto_dist": min_d,
        "collapse_frac": collapse,
        "sigma_floor_frac": jnp.mean(
            (gmm.sigmas <= sigma_floor).astype(jnp.float32)
        ),
        "memory_occupancy": jnp.mean(memory.length / cap),
        "memory_full_frac": jnp.mean(
            (memory.length == cap).astype(jnp.float32)
        ),
        "memory_updated_frac": jnp.mean(memory.updated.astype(jnp.float32)),
    }


def make_mean_optimizer(cfg: EMConfig) -> optax.GradientTransformation:
    """Adam on the means (reference main.py:223-227; its StepLR is created but
    never stepped — main.py:229 — so the lr is constant)."""
    return optax.adam(cfg.mean_lr)


def resolve_em_config(
    cfg: EMConfig, num_classes: int, global_batch: int
) -> EMConfig:
    """Resolve `max_active_classes=-1` (auto) to min(C, global batch): one
    step's enqueue can newly dirty at most one class per batch row, so at
    EM-every-step steady state the compact slab provably covers every dirty
    class; the dense fallback only fires when EM was gated off long enough
    for dirt to accumulate (counted in telemetry, never a recompile)."""
    if cfg.max_active_classes != -1:
        return cfg
    return dataclasses.replace(
        cfg, max_active_classes=min(num_classes, max(int(global_batch), 1))
    )


def _resolve_fused_estep(cfg: EMConfig) -> Tuple[bool, bool]:
    """(use fused kernel, run it in interpret mode). None = auto, like
    ModelConfig.fused_scoring: Mosaic on TPU, off elsewhere (the interpreter
    is correct but slow — forcing True on CPU is for tests/microbenches)."""
    on_tpu = jax.default_backend() == "tpu"
    fused = cfg.fused_estep if cfg.fused_estep is not None else on_tpu
    return bool(fused), not on_tpu


def _class_objective(
    mu: jax.Array,
    x: jax.Array,
    resp: jax.Array,
    pi_old: jax.Array,
    sigmas: jax.Array,
    lam: float,
    eps: float = 1e-10,
) -> jax.Array:
    """The reference's per-class gmm_loss (model.py:387-393): responsibility-
    weighted NLL + diversity cost. Shapes: mu/sigmas [K,d], x [N,d],
    resp [N,K], pi_old [K]. The ONE definition of the M-step objective —
    vmapped by `_m_step_objective`, sliced by `_reference_em_update` — so the
    two EM modes provably optimize the same loss."""
    ll = diag_gaussian_log_prob(x, mu, sigmas) + jnp.log(pi_old + eps)
    weighted_nll = -jnp.mean(jnp.sum(resp * ll, axis=-1))
    pair = pairwise_sq_dists(mu, mu)
    off = 1.0 - jnp.eye(mu.shape[0])
    diversity = jnp.sum(jnp.exp(-pair) * off) / jnp.sum(off)
    return weighted_nll + lam * diversity


def _m_step_objective(
    means: jax.Array,
    x: jax.Array,
    resp: jax.Array,
    pi_old: jax.Array,
    sigmas: jax.Array,
    active: jax.Array,
    lam: float,
) -> jax.Array:
    """Masked sum over classes of `_class_objective`. Shapes: means/sigmas
    [C,K,d], x [C,N,d], resp [C,N,K], pi_old [C,K], active [C]."""
    per_class = jax.vmap(_class_objective, in_axes=(0, 0, 0, 0, 0, None))(
        means, x, resp, pi_old, sigmas, lam
    )
    return jnp.sum(per_class * active)


def _class_objective_stats(
    mu: jax.Array,
    s: jax.Array,
    sx: jax.Array,
    sxx: jax.Array,
    pi_old: jax.Array,
    sigmas: jax.Array,
    lam: float,
    n: int,
    eps: float = 1e-10,
) -> jax.Array:
    """`_class_objective` evaluated from SMOOTHED sufficient statistics
    (s [K], sx [K,d], sxx [K,d]) instead of resp [N,K] — the same math:
    with the shared quadratic expansion logN = const + x.(mu/s^2) - x^2/2s^2,

      sum_n r logN = s*const + <mu/s^2, sx> - 0.5 <1/s^2, sxx>

    so the responsibility matrix never needs to exist here (it was reduced
    away inside ops/em_kernels.py). Gradients flow through mu only —
    statistics are constants, exactly like resp in `_class_objective`."""
    m_scaled, inv_var, const = precompute_diag_gaussian(mu, sigmas, eps)
    ll_sum = (
        s * (const + jnp.log(pi_old + eps))
        + jnp.sum(m_scaled * sx, axis=-1)
        - 0.5 * jnp.sum(inv_var * sxx, axis=-1)
    )  # [K] = sum_n resp[n, k] * ll[n, k]
    weighted_nll = -jnp.sum(ll_sum) / n
    pair = pairwise_sq_dists(mu, mu)
    off = 1.0 - jnp.eye(mu.shape[0])
    diversity = jnp.sum(jnp.exp(-pair) * off) / jnp.sum(off)
    return weighted_nll + lam * diversity


def _m_step_objective_stats(
    means: jax.Array,
    s: jax.Array,
    sx: jax.Array,
    sxx: jax.Array,
    pi_old: jax.Array,
    sigmas: jax.Array,
    active: jax.Array,
    lam: float,
    n: int,
    eps: float = 1e-10,
) -> jax.Array:
    """Masked sum over classes of `_class_objective_stats`."""
    per_class = jax.vmap(
        _class_objective_stats, in_axes=(0, 0, 0, 0, 0, 0, None, None, None)
    )(means, s, sx, sxx, pi_old, sigmas, lam, n, eps)
    return jnp.sum(per_class * active)


def _reference_em_update(
    gmm: GMMState,
    memory: Memory,
    opt_state: optax.OptState,
    mean_tx: optax.GradientTransformation,
    cfg: EMConfig,
    eps: float = 1e-10,
) -> Tuple[GMMState, Memory, optax.OptState, EMAux]:
    """Reference-exact stepping (cfg.reference_stepping=True).

    Reproduces the reference's control flow under jit: a sequential scan over
    classes IN ORDER (model.py:281); per active class, `num_em_loop` rounds of
    E-step → smoothed responsibilities → ONE Adam step whose gradient is
    nonzero only in that class's slice but which updates the WHOLE [C,K,d]
    tensor through the shared optimizer state (torch keeps one Adam over the
    full parameter, main.py:223-227 — zero-grad slices still move under
    moment decay, and the step count advances once per (class, round)) →
    τ-momentum prior write-back for that class. Inactive classes take no
    step of their own but DO drift during other classes' steps — the exact
    torch artifact the default path deliberately removes."""
    c_num, cap, _ = memory.feats.shape
    active = memory.updated & (memory.length == cap)
    x_all = memory.feats
    lam = cfg.diversity_lambda

    def class_step(carry, c):
        means, priors, opt_state = carry
        xc = x_all[c]  # [N, d]
        sig_c = gmm.sigmas[c]  # [K, d]

        def run(args):
            means, priors, opt_state = args

            def em_round(inner, _):
                means, pi_old, opt_state = inner
                ll_c, log_resp = e_step(xc, means[c], sig_c, pi_old)
                resp = jnp.exp(log_resp)
                resp = (resp + cfg.alpha) / jnp.sum(
                    resp + cfg.alpha, axis=-1, keepdims=True
                )
                pi_unnorm = jnp.sum(resp, axis=0) + eps

                def obj(m):
                    # m[c]: only this class's slice carries gradient
                    return _class_objective(
                        m[c], xc, resp, pi_old, sig_c, lam, eps
                    )

                loss, grads = jax.value_and_grad(obj)(means)
                updates, opt_state = mean_tx.update(grads, opt_state, means)
                means = optax.apply_updates(means, updates)
                pi_old = momentum_update(pi_old, pi_unnorm / cap, cfg.tau)
                return (means, pi_old, opt_state), (loss, ll_c)

            (means, pi_old, opt_state), (losses, lls) = jax.lax.scan(
                em_round, (means, priors[c], opt_state), None,
                length=cfg.num_em_loop,
            )
            priors = priors.at[c].set(pi_old)
            return means, priors, opt_state, losses[-1], lls[-1]

        def skip(args):
            means, priors, opt_state = args
            return means, priors, opt_state, jnp.zeros(()), jnp.zeros(())

        means, priors, opt_state, loss, ll = jax.lax.cond(
            active[c], run, skip, (means, priors, opt_state)
        )
        return (means, priors, opt_state), (loss, ll)

    (means, priors, opt_state), (losses, lls) = jax.lax.scan(
        class_step, (gmm.means, gmm.priors, opt_state), jnp.arange(c_num)
    )
    active_f = active.astype(jnp.float32)
    n_active = jnp.maximum(jnp.sum(active_f), 1.0)
    new_gmm = gmm._replace(means=means, priors=priors)
    return (
        new_gmm,
        clear_updated(memory),
        opt_state,
        EMAux(
            loss=jnp.sum(losses * active_f),
            num_active=jnp.sum(active),
            log_likelihood=jnp.sum(lls * active_f) / n_active,
            compact_fallback=jnp.zeros((), jnp.int32),
        ),
    )


def _em_rounds(
    means: jax.Array,
    pi_slab: jax.Array,
    x_slab: jax.Array,
    sigmas_slab: jax.Array,
    active_slab: jax.Array,
    idx: Optional[jax.Array],
    opt_state: optax.OptState,
    mean_tx: optax.GradientTransformation,
    cfg: EMConfig,
    cap: int,
    eps: float,
    fused: bool,
    interpret: bool,
    mesh,
) -> Tuple[jax.Array, jax.Array, optax.OptState, jax.Array, jax.Array]:
    """`num_em_loop` EM rounds over a slab of classes — the shared loop of
    the dense (idx=None: slab == all classes) and compact (idx [A]: slab ==
    means[idx]) paths. `means` is always the FULL [C, K, d] tensor: the one
    Adam step per round runs over it either way, so zero-grad classes see
    identical moment decay and the two paths' optimizer bookkeeping cannot
    diverge. Returns (means, pi_slab, opt_state, last loss, last masked
    mean log-likelihood)."""
    active_f = active_slab.astype(jnp.float32)
    n_active = jnp.maximum(jnp.sum(active_f), 1.0)
    n = x_slab.shape[1]
    k = sigmas_slab.shape[1]
    loss = jnp.zeros(())
    ll_mean = jnp.zeros(())
    for _ in range(cfg.num_em_loop):
        mu_slab = means if idx is None else means[idx]
        if fused:
            ll, s_raw, sx_raw, sxx_raw = em_estep_stats(
                x_slab, mu_slab, sigmas_slab, pi_slab, eps,
                interpret=interpret, mesh=mesh,
            )
            # additive smoothing in statistics space (model.py:383): raw
            # responsibilities sum to 1 over K, so the per-sample smoothing
            # denominator is the constant 1 + K*alpha, and sum_n x /
            # sum_n x^2 are recovered from the raw stats themselves
            # (ops/em_kernels.py docstring)
            denom = 1.0 + k * cfg.alpha
            s = (s_raw + n * cfg.alpha) / denom
            sx = (
                sx_raw + cfg.alpha * jnp.sum(sx_raw, axis=1, keepdims=True)
            ) / denom
            sxx = (
                sxx_raw + cfg.alpha * jnp.sum(sxx_raw, axis=1, keepdims=True)
            ) / denom
            pi_unnorm = s + eps  # == sum_n resp_smoothed + eps
            pi_old = pi_slab

            def obj(m, s=s, sx=sx, sxx=sxx, pi_old=pi_old):
                m_slab = m if idx is None else m[idx]
                return _m_step_objective_stats(
                    m_slab, s, sx, sxx, pi_old, sigmas_slab, active_f,
                    cfg.diversity_lambda, n, eps,
                )
        else:
            with jax.named_scope("em_estep"):
                ll, log_resp = jax.vmap(e_step, in_axes=(0, 0, 0, 0))(
                    x_slab, mu_slab, sigmas_slab, pi_slab
                )  # ll [A], log_resp [A, N, K]
            resp = jnp.exp(log_resp)
            resp = (resp + cfg.alpha) / jnp.sum(
                resp + cfg.alpha, axis=-1, keepdims=True
            )  # model.py:383
            pi_unnorm = jnp.sum(resp, axis=1) + eps  # [A, K], model.py:385
            pi_old = pi_slab

            def obj(m, resp=resp, pi_old=pi_old):
                m_slab = m if idx is None else m[idx]
                return _m_step_objective(
                    m_slab, x_slab, resp, pi_old, sigmas_slab, active_f,
                    cfg.diversity_lambda,
                )

        with jax.named_scope("em_mstep"):
            loss, grads = jax.value_and_grad(obj)(means)
            updates, opt_state = mean_tx.update(grads, opt_state, means)
            means = optax.apply_updates(means, updates)

        pi_new = pi_unnorm / cap  # model.py:399
        pi_slab = jnp.where(
            active_slab[:, None],
            momentum_update(pi_slab, pi_new, cfg.tau),
            pi_slab,
        )
        ll_mean = jnp.sum(ll * active_f) / n_active
    return means, pi_slab, opt_state, loss, ll_mean


def _dense_em_update(
    gmm: GMMState,
    memory: Memory,
    opt_state: optax.OptState,
    mean_tx: optax.GradientTransformation,
    cfg: EMConfig,
    eps: float,
    fused: bool,
    interpret: bool,
    mesh,
) -> Tuple[GMMState, Memory, optax.OptState, EMAux]:
    """All-class EM (reference `update_GMM`, model.py:277-301): vmapped over
    the full class axis, inactive classes masked and pinned."""
    c, cap, _ = memory.feats.shape
    active = memory.updated & (memory.length == cap)  # model.py:283,289
    means, priors, opt_state, loss, ll_mean = _em_rounds(
        gmm.means, gmm.priors, memory.feats, gmm.sigmas, active, None,
        opt_state, mean_tx, cfg, cap, eps, fused, interpret, mesh,
    )
    new_gmm = gmm._replace(
        means=jnp.where(active[:, None, None], means, gmm.means),
        priors=priors,
    )
    return (
        new_gmm,
        clear_updated(memory),
        opt_state,
        EMAux(
            loss=loss,
            num_active=jnp.sum(active),
            log_likelihood=ll_mean,
            compact_fallback=jnp.zeros((), jnp.int32),
        ),
    )


def _compact_em_update(
    gmm: GMMState,
    memory: Memory,
    opt_state: optax.OptState,
    mean_tx: optax.GradientTransformation,
    cfg: EMConfig,
    eps: float,
    width: int,
    fused: bool,
    interpret: bool,
) -> Tuple[GMMState, Memory, optax.OptState, EMAux]:
    """Compact dirty-class EM: gather the <=`width` dirty banks into an
    [A, N, d] slab, run E/M there, scatter means/priors back.

    The bank is touched ONLY through the `[idx]` gathers below (the lint
    scripts/check_em_compact.py pins this): E-step reads [A, N, d] instead
    of [C, N, d] and the m-step backward never sees the bank at all in the
    fused mode. The Adam step still spans the full [C, K, d] means tensor
    (tiny next to the bank) with the slab gradient scattered in, so the
    optimizer trajectory is the dense path's exactly."""
    c, cap, _ = memory.feats.shape
    active = memory.updated & (memory.length == cap)
    with jax.named_scope("em_compact_gather"):
        # fixed-width compaction: top_k over the dirty mask pulls the dirty
        # class ids to the front (ties resolve to ascending class id, so the
        # slab order is deterministic); when fewer than `width` classes are
        # dirty the tail slots carry arbitrary clean classes, masked inert
        # by `slab_active`.
        _, idx = jax.lax.top_k(active.astype(jnp.int32), width)
        slab_active = active[idx]  # [A]
        x_slab = memory.feats[idx]  # [A, N, d] — the only bank traffic
        sig_slab = gmm.sigmas[idx]
        pi_slab = gmm.priors[idx]
    means, pi_slab, opt_state, loss, ll_mean = _em_rounds(
        gmm.means, pi_slab, x_slab, sig_slab, slab_active, idx,
        opt_state, mean_tx, cfg, cap, eps, fused, interpret, None,
    )
    with jax.named_scope("em_compact_scatter"):
        # inactive slab slots still hold their gathered (untouched) priors,
        # so the distinct-index scatter writes them back bit-identically
        new_gmm = gmm._replace(
            means=jnp.where(active[:, None, None], means, gmm.means),
            priors=gmm.priors.at[idx].set(pi_slab),
        )
    return (
        new_gmm,
        clear_updated(memory),
        opt_state,
        EMAux(
            loss=loss,
            num_active=jnp.sum(active),
            log_likelihood=ll_mean,
            compact_fallback=jnp.zeros((), jnp.int32),
        ),
    )


def _sharded_em_update(
    gmm: GMMState,
    memory: Memory,
    opt_state: optax.OptState,
    mean_tx: optax.GradientTransformation,
    cfg: EMConfig,
    eps: float,
    mesh,
    model_size: int,
) -> Tuple[GMMState, Memory, optax.OptState, EMAux]:
    """Class-sharded compact EM with psum'd statistics (ISSUE 14 tentpole).

    shard_map over the mesh's 'model' axis: every shard runs the FULL
    single-device EM dispatch (`em_update` with mesh=None) on its OWN class
    slab — its local dirty-class top_k, its local compact/dense lax.cond,
    its local slice of the mean-Adam moments — so the dirty-class gather
    respects shard locality (a shard only ever compacts its own classes)
    and no shard materializes another shard's [C/S, cap, d] bank: the only
    cross-shard traffic of the whole bank phase is the psum of the four
    EMAux SCALARS below. The per-class sufficient statistics (Σr, Σr·x,
    Σr·x²) stay entirely shard-local by construction — each class's bank
    lives whole on its shard — which is what keeps per-chip bank traffic
    flat as the model axis grows (the weak-scaling contract
    `bench.py --measure weakscale` measures and
    `mgproto-telemetry check --weakscale` gates).

    Parity: per-class E/M math is the dense path's bit-for-bit (same
    `_em_rounds`, same per-class gradients; Adam moments are elementwise so
    a class-sliced step walks the identical trajectory); the psum'd scalars
    reassociate float sums across shards, hence the usual 2e-5-grade
    tolerance in the parity tests. `compact_fallback` becomes the COUNT of
    shards that overflowed their local width this call (0/1 per shard,
    psum'd — the telemetry counter semantics documented on EMAux).
    """
    from jax.sharding import PartitionSpec as P

    from mgproto_tpu.parallel.mesh import MODEL_AXIS, shard_map_compat

    c = memory.feats.shape[0]
    c_local = c // model_size
    # each shard compacts within its local class slab: width clips to the
    # slab (a width >= C/S degenerates to the local dense path, which is
    # the same bank traffic — compaction cannot help there)
    local_cfg = dataclasses.replace(
        cfg, max_active_classes=min(max(cfg.max_active_classes, 0), c_local)
    )

    def class_spec(tree):
        return jax.tree.map(
            lambda x: (
                P(MODEL_AXIS)
                if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] == c
                else P()
            ),
            tree,
        )

    in_specs = (class_spec(gmm), class_spec(memory), class_spec(opt_state))
    aux_specs = EMAux(
        loss=P(), num_active=P(), log_likelihood=P(), compact_fallback=P()
    )
    out_specs = in_specs + (aux_specs,)

    def local_em(g, m, o):
        g2, m2, o2, aux = em_update(g, m, o, mean_tx, local_cfg, eps)
        # psum'd aggregate statistics: exactly the dense path's globals.
        # log_likelihood is a weighted mean — un-normalize with the local
        # active count (0 active -> numerator 0 by construction), psum
        # numerator and denominator, renormalize.
        n_local = aux.num_active.astype(jnp.float32)
        ll_num = aux.log_likelihood * jnp.maximum(n_local, 1.0)
        n = jax.lax.psum(n_local, MODEL_AXIS)
        return g2, m2, o2, EMAux(
            loss=jax.lax.psum(aux.loss, MODEL_AXIS),
            num_active=n.astype(jnp.int32),
            log_likelihood=(
                jax.lax.psum(ll_num, MODEL_AXIS) / jnp.maximum(n, 1.0)
            ),
            compact_fallback=jax.lax.psum(
                aux.compact_fallback, MODEL_AXIS
            ),
        )

    return shard_map_compat(
        local_em, mesh, in_specs=in_specs, out_specs=out_specs
    )(gmm, memory, opt_state)


def em_update(
    gmm: GMMState,
    memory: Memory,
    opt_state: optax.OptState,
    mean_tx: optax.GradientTransformation,
    cfg: EMConfig,
    eps: float = 1e-10,
    mesh=None,
) -> Tuple[GMMState, Memory, optax.OptState, EMAux]:
    """One full EM call (reference `update_GMM`, model.py:277-301). Jittable;
    call every `update_interval` training steps once the epoch gate is open.

    Dispatch (all static python branches except the one lax.cond):
      * cfg.reference_stepping: the reference-exact sequential scan.
      * `mesh` given (a Mesh with a 'model' axis > 1, from ShardedTrainer's
        score mesh) with the class axis sharding evenly: the class-sharded
        shard_map path (`_sharded_em_update`) — every shard compacts its
        OWN dirty classes and only the aggregate scalars psum across
        shards, so no shard ever touches another's bank.
      * compaction disabled (`max_active_classes` <= 0, unresolved auto, or
        >= C where it cannot help) or a non-divisible meshed class axis:
        the dense path (GSPMD-partitioned under a mesh; the fused E-step
        kernel then runs shard_mapped per class shard).
      * otherwise: lax.cond on the dirty count — compact slab when it fits
        the width, dense fallback (flagged in EMAux.compact_fallback) when
        it does not. Both branches compile once; steady state never
        retraces.
    """
    if cfg.reference_stepping:
        return _reference_em_update(gmm, memory, opt_state, mean_tx, cfg, eps)
    fused, interpret = _resolve_fused_estep(cfg)
    c, cap, _ = memory.feats.shape
    width = cfg.max_active_classes
    if mesh is not None:
        from mgproto_tpu.parallel.mesh import MODEL_AXIS

        model_size = int(mesh.shape[MODEL_AXIS])
        if model_size > 1 and c % model_size == 0:
            return _sharded_em_update(
                gmm, memory, opt_state, mean_tx, cfg, eps, mesh, model_size
            )
        width = 0
    if width <= 0 or width >= c:
        return _dense_em_update(
            gmm, memory, opt_state, mean_tx, cfg, eps, fused, interpret, mesh
        )
    active = memory.updated & (memory.length == cap)
    n_active = jnp.sum(active)

    def compact(ops):
        g, m, o = ops
        return _compact_em_update(
            g, m, o, mean_tx, cfg, eps, width, fused, interpret
        )

    def dense(ops):
        g, m, o = ops
        g2, m2, o2, aux = _dense_em_update(
            g, m, o, mean_tx, cfg, eps, fused, interpret, None
        )
        return g2, m2, o2, aux._replace(
            compact_fallback=jnp.ones((), jnp.int32)
        )

    return jax.lax.cond(n_active <= width, compact, dense, (gmm, memory, opt_state))
