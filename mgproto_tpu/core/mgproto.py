"""The MGProto model: Flax feature extractor + pure-functional GMM head.

TPU-native redesign of reference model.py:77-510. The torch module mixes
trainable params, frozen buffers, a mutable memory bank and an embedded
optimizer in one nn.Module; here the pieces live where JAX wants them:

  * `MGProtoFeatures` (flax): backbone trunk + add-on 1x1 convs + auxiliary
    DML embedding head — everything trained by backprop.
  * `GMMState` (pytree): prototype means/sigmas/priors + pruning mask —
    trained only by EM (core/em.py) and push projection (engine/push.py),
    exactly like the reference where compute_log_prob detaches the means
    (model.py:264-265) and the last layer is frozen (model.py:64).
  * `head_forward()` (pure fn): density -> top-T mining pool -> mine masking
    -> per-class mixture log-likelihoods, plus deduped enqueue candidates.

Everything is log-domain: the reference exponentiates per-patch log-densities
(model.py:215), pools probs, then takes log of the priors-weighted sum
(model.py:222,254). Monotonicity of exp makes top-T selection identical, and
logsumexp reproduces log(sum pi * p) exactly, without underflow for 64-d
Gaussians.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from mgproto_tpu.config import ModelConfig
from mgproto_tpu.models import build_backbone
from mgproto_tpu.ops.gaussian import diag_gaussian_log_prob
from mgproto_tpu.ops.pooling import (
    PooledActivations,
    dedup_first_occurrence,
    mine_mask_activations,
    top_t_pool,
)


def l2_normalize(x: jax.Array, axis: int = -1, eps: float = 1e-12) -> jax.Array:
    """F.normalize parity (reference model.py:40-41)."""
    return x / jnp.maximum(jnp.linalg.norm(x, axis=axis, keepdims=True), eps)


class GMMState(NamedTuple):
    """Per-class Gaussian mixture over prototype space.

    means:  [C, K, d] — trained by EM + push only.
    sigmas: [C, K, d] — std (not variance), frozen at 1/sqrt(2*pi)
            (reference model.py:151-152).
    priors: [C, K]    — mixture weights; the reference stores them as the
            frozen NonNegLinear weight rows (model.py:154, 298-300).
    keep:   [C, K] bool — pruning mask (model.py:467-482); pruned slots also
            have prior zeroed, `keep` is retained for bookkeeping/rendering.
    """

    means: jax.Array
    sigmas: jax.Array
    priors: jax.Array
    keep: jax.Array

    @property
    def num_classes(self) -> int:
        return self.means.shape[0]

    @property
    def k_per_class(self) -> int:
        return self.means.shape[1]


def init_gmm(cfg: ModelConfig, key: jax.Array) -> GMMState:
    """L2-normalized uniform-random means, sigma=1/sqrt(2pi), priors=1/K
    (reference model.py:148-154 + set_last_layer_incorrect_connection
    model.py:440-447 with incorrect_strength=0)."""
    c, k, d = cfg.num_classes, cfg.prototypes_per_class, cfg.proto_dim
    means = l2_normalize(jax.random.uniform(key, (c, k, d), jnp.float32))
    return GMMState(
        means=means,
        sigmas=jnp.full((c, k, d), cfg.init_sigma, jnp.float32),
        priors=jnp.full((c, k), 1.0 / k, jnp.float32),
        keep=jnp.ones((c, k), bool),
    )


class AddOnLayers(nn.Module):
    """1x1 conv adapter into prototype space (reference model.py:117-143).

    'regular' (settings.py:5): two 1x1 convs, NO activations.
    'bottleneck': channel-halving chain with ReLU, ending in Sigmoid.
    """

    proto_dim: int
    add_on_type: str
    in_channels: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x):
        if self.add_on_type == "regular":
            x = nn.Conv(self.proto_dim, (1, 1), name="conv0", dtype=self.dtype)(x)
            x = nn.Conv(self.proto_dim, (1, 1), name="conv1", dtype=self.dtype)(x)
            return x
        if self.add_on_type == "bottleneck":
            current_in = self.in_channels
            i = 0
            while True:
                current_out = max(self.proto_dim, current_in // 2)
                x = nn.Conv(
                    current_out, (1, 1), name=f"conv{i}_a", dtype=self.dtype
                )(x)
                x = nn.relu(x)
                x = nn.Conv(
                    current_out, (1, 1), name=f"conv{i}_b", dtype=self.dtype
                )(x)
                if current_out > self.proto_dim:
                    x = nn.relu(x)
                else:
                    x = nn.sigmoid(x)
                    return x
                current_in = current_in // 2
                i += 1
        raise ValueError(f"unknown add_on_type {self.add_on_type!r}")


class MGProtoFeatures(nn.Module):
    """Backbone + add-on + aux embedding (reference model.py:176-186).

    Returns (proto_map [B,H,W,d], embed [B,E]): the L2 normalization of the
    proto map happens in `forward()` so push/eval paths share it.
    """

    cfg: ModelConfig

    def setup(self):
        # Mixed precision: convs/BN run in cfg.compute_dtype (bf16 on the MXU),
        # params + batch_stats stay f32, and everything downstream of the
        # trunk — L2 norm, density, losses — is cast back to f32 (OoD
        # thresholds depend on the p(x) scale; SURVEY.md §7.3.5).
        dtype = jnp.dtype(self.cfg.compute_dtype)
        dtype = None if dtype == jnp.float32 else dtype
        kw = {"dtype": dtype}
        if self.cfg.remat or self.cfg.remat_stages:
            if not self.cfg.arch.startswith(("resnet", "densenet")):
                raise ValueError(
                    "remat is implemented for resnet/densenet blocks only "
                    f"(got arch={self.cfg.arch!r})"
                )
        if self.cfg.remat:
            # full-trunk remat wins over any stage selection
            kw["remat"] = True
        elif self.cfg.remat_stages:
            prefix = (
                "layer" if self.cfg.arch.startswith("resnet") else "denseblock"
            )
            known = {f"{prefix}{i}" for i in range(1, 5)}
            unknown = set(self.cfg.remat_stages) - known
            if unknown:
                raise ValueError(
                    f"unknown remat_stages {sorted(unknown)} for arch "
                    f"{self.cfg.arch!r}; options: {sorted(known)}"
                )
            kw["remat_stages"] = tuple(self.cfg.remat_stages)
        # fused block epilogue (ops/fused_epilogue.py): resnet family only —
        # resolved per backend like fused_scoring (Mosaic on TPU, interpret
        # elsewhere); the kernel's backward is the exact VJP of the XLA
        # reference, so this is a byte-traffic switch, not a numerics one
        from mgproto_tpu.ops.fused_epilogue import resolve_fused_epilogue

        if self.cfg.arch.startswith("resnet"):
            kw["fused_epilogue"] = resolve_fused_epilogue(
                self.cfg.fused_epilogue, self.cfg.arch
            )
        elif self.cfg.fused_epilogue:
            raise ValueError(
                "fused_epilogue=True is implemented for resnet blocks only "
                f"(got arch={self.cfg.arch!r}); leave it None/False here"
            )
        self.features = build_backbone(self.cfg.arch, **kw)
        self.add_on = AddOnLayers(
            proto_dim=self.cfg.proto_dim,
            add_on_type=self.cfg.add_on_type,
            in_channels=self.features.out_channels,
            dtype=dtype,
            name="add_on",
        )
        # aux embedding reads the BACKBONE output, not the add-on output
        # (reference model.py:180-184); tiny Dense, kept in f32
        self.embedding = nn.Dense(self.cfg.sz_embedding, name="embedding")

    def __call__(self, x, train: bool = False):
        dtype = jnp.dtype(self.cfg.compute_dtype)
        x = self.features(x.astype(dtype), train=train)
        proto_map = self.add_on(x).astype(jnp.float32)
        # GAP (model.py:145) — accumulate in f32: H*W bf16 additions would
        # round before the downstream-of-trunk f32 boundary
        pooled = jnp.mean(x, axis=(1, 2), dtype=jnp.float32)
        embed = l2_normalize(self.embedding(pooled), axis=-1)
        return proto_map, embed

    def conv_info(self):
        return build_backbone(self.cfg.arch).conv_info()


def patch_log_densities(
    proto_map: jax.Array, gmm: GMMState
) -> Tuple[jax.Array, jax.Array]:
    """L2-normalize the proto map and score every patch under every prototype.

    Returns (log_prob [B, C, K, H, W], normalized feature map [B, H, W, d]).
    Reference: model.py:208-215 (+ blocked compute_log_prob 256-275, replaced
    by one MXU matmul in ops/gaussian.py).
    """
    b, h, w, d = proto_map.shape
    feat = l2_normalize(proto_map, axis=-1)
    lp = diag_gaussian_log_prob(feat.reshape(-1, d), gmm.means, gmm.sigmas)
    lp = lp.reshape(b, h, w, gmm.num_classes, gmm.k_per_class)
    return jnp.transpose(lp, (0, 3, 4, 1, 2)), feat


def _fused_pool(
    proto_map: jax.Array, gmm: GMMState, mine_T: int, mesh=None
) -> Tuple[PooledActivations, jax.Array]:
    """score_pool-backed equivalent of patch_log_densities + top_t_pool:
    the [B*H*W, C*K] density matrix never hits HBM (ops/fused_scoring.py).

    `mesh` (a jax.sharding.Mesh with 'data'/'model' axes) routes the kernel
    through shard_map when the class axis is sharded: each model shard runs
    the SAME pallas_call on its local [C/nm, K, d] prototype slab — per-class
    density is class-independent, so no collective is needed in the forward,
    and shard_map's transpose inserts the one psum over 'model' that the
    feature gradient needs (feat enters replicated across 'model'). Without
    this, SPMD jit cannot partition a pallas_call over the sharded class axis
    at all (the r4 fallback silently ran the ~2x-slower unfused path exactly
    where the density matrix is largest — VERDICT r4 item 2)."""
    from mgproto_tpu.ops.fused_scoring import score_pool
    from mgproto_tpu.ops.gaussian import DEFAULT_SIGMA_EPS

    b, h, w, d = proto_map.shape
    feat = l2_normalize(proto_map, axis=-1).reshape(b, h * w, d)
    # the Mosaic lowering (VMEM scratch, sequential minor grid) is TPU-only;
    # every other backend gets the correct-but-slow interpreter
    interpret = jax.default_backend() != "tpu"
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from mgproto_tpu.parallel.mesh import (
            DATA_AXIS,
            MODEL_AXIS,
            shard_map_compat,
        )

        sharded_score = shard_map_compat(
            lambda f, m, s: score_pool(
                f, m, s, mine_T, DEFAULT_SIGMA_EPS, interpret
            ),
            mesh=mesh,
            in_specs=(P(DATA_AXIS), P(MODEL_AXIS), P(MODEL_AXIS)),
            # local [B/nd, (C/nm)*K, T] blocks tile the global [B, C*K, T]
            # class-major, matching the unfused path's prototype ordering
            out_specs=(P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS)),
        )
        vals, idx = sharded_score(feat, gmm.means, gmm.sigmas)
    else:
        vals, idx = score_pool(
            feat, gmm.means, gmm.sigmas, mine_T, DEFAULT_SIGMA_EPS, interpret
        )
    c, k = gmm.num_classes, gmm.k_per_class
    top1 = idx[..., 0].reshape(b, c, k)
    top1_feat = jnp.take_along_axis(
        feat, idx[..., 0][..., None], axis=1
    ).reshape(b, c, k, d)
    pooled = PooledActivations(
        log_act=vals.reshape(b, c, k, mine_T),
        top1_idx=top1,
        top1_feat=top1_feat,
    )
    return pooled, feat.reshape(b, h, w, d)


def head_forward(
    proto_map: jax.Array,
    gmm: GMMState,
    labels: Optional[jax.Array],
    mine_T: int,
    prior_eps: float = 1e-10,
    fused: bool = False,
    mesh=None,
) -> Tuple[jax.Array, PooledActivations, Tuple[jax.Array, jax.Array, jax.Array]]:
    """GMM head on an add-on feature map: returns (logits [B,C,T], pooled,
    enqueue candidates). Pure function; no flax. `fused` routes the density +
    top-T through the Pallas kernel (identical numerics, no [BHW, P] in HBM);
    `mesh` additionally shard_maps it over a class-sharded device mesh."""
    if fused and mesh is not None:
        # shard_map needs exact divisibility (trace-time-static shapes): a
        # ragged final eval batch or a non-divisible class count falls back
        # to the XLA path for THIS shape only — jit retraces per shape, so
        # regular batches keep the kernel
        from mgproto_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        if (
            proto_map.shape[0] % mesh.shape[DATA_AXIS] != 0
            or gmm.num_classes % mesh.shape[MODEL_AXIS] != 0
        ):
            fused = False
    if fused:
        pooled, feat = _fused_pool(proto_map, gmm, mine_T, mesh)
    else:
        log_prob, feat = patch_log_densities(proto_map, gmm)
        pooled = top_t_pool(log_prob, feat, mine_T)
    act = mine_mask_activations(pooled.log_act, labels)  # [B, C, K, T]
    # exactly-zero priors (pruned slots, model.py:481-482) must contribute
    # exp(-inf)=0, not eps — eps only stabilizes small-but-live priors
    log_priors = jnp.where(
        gmm.priors > 0, jnp.log(gmm.priors + prior_eps), -jnp.inf
    )  # [C, K]
    # [B, C, K, T] + [C, K] -> logsumexp over K at each mining level
    logits = jax.nn.logsumexp(
        act + log_priors[None, :, :, None], axis=2
    )  # [B, C, T]

    b, c, k = pooled.top1_idx.shape
    if labels is not None:
        # gt-class top-1 features, deduped by spatial index within each sample
        # (reference model.py:224-250)
        sel = labels[:, None, None]
        idx = jnp.take_along_axis(pooled.top1_idx, sel, axis=1)[:, 0]  # [B, K]
        feats = jnp.take_along_axis(
            pooled.top1_feat, sel[..., None], axis=1
        )[:, 0]  # [B, K, d]
        valid = dedup_first_occurrence(idx)  # [B, K]
        enq = (
            feats.reshape(b * k, -1),
            jnp.repeat(labels, k),
            valid.reshape(b * k),
        )
    else:
        d = pooled.top1_feat.shape[-1]
        enq = (
            jnp.zeros((b * k, d), proto_map.dtype),
            jnp.full((b * k,), -1, jnp.int32),
            jnp.zeros((b * k,), bool),
        )
    return logits, pooled, enq


def prune_top_m(
    gmm: GMMState, top_m: int, renormalize: bool = False
) -> GMMState:
    """Keep each class's top-M prototypes by prior; zero the rest.

    Reference `prune_prototypes_topM` (model.py:467-482): the per-class
    keep set is `prior >= kth-largest prior` (so prior TIES at the threshold
    keep MORE than M slots, exactly as the reference's `>=` does), pruned
    slots get prior 0 in the classifier weights, and priors are NOT
    renormalized. Density for pruned slots still gets computed here (they
    contribute exp(-inf)=0 via the zero prior), matching the reference where
    pruned columns stay in the weight matrix as zeros.

    `renormalize=True` (beyond-parity opt-in) rescales the kept priors to
    sum to 1 per class, preserving each class's mixture mass. When priors
    are still near-uniform (short runs / frequent pruning) the reference
    semantics shift class log-likelihoods by the removed mass and can
    collapse accuracy; renormalizing recovers most of it (measured on the
    evidence run: prune-4-of-5 at epoch 29 gives 0.13 reference vs 0.43
    renormalized vs 0.52 unpruned — evidence/README.md). Note it changes the
    absolute p(x) scale, so recompute OoD thresholds afterwards."""
    if not (1 <= top_m <= gmm.k_per_class):
        raise ValueError(f"top_m {top_m} not in [1, {gmm.k_per_class}]")
    thresh = jax.lax.top_k(gmm.priors, top_m)[0][:, -1]  # [C] kth largest
    keep = gmm.priors >= thresh[:, None]  # [C, K]
    priors = jnp.where(keep, gmm.priors, 0.0)
    if renormalize:
        priors = priors / jnp.maximum(priors.sum(-1, keepdims=True), 1e-12)
    return gmm._replace(priors=priors, keep=keep)


def log_px(logits_level0: jax.Array) -> jax.Array:
    """OoD score log p(x) = log sum_c p(x|c) (reference
    train_and_test.py:184-199 sums probs; logsumexp is the stable form)."""
    return jax.nn.logsumexp(logits_level0, axis=-1)
