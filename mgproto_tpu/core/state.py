"""Functional train state: everything the reference keeps as mutable module
state (params, BN stats, GMM, memory bank, three optimizers, iteration
counter — SURVEY.md §7.1) as one explicit pytree threaded through jitted
steps."""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from mgproto_tpu.config import Config
from mgproto_tpu.core.em import make_mean_optimizer
from mgproto_tpu.core.losses import PROXY_BASED, init_proxies
from mgproto_tpu.core.memory import Memory, init_memory
from mgproto_tpu.core.mgproto import GMMState, MGProtoFeatures, init_gmm


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any  # {'net': flax params, 'proxies': [C, E] or absent}
    batch_stats: Any
    gmm: GMMState
    memory: Memory
    opt_state: Any  # joint optimizer state
    warm_opt_state: Any  # warm-phase optimizer state (separate Adam, main.py:215-220)
    proto_opt_state: Any  # EM mean-optimizer state


class TrunkState(NamedTuple):
    """The trunk program's slice of TrainState: everything the forward +
    losses + backward + optimizer phase mutates. Donated as a unit by the
    async bank pipeline's trunk program (engine/train.py)."""

    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    warm_opt_state: Any


class BankState(NamedTuple):
    """The bank program's slice of TrainState: the memory bank, the GMM head
    it trains, and the EM mean-optimizer state. Donated as a unit by the
    async bank program so the [C, cap, d] bank is updated in place instead
    of round-tripping HBM as a copy every step."""

    gmm: GMMState
    memory: Memory
    proto_opt_state: Any


def split_state(state: "TrainState") -> Tuple[TrunkState, BankState]:
    """TrainState -> (trunk slice, bank slice). Works on any TrainState-
    shaped pytree — including the NamedSharding tree `state_shardings`
    builds, which is how the sharded trunk/bank jits get their specs."""
    return (
        TrunkState(
            step=state.step,
            params=state.params,
            batch_stats=state.batch_stats,
            opt_state=state.opt_state,
            warm_opt_state=state.warm_opt_state,
        ),
        BankState(
            gmm=state.gmm,
            memory=state.memory,
            proto_opt_state=state.proto_opt_state,
        ),
    )


def merge_state(trunk: TrunkState, bank: BankState) -> TrainState:
    """Inverse of `split_state`."""
    return TrainState(
        step=trunk.step,
        params=trunk.params,
        batch_stats=trunk.batch_stats,
        gmm=bank.gmm,
        memory=bank.memory,
        opt_state=trunk.opt_state,
        warm_opt_state=trunk.warm_opt_state,
        proto_opt_state=bank.proto_opt_state,
    )


def torch_adam(
    lr: optax.ScalarOrSchedule, weight_decay: float = 0.0
) -> optax.GradientTransformation:
    """torch.optim.Adam semantics: weight decay is added to the GRADIENT
    before the Adam moments (L2-in-grad), unlike optax.adamw which decays
    after preconditioning (reference main.py:205-220 uses Adam(weight_decay=1e-4))."""
    parts = []
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(optax.scale_by_adam(b1=0.9, b2=0.999, eps=1e-8))
    parts.append(
        optax.scale_by_learning_rate(lr)
    )  # handles schedules and the sign flip
    return optax.chain(*parts)


def staircase_schedule(
    base_lr: float,
    steps_per_epoch: int,
    decay_epochs: Tuple[int, ...],
    gamma: float,
    epoch_offset: int = 0,
) -> Callable[[jax.Array], jax.Array]:
    """StepLR stepped at fixed ABSOLUTE epochs (reference main.py:248-250:
    gamma=0.4 at epochs {30,45,60,75,90} for R34, counted from epoch 0
    regardless of warm-up). The joint optimizer's internal step count starts
    when the joint phase starts, so `epoch_offset` (= num_warm_epochs) maps
    its counter back to absolute epochs."""

    def schedule(step: jax.Array) -> jax.Array:
        epoch = step // steps_per_epoch + epoch_offset
        n = jnp.sum(jnp.asarray(decay_epochs) <= epoch)
        return base_lr * (gamma**n)

    return schedule


def _param_labels(params: Dict, train_embedding: bool) -> Dict:
    """Label each top-level param subtree with its optimizer group
    (reference main.py:205-220: features / add_on_layers / aux_criterion;
    the embedding Dense is absent from every group there, i.e. frozen)."""
    net_labels = {}
    for k in params["net"]:
        if k == "features":
            net_labels[k] = "features"
        elif k == "add_on":
            net_labels[k] = "add_on"
        elif k == "embedding":
            net_labels[k] = "features" if train_embedding else "frozen"
        else:
            net_labels[k] = "frozen"
    labels = {"net": net_labels}
    if "proxies" in params:
        labels["proxies"] = "aux"
    return labels


def make_joint_optimizer(
    cfg: Config, steps_per_epoch: int
) -> optax.GradientTransformation:
    o = cfg.optim
    sched = lambda base: staircase_schedule(
        base,
        steps_per_epoch,
        o.lr_decay_epochs,
        o.lr_decay_gamma,
        epoch_offset=cfg.schedule.num_warm_epochs,
    )
    return optax.multi_transform(
        {
            "features": torch_adam(sched(o.features_lr), o.weight_decay),
            "add_on": torch_adam(sched(o.add_on_lr), o.weight_decay),
            "aux": torch_adam(sched(o.aux_proxies_lr), o.weight_decay),
            "frozen": optax.set_to_zero(),
        },
        lambda p: _param_labels(p, o.train_embedding),
    )


def make_warm_optimizer(cfg: Config) -> optax.GradientTransformation:
    """Warm phase: backbone frozen (reference train_and_test.py:260-268 +
    main.py:215-220); constant lrs, no staircase (warm epochs precede it)."""
    o = cfg.optim
    return optax.multi_transform(
        {
            "features": optax.set_to_zero(),
            "add_on": torch_adam(o.add_on_lr, o.weight_decay),
            "aux": torch_adam(o.aux_proxies_lr, o.weight_decay),
            "frozen": optax.set_to_zero(),
        },
        lambda p: _param_labels(p, o.train_embedding),
    )


def create_train_state(
    cfg: Config,
    steps_per_epoch: int,
    rng: jax.Array,
    model: Optional[MGProtoFeatures] = None,
    joint_tx: Optional[optax.GradientTransformation] = None,
    warm_tx: Optional[optax.GradientTransformation] = None,
    proto_tx: Optional[optax.GradientTransformation] = None,
    for_restore: bool = False,
) -> Tuple[TrainState, MGProtoFeatures]:
    """Initialize model, GMM, memory and all optimizer states. Callers that
    already hold the model/transforms (engine.Trainer) pass them in so there
    is exactly one construction site. `for_restore=True` skips the pretrained
    trunk load: the state is only a restore target."""
    m = cfg.model
    model = model or MGProtoFeatures(cfg=m)
    joint_tx = joint_tx or make_joint_optimizer(cfg, steps_per_epoch)
    warm_tx = warm_tx or make_warm_optimizer(cfg)
    proto_tx = proto_tx or make_mean_optimizer(cfg.em)

    k_init, k_gmm, k_proxy = jax.random.split(rng, 3)
    dummy = jnp.zeros((1, m.img_size, m.img_size, 3), jnp.float32)
    variables = model.init(k_init, dummy, train=False)

    net_params = dict(variables["params"])
    batch_stats = dict(variables.get("batch_stats", {}))
    if m.pretrained and not for_restore:
        # reference model.py:492: every backbone starts from torchvision /
        # BBN-iNat weights; converted once on host, cached as npz
        from mgproto_tpu.models.pretrained import (
            load_pretrained_trunk,
            merge_pretrained_trunk,
        )

        net_params, batch_stats = merge_pretrained_trunk(
            net_params, batch_stats, load_pretrained_trunk(m.arch)
        )

    params: Dict[str, Any] = {"net": net_params}
    if cfg.loss.aux_loss in PROXY_BASED:
        params["proxies"] = init_proxies(k_proxy, m.num_classes, m.sz_embedding)

    gmm = init_gmm(m, k_gmm)
    memory = init_memory(m.num_classes, m.mem_capacity, m.proto_dim)

    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        gmm=gmm,
        memory=memory,
        opt_state=joint_tx.init(params),
        warm_opt_state=warm_tx.init(params),
        proto_opt_state=proto_tx.init(gmm.means),
    )
    return state, model
