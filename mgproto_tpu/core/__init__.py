from mgproto_tpu.core.memory import Memory, init_memory, memory_push, memory_pull_all

__all__ = ["Memory", "init_memory", "memory_push", "memory_pull_all"]
