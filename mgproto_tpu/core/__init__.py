from mgproto_tpu.core.memory import (
    Memory,
    clear_updated,
    init_memory,
    memory_push,
    memory_pull_all,
)
from mgproto_tpu.core.mgproto import (
    GMMState,
    MGProtoFeatures,
    head_forward,
    init_gmm,
    l2_normalize,
    log_px,
    patch_log_densities,
)
from mgproto_tpu.core.em import em_update, make_mean_optimizer, EMAux
from mgproto_tpu.core.state import TrainState, create_train_state

__all__ = [
    "Memory",
    "clear_updated",
    "init_memory",
    "memory_push",
    "memory_pull_all",
    "GMMState",
    "MGProtoFeatures",
    "head_forward",
    "init_gmm",
    "l2_normalize",
    "log_px",
    "patch_log_densities",
    "em_update",
    "make_mean_optimizer",
    "EMAux",
    "TrainState",
    "create_train_state",
]
