"""Functional per-class FIFO feature memory.

Reference: /root/reference/utils/memory.py — an nn.Module holding one mutable
`cls%d` buffer per class, pushed to from inside `forward` (a replica-lost-write
hazard under DataParallel, SURVEY.md §2.3). TPU-native design: the memory is a
fixed-shape pytree threaded through the jitted train step; the push is one
fixed-shape, scatter-free merge (no per-class python loop), so it is safe
under any sharding — candidates are globally visible after an all_gather over
the data axis.

FIFO semantics: a circular buffer per class. The reference keeps buffers
left-compacted and shifts on eviction (memory.py:56-67); since the only
consumer is EM, which treats the bank as a *set* (model.py:279-291), a cursor-
based circular write preserves the exact same retained-set semantics (oldest
evicted first) with O(1) work.

Scatter-free enqueue (PERF.md stall list: "the memory-bank enqueue scatter"):
the original write was `feats.at[cls, pos].set(..., mode='drop')` — a
row-scatter of up to B*K updates that TPUs lower as a serial chain of tiny
dynamic-update-slices, latency-bound at ~800 updates/step at flagship
shapes. Instead the batch is STABLY SORTED by class (one [N] argsort of
int32 keys), which lays the kept rows out as per-class contiguous segments
in rank order; each bank slot then *gathers* its writer — slot j of class c
is written by segment row `(j - cursor_c) mod cap` iff that rank is below
the class's batch count — and one fused select pass produces the new bank.
Same bit-exact contents (tests/test_em_compact.py pins it against the
scatter oracle), but the op mix is sort + gather + select: everything
vectorizes, nothing serializes.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class Memory(NamedTuple):
    """feats: [C, cap, d]; length/cursor: [C] int32; updated: [C] bool
    (`updated` mirrors reference model.py:167 `memory_updated_cls`)."""

    feats: jax.Array
    length: jax.Array
    cursor: jax.Array
    updated: jax.Array

    @property
    def capacity(self) -> int:
        return self.feats.shape[1]

    @property
    def num_classes(self) -> int:
        return self.feats.shape[0]


def init_memory(num_classes: int, capacity: int, dim: int) -> Memory:
    return Memory(
        feats=jnp.zeros((num_classes, capacity, dim), jnp.float32),
        length=jnp.zeros((num_classes,), jnp.int32),
        cursor=jnp.zeros((num_classes,), jnp.int32),
        updated=jnp.zeros((num_classes,), bool),
    )


def memory_push(
    mem: Memory, feats: jax.Array, classes: jax.Array, valid: jax.Array
) -> Memory:
    """Enqueue a flat batch of candidates (reference memory.py:31-73 semantics).

    Args:
      mem:     current memory state.
      feats:   [N, d] candidate feature vectors.
      classes: [N] int class ids.
      valid:   [N] bool; invalid rows are dropped.

    Jit-safe and scatter-free: everything is fixed-shape, and the bank write
    is a sort + gather + select (module docstring) — no scatter for XLA to
    serialize. If a single push holds more than `capacity` valid rows of one
    class, the first `capacity` are kept (the reference random-samples
    `capacity` of them, memory.py:51-53 — deterministic-first is the
    jit-friendly equivalent; a batch never realistically exceeds capacity).
    """
    with jax.named_scope("memory_push"):
        from mgproto_tpu.perf.precision import assert_f32_stats

        # the bank is a statistics buffer (EM fits the mixture to it): it
        # must never be demoted below f32, whatever the trunk's compute
        # dtype (perf/precision.py). Trace-time check, free in the program.
        assert_f32_stats(mem.feats, "memory bank feats")
        n, _ = feats.shape
        if n == 0:  # static shape: nothing to enqueue
            return mem
        c, cap, _ = mem.feats.shape
        sentinel = jnp.int32(c)
        ok = valid & (classes >= 0) & (classes < c)
        cls = jnp.where(ok, classes.astype(jnp.int32), sentinel)  # [N]

        one_hot = jax.nn.one_hot(cls, c, dtype=jnp.int32)  # [N, C] (sentinel -> 0s)
        csum = jnp.cumsum(one_hot, axis=0)  # inclusive
        rank = (
            jnp.take_along_axis(
                csum, jnp.clip(cls, 0, c - 1)[:, None], axis=1
            )[:, 0]
            - 1
        )  # [N] 0-based rank within class, in batch order
        keep = ok & (rank < cap)
        cls = jnp.where(keep, cls, sentinel)
        counts = jnp.sum(one_hot * keep[:, None], axis=0)  # [C] (<= cap)

        # per-class segment layout: a stable sort by class id groups the kept
        # rows class-contiguously IN BATCH ORDER (stable => rank order);
        # dropped rows carry the sentinel key and sort to the tail. Segment c
        # spans [start_c, start_c + counts_c).
        order = jnp.argsort(cls, stable=True)  # [N]
        start = jnp.cumsum(counts) - counts  # [C] exclusive prefix

        # each bank slot gathers its writer: slot j of class c receives the
        # class's rank-r row, r = (j - cursor_c) mod cap, iff r < counts_c
        slot = jnp.arange(cap, dtype=jnp.int32)[None, :]  # [1, cap]
        r = (slot - mem.cursor[:, None]) % cap  # [C, cap]
        written = r < counts[:, None]  # [C, cap]
        src = order[jnp.clip(start[:, None] + r, 0, max(n - 1, 0))]  # [C, cap]
        new_feats = jnp.where(
            written[..., None],
            feats.astype(mem.feats.dtype)[src],
            mem.feats,
        )
        return Memory(
            feats=new_feats,
            length=jnp.minimum(mem.length + counts, cap),
            cursor=(mem.cursor + counts) % cap,
            updated=mem.updated | (counts > 0),
        )


def memory_pull_all(mem: Memory) -> Tuple[jax.Array, jax.Array]:
    """All stored features with a validity mask (reference memory.py:135-151,
    kept fixed-shape: [C, cap, d] feats + [C, cap] bool instead of a ragged
    concat — EM consumes them per class anyway)."""
    mask = jnp.arange(mem.capacity)[None, :] < mem.length[:, None]
    return mem.feats, mask


def memory_nbytes(num_classes: int, capacity: int, dim: int) -> int:
    """Device bytes one Memory pytree occupies (f32 feats + the int32/bool
    per-class bookkeeping). The HBM-budget planner (perf/planner.py
    measure_candidate) reports this as the analytic cross-check next to
    XLA's measured peak — under bank-buffer donation (engine/train.py
    async pipeline) exactly one generation is live, which is the
    copy-traffic saving the donation exists for."""
    feats = num_classes * capacity * dim * 4
    per_class = num_classes * (4 + 4 + 1)  # length + cursor + updated
    return feats + per_class


def clear_updated(mem: Memory) -> Memory:
    """Reset the per-class updated flags after an EM pass
    (reference model.py:287)."""
    return mem._replace(updated=jnp.zeros_like(mem.updated))
