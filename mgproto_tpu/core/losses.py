"""Classification + auxiliary deep-metric-learning losses.

Reference: train_and_test.py:37-55 (CE + mine CE), utils/losses.py (DML).
The reference implements Proxy-Anchor natively (losses.py:29-61) and wraps
pytorch_metric_learning for the other five; here all six are first-party JAX
(no pml on TPU), implemented from their published formulations.

Note the reference CLI can only ever reach Proxy-Anchor (main.py:187-198
reads `args.loss`, which doesn't exist — SURVEY.md §2 dead-code list); the
others are provided for capability parity.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mgproto_tpu.core.mgproto import l2_normalize


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Softmax CE over class log-likelihoods (reference applies
    F.cross_entropy to log p(x|c), i.e. a second log_softmax on top —
    identical here)."""
    return -jnp.mean(
        jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1), labels[:, None], axis=-1
        )
    )


def mine_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean CE over mining levels t >= 1 (reference train_and_test.py:38)."""
    t = logits.shape[-1]
    if t <= 1:
        return jnp.zeros(())
    per_level = jax.vmap(cross_entropy, in_axes=(2, None))(
        logits[..., 1:], labels
    )
    return jnp.mean(per_level)


# ---------------------------------------------------------------------------
# auxiliary DML losses on the 32-d embedding
# ---------------------------------------------------------------------------


def init_proxies(key: jax.Array, num_classes: int, sz_embed: int) -> jax.Array:
    """Kaiming-normal proxies (reference losses.py:33-34: randn then
    kaiming_normal_ fan_out => std = sqrt(2/fan_out) = sqrt(2/sz_embed))."""
    return jax.random.normal(key, (num_classes, sz_embed)) * jnp.sqrt(
        2.0 / sz_embed
    )


def proxy_anchor(
    embeddings: jax.Array,
    labels: jax.Array,
    proxies: jax.Array,
    margin: float = 0.1,
    beta: float = 32.0,
) -> jax.Array:
    """Proxy-Anchor loss (Kim et al., CVPR 2020); reference losses.py:41-61.

    pos term averages over proxies WITH positives in the batch; neg term
    averages over all classes.
    """
    num_classes = proxies.shape[0]
    cos = l2_normalize(embeddings) @ l2_normalize(proxies).T  # [B, C]
    pos_mask = jax.nn.one_hot(labels, num_classes)  # [B, C]
    neg_mask = 1.0 - pos_mask

    pos_exp = jnp.exp(-beta * (cos - margin))
    neg_exp = jnp.exp(beta * (cos + margin))

    with_pos = jnp.sum(pos_mask, axis=0) > 0  # [C]
    num_valid = jnp.maximum(jnp.sum(with_pos), 1)

    p_sim_sum = jnp.sum(pos_exp * pos_mask, axis=0)  # [C]
    n_sim_sum = jnp.sum(neg_exp * neg_mask, axis=0)

    pos_term = jnp.sum(jnp.log1p(p_sim_sum) * with_pos) / num_valid
    neg_term = jnp.sum(jnp.log1p(n_sim_sum)) / num_classes
    return pos_term + neg_term


def proxy_nca(
    embeddings: jax.Array,
    labels: jax.Array,
    proxies: jax.Array,
    softmax_scale: float = 32.0,
) -> jax.Array:
    """Proxy-NCA (Movshovitz-Attias et al., ICCV 2017): CE over scaled
    negative squared distances to L2-normalized proxies."""
    x = l2_normalize(embeddings)
    p = l2_normalize(proxies)
    d2 = jnp.sum((x[:, None, :] - p[None, :, :]) ** 2, axis=-1)  # [B, C]
    return cross_entropy(-softmax_scale * d2, labels)


class _PairMasks(NamedTuple):
    pos: jax.Array  # [B, B] same-label, i != j
    neg: jax.Array  # [B, B] different-label


def _pair_masks(labels: jax.Array) -> _PairMasks:
    same = labels[:, None] == labels[None, :]
    eye = jnp.eye(labels.shape[0], dtype=bool)
    return _PairMasks(pos=same & ~eye, neg=~same)


def multi_similarity(
    embeddings: jax.Array,
    labels: jax.Array,
    thresh: float = 0.5,
    epsilon: float = 0.1,
    scale_pos: float = 2.0,
    scale_neg: float = 50.0,
) -> jax.Array:
    """Multi-Similarity loss with its pair miner (Wang et al., CVPR 2019);
    reference losses.py:77-91 hyperparameters."""
    s = l2_normalize(embeddings) @ l2_normalize(embeddings).T  # [B, B]
    m = _pair_masks(labels)

    neg_inf = jnp.finfo(s.dtype).min
    # miner: negatives harder than (min pos sim - eps); positives harder than
    # (max neg sim + eps)
    min_pos = jnp.min(jnp.where(m.pos, s, -neg_inf), axis=1)  # [B]
    max_neg = jnp.max(jnp.where(m.neg, s, neg_inf), axis=1)
    pos_keep = m.pos & (s < (max_neg + epsilon)[:, None])
    neg_keep = m.neg & (s > (min_pos - epsilon)[:, None])

    pos_sum = jnp.sum(jnp.exp(-scale_pos * (s - thresh)) * pos_keep, axis=1)
    neg_sum = jnp.sum(jnp.exp(scale_neg * (s - thresh)) * neg_keep, axis=1)
    has_any = (jnp.sum(pos_keep, 1) > 0) | (jnp.sum(neg_keep, 1) > 0)
    per_anchor = jnp.log1p(pos_sum) / scale_pos + jnp.log1p(neg_sum) / scale_neg
    return jnp.sum(per_anchor * has_any) / jnp.maximum(jnp.sum(has_any), 1)


def contrastive(
    embeddings: jax.Array,
    labels: jax.Array,
    pos_margin: float = 0.0,
    neg_margin: float = 0.5,
) -> jax.Array:
    """Pairwise contrastive loss on euclidean distances (Hadsell et al. 2006);
    reference losses.py:93-101 (neg_margin=0.5)."""
    x = embeddings
    d = jnp.sqrt(
        jnp.maximum(jnp.sum((x[:, None] - x[None, :]) ** 2, -1), 1e-12)
    )
    m = _pair_masks(labels)
    pos = jnp.maximum(d - pos_margin, 0.0)
    neg = jnp.maximum(neg_margin - d, 0.0)
    pos_loss = jnp.sum(pos * m.pos) / jnp.maximum(jnp.sum(m.pos), 1)
    neg_loss = jnp.sum(neg * m.neg) / jnp.maximum(jnp.sum(m.neg), 1)
    return pos_loss + neg_loss


def triplet_semihard(
    embeddings: jax.Array, labels: jax.Array, margin: float = 0.1
) -> jax.Array:
    """Triplet loss over semihard triplets (reference losses.py:103-113:
    TripletMarginMiner(type='semihard')): negatives with
    d_ap < d_an < d_ap + margin."""
    x = embeddings
    d = jnp.sqrt(
        jnp.maximum(jnp.sum((x[:, None] - x[None, :]) ** 2, -1), 1e-12)
    )
    m = _pair_masks(labels)
    d_ap = d[:, :, None]  # anchor-positive [B, B, 1]
    d_an = d[:, None, :]  # anchor-negative [B, 1, B]
    valid = m.pos[:, :, None] & m.neg[:, None, :]
    semihard = valid & (d_an > d_ap) & (d_an < d_ap + margin)
    losses = jnp.maximum(d_ap - d_an + margin, 0.0)
    return jnp.sum(losses * semihard) / jnp.maximum(jnp.sum(semihard), 1)


def npair(embeddings: jax.Array, labels: jax.Array, l2_reg: float = 0.0) -> jax.Array:
    """N-pair loss (Sohn, NeurIPS 2016): for each anchor with a positive in
    the batch, CE over inner-product logits against all other samples
    (reference losses.py:115-123, normalize_embeddings=False)."""
    b = embeddings.shape[0]
    logits = embeddings @ embeddings.T  # [B, B]
    m = _pair_masks(labels)
    eye = jnp.eye(b, dtype=bool)
    # first positive per anchor as the target
    has_pos = jnp.any(m.pos, axis=1)
    target = jnp.argmax(m.pos, axis=1)
    masked = jnp.where(eye, jnp.finfo(logits.dtype).min, logits)
    logp = jax.nn.log_softmax(masked, axis=1)
    per_anchor = -jnp.take_along_axis(logp, target[:, None], axis=1)[:, 0]
    loss = jnp.sum(per_anchor * has_pos) / jnp.maximum(jnp.sum(has_pos), 1)
    return loss + l2_reg * jnp.mean(jnp.sum(embeddings**2, -1))


AUX_LOSSES = {
    "proxy_anchor": proxy_anchor,
    "proxy_nca": proxy_nca,
    "ms": multi_similarity,
    "contrastive": contrastive,
    "triplet": triplet_semihard,
    "npair": npair,
}

# losses that take trainable proxies as third argument
PROXY_BASED = {"proxy_anchor", "proxy_nca"}
