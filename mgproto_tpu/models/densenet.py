"""DenseNet feature trunks (Flax), reference parity with
models/densenet_features.py.

Reference quirks reproduced: the stem pool0 is removed
(densenet_features.py:116) — `stem_pool=False` default — and a final BN+ReLU
caps the trunk (densenet_features.py:151-152). conv_info() reports executed
ops only (the reference counts the removed pool0, densenet_features.py:119).
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from mgproto_tpu.models.common import BatchNorm, ConvInfo, avg_pool, conv, max_pool


class DenseLayer(nn.Module):
    """BN-ReLU-1x1 -> BN-ReLU-3x3, output concatenated to input
    (reference densenet_features.py:18-47)."""

    growth_rate: int
    bn_size: int = 4
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool):
        y = BatchNorm(name="norm1", dtype=self.dtype)(x, use_running_average=not train)
        y = nn.relu(y)
        y = conv(
            self.bn_size * self.growth_rate, 1, 1, 0, name="conv1", dtype=self.dtype
        )(y)
        y = BatchNorm(name="norm2", dtype=self.dtype)(y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.growth_rate, 3, 1, 1, name="conv2", dtype=self.dtype)(y)
        return jnp.concatenate([x, y], axis=-1)


class Transition(nn.Module):
    """BN-ReLU-1x1 + 2x2 avgpool (reference densenet_features.py:71-84)."""

    out_features: int
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool):
        x = BatchNorm(name="norm", dtype=self.dtype)(x, use_running_average=not train)
        x = nn.relu(x)
        x = conv(self.out_features, 1, 1, 0, name="conv", dtype=self.dtype)(x)
        return avg_pool(x, 2, 2)


class DenseNetFeatures(nn.Module):
    growth_rate: int = 32
    block_config: Sequence[int] = (6, 12, 24, 16)
    num_init_features: int = 64
    bn_size: int = 4
    stem_pool: bool = False  # reference removes pool0 (densenet_features.py:116)
    dtype: Any = None
    remat: bool = False  # jax.checkpoint each dense layer (see resnet.py)
    # selective per-stage remat: checkpoint only the named dense blocks
    # ("denseblock1".."denseblock4"); ignored when `remat` is True
    remat_stages: Tuple[str, ...] = ()

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv(self.num_init_features, 7, 2, 3, name="conv0", dtype=self.dtype)(x)
        x = BatchNorm(name="norm0", dtype=self.dtype)(x, use_running_average=not train)
        x = nn.relu(x)
        if self.stem_pool:
            x = max_pool(x, 3, 2, 1)

        remat_cls = nn.remat(DenseLayer, static_argnums=(2,))
        num_features = self.num_init_features
        for bi, num_layers in enumerate(self.block_config):
            stage_remat = (
                self.remat or f"denseblock{bi + 1}" in self.remat_stages
            )
            layer_cls = remat_cls if stage_remat else DenseLayer
            for li in range(num_layers):
                x = layer_cls(
                    growth_rate=self.growth_rate,
                    bn_size=self.bn_size,
                    name=f"denseblock{bi + 1}_denselayer{li + 1}",
                    dtype=self.dtype,
                )(x, train)
            num_features += num_layers * self.growth_rate
            if bi != len(self.block_config) - 1:
                num_features //= 2
                x = Transition(
                    out_features=num_features,
                    name=f"transition{bi + 1}",
                    dtype=self.dtype,
                )(x, train)

        x = BatchNorm(name="norm5", dtype=self.dtype)(x, use_running_average=not train)
        return nn.relu(x)

    @property
    def out_channels(self) -> int:
        n = self.num_init_features
        for bi, num_layers in enumerate(self.block_config):
            n += num_layers * self.growth_rate
            if bi != len(self.block_config) - 1:
                n //= 2
        return n

    def conv_info(self) -> ConvInfo:
        ks: List[int] = [7]
        ss: List[int] = [2]
        ps: List[int] = [3]
        if self.stem_pool:
            ks += [3]
            ss += [2]
            ps += [1]
        for bi, num_layers in enumerate(self.block_config):
            for _ in range(num_layers):
                ks += [1, 3]
                ss += [1, 1]
                ps += [0, 1]
            if bi != len(self.block_config) - 1:
                ks += [1, 2]
                ss += [1, 2]
                ps += [0, 0]
        return ks, ss, ps


def densenet121(**kw):
    return DenseNetFeatures(32, (6, 12, 24, 16), 64, **kw)


def densenet169(**kw):
    return DenseNetFeatures(32, (6, 12, 32, 32), 64, **kw)


def densenet201(**kw):
    return DenseNetFeatures(32, (6, 12, 48, 32), 64, **kw)


def densenet161(**kw):
    return DenseNetFeatures(48, (6, 12, 36, 24), 96, **kw)
