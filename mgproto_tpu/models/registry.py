"""Backbone registry (reference model.py:21-37 `base_architecture_to_features`)
plus a tiny CNN for tests/dry-runs."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict

import flax.linen as nn

from mgproto_tpu.models import densenet, resnet, vgg
from mgproto_tpu.models.common import BatchNorm, ConvInfo, conv


class TinyFeatures(nn.Module):
    """A 3-conv trunk used by unit tests and the multi-chip dry run; same
    structural contract (NHWC in/out, conv_info, out_channels) as the zoo."""

    width: int = 32
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv(self.width, 3, 2, 1, name="conv0", dtype=self.dtype)(x)
        x = BatchNorm(name="bn0", dtype=self.dtype)(x, use_running_average=not train)
        x = nn.relu(x)
        x = conv(self.width, 3, 2, 1, name="conv1", dtype=self.dtype)(x)
        x = BatchNorm(name="bn1", dtype=self.dtype)(x, use_running_average=not train)
        x = nn.relu(x)
        x = conv(self.width, 3, 1, 1, name="conv2", dtype=self.dtype)(x)
        return nn.relu(x)

    @property
    def out_channels(self) -> int:
        return self.width

    def conv_info(self) -> ConvInfo:
        return [3, 3, 3], [2, 2, 1], [1, 1, 1]


@dataclasses.dataclass(frozen=True)
class BackboneSpec:
    factory: Callable[..., nn.Module]
    family: str  # resnet | vgg | densenet | tiny


BACKBONES: Dict[str, BackboneSpec] = {
    "resnet18": BackboneSpec(resnet.resnet18, "resnet"),
    "resnet34": BackboneSpec(resnet.resnet34, "resnet"),
    "resnet50": BackboneSpec(resnet.resnet50, "resnet"),
    "resnet101": BackboneSpec(resnet.resnet101, "resnet"),
    "resnet152": BackboneSpec(resnet.resnet152, "resnet"),
    "vgg11": BackboneSpec(vgg.vgg11, "vgg"),
    "vgg11_bn": BackboneSpec(vgg.vgg11_bn, "vgg"),
    "vgg13": BackboneSpec(vgg.vgg13, "vgg"),
    "vgg13_bn": BackboneSpec(vgg.vgg13_bn, "vgg"),
    "vgg16": BackboneSpec(vgg.vgg16, "vgg"),
    "vgg16_bn": BackboneSpec(vgg.vgg16_bn, "vgg"),
    "vgg19": BackboneSpec(vgg.vgg19, "vgg"),
    "vgg19_bn": BackboneSpec(vgg.vgg19_bn, "vgg"),
    "densenet121": BackboneSpec(densenet.densenet121, "densenet"),
    "densenet161": BackboneSpec(densenet.densenet161, "densenet"),
    "densenet169": BackboneSpec(densenet.densenet169, "densenet"),
    "densenet201": BackboneSpec(densenet.densenet201, "densenet"),
    "tiny": BackboneSpec(TinyFeatures, "tiny"),
}


def build_backbone(arch: str, **kw) -> nn.Module:
    if arch not in BACKBONES:
        raise ValueError(f"unknown backbone {arch!r}; options: {sorted(BACKBONES)}")
    return BACKBONES[arch].factory(**kw)
