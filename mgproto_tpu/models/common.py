"""Shared building blocks for the Flax backbone zoo.

Conventions:
  * NHWC layout (TPU-native; XLA tiles NHWC convs onto the MXU directly).
  * BatchNorm state lives in the `batch_stats` collection; `train` toggles
    use_running_average — cross-chip stats come from `axis_name='data'` when
    a mesh is active.
  * Module/parameter names deliberately mirror the torch module paths of the
    reference backbones (conv1, bn1, layer1/0/conv2, ...) so the
    torch->flax checkpoint converter (models/convert.py) is a mechanical
    key/layout transform rather than a lookup table.
  * Each backbone exposes `conv_info()` -> (kernels, strides, paddings) for
    the receptive-field arithmetic, describing the ops the forward pass
    ACTUALLY runs (the reference includes stem pools it skips — see
    ops/receptive_field.py docstring).
"""

from __future__ import annotations

from functools import partial
from typing import Any, List, Tuple

import flax.linen as nn
import jax.numpy as jnp

ConvInfo = Tuple[List[int], List[int], List[Any]]

# torch BatchNorm2d defaults: momentum=0.1 (flax momentum = 1 - 0.1), eps=1e-5.
# `dtype` is the mixed-precision compute dtype (params/batch_stats stay f32 via
# param_dtype; flax computes the batch statistics themselves in f32 regardless).
BatchNorm = partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5)


def conv(
    features: int,
    kernel: int,
    stride: int = 1,
    padding: int = 0,
    use_bias: bool = False,
    name: str | None = None,
    dtype: Any = None,
) -> nn.Conv:
    return nn.Conv(
        features=features,
        kernel_size=(kernel, kernel),
        strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        use_bias=use_bias,
        name=name,
        dtype=dtype,
    )


def max_pool(x: jnp.ndarray, kernel: int, stride: int, padding: int) -> jnp.ndarray:
    return nn.max_pool(
        x,
        window_shape=(kernel, kernel),
        strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
    )


def avg_pool(x: jnp.ndarray, kernel: int, stride: int) -> jnp.ndarray:
    return nn.avg_pool(x, window_shape=(kernel, kernel), strides=(stride, stride))
