"""Pretrained-trunk wiring: locate a torch checkpoint, convert, cache, merge.

The reference constructs every backbone with `pretrained=True`
(/root/reference/model.py:492, resnet_features.py:228-252 — torchvision
model-zoo weights, plus the BBN-iNaturalist R50 variant): CUB-class accuracy
is unreachable from random init. This module is the production consumer of
`models/convert.py`:

    create_train_state(pretrained=True)
      -> load_pretrained_trunk(arch)
           1. converted-cache hit?   ~/.cache/mgproto_tpu/converted/{arch}.npz
           2. else find a torch .pth in the search path, convert, write cache
      -> merge_pretrained_trunk(...)  — swap the 'features' subtree of the
         fresh init with the converted {params, batch_stats}

Search path for .pth files (first hit wins):
    $MGPROTO_PRETRAINED_DIR
    $TORCH_HOME/hub/checkpoints        (default ~/.cache/torch/hub/checkpoints)
    ~/.cache/mgproto_tpu/pretrained

This environment has no egress, so there is deliberately NO download step:
a missing checkpoint raises FileNotFoundError naming every directory
searched and the filename patterns tried, which is the actionable message
(drop the torchvision file in one of those dirs).
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from flax.traverse_util import flatten_dict, unflatten_dict

from mgproto_tpu.models.convert import convert_backbone, load_torch_checkpoint

# torchvision publishes files as "{arch}-{hash}.pth". Exception: this repo's
# resnet50 IS the BBN-iNaturalist variant (layer4 has 4 blocks, reference
# resnet_features.py:276-287), so plain torchvision resnet50 files are
# deliberately NOT matched — their 3-block layer4 cannot populate this trunk
# and would die deep in the converter instead of with an actionable error.
_ARCH_PATTERNS = {
    "resnet50": ["*BBN*iNaturalist*res50*.pth", "*iNat*res50*.pth"],
}


def _search_dirs() -> List[str]:
    dirs = []
    env = os.environ.get("MGPROTO_PRETRAINED_DIR")
    if env:
        dirs.append(env)
    torch_home = os.environ.get(
        "TORCH_HOME", os.path.join(os.path.expanduser("~"), ".cache", "torch")
    )
    dirs.append(os.path.join(torch_home, "hub", "checkpoints"))
    dirs.append(
        os.path.join(os.path.expanduser("~"), ".cache", "mgproto_tpu", "pretrained")
    )
    return dirs


def _cache_dir() -> str:
    return os.environ.get(
        "MGPROTO_CONVERTED_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "mgproto_tpu", "converted"),
    )


def _patterns(arch: str) -> List[str]:
    if arch in _ARCH_PATTERNS:
        return _ARCH_PATTERNS[arch]
    return [f"{arch}-*.pth", f"{arch}.pth"]


def find_torch_checkpoint(arch: str) -> Optional[str]:
    """First .pth on the search path matching this arch's filename patterns."""
    for d in _search_dirs():
        for pat in _patterns(arch):
            hits = sorted(glob.glob(os.path.join(d, pat)))
            if hits:
                return hits[0]
    return None


def _flatten(tree: Dict) -> Dict[str, np.ndarray]:
    return {
        k: np.asarray(v) for k, v in flatten_dict(dict(tree), sep="/").items()
    }


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    return unflatten_dict(flat, sep="/")


def load_pretrained_trunk(arch: str, cache: bool = True) -> Dict[str, Any]:
    """{'params': ..., 'batch_stats': ...} for the trunk, from the converted
    cache or by converting a located torch checkpoint.

    The cache records its source .pth path+mtime and is invalidated when the
    currently-resolved source differs — replacing the checkpoint file must
    not silently train from stale converted weights."""
    cache_path = os.path.join(_cache_dir(), f"{arch}.npz")
    pth = find_torch_checkpoint(arch)
    if cache and os.path.exists(cache_path):
        with np.load(cache_path) as z:
            src = str(z["__source__"]) if "__source__" in z.files else ""
            mtime = float(z["__mtime__"]) if "__mtime__" in z.files else -1.0
            fresh = pth is None or (
                src == pth and abs(mtime - os.path.getmtime(pth)) < 1e-6
            )
            if fresh:
                return _unflatten(
                    {k: z[k] for k in z.files if not k.startswith("__")}
                )
    if pth is None:
        searched = "\n  ".join(_search_dirs())
        pats = ", ".join(_patterns(arch))
        note = ""
        if arch == "resnet50":
            note = (
                "\nNOTE: this trunk is the BBN-iNaturalist R50 variant "
                "(4-block layer4); plain torchvision resnet50 files are "
                "incompatible and not accepted."
            )
        raise FileNotFoundError(
            f"no pretrained checkpoint for {arch!r}: tried patterns [{pats}] "
            f"in:\n  {searched}\n(this environment has no egress — place the "
            f"torchvision/BBN .pth file in one of those directories, e.g. "
            f"$MGPROTO_PRETRAINED_DIR){note}"
        )
    variables = convert_backbone(arch, load_torch_checkpoint(pth))
    if cache:
        os.makedirs(_cache_dir(), exist_ok=True)
        # pid-unique tmp + atomic rename: concurrent processes (multi-host
        # startup) may convert simultaneously without corrupting the cache
        tmp = f"{cache_path}.{os.getpid()}.tmp.npz"  # .npz: savez must not append
        np.savez(
            tmp,
            __source__=np.asarray(pth),
            __mtime__=np.asarray(os.path.getmtime(pth)),
            **_flatten(variables),
        )
        os.replace(tmp, cache_path)
    return variables


def merge_pretrained_trunk(
    net_params: Dict[str, Any],
    batch_stats: Dict[str, Any],
    trunk: Dict[str, Any],
    feature_key: str = "features",
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Replace the trunk subtree of a fresh init with converted weights.

    Validates that the converted tree has exactly the structure+shapes the
    model initialized — a mismatch means the arch and the checkpoint disagree
    and must fail loudly, not train silently from a half-merged net."""

    def _check(name: str, init_tree: Any, new_tree: Any) -> None:
        init_flat = _flatten(init_tree)
        new_flat = _flatten(new_tree)
        if init_flat.keys() != new_flat.keys():
            missing = sorted(init_flat.keys() - new_flat.keys())[:5]
            extra = sorted(new_flat.keys() - init_flat.keys())[:5]
            raise ValueError(
                f"pretrained {name} tree mismatch: missing={missing} "
                f"extra={extra}"
            )
        for k, v in init_flat.items():
            if v.shape != new_flat[k].shape:
                raise ValueError(
                    f"pretrained {name}[{k}] shape {new_flat[k].shape} != "
                    f"model's {v.shape}"
                )

    _check("params", net_params[feature_key], trunk["params"])
    cast = lambda ref, new: jax.tree_util.tree_map(
        lambda r, n: np.asarray(n, dtype=r.dtype), ref, new
    )
    net_params = dict(net_params)
    net_params[feature_key] = cast(net_params[feature_key], trunk["params"])
    new_stats = dict(batch_stats)
    if trunk.get("batch_stats"):
        _check("batch_stats", batch_stats[feature_key], trunk["batch_stats"])
        new_stats[feature_key] = cast(
            batch_stats[feature_key], trunk["batch_stats"]
        )
    return net_params, new_stats
