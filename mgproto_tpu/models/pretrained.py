"""Pretrained-trunk wiring: locate a torch checkpoint, convert, cache, merge.

The reference constructs every backbone with `pretrained=True`
(/root/reference/model.py:492, resnet_features.py:228-252 — torchvision
model-zoo weights, plus the BBN-iNaturalist R50 variant): CUB-class accuracy
is unreachable from random init. This module is the production consumer of
`models/convert.py`:

    create_train_state(pretrained=True)
      -> load_pretrained_trunk(arch)
           1. converted-cache hit?   ~/.cache/mgproto_tpu/converted/{arch}.npz
           2. else find a torch .pth in the search path, convert, write cache
      -> merge_pretrained_trunk(...)  — swap the 'features' subtree of the
         fresh init with the converted {params, batch_stats}

Search path for .pth files (first hit wins):
    $MGPROTO_PRETRAINED_DIR
    $TORCH_HOME/hub/checkpoints        (default ~/.cache/torch/hub/checkpoints)
    ~/.cache/mgproto_tpu/pretrained

Auto-fetch (VERDICT r3 item 6, OFF by default): with MGPROTO_AUTO_FETCH=1 a
missing checkpoint is downloaded from the torchvision model zoo (the URLs
the reference's model_urls tables point at, resnet_features.py:6-11 /
densenet_features.py:10-13 / vgg_features.py:6-13) into the cache search
path, sha256-verified against the 8-hex digest torchvision embeds in every
filename. The default stays manual-placement because this build environment
has zero egress — a fresh TPU VM flips one env var and `pretrained=True`
works with no torch-side step. Per-arch URL/digest env overrides
(MGPROTO_PRETRAINED_URL_<ARCH>, MGPROTO_PRETRAINED_SHA256_<ARCH>) exist for
mirrors — and give tests a file:// path to exercise the machinery offline.
The BBN-iNaturalist R50 has no stable public direct URL (the reference
points at a Google Drive page), so resnet50 stays manual unless a URL
override is supplied.
"""

from __future__ import annotations

import glob
import hashlib
import os
import re
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from flax.traverse_util import flatten_dict, unflatten_dict

from mgproto_tpu.models.convert import convert_backbone, load_torch_checkpoint

# torchvision publishes files as "{arch}-{hash}.pth". Exception: this repo's
# resnet50 IS the BBN-iNaturalist variant (layer4 has 4 blocks, reference
# resnet_features.py:276-287), so plain torchvision resnet50 files are
# deliberately NOT matched — their 3-block layer4 cannot populate this trunk
# and would die deep in the converter instead of with an actionable error.
_ARCH_PATTERNS = {
    "resnet50": ["*BBN*iNaturalist*res50*.pth", "*iNat*res50*.pth"],
}

# torchvision model-zoo URLs (the same files the reference's model_urls
# tables download). The 8-hex suffix in each filename is the first 8 chars
# of the file's sha256 — the download is verified against it. resnet50 is
# deliberately absent: this repo's resnet50 is the BBN-iNaturalist variant
# with no stable public direct URL.
_ZOO_URLS = {
    "resnet18": "https://download.pytorch.org/models/resnet18-5c106cde.pth",
    "resnet34": "https://download.pytorch.org/models/resnet34-333f7ec4.pth",
    "resnet101": "https://download.pytorch.org/models/resnet101-5d3b4d8f.pth",
    "resnet152": "https://download.pytorch.org/models/resnet152-b121ed2d.pth",
    "densenet121": "https://download.pytorch.org/models/densenet121-a639ec97.pth",
    "densenet169": "https://download.pytorch.org/models/densenet169-b2777c0a.pth",
    "densenet201": "https://download.pytorch.org/models/densenet201-c1103571.pth",
    "densenet161": "https://download.pytorch.org/models/densenet161-8d451a50.pth",
    "vgg11": "https://download.pytorch.org/models/vgg11-bbd30ac9.pth",
    "vgg13": "https://download.pytorch.org/models/vgg13-c768596a.pth",
    "vgg16": "https://download.pytorch.org/models/vgg16-397923af.pth",
    "vgg19": "https://download.pytorch.org/models/vgg19-dcbb9e9d.pth",
    "vgg11_bn": "https://download.pytorch.org/models/vgg11_bn-6002323d.pth",
    "vgg13_bn": "https://download.pytorch.org/models/vgg13_bn-abd245e5.pth",
    "vgg16_bn": "https://download.pytorch.org/models/vgg16_bn-6c64b313.pth",
    "vgg19_bn": "https://download.pytorch.org/models/vgg19_bn-c79401a0.pth",
}

_HASH_IN_NAME = re.compile(r"-([0-9a-f]{8,64})\.pth$")


def _search_dirs() -> List[str]:
    dirs = []
    env = os.environ.get("MGPROTO_PRETRAINED_DIR")
    if env:
        dirs.append(env)
    torch_home = os.environ.get(
        "TORCH_HOME", os.path.join(os.path.expanduser("~"), ".cache", "torch")
    )
    dirs.append(os.path.join(torch_home, "hub", "checkpoints"))
    dirs.append(
        os.path.join(os.path.expanduser("~"), ".cache", "mgproto_tpu", "pretrained")
    )
    return dirs


def _cache_dir() -> str:
    return os.environ.get(
        "MGPROTO_CONVERTED_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "mgproto_tpu", "converted"),
    )


def _patterns(arch: str) -> List[str]:
    if arch in _ARCH_PATTERNS:
        return _ARCH_PATTERNS[arch]
    return [f"{arch}-*.pth", f"{arch}.pth"]


def find_torch_checkpoint(arch: str) -> Optional[str]:
    """First .pth on the search path matching this arch's filename patterns."""
    for d in _search_dirs():
        for pat in _patterns(arch):
            hits = sorted(glob.glob(os.path.join(d, pat)))
            if hits:
                return hits[0]
    return None


# ------------------------------------------------------------- auto-fetch
def _url_for(arch: str) -> Optional[str]:
    """Download URL for an arch: env override first (mirrors; also how the
    offline tests inject file:// URLs), then the torchvision zoo table."""
    return (
        os.environ.get(f"MGPROTO_PRETRAINED_URL_{arch.upper()}")
        or _ZOO_URLS.get(arch)
    )


def _expected_sha256(arch: str, url: str) -> Optional[str]:
    """Hex digest (or unambiguous prefix) the download must match: env
    override first, else the 8-hex digest torchvision embeds in the
    filename. None = no checksum available (fetch refuses to proceed)."""
    env = os.environ.get(f"MGPROTO_PRETRAINED_SHA256_{arch.upper()}")
    if env:
        return env.lower()
    m = _HASH_IN_NAME.search(os.path.basename(url))
    return m.group(1) if m else None


def fetch_checkpoint(arch: str, url: Optional[str] = None,
                     dest_dir: Optional[str] = None) -> str:
    """Download the arch's checkpoint into the search path, sha256-verified.

    Streams to a pid-unique tmp file and renames atomically, so concurrent
    multi-host starts cannot corrupt each other; a checksum mismatch deletes
    the tmp and raises (nothing half-written ever enters the search path)."""
    url = url or _url_for(arch)
    if url is None:
        raise ValueError(
            f"no download URL known for arch {arch!r} (the BBN-iNaturalist "
            "resnet50 must be placed manually, or supply "
            f"MGPROTO_PRETRAINED_URL_{arch.upper()})"
        )
    expected = _expected_sha256(arch, url)
    if expected is None:
        raise ValueError(
            f"refusing to fetch {url}: no sha256 available — torchvision "
            "files carry it in the filename; for other sources set "
            f"MGPROTO_PRETRAINED_SHA256_{arch.upper()}"
        )
    import tempfile

    dest_dir = dest_dir or _search_dirs()[-1]
    os.makedirs(dest_dir, exist_ok=True)
    dest = os.path.join(dest_dir, os.path.basename(url))
    # mkstemp: unique even across hosts sharing the cache over NFS (pids can
    # coincide there); same dir so os.replace stays atomic
    fd, tmp = tempfile.mkstemp(dir=dest_dir, suffix=".fetch.tmp")
    digest = hashlib.sha256()
    try:
        # fdopen FIRST: if urlopen raises (DNS/404/timeout), f's exit still
        # closes the mkstemp descriptor — a mirror-retry loop must not leak
        # fds. Socket timeout covers connect AND read stalls: a blackholed
        # route must fail startup loudly, not hang a multi-host job at init.
        with os.fdopen(fd, "wb") as f, \
                urllib.request.urlopen(url, timeout=60) as r:
            while True:
                chunk = r.read(1 << 20)
                if not chunk:
                    break
                digest.update(chunk)
                f.write(chunk)
        got = digest.hexdigest()
        if not got.startswith(expected):
            raise ValueError(
                f"sha256 mismatch for {url}: got {got[:16]}..., "
                f"expected prefix {expected}"
            )
        os.replace(tmp, dest)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return dest


def _flatten(tree: Dict) -> Dict[str, np.ndarray]:
    return {
        k: np.asarray(v) for k, v in flatten_dict(dict(tree), sep="/").items()
    }


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    return unflatten_dict(flat, sep="/")


def load_pretrained_trunk(arch: str, cache: bool = True) -> Dict[str, Any]:
    """{'params': ..., 'batch_stats': ...} for the trunk, from the converted
    cache or by converting a located torch checkpoint.

    The cache records its source .pth path+mtime and is invalidated when the
    currently-resolved source differs — replacing the checkpoint file must
    not silently train from stale converted weights."""
    cache_path = os.path.join(_cache_dir(), f"{arch}.npz")
    pth = find_torch_checkpoint(arch)
    if cache and os.path.exists(cache_path):
        with np.load(cache_path) as z:
            src = str(z["__source__"]) if "__source__" in z.files else ""
            mtime = float(z["__mtime__"]) if "__mtime__" in z.files else -1.0
            fresh = pth is None or (
                src == pth and abs(mtime - os.path.getmtime(pth)) < 1e-6
            )
            if fresh:
                return _unflatten(
                    {k: z[k] for k in z.files if not k.startswith("__")}
                )
    if pth is None and os.environ.get("MGPROTO_AUTO_FETCH") == "1":
        if _url_for(arch) is not None:
            pth = fetch_checkpoint(arch)
    if pth is None:
        searched = "\n  ".join(_search_dirs())
        pats = ", ".join(_patterns(arch))
        note = ""
        if arch == "resnet50":
            note = (
                "\nNOTE: this trunk is the BBN-iNaturalist R50 variant "
                "(4-block layer4); plain torchvision resnet50 files are "
                "incompatible and not accepted."
            )
        elif arch in _ZOO_URLS:
            note = (
                "\nNOTE: set MGPROTO_AUTO_FETCH=1 to download it from the "
                "torchvision model zoo automatically (off by default; this "
                "build environment has zero egress)."
            )
        raise FileNotFoundError(
            f"no pretrained checkpoint for {arch!r}: tried patterns [{pats}] "
            f"in:\n  {searched}\n(place the torchvision/BBN .pth file in one "
            f"of those directories, e.g. $MGPROTO_PRETRAINED_DIR){note}"
        )
    variables = convert_backbone(arch, load_torch_checkpoint(pth))
    if cache:
        os.makedirs(_cache_dir(), exist_ok=True)
        # pid-unique tmp + atomic rename: concurrent processes (multi-host
        # startup) may convert simultaneously without corrupting the cache
        tmp = f"{cache_path}.{os.getpid()}.tmp.npz"  # .npz: savez must not append
        np.savez(
            tmp,
            __source__=np.asarray(pth),
            __mtime__=np.asarray(os.path.getmtime(pth)),
            **_flatten(variables),
        )
        os.replace(tmp, cache_path)
    return variables


def merge_pretrained_trunk(
    net_params: Dict[str, Any],
    batch_stats: Dict[str, Any],
    trunk: Dict[str, Any],
    feature_key: str = "features",
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Replace the trunk subtree of a fresh init with converted weights.

    Validates that the converted tree has exactly the structure+shapes the
    model initialized — a mismatch means the arch and the checkpoint disagree
    and must fail loudly, not train silently from a half-merged net."""

    def _check(name: str, init_tree: Any, new_tree: Any) -> None:
        init_flat = _flatten(init_tree)
        new_flat = _flatten(new_tree)
        if init_flat.keys() != new_flat.keys():
            missing = sorted(init_flat.keys() - new_flat.keys())[:5]
            extra = sorted(new_flat.keys() - init_flat.keys())[:5]
            raise ValueError(
                f"pretrained {name} tree mismatch: missing={missing} "
                f"extra={extra}"
            )
        for k, v in init_flat.items():
            if v.shape != new_flat[k].shape:
                raise ValueError(
                    f"pretrained {name}[{k}] shape {new_flat[k].shape} != "
                    f"model's {v.shape}"
                )

    _check("params", net_params[feature_key], trunk["params"])
    cast = lambda ref, new: jax.tree_util.tree_map(
        lambda r, n: np.asarray(n, dtype=r.dtype), ref, new
    )
    net_params = dict(net_params)
    net_params[feature_key] = cast(net_params[feature_key], trunk["params"])
    new_stats = dict(batch_stats)
    if trunk.get("batch_stats"):
        _check("batch_stats", batch_stats[feature_key], trunk["batch_stats"])
        new_stats[feature_key] = cast(
            batch_stats[feature_key], trunk["batch_stats"]
        )
    return net_params, new_stats
