from mgproto_tpu.models.registry import build_backbone, BACKBONES, BackboneSpec

__all__ = ["build_backbone", "BACKBONES", "BackboneSpec"]
