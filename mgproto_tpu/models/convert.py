"""Torch-checkpoint -> Flax-variables converter for the backbone zoo.

Keeps the reference's "pretrained=True" capability (resnet_features.py:228-317,
densenet_features.py:178-328, vgg_features.py:127-293) without torch at train
time: the torchvision / BBN-iNaturalist state_dicts are converted once, on
host, to a flax {params, batch_stats} tree and saved as an orbax/npz
checkpoint. Handles the reference's checkpoint-key quirks:

  * BBN iNat R50: 'module.backbone.' prefix strip + cb_block/rb_block ->
    layer4.2/layer4.3 renames (resnet_features.py:283-287);
  * legacy DenseNet 'norm.1' -> 'norm1' key regex (densenet_features.py:192-207)
    — normalized here by simply dropping dots inside layer-local names;
  * classifier/fc heads dropped (trunks only).

Layout transforms: conv [O,I,kh,kw] -> [kh,kw,I,O]; linear [O,I] -> [I,O];
BatchNorm weight/bias -> scale/bias (params), running_mean/var -> mean/var
(batch_stats).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Mapping, Tuple

import numpy as np


def _set(tree: Dict, path: Tuple[str, ...], value: np.ndarray) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


def _conv_kernel(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 1, 0))


def normalize_torch_keys(state: Mapping[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Strip wrapper prefixes and legacy dot-names so every key looks like the
    modern torchvision layout."""
    out: Dict[str, np.ndarray] = {}
    for k, v in state.items():
        k = re.sub(r"^module\.", "", k)
        k = re.sub(r"^backbone\.", "", k)
        # BBN iNaturalist R50 (resnet_features.py:286)
        k = k.replace("cb_block", "layer4.2").replace("rb_block", "layer4.3")
        # legacy densenet 'norm.1.weight' -> 'norm1.weight'
        k = re.sub(r"\.(norm|relu|conv)\.(\d)\.", r".\1\2.", k)
        # densenet checkpoints nest under 'features.'
        k = re.sub(r"^features\.", "", k)
        if k.startswith(("classifier.", "fc.")):
            continue
        out[k] = np.asarray(v)
    return out


def _convert_bn(
    params: Dict, stats: Dict, flax_path: Tuple[str, ...],
    state: Mapping[str, np.ndarray], torch_prefix: str,
) -> None:
    _set(params, flax_path + ("scale",), state[torch_prefix + ".weight"])
    _set(params, flax_path + ("bias",), state[torch_prefix + ".bias"])
    _set(stats, flax_path + ("mean",), state[torch_prefix + ".running_mean"])
    _set(stats, flax_path + ("var",), state[torch_prefix + ".running_var"])


def _convert_conv(
    params: Dict, flax_path: Tuple[str, ...],
    state: Mapping[str, np.ndarray], torch_prefix: str,
) -> None:
    _set(params, flax_path + ("kernel",), _conv_kernel(state[torch_prefix + ".weight"]))
    if torch_prefix + ".bias" in state:
        _set(params, flax_path + ("bias",), state[torch_prefix + ".bias"])


def convert_resnet(
    state: Mapping[str, np.ndarray], layers: Tuple[int, ...], bottleneck: bool
) -> Dict[str, Any]:
    state = normalize_torch_keys(state)
    params: Dict = {}
    stats: Dict = {}
    _convert_conv(params, ("conv1",), state, "conv1")
    _convert_bn(params, stats, ("bn1",), state, "bn1")
    n_convs = 3 if bottleneck else 2
    for li, blocks in enumerate(layers, start=1):
        for bi in range(blocks):
            t = f"layer{li}.{bi}"
            f = f"layer{li}_{bi}"
            for ci in range(1, n_convs + 1):
                _convert_conv(params, (f, f"conv{ci}"), state, f"{t}.conv{ci}")
                _convert_bn(params, stats, (f, f"bn{ci}"), state, f"{t}.bn{ci}")
            if f"{t}.downsample.0.weight" in state:
                _convert_conv(params, (f, "downsample_conv"), state, f"{t}.downsample.0")
                _convert_bn(params, stats, (f, "downsample_bn"), state, f"{t}.downsample.1")
    return {"params": params, "batch_stats": stats}


def convert_vgg(
    state: Mapping[str, np.ndarray], cfg: Tuple, batch_norm: bool
) -> Dict[str, Any]:
    """Torch VGG `features.{seq_idx}` -> our `conv{j}`/`bn{j}` naming: walk the
    cfg the same way _make_layers does, tracking the torch sequential index."""
    state = normalize_torch_keys(state)
    params: Dict = {}
    stats: Dict = {}
    seq = 0
    conv_idx = 0
    for v in cfg:
        if v == "M":
            seq += 1  # pool (present in torch checkpoints' indexing)
            continue
        _convert_conv(params, (f"conv{conv_idx}",), state, f"{seq}")
        seq += 1
        if batch_norm:
            _convert_bn(params, stats, (f"bn{conv_idx}",), state, f"{seq}")
            seq += 1
        seq += 1  # relu
        conv_idx += 1
    return {"params": params, "batch_stats": stats}


def convert_densenet(
    state: Mapping[str, np.ndarray], block_config: Tuple[int, ...]
) -> Dict[str, Any]:
    state = normalize_torch_keys(state)
    params: Dict = {}
    stats: Dict = {}
    _convert_conv(params, ("conv0",), state, "conv0")
    _convert_bn(params, stats, ("norm0",), state, "norm0")
    for bi, num_layers in enumerate(block_config, start=1):
        for li in range(1, num_layers + 1):
            t = f"denseblock{bi}.denselayer{li}"
            f = f"denseblock{bi}_denselayer{li}"
            _convert_bn(params, stats, (f, "norm1"), state, f"{t}.norm1")
            _convert_conv(params, (f, "conv1"), state, f"{t}.conv1")
            _convert_bn(params, stats, (f, "norm2"), state, f"{t}.norm2")
            _convert_conv(params, (f, "conv2"), state, f"{t}.conv2")
        if bi != len(block_config):
            t = f"transition{bi}"
            _convert_bn(params, stats, (t, "norm"), state, f"{t}.norm")
            _convert_conv(params, (t, "conv"), state, f"{t}.conv")
    _convert_bn(params, stats, ("norm5",), state, "norm5")
    return {"params": params, "batch_stats": stats}


def convert_backbone(arch: str, state: Mapping[str, np.ndarray]) -> Dict[str, Any]:
    """Dispatch on architecture name (registry names)."""
    from mgproto_tpu.models import vgg as vgg_mod

    if arch.startswith("resnet"):
        layers = {
            "resnet18": ((2, 2, 2, 2), False),
            "resnet34": ((3, 4, 6, 3), False),
            "resnet50": ((3, 4, 6, 4), True),
            "resnet101": ((3, 4, 23, 3), True),
            "resnet152": ((3, 8, 36, 3), True),
        }[arch]
        return convert_resnet(state, *layers)
    if arch.startswith("vgg"):
        bn = arch.endswith("_bn")
        cfg_key = {"vgg11": "A", "vgg13": "B", "vgg16": "D", "vgg19": "E"}[
            arch.replace("_bn", "")
        ]
        return convert_vgg(state, tuple(vgg_mod.CFGS[cfg_key]), bn)
    if arch.startswith("densenet"):
        cfgs = {
            "densenet121": (6, 12, 24, 16),
            "densenet169": (6, 12, 32, 32),
            "densenet201": (6, 12, 48, 32),
            "densenet161": (6, 12, 36, 24),
        }
        return convert_densenet(state, cfgs[arch])
    raise ValueError(f"no converter for {arch!r}")


def load_torch_checkpoint(path: str) -> Dict[str, np.ndarray]:
    """Load a .pth state_dict to numpy (torch is a host-side tool only)."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(obj, "state_dict"):
        obj = obj.state_dict()
    if "state_dict" in obj and isinstance(obj["state_dict"], dict):
        obj = obj["state_dict"]
    return {k: v.numpy() for k, v in obj.items() if hasattr(v, "numpy")}
