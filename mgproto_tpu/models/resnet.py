"""ResNet feature trunks (Flax), reference parity with models/resnet_features.py.

Reference quirks reproduced:
  * the stem maxpool is SKIPPED in the forward pass (resnet_features.py:199),
    doubling the latent grid (14x14 -> 28x28 for R50-style stacks at 224);
    controlled by `stem_pool` (default False = reference behavior);
  * resnet50 uses layers [3, 4, 6, 4] — an extra layer4 block so the BBN
    iNaturalist checkpoint's cb/rb blocks map to layer4.2/layer4.3
    (resnet_features.py:276-287).

conv_info() reports only ops the forward actually executes (unlike the
reference, which always counts the skipped maxpool, resnet_features.py:140).
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import flax.linen as nn

from mgproto_tpu.models.common import BatchNorm, ConvInfo, conv, max_pool
from mgproto_tpu.ops.fused_epilogue import BNEpilogue


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity shortcut (reference resnet_features.py:27-69).

    `fused_epilogue` routes the block tail (bn2 + shortcut add + ReLU)
    through the Pallas epilogue kernel (ops/fused_epilogue.py) — identical
    param/stat layout under the same "bn2" mount, parity-pinned — instead
    of the plain nn.BatchNorm chain."""

    planes: int
    stride: int = 1
    has_downsample: bool = False
    expansion: int = 1
    dtype: Any = None
    fused_epilogue: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        identity = x
        out = conv(self.planes, 3, self.stride, 1, name="conv1", dtype=self.dtype)(x)
        out = BatchNorm(name="bn1", dtype=self.dtype)(out, use_running_average=not train)
        out = nn.relu(out)
        out = conv(self.planes, 3, 1, 1, name="conv2", dtype=self.dtype)(out)
        if self.has_downsample:
            identity = conv(
                self.planes, 1, self.stride, 0, name="downsample_conv", dtype=self.dtype
            )(x)
            identity = BatchNorm(name="downsample_bn", dtype=self.dtype)(
                identity, use_running_average=not train
            )
        if self.fused_epilogue:
            return BNEpilogue(name="bn2", dtype=self.dtype)(
                out, identity, use_running_average=not train
            )
        out = BatchNorm(name="bn2", dtype=self.dtype)(out, use_running_average=not train)
        return nn.relu(out + identity)

    @staticmethod
    def block_conv_info(stride: int) -> ConvInfo:
        return [3, 3], [stride, 1], [1, 1]


class Bottleneck(nn.Module):
    """1x1 -> 3x3 -> 1x1 with 4x expansion (reference resnet_features.py:72-119)."""

    planes: int
    stride: int = 1
    has_downsample: bool = False
    expansion: int = 4
    dtype: Any = None
    fused_epilogue: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        identity = x
        out = conv(self.planes, 1, 1, 0, name="conv1", dtype=self.dtype)(x)
        out = BatchNorm(name="bn1", dtype=self.dtype)(out, use_running_average=not train)
        out = nn.relu(out)
        out = conv(self.planes, 3, self.stride, 1, name="conv2", dtype=self.dtype)(out)
        out = BatchNorm(name="bn2", dtype=self.dtype)(out, use_running_average=not train)
        out = nn.relu(out)
        out = conv(self.planes * 4, 1, 1, 0, name="conv3", dtype=self.dtype)(out)
        if self.has_downsample:
            identity = conv(
                self.planes * 4, 1, self.stride, 0, name="downsample_conv",
                dtype=self.dtype,
            )(x)
            identity = BatchNorm(name="downsample_bn", dtype=self.dtype)(
                identity, use_running_average=not train
            )
        if self.fused_epilogue:
            return BNEpilogue(name="bn3", dtype=self.dtype)(
                out, identity, use_running_average=not train
            )
        out = BatchNorm(name="bn3", dtype=self.dtype)(out, use_running_average=not train)
        return nn.relu(out + identity)

    @staticmethod
    def block_conv_info(stride: int) -> ConvInfo:
        return [1, 3, 1], [1, stride, 1], [0, 1, 0]


class ResNetFeatures(nn.Module):
    """Conv trunk of ResNet; avgpool/fc removed (reference :122-226)."""

    block_cls: type
    layers: Sequence[int]
    stem_pool: bool = False  # reference skips it (resnet_features.py:199)
    dtype: Any = None
    # jax.checkpoint each residual block: backward recomputes block internals
    # instead of storing them — HBM for FLOPs, the standard remat trade for
    # larger batches (scope names are preserved, so checkpoints interchange)
    remat: bool = False
    # selective per-stage remat: checkpoint only the named stages
    # ("layer1".."layer4"). layer1 is the sweet spot at the reference's
    # no-stem-pool 112^2 resolution: its 64-channel blocks are cheap to
    # recompute but hold the widest activations in the trunk (PERF.md).
    # Ignored when `remat` is True.
    remat_stages: Tuple[str, ...] = ()
    # fuse each block's BN+shortcut-add+ReLU tail into one Pallas VMEM pass
    # (ops/fused_epilogue.py; resolved per-backend by core/mgproto.py)
    fused_epilogue: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv(64, 7, 2, 3, name="conv1", dtype=self.dtype)(x)
        x = BatchNorm(name="bn1", dtype=self.dtype)(x, use_running_average=not train)
        x = nn.relu(x)
        if self.stem_pool:
            x = max_pool(x, 3, 2, 1)

        remat_cls = nn.remat(self.block_cls, static_argnums=(2,))
        inplanes = 64
        for li, (planes, blocks) in enumerate(
            zip((64, 128, 256, 512), self.layers)
        ):
            stage_remat = self.remat or f"layer{li + 1}" in self.remat_stages
            block_cls = remat_cls if stage_remat else self.block_cls
            stride = 1 if li == 0 else 2
            for bi in range(blocks):
                s = stride if bi == 0 else 1
                needs_ds = s != 1 or inplanes != planes * self.block_cls.expansion
                x = block_cls(
                    planes=planes,
                    stride=s,
                    has_downsample=needs_ds and bi == 0,
                    name=f"layer{li + 1}_{bi}",
                    dtype=self.dtype,
                    fused_epilogue=self.fused_epilogue,
                )(x, train)
                inplanes = planes * self.block_cls.expansion
        return x

    @property
    def out_channels(self) -> int:
        return 512 * self.block_cls.expansion

    def conv_info(self) -> ConvInfo:
        ks: List[int] = [7]
        ss: List[int] = [2]
        ps: List[int] = [3]
        if self.stem_pool:
            ks += [3]
            ss += [2]
            ps += [1]
        for li, blocks in enumerate(self.layers):
            stride = 1 if li == 0 else 2
            for bi in range(blocks):
                k, s, p = self.block_cls.block_conv_info(stride if bi == 0 else 1)
                ks += k
                ss += s
                ps += p
        return ks, ss, ps


def resnet18(**kw) -> ResNetFeatures:
    return ResNetFeatures(BasicBlock, [2, 2, 2, 2], **kw)


def resnet34(**kw) -> ResNetFeatures:
    return ResNetFeatures(BasicBlock, [3, 4, 6, 3], **kw)


def resnet50(**kw) -> ResNetFeatures:
    # [3,4,6,4]: extra layer4 block for the BBN iNaturalist checkpoint
    # (reference resnet_features.py:276)
    return ResNetFeatures(Bottleneck, [3, 4, 6, 4], **kw)


def resnet101(**kw) -> ResNetFeatures:
    return ResNetFeatures(Bottleneck, [3, 4, 23, 3], **kw)


def resnet152(**kw) -> ResNetFeatures:
    return ResNetFeatures(Bottleneck, [3, 8, 36, 3], **kw)
