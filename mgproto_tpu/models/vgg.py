"""VGG feature trunks (Flax), reference parity with models/vgg_features.py.

Reference quirks reproduced (defaults): the FINAL maxpool of the standard cfg
is removed (vgg_features.py:64-68), so the latent grid is 14x14 at 224 input;
`final_relu=False` drops the ReLU after the final conv of non-BN variants
(vgg_features.py:80-84 — the `i >= n-2` test only ever matches the last conv,
since the last cfg entry is always 'M'; default True = ReLU kept).
"""

from __future__ import annotations

from typing import Any, List, Tuple, Union

import flax.linen as nn

from mgproto_tpu.models.common import BatchNorm, ConvInfo, conv, max_pool

CFGS = {
    # reference vgg_features.py:18-23
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512,
          "M", 512, 512, 512, 512, "M"],
}


class VGGFeatures(nn.Module):
    cfg: Tuple[Union[int, str], ...]
    batch_norm: bool = False
    final_maxpool: bool = False  # reference default: final pool removed
    final_relu: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv_idx = 0
        n = len(self.cfg)
        for i, v in enumerate(self.cfg):
            if v == "M":
                if i == n - 1 and not self.final_maxpool:
                    continue
                x = max_pool(x, 2, 2, 0)
            else:
                # torch VGG convs have bias (nn.Conv2d default)
                x = conv(
                    int(v), 3, 1, 1, use_bias=True, name=f"conv{conv_idx}",
                    dtype=self.dtype,
                )(x)
                if self.batch_norm:
                    x = BatchNorm(name=f"bn{conv_idx}", dtype=self.dtype)(
                        x, use_running_average=not train
                    )
                    x = nn.relu(x)
                elif i >= n - 2 and not self.final_relu:
                    pass  # reference vgg_features.py:80-82
                else:
                    x = nn.relu(x)
                conv_idx += 1
        return x

    @property
    def out_channels(self) -> int:
        return int([v for v in self.cfg if v != "M"][-1])

    def conv_info(self) -> ConvInfo:
        ks: List[int] = []
        ss: List[int] = []
        ps: List[int] = []
        n = len(self.cfg)
        for i, v in enumerate(self.cfg):
            if v == "M":
                if i == n - 1 and not self.final_maxpool:
                    continue
                ks += [2]
                ss += [2]
                ps += [0]
            else:
                ks += [3]
                ss += [1]
                ps += [1]
        return ks, ss, ps


def _vgg(cfg_key: str, batch_norm: bool, **kw) -> VGGFeatures:
    return VGGFeatures(cfg=tuple(CFGS[cfg_key]), batch_norm=batch_norm, **kw)


def vgg11(**kw):
    return _vgg("A", False, **kw)


def vgg11_bn(**kw):
    return _vgg("A", True, **kw)


def vgg13(**kw):
    return _vgg("B", False, **kw)


def vgg13_bn(**kw):
    return _vgg("B", True, **kw)


def vgg16(**kw):
    return _vgg("D", False, **kw)


def vgg16_bn(**kw):
    return _vgg("D", True, **kw)


def vgg19(**kw):
    return _vgg("E", False, **kw)


def vgg19_bn(**kw):
    return _vgg("E", True, **kw)
