"""Top-T spatial mining pool, in log domain.

Reference: /root/reference/model.py:188-206 (`global_max_pooling_gmm_topT`)
takes top-T of exp(log_prob) over the H*W grid per prototype, then gathers the
feature vector at each selected location with a T-iteration python gather loop.

TPU-native design: log is monotonic, so top-T over log-densities selects the
same locations/ordering as top-T over densities — we stay in log domain (no
overflow, no exp) and use a single `lax.top_k` + one `take_along_axis` for the
top-1 features (only the top-1 features are ever consumed downstream — the
reference computes all T and drops T-1 of them, model.py:225-226).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class PooledActivations(NamedTuple):
    """Result of the mining pool.

    log_act:   [B, C, K, T] top-T per-prototype log-densities (sorted desc).
    top1_idx:  [B, C, K] flat spatial index (h * W + w) of each prototype's
               best patch.
    top1_feat: [B, C, K, d] feature vector at that patch.
    """

    log_act: jax.Array
    top1_idx: jax.Array
    top1_feat: jax.Array


def top_t_pool(log_prob: jax.Array, features: jax.Array, mine_T: int) -> PooledActivations:
    """Args:
      log_prob: [B, C, K, H, W] per-patch log-densities.
      features: [B, H, W, d] L2-normalized feature map (NHWC).
      mine_T:   number of mining levels T.
    """
    b, c, k, h, w = log_prob.shape
    flat = log_prob.reshape(b, c, k, h * w)
    vals, idx = jax.lax.top_k(flat, mine_T)  # [B, C, K, T]

    top1 = idx[..., 0]  # [B, C, K]
    feats_flat = features.reshape(b, h * w, -1)  # [B, HW, d]
    gathered = jnp.take_along_axis(
        feats_flat, top1.reshape(b, c * k, 1), axis=1
    )  # [B, C*K, d]
    top1_feat = gathered.reshape(b, c, k, -1)
    return PooledActivations(log_act=vals, top1_idx=top1, top1_feat=top1_feat)


def mine_mask_activations(
    log_act: jax.Array, labels: jax.Array | None
) -> jax.Array:
    """Hard-mining mask (reference model.py:218-221).

    For mining level t >= 1, prototypes NOT belonging to the ground-truth class
    keep their top-1 activation, while ground-truth prototypes use their t-th
    strongest patch (weaker evidence) — so the mine CE pits the target class's
    t-th-best evidence against every other class's best evidence.

    Args:
      log_act: [B, C, K, T]; labels: [B] int or None (eval: no masking).
    Returns:
      [B, C, K, T] masked activations.
    """
    if labels is None:
        return log_act
    c = log_act.shape[1]
    is_gt = jax.nn.one_hot(labels, c, dtype=bool)  # [B, C]
    top1 = log_act[..., :1]  # [B, C, K, 1]
    keep = is_gt[:, :, None, None]  # [B, C, 1, 1]
    # level 0 is untouched either way: top1 IS log_act[..., 0]
    return jnp.where(keep, log_act, jnp.broadcast_to(top1, log_act.shape))


def dedup_first_occurrence(idx: jax.Array) -> jax.Array:
    """Mask keeping only the first occurrence of each value along the last axis.

    Functional replacement for the reference's per-sample python dedup of
    enqueue candidates by spatial index (model.py:238-246): several prototypes
    of the same class often peak at the same patch; only one copy of that
    feature vector may enter the memory bank.

    Args:
      idx: [..., K] integer spatial indices.
    Returns:
      [..., K] bool mask, True where idx[i] != idx[j] for all j < i.
    """
    k = idx.shape[-1]
    eq = idx[..., :, None] == idx[..., None, :]  # [..., K, K]
    earlier = jnp.tril(jnp.ones((k, k), dtype=bool), k=-1)
    dup_of_earlier = jnp.any(eq & earlier, axis=-1)
    return ~dup_of_earlier
