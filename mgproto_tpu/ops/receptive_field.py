"""Closed-form receptive-field arithmetic.

Standard conv-net RF propagation (size n, jump j, RF extent r, first-center
offset). Same math as reference utils/receptive_field.py:4-141, which maps a
prototype's latent (h, w) location back to an input-pixel box for
visualization. Framework-neutral; runs on host at model-construction time.

Note: the reference's ResNet `conv_info` includes the stem maxpool even though
the forward pass skips it (resnet_features.py:140-142 vs :199), silently
halving the RF grid size. Our backbones emit conv_info that matches the ops
actually executed; `RFInfo.grid_size` therefore equals the real latent H/W.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class RFInfo:
    """RF state after some prefix of layers."""

    grid_size: int  # spatial size n of this layer's output
    jump: int  # input pixels per unit step in this layer's grid
    rf_size: int  # RF extent r in input pixels
    start: float  # input-pixel center of output position (0, 0)


def propagate(
    rf: RFInfo, kernel: int, stride: int, padding: int | str
) -> RFInfo:
    """Propagate RF info through one conv/pool layer (reference :4-42)."""
    n_in, j_in, r_in, start_in = rf.grid_size, rf.jump, rf.rf_size, rf.start

    if padding == "SAME":
        n_out = math.ceil(n_in / stride)
        if n_in % stride == 0:
            pad = max(kernel - stride, 0)
        else:
            pad = max(kernel - (n_in % stride), 0)
    elif padding == "VALID":
        n_out = math.ceil((n_in - kernel + 1) / stride)
        pad = 0
    else:
        pad = padding * 2
        n_out = (n_in - kernel + pad) // stride + 1

    pad_left = pad // 2
    return RFInfo(
        grid_size=n_out,
        jump=j_in * stride,
        rf_size=r_in + (kernel - 1) * j_in,
        start=start_in + ((kernel - 1) / 2 - pad_left) * j_in,
    )


def proto_layer_rf_info(
    img_size: int,
    kernels: Sequence[int],
    strides: Sequence[int],
    paddings: Sequence[int | str],
    proto_kernel_size: int = 1,
) -> RFInfo:
    """RF info of the prototype layer (reference :111-141): the backbone stack
    followed by the 1x1 (VALID) prototype comparison window."""
    assert len(kernels) == len(strides) == len(paddings)
    rf = RFInfo(grid_size=img_size, jump=1, rf_size=1, start=0.5)
    for k, s, p in zip(kernels, strides, paddings):
        rf = propagate(rf, k, s, p)
    return propagate(rf, proto_kernel_size, 1, "VALID")


def rf_box_at(
    rf: RFInfo, img_size: int, h: int, w: int
) -> Tuple[int, int, int, int]:
    """Input-pixel box (h0, h1, w0, w1) of the RF centered at latent (h, w)
    (reference :44-62)."""
    assert h < rf.grid_size and w < rf.grid_size, (h, w, rf.grid_size)
    ch = rf.start + h * rf.jump
    cw = rf.start + w * rf.jump
    half = rf.rf_size / 2
    return (
        max(int(ch - half), 0),
        min(int(ch + half), img_size),
        max(int(cw - half), 0),
        min(int(cw + half), img_size),
    )


def rf_boxes(
    rf: RFInfo, img_size: int, locations: Sequence[Tuple[int, int, int]]
) -> List[Tuple[int, int, int, int, int]]:
    """Batch version over (img_index, h, w) triples (reference :64-87)."""
    out = []
    for img_index, h, w in locations:
        out.append((img_index, *rf_box_at(rf, img_size, h, w)))
    return out
