"""Fused Pallas E-step: responsibilities + sufficient statistics in VMEM.

EM's E-step over the memory bank is the heaviest non-MXU phase of the
steady-state train step (PERF.md: "EM's masked reductions over the full
[200, 800, 64] memory bank"). Evaluated the XLA way (ops/gaussian.py e_step
vmapped over classes), each EM round materializes per-class [N, K]
log-density and responsibility matrices in HBM, and the m-step objective's
backward re-reads the bank and the responsibilities once more.

This kernel keeps one class's whole E-step in VMEM: two MXU matmuls produce
the [N, K] weighted log-densities, a stable softmax turns them into
responsibilities, and only the SUFFICIENT STATISTICS leave the chip —

    s   [K]    = sum_n r[n, k]
    sx  [K, d] = sum_n r[n, k] * x[n]
    sxx [K, d] = sum_n r[n, k] * x[n]^2
    ll  scalar = mean_n logsumexp_k

(~2 KB per class at flagship K=10, d=64 vs ~2.6 MB of intermediates). The
m-step objective is an exact function of (s, sx, sxx) — see core/em.py
`_m_step_objective_stats` — so no [N, K] array is ever needed again, and
because responsibilities are CONSTANTS in the m-step (the reference computes
them under no_grad, model.py:340-344), the kernel needs no custom VJP at
all: nothing differentiates through it.

Smoothing note (why raw stats suffice): the reference smooths
resp' = (resp + alpha) / sum_k(resp + alpha) (model.py:383); since
sum_k resp[n, :] = 1, the denominator is the constant 1 + K*alpha, so
smoothed statistics are affine in the raw ones, with sum_n x = sum_k sx
and sum_n x^2 = sum_k sxx (again because responsibilities sum to 1).
core/em.py applies that affine map; the kernel stays smoothing-agnostic.

Numerics: the same `precompute_diag_gaussian` as every other density path
(single source of the quadratic expansion), f32 with HIGHEST matmul
precision. Auto-gated like ops/fused_scoring.py: Mosaic on TPU, interpret
mode elsewhere (correct but slow — tests only). On class-sharded meshes the
call is shard_map-composed (each model shard runs the same pallas_call on
its local class slab; per-class stats need no collective).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from mgproto_tpu.ops.gaussian import DEFAULT_SIGMA_EPS, precompute_diag_gaussian

_NEG_INF = float("-inf")


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _estep_kernel(x_ref, msc_ref, ivar_ref, const_ref, s_ref, sx_ref, sxx_ref, ll_ref):
    """One class per grid cell.

    x_ref:     [1, N, d]   the class's memory-bank slab.
    msc_ref:   [1, KP, d]  mu / sigma^2 (K padded to KP lanes).
    ivar_ref:  [1, KP, d]  1 / sigma^2 (0 in padded slots).
    const_ref: [1, KP]     density const + log prior (-inf in padded slots).
    s_ref:     [1, KP]     out: sum_n resp.
    sx_ref:    [1, KP, d]  out: resp^T x.
    sxx_ref:   [1, KP, d]  out: resp^T x^2.
    ll_ref:    [1, LP]     out: mean log-likelihood, broadcast over LP.
    """
    x = x_ref[0]  # [N, d]
    xx = x * x
    # weighted log-density w[n, k] = const_k + x.(mu*s) - 0.5 (x*x).s
    cross = jax.lax.dot_general(
        x, msc_ref[0],
        (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # [N, KP]
    quad = jax.lax.dot_general(
        xx, ivar_ref[0],
        (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # [N, KP]
    w = const_ref[0][None, :] + cross - 0.5 * quad  # [N, KP]

    # stable softmax over K: padded slots hold -inf -> exp 0, never selected
    m = jnp.max(w, axis=1, keepdims=True)  # [N, 1]; finite (K live slots)
    e = jnp.exp(w - m)
    z = jnp.sum(e, axis=1, keepdims=True)
    resp = e / z  # [N, KP]
    log_norm = m + jnp.log(z)  # [N, 1] logsumexp

    s_ref[0, :] = jnp.sum(resp, axis=0)
    sx_ref[0] = jax.lax.dot_general(
        resp, x,
        (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # [KP, d]
    sxx_ref[0] = jax.lax.dot_general(
        resp, xx,
        (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    ll_ref[0, :] = jnp.full((ll_ref.shape[1],), jnp.mean(log_norm), jnp.float32)


def _estep_stats_impl(
    x: jax.Array,
    means: jax.Array,
    sigmas: jax.Array,
    priors: jax.Array,
    eps: float,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    a, n, d = x.shape
    k = means.shape[1]
    # K is a SUBLANE dim in the [1, KP, d] blocks (d is the lane dim) and
    # the lane dim only of the in-VMEM [N, KP] density tile, which Mosaic
    # pads to lane width internally for free — so 8-alignment suffices, and
    # the HBM-resident padded tensors stay ~K-sized instead of 128-sized
    # (12.8x at flagship K=10)
    kp = _round_up(k, 8)
    lp = 8  # ll is a per-class scalar; a sublane-aligned row to write it to

    # shared density precompute (ops/gaussian.py — the ONE quadratic
    # expansion), then fold the log prior in and pad K. Padded slots get
    # inv_var=0 / const=-inf: densities -inf, responsibilities exactly 0.
    m_scaled, inv_var, const = precompute_diag_gaussian(means, sigmas, eps)
    m_scaled = m_scaled.reshape(a, k, d)
    inv_var = inv_var.reshape(a, k, d)
    const = const.reshape(a, k) + jnp.log(priors.astype(jnp.float32) + eps)
    msc = jnp.pad(m_scaled, ((0, 0), (0, kp - k), (0, 0)))
    ivar = jnp.pad(inv_var, ((0, 0), (0, kp - k), (0, 0)))
    const = jnp.pad(const, ((0, 0), (0, kp - k)), constant_values=_NEG_INF)

    with jax.named_scope("em_estep_fused"):
        s, sx, sxx, ll = pl.pallas_call(
            _estep_kernel,
            grid=(a,),
            in_specs=[
                pl.BlockSpec((1, n, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, kp, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, kp, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, kp), lambda i: (i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, kp), lambda i: (i, 0)),
                pl.BlockSpec((1, kp, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, kp, d), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, lp), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((a, kp), jnp.float32),
                jax.ShapeDtypeStruct((a, kp, d), jnp.float32),
                jax.ShapeDtypeStruct((a, kp, d), jnp.float32),
                jax.ShapeDtypeStruct((a, lp), jnp.float32),
            ],
            interpret=interpret,
        )(x.astype(jnp.float32), msc, ivar, const)
    return ll[:, 0], s[:, :k], sx[:, :k, :], sxx[:, :k, :]


def em_estep_stats(
    x: jax.Array,
    means: jax.Array,
    sigmas: jax.Array,
    priors: jax.Array,
    eps: float = DEFAULT_SIGMA_EPS,
    interpret: bool = False,
    mesh=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused E-step over a class slab.

    Args:
      x:      [A, N, d] per-class memory features (full queues).
      means:  [A, K, d] mixture means.
      sigmas: [A, K, d] mixture stds.
      priors: [A, K] mixture priors.
      mesh:   optional jax.sharding.Mesh with a 'model' axis: the call is
        shard_mapped so each model shard runs the kernel on its local class
        slab (class-sharded EM state; per-class stats need no collective).

    Returns:
      (ll [A] mean log-likelihood — e_step's first output,
       s [A, K], sx [A, K, d], sxx [A, K, d] RAW responsibility statistics).
    """
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from mgproto_tpu.parallel.mesh import MODEL_AXIS, shard_map_compat

        spec = P(MODEL_AXIS)
        return shard_map_compat(
            functools.partial(
                _estep_stats_impl, eps=eps, interpret=interpret
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec, spec),
            out_specs=(spec, spec, spec, spec),
        )(x, means, sigmas, priors)
    return _estep_stats_impl(x, means, sigmas, priors, eps, interpret)
