"""Fused Pallas kernel: BatchNorm apply + residual add + ReLU block epilogue.

The byte-ranked fusion table (obs/stall.py `top_byte_movers`, ISSUE 12) names
the ResNet block epilogue at layer1's 112^2 resolution as the top non-MXU
byte mover of the flagship step: the BN normalize, the shortcut add and the
ReLU each stream the full [B, 112, 112, 64] activation (1.6 GB at batch 256
in bf16) through HBM when XLA materializes the chain — and whether XLA fuses
across the residual junction is a per-program fusion-heuristic outcome, not
a contract. This kernel makes it a contract: given the per-channel
normalization constants, ONE VMEM pass reads x and the shortcut and writes
relu((x - mean) * rsqrt(var + eps) * scale + bias + shortcut) — the byte
floor (2 reads + 1 write) instead of up to 4 reads + 3 writes.

Gradient contract: the backward is the EXACT VJP of the XLA reference
implementation (`epilogue_reference`), obtained by re-running it under
`jax.vjp` at backward time — remat-style recompute of a cheap elementwise
chain, so the fused forward can never diverge from the reference gradients
(including the batch-statistics terms: `mean`/`var` are differentiable
INPUTS here, so the train-mode BN backward through the statistics happens
in the caller's XLA graph exactly as without the kernel). Parity is pinned
in tests/test_fused_epilogue.py (CPU interpret mode, `pallas` marker).

`BNEpilogue` is the flax wrapper the resnet blocks mount when
`ModelConfig.fused_epilogue` resolves on: parameter/stat names mirror
nn.BatchNorm (params `scale`/`bias`; batch_stats `mean`/`var`, flax
momentum/fast-variance semantics) so checkpoints interchange with the
unfused blocks bit-for-bit.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def resolve_fused_epilogue(flag: Optional[bool], arch: str) -> bool:
    """None = auto, like fused_scoring: the Mosaic lowering is TPU-only and
    the kernel is mounted by the resnet block family; every other backend/
    arch keeps the plain XLA path. Explicit True/False always honored
    (tests force ON on CPU, where the kernel runs in interpret mode)."""
    if flag is not None:
        return bool(flag)
    return jax.default_backend() == "tpu" and arch.startswith("resnet")


def epilogue_reference(x, mean, var, scale, bias, residual, eps, compute_dtype):
    """The XLA reference: flax nn.BatchNorm's apply arithmetic (promote to
    the compute dtype, y = (x - mean) * rsqrt(var + eps) * scale + bias) +
    shortcut add + ReLU. The ONE definition of the epilogue's math — the
    fused path's backward is this function's VJP, so the two cannot drift."""
    dt = jnp.dtype(compute_dtype)
    mul = jax.lax.rsqrt(var.astype(dt) + jnp.asarray(eps, dt))
    mul = mul * scale.astype(dt)
    y = (x.astype(dt) - mean.astype(dt)) * mul
    y = y + bias.astype(dt) + residual.astype(dt)
    return jnp.maximum(y, jnp.asarray(0, dt))


# ------------------------------------------------------------------- kernel
def _epilogue_kernel(x_ref, res_ref, a_ref, b_ref, o_ref):
    """One [TILE_M, C] row tile: o = max(x * a + b + res, 0). `a`/`b` are the
    folded per-channel constants (a = scale * rsqrt(var + eps),
    b = bias - mean * a), f32; the multiply-add runs in f32 regardless of
    the wire dtype (never LESS precise than the reference) and the output
    is cast back to the activation dtype."""
    x = x_ref[...].astype(jnp.float32)
    r = res_ref[...].astype(jnp.float32)
    y = x * a_ref[...] + b_ref[...] + r
    o_ref[...] = jnp.maximum(y, 0.0).astype(o_ref.dtype)


_TILE_M = 512


def _pick_row_tile(m: int) -> int:
    """Largest sublane-aligned (multiple-of-8) row tile <= _TILE_M that
    DIVIDES m, or 0 when none exists. An exact divisor means no operand
    padding: a padded tile would cost jnp.pad copies of x and the shortcut
    plus an output slice — whole-tensor HBM round trips on the exact path
    whose purpose is removing them (e.g. layer4 at batch 256: m = 12544
    divides by 448, not 512)."""
    for t in range(min(_TILE_M, m - m % 8), 7, -8):
        if m % t == 0:
            return t
    return 0


def _epilogue_call(x, mean, var, scale, bias, residual, eps, dt, interpret):
    """Flatten [B, H, W, C] -> [M, C], tile rows, one grid pass."""
    shape = x.shape
    c = shape[-1]
    m = x.size // c
    xd = x.reshape(m, c).astype(dt)
    rd = residual.reshape(m, c).astype(dt)
    a = (jax.lax.rsqrt(var.astype(jnp.float32) + jnp.float32(eps))
         * scale.astype(jnp.float32))
    b = bias.astype(jnp.float32) - mean.astype(jnp.float32) * a
    tile = _pick_row_tile(m)
    if tile:
        m_pad = m
    else:  # no aligned divisor (tiny/ragged m): pad, slice back after
        tile = min(_TILE_M, _round_up(m, 8))
        m_pad = _round_up(m, tile)
        xd = jnp.pad(xd, ((0, m_pad - m), (0, 0)))
        rd = jnp.pad(rd, ((0, m_pad - m), (0, 0)))
    out = pl.pallas_call(
        _epilogue_kernel,
        grid=(m_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, c), lambda i: (i, 0)),
            pl.BlockSpec((tile, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m_pad, c), jnp.dtype(dt)),
        interpret=interpret,
    )(xd, rd, a[None, :], b[None, :])
    return out[:m].reshape(shape[:-1] + (c,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _bn_add_relu(x, mean, var, scale, bias, residual, eps, dt, interpret):
    return _epilogue_call(x, mean, var, scale, bias, residual, eps, dt,
                          interpret)


def _bn_add_relu_fwd(x, mean, var, scale, bias, residual, eps, dt, interpret):
    y = _epilogue_call(x, mean, var, scale, bias, residual, eps, dt,
                       interpret)
    return y, (x, mean, var, scale, bias, residual)


def _bn_add_relu_bwd(eps, dt, interpret, saved, g):
    # the exact VJP of the XLA reference: recompute the cheap elementwise
    # forward under jax.vjp (remat-style) so fused and unfused training
    # trajectories share one gradient definition
    _, vjp = jax.vjp(
        lambda *a: epilogue_reference(*a, eps, dt), *saved
    )
    return vjp(g)


_bn_add_relu.defvjp(_bn_add_relu_fwd, _bn_add_relu_bwd)


def fused_bn_epilogue(x, mean, var, scale, bias, residual,
                      eps: float = 1e-5,
                      compute_dtype: Any = None,
                      interpret: Optional[bool] = None):
    """Public entry: fused BN apply + residual add + ReLU.

    Args:
      x:        [B, H, W, C] conv output (any float dtype).
      mean/var: [C] normalization statistics (batch stats in train mode —
                differentiable inputs, so the BN stats backward stays in
                the caller's graph — or running averages in eval mode).
      scale/bias: [C] BN affine params (f32 masters).
      residual: [B, H, W, C] shortcut branch.
      compute_dtype: output/accumulate wire dtype (None = x.dtype).
      interpret: None = auto (Mosaic on TPU, interpreter elsewhere).
    """
    dt = jnp.dtype(compute_dtype) if compute_dtype is not None else x.dtype
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _bn_add_relu(x, mean, var, scale, bias, residual,
                        float(eps), str(jnp.dtype(dt)), bool(interpret))


# ------------------------------------------------------------- flax wrapper
class BNEpilogue(nn.Module):
    """BatchNorm + residual add + ReLU with the elementwise tail fused.

    Parameter/stat layout mirrors nn.BatchNorm exactly (params:
    `scale`, `bias`; batch_stats: `mean`, `var`; f32 masters; flax
    fast-variance batch statistics and momentum running-average update), so
    a checkpoint written by the unfused blocks restores here unchanged —
    the module NAME at the mount point ("bn2"/"bn3") is the same either
    way. The fused kernel only replaces the elementwise apply."""

    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None  # compute dtype (None = input dtype), like nn.BatchNorm

    @nn.compact
    def __call__(self, x, residual, use_running_average: bool):
        c = x.shape[-1]
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((c,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((c,), jnp.float32)
        )
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            # flax _compute_stats semantics: f32 statistics regardless of
            # the compute dtype, fast variance max(E[x^2] - E[x]^2, 0)
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=(0, 1, 2))
            mean2 = jnp.mean(jnp.square(xf), axis=(0, 1, 2))
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value
                    + (1.0 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value
                    + (1.0 - self.momentum) * var
                )
        return fused_bn_epilogue(
            x, mean, var, scale, bias, residual,
            eps=self.epsilon, compute_dtype=self.dtype or x.dtype,
        )
