"""Device-side corruption ladder: seeded, jitted distribution-shift probes.

The trust verification plane (mgproto_tpu/trust/) proves GRACEFUL
DEGRADATION: as inputs drift off-manifold, the calibrated serving path must
abstain more and stay accurate on what it still answers. That claim needs a
controllable shift axis, so this module implements the common-corruption
families (the ImageNet-C recipe: noise / blur / contrast / pixelate) as
pure jitted device functions at five severities, beside `ops/augment.py`
whose per-sample threefry seeding discipline it reuses. Device-side for the
same reason the augmentation tail is: the corruption runs where the serving
batch already lives, one fused program per (kind, severity), and the host
never materializes a second float copy of the ladder.

Domain: the corruptions operate on the NORMALIZED float32 images the
serving path accepts (`serving/validate.py` — mean/std normalized, roughly
unit-scale). Severity tables are therefore stated in normalized units, not
u8 steps; `SEVERITIES` spans "barely perceptible" (1) to "heavily degraded
but class-bearing" (5). Every corruption is deterministic given (kind,
severity, per-sample seeds): noise draws from raw-threefry keys exactly
like `augment_tail`, the other families are parameter-deterministic.

Shapes are static per (kind, severity): `make_corrupt_fn` returns one
jitted callable per cell, so a 4-kind x 5-severity matrix compiles exactly
20 tiny programs once and the SERVING program underneath recompiles zero
times (asserted by the trust matrix via the engine's StepMonitor).
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

CORRUPTION_KINDS: Tuple[str, ...] = ("noise", "blur", "contrast", "pixelate")
SEVERITIES: Tuple[int, ...] = (1, 2, 3, 4, 5)

# severity -> parameter, index 0 unused so tables read naturally at [s]
_NOISE_STD = (None, 0.12, 0.25, 0.45, 0.70, 1.00)  # additive gaussian std
_BLUR_SIGMA = (None, 0.6, 1.0, 1.6, 2.4, 3.5)  # gaussian blur std (px)
_CONTRAST_F = (None, 0.70, 0.55, 0.40, 0.25, 0.12)  # contrast retain factor
_PIXELATE_F = (None, 2, 3, 4, 6, 8)  # pixelation block factor

# distinguishes corruption key data from augment's ("mg_c" vs "mg_a")
_KEY_TAG = np.uint32(0x6D675F63)


def _per_sample_keys(seeds: jax.Array) -> jax.Array:
    """[B] uint32 loader-style seeds -> [B, 2] raw threefry key data (the
    `ops/augment.py` convention: seeds are already splitmix64-mixed, the
    tag only separates this consumer's stream)."""
    return jnp.stack(
        [jnp.full_like(seeds, _KEY_TAG), seeds], axis=-1
    ).astype(jnp.uint32)


def _noise(x: jax.Array, seeds: jax.Array, severity: int) -> jax.Array:
    std = _NOISE_STD[severity]

    def one(img, key):
        # raw [2]-uint32 key data consumed directly, the augment_tail way
        return img + std * jax.random.normal(key, img.shape, img.dtype)

    return jax.vmap(one)(x, _per_sample_keys(seeds))


def _gauss_kernel(sigma: float) -> np.ndarray:
    """Odd-width 1D gaussian, radius 3*sigma (host-side constant: the
    kernel is static per severity, baked into the program)."""
    radius = max(1, int(np.ceil(3.0 * sigma)))
    xs = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-0.5 * (xs / sigma) ** 2)
    return (k / k.sum()).astype(np.float32)


def _blur(x: jax.Array, seeds: jax.Array, severity: int) -> jax.Array:
    """Separable gaussian blur with edge-replicate padding (a zero pad
    would darken borders in the normalized domain and read as a contrast
    shift, contaminating the ladder's axes)."""
    del seeds  # deterministic family
    k = jnp.asarray(_gauss_kernel(_BLUR_SIGMA[severity]))
    r = (k.shape[0] - 1) // 2

    def conv_axis(img, axis):
        pad = [(0, 0)] * img.ndim
        pad[axis] = (r, r)
        padded = jnp.pad(img, pad, mode="edge")
        # [B, H, W, C] conv along `axis` via moveaxis + dot with the kernel
        windows = jnp.stack(
            [
                jax.lax.slice_in_dim(padded, i, i + img.shape[axis], axis=axis)
                for i in range(2 * r + 1)
            ],
            axis=0,
        )
        return jnp.tensordot(k, windows, axes=(0, 0))

    return conv_axis(conv_axis(x, 1), 2)


def _contrast(x: jax.Array, seeds: jax.Array, severity: int) -> jax.Array:
    del seeds  # deterministic family
    f = _CONTRAST_F[severity]
    mean = jnp.mean(x, axis=(1, 2, 3), keepdims=True)
    return mean + f * (x - mean)


def _pixelate(x: jax.Array, seeds: jax.Array, severity: int) -> jax.Array:
    """Downsample by the block factor (area average) then nearest-upsample
    back — jax.image keeps it shape-polymorphic over non-divisible sizes."""
    del seeds  # deterministic family
    f = _PIXELATE_F[severity]
    b, h, w, c = x.shape
    small = (b, max(1, h // f), max(1, w // f), c)
    down = jax.image.resize(x, small, method="linear")
    return jax.image.resize(down, (b, h, w, c), method="nearest")


_FAMILIES: Dict[str, Callable] = {
    "noise": _noise,
    "blur": _blur,
    "contrast": _contrast,
    "pixelate": _pixelate,
}


def make_corrupt_fn(kind: str, severity: int) -> Callable:
    """One jitted `(images [B,H,W,3] f32, seeds [B] uint32) -> images`
    program for a ladder cell. kind/severity are static (baked into the
    program); batch shape follows the caller's bucketing."""
    if kind not in _FAMILIES:
        raise ValueError(
            f"unknown corruption kind {kind!r}; options: {CORRUPTION_KINDS}"
        )
    if severity not in SEVERITIES:
        raise ValueError(
            f"severity must be in {SEVERITIES}, got {severity}"
        )
    family = _FAMILIES[kind]

    def fn(images: jax.Array, seeds: jax.Array) -> jax.Array:
        return family(images.astype(jnp.float32), seeds, severity)

    return jax.jit(fn)


def per_sample_seeds(seed: int, count: int, offset: int = 0) -> np.ndarray:
    """The ONE per-sample uint32 seed recipe of the corruption ladder:
    Knuth-hash the run seed, offset by global row index. Shared by
    `corrupt_numpy` and the trust matrix's chunked driver
    (trust/matrix.py) — the committed drill's byte-identical
    reproducibility depends on there being exactly one copy of this."""
    mixed = (int(seed) * 2654435761) & 0xFFFFFFFF  # knuth hash, mod 2^32
    return np.uint32(mixed) + np.arange(
        offset, offset + count, dtype=np.uint32
    )


def corrupt_numpy(
    images: np.ndarray, kind: str, severity: int, seed: int = 0
) -> np.ndarray:
    """Convenience host wrapper: derives per-sample uint32 seeds from
    (seed, row index) and returns a host array. The trust matrix uses the
    jitted `make_corrupt_fn` directly (one program per cell, reused across
    batches); this wrapper exists for scripts and tests."""
    seeds = per_sample_seeds(seed, images.shape[0])
    fn = make_corrupt_fn(kind, severity)
    return np.asarray(fn(jnp.asarray(images, jnp.float32), jnp.asarray(seeds)))
