"""Fused Pallas kernel: Gaussian prototype scoring + top-T spatial pool.

The hot op of MGProto (reference model.py:256-275 `compute_log_prob` +
model.py:188-206 `global_max_pooling_gmm_topT`) evaluated the naive way
materializes a [B*H*W, P] density matrix in HBM (~500 MB at the flagship
R34-CUB shapes: 80*28*28 patches x 2000 prototypes, f32) only for top-T to
immediately reduce it over the spatial axis. This kernel keeps each
[HW, P_tile] density tile in VMEM: two MXU matmuls produce the tile, an
unrolled T-pass max/argmax reduction pools it, and only [B, T, P] values +
indices (~13 MB) ever reach HBM.

Gradient contract: prototypes are CONSTANTS here — the reference detaches
means/covs inside compute_log_prob (model.py:264-265), so the classification
loss trains features only (means train via EM on the memory bank, which calls
ops/gaussian.py directly and never goes through this kernel). The custom VJP
therefore returns a gradient for the feature map alone, rebuilding the sparse
[HW, P] selection weights tile-by-tile from the saved indices (20 compare+add
passes) and turning them into two [HW,P_tile]x[P_tile,d] MXU matmuls:

    d logN / dx = (mu - x) / sigma^2   at each selected patch
    grad_x = w @ (mu * s) - x * (w @ s),   s = 1/sigma^2,
    w[n, p] = sum_t g[p, t] * [idx[p, t] == n]

Math identical to ops/gaussian.py's quadratic expansion; f32 throughout with
HIGHEST matmul precision (OoD p(x) thresholds ride on the density scale,
SURVEY.md §7.3.5).
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mgproto_tpu.ops.gaussian import DEFAULT_SIGMA_EPS, precompute_diag_gaussian

_NEG_INF = float("-inf")


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# --------------------------------------------------------------------- forward
def _fwd_kernel(feat_ref, msc_ref, ivar_ref, const_ref, vals_ref, idx_ref, *, t_levels):
    """One (batch b, prototype tile j) grid cell.

    feat_ref:  [1, HW, d]   L2-normalized patch features of sample b.
    msc_ref:   [TP, d]      mu * s for this prototype tile (s = 1/sigma^2).
    ivar_ref:  [TP, d]      s.
    const_ref: [1, TP]      -d/2 log(2pi) - sum log sigma - 1/2 mu.s.mu.
    vals_ref:  [1, Tpad, TP] out: top-T log-densities (sorted desc).
    idx_ref:   [1, Tpad, TP] out: flat spatial index of each.
    """
    feat = feat_ref[0]  # [HW, d]
    hw = feat.shape[0]
    # logN[n, p] = const_p + x.(mu*s) - 0.5 * (x*x).s
    cross = jax.lax.dot_general(
        feat, msc_ref[...],
        (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # [HW, TP]
    xquad = jax.lax.dot_general(
        feat * feat, ivar_ref[...],
        (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # [HW, TP]
    dens = const_ref[0][None, :] + cross - 0.5 * xquad  # [HW, TP]

    row = jax.lax.broadcasted_iota(jnp.int32, dens.shape, 0)  # [HW, TP]
    for t in range(t_levels):
        mx = jnp.max(dens, axis=0)  # [TP]
        am = jnp.argmax(dens, axis=0).astype(jnp.int32)  # [TP] first-of-ties,
        # matching lax.top_k's lowest-index tie-break in the unfused path
        vals_ref[0, t, :] = mx
        idx_ref[0, t, :] = am
        dens = jnp.where(row == am[None, :], _NEG_INF, dens)
    for t in range(t_levels, vals_ref.shape[1]):  # Tpad tail: inert filler
        vals_ref[0, t, :] = jnp.full(dens.shape[1:], _NEG_INF, jnp.float32)
        idx_ref[0, t, :] = jnp.zeros(dens.shape[1:], jnp.int32)


# -------------------------------------------------------------------- backward
def _bwd_kernel(
    g_ref, idx_ref, feat_ref, msc_ref, ivar_ref, out_ref, acc_m, acc_s, *, t_levels
):
    """Accumulates grad_feat for sample b across prototype tiles j (the minor,
    sequential grid axis): scratch accumulators persist over j and the output
    block (mapped by b only) is written once at the last tile."""
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc_m[...] = jnp.zeros_like(acc_m)
        acc_s[...] = jnp.zeros_like(acc_s)

    hw = feat_ref.shape[1]
    tp = msc_ref.shape[0]
    row = jax.lax.broadcasted_iota(jnp.int32, (hw, tp), 0)
    w = jnp.zeros((hw, tp), jnp.float32)
    for t in range(t_levels):
        w = w + jnp.where(
            row == idx_ref[0, t, :][None, :], g_ref[0, t, :][None, :], 0.0
        )
    acc_m[...] += jax.lax.dot_general(
        w, msc_ref[...],
        (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    acc_s[...] += jax.lax.dot_general(
        w, ivar_ref[...],
        (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nj - 1)
    def _finalize():
        out_ref[0] = acc_m[...] - feat_ref[0] * acc_s[...]


# ------------------------------------------------------------------ public API
def _prepare(means, sigmas, eps, p_pad):
    """Precompute (mu*s, s, const) via the SAME helper as the unfused path
    (ops/gaussian.py precompute_diag_gaussian — single source of the density
    numerics), then pad P. Padded slots get s=0, const=-inf: their densities
    are -inf so they can never enter a top-T, and they contribute exactly 0 to
    the backward matmuls."""
    m_scaled, inv_var, const = precompute_diag_gaussian(means, sigmas, eps)
    pad = p_pad - m_scaled.shape[0]
    msc = jnp.pad(m_scaled, ((0, pad), (0, 0)))
    ivar = jnp.pad(inv_var, ((0, pad), (0, 0)))
    const = jnp.pad(const, (0, pad), constant_values=_NEG_INF)
    return msc, ivar, const[None, :]


def _pick_tile(p_pad: int) -> int:
    for tile in (512, 256, 128):
        if p_pad % tile == 0:
            return tile
    return p_pad


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5)
)
def score_pool(
    feat: jax.Array,
    means: jax.Array,
    sigmas: jax.Array,
    t_levels: int,
    eps: float = DEFAULT_SIGMA_EPS,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Fused density + top-T pool.

    Args:
      feat:   [B, HW, d] f32 patch features (already L2-normalized).
      means:  [..., d] prototype means (leading shape flattens to P).
      sigmas: [..., d] prototype stds.
      t_levels: T mining levels.
    Returns:
      (vals [B, P, T] f32 top-T log-densities sorted desc,
       idx  [B, P, T] int32 flat spatial indices). Gradients flow to `feat`
      only (prototypes are EM-trained constants here, model.py:264-265).
    """
    vals, idx = _score_pool_fwd_impl(feat, means, sigmas, t_levels, eps, interpret)
    return vals, idx


def _score_pool_fwd_impl(feat, means, sigmas, t_levels, eps, interpret):
    b, hw, d = feat.shape
    p = means.size // d
    p_pad = _round_up(p, 128)
    t_pad = _round_up(t_levels, 8)
    tile = _pick_tile(p_pad)
    msc, ivar, const = _prepare(means, sigmas, eps, p_pad)
    feat = feat.astype(jnp.float32)

    grid = (b, p_pad // tile)
    vals, idx = pl.pallas_call(
        functools.partial(_fwd_kernel, t_levels=t_levels),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, hw, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((tile, d), lambda i, j: (j, 0)),
            pl.BlockSpec((tile, d), lambda i, j: (j, 0)),
            pl.BlockSpec((1, tile), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, t_pad, tile), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, t_pad, tile), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, t_pad, p_pad), jnp.float32),
            jax.ShapeDtypeStruct((b, t_pad, p_pad), jnp.int32),
        ],
        interpret=interpret,
    )(feat, msc, ivar, const)
    # [B, Tpad, Ppad] -> [B, P, T]
    vals = jnp.swapaxes(vals[:, :t_levels, :p], 1, 2)
    idx = jnp.swapaxes(idx[:, :t_levels, :p], 1, 2)
    return vals, idx


def _score_pool_fwd(feat, means, sigmas, t_levels, eps, interpret):
    vals, idx = _score_pool_fwd_impl(feat, means, sigmas, t_levels, eps, interpret)
    return (vals, idx), (feat, means, sigmas, idx)


def _score_pool_bwd(t_levels, eps, interpret, res, cts):
    feat, means, sigmas, idx = res
    g_vals, _ = cts  # idx output is integer: no cotangent
    b, hw, d = feat.shape
    p = means.size // d
    p_pad = _round_up(p, 128)
    t_pad = _round_up(t_levels, 8)
    tile = _pick_tile(p_pad)
    msc, ivar, _ = _prepare(means, sigmas, eps, p_pad)
    feat32 = feat.astype(jnp.float32)

    # [B, P, T] -> [B, Tpad, Ppad]; padded g is 0 so padded slots are inert
    g = jnp.swapaxes(g_vals.astype(jnp.float32), 1, 2)
    g = jnp.pad(g, ((0, 0), (0, t_pad - t_levels), (0, p_pad - p)))
    ix = jnp.swapaxes(idx, 1, 2)
    ix = jnp.pad(ix, ((0, 0), (0, t_pad - t_levels), (0, p_pad - p)),
                 constant_values=-1)

    grid = (b, p_pad // tile)
    grad_feat = pl.pallas_call(
        functools.partial(_bwd_kernel, t_levels=t_levels),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, t_pad, tile), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, t_pad, tile), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, hw, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((tile, d), lambda i, j: (j, 0)),
            pl.BlockSpec((tile, d), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, hw, d), lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hw, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((hw, d), jnp.float32),
            pltpu.VMEM((hw, d), jnp.float32),
        ],
        interpret=interpret,
    )(g, ix, feat32, msc, ivar)
    return (
        grad_feat.astype(feat.dtype),
        jnp.zeros_like(means),
        jnp.zeros_like(sigmas),
    )


score_pool.defvjp(_score_pool_fwd, _score_pool_bwd)
