"""Device-side augmentation tail: flip + color jitter + normalize in-step.

The host input pipeline ships every 224² train sample as a ~602 KB float32
array — pickled across the worker boundary, copied again into the batch,
and pushed over PCIe — when the information content is a 150 KB uint8
image. This module is the device half of the uint8 wire format (ISSUE 5,
the tf.data/DALI split named in PAPERS.md): the host keeps samples uint8
through decode → geometry → IPC → H2D, and the cheap per-pixel tail —
horizontal flip, brightness/contrast/saturation/hue jitter, u8→f32
normalize — runs HERE, inside the jitted train step, where XLA fuses it
into the trunk's first conv. Geometry (perspective/affine/resized-crop)
stays host-side on PIL (data/transforms.py
TrainTransform(device_augment=True)).

Determinism: every sample carries a uint32 seed derived by the loader from
the SAME (seed, epoch, index) identity that seeds the host RNG streams
(data/loader.py `augment_seeds`), so a batch's augmentation is reproducible
regardless of worker scheduling, backend, or sharding — the per-sample
draws are pure functions of the seed.

Parity vs the host path (documented tolerance, pinned in
tests/test_augment.py): each jitter op mirrors PIL's semantics in f32 —
brightness `f·x`, contrast `deg + f·(x-deg)` with `deg` the rounded mean
of the PIL luma, saturation `luma + f·(x-luma)`, hue the RGB→HSV→RGB
round trip with the same uint8-quantized shift — but WITHOUT the uint8
truncation PIL performs between chained ops, and in the fixed order
brightness → contrast → saturation → hue rather than a random
permutation. Each op therefore agrees with its host counterpart to a few
u8 steps at equal factors; the factor distributions are identical, the
draws come from a different (device threefry vs host PCG64) stream.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mgproto_tpu.utils.images import IMAGENET_MEAN, IMAGENET_STD

# ColorJitter ranges of the reference train stack (main.py:100)
BRIGHTNESS: Tuple[float, float] = (0.6, 1.4)
CONTRAST: Tuple[float, float] = (0.6, 1.4)
SATURATION: Tuple[float, float] = (0.6, 1.4)
HUE: Tuple[float, float] = (-0.02, 0.02)
FLIP_P: float = 0.5

# distinguishes the raw key data built from a loader seed from an actual
# threefry hash (the seeds are already splitmix64-mixed by the loader)
_KEY_TAG = np.uint32(0x6D675F61)  # "mg_a"


def resolve_device_augment(flag: Optional[bool]) -> bool:
    """None = auto: ON for TPU backends (where the u8 wire + fused tail
    measured wins live), OFF elsewhere. True/False force the path."""
    if flag is not None:
        return bool(flag)
    return jax.default_backend() == "tpu"


def _luma(x: jax.Array) -> jax.Array:
    """PIL convert("L") luminance in float: (19595 R + 38470 G + 7471 B)
    / 65536 — same integer coefficients, no final rounding (≤1 u8 step)."""
    return (
        19595.0 * x[..., 0] + 38470.0 * x[..., 1] + 7471.0 * x[..., 2]
    ) / 65536.0


def adjust_brightness(x: jax.Array, factor: jax.Array) -> jax.Array:
    """PIL ImageEnhance.Brightness in f32: blend toward black."""
    return jnp.clip(factor * x, 0.0, 255.0)


def adjust_contrast(x: jax.Array, factor: jax.Array) -> jax.Array:
    """PIL ImageEnhance.Contrast in f32: blend toward the rounded mean
    luma. `x` is [..., H, W, 3]; the mean is per image."""
    deg = jnp.round(jnp.mean(_luma(x), axis=(-2, -1), keepdims=True))
    deg = deg[..., None]  # broadcast over channels
    return jnp.clip(deg + factor * (x - deg), 0.0, 255.0)


def adjust_saturation(x: jax.Array, factor: jax.Array) -> jax.Array:
    """PIL ImageEnhance.Color in f32: blend toward per-pixel luma."""
    lum = _luma(x)[..., None]
    return jnp.clip(lum + factor * (x - lum), 0.0, 255.0)


def adjust_hue(x: jax.Array, factor: jax.Array) -> jax.Array:
    """Hue shift by `factor` turns: the RGB→HSV→(H+shift)→RGB round trip
    in continuous f32. The shift is quantized to the same uint8 step the
    host path uses (trunc(f·255) mod 256), so device and host agree on the
    shift itself; the host additionally quantizes H/S to uint8 mid-trip,
    which this path doesn't — the residual is a few u8 steps on saturated
    pixels (the documented tolerance). This was the profiled hot spot of
    the whole host jitter stack at flagship sizes (~6.5 ms/sample at
    500×375 even native); here it is a handful of fused elementwise ops."""
    shift = jnp.mod(jnp.trunc(factor * 255.0), 256.0) / 255.0
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    c = mx - mn
    safe_c = jnp.where(c == 0, 1.0, c)
    h6 = jnp.where(
        mx == r, jnp.mod((g - b) / safe_c, 6.0),
        jnp.where(mx == g, (b - r) / safe_c + 2.0, (r - g) / safe_c + 4.0),
    )
    h = jnp.where(c == 0, 0.0, h6 / 6.0)
    h = jnp.mod(h + shift, 1.0)
    s = jnp.where(mx == 0, 0.0, c / jnp.where(mx == 0, 1.0, mx))
    h6 = h * 6.0
    i = jnp.floor(h6)
    f = h6 - i
    p = mx * (1.0 - s)
    q = mx * (1.0 - s * f)
    t = mx * (1.0 - s * (1.0 - f))
    i = i.astype(jnp.int32) % 6
    out = jnp.stack(
        [
            jnp.select([i == k for k in range(6)], [mx, q, p, p, t, mx]),
            jnp.select([i == k for k in range(6)], [t, mx, mx, q, p, p]),
            jnp.select([i == k for k in range(6)], [p, p, t, mx, mx, q]),
        ],
        axis=-1,
    )
    return jnp.where((c == 0)[..., None], x, out)


def normalize_u8(x: jax.Array, mean=IMAGENET_MEAN, std=IMAGENET_STD) -> jax.Array:
    """u8-domain values (0..255, any float/int dtype) -> normalized f32,
    in the same scale/bias form as the host's native u8_to_f32_norm pass
    (x·1/(255σ) − μ/σ), so unaugmented pixels agree to f32 rounding."""
    scale = jnp.asarray(1.0 / (255.0 * np.asarray(std, np.float32)), jnp.float32)
    bias = jnp.asarray(
        -np.asarray(mean, np.float32) / np.asarray(std, np.float32), jnp.float32
    )
    return x.astype(jnp.float32) * scale + bias


def _keys_from_seeds(seeds: jax.Array) -> jax.Array:
    """[B] uint32 loader seeds -> [B, 2] raw threefry key data. The seeds
    are already splitmix64-mixed host-side, so they are used as key words
    directly (no second hash)."""
    seeds = seeds.astype(jnp.uint32)
    return jnp.stack([jnp.full_like(seeds, _KEY_TAG), seeds], axis=-1)


def augment_tail(
    images: jax.Array,
    seeds: jax.Array,
    brightness: Tuple[float, float] = BRIGHTNESS,
    contrast: Tuple[float, float] = CONTRAST,
    saturation: Tuple[float, float] = SATURATION,
    hue: Tuple[float, float] = HUE,
    flip_p: float = FLIP_P,
    mean=IMAGENET_MEAN,
    std=IMAGENET_STD,
) -> jax.Array:
    """[B, H, W, 3] uint8 wire batch + [B] uint32 seeds -> augmented,
    normalized f32 batch. Pure; traced into the train step (every op is a
    vectorized elementwise pass — XLA fuses the whole tail into the first
    conv's input read, so it costs HBM bandwidth, not a kernel launch)."""
    x = images.astype(jnp.float32)  # u8 wire (accepts f32 chaos batches)
    keys = _keys_from_seeds(seeds)
    sub = jax.vmap(lambda k: jax.random.split(k, 5))(keys)  # [B, 5, 2]

    def draw(col: int, lo: float, hi: float) -> jax.Array:
        return jax.vmap(
            lambda k: jax.random.uniform(k, (), jnp.float32, lo, hi)
        )(sub[:, col])[:, None, None, None]

    x = adjust_brightness(x, draw(1, *brightness))
    x = adjust_contrast(x, draw(2, *contrast))
    x = adjust_saturation(x, draw(3, *saturation))
    x = adjust_hue(x, draw(4, *hue)[..., 0])  # [B,1,1] broadcast over HW
    flip = jax.vmap(lambda k: jax.random.bernoulli(k, flip_p))(sub[:, 0])
    x = jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)
    return normalize_u8(x, mean, std)
