"""Diagonal-Gaussian prototype scoring — the hot op of MGProto.

Reference semantics: /root/reference/model.py:256-275 (`compute_log_prob`) and
model.py:323-336 (`_estimate_log_prob`): for features x in R^d and per-prototype
(mean mu, std sigma),

    log N(x; mu, sigma) = -d/2 log(2 pi) - sum_d log sigma_d
                          - 1/2 sum_d ((x_d - mu_d) / sigma_d)^2

The reference evaluates this with python-blocked broadcast/pow loops
(model.py:263-274, n_block=4) to bound GPU memory. TPU-native design: expand
the quadratic so the cross term is ONE [N, d] x [d, P] matmul on the MXU and
the rest are rank-1 broadcasts — no blocking, no python loops; XLA fuses the
elementwise epilogue. Density math stays in float32 regardless of the model's
compute dtype (OoD p(x) thresholds depend on its scale, SURVEY.md §7.3.5) —
this is the `score_dtype` leg of the mixed-precision policy
(perf/precision.py): the explicit f32 casts below are what lets the TRUNK
run bf16 while every p(x) a calibration ever thresholds stays on one scale.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

_LOG_2PI = math.log(2.0 * math.pi)

# sigma regularizer shared by every density evaluation (reference model.py:272
# uses sigma + 0 in compute_log_prob and sigma + 1e-10 in the EM path; both are
# the identity at f32 for sigma ~ 0.4). The fused Pallas kernel
# (ops/fused_scoring.py) uses the same precompute so the paths cannot desync.
DEFAULT_SIGMA_EPS = 1e-10


def precompute_diag_gaussian(means: jax.Array, sigmas: jax.Array, eps: float):
    """Shared precompute for the quadratic expansion.

    Flattens [..., d] prototypes to [P, d] and returns
      (m_scaled [P, d] = mu / sigma^2,
       inv_var  [P, d] = 1 / sigma^2,
       const    [P]    = -d/2 log(2pi) - sum log sigma - 1/2 mu.(mu/sigma^2))
    so that  log N(x) = const + x @ m_scaled.T - 1/2 (x*x) @ inv_var.T.
    """
    d = means.shape[-1]
    m = means.astype(jnp.float32).reshape(-1, d)
    s = (sigmas.astype(jnp.float32) + eps).reshape(-1, d)
    inv_var = 1.0 / (s * s)
    m_scaled = m * inv_var
    const = (
        -0.5 * d * _LOG_2PI
        - jnp.sum(jnp.log(s), axis=-1)
        - 0.5 * jnp.sum(m * m_scaled, axis=-1)
    )
    return m_scaled, inv_var, const


def diag_gaussian_log_prob(
    x: jax.Array,
    means: jax.Array,
    sigmas: jax.Array,
    eps: float = DEFAULT_SIGMA_EPS,
) -> jax.Array:
    """Per-sample log-density under every diagonal Gaussian prototype.

    Args:
      x:      [N, d] feature vectors.
      means:  [..., d] prototype means (any leading shape, e.g. [C, K]).
      sigmas: [..., d] prototype stds (same leading shape as means).
      eps:    added to sigma before dividing (reference model.py:272 uses
              sigma + 0 in compute_log_prob and sigma + 1e-10 in the EM path;
              both are the identity at f32 for sigma ~ 0.4).

    Returns:
      [N, *leading] log-densities in float32.

    Quadratic expansion: with s = 1/sigma^2,
      sum_d ((x-mu)/sigma)^2 = (x*x) @ s - 2 * x @ (mu*s) + sum_d mu^2 s
    The middle term is the MXU matmul; everything else is O(N) or O(P).
    """
    x = x.astype(jnp.float32)
    lead = means.shape[:-1]
    m_scaled, inv_var, const = precompute_diag_gaussian(means, sigmas, eps)

    # Precision.HIGHEST: keep the MXU passes at full f32 — default TPU matmul
    # precision truncates inputs to bf16, and the quadratic expansion is
    # cancellation-prone; OoD p(x) thresholds ride on this scale.
    x_quad = jnp.matmul(
        x * x, inv_var.T, precision=jax.lax.Precision.HIGHEST
    )  # [N, P]
    cross = jnp.matmul(
        x, m_scaled.T, precision=jax.lax.Precision.HIGHEST
    )  # [N, P]  <- MXU
    out = const[None, :] + cross - 0.5 * x_quad
    return out.reshape(x.shape[0], *lead)


def mixture_log_likelihood(
    log_prob: jax.Array, log_priors: jax.Array
) -> jax.Array:
    """log p(x|c) = logsumexp_k [ log pi_{c,k} + log N(x; mu_{c,k}) ].

    Log-domain equivalent of the reference's priors-as-weights NonNegLinear
    over exponentiated densities (model.py:222 + model.py:54-74): because the
    last-layer row for class c holds exactly pi_c on class-c prototypes and 0
    elsewhere, the linear layer IS a per-class mixture sum; we never build the
    [P, C] masked weight matrix.

    Args:
      log_prob:   [..., C, K] per-component log-densities.
      log_priors: [C, K] log mixture priors (may be -inf for pruned slots).
    Returns:
      [..., C] class log-likelihoods.
    """
    return jax.nn.logsumexp(log_prob + log_priors, axis=-1)


def e_step(
    x: jax.Array,
    means: jax.Array,
    sigmas: jax.Array,
    priors: jax.Array,
    eps: float = 1e-10,
):
    """EM E-step for one class mixture (reference model.py:303-321).

    Args:
      x:      [N, d] memory features of the class.
      means:  [K, d], sigmas: [K, d], priors: [K].
    Returns:
      (mean log-likelihood scalar, log-responsibilities [N, K])
    """
    weighted = diag_gaussian_log_prob(x, means, sigmas) + jnp.log(priors + eps)
    log_norm = jax.nn.logsumexp(weighted, axis=-1, keepdims=True)  # [N, 1]
    log_resp = weighted - log_norm
    return jnp.mean(log_norm), log_resp


def pairwise_sq_dists(a: jax.Array, b: jax.Array) -> jax.Array:
    """[N, d] x [M, d] -> [N, M] squared euclidean distances
    (reference utils/helpers.py:13-14 `list_of_distances`)."""
    return jnp.sum((a[:, None, :] - b[None, :, :]) ** 2, axis=-1)


def momentum_update(old: jax.Array, new: jax.Array, momentum: float) -> jax.Array:
    """EMA update (reference model.py:44-50)."""
    return momentum * old + (1.0 - momentum) * new
