from mgproto_tpu.ops.gaussian import (
    diag_gaussian_log_prob,
    mixture_log_likelihood,
    e_step,
)
from mgproto_tpu.ops.em_kernels import em_estep_stats
from mgproto_tpu.ops.pooling import top_t_pool, mine_mask_activations
from mgproto_tpu.ops import receptive_field

__all__ = [
    "diag_gaussian_log_prob",
    "mixture_log_likelihood",
    "e_step",
    "em_estep_stats",
    "top_t_pool",
    "mine_mask_activations",
    "receptive_field",
]
