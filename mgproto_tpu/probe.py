"""Relay/backend health probe (VERDICT r3 items 1-2).

`probe_once` answers one question cheaply: *can a fresh python process bring
up the default jax backend and run a tiny jitted matmul right now?* It exists
because this environment's TPU is reached through a relay that, when wedged,
HANGS backend init inside native PJRT code (rounds 1-3: every bench attempt
died this way after burning its full timeout). A 60-90s child probe is ~10x
cheaper than discovering the same hang with a 420-900s flagship bench attempt.

The probe runs in a CHILD process on purpose: SIGALRM cannot interrupt a
native call blocked on a wedged relay (python signal handlers only fire at
bytecode boundaries), and a half-initialized backend poisons every later
in-process jax use. A subprocess gives a hard kill and leaks nothing into the
caller.

This module is import-light (stdlib only, no jax) so `bench.py` and
`scripts/tpu_probe.py` can load it without touching any backend.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

# Child: backend init + one 256x256 bf16 matmul under jit + a host readback.
# First TPU compile is slow (~20-40s observed), so timeouts must comfortably
# exceed that; a relay HANG blows far past it, which is what the kill detects.
_CHILD_SRC = r"""
import json, time
t0 = time.time()
import jax, jax.numpy as jnp
t_import = time.time() - t0
x = jnp.ones((256, 256), jnp.bfloat16)
# f32 cast before the reduction: a bf16-accumulated sum of 2^16 terms rounds,
# which would flag a healthy backend as broken
v = float(jax.jit(lambda a: (a @ a).astype(jnp.float32).sum())(x))
expected = 256.0 ** 3  # ones @ ones: every entry 256, summed over 256*256
d = jax.devices()[0]
print(json.dumps({
    "device_kind": d.device_kind,
    "platform": d.platform,
    "n_devices": len(jax.devices()),
    "import_s": round(t_import, 2),
    "value_ok": abs(v - expected) / expected < 1e-2,
}))
"""


def probe_once(timeout_s: float = 75.0) -> dict:
    """Run one child probe; never raises.

    Returns a record with at least {ts, ok, elapsed_s}; on success also
    {device_kind, platform, n_devices, import_s}; on failure {error}.
    The child inherits this process's environment, so whatever platform the
    caller would get (axon TPU in production, pinned CPU under the test
    suite) is exactly what is probed.
    """
    t0 = time.monotonic()
    record: dict = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "ok": False,
    }
    try:
        proc = subprocess.run(
            [sys.executable, "-u", "-c", _CHILD_SRC],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        record["elapsed_s"] = round(time.monotonic() - t0, 2)
        if proc.returncode == 0 and proc.stdout.strip():
            child = json.loads(proc.stdout.strip().splitlines()[-1])
            record.update(child)
            record["ok"] = bool(child.get("value_ok"))
        else:
            tail = (proc.stderr or proc.stdout or "").strip()[-400:]
            record["error"] = f"child rc={proc.returncode}: {tail}"
    except subprocess.TimeoutExpired:
        record["elapsed_s"] = round(time.monotonic() - t0, 2)
        record["error"] = (
            f"timeout: backend init + tiny jit did not finish in "
            f"{timeout_s:.0f}s (relay hang)"
        )
    except Exception as e:  # defensive: the record must always come back
        record["elapsed_s"] = round(time.monotonic() - t0, 2)
        record["error"] = f"{type(e).__name__}: {e}"
    return record
