"""Background consolidation: captured traffic -> memory banks -> compact EM.

The learning half of the online plane. On a poll-driven cadence (injectable
clock, no blocking sleeps — the serving-plane discipline, enforced by
check_no_blocking_sleep), staged samples from the trusted capture are
drained and pushed through ONE jitted program:

    images --(the trainer's own eval-mode forward)--> add-on feature map
           --(head_forward with the staged labels)--> enqueue candidates
           --(core/memory.memory_push)-------------> per-class banks
           --(core/em.em_update, compact dirty-class width = the
              consolidation batch)------------------> candidate GMM

This is deliberately the TRAINING enqueue semantics (top-1 patch features
of the labeled class, spatially deduped) and the PR-4 compact-EM machinery:
a consolidation batch of W samples dirties at most W classes, so the
compact slab covers every dirty bank and the dense fallback stays a
counter, never a recompile. The program is compiled ONCE at a fixed batch
width — drained samples are chunked and the tail padded with valid=False
rows (memory_push drops them) — and watched by its own StepMonitor, so the
zero-steady-state-recompile contract is assertable exactly like serving's.

The consolidator owns the CANDIDATE state (gmm/memory/EM-optimizer moments,
seeded from the serving state): serving keeps scoring with its frozen
mixture while the candidate learns, and only a drift-triggered republish
(online/republish.py) moves traffic — consolidation never touches the pump.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence

import numpy as np

from mgproto_tpu.online import metrics as om
from mgproto_tpu.online.capture import CapturedSample, TrustedCapture

RESULT_RAN = "ran"
RESULT_EMPTY = "empty"


@dataclasses.dataclass(frozen=True)
class ConsolidatorConfig:
    cadence_s: float = 1.0  # how often `tick` actually consolidates
    batch_width: int = 16  # the ONE compiled consolidation batch shape
    min_samples: int = 1  # don't bother below this many staged


@dataclasses.dataclass(frozen=True)
class ConsolidationReport:
    """What one cadence firing did."""

    t: float
    drained: int
    batches: int
    em_active_max: int  # widest EM call of the run (0 = no bank was ready)
    compact_fallbacks: int
    result: str  # ran | empty

    def to_dict(self):
        return dataclasses.asdict(self)


class Consolidator:
    """The cadence loop's engine: drain -> push -> compact EM (see module
    docstring). Not thread-safe by design — exactly one consolidation
    driver per process, the same single-pump rule the serving plane uses."""

    def __init__(
        self,
        trainer,
        state,
        capture: Optional[TrustedCapture] = None,
        config: Optional[ConsolidatorConfig] = None,
        clock=time.monotonic,
        monitor=None,
    ):
        import jax
        import jax.numpy as jnp

        from mgproto_tpu.core.em import em_update, resolve_em_config
        from mgproto_tpu.core.memory import memory_push
        from mgproto_tpu.core.mgproto import head_forward
        from mgproto_tpu.telemetry.monitor import StepMonitor

        self.config = config or ConsolidatorConfig()
        self.capture = capture
        self.clock = clock
        self.trainer = trainer
        cfg = trainer.cfg
        self._k = cfg.model.prototypes_per_class
        self._img = cfg.model.img_size
        self._c = cfg.model.num_classes
        width = max(int(self.config.batch_width), 1)
        self._width = width
        # the candidate state: banks + mixture + EM moments, seeded from
        # (and shaped exactly like) the serving state's
        self.gmm = state.gmm
        self.memory = state.memory
        self.opt_state = state.proto_opt_state
        self._params = state.params
        self._batch_stats = state.batch_stats
        self._mean_tx = trainer.proto_tx
        # compact dirty-class EM at the consolidation width: W samples can
        # newly dirty at most W classes (core/em.py resolve_em_config)
        em_cfg = resolve_em_config(cfg.em, self._c, width)
        self._em_cfg = em_cfg

        def consolidate_fn(params, batch_stats, gmm, memory, opt_state,
                           images, classes, valid):
            (proto_map, _), _ = trainer._apply(
                params, batch_stats, images, train=False
            )
            # padding rows carry class -1: clip for the label-indexed
            # feature gather (valid=False already drops them at the push)
            labels = jnp.clip(classes, 0, self._c - 1)
            _, _, enq = head_forward(
                proto_map, gmm, labels, cfg.model.mine_T,
                fused=trainer._fused,
            )
            feats, enq_classes, enq_valid = enq
            enq_valid = enq_valid & jnp.repeat(valid, self._k)
            mem = memory_push(memory, feats, enq_classes, enq_valid)
            gmm2, mem2, opt2, aux = em_update(
                gmm, mem, opt_state, self._mean_tx, em_cfg
            )
            return gmm2, mem2, opt2, aux.num_active, aux.compact_fallback

        self._jit = jax.jit(consolidate_fn)
        self.monitor = monitor if monitor is not None else StepMonitor(
            phase="online"
        )
        self.monitor.watch(self._jit)
        self._next_due = self.clock() + self.config.cadence_s
        self.runs = 0
        self.samples_consolidated = 0
        self.reports: List[ConsolidationReport] = []

    # ---------------------------------------------------------------- cadence
    def tick(self, now: Optional[float] = None) -> Optional[ConsolidationReport]:
        """One poll: consolidate iff the cadence elapsed AND enough samples
        are staged. Returns the report when the cadence fired, else None.
        Poll-driven — the caller's pump decides when host time is spare."""
        now = self.clock() if now is None else now
        if now < self._next_due or self.capture is None:
            return None
        self._next_due = now + self.config.cadence_s
        if self.capture.staged_count() < self.config.min_samples:
            om.counter(om.CONSOLIDATIONS).inc(result=RESULT_EMPTY)
            report = ConsolidationReport(
                t=now, drained=0, batches=0, em_active_max=0,
                compact_fallbacks=0, result=RESULT_EMPTY,
            )
            self.reports.append(report)
            return report
        return self.ingest(self.capture.drain(), now=now)

    # ----------------------------------------------------------------- ingest
    def ingest(
        self, samples: Sequence[CapturedSample], now: Optional[float] = None
    ) -> ConsolidationReport:
        """Consolidate `samples` immediately (the drill's bootstrap path
        and tick's worker). Chunks to the ONE compiled width; the tail pads
        with valid=False rows."""
        now = self.clock() if now is None else now
        w = self._width
        em_active_max = 0
        fallbacks = 0
        batches = 0
        for i in range(0, len(samples), w):
            chunk = samples[i:i + w]
            images = np.zeros((w, self._img, self._img, 3), np.float32)
            classes = np.full((w,), -1, np.int32)
            valid = np.zeros((w,), bool)
            for j, s in enumerate(chunk):
                images[j] = np.asarray(s.payload, np.float32)
                classes[j] = s.class_id
                valid[j] = True
            gmm, mem, opt, n_active, fallback = self._jit(
                self._params, self._batch_stats, self.gmm, self.memory,
                self.opt_state, images, classes, valid,
            )
            self.gmm, self.memory, self.opt_state = gmm, mem, opt
            em_active_max = max(em_active_max, int(n_active))
            fallbacks += int(fallback)
            batches += 1
        self.runs += 1
        self.samples_consolidated += len(samples)
        om.counter(om.CONSOLIDATIONS).inc(result=RESULT_RAN)
        om.counter(om.CONSOLIDATED_SAMPLES).inc(float(len(samples)))
        report = ConsolidationReport(
            t=now,
            drained=len(samples),
            batches=batches,
            em_active_max=em_active_max,
            compact_fallbacks=fallbacks,
            result=RESULT_RAN,
        )
        self.reports.append(report)
        return report

    # ------------------------------------------------------------- candidate
    def claim_class(self, slot: int) -> None:
        """Class addition (online/classes.py): raise the padded slot's
        priors to uniform in the CANDIDATE mixture — host-side, on the
        cadence, never in a compiled step."""
        from mgproto_tpu.online.classes import claim_slot

        self.gmm = claim_slot(self.gmm, slot)

    def candidate_state(self, serving_state):
        """`serving_state` with the candidate's gmm/memory/EM moments —
        what recalibration scores and the republish promotes."""
        return serving_state.replace(
            gmm=self.gmm,
            memory=self.memory,
            proto_opt_state=self.opt_state,
        )

    def bank_arrays(self):
        """(feats, length) of the candidate bank as host numpy — the drift
        monitor's `observe_bank` input."""
        return (
            np.asarray(self.memory.feats),
            np.asarray(self.memory.length),
        )

    def steady_recompiles(self) -> int:
        """Recompiles of the consolidation program since the last check —
        after the first ingest this must stay 0 (tier-1 asserts it)."""
        return self.monitor.check_recompiles()
