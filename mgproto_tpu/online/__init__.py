"""Online MGProto: continual learning from production traffic (ISSUE 11).

The control plane that closes ROADMAP item 4's loop, sitting BESIDE the
serving plane and off its hot path:

  capture.py     — trusted capture: a post-`record()` tap in the serving
                   engine stages high-p(x) production samples into bounded
                   per-class reservoirs (one None-check when disabled).
  consolidate.py — background consolidation: a poll-driven cadence loop
                   (injectable clock, no blocking sleeps) drains staged
                   samples into the per-class memory banks (core/memory.
                   memory_push) and runs compact dirty-class EM (core/em.py)
                   on the touched classes — one compiled program, zero
                   steady-state recompiles, never on the pump.
  classes.py     — class addition without trunk recompilation: pad-to-bucket
                   over classes (ModelConfig.class_bucket), padded slots
                   carry floor priors until a new class claims one.
  drift.py       — drift detection via p(x): per-class bank mean/covariance
                   shift (the mean-embedding view, "Deep Mean Maps") and
                   serving-time p(x) quantile-sketch divergence vs the
                   artifact's calibration, as drift_* gauges + flight-
                   recorder events.
  republish.py   — zero-downtime republish: recalibrate the consolidated
                   candidate through the PR-3 path and promote it via the
                   PR-7 blue/green swap, TrustGate fail-closed.
  metrics.py     — online_*/drift_* metric names + registration (jax-free).

Import discipline: this package __init__ stays import-free so the serving
engine's tap (`from mgproto_tpu.online import capture`) never drags jax into
a jax-free process; `consolidate`/`republish` (which need the model stack)
are imported explicitly by their callers.
"""
