"""Drift-triggered republish: recalibrate the candidate, swap with zero drops.

The correction arm of the online loop. When the drift monitor breaches and
the consolidator holds a candidate mixture, the republisher:

  1. RECALIBRATES through the PR-3 path (the injected `recalibrate`
     closure runs `serving.calibration.calibrate` over held-out samples
     with the CANDIDATE state — same eval code path as serving, fingerprint
     stamped from the candidate's actual mixture);
  2. PROMOTES via the PR-7 blue/green swap (`serving.swap.hot_swap`): a
     full standby fleet is staged + warmed OFF the pump, verified
     fail-closed — the TrustGate refuses an uncalibrated candidate or one
     whose calibration fingerprint disagrees with the mixture it would
     serve — and only then does traffic flip, queued requests transferred,
     zero dropped by construction;
  3. REBASES the drift monitor on commit: the new calibration + candidate
     bank become the reference, so the monitor now watches the corrected
     model.

A refused promotion is an outcome, not an error (the SwapReport's reason
says why); the old model keeps serving, the breach keeps counting, and the
operator sees `online_republish_total{result=rejected}` climb. A minimum
republish interval stops a flapping drift signal from thrashing the fleet
through back-to-back warmup storms.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

from mgproto_tpu.obs.flightrec import record_event
from mgproto_tpu.online import metrics as om

RESULT_COMMITTED = "committed"
RESULT_REJECTED = "rejected"


@dataclasses.dataclass(frozen=True)
class RepublishRecord:
    """One attempt, committed or refused."""

    t: float
    result: str
    swap: Dict[str, Any]  # serving.swap.SwapReport.to_dict()
    calibration_fingerprint: Optional[str]
    trigger_signals: tuple

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["trigger_signals"] = list(self.trigger_signals)
        return d


class Republisher:
    """Drift breach -> recalibrate -> blue/green promote (see module
    docstring). The model stack enters only through the injected closures,
    so this module stays importable on a bare serving host."""

    def __init__(
        self,
        replica_set,
        recalibrate: Callable[[], Any],  # -> serving Calibration (candidate)
        factory_builder: Callable[[Any], Callable],  # calibration -> engine factory
        clock=time.monotonic,
        min_interval_s: float = 5.0,
        min_confirmations: int = 2,
        require_calibrated: bool = True,
        on_commit: Optional[Callable[[Any], None]] = None,
    ):
        self.replica_set = replica_set
        self.recalibrate = recalibrate
        self.factory_builder = factory_builder
        self.clock = clock
        self.min_interval_s = float(min_interval_s)
        # a republish is a fleet-wide warmup event: demand the breach hold
        # over this many CONSECUTIVE drift evaluations before acting, so a
        # single noisy window cannot thrash the fleet (and the detection
        # timestamp provably precedes the correction)
        self.min_confirmations = max(int(min_confirmations), 1)
        self.require_calibrated = require_calibrated
        self.on_commit = on_commit
        self._next_allowed = self.clock()
        self._consecutive = 0
        self.records: List[RepublishRecord] = []

    @property
    def committed(self) -> int:
        return sum(r.result == RESULT_COMMITTED for r in self.records)

    def maybe_republish(
        self, drift_report, now: Optional[float] = None
    ) -> Optional[RepublishRecord]:
        """Attempt a republish iff `drift_report` breached and the
        interval allows. Returns the record of an attempt, else None."""
        from mgproto_tpu.serving.swap import hot_swap

        if drift_report is None:
            return None
        if not drift_report.breached:
            self._consecutive = 0
            return None
        self._consecutive += 1
        if self._consecutive < self.min_confirmations:
            return None
        now = self.clock() if now is None else now
        if now < self._next_allowed:
            return None
        self._next_allowed = now + self.min_interval_s
        record_event(
            "republish_triggered",
            signals=",".join(drift_report.signals),
            px_divergence=drift_report.px_divergence,
        )
        try:
            calibration = self.recalibrate()
        except Exception as e:
            # recalibration failing must not take serving down: count the
            # refusal, keep the old model, let the breach keep ringing
            report = {"ok": False, "reason": "recalibrate_failed",
                      "detail": f"{type(e).__name__}: {e}"}
            rec = RepublishRecord(
                t=now, result=RESULT_REJECTED, swap=report,
                calibration_fingerprint=None,
                trigger_signals=drift_report.signals,
            )
            om.counter(om.REPUBLISH).inc(result=RESULT_REJECTED)
            record_event("republish_rejected", reason="recalibrate_failed")
            self.records.append(rec)
            return rec
        factory = self.factory_builder(calibration)
        swap = hot_swap(
            self.replica_set, factory,
            require_calibrated=self.require_calibrated,
        )
        result = RESULT_COMMITTED if swap.ok else RESULT_REJECTED
        rec = RepublishRecord(
            t=now,
            result=result,
            swap=swap.to_dict(),
            calibration_fingerprint=getattr(
                calibration, "gmm_fingerprint", None
            ),
            trigger_signals=drift_report.signals,
        )
        om.counter(om.REPUBLISH).inc(result=result)
        record_event(f"republish_{result}", reason=swap.reason)
        self.records.append(rec)
        if swap.ok and self.on_commit is not None:
            self.on_commit(calibration)
        return rec
