"""Class addition without trunk recompilation: pad-to-bucket over classes.

XLA compiles per shape, and the class count C is a shape: logits [B, C],
GMM means [C, K, d], memory bank [C, cap, d]. Growing C naively recompiles
the trunk — exactly the steady-state-recompile regression the serving plane
forbids. The fix mirrors the batch buckets (serving/engine.py pads requests
to a compiled batch size): the model is BUILT at the class count rounded up
to `ModelConfig.class_bucket`, and the padded slots are inert until claimed:

  * a padded slot carries FLOOR (exactly zero) priors — head_forward maps
    zero priors to -inf logits (the pruned-slot convention, core/mgproto.py)
    so a padded slot can never win an argmax and contributes nothing to
    p(x);
  * `ClassDirectory.add_class` claims the next free slot; `claim_slot`
    raises its priors to uniform 1/K so EM can own it as soon as its bank
    fills (means stay at their random init — consolidation's EM pulls them
    onto the new class's data manifold);
  * every compiled program — trunk, eval, serving buckets, consolidation —
    was traced at the PADDED width, so the addition is pure data movement:
    zero recompiles, asserted in tests/test_online.py via the StepMonitor
    recompile detector.

When the bucket itself is exhausted the addition is REFUSED with a typed
error naming the recompile the operator would be buying — growing past the
bucket is a deliberate re-export/republish event, never a silent stall.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional

from mgproto_tpu.online import metrics as om


class ClassBucketFull(RuntimeError):
    """Every padded slot is claimed: adding another class means rebuilding
    (and recompiling) the trunk at the next bucket — an operator decision,
    not something the online plane does implicitly."""


def padded_num_classes(num_classes: int, class_bucket: int) -> int:
    """`num_classes` rounded up to a multiple of `class_bucket`
    (<=1 disables padding, the pre-online behavior)."""
    c, b = int(num_classes), int(class_bucket)
    if b <= 1:
        return c
    return ((c + b - 1) // b) * b


def apply_class_bucket(cfg):
    """A Config whose model is built at the padded class width (the trunk,
    banks and buckets then all compile at the bucket). No-op when
    `class_bucket` is unset or the count is already aligned."""
    padded = padded_num_classes(
        cfg.model.num_classes, cfg.model.class_bucket
    )
    if padded == cfg.model.num_classes:
        return cfg
    return cfg.replace(
        model=dataclasses.replace(cfg.model, num_classes=padded)
    )


def floor_padded_priors(gmm, active_classes: int):
    """Zero the priors of every slot at or past `active_classes` — the
    floor that keeps padded slots out of argmax and p(x) until claimed.
    (Exact zero, not epsilon: head_forward maps zero priors to -inf logits,
    the same convention pruning uses.)"""
    import jax.numpy as jnp

    c = gmm.priors.shape[0]
    mask = jnp.arange(c) < int(active_classes)  # [C]
    return gmm._replace(priors=jnp.where(mask[:, None], gmm.priors, 0.0))


def claim_slot(gmm, slot: int):
    """Raise a padded slot's priors to uniform 1/K — the moment a new class
    takes ownership. Host-side (runs on the consolidation cadence, never in
    a compiled step)."""
    k = gmm.priors.shape[1]
    return gmm._replace(priors=gmm.priors.at[int(slot)].set(1.0 / k))


class ClassDirectory:
    """Which padded slots are live, and what external class they carry.

    The serving/consolidation planes address classes by SLOT (the model's
    class axis); the directory owns the slot <-> external-name mapping and
    the free list. Thread-safe: additions come from the operator/feedback
    path while the consolidation cadence reads."""

    def __init__(self, base_classes: int, padded_classes: int):
        base, padded = int(base_classes), int(padded_classes)
        if padded < base:
            raise ValueError(
                f"padded class count {padded} < base {base}"
            )
        self.padded_classes = padded
        self._lock = threading.Lock()
        # slots [0, base) are the classes the model shipped with
        self._names: Dict[int, str] = {
            i: f"class{i}" for i in range(base)
        }
        self._next_free = base
        om.gauge(om.ACTIVE_CLASSES).set(float(base))

    @property
    def active_classes(self) -> int:
        with self._lock:
            return len(self._names)

    @property
    def free_slots(self) -> int:
        with self._lock:
            return self.padded_classes - self._next_free

    def slot_of(self, name: str) -> Optional[int]:
        with self._lock:
            for slot, n in self._names.items():
                if n == name:
                    return slot
        return None

    def add_class(self, name: Optional[str] = None) -> int:
        """Claim the next free padded slot for a new class; returns the
        slot index. Raises ClassBucketFull when the bucket is exhausted."""
        with self._lock:
            if self._next_free >= self.padded_classes:
                raise ClassBucketFull(
                    f"all {self.padded_classes} bucketed class slots are "
                    "claimed; growing further requires rebuilding at the "
                    "next class_bucket (a recompile + republish, not an "
                    "online addition)"
                )
            slot = self._next_free
            self._next_free += 1
            self._names[slot] = name or f"class{slot}"
            count = len(self._names)
        om.counter(om.CLASS_ADDITIONS).inc()
        om.gauge(om.ACTIVE_CLASSES).set(float(count))
        return slot
