"""Trusted capture: stage high-p(x) production samples for consolidation.

The serving engine answers each request with a calibrated trust decision
(serving/gate.py); this module is the tap BEHIND that decision — the moment
a response leaves `record()`, a sample whose log p(x) clears the CAPTURE
gate (a stricter percentile of the same calibration the abstention gate
uses) is staged, with its predicted class as the label, into a bounded
per-class reservoir. The generative score is what makes self-labeling
sound: a sample the mixture assigns high p(x) is, by the model's own
account, drawn from the distribution the banks were fit on — exactly the
traffic EM can consolidate without supervision. Everything the gate would
not vouch for — abstentions, rejects, sheds, degraded-mode predictions,
low-p(x) predictions (the chaos poison drill's mislabeled junk) — never
enqueues, and is counted by outcome.

Off the hot path by construction:

  * the engine-side tap is `get_active()` — ONE module-global None-check
    when disabled (the obs/reqtrace discipline), and an O(1) reservoir
    append when enabled (no feature extraction, no device work: raw
    payloads are staged; consolidation recomputes features through the
    SAME model path training uses, on its own cadence).
  * per-class queues are bounded with seeded reservoir-style eviction:
    once a class's queue is full, an arriving sample replaces a random
    staged one with probability capacity/seen — a uniform sample over the
    class's accepted stream, so a long steady phase cannot starve the
    window of recent traffic nor recency wash out the steady state.

A second, smaller reservoir (`recal_capacity`) keeps accepted samples for
RECALIBRATION: consolidation drains the staging queues destructively, but
republish needs held-out ID samples to re-derive thresholds under the
candidate mixture (online/republish.py) — these are not consumed by drain.

`submit_labeled` is the operator-labeled feedback path class ADDITION needs
(online/classes.py): a brand-new class has no calibrated p(x) to clear (the
serving mixture knows nothing about it yet), so labeled samples bypass the
percentile gate — trusted by provenance instead of by score — and are
counted under their own outcome label.

jax-free: the tap must be installable in any process that can answer
requests, device stack or not.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from mgproto_tpu.online import metrics as om

OUTCOME_ACCEPTED = "accepted"
OUTCOME_GATE_REJECTED = "gate_rejected"
OUTCOME_SKIPPED = "outcome_skipped"
OUTCOME_CLASS_UNKNOWN = "class_unknown"
OUTCOME_LABELED = "labeled"

DEFAULT_CAPTURE_PERCENTILE = 25.0


@dataclasses.dataclass(frozen=True)
class CaptureConfig:
    """Knobs of the trusted-capture gate and its staging reservoirs."""

    # log p(x) must exceed the calibration's threshold at THIS percentile
    # to stage (stricter than the abstention operating point: only
    # comfortably in-distribution traffic self-labels)
    percentile: float = DEFAULT_CAPTURE_PERCENTILE
    capacity_per_class: int = 64  # staging reservoir bound, per class
    recal_capacity: int = 128  # held-out recalibration reservoir (global)
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class CapturedSample:
    """One staged sample: the raw payload plus its provenance."""

    payload: Any  # the validated input (features or image array)
    class_id: int
    log_px: Optional[float]
    request_id: str
    labeled: bool = False  # operator feedback (class addition) vs self-label


class TrustedCapture:
    """Per-class staging reservoirs behind the calibrated capture gate."""

    def __init__(
        self,
        calibration,
        num_classes: int,
        config: Optional[CaptureConfig] = None,
        tenant: Optional[str] = None,
    ):
        self.config = config or CaptureConfig()
        self.num_classes = int(num_classes)
        self.calibration = calibration
        # multi-tenant serving (ISSUE 17): a tenant-owned reservoir labels
        # its capture counters, so each tenant's self-labeling stream is
        # accounted separately. None = the single-tenant tap, unchanged.
        self.tenant = tenant
        self._labels = {} if tenant is None else {"tenant": str(tenant)}
        self.threshold: Optional[float] = None
        if calibration is not None:
            self.threshold = calibration.threshold_for(
                self.config.percentile
            )
        self._lock = threading.Lock()
        self._rng = np.random.RandomState(self.config.seed)
        self._queues: Dict[int, List[CapturedSample]] = {}
        self._seen: Dict[int, int] = {}  # accepted per class (reservoir N)
        self._recal: List[CapturedSample] = []
        self._recal_seen = 0
        self.accepted = 0
        self.evicted = 0
        # accepted request ids, bounded — the poison drill's ground truth
        # for "did mislabeled junk ever actually get staged"
        self._accepted_ids: Deque[str] = deque(maxlen=4096)
        self._accepted_set: set = set()

    def retarget(self, calibration) -> None:
        """Adopt a republished model's calibration: the capture gate must
        judge p(x) on the scale of the mixture NOW serving."""
        self.calibration = calibration
        self.threshold = (
            calibration.threshold_for(self.config.percentile)
            if calibration is not None else None
        )

    # ------------------------------------------------------------------- tap
    def on_response(self, payload, resp) -> bool:
        """The post-record() tap: stage `payload` iff `resp` is a trusted,
        gate-clearing prediction. Returns True when staged. Never raises —
        a capture bug must not take serving down."""
        try:
            if (
                resp.outcome != "predict"
                or resp.degraded
                or resp.trust != "in_dist"
                or resp.log_px is None
            ):
                om.counter(om.CAPTURED).inc(
                    outcome=OUTCOME_SKIPPED, **self._labels
                )
                return False
            if self.threshold is None or not (
                float(resp.log_px) > self.threshold
            ):
                # at-or-below the capture percentile (or no calibration to
                # gate with): the poison drill's low-p(x) mislabeled junk
                # lands here when it lands anywhere at all
                om.counter(om.CAPTURED).inc(
                    outcome=OUTCOME_GATE_REJECTED, **self._labels
                )
                return False
            cls = int(resp.prediction)
            if not 0 <= cls < self.num_classes:
                om.counter(om.CAPTURED).inc(
                    outcome=OUTCOME_CLASS_UNKNOWN, **self._labels
                )
                return False
            self._stage(CapturedSample(
                payload=payload,
                class_id=cls,
                log_px=float(resp.log_px),
                request_id=resp.request_id,
            ))
            om.counter(om.CAPTURED).inc(
                outcome=OUTCOME_ACCEPTED, **self._labels
            )
            return True
        except Exception:
            return False

    def submit_labeled(
        self, payload, class_id: int, request_id: str = ""
    ) -> bool:
        """Operator-labeled feedback (class addition): bypasses the p(x)
        gate — the serving mixture cannot score a class it does not know —
        but still bounded by the same reservoirs."""
        cls = int(class_id)
        if not 0 <= cls < self.num_classes:
            om.counter(om.CAPTURED).inc(
                outcome=OUTCOME_CLASS_UNKNOWN, **self._labels
            )
            return False
        self._stage(CapturedSample(
            payload=payload,
            class_id=cls,
            log_px=None,
            request_id=request_id,
            labeled=True,
        ))
        om.counter(om.CAPTURED).inc(
            outcome=OUTCOME_LABELED, **self._labels
        )
        return True

    def was_captured(self, request_id: str) -> bool:
        """True iff a sample with this request id was ever staged (over
        the last 4096 acceptances)."""
        with self._lock:
            return request_id in self._accepted_set

    def _stage(self, sample: CapturedSample) -> None:
        cap = max(int(self.config.capacity_per_class), 1)
        with self._lock:
            if sample.request_id:
                if len(self._accepted_ids) == self._accepted_ids.maxlen:
                    self._accepted_set.discard(self._accepted_ids[0])
                self._accepted_ids.append(sample.request_id)
                self._accepted_set.add(sample.request_id)
            q = self._queues.setdefault(sample.class_id, [])
            seen = self._seen.get(sample.class_id, 0) + 1
            self._seen[sample.class_id] = seen
            if len(q) < cap:
                q.append(sample)
            else:
                # reservoir step: keep with prob cap/seen, displacing a
                # uniformly random staged sample — the queue stays a
                # uniform sample of the class's accepted stream. Only an
                # actual displacement counts as an eviction (j >= cap
                # drops the ARRIVING sample, nothing staged moved).
                j = int(self._rng.randint(0, seen))
                if j < cap:
                    q[j] = sample
                    self.evicted += 1
                    om.counter(om.CAPTURE_EVICTED).inc()
            self.accepted += 1
            # recalibration holdout: plain reservoir over ALL accepted
            self._recal_seen += 1
            if len(self._recal) < max(int(self.config.recal_capacity), 1):
                self._recal.append(sample)
            else:
                j = int(self._rng.randint(0, self._recal_seen))
                if j < len(self._recal):
                    self._recal[j] = sample
            om.gauge(om.STAGED).set(float(
                sum(len(v) for v in self._queues.values())
            ))

    # ----------------------------------------------------------------- drain
    def staged_count(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def drain(self) -> List[CapturedSample]:
        """Pop EVERYTHING staged (consolidation's input), oldest class id
        first — deterministic order for a deterministic drill."""
        with self._lock:
            out: List[CapturedSample] = []
            for cls in sorted(self._queues):
                out.extend(self._queues[cls])
            self._queues.clear()
            om.gauge(om.STAGED).set(0.0)
            return out

    def recal_samples(self) -> List[CapturedSample]:
        """A COPY of the recalibration holdout (not consumed)."""
        with self._lock:
            return list(self._recal)

    def recal_batches(
        self, batch_size: int
    ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """The holdout as full (images, labels) eval batches for the PR-3
        `calibrate()` path. Full batches only: the serving buckets pinned
        the eval program's widths, and recalibration must not compile a
        ragged-tail variant."""
        samples = self.recal_samples()
        out = []
        for i in range(0, len(samples) - batch_size + 1, batch_size):
            chunk = samples[i:i + batch_size]
            out.append((
                np.stack([np.asarray(s.payload, np.float32) for s in chunk]),
                np.asarray([s.class_id for s in chunk], np.int32),
            ))
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "accepted": self.accepted,
                "evicted": self.evicted,
                "staged": sum(len(q) for q in self._queues.values()),
                "staged_classes": sorted(self._queues),
                "recal_held": len(self._recal),
                "threshold_log_px": self.threshold,
                "percentile": self.config.percentile,
            }


# --------------------------------------------------------- process-wide tap
# The serving engine consults this exactly like obs/reqtrace: disabled is
# one module-global None-check, no per-request work.
_ACTIVE: Optional[TrustedCapture] = None


def get_active() -> Optional[TrustedCapture]:
    """The process-active capture tap (None = capture off)."""
    return _ACTIVE


def install(capture: Optional[TrustedCapture]) -> Optional[TrustedCapture]:
    """Install `capture` as the process-active tap; returns the previous
    one so callers can restore it (the load-test/CLI try/finally pattern)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = capture
    return prev


def uninstall() -> None:
    install(None)
