"""Drift detection via p(x): see the shift BEFORE accuracy pays for it.

MGProto is a generative classifier, and that buys the one signal a
discriminative serving stack does not have: summing p(x|c) gives a
calibrated p(x) that measures DISTRIBUTION FIT per request. When production
traffic drifts, p(x) falls while argmax often still limps along — so drift
is measurable before it is corrected, and the correction (consolidate +
recalibrate + republish) can land before accuracy does the telling.

Two complementary signals, both against the ARTIFACT'S OWN calibration:

  * p(x) QUANTILE-SKETCH DIVERGENCE — the calibration carries a 101-point
    quantile sketch of the held-out ID log p(x) distribution
    (serving/calibration.py); the monitor keeps a bounded window of
    serving-time scores, computes the same sketch, and reports the mean
    absolute quantile displacement normalized by the calibration sketch's
    IQR. Covariate shift moves the whole curve; the gauge reads in units
    of "ID interquartile ranges".
  * PER-CLASS BANK MEAN/COVARIANCE SHIFT — the consolidated memory banks
    are per-class feature samples, so their first two moments are exactly
    the mean-embedding view of "Deep Mean Maps" (PAPERS.md): the L2 shift
    of each class's bank mean (and the mean |Δ| of its diagonal variance)
    against the calibration-time baseline is the per-class drift
    statistic EM itself will chase.

Breaches land as `drift_breach_total{signal=px|bank}` + a flight-recorder
event, and the gauges feed the summarize "drift" section. Poll-driven on an
injectable clock (`evaluate` is cadence-gated, never sleeps) — the same
discipline as the serving plane, enforced by check_no_blocking_sleep.

numpy + stdlib only: the monitor runs on serving hosts.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from mgproto_tpu.obs.flightrec import record_event
from mgproto_tpu.online import metrics as om

SIGNAL_PX = "px"
SIGNAL_BANK = "bank"


@dataclasses.dataclass(frozen=True)
class DriftConfig:
    px_window: int = 512  # serving-time log p(x) scores kept
    min_px_samples: int = 64  # below this the px signal stays quiet
    eval_interval_s: float = 1.0  # cadence of `evaluate` (injected clock)
    # breach thresholds; <= 0 disables a signal
    px_divergence_threshold: float = 0.35  # in calibration-IQR units
    mean_shift_threshold: float = 0.25  # L2 in feature space
    cov_shift_threshold: float = 0.0  # mean |Δ diag var|; default observe-only


@dataclasses.dataclass(frozen=True)
class DriftReport:
    """One cadence evaluation — always returned, breach or not."""

    t: float
    px_divergence: Optional[float]
    mean_shift_max: float
    cov_shift_max: float
    class_shifts: Dict[int, float]  # per-class bank mean L2 shift
    breached: bool
    signals: Tuple[str, ...]  # which thresholds breached

    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d["signals"] = list(self.signals)
        d["class_shifts"] = {
            str(k): v for k, v in self.class_shifts.items()
        }
        return d


def bank_moments(
    feats: np.ndarray, length: np.ndarray
) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
    """{class: (mean [d], diag var [d])} over each class's VALID bank rows
    (circular buffer: row order is irrelevant to moments). Classes with an
    empty bank are omitted — no data, no drift claim."""
    feats = np.asarray(feats, np.float64)
    length = np.asarray(length)
    out: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
    for c in range(feats.shape[0]):
        n = int(length[c])
        if n <= 0:
            continue
        rows = feats[c, : min(n, feats.shape[1])]
        out[c] = (rows.mean(axis=0), rows.var(axis=0))
    return out


class DriftMonitor:
    """Serving-time drift gauges against a calibration-time baseline."""

    def __init__(
        self,
        calibration,
        config: Optional[DriftConfig] = None,
        clock=time.monotonic,
        tenant: Optional[str] = None,
    ):
        self.config = config or DriftConfig()
        self.clock = clock
        self.calibration = calibration
        # multi-tenant serving (ISSUE 17): a tenant-owned monitor labels
        # every gauge/breach with its tenant, so one tenant's drifting
        # traffic is ATTRIBUTED, not a fleet-wide alarm. None = the
        # single-tenant monitor, metrics unchanged.
        self.tenant = tenant
        self._labels = {} if tenant is None else {"tenant": str(tenant)}
        self._scores: Deque[float] = deque(
            maxlen=max(int(self.config.px_window), 1)
        )
        self._baseline: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self._current: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self._next_eval = self.clock()
        self.breaches = 0
        self.first_breach: Optional[DriftReport] = None
        self.last_report: Optional[DriftReport] = None

    # ------------------------------------------------------------ observation
    def set_bank_baseline(self, feats, length) -> None:
        """Freeze the calibration-time bank moments (the Deep-Mean-Maps
        reference point the shift gauges measure against)."""
        self._baseline = bank_moments(feats, length)
        self._current = dict(self._baseline)

    def observe_px(self, log_px: float) -> None:
        """One served score into the sliding window (predict/abstain
        responses both carry it — abstentions are exactly the drifted
        tail the monitor must see)."""
        if log_px is not None and np.isfinite(log_px):
            self._scores.append(float(log_px))

    def observe_bank(self, feats, length) -> None:
        """Refresh the current bank moments (the consolidation cadence
        calls this after each run — bank reads stay off the pump)."""
        self._current = bank_moments(feats, length)

    # ------------------------------------------------------------- evaluation
    def px_divergence(self) -> Optional[float]:
        """Mean |serving quantile - calibration quantile| over the interior
        sketch points, in units of the calibration sketch's IQR. None until
        the window holds `min_px_samples` scores."""
        if (
            self.calibration is None
            or len(self._scores) < self.config.min_px_samples
        ):
            return None
        ref = np.asarray(self.calibration.quantile_log_px, np.float64)
        pts = np.linspace(0.0, 100.0, ref.size)
        # interior points only: the extreme tails of a bounded window are
        # order statistics of a few samples — all noise, no signal
        interior = (pts >= 5.0) & (pts <= 95.0)
        window = np.asarray(self._scores, np.float64)
        cur = np.percentile(window, pts[interior])
        iqr = float(
            np.interp(75.0, pts, ref) - np.interp(25.0, pts, ref)
        )
        iqr = max(iqr, 1e-9)
        return float(np.mean(np.abs(cur - ref[interior])) / iqr)

    def bank_shift(self) -> Tuple[float, float, Dict[int, float]]:
        """(max mean L2 shift, max mean |Δ diag var|, per-class mean
        shifts) of the current bank moments vs the baseline."""
        mean_max, cov_max = 0.0, 0.0
        per_class: Dict[int, float] = {}
        for c, (mu, var) in self._current.items():
            base = self._baseline.get(c)
            if base is None:
                continue
            d_mu = float(np.linalg.norm(mu - base[0]))
            d_var = float(np.mean(np.abs(var - base[1])))
            per_class[c] = d_mu
            mean_max = max(mean_max, d_mu)
            cov_max = max(cov_max, d_var)
        return mean_max, cov_max, per_class

    def evaluate(self, now: Optional[float] = None) -> Optional[DriftReport]:
        """Cadence-gated evaluation: None when the interval has not
        elapsed; else a DriftReport, with gauges refreshed and breaches
        counted + flight-recorded."""
        now = self.clock() if now is None else now
        if now < self._next_eval:
            return None
        self._next_eval = now + self.config.eval_interval_s
        cfg = self.config
        div = self.px_divergence()
        mean_max, cov_max, per_class = self.bank_shift()
        signals: List[str] = []
        if (
            div is not None
            and cfg.px_divergence_threshold > 0
            and div > cfg.px_divergence_threshold
        ):
            signals.append(SIGNAL_PX)
        if (
            cfg.mean_shift_threshold > 0
            and mean_max > cfg.mean_shift_threshold
        ) or (
            cfg.cov_shift_threshold > 0
            and cov_max > cfg.cov_shift_threshold
        ):
            signals.append(SIGNAL_BANK)
        if div is not None:
            om.gauge(om.DRIFT_PX_DIVERGENCE).set(div, **self._labels)
        om.gauge(om.DRIFT_SHIFT_MAX).set(mean_max, **self._labels)
        om.gauge(om.DRIFT_COV_SHIFT_MAX).set(cov_max, **self._labels)
        for c, v in per_class.items():
            om.gauge(om.DRIFT_CLASS_SHIFT).set(
                v, **{"class": str(c), **self._labels}
            )
        report = DriftReport(
            t=now,
            px_divergence=div,
            mean_shift_max=mean_max,
            cov_shift_max=cov_max,
            class_shifts=per_class,
            breached=bool(signals),
            signals=tuple(signals),
        )
        if signals:
            self.breaches += 1
            if self.first_breach is None:
                self.first_breach = report
            for sig in signals:
                om.counter(om.DRIFT_BREACHES).inc(
                    signal=sig, **self._labels
                )
            record_event(
                "drift_breach",
                signals=",".join(signals),
                px_divergence=div,
                mean_shift_max=mean_max,
                **self._labels,
            )
        self.last_report = report
        return report

    # --------------------------------------------------------------- rebase
    def rebase(self, calibration, feats=None, length=None) -> None:
        """Adopt a republished model's calibration (and optionally its
        consolidated bank) as the new reference: the window clears, the
        breach latch resets — the monitor now watches for drift away from
        the CORRECTED model, not the old one."""
        self.calibration = calibration
        self._scores.clear()
        if feats is not None and length is not None:
            self.set_bank_baseline(feats, length)
        else:
            self._baseline = dict(self._current)
        self.first_breach = None
        record_event("drift_rebase")
