"""Online-learning + drift metric names and registration (jax-free).

Companion to `serving/metrics.py` and `resilience/metrics.py`: every event
of the continual-learning plane — captures by outcome, reservoir evictions,
consolidation runs, class additions, drift gauges and breaches, republish
attempts — lands in the telemetry registry as a labeled counter/gauge, so
`mgproto-telemetry summarize` renders the drift story next to serving and
training health. The whole family is PRE-registered with explicit zeros
(`register_online_metrics`, called by TelemetrySession) so a run that never
drifted still snapshots the series and `check` baselines can gate them —
the repo convention `scripts/check_metric_registry.py` enforces.
"""

from __future__ import annotations

from mgproto_tpu.telemetry.registry import (
    Counter,
    Gauge,
    default_registry,
)

# trusted capture (online/capture.py): label outcome=
#   accepted        — cleared the gate, staged for consolidation
#   gate_rejected   — log p(x) below the capture percentile threshold
#   outcome_skipped — non-predict / abstained / degraded response (tap
#                     never stages what the trust gate would not vouch for)
#   class_unknown   — predicted class outside the staging directory
#   labeled         — operator-labeled feedback (the new-class path)
CAPTURED = "online_capture_total"
CAPTURE_EVICTED = "online_capture_evicted_total"
STAGED = "online_staged_samples"

# background consolidation (online/consolidate.py): label result=
#   ran | empty (cadence fired with nothing staged)
CONSOLIDATIONS = "online_consolidation_total"
CONSOLIDATED_SAMPLES = "online_consolidated_samples_total"

# class addition (online/classes.py)
CLASS_ADDITIONS = "online_class_additions_total"
ACTIVE_CLASSES = "online_active_classes"

# republish (online/republish.py): label result= committed | rejected
REPUBLISH = "online_republish_total"

# drift monitor (online/drift.py). Values are distances in log p(x) /
# feature space, not times — no _seconds suffix by design.
DRIFT_PX_DIVERGENCE = "drift_px_divergence"
DRIFT_CLASS_SHIFT = "drift_class_mean_shift"  # labeled class=<c>
DRIFT_SHIFT_MAX = "drift_class_mean_shift_max"
DRIFT_COV_SHIFT_MAX = "drift_class_cov_shift_max"
DRIFT_BREACHES = "drift_breach_total"  # labeled signal= px | bank

COUNTER_HELP = {
    CAPTURED: "capture-tap decisions by outcome "
              "(accepted/gate_rejected/outcome_skipped/class_unknown/labeled)",
    CAPTURE_EVICTED:
        "staged samples displaced by reservoir eviction (full class queue)",
    CONSOLIDATIONS: "background consolidation cadence firings by result",
    CONSOLIDATED_SAMPLES:
        "captured samples drained into the memory banks by consolidation",
    CLASS_ADDITIONS: "classes added online into padded class-bucket slots",
    REPUBLISH: "drift-triggered republish attempts by result "
               "(committed/rejected — rejection is the TrustGate failing "
               "closed)",
    DRIFT_BREACHES: "drift threshold breaches by signal (px/bank)",
}

GAUGE_HELP = {
    STAGED: "samples currently staged across all per-class capture queues",
    ACTIVE_CLASSES: "classes registered in the online class directory",
    DRIFT_PX_DIVERGENCE:
        "mean |serving-quantile - calibration-quantile| of log p(x), "
        "normalized by the calibration sketch's IQR",
    DRIFT_CLASS_SHIFT:
        "L2 shift of a class's bank mean vs the calibration-time baseline "
        "(labeled class=<c>)",
    DRIFT_SHIFT_MAX: "max per-class bank mean shift",
    DRIFT_COV_SHIFT_MAX:
        "max per-class mean absolute shift of the bank's diagonal variance",
}

ALL_COUNTERS = tuple(COUNTER_HELP)
ALL_GAUGES = tuple(GAUGE_HELP)


def counter(name: str) -> Counter:
    """The named online counter in the process-current registry."""
    return default_registry().counter(name, COUNTER_HELP.get(name, ""))


def gauge(name: str) -> Gauge:
    """The named online gauge in the process-current registry."""
    return default_registry().gauge(name, GAUGE_HELP.get(name, ""))


def register_online_metrics(registry) -> None:
    """Pre-create the online/drift family with explicit zero-valued
    unlabeled series (the registry-lint contract: summarize/check always
    see the series, even when the run never drifted)."""
    for name in ALL_COUNTERS:
        registry.counter(name, COUNTER_HELP[name]).inc(0.0)
    for name in ALL_GAUGES:
        registry.gauge(name, GAUGE_HELP[name]).set(0.0)
