"""QuantPolicy + per-channel int8 weight-only quantization (ISSUE 20).

The serving program is byte-bound the same way training was pre-bf16
(evidence/stall_report_b256.json: 43.7% HBM-bound), and per-replica HBM is
what caps buckets-per-chip and tenants-per-fleet. This module quantizes the
backbone's conv/dense KERNELS to int8 with one float32 scale per output
channel at EXPORT time; the exported inference program carries the int8
tensors + scale vectors as its baked constants and dequantizes in-kernel
(`q.astype(f32) * scale`, fused by XLA into the consuming conv read), so
steady-state weight traffic is 1 byte/param + a scale vector instead of 4.

What is NEVER quantized — the MGProto-specific hard part: a generative
classifier's absolute p(x) scale is exactly what naive quantization breaks,
so everything the trust plane rides on keeps full precision BY TYPE:

  * the GMM banks / means / priors (state.gmm, state.memory) — they live
    outside state.params and this module never sees them;
  * biases, BatchNorm scale/offset/statistics, proxy matrices — structurally
    skipped (only `kernel` leaves with ndim >= 2 are eligible);
  * log p(x) / density math and the serving calibration — pinned f32 fields
    on QuantPolicy (refused in __post_init__, mirroring
    perf/precision.py::PrecisionPolicy), linted statically by
    scripts/check_dtype_discipline.py's int8 extension.

The quantization choice is the boring-on-purpose one: symmetric (no zero
point — a zero point adds an int add on the fused dequant path and buys
nothing for weight distributions centered on 0 by init+decay), per OUTPUT
channel (the last kernel axis for both flax convs [kh, kw, cin, cout] and
dense [in, out]), scale = amax/127 so the representable range exactly
covers the observed weights. Round-trip error is bounded by scale/2 per
element (asserted in tests/test_quant.py).

`quant_config()` is the provenance block stamped into the artifact's
meta.json; its `tag` ("int8:per_channel:symmetric") is the serving-seam
identity: the AOT cache key gains it as an axis, the calibration is stamped
with it, and TrustGate fails closed on a mismatch exactly like a
fingerprint mismatch (serving_quant_mismatch_total).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

QUANT_FORMAT = "mgproto-quant-v1"
SUPPORTED_QUANT_MODES = ("none", "int8")

# the serving-seam identity of the one supported scheme; "" = unquantized
QUANT_TAG_INT8 = "int8:per_channel:symmetric"


class QuantError(ValueError):
    """A request violated the quantization policy's f32 invariants."""


@dataclasses.dataclass(frozen=True)
class QuantPolicy:
    """What may be quantized, stated as a type. Only `mode` is a knob; the
    f32 fields are stated (not configurable) because the trust plane's
    correctness arguments depend on them — the GMM banks/priors, log p(x)
    scores and calibration math must keep the scale the thresholds were
    measured on (see module docstring and perf/precision.py)."""

    mode: str = "none"  # backbone conv/dense kernels: none | int8
    granularity: str = "per_channel"  # one f32 scale per output channel
    symmetric: bool = True  # no zero point
    gmm_dtype: str = "float32"  # mixture banks / means / priors
    score_dtype: str = "float32"  # density / log p(x) math
    calibration_dtype: str = "float32"  # serving threshold math

    def __post_init__(self):
        if self.mode not in SUPPORTED_QUANT_MODES:
            raise QuantError(
                f"quantize mode must be one of {SUPPORTED_QUANT_MODES}, "
                f"got {self.mode!r}"
            )
        if self.granularity != "per_channel":
            raise QuantError(
                "granularity is not a knob: per-tensor scales lose the "
                "per-output-channel dynamic range conv kernels need "
                f"(got {self.granularity!r})"
            )
        if not self.symmetric:
            raise QuantError(
                "asymmetric quantization is not a knob: a zero point adds "
                "an integer add to the fused dequant path for no benefit "
                "on zero-centered weight distributions"
            )
        for field in ("gmm_dtype", "score_dtype", "calibration_dtype"):
            if getattr(self, field) != "float32":
                raise QuantError(
                    f"{field} is not a knob: it must stay float32 "
                    f"(got {getattr(self, field)!r}); quantizing the GMM/"
                    "score/calibration path shifts the p(x) scale every "
                    "trust threshold depends on"
                )

    @property
    def quantized(self) -> bool:
        return self.mode != "none"

    @property
    def tag(self) -> str:
        """Serving-seam identity ("" for f32 — matches unstamped
        pre-quant calibrations by construction)."""
        return QUANT_TAG_INT8 if self.mode == "int8" else ""


def resolve_quant_policy(mode: str) -> QuantPolicy:
    """The policy a `--quantize MODE` flag implies."""
    return QuantPolicy(mode=str(mode or "none"))


def _is_quantizable(path: Tuple[str, ...], leaf: Any) -> bool:
    """Backbone conv/dense kernels only: named `kernel`, rank >= 2,
    floating. Everything else — biases, BN scale/offset, proxies, any
    1-D vector — keeps f32 (their bytes are noise; their scale is not)."""
    if not any(str(k) == "kernel" for k in path):
        return False
    shape = getattr(leaf, "shape", ())
    dtype = getattr(leaf, "dtype", None)
    if len(shape) < 2 or dtype is None:
        return False
    return np.issubdtype(np.dtype(dtype), np.floating)


def quantize_array(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-output-channel symmetric int8: (q[int8, w.shape],
    scale[f32, out_channels]). The output channel is the LAST axis (flax
    convs are [kh, kw, cin, cout], dense [in, out]). A dead channel
    (amax == 0) gets scale 1.0 so dequant round-trips its exact zeros."""
    w = np.asarray(w, np.float32)
    reduce_axes = tuple(range(w.ndim - 1))
    amax = np.max(np.abs(w), axis=reduce_axes)
    scale = np.where(amax > 0.0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_array(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """The inverse the serving program computes in-kernel."""
    return q.astype(np.float32) * np.asarray(scale, np.float32)


@dataclasses.dataclass(frozen=True)
class QuantizedParams:
    """A quantized snapshot of a trunk params pytree.

    `entries` holds one record per leaf, in treedef order:
    ("f32", leaf) for skipped leaves, ("int8", q, scale) for quantized
    kernels. `materialize()` rebuilds a params pytree of dequantized f32
    arrays — with `barrier=True` (inside a jax trace) each int8/scale pair
    is wrapped in `lax.optimization_barrier` so XLA cannot constant-fold
    the dequant back into a baked f32 tensor, which would silently restore
    the 4-byte weight traffic the whole exercise removes."""

    policy: QuantPolicy
    treedef: Any
    entries: Tuple[Tuple, ...]
    report: Tuple[Dict[str, Any], ...]

    def materialize(self, barrier: bool = False):
        import jax

        leaves = []
        for entry in self.entries:
            if entry[0] == "f32":
                leaves.append(entry[1])
                continue
            _, q, scale = entry
            if barrier:
                q, scale = jax.lax.optimization_barrier((q, scale))
                import jax.numpy as jnp

                leaves.append(q.astype(jnp.float32) * scale)
            else:
                leaves.append(dequantize_array(q, scale))
        import jax

        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    @property
    def num_quantized(self) -> int:
        return sum(1 for r in self.report if r["quantized"])

    @property
    def num_skipped(self) -> int:
        return sum(1 for r in self.report if not r["quantized"])

    @property
    def f32_weight_bytes(self) -> int:
        """f32 bytes of the QUANTIZED leaves only — the honest numerator
        of the reduction ratio (skipped leaves move the same bytes either
        way)."""
        return sum(r["f32_bytes"] for r in self.report if r["quantized"])

    @property
    def quantized_weight_bytes(self) -> int:
        """int8 + scale bytes of the quantized leaves."""
        return sum(
            r["quant_bytes"] for r in self.report if r["quantized"]
        )

    @property
    def total_weight_bytes(self) -> int:
        """Resident backbone weight bytes of the quantized program
        (quantized leaves as int8+scales, skipped leaves as f32)."""
        return sum(r["quant_bytes"] for r in self.report)

    @property
    def total_f32_bytes(self) -> int:
        """Resident backbone weight bytes of the f32 program."""
        return sum(r["f32_bytes"] for r in self.report)

    def fingerprint(self) -> str:
        """Content hash over the quantized tensors + scales (the analogue
        of the GMM fingerprint for the quantized weight constants)."""
        h = hashlib.sha256()
        for entry in self.entries:
            if entry[0] == "int8":
                _, q, scale = entry
                h.update(np.ascontiguousarray(q).tobytes())
                h.update(np.ascontiguousarray(scale).tobytes())
        return h.hexdigest()

    def quant_config(self) -> Dict[str, Any]:
        """The meta.json provenance block (and the mismatch-detection
        identity: `tag` is what calibrations are stamped with and what
        the AOT cache key carries)."""
        return {
            "format": QUANT_FORMAT,
            "mode": self.policy.mode,
            "granularity": self.policy.granularity,
            "symmetric": self.policy.symmetric,
            "tag": self.policy.tag,
            "num_quantized": self.num_quantized,
            "num_skipped": self.num_skipped,
            "f32_weight_bytes": int(self.f32_weight_bytes),
            "quantized_weight_bytes": int(self.quantized_weight_bytes),
            "total_weight_bytes": int(self.total_weight_bytes),
            "total_f32_bytes": int(self.total_f32_bytes),
            "fingerprint": self.fingerprint(),
        }


def quantize_params(params, policy: Optional[QuantPolicy] = None):
    """Quantize a trunk params pytree under `policy` (default int8).

    Host-side numpy — runs once at export time. Returns QuantizedParams;
    with mode "none" every leaf is a skipped f32 entry (materialize() is
    then the identity, which is what makes `--quantize none` byte-exact)."""
    import jax

    policy = policy or QuantPolicy(mode="int8")
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(
        params
    )
    entries: List[Tuple] = []
    report: List[Dict[str, Any]] = []
    for key_path, leaf in leaves_with_paths:
        path = tuple(
            getattr(k, "key", getattr(k, "name", getattr(k, "idx", k)))
            for k in key_path
        )
        arr = np.asarray(leaf)
        f32_bytes = int(arr.size * 4)
        if policy.quantized and _is_quantizable(path, arr):
            q, scale = quantize_array(arr)
            entries.append(("int8", q, scale))
            report.append({
                "path": "/".join(str(p) for p in path),
                "shape": list(arr.shape),
                "quantized": True,
                "f32_bytes": f32_bytes,
                "quant_bytes": int(q.nbytes + scale.nbytes),
            })
        else:
            entries.append(("f32", np.asarray(leaf)))
            report.append({
                "path": "/".join(str(p) for p in path),
                "shape": list(arr.shape),
                "quantized": False,
                "f32_bytes": f32_bytes,
                "quant_bytes": f32_bytes,
            })
    return QuantizedParams(
        policy=policy,
        treedef=treedef,
        entries=tuple(entries),
        report=tuple(report),
    )


def weight_bytes_report(params) -> Dict[str, int]:
    """Shape-math weight bytes (works on ShapeDtypeStructs — no values
    needed): what the trunk's weights cost resident as f32 vs as
    int8+per-channel-scales. The planner's quant model
    (perf/planner.py::state_bytes_per_chip) rides on this."""
    import jax

    leaves_with_paths, _ = jax.tree_util.tree_flatten_with_path(params)
    f32_total = 0
    int8_total = 0
    for key_path, leaf in leaves_with_paths:
        path = tuple(
            getattr(k, "key", getattr(k, "name", getattr(k, "idx", k)))
            for k in key_path
        )
        shape = tuple(getattr(leaf, "shape", ()))
        n = int(np.prod(shape)) if shape else 1
        f32_bytes = n * 4
        f32_total += f32_bytes
        if _is_quantizable(path, leaf):
            out_ch = int(shape[-1])
            int8_total += n * 1 + out_ch * 4
        else:
            int8_total += f32_bytes
    return {"f32_bytes": int(f32_total), "int8_bytes": int(int8_total)}
