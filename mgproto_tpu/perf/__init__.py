"""Performance planning: compiled-module memory models, the HBM-budget
auto-tuner (`--auto_tune`), and the mixed-precision policy. See
perf/planner.py and perf/precision.py."""

from mgproto_tpu.perf.precision import (  # noqa: F401
    PrecisionError,
    PrecisionPolicy,
    assert_f32_stats,
    policy_meta,
    resolve_policy,
)
from mgproto_tpu.perf.planner import (  # noqa: F401
    HBMPlanner,
    PlanCandidate,
    PlanOutcome,
    PlanReport,
    apply_plan,
    autotune,
    candidate_plans,
    default_budget_bytes,
)
