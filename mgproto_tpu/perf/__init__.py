"""Performance planning: compiled-module memory models and the HBM-budget
auto-tuner (`--auto_tune`). See perf/planner.py."""

from mgproto_tpu.perf.planner import (  # noqa: F401
    HBMPlanner,
    PlanCandidate,
    PlanOutcome,
    PlanReport,
    apply_plan,
    autotune,
    candidate_plans,
    default_budget_bytes,
)
