"""PrecisionPolicy: the one statement of what runs in which dtype.

The mixed-precision flagship (ROADMAP item 2, ISSUE 12) runs the backbone
TRUNK — convs, BatchNorm apply, add-on 1x1s, and therefore the whole
backward through them — in `compute_dtype=bfloat16`, halving the trunk's
activation/gradient HBM traffic (the 43.7% HBM-bound stall budget in
evidence/stall_report_b256.json is almost entirely trunk bytes). Everything
whose ABSOLUTE SCALE carries meaning stays float32:

  * master params + optimizer moments (flax param_dtype default; optax
    states follow the params),
  * BatchNorm batch statistics (flax computes them in f32 regardless of
    the module dtype) and running stats,
  * the EM sufficient statistics and the [C, cap, d] memory bank
    (core/em.py, core/memory.py — a bf16 bank would quantize the very
    features the mixture is fit to),
  * density math and log p(x) scores (ops/gaussian.py pins f32 +
    HIGHEST matmul precision; OoD thresholds ride on the p(x) scale,
    SURVEY.md §7.3.5),
  * serving calibration thresholds (host-side float64).

This module is the policy's single home: `resolve_policy` derives it from a
Config, `policy_meta` is the provenance block recorded in telemetry meta
and in exported-artifact `meta.json` (the serving TrustGate fails closed on
a dtype mismatch the same way it does on a GMM-fingerprint mismatch), and
`assert_f32_stats` is the trace-time guard the EM/bank entry points call so
a future refactor cannot silently demote the f32-statistics invariant
(scripts/check_dtype_discipline.py enforces the same invariant statically).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

SUPPORTED_COMPUTE_DTYPES = ("float32", "bfloat16")

# dtypes that must never appear in EM statistics / bank / calibration math
HALF_DTYPES = ("bfloat16", "float16")


class PrecisionError(TypeError):
    """A tensor violated the precision policy's f32-statistics invariant."""


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """What runs in which dtype. Only `compute_dtype` is a knob; the f32
    fields are stated (not configurable) because the system's correctness
    arguments depend on them — they are recorded so artifacts and
    telemetry carry the full story, and so a future knob would have to
    touch this type (and its assertions) explicitly."""

    compute_dtype: str = "float32"  # trunk activations AND their gradients
    param_dtype: str = "float32"  # master params + optimizer moments
    stats_dtype: str = "float32"  # EM sufficient stats, bank, BN stats
    score_dtype: str = "float32"  # density / log p(x) / calibration math

    def __post_init__(self):
        if self.compute_dtype not in SUPPORTED_COMPUTE_DTYPES:
            raise ValueError(
                f"compute_dtype must be one of {SUPPORTED_COMPUTE_DTYPES}, "
                f"got {self.compute_dtype!r}"
            )
        for field in ("param_dtype", "stats_dtype", "score_dtype"):
            if getattr(self, field) != "float32":
                raise ValueError(
                    f"{field} is not a knob: it must stay float32 "
                    f"(got {getattr(self, field)!r}); see module docstring"
                )

    @property
    def mixed(self) -> bool:
        return self.compute_dtype != "float32"


def resolve_policy(cfg) -> PrecisionPolicy:
    """The policy a Config implies (cfg.model.compute_dtype is the knob)."""
    return PrecisionPolicy(compute_dtype=cfg.model.compute_dtype)


def policy_meta(policy: PrecisionPolicy) -> Dict[str, Any]:
    """Provenance block for telemetry meta.json and exported artifacts."""
    return {
        "compute_dtype": policy.compute_dtype,
        "param_dtype": policy.param_dtype,
        "stats_dtype": policy.stats_dtype,
        "score_dtype": policy.score_dtype,
        "mixed": policy.mixed,
    }


def is_half_dtype(dtype: Any) -> bool:
    """True for bf16/f16 in any spelling (str, np/jnp dtype, scalar type)."""
    try:
        import numpy as np

        name = np.dtype(dtype).name
    except TypeError:
        name = str(dtype)
    return name in HALF_DTYPES


def assert_f32_stats(x: Any, what: str) -> Any:
    """Trace-time guard: raise PrecisionError if a statistics tensor is
    half-precision. Called at the EM/bank entry points (core/em.py) on the
    tensors the f32-statistics invariant protects; a static python check,
    so it costs nothing in the compiled program. Returns `x` unchanged."""
    dtype = getattr(x, "dtype", None)
    if dtype is not None and is_half_dtype(dtype):
        raise PrecisionError(
            f"{what} is {dtype} but the precision policy pins EM/bank/"
            "score statistics to float32 (perf/precision.py): a half-"
            "precision statistic silently shifts the p(x) scale every "
            "calibrated threshold depends on"
        )
    return x
