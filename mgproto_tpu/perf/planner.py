"""HBM-budget auto-tuner: pick (batch, remat, prefetch, augment, async_bank,
compute_dtype) from a memory model instead of by DNF.

The batch-512 DNF (PERF.md "MFU headroom") and the hand-curated sweep showed
run sizing was still trial-and-error: a config either fit the chip's HBM or
died on the relay with nothing learned. Following "Memory Safe Computations
with XLA Compiler" (PAPERS.md), this module turns sizing into a solved
problem: for each candidate plan it compiles the EXACT production step
program(s) and reads XLA's compiled-module memory analysis — the same
machinery `scripts/perf_model.py` and `bench.py --measure em/overlap`
already use — then selects the largest plan that fits the device budget
with a configurable margin.

Peak model per candidate (`PlanReport.detail` carries the breakdown):

    peak = program peak (arguments + outputs + temps - donation aliasing,
           summed over the trunk+bank programs when async_bank — the two
           can be resident together)
         + prefetch headroom: prefetch_depth x batch_bytes (PERF.md lever
           2 — each in-flight batch is HBM the step never sees; ~154 MB
           per unit at f32 batch 256, a quarter of that under the uint8
           wire format)

Donation matters twice: the bank program's `alias_size_in_bytes` is the
[C, cap, d] bank + EM state it updates in place (engine/train.py), and the
monolithic step aliases the whole TrainState — the model charges aliased
bytes once, like the runtime does.

Budget resolution order: explicit argument > MGPROTO_HBM_BUDGET_BYTES env >
the device's own `memory_stats()['bytes_limit']` > a 16 GiB v5e-class
default (the CPU backend has no device budget — `--auto_tune` still plans
there, which is exactly how the unit tests and a laptop dry-run use it).
The safety margin defaults to 8% and is overridable via MGPROTO_HBM_MARGIN.

`measure` is injectable so tests (and future analytic models) can replace
the compile with a simulation; the default compiles through
`engine.train.Trainer`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

DEFAULT_BUDGET_BYTES = 16 * 1024**3  # v5e-class HBM
BUDGET_ENV = "MGPROTO_HBM_BUDGET_BYTES"
MARGIN_ENV = "MGPROTO_HBM_MARGIN"
DEFAULT_MARGIN = 0.08

# backbone families whose stages accept selective remat (models/common.py
# validates stage names; other archs get no remat candidates)
_REMAT_ARCH_PREFIXES = ("resnet", "densenet")


@dataclasses.dataclass(frozen=True)
class PlanCandidate:
    """One (batch, remat, prefetch, augment, async_bank, dtype) tuple under
    consideration. `batch` is the GLOBAL train batch size.

    `compute_dtype` is the dtype axis (ISSUE 12): "" inherits the base
    config's compute dtype; "bfloat16"/"float32" override it. On TPU the
    compiled-module measurement then sees bf16's halved activation bytes
    directly — which is what finally lets `fused_b512_remat_l1` fit the
    v5e budget (the batch-512 DNF, PERF.md). NOTE the CPU backend cannot
    measure this axis (XLA float normalization rewrites bf16 programs to
    f32-with-converts), so off-TPU the bf16 candidates predict ~f32 peaks
    — conservative, never unsafe."""

    batch: int
    remat_stages: Tuple[str, ...] = ()
    prefetch_depth: int = 2
    device_augment: bool = False
    async_bank: bool = False
    compute_dtype: str = ""  # "" = the base config's dtype

    @property
    def name(self) -> str:
        parts = [f"b{self.batch}"]
        if self.remat_stages:
            parts.append("remat_" + "+".join(self.remat_stages))
        parts.append(f"pf{self.prefetch_depth}")
        if self.device_augment:
            parts.append("u8")
        if self.async_bank:
            parts.append("async")
        if self.compute_dtype:
            parts.append(
                "bf16" if self.compute_dtype == "bfloat16"
                else self.compute_dtype
            )
        return "_".join(parts)


@dataclasses.dataclass(frozen=True)
class PlanReport:
    """One measured candidate: predicted peak bytes vs the effective
    budget, plus the breakdown (telemetry meta records all of these)."""

    candidate: PlanCandidate
    peak_bytes: int
    fits: bool
    detail: Dict[str, int]
    error: str = ""

    def to_meta(self) -> Dict:
        return {
            "name": self.candidate.name,
            "batch": self.candidate.batch,
            "remat_stages": list(self.candidate.remat_stages),
            "prefetch_depth": self.candidate.prefetch_depth,
            "device_augment": self.candidate.device_augment,
            "async_bank": self.candidate.async_bank,
            "compute_dtype": self.candidate.compute_dtype,
            "peak_bytes": int(self.peak_bytes),
            "fits": bool(self.fits),
            # the weak-scaling per-chip sharded-state measure (ISSUE 14):
            # ride into the meta.json "autotune" block so the telemetry
            # gauges of the same names have a recorded provenance
            **{
                k: int(self.detail[k])
                for k in (
                    "bank_bytes_per_chip", "opt_bytes_per_chip",
                    "param_bytes_per_chip",
                )
                if k in self.detail
            },
            **({"error": self.error} if self.error else {}),
        }


@dataclasses.dataclass(frozen=True)
class PlanOutcome:
    chosen: Optional[PlanReport]
    reports: Tuple[PlanReport, ...]
    budget_bytes: int
    margin: float

    @property
    def rejected(self) -> int:
        return sum(1 for r in self.reports if not r.fits)

    def to_meta(self) -> Dict:
        """The telemetry meta.json "autotune" record: the chosen plan plus
        every candidate's predicted peak, so a DNF is a read, not a rerun."""
        return {
            "plan": self.chosen.to_meta() if self.chosen else None,
            "budget_bytes": int(self.budget_bytes),
            "margin": self.margin,
            "rejected": self.rejected,
            "candidates": [r.to_meta() for r in self.reports],
        }


def default_budget_bytes() -> Tuple[int, str]:
    """(budget bytes, source) — env override, else the device's own limit,
    else the v5e-class default (CPU backends report no bytes_limit)."""
    raw = os.environ.get(BUDGET_ENV)
    if raw:
        return int(raw), "env"
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return int(limit), "device"
    except Exception:  # no backend / no stats: fall through to the default
        pass
    return DEFAULT_BUDGET_BYTES, "default"


def resolve_margin(margin: Optional[float] = None) -> float:
    if margin is not None:
        return float(margin)
    raw = os.environ.get(MARGIN_ENV)
    if raw:
        return float(raw)
    return DEFAULT_MARGIN


def batch_bytes(
    batch: int, img_size: int, device_augment: bool
) -> int:
    """Host->device bytes of one train batch: images (uint8 wire under
    device_augment, f32 otherwise) + labels + augmentation seeds."""
    px = batch * img_size * img_size * 3
    images = px if device_augment else px * 4
    return images + batch * 4 + batch * 4  # + int32 labels + uint32 seeds


def _program_peak(compiled) -> Tuple[int, Dict[str, int]]:
    """Peak resident bytes of one compiled program from XLA's memory
    analysis: arguments + outputs + temps, minus donation aliasing (an
    aliased output IS its argument buffer — charging both would bill the
    donated TrainState twice)."""
    ma = compiled.memory_analysis()
    args = int(getattr(ma, "argument_size_in_bytes", 0) or 0)
    out = int(getattr(ma, "output_size_in_bytes", 0) or 0)
    temp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
    alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
    peak = max(args + out + temp - alias, 0)
    return peak, {
        "argument_bytes": args,
        "output_bytes": out,
        "temp_bytes": temp,
        "alias_bytes": alias,
    }


def plan_config(base_cfg, cand: PlanCandidate):
    """`base_cfg` with the candidate's knobs applied (the same projection
    `apply_plan` uses, shared so measurement and application can't drift)."""
    data = dataclasses.replace(
        base_cfg.data,
        train_batch_size=cand.batch,
        prefetch_depth=cand.prefetch_depth,
        device_augment=cand.device_augment,
    )
    model = dataclasses.replace(
        base_cfg.model, remat_stages=tuple(cand.remat_stages)
    )
    if cand.compute_dtype:
        model = dataclasses.replace(model, compute_dtype=cand.compute_dtype)
    em = dataclasses.replace(base_cfg.em, async_bank=cand.async_bank)
    return base_cfg.replace(data=data, model=model, em=em)


apply_plan = plan_config  # the public name run_training uses


def data_axis_size(cfg) -> int:
    """Devices on the mesh's data axis for this config."""
    import jax

    n_model = max(int(cfg.mesh.model), 1)
    if cfg.mesh.data == -1:
        return max(jax.device_count() // n_model, 1)
    return max(int(cfg.mesh.data), 1)


def batch_shard_size(cfg) -> int:
    """Devices one GLOBAL batch splits over — the divisor that turns a
    candidate batch into the per-chip batch one device materializes. Since
    the weak-scaling layout (parallel/sharding.py batch_spec) batch rows
    shard over BOTH mesh axes, so the divisor is the whole mesh."""
    return data_axis_size(cfg) * max(int(cfg.mesh.model), 1)


def state_bytes_per_chip(
    cfg, model_size: Optional[int] = None, state=None,
    quant_mode: str = "",
) -> Dict[str, int]:
    """Per-chip bytes of the sharded TrainState groups under the
    weak-scaling layout (parallel/sharding.py state_partition_specs) —
    pure shape math over an eval_shape state, no device work:

      bank_bytes_per_chip  — the [C, cap, d] memory bank + bookkeeping
      opt_bytes_per_chip   — Adam moments: joint + warm + EM-mean trees
      param_bytes_per_chip — master f32 params (per-param map)

    These are the telemetry gauges of the same names (ISSUE 14), the
    planner candidate detail, and the raw numbers `bench.py --measure
    weakscale` cross-checks against live shard shapes.

    `state` (a TrainState-shaped pytree of arrays or ShapeDtypeStructs)
    skips the eval_shape — callers that already traced one
    (measure_candidate per candidate) pass it instead of re-tracing.

    `quant_mode="int8"` (ISSUE 20, serving only) models the params group
    as int8 weight-only quantized (perf/quant.py::weight_bytes_report
    shape math over the same eval_shape params: 1 byte/elem + a per-
    output-channel f32 scale vector on the quantizable kernels, f32 on
    everything else). The f32 figure stays in the result as
    `param_bytes_per_chip_f32`, and `quant_mode` is echoed, so the
    planner's predicted per-replica HBM drop is auditable from the one
    dict."""
    import jax

    from mgproto_tpu.parallel.sharding import (
        state_partition_specs,
        tree_bytes_per_chip,
    )

    m = max(int(cfg.mesh.model), 1) if model_size is None else int(model_size)
    if state is None:
        from mgproto_tpu.core.state import create_train_state

        state = jax.eval_shape(
            lambda rng: create_train_state(
                cfg, 100, rng, for_restore=True
            )[0],
            jax.random.PRNGKey(0),
        )
    specs = state_partition_specs(state, cfg.model.num_classes, m)

    def group(*fields):
        return sum(
            tree_bytes_per_chip(getattr(state, f), getattr(specs, f), m)
            for f in fields
        )

    out = {
        "bank_bytes_per_chip": group("memory"),
        "opt_bytes_per_chip": group(
            "opt_state", "warm_opt_state", "proto_opt_state"
        ),
        "param_bytes_per_chip": group("params"),
    }
    if quant_mode == "int8":
        from mgproto_tpu.perf.quant import weight_bytes_report

        # params are replicated under the serving layout, so the per-chip
        # figure scales by the same int8/f32 byte ratio as the whole tree
        rep = weight_bytes_report(state.params)
        f32 = out["param_bytes_per_chip"]
        out["param_bytes_per_chip_f32"] = f32
        out["param_bytes_per_chip"] = (
            int(round(f32 * rep["int8_bytes"] / rep["f32_bytes"]))
            if rep["f32_bytes"] else f32
        )
        out["quant_mode"] = quant_mode
    return out


def lower_split_programs(trainer, state, images, labels, seeds, use_mine,
                         update_gmm):
    """Lower (NOT compile) the async pipeline's two programs for one
    operand set. The ONE definition of the trunk/bank lowering (bench.py
    --measure overlap and measure_candidate both use it, so a signature
    change in either program cannot leave one caller silently measuring
    the wrong thing). Returns (trunk_lowered, bank_lowered); callers
    `.compile()` each — separately, so per-program compile time stays
    attributable."""
    import jax
    import jax.numpy as jnp

    from mgproto_tpu.core.state import split_state

    trunk, bank = split_state(state)
    trunk_lowered = trainer._trunk_jit.lower(
        trunk, bank.gmm, images, labels, seeds, use_mine, warm=False
    )
    _, out_shape = jax.eval_shape(
        lambda *a: trainer._trunk_step(*a, warm=False),
        trunk, bank.gmm, images, labels, seeds, use_mine,
    )
    enq = tuple(
        jax.ShapeDtypeStruct(s.shape, s.dtype)
        for s in (
            out_shape.enq_feats, out_shape.enq_classes, out_shape.enq_valid
        )
    )
    bank_lowered = trainer._bank_jit.lower(
        bank, *enq, state.step, update_gmm, jnp.asarray(True)
    )
    return trunk_lowered, bank_lowered


def measure_candidate(base_cfg, cand: PlanCandidate) -> Tuple[int, Dict]:
    """Default measurement: compile the candidate's ACTUAL step program(s)
    (trunk + bank when async, the monolithic step otherwise) via the
    production Trainer and read the compiled-module memory analysis, then
    add the prefetch-depth headroom. Returns (peak_bytes, detail).

    PER-CHIP model: the candidate batch is GLOBAL, but HBM is a per-chip
    resource — the program is compiled at the per-chip batch share
    (global / data-axis size) with the full replicated state, which is
    what one device actually holds under the production ShardedTrainer's
    data-parallel layout. Class-sharded state (mesh.model > 1) is charged
    unsharded — a deliberate conservative over-count of the bank shard."""
    import jax
    import jax.numpy as jnp

    from mgproto_tpu.core.memory import memory_nbytes
    from mgproto_tpu.engine.train import Trainer

    cfg = plan_config(base_cfg, cand)
    trainer = Trainer(cfg, steps_per_epoch=100, donate=True)
    n_model = max(int(cfg.mesh.model), 1)
    # shapes only: lowering accepts ShapeDtypeStructs, so no candidate ever
    # allocates a real state (or loads pretrained weights — for_restore
    # skips that too, and eval_shape never runs the init anyway)
    state = jax.eval_shape(
        lambda rng: trainer.init_state(rng, for_restore=True),
        jax.random.PRNGKey(0),
    )
    m = cfg.model
    per_chip = max(cand.batch // batch_shard_size(cfg), 1)
    img_dtype = jnp.uint8 if trainer._device_augment else jnp.float32
    images = jax.ShapeDtypeStruct(
        (per_chip, m.img_size, m.img_size, 3), img_dtype
    )
    labels = jax.ShapeDtypeStruct((per_chip,), jnp.int32)
    seeds = jax.ShapeDtypeStruct((per_chip,), jnp.uint32)
    use_mine = jnp.asarray(1.0, jnp.float32)
    update_gmm = jnp.asarray(True, bool)

    detail: Dict[str, int] = {"per_chip_batch": per_chip}
    if trainer.async_bank:
        trunk_lowered, bank_lowered = lower_split_programs(
            trainer, state, images, labels, seeds, use_mine, update_gmm
        )
        t_peak, t_detail = _program_peak(trunk_lowered.compile())
        b_peak, b_detail = _program_peak(bank_lowered.compile())
        # both programs can be resident at once — that is the point of the
        # pipeline — so their peaks add
        program_peak = t_peak + b_peak
        detail["trunk_peak_bytes"] = t_peak
        detail["bank_peak_bytes"] = b_peak
        detail.update({f"trunk_{k}": v for k, v in t_detail.items()})
        detail.update({f"bank_{k}": v for k, v in b_detail.items()})
    else:
        program_peak, p_detail = _program_peak(
            trainer._train_step.lower(
                state, images, labels, seeds, use_mine, update_gmm,
                warm=False,
            ).compile()
        )
        detail.update(p_detail)

    prefetch = cand.prefetch_depth * batch_bytes(
        per_chip, m.img_size, trainer._device_augment
    )
    detail["program_peak_bytes"] = int(program_peak)
    detail["prefetch_headroom_bytes"] = int(prefetch)
    # analytic cross-check of the dominant bank buffer (one generation
    # live under donation): visible in the detail so a memory_analysis
    # regression on a new backend is a read, not a mystery
    detail["bank_bytes_analytic"] = memory_nbytes(
        m.num_classes, m.mem_capacity, m.proto_dim
    )
    # per-chip sharded-state accounting (ISSUE 14): what one chip actually
    # holds of the bank / optimizer moments / master params under the
    # weak-scaling layout — the bank_bytes_per_chip / opt_bytes_per_chip
    # telemetry gauges and the `check --weakscale` raw numbers. The
    # compiled-module peak above still charges class-sharded state
    # unsharded (a deliberate conservative over-count); these fields are
    # the sharded truth beside it. Reuses the shape state already traced
    # above — no second eval_shape per candidate.
    detail.update(state_bytes_per_chip(cfg, n_model, state=state))
    return int(program_peak + prefetch), detail


def make_cached_measure(base_cfg) -> Callable:
    """The default `autotune` measure: `measure_candidate` memoized on the
    program identity (batch, remat, augment, async). Candidates that differ
    ONLY in prefetch_depth compile the same program — their peaks differ by
    pure arithmetic (prefetch_depth x per-chip batch bytes) — so the
    prefetch ladder in `candidate_plans` costs zero extra compiles."""
    import dataclasses as _dc

    cache: Dict[Tuple, Tuple[int, Dict]] = {}

    def measure(cand: PlanCandidate) -> Tuple[int, Dict]:
        key = (
            cand.batch, tuple(cand.remat_stages),
            cand.device_augment, cand.async_bank, cand.compute_dtype,
        )
        if key not in cache:
            cache[key] = measure_candidate(
                base_cfg, _dc.replace(cand, prefetch_depth=0)
            )
        peak0, det0 = cache[key]
        if cand.prefetch_depth <= 0:
            return peak0, det0
        prefetch = cand.prefetch_depth * batch_bytes(
            det0["per_chip_batch"], base_cfg.model.img_size,
            cand.device_augment,
        )
        detail = dict(det0, prefetch_headroom_bytes=int(prefetch))
        return int(det0["program_peak_bytes"] + prefetch), detail

    return measure


class HBMPlanner:
    """Selects the largest candidate whose predicted peak fits
    budget * (1 - margin).

    Preference order: larger batch first (throughput — the measured sweep
    climbs monotonically to the HBM cliff, PERF.md), then fewer remat
    stages (less recompute), then deeper prefetch. A candidate whose
    measurement RAISES is treated as over-budget (that is the compile-time
    analogue of the DNF this planner exists to prevent) and reported with
    the error string.
    """

    def __init__(
        self,
        budget_bytes: Optional[int] = None,
        margin: Optional[float] = None,
        measure: Optional[Callable] = None,
        log: Optional[Callable[[str], None]] = None,
    ):
        if budget_bytes is None:
            budget_bytes, self.budget_source = default_budget_bytes()
        else:
            self.budget_source = "explicit"
        self.budget_bytes = int(budget_bytes)
        self.margin = resolve_margin(margin)
        self._measure = measure
        self._log = log or (lambda s: None)

    @property
    def effective_budget(self) -> int:
        return int(self.budget_bytes * (1.0 - self.margin))

    def plan(
        self, base_cfg, candidates: Sequence[PlanCandidate]
    ) -> PlanOutcome:
        measure = self._measure or make_cached_measure(base_cfg)
        reports: List[PlanReport] = []
        for cand in candidates:
            try:
                measured = measure(cand)
                peak, detail = (
                    measured if isinstance(measured, tuple)
                    else (int(measured), {})
                )
                err = ""
            except Exception as e:  # compile/measure failure == does not fit
                peak, detail, err = 0, {}, f"{type(e).__name__}: {e}"
            fits = not err and peak <= self.effective_budget
            reports.append(PlanReport(
                candidate=cand, peak_bytes=int(peak), fits=fits,
                detail=detail, error=err,
            ))
            self._log(
                f"autotune: {cand.name} peak={peak / 1e9:.2f} GB "
                f"{'fits' if fits else 'REJECTED'}"
                + (f" ({err})" if err else "")
            )
        fitting = [r for r in reports if r.fits]
        chosen = max(
            fitting,
            key=lambda r: (
                r.candidate.batch,
                # at equal batch, keep the run's own numerics: a dtype
                # override (the bf16 axis) wins only when it is what makes
                # a LARGER batch fit — the auto-tuner must never flip
                # training numerics for free
                not r.candidate.compute_dtype,
                -len(r.candidate.remat_stages),
                r.candidate.prefetch_depth,
            ),
            default=None,
        )
        return PlanOutcome(
            chosen=chosen,
            reports=tuple(reports),
            budget_bytes=self.budget_bytes,
            margin=self.margin,
        )


def candidate_plans(
    cfg,
    batches: Optional[Sequence[int]] = None,
    device_augment: Optional[bool] = None,
    async_bank: Optional[bool] = None,
    dtypes: Optional[Sequence[str]] = None,
) -> List[PlanCandidate]:
    """The default candidate ladder for a base config: the configured batch
    and its 2x/4x, each with the configured remat plus — for rematable
    backbones — the layer1-only selective variant that resolved the
    batch-512 DNF hypothesis (PERF.md lever 3), and each additionally at
    prefetch_depth 0 (the no-headroom operating point device_prefetch
    supports; FREE to evaluate — same compiled program, different
    arithmetic, see make_cached_measure — and the tie-break prefers deeper
    prefetch, so pf0 only wins when the headroom is what did not fit).
    Augment/async default to the config's own resolution so the plan
    measures what the run will actually execute.

    `dtypes` is the opt-in dtype axis (ISSUE 12): each extra entry (e.g.
    "bfloat16") re-emits the whole ladder under that compute dtype. It is
    OPT-IN (`--auto_tune` alone never changes training numerics): pass it
    explicitly or set MGPROTO_AUTOTUNE_DTYPES=bfloat16. The tie-break in
    HBMPlanner.plan prefers the config's own dtype at equal batch, so a
    dtype override is chosen only when it buys a strictly larger batch —
    the `fused_b512_remat_l1` resolution path."""
    import jax

    b0 = cfg.data.train_batch_size * jax.process_count()
    batches = list(batches) if batches else [b0, 2 * b0, 4 * b0]
    if device_augment is None:
        from mgproto_tpu.ops.augment import resolve_device_augment

        device_augment = resolve_device_augment(cfg.data.device_augment)
    if async_bank is None:
        from mgproto_tpu.engine.train import resolve_async_bank

        async_bank = resolve_async_bank(cfg.em.async_bank)
    remat_options: List[Tuple[str, ...]] = [tuple(cfg.model.remat_stages)]
    if (
        cfg.model.arch.startswith(_REMAT_ARCH_PREFIXES)
        and not cfg.model.remat
    ):
        l1 = ("denseblock1",) if "densenet" in cfg.model.arch else ("layer1",)
        if l1 not in remat_options:
            remat_options.append(l1)
    prefetch_options = sorted({int(cfg.data.prefetch_depth), 0},
                              reverse=True)
    if dtypes is None:
        raw = os.environ.get("MGPROTO_AUTOTUNE_DTYPES", "")
        dtypes = tuple(s.strip() for s in raw.split(",") if s.strip())
    # "" = the config's own dtype, always first; an override equal to the
    # config's dtype would compile the identical program twice — drop it
    dtype_options = [""] + [
        d for d in dtypes if d and d != cfg.model.compute_dtype
    ]
    out: List[PlanCandidate] = []
    for b in sorted(set(batches)):
        for dt in dtype_options:
            for stages in remat_options:
                for pf in prefetch_options:
                    out.append(PlanCandidate(
                        batch=int(b),
                        remat_stages=stages,
                        prefetch_depth=pf,
                        device_augment=bool(device_augment),
                        async_bank=bool(async_bank),
                        compute_dtype=dt,
                    ))
    return out


def plan_serve_buckets(
    engine,
    budget_bytes: Optional[int] = None,
    margin: Optional[float] = None,
    measure: Optional[Callable] = None,
    log: Optional[Callable[[str], None]] = None,
    weight_bytes: int = 0,
) -> Tuple[List[int], PlanOutcome]:
    """`mgproto-serve --auto_tune`: size the warmup bucket set from the
    same memory model. Each requested bucket's serving program is lowered
    and its compiled-module peak read; buckets over budget are dropped
    BEFORE warmup would OOM compiling them. Returns (fitting bucket sizes,
    outcome). No prefetch headroom — serving holds one batch.

    `weight_bytes` (ISSUE 20): resident bytes of the artifact's baked
    weight constants, added to every bucket's measured program peak.
    XLA's compiled-module memory analysis counts live buffers, not
    constants baked into the program, so the weight-resident term must be
    modeled explicitly — pass the artifact's quant_config
    total_weight_bytes (int8) or total_f32_bytes (f32) and the bucket
    ladder honestly grows when the backbone shrinks 4x. Each report's
    detail records both terms (program_peak_bytes / weight_resident_bytes)
    so the split stays auditable.

    Known cost: the planning compile is AOT and does not populate the
    engine's jit dispatch cache, so warmup recompiles the fitting buckets
    (~2x serve startup compile). That is the price of refusing to execute
    a predicted OOM; skip --auto_tune on a device you know fits."""
    import numpy as np

    def bucket_measure(cand: PlanCandidate):
        zeros = np.zeros(
            (cand.batch, engine.img_size, engine.img_size, 3), np.float32
        )
        return _program_peak(engine._jit.lower(zeros).compile())

    inner = measure or bucket_measure

    def with_weights(cand: PlanCandidate):
        measured = inner(cand)
        peak, detail = (
            measured if isinstance(measured, tuple) else (int(measured), {})
        )
        detail = dict(
            detail,
            program_peak_bytes=int(peak),
            weight_resident_bytes=int(weight_bytes),
        )
        return int(peak) + int(weight_bytes), detail

    planner = HBMPlanner(
        budget_bytes=budget_bytes, margin=margin,
        measure=with_weights, log=log,
    )
    cands = [
        PlanCandidate(batch=int(b), prefetch_depth=0)
        for b in sorted(engine.buckets)
    ]
    outcome = planner.plan(None, cands)
    fitting = [r.candidate.batch for r in outcome.reports if r.fits]
    return fitting, outcome


def autotune(
    cfg,
    budget_bytes: Optional[int] = None,
    margin: Optional[float] = None,
    candidates: Optional[Sequence[PlanCandidate]] = None,
    measure: Optional[Callable] = None,
    log: Optional[Callable[[str], None]] = None,
):
    """One-call driver for `--auto_tune`: build candidates, plan, apply.
    Returns (possibly-updated cfg, PlanOutcome). When no candidate fits
    (a genuinely undersized device), the base config is returned unchanged
    so the run proceeds exactly as hand-configured — with the rejection
    trail in telemetry instead of an OOM at first step."""
    planner = HBMPlanner(
        budget_bytes=budget_bytes, margin=margin, measure=measure, log=log
    )
    cands = (
        list(candidates) if candidates is not None else candidate_plans(cfg)
    )
    outcome = planner.plan(cfg, cands)
    if outcome.chosen is None:
        return cfg, outcome
    chosen = outcome.chosen.candidate
    import jax

    # candidate batches are GLOBAL; DataConfig batch sizes are per-process
    per_process = dataclasses.replace(
        chosen, batch=max(chosen.batch // max(jax.process_count(), 1), 1)
    )
    return apply_plan(cfg, per_process), outcome
