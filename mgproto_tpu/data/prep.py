"""Offline dataset preparation.

Reference: preprocess_data/{cropimages,cropimages_cars,cropmasks,
preprocess_mask,img_aug,img_aug_cars,img_pets}.py — seven hard-coded-path
scripts. Here each is a parameterized function behind `cli.prep`.

Differences by design: crops are written to NEW trees (the reference
OVERWRITES its source images in place, cropimages.py:24-27 — destructive and
unrepeatable); offline augmentation reimplements the reference's four
Augmentor pipelines (img_aug.py:23-50: rotate/skew/shear/grid-distortion,
each x10 with 50% h-flip) in PIL+numpy, seeded and deterministic.
"""

from __future__ import annotations

import os
import shutil
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np
from PIL import Image

BICUBIC = Image.Resampling.BICUBIC
BILINEAR = Image.Resampling.BILINEAR


# ------------------------------------------------------------------ CUB crop
def _load_cub_index(cub_root: str):
    """([(img_id, rel_path)...], img_id -> float bbox, img_id -> is_train)
    from the CUB txts — one shared parser with the parts tables
    (data/cub_parts.py)."""
    from mgproto_tpu.data.cub_parts import (
        read_bounding_boxes,
        read_images_txt,
        read_train_test_split,
    )

    return (
        read_images_txt(cub_root),
        read_bounding_boxes(cub_root),
        read_train_test_split(cub_root),
    )


def crop_cub(
    cub_root: str, out_root: str, quality: int = 95, limit: Optional[int] = None
) -> Tuple[int, int]:
    """Bbox-crop every CUB image into out_root/{train,test}_cropped/<class>/
    (reference cropimages.py semantics, non-destructive). Returns
    (n_train, n_test)."""
    names, boxes, split = _load_cub_index(cub_root)
    counts = [0, 0]
    for img_id, rel in names[: limit if limit else len(names)]:
        x, y, w, h = boxes[img_id]
        dest = "train_cropped" if split[img_id] == 1 else "test_cropped"
        out_path = os.path.join(out_root, dest, rel)
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with Image.open(os.path.join(cub_root, "images", rel)) as im:
            im.crop((x, y, x + w, y + h)).save(out_path, quality=quality)
        counts[0 if split[img_id] == 1 else 1] += 1
    return counts[0], counts[1]


def crop_cub_masks(
    cub_root: str, seg_root: str, out_root: str, limit: Optional[int] = None
) -> int:
    """Bbox-crop the CUB segmentation PNGs into out_root/mask_{train,test}/
    class trees (reference cropmasks.py, non-destructive)."""
    names, boxes, split = _load_cub_index(cub_root)
    n = 0
    for img_id, rel in names[: limit if limit else len(names)]:
        mask_rel = rel.rsplit(".", 1)[0] + ".png"
        x, y, w, h = boxes[img_id]
        dest = "mask_train" if split[img_id] == 1 else "mask_test"
        out_path = os.path.join(out_root, dest, mask_rel)
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with Image.open(os.path.join(seg_root, mask_rel)) as im:
            im.crop((x, y, x + w, y + h)).save(out_path)
        n += 1
    return n


def binarize_masks(src_root: str, dst_root: str) -> int:
    """Foreground extraction (reference preprocess_mask.py:24-40): the two
    lowest gray levels (background + border) become 0, everything else 255."""
    n = 0
    for dirpath, _dirs, files in os.walk(src_root):
        for fname in sorted(files):
            if not fname.lower().endswith(".png"):
                continue
            src = os.path.join(dirpath, fname)
            with Image.open(src) as im:
                mask = np.asarray(im.convert("L"))
            levels = np.sort(np.unique(mask))
            # the two lowest levels are background + border (reference
            # preprocess_mask.py:28 "0 and 51") — but a clean binary mask
            # has only {bg, fg}, where only the lowest is background
            n_bg = 2 if len(levels) > 2 else 1
            fg = ~np.isin(mask, levels[:n_bg])
            out = os.path.join(dst_root, os.path.relpath(src, src_root))
            os.makedirs(os.path.dirname(out), exist_ok=True)
            Image.fromarray((fg * 255).astype(np.uint8)).save(out)
            n += 1
    return n


# ---------------------------------------------------------------- Cars crop
def crop_cars(
    annos_mat: str, images_root: str, out_root: str, quality: int = 95
) -> int:
    """Stanford Cars bbox crop into 3-digit class folders, train/test split
    from the annotation table (reference cropimages_cars.py: indicator 0 =
    train, 1 = test)."""
    import scipy.io

    mat = scipy.io.loadmat(annos_mat)["annotations"][0]
    n = 0
    for info in mat:
        name = str(info[0][0])
        x1, y1, x2, y2 = (int(info[i]) for i in range(1, 5))
        cls = int(info[-2])
        is_test = int(info[-1]) == 1
        dest = "test_cropped" if is_test else "train_cropped"
        out_path = os.path.join(
            out_root, dest, f"{cls:03d}", os.path.basename(name)
        )
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with Image.open(os.path.join(images_root, name)) as im:
            im.crop((x1, y1, x2, y2)).save(out_path, quality=quality)
        n += 1
    return n


# --------------------------------------------------------------------- Pets
def build_pets(img_dir: str, label_file: str, out_dir: str) -> int:
    """Class-folder tree from an Oxford-IIIT Pets annotation list
    (reference img_pets.py: `<name> <class_id> ...` lines; images copied to
    out_dir/<class_id>/<name>.jpg)."""
    n = 0
    for line in open(label_file):
        info = line.strip().split(" ")
        if not info[0] or info[0].startswith("#"):
            continue
        src = os.path.join(img_dir, info[0] + ".jpg")
        dst = os.path.join(out_dir, info[1], info[0] + ".jpg")
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copyfile(src, dst)
        n += 1
    return n


# ------------------------------------------------------- offline augmentation
def _rotate_crop(img: Image.Image, rng: np.random.Generator, max_deg: float = 15.0):
    """Rotate then crop the largest inscribed axis-aligned rectangle and
    resize back (Augmentor rotate semantics — no black corners)."""
    deg = float(rng.uniform(-max_deg, max_deg))
    w, h = img.size
    out = img.rotate(deg, resample=BICUBIC, expand=True)
    # largest inscribed rectangle of a rotated rectangle
    a = abs(np.deg2rad(deg))
    if w <= 0 or h <= 0:
        return img
    long_side, short_side = max(w, h), min(w, h)
    sin_a, cos_a = np.sin(a), np.cos(a)
    if short_side <= 2.0 * sin_a * cos_a * long_side or abs(sin_a - cos_a) < 1e-10:
        x = 0.5 * short_side
        wr, hr = (x / sin_a, x / cos_a) if w >= h else (x / cos_a, x / sin_a)
    else:
        cos_2a = cos_a * cos_a - sin_a * sin_a
        wr = (w * cos_a - h * sin_a) / cos_2a
        hr = (h * cos_a - w * sin_a) / cos_2a
    ow, oh = out.size
    left, top = (ow - wr) / 2.0, (oh - hr) / 2.0
    return out.crop((left, top, left + wr, top + hr)).resize((w, h), BICUBIC)


def _skew(img: Image.Image, rng: np.random.Generator, magnitude: float = 0.2):
    """Random corner-perspective tilt (Augmentor skew magnitude 0.2)."""
    w, h = img.size
    dx, dy = magnitude * w, magnitude * h
    src = [(0, 0), (w, 0), (w, h), (0, h)]
    dst = [
        (
            float(x + rng.uniform(0, dx) * (1 if x == 0 else -1)),
            float(y + rng.uniform(0, dy) * (1 if y == 0 else -1)),
        )
        for x, y in src
    ]
    # solve the 8-dof projective map dst -> src for Image.transform
    mat = []
    for (x, y), (u, v) in zip(dst, src):
        mat.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        mat.append([0, 0, 0, x, y, 1, -v * x, -v * y])
    a = np.asarray(mat, np.float64)
    b = np.asarray([c for uv in src for c in uv], np.float64)
    coeffs = np.linalg.solve(a, b)
    return img.transform((w, h), Image.Transform.PERSPECTIVE, coeffs, BICUBIC)


def _shear(img: Image.Image, rng: np.random.Generator, max_deg: float = 10.0):
    """Horizontal or vertical shear up to +-max_deg (Augmentor shear)."""
    w, h = img.size
    deg = float(rng.uniform(-max_deg, max_deg))
    t = np.tan(np.deg2rad(deg))
    if rng.uniform() < 0.5:
        coeffs = (1, t, -t * h / 2, 0, 1, 0)  # x-shear about center
    else:
        coeffs = (1, 0, 0, t, 1, -t * w / 2)  # y-shear
    return img.transform((w, h), Image.Transform.AFFINE, coeffs, BICUBIC)


def _grid_distortion(
    img: Image.Image,
    rng: np.random.Generator,
    grid: int = 10,
    magnitude: float = 5.0,
):
    """Elastic grid distortion (Augmentor random_distortion grid 10x10,
    magnitude 5): jitter interior grid nodes, map each cell as a quad mesh."""
    w, h = img.size
    gx = np.linspace(0, w, grid + 1)
    gy = np.linspace(0, h, grid + 1)
    disp = rng.uniform(-magnitude, magnitude, size=(grid + 1, grid + 1, 2))
    disp[0, :] = disp[-1, :] = 0  # pin the borders
    disp[:, 0] = disp[:, -1] = 0
    mesh = []
    for j in range(grid):
        for i in range(grid):
            box = (int(gx[i]), int(gy[j]), int(gx[i + 1]), int(gy[j + 1]))
            quad = []
            for jj, ii in ((j, i), (j + 1, i), (j + 1, i + 1), (j, i + 1)):
                quad.extend(
                    [gx[ii] + disp[jj, ii, 0], gy[jj] + disp[jj, ii, 1]]
                )
            mesh.append((box, tuple(quad)))
    return img.transform((w, h), Image.Transform.MESH, mesh, BICUBIC)


_AUG_OPS = {
    "rotate": _rotate_crop,
    "skew": _skew,
    "shear": _shear,
    "distortion": _grid_distortion,
}


def augment_offline(
    src_dir: str,
    dst_dir: str,
    copies_per_op: int = 10,
    seed: int = 0,
    ops: Optional[List[str]] = None,
) -> int:
    """Offline augmentation of a class-folder tree (reference img_aug.py):
    for each image, `copies_per_op` variants of each op, each with a 50%
    horizontal flip — 4 ops x 10 copies = the reference's 40x expansion.
    Deterministic per (seed, class, file, op, copy). Returns files written."""
    op_names = ops if ops is not None else list(_AUG_OPS)
    if not op_names:
        raise ValueError("ops must name at least one augmentation")
    n = 0
    classes = sorted(
        e.name for e in os.scandir(src_dir) if e.is_dir()
    )
    for cls in classes:
        out_cls = os.path.join(dst_dir, cls)
        os.makedirs(out_cls, exist_ok=True)
        files = sorted(
            f for f in os.listdir(os.path.join(src_dir, cls))
            if f.lower().endswith((".jpg", ".jpeg", ".png"))
        )
        for fname in files:
            with Image.open(os.path.join(src_dir, cls, fname)) as im:
                img = im.convert("RGB")
                # keep the source extension in the stem so a.jpg and a.png
                # don't collide on identical output names
                base, ext = os.path.splitext(fname)
                stem = f"{base}_{ext.lstrip('.').lower()}"
                for op_name in op_names:
                    op = _AUG_OPS[op_name]
                    for c in range(copies_per_op):
                        # crc32, not hash(): python str hashing is salted
                        # per process and would break run-to-run determinism
                        key = f"{seed}/{cls}/{fname}/{op_name}/{c}"
                        rng = np.random.default_rng(
                            zlib.crc32(key.encode())
                        )
                        out = op(img, rng)
                        if rng.uniform() < 0.5:
                            out = out.transpose(
                                Image.Transpose.FLIP_LEFT_RIGHT
                            )
                        out.save(
                            os.path.join(
                                out_cls, f"{stem}_{op_name}{c}.jpg"
                            ),
                            quality=95,
                        )
                        n += 1
    return n
