"""CUB-200-2011 part/bbox annotation tables.

Reference: utils/local_parts.py — which parses all tables at IMPORT time from
a hard-coded path (local_parts.py:14-81). Here the same tables are a class
constructed from a root directory (SURVEY.md §5.6: no import-time I/O).

Table semantics preserved exactly:
  * id_to_path: img_id -> (class_folder, file_name)
  * id_to_bbox: img_id -> (x1, y1, x2, y2), truncated-int pixel coords
  * id_to_part_loc: img_id -> [[part_id(1-based), x, y], ...] VISIBLE parts only
  * cls_to_id: 0-based class -> [img_id...]
  * id_to_train: img_id -> 1 (train) | 0 (test)
  * part_num: number of distinct part classes (15 for CUB)
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple


def read_images_txt(root: str) -> List[Tuple[int, str]]:
    """Raw images.txt rows: (img_id, 'class_folder/file.jpg'). Single parser
    for every consumer (parts tables here, offline crops in data/prep.py)."""
    out: List[Tuple[int, str]] = []
    with open(os.path.join(root, "images.txt")) as f:
        for line in f:
            if line.strip():
                sid, path = line.split(" ", 1)
                out.append((int(sid), path.strip()))
    return out


def read_bounding_boxes(root: str) -> Dict[int, Tuple[float, float, float, float]]:
    """Raw bounding_boxes.txt: img_id -> (x, y, w, h) FLOATS as stored on
    disk. Consumers apply their own rounding (CubParts truncates to int per
    reference local_parts.py:33-40; crops keep floats)."""
    out: Dict[int, Tuple[float, float, float, float]] = {}
    with open(os.path.join(root, "bounding_boxes.txt")) as f:
        for line in f:
            if line.strip():
                sid, x, y, w, h = line.split()
                out[int(sid)] = (float(x), float(y), float(w), float(h))
    return out


def read_train_test_split(root: str) -> Dict[int, int]:
    """train_test_split.txt: img_id -> 1 (train) | 0 (test)."""
    out: Dict[int, int] = {}
    with open(os.path.join(root, "train_test_split.txt")) as f:
        for line in f:
            if line.strip():
                sid, is_train = line.split()
                out[int(sid)] = int(is_train)
    return out


def in_bbox(loc_yx: Tuple[int, int], bbox_yyxx: Tuple[int, int, int, int]) -> bool:
    """Is (y, x) inside (y1, y2, x1, x2)? (reference local_parts.py:10-11)."""
    y, x = loc_yx
    y1, y2, x1, x2 = bbox_yyxx
    return y1 <= y <= y2 and x1 <= x <= x2


class CubParts:
    """Parse the CUB metadata/part tables under `root` (the directory holding
    images.txt, bounding_boxes.txt, image_class_labels.txt,
    train_test_split.txt and parts/)."""

    def __init__(self, root: str):
        self.root = os.path.expanduser(root)

        self.id_to_path: Dict[int, Tuple[str, str]] = {}
        for sid, path in read_images_txt(self.root):
            folder, name = path.split("/", 1)
            self.id_to_path[sid] = (folder, name)

        # bbox floats truncated to int, x2/y2 = x+w, y+h
        # (reference local_parts.py:33-40)
        self.id_to_bbox: Dict[int, Tuple[int, int, int, int]] = {}
        for sid, (x, y, w, h) in read_bounding_boxes(self.root).items():
            x, y, w, h = int(x), int(y), int(w), int(h)
            self.id_to_bbox[sid] = (x, y, x + w, y + h)

        self.cls_to_id: Dict[int, List[int]] = {}
        with open(os.path.join(self.root, "image_class_labels.txt")) as f:
            for line in f:
                sid, cls = line.split()
                self.cls_to_id.setdefault(int(cls) - 1, []).append(int(sid))

        self.id_to_train: Dict[int, int] = read_train_test_split(self.root)

        self.part_id_to_part: Dict[int, str] = {}
        with open(os.path.join(self.root, "parts", "parts.txt")) as f:
            for line in f:
                pid, name = line.split(" ", 1)
                self.part_id_to_part[int(pid)] = name.strip()
        self.part_num: int = len(self.part_id_to_part)

        # visible parts only (reference local_parts.py:71-81)
        self.id_to_part_loc: Dict[int, List[List[int]]] = {}
        with open(os.path.join(self.root, "parts", "part_locs.txt")) as f:
            for line in f:
                sid, pid, x, y, visible = line.split()
                self.id_to_part_loc.setdefault(int(sid), [])
                if int(visible) == 1:
                    self.id_to_part_loc[int(sid)].append(
                        [int(pid), int(float(x)), int(float(y))]
                    )

    def image_path(self, img_id: int) -> str:
        folder, name = self.id_to_path[img_id]
        return os.path.join(self.root, "images", folder, name)

    def orig_wh(self, img_id: int) -> Tuple[int, int]:
        """Original (width, height), cached — reading the header once per
        image instead of re-opening it for every metric pass."""
        cache = getattr(self, "_wh_cache", None)
        if cache is None:
            cache = self._wh_cache = {}
        if img_id not in cache:
            from PIL import Image

            with Image.open(self.image_path(img_id)) as im:
                cache[img_id] = im.size
        return cache[img_id]

    def scaled_part_labels(
        self, img_id: int, orig_wh: Tuple[int, int], img_size: int
    ) -> Tuple[List[List[int]], "list"]:
        """Part labels rescaled from the ORIGINAL full-image pixel grid to a
        (img_size, img_size) resize, plus the part-presence mask.

        Reference interpretability.py:95-105: ratio against the original
        image size, int truncation; 1-based part ids become 0-based."""
        import numpy as np

        w, h = orig_wh
        part_mask = np.zeros((self.part_num,))
        out: List[List[int]] = []
        for pid, x, y in self.id_to_part_loc.get(img_id, []):
            part_mask[pid - 1] = 1
            out.append(
                [pid - 1, int(img_size * x / w), int(img_size * y / h)]
            )
        return out, part_mask
