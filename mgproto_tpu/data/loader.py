"""Prefetching batch loader with thread- and process-worker backends.

The reference trains with `num_workers=0` — every JPEG decoded serially on
the main thread between optimizer steps (reference main.py:94; SURVEY.md
§7.3.6 calls this the bottleneck-by-neglect). Here decode/augment runs on a
worker pool overlapped with device compute, and batches are pre-assembled
into numpy arrays ready for device_put.

Two backends (`worker_backend`):
  * "thread" (default): a persistent ThreadPoolExecutor (created on first
    use, reused across epochs, torn down by close()). PIL decode releases
    the GIL, but the numpy-heavy augmentation math (color jitter, affine)
    does not — on a many-core host the pipeline serializes on the GIL well
    below the ~2,100 img/s the v5e-8 north star needs (VERDICT r3 item 5).
  * "process": a SPAWN-context multiprocessing.Pool, created lazily on
    first use and reused for the loader's lifetime. Spawn, not fork: the
    loader's first iteration typically happens after the JAX/PJRT runtime
    is live, and forking a parent with XLA/grpc threads can deadlock the
    children (jax explicitly does not support it); spawn children import a
    fresh interpreter and never touch jax. The dataset is pickled ONCE into
    each worker (initializer), not per task.

Shared-memory batch assembly (ISSUE 5): by default the process backend no
longer pickles image payloads back to the parent. A small PERSISTENT ring
of `multiprocessing.shared_memory` batch slabs ([B, H, W, C] in the sample
dtype — uint8 with the device-augment wire format, 4x fewer bytes than
f32) is written IN PLACE by chunked worker tasks (one per worker per
batch, so the pool's dispatch/result round trip amortizes over the row
range); only per-row (row, label, id) tuples cross IPC. The parent copies
each finished slab into the yielded batch (one big memcpy instead of
per-sample pickle + pipe + unpickle + stack), patches sentinel rows, and
returns the slab to the ring. The ring survives epochs so shared-page
faults are paid once, and is rebuilt only after an early-terminated epoch
(see _SlabRing) or a spec change; the per-sample pickle protocol remains
as the thread/sync path, the `use_shm=False` fallback, and the measured
baseline. A sample whose shape/dtype does not match the slab degrades to
the pickle payload for that row only — no data loss on variable-shape
datasets. `loader_shm_slabs_in_use` gauges ring occupancy.

Self-healing (ISSUE 2): a failing sample load retries with exponential
backoff + deterministic jitter inside `_load_sample` (transient NFS/GCS
hiccups heal invisibly; retries count into
`resilience_retries_total{scope="loader"}`); a sample that exhausts its
retries is SUBSTITUTED by a sentinel row (zero image, label -1 — counted in
`loader_sentinel_rows_total`, never fatal: one rotted JPEG must not kill a
pod run). A process worker that never returns (OOM-kill, segfault) no
longer raises RuntimeError: the pool is RESTARTED once per incident
(`loader_worker_restarts_total`) and the lost sample is recovered in-parent
through the same deterministic `_load_sample` path — under shared memory
the recovered row is written into the slab in-parent — so the batch content
is identical to an incident-free run. Process-backend caveat: retries
happen inside spawn workers whose metric registry is separate, so parent
telemetry sees sentinel substitutions and pool restarts but NOT worker-side
retry counts (thread/sync backends count everything); chaos loader-IO
injection IS re-armed inside workers (the pool initializer ships the plan).

Determinism: sample i of epoch e is transformed with a generator seeded by
(seed, epoch, sample index) — reproducible regardless of worker scheduling
OR backend (all call the same `_load_sample`), unlike torch's global-RNG
loaders. `with_seeds=True` additionally ships a per-sample uint32 seed
(`augment_seeds`, splitmix64 over the same identity) for the device-side
augmentation tail (ops/augment.py), so device draws inherit the same
determinism. `tests/test_data.py` asserts thread==process==sync batch
equality across the pickle and shared-memory paths.
"""

from __future__ import annotations

import multiprocessing
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional, Tuple

import numpy as np

# per-sample retry budget: attempts = retries + 1, backoff base * 2^k with
# deterministic jitter (seeded by sample identity, so a chaos-injected run
# is bit-reproducible)
_SAMPLE_RETRIES = 3
_RETRY_BASE_DELAY_S = 0.05
_RETRY_MAX_DELAY_S = 2.0

# IPC-safe markers compared by VALUE (a spawn worker's module object differs
# from the parent's, so `is` sentinels would not survive pickling)
_FAILED = "__mgproto_load_failed__"  # sample failed every attempt
_SHM_ROW = "__mgproto_shm_row__"  # sample image is in the shm slab row

# ring occupancy gauge (pre-registered by telemetry sessions)
SHM_SLABS_GAUGE = "loader_shm_slabs_in_use"


def _count(name: str, amount: float = 1.0, **labels) -> None:
    """Resilience counter inc (lazy import: spawn workers touch this module
    before the parent package finishes importing; telemetry is jax-free)."""
    from mgproto_tpu.resilience import metrics as _m

    _m.counter(name).inc(amount, **labels)


def _gauge(name: str, value: float) -> None:
    from mgproto_tpu.telemetry.registry import default_registry

    default_registry().gauge(name).set(value)


_SPLITMIX_MASK = np.uint64(0xFFFFFFFFFFFFFFFF)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (uint64 in/out, wrapping)."""
    with np.errstate(over="ignore"):
        z = (x + np.uint64(0x9E3779B97F4A7C15)) & _SPLITMIX_MASK
        z = ((z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) & _SPLITMIX_MASK
        z = ((z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) & _SPLITMIX_MASK
        return z ^ (z >> np.uint64(31))


def augment_seeds(seed: int, epoch: int, indices: np.ndarray) -> np.ndarray:
    """Per-sample uint32 seeds for the device augmentation tail, derived
    from the SAME (seed, epoch, index) identity as the host RNG streams —
    deterministic across backends, worker scheduling and restarts. Pad
    (-1) rows get a seed too; their zero images make it inert."""
    idx = np.asarray(indices, np.int64).astype(np.uint64)
    with np.errstate(over="ignore"):
        h = _splitmix64(np.uint64(int(seed) & 0xFFFFFFFFFFFFFFFF)
                        + np.uint64(0xA076_1D64_78BD_642F))
        h = _splitmix64(h + np.uint64(int(epoch)))
        h = _splitmix64(h + idx)
    return (h >> np.uint64(32)).astype(np.uint32)


def _load_sample(dataset, seed: int, index: int, epoch: int,
                 retries: int = _SAMPLE_RETRIES):
    """The ONE sample-load path both backends share: deterministic per
    (seed, epoch, index), so backends are interchangeable mid-experiment.

    Retries transient load failures with backoff + seeded jitter; returns
    (`_FAILED`, index, repr(err)) after the budget is exhausted — the
    parent substitutes a sentinel row and counts it. The sample's dtype is
    PRESERVED (uint8 stays uint8: the wire format of the device-augment
    pipeline; classic transforms return f32 as before)."""
    if index < 0:  # sentinel pad row (multi-host tail alignment)
        return None
    from mgproto_tpu.resilience import metrics as _m
    from mgproto_tpu.resilience.chaos import get_active
    from mgproto_tpu.resilience.retry import backoff_delays

    last_err = None
    delays = None  # built lazily: the happy path never pays the jitter rng
    for attempt in range(retries + 1):
        try:
            chaos = get_active()
            if chaos is not None and chaos.loader_should_fail(
                seed, epoch, index, attempt
            ):
                raise IOError(
                    f"chaos: injected loader IO error (epoch {epoch}, "
                    f"sample {index}, attempt {attempt})"
                )
            rng = np.random.default_rng([seed, epoch, int(index)])
            img, label, sid = dataset.load(int(index), rng)
            img = np.asarray(img)
            if img.dtype != np.uint8:
                img = img.astype(np.float32, copy=False)
            return img, label, sid
        except Exception as e:  # decode/IO errors; never KeyboardInterrupt
            last_err = e
            if attempt >= retries:
                break
            _count(_m.RETRIES, scope="loader")
            if delays is None:
                delays = backoff_delays(
                    retries, _RETRY_BASE_DELAY_S, _RETRY_MAX_DELAY_S,
                    rng=np.random.default_rng(
                        [seed, epoch, int(index), 0xBACC0FF]
                    ),
                )
            time.sleep(next(delays))
    return (_FAILED, int(index), repr(last_err))


def _is_failed(r) -> bool:
    return (
        isinstance(r, tuple) and len(r) == 3
        and isinstance(r[0], str) and r[0] == _FAILED
    )


# per-worker state for process workers: the initializer receives the
# (pickled-once) dataset when the spawn child starts — never per task
_WORKER_STATE: dict = {}

# ceiling on one sample load (decode + augment is ms-scale; minutes means a
# dead/stuck worker) — Pool replaces a killed worker but never completes the
# lost task's AsyncResult, so an un-timed get() would hang training silently
_RESULT_TIMEOUT_S = 120.0


def _proc_worker_init(dataset, seed: int, chaos_plan=None) -> None:
    _WORKER_STATE["dataset"] = dataset
    _WORKER_STATE["seed"] = seed
    if chaos_plan is not None:
        # re-arm chaos inside the spawn worker (the parent's ChaosState is
        # not inherited): per-sample IO injection is (epoch, index)-
        # deterministic so per-worker states agree; the one-shot kinds
        # (nan/preempt/checkpoint) never run in workers. Worker-side retry
        # COUNTERS stay in the worker's registry — parent telemetry sees
        # sentinel substitutions and pool restarts, not worker retries.
        from mgproto_tpu.resilience.chaos import ChaosState, set_active

        set_active(ChaosState(chaos_plan))


def _proc_load_one(args: Tuple[int, int]):
    index, epoch = args
    return _load_sample(
        _WORKER_STATE["dataset"], _WORKER_STATE["seed"], index, epoch
    )


def _worker_slab_view(name: str, shape, dtype) -> np.ndarray:
    """Attach (and cache) a parent-created shm slab inside a spawn worker.

    Lifetime note: spawn pool children inherit the PARENT's resource
    tracker, so the attach-time re-registration CPython performs
    (bpo-39959) is a set-level no-op there and the parent's one
    unlink+unregister at ring teardown stays authoritative — the worker
    must NOT unregister (that would strip the parent's registration from
    the shared tracker and leak the segment on a parent crash)."""
    cache = _WORKER_STATE.setdefault("slabs", {})
    shm = cache.get(name)
    if shm is None:
        from multiprocessing import shared_memory

        if len(cache) >= 32:  # stale rings from earlier epochs
            for old in cache.values():
                try:
                    old.close()
                except OSError:
                    pass
            cache.clear()
        shm = shared_memory.SharedMemory(name=name)
        cache[name] = shm
    return np.ndarray(shape, dtype=np.dtype(dtype), buffer=shm.buf)


def _proc_load_chunk_shm(args):
    """Load a CHUNK of samples, writing each image into its slab row; only
    per-row (marker, label, id) tuples return through IPC. Chunked, not
    per-sample: one pool task per worker per batch amortizes the pool's
    dispatch/result round-trip (~ms-scale on syscall-taxed sandboxes) over
    the whole row range — this is what makes the slab transport outrun the
    legacy per-sample pickle protocol even before the byte savings. A
    shape/dtype mismatch with the slab degrades to the pickle payload for
    that row only."""
    indices, rows, epoch, slab_name, shape, dtype = args
    out = []
    view = None
    for index, row in zip(indices, rows):
        r = _load_sample(
            _WORKER_STATE["dataset"], _WORKER_STATE["seed"], index, epoch
        )
        if r is None or _is_failed(r):
            out.append(r)
            continue
        img, label, sid = r
        if img.shape != tuple(shape[1:]) or img.dtype != np.dtype(dtype):
            out.append((img, label, sid))  # per-row pickle fallback
            continue
        if view is None:
            view = _worker_slab_view(slab_name, shape, dtype)
        view[row] = img
        out.append((_SHM_ROW, label, sid))
    return out


class _SlabRing:
    """A ring of shared-memory batch slabs, PERSISTENT across epochs.

    `acquire` blocks until a slab is free (bounded by the prefetch depth +
    in-flight batches, so the ring never grows); `release` returns it after
    the parent copied the batch out. Occupancy is gauged so telemetry shows
    whether the consumer (release side) or the workers (write side) gate.

    Persistence is load-bearing, not a nicety: segment names stay stable,
    so worker attachments — and the page mappings behind them — survive
    across epochs. The first write to each shared page pays a fault that
    some kernels (gVisor-style sandboxes included) make ~100x a hot write;
    recreating the ring per epoch re-paid that for every slab every epoch
    and measured SLOWER than pickle. The loader recreates the ring only
    after an epoch that ended early (abandoned in-flight writes could race
    a reused slab row) or a shape/dtype change."""

    def __init__(self, n_slabs: int, shape, dtype):
        from multiprocessing import shared_memory

        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        nbytes = max(1, int(np.prod(self.shape)) * self.dtype.itemsize)
        self._shms = [
            shared_memory.SharedMemory(create=True, size=nbytes)
            for _ in range(n_slabs)
        ]
        self._free: "queue.Queue[int]" = queue.Queue()
        for i in range(n_slabs):
            self._free.put(i)
        self.n_slabs = n_slabs

    def reset_free(self) -> None:
        """Return every slab to the free list (epoch boundary: a cleanly
        finished epoch has no in-flight writers)."""
        while True:
            try:
                self._free.get_nowait()
            except queue.Empty:
                break
        for i in range(self.n_slabs):
            self._free.put(i)
        _gauge(SHM_SLABS_GAUGE, 0)

    def acquire(self, stop: threading.Event) -> Optional[int]:
        while not stop.is_set():
            try:
                i = self._free.get(timeout=0.1)
                _gauge(SHM_SLABS_GAUGE, self.n_slabs - self._free.qsize())
                return i
            except queue.Empty:
                continue
        return None

    def release(self, i: int) -> None:
        self._free.put(i)
        _gauge(SHM_SLABS_GAUGE, self.n_slabs - self._free.qsize())

    def name(self, i: int) -> str:
        return self._shms[i].name

    def view(self, i: int) -> np.ndarray:
        return np.ndarray(self.shape, dtype=self.dtype, buffer=self._shms[i].buf)

    def destroy(self) -> None:
        for shm in self._shms:
            try:
                shm.close()
                shm.unlink()
            except OSError:
                pass
        self._shms = []
        _gauge(SHM_SLABS_GAUGE, 0)


class DataLoader:
    """Iterable over (images [B,H,W,3], labels [B] i32, ids [B] i64) — plus
    a [B] u32 augmentation-seed array when `with_seeds=True`. Images are
    f32 for the classic transforms, uint8 for the device-augment wire
    format (whatever the dataset's transform returns).

    Args:
      dataset: object with __len__ and load(index, rng) -> (img, label, id).
      batch_size: PER-PROCESS batch size (the global batch is
        batch_size * shard_count).
      shuffle: reshuffle each epoch (epoch advances on each __iter__).
      drop_last: drop the trailing partial GLOBAL batch (train: True so
        jitted shapes stay static; eval: False, the tail is padded with
        sentinel rows — zero image, label -1, id -1).
      num_workers: decode workers (0 = synchronous, backend ignored).
      worker_backend: "thread" (GIL-sharing pool; PIL decode overlaps) or
        "process" (spawn pool, dataset pickled once per worker;
        augmentation math scales past the GIL).
      seed: base seed for shuffle + augmentation streams.
      shard_index/shard_count: multi-host data sharding. Every process
        computes the SAME global order (seeded identically), walks it in
        windows of batch_size*shard_count, and takes its own batch_size
        slice of each window — so the assembled global batch is a disjoint
        partition of the dataset, every process runs the SAME number of
        batches (equal-shape collectives), and shard_count=1 reproduces the
        single-host loader exactly.
      with_seeds: also yield per-sample uint32 seeds (`augment_seeds`) for
        the device augmentation tail.
      use_shm: shared-memory batch assembly for the process backend. None
        (auto) = ON for worker_backend="process"; ignored for thread/sync
        (no IPC to shortcut). Requires a probe-able sample shape; falls
        back to pickle per epoch when the probe fails, and per ROW when a
        sample's shape/dtype mismatches the slab.
      sample_spec: optional ((H, W, C), dtype) hint for slab allocation and
        sentinel rows — skips the probe load (and makes sentinel synthesis
        possible even when sample 0 itself is unreadable).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = False,
        drop_last: bool = False,
        num_workers: int = 8,
        worker_backend: str = "thread",
        seed: int = 0,
        prefetch_batches: int = 2,
        shard_index: int = 0,
        shard_count: int = 1,
        with_seeds: bool = False,
        use_shm: Optional[bool] = None,
        sample_spec: Optional[tuple] = None,
    ):
        if not 0 <= shard_index < shard_count:
            raise ValueError(f"shard_index {shard_index} not in [0, {shard_count})")
        if worker_backend not in ("thread", "process"):
            raise ValueError(
                f"worker_backend must be 'thread' or 'process', "
                f"got {worker_backend!r}"
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.num_workers = num_workers
        self.worker_backend = worker_backend
        self.seed = seed
        self.prefetch_batches = prefetch_batches
        self.shard_index = shard_index
        self.shard_count = shard_count
        self.with_seeds = with_seeds
        self.use_shm = use_shm
        self.epoch = 0
        # (shape, dtype) of a sample image — for sentinel rows + shm slabs
        self._template = (
            (tuple(sample_spec[0]), np.dtype(sample_spec[1]))
            if sample_spec is not None else None
        )
        self._pool = None  # lazy persistent process pool (backend="process")
        self._pool_gen = 0  # bumped on every restart (stale-future detection)
        self._pool_lock = threading.Lock()
        self._thread_pool = None  # lazy persistent executor (backend="thread")
        self._ring = None  # persistent shm slab ring (see _SlabRing)
        self._ring_clean = True  # last epoch finished with no in-flight work

    def _ensure_pool(self):
        """The process pool, created on first use and reused across epochs
        (spawn startup pickles the dataset into each worker — pay it once,
        not per epoch). Pool workers are daemonic: they die with the parent,
        so an unclosed loader cannot outlive the process."""
        if self._pool is None:
            from mgproto_tpu.resilience.chaos import get_active

            active = get_active()
            self._pool = multiprocessing.get_context("spawn").Pool(
                self.num_workers,
                initializer=_proc_worker_init,
                initargs=(
                    self.dataset, self.seed,
                    active.plan if active is not None else None,
                ),
            )
        return self._pool

    def _ensure_thread_pool(self) -> ThreadPoolExecutor:
        """The thread executor, persistent across epochs like the process
        pool (rebuilding it every __iter__ paid thread spawn/join per epoch
        for nothing); close() tears it down."""
        if self._thread_pool is None:
            self._thread_pool = ThreadPoolExecutor(
                max_workers=self.num_workers
            )
        return self._thread_pool

    def close(self) -> None:
        """Tear down the worker pools (process and/or thread) and the shm
        slab ring. Idempotent."""
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None
        if self._thread_pool is not None:
            self._thread_pool.shutdown(wait=True, cancel_futures=True)
            self._thread_pool = None
        if self._ring is not None:
            self._ring.destroy()
            self._ring = None

    def _restart_pool(self, gen: int) -> None:
        """Replace a wedged/dead process pool (self-healing path). `gen` is
        the generation the caller observed failing: if another thread
        already restarted past it, do nothing — one incident must trigger
        at most one restart, not one per in-flight batch."""
        from mgproto_tpu.resilience import metrics as _m

        with self._pool_lock:
            if self._pool_gen != gen:
                return
            if self._pool is not None:
                self._pool.terminate()
                self._pool.join()
                self._pool = None
            self._pool_gen += 1
            _count(_m.WORKER_RESTARTS)
            self._ensure_pool()

    def __len__(self) -> int:
        n = len(self.dataset)
        span = self.batch_size * self.shard_count
        if self.drop_last:
            return n // span
        return (n + span - 1) // span

    def _order(self) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            return np.random.default_rng(
                [self.seed, self.epoch]
            ).permutation(n)
        return np.arange(n)

    def _load_one(self, index: int, epoch: int):
        return _load_sample(self.dataset, self.seed, index, epoch)

    def _probe_template(self, epoch: int) -> Optional[tuple]:
        """(shape, dtype) of a sample image, learned by loading sample 0
        through `_load_sample` — the retry/chaos-aware path, NOT a bare
        dataset.load (a rotted sample 0 used to crash the very machinery
        meant to substitute for it). Falls back to the configured
        sample_spec; None when neither is available."""
        if self._template is None:
            r = _load_sample(self.dataset, self.seed, 0, epoch)
            if r is not None and not _is_failed(r):
                self._template = (r[0].shape, r[0].dtype)
        return self._template

    def _sentinel_row(self):
        if self._template is None and self._probe_template(self.epoch) is None:
            raise RuntimeError(
                "cannot synthesize a sentinel row: sample 0 is unreadable "
                "and no sample_spec was configured"
            )
        shape, dtype = self._template
        return np.zeros(shape, dtype), -1, -1

    def _batches_of_indices(self, order: np.ndarray):
        n = len(order)
        b, p, s = self.batch_size, self.shard_index, self.shard_count
        span = b * s
        if self.drop_last:
            stop = (n // span) * span
        else:
            stop = ((n + span - 1) // span) * span
            order = np.concatenate(
                [order, np.full(stop - n, -1, order.dtype)]
            )
        for i in range(0, stop, span):
            yield order[i + p * b : i + (p + 1) * b]

    def __iter__(self) -> Iterator[Tuple[np.ndarray, ...]]:
        order = self._order()
        epoch = self.epoch
        self.epoch += 1

        def finish(imgs, labels, ids, idx_batch):
            out = (
                imgs,
                np.asarray(labels, np.int32),
                np.asarray(ids, np.int64),
            )
            if self.with_seeds:
                out = out + (augment_seeds(self.seed, epoch, idx_batch),)
            return out

        def assemble(results, idx_batch):
            failed = sum(1 for r in results if _is_failed(r))
            if failed:
                # exhausted-retry substitutions: counted, never fatal (one
                # rotted file must not kill a pod run)
                from mgproto_tpu.resilience import metrics as _m

                _count(_m.SENTINEL_ROWS, failed)
            if self._template is None:
                for r in results:  # learn the sentinel spec from any real
                    if r is not None and not _is_failed(r):  # row (process
                        self._template = (r[0].shape, r[0].dtype)  # workers
                        break  # can't set parent state)
            results = [
                r if r is not None and not _is_failed(r)
                else self._sentinel_row()
                for r in results
            ]
            imgs = np.stack([r[0] for r in results])
            return finish(
                imgs, [r[1] for r in results], [r[2] for r in results],
                idx_batch,
            )

        def assemble_shm(results, idx_batch, ring, slab_id):
            """Slab -> batch: one memcpy of the whole slab, then patch the
            non-shm rows (sentinels, pads, per-row pickle fallbacks)."""
            imgs = np.array(ring.view(slab_id))  # copy before release
            ring.release(slab_id)
            labels = np.empty(len(results), np.int32)
            ids = np.empty(len(results), np.int64)
            failed = 0
            for row, r in enumerate(results):
                if r is None or _is_failed(r):
                    failed += _is_failed(r)
                    imgs[row] = 0
                    labels[row] = -1
                    ids[row] = -1
                elif isinstance(r[0], str) and r[0] == _SHM_ROW:
                    labels[row] = r[1]
                    ids[row] = r[2]
                else:  # pickle fallback row (shape/dtype mismatch)
                    img = np.asarray(r[0])
                    # mirror the worker's check: a dtype mismatch must not
                    # silently numpy-cast (f32 pixels into a u8 batch is
                    # garbage, not data) — zero the row like a bad shape
                    imgs[row] = (
                        img
                        if img.shape == imgs.shape[1:]
                        and img.dtype == imgs.dtype
                        else np.zeros(imgs.shape[1:], imgs.dtype)
                    )
                    labels[row] = r[1]
                    ids[row] = r[2]
            if failed:
                from mgproto_tpu.resilience import metrics as _m

                _count(_m.SENTINEL_ROWS, failed)
            return finish(imgs, labels, ids, idx_batch)

        if self.num_workers <= 0:
            for idx_batch in self._batches_of_indices(order):
                yield assemble(
                    [self._load_one(i, epoch) for i in idx_batch], idx_batch
                )
            return

        # shared-memory assembly: process backend only (thread workers share
        # the parent's address space — nothing to shortcut)
        shm_active = (
            self.worker_backend == "process"
            and (self.use_shm is None or self.use_shm)
            and self._probe_template(epoch) is not None
        )
        ring = None
        if shm_active:
            shape, dtype = self._template
            slab_shape = (self.batch_size,) + tuple(shape)
            if self._ring is not None and (
                not self._ring_clean
                or self._ring.shape != slab_shape
                or self._ring.dtype != np.dtype(dtype)
            ):
                self._ring.destroy()
                self._ring = None
            if self._ring is None:
                self._ring = _SlabRing(
                    self.prefetch_batches + 2, slab_shape, dtype
                )
            else:
                self._ring.reset_free()
            ring = self._ring
            self._ring_clean = False  # until this epoch finishes cleanly

        # pipelined: a feeder thread keeps `prefetch_batches` batches in
        # flight; each batch's samples decode in parallel on the pool.
        # An early `break` by the consumer (GeneratorExit) must unblock the
        # feeder (stuck in put on the bounded queue) or the thread leaks.
        batch_q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_batches)
        sentinel = object()
        stop = threading.Event()

        if self.worker_backend == "process":
            self._ensure_pool()  # persistent across epochs

            def submit(i):
                # (handle, index, generation): the index makes a lost task
                # recoverable in-parent, the generation makes restart
                # decisions idempotent across in-flight batches
                with self._pool_lock:
                    p, gen = self._pool, self._pool_gen
                return p.apply_async(_proc_load_one, ((i, epoch),)), i, gen

            def submit_chunk(indices, rows, slab_id):
                with self._pool_lock:
                    p, gen = self._pool, self._pool_gen
                h = p.apply_async(_proc_load_chunk_shm, ((
                    [int(i) for i in indices], [int(r) for r in rows],
                    epoch, ring.name(slab_id), ring.shape, ring.dtype.str,
                ),))
                return h, indices, rows, gen, slab_id

            def _recover_row(index, slab_id, row):
                """In-parent reload of a sample a dead worker lost; under
                shm the recovered row lands in the slab exactly where the
                worker would have written it."""
                r = self._load_one(index, epoch)
                if (
                    r is not None and not _is_failed(r)
                    and r[0].shape == ring.shape[1:]
                    and r[0].dtype == ring.dtype
                ):
                    ring.view(slab_id)[row] = r[0]
                    return (_SHM_ROW, r[1], r[2])
                return r

            def result_of(item):
                handle, index, gen = item
                try:
                    return handle.get(timeout=_RESULT_TIMEOUT_S)
                except multiprocessing.TimeoutError:
                    # a worker died/hung: Pool will never complete this
                    # AsyncResult. Restart the pool (once per incident) and
                    # recover THIS sample in-parent via the same
                    # deterministic path — identical batch content, no
                    # RuntimeError (the seed behavior this replaces).
                    self._restart_pool(gen)
                    return self._load_one(index, epoch)

            def chunk_result_of(item):
                handle, indices, rows, gen, slab_id = item
                try:
                    return handle.get(timeout=_RESULT_TIMEOUT_S)
                except multiprocessing.TimeoutError:
                    # same self-healing contract as result_of, per chunk:
                    # restart once, then recover every lost row in-parent
                    self._restart_pool(gen)
                    return [
                        _recover_row(int(i), slab_id, int(r))
                        for i, r in zip(indices, rows)
                    ]
        else:
            pool = self._ensure_thread_pool()  # persistent across epochs

            def submit(i):
                return pool.submit(self._load_one, i, epoch), i, 0

            def result_of(item):
                return item[0].result()

        def put_or_stop(item) -> bool:
            while not stop.is_set():
                try:
                    batch_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def feeder():
            try:
                for idx_batch in self._batches_of_indices(order):
                    if ring is not None:
                        slab_id = ring.acquire(stop)
                        if slab_id is None:  # consumer gone
                            return
                        # one chunk task per worker: the pool round
                        # trip amortizes over the row range
                        rows = np.arange(len(idx_batch))
                        futures = [
                            submit_chunk(idx_batch[c], c, slab_id)
                            for c in np.array_split(
                                rows,
                                max(1, min(self.num_workers, len(rows))),
                            )
                            if len(c)
                        ]
                    else:
                        slab_id = None
                        futures = [submit(i) for i in idx_batch]
                    if not put_or_stop((futures, idx_batch, slab_id)):
                        if slab_id is not None:
                            ring.release(slab_id)
                        return
            finally:
                put_or_stop(sentinel)

        t = threading.Thread(target=feeder, daemon=True)
        t.start()
        try:
            while True:
                item = batch_q.get()
                if item is sentinel:
                    # clean finish: every submitted task was consumed,
                    # so the persistent ring may be reused hot next
                    # epoch (no in-flight writers left behind)
                    if ring is not None:
                        self._ring_clean = True
                    break
                futures, idx_batch, slab_id = item
                if slab_id is not None:
                    results = [None] * len(idx_batch)
                    for f in futures:  # (handle, indices, rows, ...)
                        for row, r in zip(f[2], chunk_result_of(f)):
                            results[int(row)] = r
                    yield assemble_shm(results, idx_batch, ring, slab_id)
                else:
                    yield assemble(
                        [result_of(f) for f in futures], idx_batch
                    )
        finally:
            stop.set()
            try:  # drain so the feeder's pending put unblocks
                while True:
                    item = batch_q.get_nowait()
                    if item is not sentinel and item[2] is not None:
                        ring.release(item[2])
            except queue.Empty:
                pass
            t.join(timeout=10)
        # worker pools and the shm ring persist across epochs (close()
        # tears them down); an early break marks the ring unclean so the
        # next epoch rebuilds it instead of racing abandoned in-flight
        # writes; abandoned tasks finish in the workers harmlessly


def device_prefetch(batches, put_fn, depth: int = 2):
    """Overlap host->device transfer with device compute.

    Pulls host batches from `batches`, immediately places each with
    `put_fn` (e.g. Trainer.put_batch — an async jax.device_put under the
    hood), and holds up to `depth` placed batches in flight before yielding
    the oldest. While the consumer's step N executes on device, batch N+1's
    H2D copy (and the host loader's decode/augment for N+2) proceed
    concurrently — the input-transfer overlap PERF.md names as the first
    post-55.8%-MFU lever. depth=2 costs one extra batch of HBM
    (~154 MB at flagship batch 256 f32 wire — a quarter of that with the
    uint8 wire format).

    depth <= 0 DISABLES prefetch cleanly: each batch is placed with
    `put_fn` only when the consumer asks for it and yielded immediately —
    no queue, no batch ever held in flight, no prefetch HBM headroom (the
    `--prefetch-depth 0` operating point the HBM planner can select on a
    tight budget).
    """
    import collections

    if depth <= 0:
        for batch in batches:
            yield put_fn(batch)
        return
    q = collections.deque()
    for batch in batches:
        q.append(put_fn(batch))
        if len(q) >= depth:
            yield q.popleft()
    while q:
        yield q.popleft()
