"""Prefetching batch loader with thread- and process-worker backends.

The reference trains with `num_workers=0` — every JPEG decoded serially on
the main thread between optimizer steps (reference main.py:94; SURVEY.md
§7.3.6 calls this the bottleneck-by-neglect). Here decode/augment runs on a
worker pool overlapped with device compute, and batches are pre-assembled
into numpy arrays ready for device_put.

Two backends (`worker_backend`):
  * "thread" (default): a ThreadPoolExecutor. PIL decode releases the GIL,
    but the numpy-heavy augmentation math (color jitter, affine) does not —
    on a many-core host the pipeline serializes on the GIL well below the
    ~2,100 img/s the v5e-8 north star needs (VERDICT r3 item 5).
  * "process": a SPAWN-context multiprocessing.Pool, created lazily on
    first use and reused for the loader's lifetime. Spawn, not fork: the
    loader's first iteration typically happens after the JAX/PJRT runtime
    is live, and forking a parent with XLA/grpc threads can deadlock the
    children (jax explicitly does not support it); spawn children import a
    fresh interpreter and never touch jax. The dataset is pickled ONCE into
    each worker (initializer), not per task; only finished (img, label, id)
    tuples cross IPC afterwards.

Self-healing (ISSUE 2): a failing sample load retries with exponential
backoff + deterministic jitter inside `_load_sample` (transient NFS/GCS
hiccups heal invisibly; retries count into
`resilience_retries_total{scope="loader"}`); a sample that exhausts its
retries is SUBSTITUTED by a sentinel row (zero image, label -1 — counted in
`loader_sentinel_rows_total`, never fatal: one rotted JPEG must not kill a
pod run). A process worker that never returns (OOM-kill, segfault) no
longer raises RuntimeError: the pool is RESTARTED once per incident
(`loader_worker_restarts_total`) and the lost sample is recovered in-parent
through the same deterministic `_load_sample` path, so the batch content is
identical to an incident-free run. Process-backend caveat: retries happen
inside spawn workers whose metric registry is separate, so parent telemetry
sees sentinel substitutions and pool restarts but NOT worker-side retry
counts (thread/sync backends count everything); chaos loader-IO injection
IS re-armed inside workers (the pool initializer ships the plan).

Determinism: sample i of epoch e is transformed with a generator seeded by
(seed, epoch, sample index) — reproducible regardless of worker scheduling
OR backend (both call the same `_load_sample`), unlike torch's global-RNG
loaders. `tests/test_data.py` asserts thread==process batch equality.
"""

from __future__ import annotations

import multiprocessing
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional, Tuple

import numpy as np

# per-sample retry budget: attempts = retries + 1, backoff base * 2^k with
# deterministic jitter (seeded by sample identity, so a chaos-injected run
# is bit-reproducible)
_SAMPLE_RETRIES = 3
_RETRY_BASE_DELAY_S = 0.05
_RETRY_MAX_DELAY_S = 2.0

# IPC-safe marker for a sample that failed every attempt: compared by VALUE
# (a spawn worker's module object differs from the parent's, so an `is`
# sentinel would not survive pickling)
_FAILED = "__mgproto_load_failed__"


def _count(name: str, amount: float = 1.0, **labels) -> None:
    """Resilience counter inc (lazy import: spawn workers touch this module
    before the parent package finishes importing; telemetry is jax-free)."""
    from mgproto_tpu.resilience import metrics as _m

    _m.counter(name).inc(amount, **labels)


def _load_sample(dataset, seed: int, index: int, epoch: int,
                 retries: int = _SAMPLE_RETRIES):
    """The ONE sample-load path both backends share: deterministic per
    (seed, epoch, index), so backends are interchangeable mid-experiment.

    Retries transient load failures with backoff + seeded jitter; returns
    (`_FAILED`, index, repr(err)) after the budget is exhausted — the
    parent substitutes a sentinel row and counts it."""
    if index < 0:  # sentinel pad row (multi-host tail alignment)
        return None
    from mgproto_tpu.resilience import metrics as _m
    from mgproto_tpu.resilience.chaos import get_active
    from mgproto_tpu.resilience.retry import backoff_delays

    last_err = None
    delays = backoff_delays(
        retries, _RETRY_BASE_DELAY_S, _RETRY_MAX_DELAY_S,
        rng=np.random.default_rng([seed, epoch, int(index), 0xBACC0FF]),
    )
    for attempt in range(retries + 1):
        try:
            chaos = get_active()
            if chaos is not None and chaos.loader_should_fail(
                seed, epoch, index, attempt
            ):
                raise IOError(
                    f"chaos: injected loader IO error (epoch {epoch}, "
                    f"sample {index}, attempt {attempt})"
                )
            rng = np.random.default_rng([seed, epoch, int(index)])
            img, label, sid = dataset.load(int(index), rng)
            return np.asarray(img, np.float32), label, sid
        except Exception as e:  # decode/IO errors; never KeyboardInterrupt
            last_err = e
            if attempt >= retries:
                break
            _count(_m.RETRIES, scope="loader")
            time.sleep(next(delays))
    return (_FAILED, int(index), repr(last_err))


# per-worker state for process workers: the initializer receives the
# (pickled-once) dataset when the spawn child starts — never per task
_WORKER_STATE: dict = {}

# ceiling on one sample load (decode + augment is ms-scale; minutes means a
# dead/stuck worker) — Pool replaces a killed worker but never completes the
# lost task's AsyncResult, so an un-timed get() would hang training silently
_RESULT_TIMEOUT_S = 120.0


def _proc_worker_init(dataset, seed: int, chaos_plan=None) -> None:
    _WORKER_STATE["dataset"] = dataset
    _WORKER_STATE["seed"] = seed
    if chaos_plan is not None:
        # re-arm chaos inside the spawn worker (the parent's ChaosState is
        # not inherited): per-sample IO injection is (epoch, index)-
        # deterministic so per-worker states agree; the one-shot kinds
        # (nan/preempt/checkpoint) never run in workers. Worker-side retry
        # COUNTERS stay in the worker's registry — parent telemetry sees
        # sentinel substitutions and pool restarts, not worker retries.
        from mgproto_tpu.resilience.chaos import ChaosState, set_active

        set_active(ChaosState(chaos_plan))


def _proc_load_one(args: Tuple[int, int]):
    index, epoch = args
    return _load_sample(
        _WORKER_STATE["dataset"], _WORKER_STATE["seed"], index, epoch
    )


class DataLoader:
    """Iterable over (images [B,H,W,3] f32, labels [B] i32, ids [B] i64).

    Args:
      dataset: object with __len__ and load(index, rng) -> (img, label, id).
      batch_size: PER-PROCESS batch size (the global batch is
        batch_size * shard_count).
      shuffle: reshuffle each epoch (epoch advances on each __iter__).
      drop_last: drop the trailing partial GLOBAL batch (train: True so
        jitted shapes stay static; eval: False, the tail is padded with
        sentinel rows — zero image, label -1, id -1).
      num_workers: decode workers (0 = synchronous, backend ignored).
      worker_backend: "thread" (GIL-sharing pool; PIL decode overlaps) or
        "process" (spawn pool, dataset pickled once per worker;
        augmentation math scales past the GIL).
      seed: base seed for shuffle + augmentation streams.
      shard_index/shard_count: multi-host data sharding. Every process
        computes the SAME global order (seeded identically), walks it in
        windows of batch_size*shard_count, and takes its own batch_size
        slice of each window — so the assembled global batch is a disjoint
        partition of the dataset, every process runs the SAME number of
        batches (equal-shape collectives), and shard_count=1 reproduces the
        single-host loader exactly.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = False,
        drop_last: bool = False,
        num_workers: int = 8,
        worker_backend: str = "thread",
        seed: int = 0,
        prefetch_batches: int = 2,
        shard_index: int = 0,
        shard_count: int = 1,
    ):
        if not 0 <= shard_index < shard_count:
            raise ValueError(f"shard_index {shard_index} not in [0, {shard_count})")
        if worker_backend not in ("thread", "process"):
            raise ValueError(
                f"worker_backend must be 'thread' or 'process', "
                f"got {worker_backend!r}"
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.num_workers = num_workers
        self.worker_backend = worker_backend
        self.seed = seed
        self.prefetch_batches = prefetch_batches
        self.shard_index = shard_index
        self.shard_count = shard_count
        self.epoch = 0
        self._template = None  # (shape,) of a sample image, for sentinel rows
        self._pool = None  # lazy persistent process pool (backend="process")
        self._pool_gen = 0  # bumped on every restart (stale-future detection)
        self._pool_lock = threading.Lock()

    def _ensure_pool(self):
        """The process pool, created on first use and reused across epochs
        (spawn startup pickles the dataset into each worker — pay it once,
        not per epoch). Pool workers are daemonic: they die with the parent,
        so an unclosed loader cannot outlive the process."""
        if self._pool is None:
            from mgproto_tpu.resilience.chaos import get_active

            active = get_active()
            self._pool = multiprocessing.get_context("spawn").Pool(
                self.num_workers,
                initializer=_proc_worker_init,
                initargs=(
                    self.dataset, self.seed,
                    active.plan if active is not None else None,
                ),
            )
        return self._pool

    def close(self) -> None:
        """Tear down the process pool (no-op for the thread backend — its
        pool is per-iteration). Idempotent."""
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None

    def _restart_pool(self, gen: int) -> None:
        """Replace a wedged/dead process pool (self-healing path). `gen` is
        the generation the caller observed failing: if another thread
        already restarted past it, do nothing — one incident must trigger
        at most one restart, not one per in-flight batch."""
        from mgproto_tpu.resilience import metrics as _m

        with self._pool_lock:
            if self._pool_gen != gen:
                return
            if self._pool is not None:
                self._pool.terminate()
                self._pool.join()
                self._pool = None
            self._pool_gen += 1
            _count(_m.WORKER_RESTARTS)
            self._ensure_pool()

    def __len__(self) -> int:
        n = len(self.dataset)
        span = self.batch_size * self.shard_count
        if self.drop_last:
            return n // span
        return (n + span - 1) // span

    def _order(self) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            return np.random.default_rng(
                [self.seed, self.epoch]
            ).permutation(n)
        return np.arange(n)

    def _load_one(self, index: int, epoch: int):
        return _load_sample(self.dataset, self.seed, index, epoch)

    def _sentinel_row(self):
        if self._template is None:
            # all-sentinel batch before any real row was seen: probe sample 0
            img, _, _ = self.dataset.load(0, np.random.default_rng(0))
            self._template = np.asarray(img, np.float32).shape
        return np.zeros(self._template, np.float32), -1, -1

    def _batches_of_indices(self, order: np.ndarray):
        n = len(order)
        b, p, s = self.batch_size, self.shard_index, self.shard_count
        span = b * s
        if self.drop_last:
            stop = (n // span) * span
        else:
            stop = ((n + span - 1) // span) * span
            order = np.concatenate(
                [order, np.full(stop - n, -1, order.dtype)]
            )
        for i in range(0, stop, span):
            yield order[i + p * b : i + (p + 1) * b]

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        order = self._order()
        epoch = self.epoch
        self.epoch += 1

        def is_failed(r) -> bool:
            return (
                isinstance(r, tuple) and len(r) == 3
                and isinstance(r[0], str) and r[0] == _FAILED
            )

        def assemble(results):
            failed = sum(1 for r in results if is_failed(r))
            if failed:
                # exhausted-retry substitutions: counted, never fatal (one
                # rotted file must not kill a pod run)
                from mgproto_tpu.resilience import metrics as _m

                _count(_m.SENTINEL_ROWS, failed)
            if self._template is None:
                for r in results:  # learn the sentinel shape from any real
                    if r is not None and not is_failed(r):  # row (process
                        self._template = r[0].shape  # workers can't set it)
                        break
            results = [
                r if r is not None and not is_failed(r)
                else self._sentinel_row()
                for r in results
            ]
            imgs = np.stack([r[0] for r in results])
            labels = np.asarray([r[1] for r in results], np.int32)
            ids = np.asarray([r[2] for r in results], np.int64)
            if not self.drop_last and len(results) < self.batch_size:
                pad = self.batch_size - len(results)
                imgs = np.concatenate(
                    [imgs, np.zeros((pad,) + imgs.shape[1:], imgs.dtype)]
                )
                labels = np.concatenate(
                    [labels, np.full((pad,), -1, np.int32)]
                )
                ids = np.concatenate([ids, np.full((pad,), -1, np.int64)])
            return imgs, labels, ids

        if self.num_workers <= 0:
            for idx_batch in self._batches_of_indices(order):
                yield assemble([self._load_one(i, epoch) for i in idx_batch])
            return

        # pipelined: a feeder thread keeps `prefetch_batches` batches in
        # flight; each batch's samples decode in parallel on the pool.
        # An early `break` by the consumer (GeneratorExit) must unblock the
        # feeder (stuck in put on the bounded queue) or the thread leaks.
        batch_q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_batches)
        sentinel = object()
        stop = threading.Event()

        if self.worker_backend == "process":
            self._ensure_pool()  # persistent across epochs
            pool = None  # looked up per submit: a restart swaps the pool

            def submit(i):
                # (handle, index, generation): the index makes a lost task
                # recoverable in-parent, the generation makes restart
                # decisions idempotent across in-flight batches
                with self._pool_lock:
                    p, gen = self._pool, self._pool_gen
                return p.apply_async(_proc_load_one, ((i, epoch),)), i, gen

            def result_of(item):
                handle, index, gen = item
                try:
                    return handle.get(timeout=_RESULT_TIMEOUT_S)
                except multiprocessing.TimeoutError:
                    # a worker died/hung: Pool will never complete this
                    # AsyncResult. Restart the pool (once per incident) and
                    # recover THIS sample in-parent via the same
                    # deterministic path — identical batch content, no
                    # RuntimeError (the seed behavior this replaces).
                    self._restart_pool(gen)
                    return self._load_one(index, epoch)
        else:
            pool = ThreadPoolExecutor(max_workers=self.num_workers)

            def submit(i):
                return pool.submit(self._load_one, i, epoch), i, 0

            def result_of(item):
                return item[0].result()

        try:
            def put_or_stop(item) -> bool:
                while not stop.is_set():
                    try:
                        batch_q.put(item, timeout=0.1)
                        return True
                    except queue.Full:
                        continue
                return False

            def feeder():
                try:
                    for idx_batch in self._batches_of_indices(order):
                        futures = [submit(i) for i in idx_batch]
                        if not put_or_stop(futures):
                            return
                finally:
                    put_or_stop(sentinel)

            t = threading.Thread(target=feeder, daemon=True)
            t.start()
            try:
                while True:
                    item = batch_q.get()
                    if item is sentinel:
                        break
                    yield assemble([result_of(f) for f in item])
            finally:
                stop.set()
                try:  # drain so the feeder's pending put unblocks
                    while True:
                        batch_q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=10)
        finally:
            if self.worker_backend != "process":
                pool.shutdown(wait=True, cancel_futures=True)
            # the process pool persists across epochs (close() tears it
            # down); abandoned in-flight tasks just finish in the workers


def device_prefetch(batches, put_fn, depth: int = 2):
    """Overlap host->device transfer with device compute.

    Pulls host batches from `batches`, immediately places each with
    `put_fn` (e.g. Trainer.put_batch — an async jax.device_put under the
    hood), and holds up to `depth` placed batches in flight before yielding
    the oldest. While the consumer's step N executes on device, batch N+1's
    H2D copy (and the host loader's decode/augment for N+2) proceed
    concurrently — the input-transfer overlap PERF.md names as the first
    post-55.8%-MFU lever. depth=2 costs one extra batch of HBM
    (~154 MB at flagship batch 256).
    """
    import collections

    q = collections.deque()
    for batch in batches:
        q.append(put_fn(batch))
        if len(q) >= depth:
            yield q.popleft()
    while q:
        yield q.popleft()
