"""Host-side image transforms, PIL/numpy implementations.

torchvision is not a dependency of this framework; these reproduce the exact
transform semantics the reference uses (reference main.py:96-163):

  train: RandomPerspective(0.2, p=.5) -> ColorJitter((.6,1.4)x3, hue .02)
         -> RandomHorizontalFlip -> RandomAffine(25deg, shear +-15,
         translate .05) -> RandomResizedCrop(img, scale=(.6,1)) -> normalize
  push:  Resize((img,img))                      [unnormalized]
  test:  Resize(img+32 shorter side) -> CenterCrop(img) -> normalize
  ood:   Resize((img,img)) -> normalize

Each random transform takes a `numpy.random.Generator` so the pipeline is
deterministic per (seed, epoch, sample) — the reference's loader is only as
deterministic as torch's global RNG.
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np
from PIL import Image, ImageEnhance

from mgproto_tpu import native
from mgproto_tpu.utils.images import IMAGENET_MEAN, IMAGENET_STD

BILINEAR = Image.BILINEAR


# --------------------------------------------------------------- deterministic
def resize(img: Image.Image, size) -> Image.Image:
    """torchvision Resize: int = shorter side to `size` keeping aspect;
    (h, w) = exact."""
    if isinstance(size, int):
        w, h = img.size
        if w <= h:
            ow, oh = size, max(1, round(size * h / w))
        else:
            oh, ow = size, max(1, round(size * w / h))
        return img.resize((ow, oh), BILINEAR)
    h, w = size
    return img.resize((w, h), BILINEAR)


def center_crop(img: Image.Image, size: int) -> Image.Image:
    w, h = img.size
    if w < size or h < size:
        img = resize(img, size)
        w, h = img.size
    x0 = int(round((w - size) / 2.0))
    y0 = int(round((h - size) / 2.0))
    return img.crop((x0, y0, x0 + size, y0 + size))


def to_array(img: Image.Image) -> np.ndarray:
    """PIL -> float32 [H, W, 3] in [0, 1] (torchvision ToTensor, NHWC)."""
    return np.asarray(img.convert("RGB"), np.float32) / 255.0


def normalize(x: np.ndarray) -> np.ndarray:
    return (x - IMAGENET_MEAN) / IMAGENET_STD


def _to_norm_f32(img: Image.Image) -> np.ndarray:
    """PIL -> normalized f32 HWC: fused native LUT pass when the C++ library
    is built (mgproto_tpu/native), numpy (x/255 - mean)/std otherwise."""
    a = np.asarray(img.convert("RGB"), np.uint8)
    return native.u8_to_f32_norm(a, IMAGENET_MEAN, IMAGENET_STD)


def _to_f32(img: Image.Image) -> np.ndarray:
    """PIL -> f32 HWC in [0, 1] (push pipeline stays unnormalized)."""
    a = np.asarray(img.convert("RGB"), np.uint8)
    return native.u8_to_f32(a)


# ------------------------------------------------------------------- random
def random_horizontal_flip(
    img: Image.Image, rng: np.random.Generator, p: float = 0.5
) -> Image.Image:
    if rng.random() < p:
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return img


def _perspective_coeffs(
    startpoints: Sequence[Tuple[float, float]],
    endpoints: Sequence[Tuple[float, float]],
) -> List[float]:
    """8-param homography mapping OUTPUT (start) -> INPUT (end) coords, the
    direction PIL's PERSPECTIVE transform wants."""
    a = []
    b = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        a.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
        b.extend([ex, ey])
    coeffs, *_ = np.linalg.lstsq(
        np.asarray(a, np.float64), np.asarray(b, np.float64), rcond=None
    )
    return coeffs.tolist()


def random_perspective(
    img: Image.Image,
    rng: np.random.Generator,
    distortion_scale: float = 0.2,
    p: float = 0.5,
) -> Image.Image:
    """torchvision RandomPerspective: each corner jitters inward by up to
    distortion_scale * half-extent."""
    if rng.random() >= p:
        return img
    w, h = img.size
    dx = distortion_scale * w / 2
    dy = distortion_scale * h / 2

    def jitter(lo_x, lo_y):
        return (
            float(rng.integers(0, int(dx) + 1)),
            float(rng.integers(0, int(dy) + 1)),
        )

    jx0, jy0 = jitter(0, 0)
    jx1, jy1 = jitter(0, 0)
    jx2, jy2 = jitter(0, 0)
    jx3, jy3 = jitter(0, 0)
    startpoints = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
    endpoints = [
        (jx0, jy0),
        (w - 1 - jx1, jy1),
        (w - 1 - jx2, h - 1 - jy2),
        (jx3, h - 1 - jy3),
    ]
    # PIL wants output->input; torchvision's F.perspective(start, end) solves
    # the homography H with H(endpoint) = startpoint, so content SHRINKS into
    # the jittered quad (borders filled), not zoom-in
    coeffs = _perspective_coeffs(endpoints, startpoints)
    return img.transform((w, h), Image.PERSPECTIVE, coeffs, BILINEAR)


def _adjust_hue(img: Image.Image, factor: float) -> Image.Image:
    """Shift hue by `factor` (in turns, [-0.5, 0.5])."""
    if abs(factor) < 1e-8:
        return img
    hsv = np.asarray(img.convert("HSV"), np.uint8).copy()
    shift = np.uint8(int(factor * 255) % 256)
    hsv[..., 0] = hsv[..., 0] + shift  # uint8 wraparound is the hue circle
    return Image.fromarray(hsv, "HSV").convert("RGB")


# -------------------------- vectorized color jitter (bit-exact with PIL)
# The PIL jitter stack was the profiled hot spot of the whole train pipeline
# (~42 of ~54 ms/sample at CUB source sizes, the HSV hue round-trip alone
# ~25 ms — VERDICT r4 item 3). The numpy path below reproduces Pillow's
# integer/float semantics BIT-EXACTLY (pinned by
# tests/test_data.py::test_fast_color_jitter_bit_exact over random images,
# factors, and orders), so it is simply the default implementation, not an
# approximation. The per-op rounding contracts, established empirically
# against Pillow 12 (mixed f32 storage with f64 expression arithmetic, i.e.
# C `float` variables in `double` expressions):
#
#   * convert("L"):  (19595 R + 38470 G + 7471 B + 0x8000) >> 16
#   * Image.blend:   f32(deg + factor * (img - deg)), clip, TRUNCATE
#   * convert("HSV") H: f32 chain with f64 expression arithmetic, trunc;
#     S: trunc(255 cr / maxc); V: maxc
#   * convert("RGB") from HSV: classic sextant formula, p/q/t rounded
#     half-up, truncated sector index
def _blend_u8(deg, img_f32, factor: float):
    """PIL Image.blend on uint8 planes: f32 math, clip, truncate."""
    out = deg + np.float32(factor) * (img_f32 - deg)
    return np.clip(out, 0.0, 255.0).astype(np.uint8)


def _luma_u8(arr: np.ndarray) -> np.ndarray:
    """PIL convert("L") — exact integer rounding."""
    r = arr[..., 0].astype(np.uint32)
    g = arr[..., 1].astype(np.uint32)
    b = arr[..., 2].astype(np.uint32)
    return ((19595 * r + 38470 * g + 7471 * b + 0x8000) >> 16).astype(
        np.uint8
    )


def _adjust_hue_array(
    arr: np.ndarray, factor: float, shift_u8: Optional[int] = None
) -> np.ndarray:
    """uint8 RGB -> PIL-exact HSV -> uint8 hue shift -> PIL-exact RGB.
    `shift_u8` overrides the factor-derived shift (native.hue_shift's
    fallback passes the shift it was handed)."""
    f32, f64 = np.float32, np.float64
    r = arr[..., 0].astype(f32)
    g = arr[..., 1].astype(f32)
    b = arr[..., 2].astype(f32)
    maxc = np.maximum(np.maximum(r, g), b)
    minc = np.minimum(np.minimum(r, g), b)
    cr = maxc - minc
    achrom = cr == 0
    safe_cr = np.where(achrom, f32(1), cr)
    safe_max = np.where(maxc == 0, f32(1), maxc)
    # C float variables, double expression arithmetic (see contract above)
    rc = ((maxc - r) / safe_cr).astype(f64)
    gc = ((maxc - g) / safe_cr).astype(f64)
    bc = ((maxc - b) / safe_cr).astype(f64)
    h = np.where(
        r == maxc, bc - gc, np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc)
    ).astype(f32)
    h = (h.astype(f64) / 6.0).astype(f32)
    h = np.where(h < 0, (h.astype(f64) + 1.0).astype(f32), h)
    hue = (h.astype(f64) * 255.0).astype(np.uint8)
    hue = np.where(achrom, np.uint8(0), hue)
    sat = (cr.astype(f64) * 255.0 / safe_max.astype(f64)).astype(np.uint8)
    sat = np.where(achrom | (maxc == 0), np.uint8(0), sat)

    if shift_u8 is None:
        shift_u8 = int(factor * 255) % 256
    hue = hue + np.uint8(shift_u8 % 256)  # uint8 wrap = hue circle

    # hsv2rgb: PURE float32 arithmetic (verified exhaustively against PIL
    # over all 2^24 HSV values — the mixed-f64 variant diverges ~1/10^6);
    # sector index truncates, p/q/t round half-up
    fh = (hue.astype(f32) * f32(6.0) / f32(255.0)).astype(f32)
    sector = fh.astype(np.int32)
    f = (fh - sector.astype(f32)).astype(f32)
    fs = (sat.astype(f32) / f32(255.0)).astype(f32)
    v32 = maxc.astype(f32)
    p = (v32 * (f32(1.0) - fs) + f32(0.5)).astype(np.int32)
    q = (v32 * (f32(1.0) - fs * f) + f32(0.5)).astype(np.int32)
    t = (v32 * (f32(1.0) - fs * (f32(1.0) - f)) + f32(0.5)).astype(np.int32)
    v = maxc.astype(np.int32)
    s6 = np.mod(sector, 6)
    conds = [s6 == i for i in range(6)]
    out = np.stack(
        [
            np.select(conds, [v, q, p, p, t, v]),
            np.select(conds, [t, v, v, q, p, p]),
            np.select(conds, [p, p, t, v, v, q]),
        ],
        axis=-1,
    )
    gray = (sat == 0)[..., None]
    return np.where(gray, maxc.astype(np.int32)[..., None], out).astype(
        np.uint8
    )


def _color_jitter_pil(img, rng, brightness, contrast, saturation, hue):
    """The original PIL implementation — retained as the oracle for the
    bit-exactness test of the vectorized default below."""
    factors = {
        0: rng.uniform(*brightness),
        1: rng.uniform(*contrast),
        2: rng.uniform(*saturation),
        3: rng.uniform(*hue),
    }
    order = rng.permutation(4)
    img = img.convert("RGB")
    for t in order:
        if t == 0:
            img = ImageEnhance.Brightness(img).enhance(factors[0])
        elif t == 1:
            img = ImageEnhance.Contrast(img).enhance(factors[1])
        elif t == 2:
            img = ImageEnhance.Color(img).enhance(factors[2])
        else:
            img = _adjust_hue(img, factors[3])
    return img


def color_jitter(
    img: Image.Image,
    rng: np.random.Generator,
    brightness: Tuple[float, float] = (0.6, 1.4),
    contrast: Tuple[float, float] = (0.6, 1.4),
    saturation: Tuple[float, float] = (0.6, 1.4),
    hue: Tuple[float, float] = (-0.02, 0.02),
) -> Image.Image:
    """torchvision ColorJitter: uniform factor per property, applied in a
    random order (reference main.py:100's exact ranges are the defaults).
    Vectorized numpy implementation, bit-exact with the PIL stack it
    replaced (same RNG draw order, so identical across the swap)."""
    factors = {
        0: rng.uniform(*brightness),
        1: rng.uniform(*contrast),
        2: rng.uniform(*saturation),
        3: rng.uniform(*hue),
    }
    order = rng.permutation(4)
    arr = np.asarray(img.convert("RGB"), np.uint8)
    # the native entry points each carry their own bit-exact numpy fallback
    # (built from this module's _blend_u8/_luma_u8/_adjust_hue_array), so
    # they are simply called unconditionally
    for t in order:
        if t == 0:
            arr = native.jitter_brightness(arr, factors[0])
        elif t == 1:
            arr = native.jitter_contrast(arr, factors[1])
        elif t == 2:
            arr = native.jitter_saturation(arr, factors[2])
        elif abs(factors[3]) >= 1e-8:
            # NB: the HSV round-trip is lossy, so it applies whenever the
            # PIL path would have (even when the uint8 shift lands on 0)
            arr = native.hue_shift(arr, int(factors[3] * 255) % 256)
    return Image.fromarray(arr)


def _inverse_affine_matrix(
    center: Tuple[float, float],
    angle_deg: float,
    translate: Tuple[float, float],
    scale: float,
    shear_deg: Tuple[float, float],
) -> List[float]:
    """Inverse of the torchvision affine (output->input, for PIL AFFINE).

    Follows the matrix convention of torchvision.transforms.functional:
    M = T(center) R(angle) S(scale) Sh(shear) T(-center) T(translate)^-1 ...
    computed directly as the inverse map."""
    rot = math.radians(angle_deg)
    sx, sy = (math.radians(s) for s in shear_deg)
    cx, cy = center
    tx, ty = translate

    # RSS: rotation * shear * scale (forward), per torchvision
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)

    # inverse of scale * RSS
    det = a * d - b * c
    ia, ib, ic, id_ = d / det, -b / det, -c / det, a / det
    ia, ib, ic, id_ = (v / scale for v in (ia, ib, ic, id_))

    # inverse translation: x_in = inv(RSS) @ (x_out - center - translate) + center
    m02 = ia * (-cx - tx) + ib * (-cy - ty) + cx
    m12 = ic * (-cx - tx) + id_ * (-cy - ty) + cy
    return [ia, ib, m02, ic, id_, m12]


def random_affine(
    img: Image.Image,
    rng: np.random.Generator,
    degrees: float = 25.0,
    translate: Tuple[float, float] = (0.05, 0.05),
    shear: Tuple[float, float] = (-15.0, 15.0),
) -> Image.Image:
    """torchvision RandomAffine(degrees=25, shear=(-15,15),
    translate=[.05,.05]) — reference main.py:102. A 2-tuple shear range
    shears the x axis only."""
    w, h = img.size
    angle = rng.uniform(-degrees, degrees)
    max_dx = translate[0] * w
    max_dy = translate[1] * h
    tx = float(np.round(rng.uniform(-max_dx, max_dx)))
    ty = float(np.round(rng.uniform(-max_dy, max_dy)))
    shear_x = rng.uniform(shear[0], shear[1])
    matrix = _inverse_affine_matrix(
        ((w - 1) * 0.5, (h - 1) * 0.5), angle, (tx, ty), 1.0, (shear_x, 0.0)
    )
    return img.transform((w, h), Image.AFFINE, matrix, BILINEAR)


def random_resized_crop(
    img: Image.Image,
    rng: np.random.Generator,
    size: int,
    scale: Tuple[float, float] = (0.6, 1.0),
    ratio: Tuple[float, float] = (3.0 / 4.0, 4.0 / 3.0),
) -> Image.Image:
    """torchvision RandomResizedCrop(size, scale=(0.6, 1.0)) — reference
    main.py:103. 10 attempts, then center-crop fallback."""
    w, h = img.size
    area = w * h
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(*log_ratio))
        cw = int(round(math.sqrt(target_area * aspect)))
        ch = int(round(math.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            x0 = int(rng.integers(0, w - cw + 1))
            y0 = int(rng.integers(0, h - ch + 1))
            return img.resize(
                (size, size), BILINEAR, box=(x0, y0, x0 + cw, y0 + ch)
            )
    # fallback: largest valid center crop
    in_ratio = w / h
    if in_ratio < ratio[0]:
        cw, ch = w, int(round(w / ratio[0]))
    elif in_ratio > ratio[1]:
        ch, cw = h, int(round(h * ratio[1]))
    else:
        cw, ch = w, h
    x0 = (w - cw) // 2
    y0 = (h - ch) // 2
    return img.resize((size, size), BILINEAR, box=(x0, y0, x0 + cw, y0 + ch))


# ---------------------------------------------------------------- pipelines
Transform = Callable[[Image.Image, Optional[np.random.Generator]], np.ndarray]

# The pipelines are CLASSES, not closures: datasets holding a transform must
# be picklable so the loader's spawn-based process workers can receive them
# (mgproto_tpu/data/loader.py; closures can't cross a spawn boundary). The
# factory functions below keep the call-site API unchanged.


class TrainTransform:
    """The reference's training augmentation stack (main.py:98-106).

    `device_augment=True` is the host half of the uint8 wire format
    (ops/augment.py): only the geometry ops that need PIL resampling —
    perspective, affine, resized-crop — run here, and the output stays
    uint8 [H, W, 3]. Flip + the whole color jitter (brightness/contrast/
    saturation/hue) + normalize then run inside the jitted train step,
    seeded per sample. The wire carries 4x fewer bytes at every hop
    (worker -> parent IPC, host -> device copy), and the host sheds the
    jitter math — including the HSV hue round trip, the profiled hot spot
    of the whole stack at flagship sizes."""

    def __init__(self, img_size: int, device_augment: bool = False):
        self.img_size = img_size
        self.device_augment = device_augment

    def __call__(self, img: Image.Image, rng: np.random.Generator) -> np.ndarray:
        img = img.convert("RGB")
        img = random_perspective(img, rng)
        if self.device_augment:
            img = random_affine(img, rng)
            img = random_resized_crop(img, rng, self.img_size)
            return np.asarray(img.convert("RGB"), np.uint8)
        img = color_jitter(img, rng)
        img = random_horizontal_flip(img, rng)
        img = random_affine(img, rng)
        img = random_resized_crop(img, rng, self.img_size)
        return _to_norm_f32(img)


class PushTransform:
    """Resize-only, UNNORMALIZED (main.py:111-116)."""

    def __init__(self, img_size: int):
        self.img_size = img_size

    def __call__(self, img: Image.Image, rng=None) -> np.ndarray:
        return _to_f32(resize(img, (self.img_size, self.img_size)))


class TestTransform:
    """Resize(shorter=img+32) + CenterCrop (main.py:128-135)."""

    def __init__(self, img_size: int):
        self.img_size = img_size

    def __call__(self, img: Image.Image, rng=None) -> np.ndarray:
        return _to_norm_f32(
            center_crop(resize(img, self.img_size + 32), self.img_size)
        )


class OodTransform:
    """Exact-resize + normalize (main.py:141-163)."""

    def __init__(self, img_size: int):
        self.img_size = img_size

    def __call__(self, img: Image.Image, rng=None) -> np.ndarray:
        return _to_norm_f32(resize(img, (self.img_size, self.img_size)))


def train_transform(img_size: int, device_augment: bool = False) -> Transform:
    return TrainTransform(img_size, device_augment=device_augment)


def push_transform(img_size: int) -> Transform:
    return PushTransform(img_size)


def test_transform(img_size: int) -> Transform:
    return TestTransform(img_size)


def ood_transform(img_size: int) -> Transform:
    return OodTransform(img_size)
