"""Datasets: class-folder trees and the CUB eval metadata set.

Reference: torchvision `ImageFolder` (used inline, main.py:96-163),
`MyImageFolder` adding file paths (utils/helpers.py:8-10), and `Cub2011Eval`
adding CUB image ids (utils/datasets.py:7-57). No import-time I/O — datasets
scan their roots at construction (cf. reference utils/local_parts.py:14-81
which parses files at import)."""

from __future__ import annotations

import os
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

IMG_EXTENSIONS = (
    ".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif", ".tiff", ".webp",
)


class Sample(NamedTuple):
    path: str
    label: int
    sample_id: int  # global dataset index (or CUB img_id for Cub2011Eval)


class ImageFolder:
    """Class-per-subdirectory dataset, torchvision-compatible layout.

    Classes are the sorted subdirectory names (torchvision's convention, so
    label ids match checkpoints trained by the reference); file lists are
    sorted for a deterministic id <-> path mapping."""

    def __init__(
        self,
        root: str,
        transform: Optional[Callable] = None,
        extensions: Sequence[str] = IMG_EXTENSIONS,
    ):
        self.root = os.path.expanduser(root)
        self.transform = transform
        classes = sorted(
            e.name for e in os.scandir(self.root) if e.is_dir()
        )
        if not classes:
            raise FileNotFoundError(f"no class directories under {self.root}")
        self.classes: List[str] = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: List[Sample] = []
        exts = tuple(e.lower() for e in extensions)
        for c in classes:
            cdir = os.path.join(self.root, c)
            for dirpath, _, filenames in sorted(os.walk(cdir)):
                for fname in sorted(filenames):
                    if fname.lower().endswith(exts):
                        self.samples.append(
                            Sample(
                                os.path.join(dirpath, fname),
                                self.class_to_idx[c],
                                len(self.samples),
                            )
                        )
        if not self.samples:
            raise FileNotFoundError(f"no images under {self.root}")

    def __len__(self) -> int:
        return len(self.samples)

    def load(
        self, index: int, rng: Optional[np.random.Generator] = None
    ) -> Tuple[np.ndarray, int, int]:
        s = self.samples[index]
        with Image.open(s.path) as img:
            img = img.convert("RGB")
            arr = (
                self.transform(img, rng) if self.transform is not None
                else np.asarray(img, np.float32) / 255.0
            )
        return arr, s.label, s.sample_id

    def path_of(self, sample_id: int) -> str:
        return self.samples[sample_id].path


class Cub2011Eval:
    """CUB-200-2011 with official ids, for part-annotation metrics.

    Reference utils/datasets.py:7-57: joins images.txt +
    image_class_labels.txt + train_test_split.txt; yields (img, target,
    img_id) with the OFFICIAL 1-based CUB img_id (needed to index the part
    annotation tables)."""

    base_folder = "images"

    def __init__(
        self, root: str, train: bool = True, transform: Optional[Callable] = None
    ):
        import pandas as pd

        self.root = os.path.expanduser(root)
        self.transform = transform
        images = pd.read_csv(
            os.path.join(self.root, "images.txt"),
            sep=" ", names=["img_id", "filepath"],
        )
        labels = pd.read_csv(
            os.path.join(self.root, "image_class_labels.txt"),
            sep=" ", names=["img_id", "target"],
        )
        split = pd.read_csv(
            os.path.join(self.root, "train_test_split.txt"),
            sep=" ", names=["img_id", "is_training_img"],
        )
        data = images.merge(labels, on="img_id").merge(split, on="img_id")
        data = data[data.is_training_img == (1 if train else 0)]
        self.samples = [
            Sample(
                os.path.join(self.root, self.base_folder, row.filepath),
                int(row.target) - 1,  # 1-based -> 0-based
                int(row.img_id),
            )
            for row in data.itertuples()
        ]

    def __len__(self) -> int:
        return len(self.samples)

    def load(
        self, index: int, rng: Optional[np.random.Generator] = None
    ) -> Tuple[np.ndarray, int, int]:
        s = self.samples[index]
        with Image.open(s.path) as img:
            img = img.convert("RGB")
            arr = (
                self.transform(img, rng) if self.transform is not None
                else np.asarray(img, np.float32) / 255.0
            )
        return arr, s.label, s.sample_id
