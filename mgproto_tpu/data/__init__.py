"""Data layer: datasets, transforms, loaders.

Replaces the reference's inline torchvision pipelines (reference
main.py:96-163) with torchvision-free PIL/numpy transforms and a threaded
prefetching loader."""

from mgproto_tpu.data.folder import Cub2011Eval, ImageFolder, Sample
from mgproto_tpu.data.loader import DataLoader
from mgproto_tpu.data.transforms import (
    ood_transform,
    push_transform,
    test_transform,
    train_transform,
)

__all__ = [
    "Cub2011Eval",
    "ImageFolder",
    "Sample",
    "DataLoader",
    "ood_transform",
    "push_transform",
    "test_transform",
    "train_transform",
]


def build_pipelines(cfg):
    """The reference's four loaders from one DataConfig (main.py:96-163):
    (train, push, test, [ood...]) — ood list may be empty. With the uint8
    wire format on (DataConfig.device_augment, auto on TPU) the train
    loader yields (u8 images, labels, ids, augment seeds) 4-tuples; the
    others keep their f32 3-tuples.

    Under multi-host (`jax.distributed`), every loader shards its dataset by
    process: each host loads a disjoint 1/num_processes of every global
    batch, and eval/push gather per-shard results (parallel/multihost.py).
    """
    import jax

    from mgproto_tpu.config import Config
    from mgproto_tpu.ops.augment import resolve_device_augment

    assert isinstance(cfg, Config)
    shard = dict(
        shard_index=jax.process_index(), shard_count=jax.process_count()
    )
    d, img = cfg.data, cfg.model.img_size
    # uint8 wire format: the train transform stops at geometry and returns
    # u8; flip + b/c/s jitter + normalize run inside the jitted step,
    # seeded per sample by the loader (with_seeds). Eval/push pipelines are
    # deterministic resize-only and stay host-side f32.
    device_augment = resolve_device_augment(d.device_augment)
    wire_dtype = "uint8" if device_augment else "float32"
    # worker_backend applies to the TRAIN loader only: the augmentation
    # stack is the GIL-bound stage; push/test/ood are resize-only, and a
    # per-loader persistent spawn pool would sit idle on each of them
    train = DataLoader(
        ImageFolder(d.train_dir, train_transform(img, device_augment)),
        d.train_batch_size,
        shuffle=True,
        drop_last=True,
        num_workers=d.num_workers,
        worker_backend=d.worker_backend,
        seed=cfg.seed,
        with_seeds=device_augment,
        sample_spec=((img, img, 3), wire_dtype),
        **shard,
    )
    push = DataLoader(
        ImageFolder(d.train_push_dir, push_transform(img)),
        d.train_push_batch_size,
        num_workers=d.num_workers,
        **shard,
    )
    test = DataLoader(
        ImageFolder(d.test_dir, test_transform(img)),
        d.test_batch_size,
        num_workers=d.num_workers,
        **shard,
    )
    oods = [
        DataLoader(
            ImageFolder(o, ood_transform(img)),
            d.test_batch_size,
            num_workers=d.num_workers,
            **shard,
        )
        for o in d.ood_dirs
    ]
    return train, push, test, oods
