"""ctypes bindings for the native host-pipeline kernels (csrc/mgproto_native.cc).

Auto-builds `libmgproto_native.so` with g++ on first use (cached next to this
file); every entry point has a pure-numpy fallback so the package works
without a toolchain. Disable with MGPROTO_NATIVE=0.

The kernels fuse the per-image uint8 HWC -> normalized f32 conversion of the
input pipeline (reference ToTensor+Normalize, main.py:98-135) into a single
LUT pass — see csrc/mgproto_native.cc for why this is native.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_LIB_NAME = "libmgproto_native.so"
_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, os.pardir, os.pardir, "csrc", "mgproto_native.cc")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build(lib_path: str) -> bool:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return False
    # compile to a pid-suffixed temp path and rename into place atomically:
    # concurrent first-builds (loader workers, pytest-xdist) must never leave
    # a half-written .so that poisons every later load
    tmp_path = f"{lib_path}.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        src, "-o", tmp_path,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp_path, lib_path)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("MGPROTO_NATIVE", "1") == "0":
            return None
        lib_path = os.path.join(_HERE, _LIB_NAME)
        if not os.path.exists(lib_path) and not _build(lib_path):
            return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.mg_u8hwc_to_f32_norm.argtypes = [
            u8p, ctypes.c_int64, f32p, f32p, f32p
        ]
        lib.mg_u8hwc_to_f32.argtypes = [u8p, ctypes.c_int64, f32p]
        lib.mg_batch_u8hwc_to_f32_norm.argtypes = [
            ctypes.POINTER(u8p), ctypes.c_int32, ctypes.c_int64,
            f32p, f32p, f32p, ctypes.c_int32,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _scale_bias(mean: np.ndarray, std: np.ndarray):
    mean = np.asarray(mean, np.float32).reshape(3)
    std = np.asarray(std, np.float32).reshape(3)
    scale = (1.0 / (255.0 * std)).astype(np.float32)
    bias = (-mean / std).astype(np.float32)
    return scale, bias


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def u8_to_f32_norm(
    img: np.ndarray, mean: np.ndarray, std: np.ndarray
) -> np.ndarray:
    """[H, W, 3] u8 -> (x/255 - mean)/std f32, one fused native pass
    (numpy fallback when the library is unavailable)."""
    lib = _load()
    img = np.ascontiguousarray(img, np.uint8)
    if lib is None or img.ndim != 3 or img.shape[-1] != 3:
        x = img.astype(np.float32) / 255.0
        return ((x - np.asarray(mean, np.float32))
                / np.asarray(std, np.float32)).astype(np.float32)
    scale, bias = _scale_bias(mean, std)
    out = np.empty(img.shape, np.float32)
    lib.mg_u8hwc_to_f32_norm(
        _u8p(img), img.shape[0] * img.shape[1], _f32p(scale), _f32p(bias),
        _f32p(out),
    )
    return out


def u8_to_f32(img: np.ndarray) -> np.ndarray:
    """[...] u8 -> f32 in [0, 1]."""
    lib = _load()
    img = np.ascontiguousarray(img, np.uint8)
    if lib is None:
        return img.astype(np.float32) / 255.0
    out = np.empty(img.shape, np.float32)
    lib.mg_u8hwc_to_f32(_u8p(img), img.size, _f32p(out))
    return out


def batch_u8_to_f32_norm(
    imgs: List[np.ndarray],
    mean: np.ndarray,
    std: np.ndarray,
    nthreads: int = 0,
) -> np.ndarray:
    """Stack + convert + normalize a batch of same-shape [H, W, 3] u8 images
    into one [B, H, W, 3] f32 array, threaded in native code."""
    lib = _load()
    shapes_ok = (
        len(imgs) > 0
        and all(i.ndim == 3 and i.shape == imgs[0].shape for i in imgs)
        and imgs[0].shape[-1] == 3
    )
    if lib is None or not shapes_ok:
        return np.stack([u8_to_f32_norm(i, mean, std) for i in imgs])
    imgs = [np.ascontiguousarray(i, np.uint8) for i in imgs]
    h, w, _ = imgs[0].shape
    b = len(imgs)
    out = np.empty((b, h, w, 3), np.float32)
    scale, bias = _scale_bias(mean, std)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    ptrs = (u8p * b)(*[_u8p(i) for i in imgs])
    if nthreads <= 0:
        nthreads = min(b, os.cpu_count() or 1)
    lib.mg_batch_u8hwc_to_f32_norm(
        ptrs, b, h * w, _f32p(scale), _f32p(bias), _f32p(out), nthreads
    )
    return out
