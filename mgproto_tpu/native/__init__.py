"""ctypes bindings for the native host-pipeline kernels (csrc/mgproto_native.cc).

Auto-builds `libmgproto_native.so` with g++ on first use (cached next to this
file); every entry point has a pure-numpy fallback so the package works
without a toolchain. Disable with MGPROTO_NATIVE=0.

The kernels fuse the per-image uint8 HWC -> normalized f32 conversion of the
input pipeline (reference ToTensor+Normalize, main.py:98-135) into a single
LUT pass — see csrc/mgproto_native.cc for why this is native.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, os.pardir, os.pardir, "csrc", "mgproto_native.cc")


def _host_tag() -> str:
    """Short fingerprint of this host's ISA. The .so is built -march=native
    and cached in the package dir; on a checkout shared across heterogeneous
    hosts (NFS-mounted repo, image built on one CPU and run on another) a
    same-named cache from a wider-ISA host would SIGILL here — keying the
    filename by CPU feature flags makes each host build (and load) its own."""
    try:
        import hashlib
        import platform

        flags = ""
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    if line.startswith("flags"):
                        flags = " ".join(sorted(line.split(":", 1)[1].split()))
                        break
        except OSError:
            pass
        return hashlib.sha1(
            (platform.machine() + ":" + flags).encode()
        ).hexdigest()[:12]
    except Exception:
        return "generic"


_LIB_NAME = f"libmgproto_native-{_host_tag()}.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build(lib_path: str) -> bool:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return False
    # compile to a pid-suffixed temp path and rename into place atomically:
    # concurrent first-builds (loader workers, pytest-xdist) must never leave
    # a half-written .so that poisons every later load
    tmp_path = f"{lib_path}.{os.getpid()}"
    # -march=native vectorizes the jitter blend loops (~2x on them); the .so
    # is built on (and cached next to) the host that runs it, so native
    # tuning is safe — with a portable fallback for unusual toolchains.
    # -ffp-contract=off is REQUIRED for bit-exactness: FMA contraction would
    # skip the intermediate f32 rounding that PIL's two-step blend performs
    # (caught by the fallback-vs-native equality check in tests).
    for extra in (["-march=native", "-funroll-loops"], []):
        cmd = [
            "g++", "-O3", "-ffp-contract=off", *extra, "-shared", "-fPIC",
            "-std=c++17", "-pthread", src, "-o", tmp_path,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp_path, lib_path)
            return True
        except (OSError, subprocess.SubprocessError):
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("MGPROTO_NATIVE", "1") == "0":
            return None
        lib_path = os.path.join(_HERE, _LIB_NAME)
        src = os.path.abspath(_SRC)
        # rebuild when the cached .so predates the source (a stale cache
        # would lack newly added symbols and poison every binding below)
        stale = (
            os.path.exists(lib_path)
            and os.path.exists(src)
            and os.path.getmtime(lib_path) < os.path.getmtime(src)
        )
        if (not os.path.exists(lib_path) or stale) and not _build(lib_path):
            if not os.path.exists(lib_path):
                return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        try:
            lib.mg_u8hwc_to_f32_norm.argtypes = [
                u8p, ctypes.c_int64, f32p, f32p, f32p
            ]
            lib.mg_u8hwc_to_f32.argtypes = [u8p, ctypes.c_int64, f32p]
            lib.mg_batch_u8hwc_to_f32_norm.argtypes = [
                ctypes.POINTER(u8p), ctypes.c_int32, ctypes.c_int64,
                f32p, f32p, f32p, ctypes.c_int32,
            ]
            for name in (
                "mg_jitter_brightness", "mg_jitter_contrast",
                "mg_jitter_saturation",
            ):
                getattr(lib, name).argtypes = [
                    u8p, ctypes.c_int64, ctypes.c_float, u8p
                ]
            lib.mg_hue_shift.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int32, u8p
            ]
        except AttributeError:
            # .so exists but lacks a symbol (stale cache that could not be
            # rebuilt, e.g. read-only dir without g++) — numpy fallbacks
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _scale_bias(mean: np.ndarray, std: np.ndarray):
    mean = np.asarray(mean, np.float32).reshape(3)
    std = np.asarray(std, np.float32).reshape(3)
    scale = (1.0 / (255.0 * std)).astype(np.float32)
    bias = (-mean / std).astype(np.float32)
    return scale, bias


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def u8_to_f32_norm(
    img: np.ndarray, mean: np.ndarray, std: np.ndarray
) -> np.ndarray:
    """[H, W, 3] u8 -> (x/255 - mean)/std f32, one fused native pass
    (numpy fallback when the library is unavailable)."""
    lib = _load()
    img = np.ascontiguousarray(img, np.uint8)
    if lib is None or img.ndim != 3 or img.shape[-1] != 3:
        x = img.astype(np.float32) / 255.0
        return ((x - np.asarray(mean, np.float32))
                / np.asarray(std, np.float32)).astype(np.float32)
    scale, bias = _scale_bias(mean, std)
    out = np.empty(img.shape, np.float32)
    lib.mg_u8hwc_to_f32_norm(
        _u8p(img), img.shape[0] * img.shape[1], _f32p(scale), _f32p(bias),
        _f32p(out),
    )
    return out


def u8_to_f32(img: np.ndarray) -> np.ndarray:
    """[...] u8 -> f32 in [0, 1]."""
    lib = _load()
    img = np.ascontiguousarray(img, np.uint8)
    if lib is None:
        return img.astype(np.float32) / 255.0
    out = np.empty(img.shape, np.float32)
    lib.mg_u8hwc_to_f32(_u8p(img), img.size, _f32p(out))
    return out


def batch_u8_to_f32_norm(
    imgs: List[np.ndarray],
    mean: np.ndarray,
    std: np.ndarray,
    nthreads: int = 0,
) -> np.ndarray:
    """Stack + convert + normalize a batch of same-shape [H, W, 3] u8 images
    into one [B, H, W, 3] f32 array, threaded in native code."""
    lib = _load()
    shapes_ok = (
        len(imgs) > 0
        and all(i.ndim == 3 and i.shape == imgs[0].shape for i in imgs)
        and imgs[0].shape[-1] == 3
    )
    if lib is None or not shapes_ok:
        return np.stack([u8_to_f32_norm(i, mean, std) for i in imgs])
    imgs = [np.ascontiguousarray(i, np.uint8) for i in imgs]
    h, w, _ = imgs[0].shape
    b = len(imgs)
    out = np.empty((b, h, w, 3), np.float32)
    scale, bias = _scale_bias(mean, std)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    ptrs = (u8p * b)(*[_u8p(i) for i in imgs])
    if nthreads <= 0:
        nthreads = min(b, os.cpu_count() or 1)
    lib.mg_batch_u8hwc_to_f32_norm(
        ptrs, b, h * w, _f32p(scale), _f32p(bias), _f32p(out), nthreads
    )
    return out


# ------------------------- color-jitter kernels (csrc fused single passes)
def jitter_available() -> bool:
    """True when the native jitter kernels are loadable (transforms.py then
    routes ColorJitter through them; numpy fallback otherwise)."""
    return _load() is not None


def _check_jitter_img(img: np.ndarray, op: str) -> None:
    """Reject empty images BEFORE they reach native code: a zero-pixel
    array sent to mg_jitter_contrast divided by n_px == 0 (NaN + an
    undefined float->int cast, ADVICE r5); the numpy fallbacks would
    likewise produce nonsense means. An explicit error beats either."""
    if img.size == 0:
        raise ValueError(f"{op}: empty image (zero pixels)")


def jitter_brightness(img: np.ndarray, factor: float) -> np.ndarray:
    """PIL ImageEnhance.Brightness.enhance(factor), bit-exact, one pass
    (bit-exact numpy fallback without the library, like every other entry
    point here)."""
    lib = _load()
    _check_jitter_img(np.asarray(img), 'jitter_brightness')
    img = np.ascontiguousarray(img, np.uint8)
    if lib is None:
        from mgproto_tpu.data import transforms as _t

        return _t._blend_u8(
            np.float32(0), img.astype(np.float32), factor
        )
    out = np.empty_like(img)
    lib.mg_jitter_brightness(
        _u8p(img), img.shape[0] * img.shape[1], np.float32(factor), _u8p(out)
    )
    return out


def jitter_contrast(img: np.ndarray, factor: float) -> np.ndarray:
    """PIL ImageEnhance.Contrast.enhance(factor), bit-exact, one pass
    (plus the internal L-mean reduction)."""
    lib = _load()
    _check_jitter_img(np.asarray(img), 'jitter_contrast')
    img = np.ascontiguousarray(img, np.uint8)
    if lib is None:
        from mgproto_tpu.data import transforms as _t

        mean = np.float32(int(_t._luma_u8(img).mean() + 0.5))
        return _t._blend_u8(mean, img.astype(np.float32), factor)
    out = np.empty_like(img)
    lib.mg_jitter_contrast(
        _u8p(img), img.shape[0] * img.shape[1], np.float32(factor), _u8p(out)
    )
    return out


def jitter_saturation(img: np.ndarray, factor: float) -> np.ndarray:
    """PIL ImageEnhance.Color.enhance(factor), bit-exact, one pass."""
    lib = _load()
    _check_jitter_img(np.asarray(img), 'jitter_saturation')
    img = np.ascontiguousarray(img, np.uint8)
    if lib is None:
        from mgproto_tpu.data import transforms as _t

        lum = _t._luma_u8(img).astype(np.float32)[..., None]
        return _t._blend_u8(lum, img.astype(np.float32), factor)
    out = np.empty_like(img)
    lib.mg_jitter_saturation(
        _u8p(img), img.shape[0] * img.shape[1], np.float32(factor), _u8p(out)
    )
    return out


def hue_shift(img: np.ndarray, shift: int) -> np.ndarray:
    """Fused RGB->HSV->(H+shift)->RGB, bit-exact with PIL's convert chain.
    NB: the fallback takes a hue FACTOR path upstream; this entry's fallback
    reproduces the same result from the uint8 shift directly."""
    lib = _load()
    _check_jitter_img(np.asarray(img), 'hue_shift')
    img = np.ascontiguousarray(img, np.uint8)
    if lib is None:
        from mgproto_tpu.data import transforms as _t

        return _t._adjust_hue_array(img, 0.0, shift_u8=int(shift))
    out = np.empty_like(img)
    lib.mg_hue_shift(
        _u8p(img), img.shape[0] * img.shape[1], np.int32(shift), _u8p(out)
    )
    return out
