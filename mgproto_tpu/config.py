"""Configuration for MGProto-TPU.

One typed, side-effect-free config tree replacing the reference's two-tier
module-constant + argparse system (reference settings.py:1-52, main.py:19-27).
No import-time I/O (cf. reference utils/local_parts.py:14-81).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Model architecture config (reference model.py:78-174, settings.py:1-5)."""

    arch: str = "resnet34"
    img_size: int = 224
    num_classes: int = 200
    # reference prototype_shape = (num_classes*K, d, 1, 1) (settings.py:3)
    prototypes_per_class: int = 10
    proto_dim: int = 64
    add_on_type: str = "regular"  # 'regular' | 'bottleneck' (model.py:117-143)
    sz_embedding: int = 32  # aux DML embedding width (model.py:146)
    mine_T: int = 20  # top-T mining levels (main.py:26 -mine_level)
    mem_capacity: int = 800  # per-class memory capacity (main.py:25 -mem_sz)
    # Gaussian prototype init std sigma = 1/sqrt(2*pi) (model.py:151)
    init_sigma: float = 1.0 / math.sqrt(2.0 * math.pi)
    pretrained: bool = False
    # dtype policy: params/activations compute dtype. Density math is always f32
    # (OoD thresholds depend on p(x) scale; see SURVEY.md §7.3.5). The full
    # statement of what runs in which dtype — and what is deliberately NOT a
    # knob (f32 master params, optimizer moments, EM statistics, bank,
    # scores) — is perf/precision.py's PrecisionPolicy; "float32" and
    # "bfloat16" are the supported values (validated there).
    compute_dtype: str = "float32"
    # Route density + top-T through the fused Pallas kernel
    # (ops/fused_scoring.py). Identical numerics (tests/test_fused_scoring.py).
    # None = auto: ON for TPU backends with an unsharded class axis — measured
    # 1.9x faster than the XLA path on real hardware (1016 vs 532 img/s/chip,
    # BENCH_PROBE_RUN.json) — OFF elsewhere (the CPU interpret-mode fallback
    # is correct but slow, and SPMD cannot partition a pallas_call over the
    # class axis). True/False force the path regardless of backend.
    fused_scoring: Optional[bool] = None
    # jax.checkpoint the backbone blocks (ResNet/DenseNet): backward
    # recomputes block internals instead of storing activations — enables
    # larger per-chip batches at ~1/3 extra FLOPs.
    remat: bool = False
    # Selective per-stage remat: checkpoint only the named backbone stages
    # ("layer1".."layer4" for resnets, "denseblock1".."denseblock4" for
    # densenets). The sweet spot for this model family is ("layer1",): the
    # reference's no-stem-pool quirk makes layer1 run at 112^2 with only 64
    # channels — cheap to recompute but the widest activations in the trunk
    # (PERF.md MFU-headroom decomposition) — so rematting it alone buys most
    # of the HBM headroom at a fraction of full-remat's recompute tax.
    # Ignored when `remat` is True (full-trunk remat wins).
    remat_stages: Tuple[str, ...] = ()
    # Fused BN+residual+ReLU block epilogue (ops/fused_epilogue.py): the
    # residual tail of every ResNet block — BatchNorm apply + shortcut add
    # + ReLU — runs as ONE Pallas VMEM pass instead of a chain of
    # elementwise ops XLA may or may not fuse across the residual
    # junction. The top entry of the byte-ranked fusion table
    # (scripts/trace_report.py top_byte_movers) at flagship shapes is this
    # epilogue at layer1's 112^2 resolution. Identical numerics: the
    # backward is the exact VJP of the XLA reference (recomputed, remat-
    # style), parity-pinned in tests/test_fused_epilogue.py. None = auto:
    # ON for TPU backends with a resnet trunk, OFF elsewhere (the CPU
    # interpret-mode kernel is correct but slow). True/False force.
    fused_epilogue: Optional[bool] = None
    # Online class addition (online/classes.py): build the class axis at
    # num_classes rounded UP to a multiple of this bucket, mirroring the
    # serving batch buckets — padded slots carry zero priors (inert for
    # argmax and p(x)) until a new class claims one, so C can grow at run
    # time without recompiling the trunk. <=1 disables (exact C, the
    # pre-online behavior). Apply with online.classes.apply_class_bucket.
    class_bucket: int = 0

    @property
    def num_prototypes(self) -> int:
        return self.num_classes * self.prototypes_per_class


@dataclasses.dataclass(frozen=True)
class EMConfig:
    """EM-over-memory config (reference model.py:171-174, main.py:223-229)."""

    num_em_loop: int = 3
    alpha: float = 0.1  # responsibility additive smoothing (model.py:353)
    tau: float = 0.990  # prior momentum (model.py:174)
    diversity_lambda: float = 1.0  # diversity cost weight (model.py:367)
    mean_lr: float = 3e-3  # Adam on means (settings.py:29 'prototype_vectors')
    update_interval: int = 1  # EM every N train iterations (model.py:171)
    # False (default): TPU-native stepping — ONE Adam step per EM round over
    # all classes at once, inactive classes pinned exactly (core/em.py
    # docstring). True: reference-exact stepping — sequential per-class Adam
    # steps on the shared means tensor, reproducing the reference's
    # step-count/bias-correction bookkeeping AND its zero-grad moment-decay
    # drift of other classes' means (model.py:281-298 under one torch Adam,
    # main.py:223-227). Slower (C sequential steps per round); exists so the
    # deviation is a switch, not a belief.
    reference_stepping: bool = False
    # Compact dirty-class EM (core/em.py): at batch B only <=B of the C class
    # queues can newly satisfy `updated & full`, yet the dense path reduces
    # over all C banks every step. With a positive width A, the <=A dirty
    # banks are compacted (lax.top_k + gather) into an [A, N, d] slab, E/M
    # runs there, and means/priors scatter back — cutting EM HBM traffic
    # ~C/A x at steady state. -1 = auto (Trainer resolves to min(C, global
    # batch)); 0 disables (dense path, the pre-compaction behavior). When
    # more than A classes are dirty (e.g. the first EM call after the epoch
    # gate opens), a lax.cond falls back to the dense path for that call —
    # counted in `em_compact_fallback_total`, never a recompile. Default
    # path only; reference_stepping keeps its sequential parity scan.
    max_active_classes: int = -1
    # Fused E-step Pallas kernel (ops/em_kernels.py): per-class
    # responsibilities + sufficient statistics (sum r, sum r*x, sum r*x^2)
    # in one VMEM pass, no [N, K] responsibility or log-density intermediates
    # in HBM; the m-step objective is evaluated in sufficient-statistics form
    # (identical math, no custom VJP needed — resp are constants there).
    # None = auto: ON for TPU backends, OFF elsewhere (the interpret-mode
    # fallback is correct but slow). True/False force the path.
    fused_estep: Optional[bool] = None
    # Async bank pipeline (engine/train.py): split the train step into a
    # trunk program (forward + losses + backward + optimizer) and a bank
    # program (memory enqueue + EM), dispatching batch N's bank program
    # concurrently with batch N+1's trunk — scoring then consumes ONE-STEP-
    # STALE prototypes (deterministic, parity-pinned in
    # tests/test_async_bank.py), and the bank/EM buffers are donated to the
    # bank program so the [C, cap, d] bank never round-trips HBM as a copy.
    # None = auto: ON for TPU backends (where the hidden bank phase is HBM
    # time off the trunk's critical path), OFF elsewhere. True/False force.
    async_bank: Optional[bool] = None


@dataclasses.dataclass(frozen=True)
class OptimConfig:
    """Optimizer groups (reference main.py:205-229, settings.py:27-35)."""

    features_lr: float = 1e-4
    add_on_lr: float = 3e-3
    aux_proxies_lr: float = 1e-2  # features_lr * 100 (main.py:209)
    weight_decay: float = 1e-4  # torch-Adam style L2-in-grad
    lr_decay_gamma: float = 0.4  # StepLR gamma (main.py:212)
    lr_decay_epochs: Tuple[int, ...] = (30, 45, 60, 75, 90)  # main.py:248
    # The reference's optimizer groups omit the aux embedding Dense entirely
    # (main.py:205-220: only features/add_on/aux_criterion), so it stays at
    # its random init while gradients flow THROUGH it into the backbone.
    # False reproduces that; True trains it with the features group.
    train_embedding: bool = False


@dataclasses.dataclass(frozen=True)
class ScheduleConfig:
    """Training schedule (reference settings.py:45-52)."""

    num_train_epochs: int = 120
    num_warm_epochs: int = 0
    mine_start: int = 40
    update_gmm_start: int = 35
    push_start: int = 100
    push_every: int = 10
    prune_top_m: int = 8  # main.py:285
    # beyond-parity: renormalize kept priors after pruning (preserves each
    # class's mixture mass; see core/mgproto.py:prune_top_m). Default False =
    # reference-exact.
    prune_renormalize: bool = False

    def push_epochs(self) -> Sequence[int]:
        return [
            e
            for e in range(self.num_train_epochs)
            if e % self.push_every == 0 and e >= self.push_start
        ]


@dataclasses.dataclass(frozen=True)
class LossConfig:
    """Loss coefficients (reference settings.py:38-42) + aux loss choice."""

    crs_ent: float = 1.0
    mine: float = 0.2
    aux: float = 0.5
    aux_loss: str = "proxy_anchor"  # proxy_anchor|proxy_nca|ms|contrastive|triplet|npair


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Dataset paths + batch sizes (reference settings.py:8-24)."""

    dataset: str = "CUB"
    train_dir: str = ""
    test_dir: str = ""
    train_push_dir: str = ""
    ood_dirs: Tuple[str, ...] = ()
    train_batch_size: int = 80
    test_batch_size: int = 80
    train_push_batch_size: int = 80
    num_workers: int = 8
    # "thread" overlaps PIL decode with device compute; "process" (spawn
    # pool, dataset pickled once per worker) additionally scales the numpy
    # augmentation math past the GIL — required to reach pod-scale input
    # rates (VERDICT r3 item 5). Applied to the TRAIN loader only: push/
    # test/ood pipelines are resize-only and not GIL-bound.
    worker_backend: str = "thread"
    # device_prefetch depth (data/loader.py): batches held in flight so batch
    # N+1's host->device copy overlaps step N's compute. Each extra unit
    # costs one batch of HBM (~154 MB at flagship batch 256); >2 only helps
    # when the loader is bursty relative to the step time.
    prefetch_depth: int = 2
    # uint8 wire format + device-side augmentation tail (ops/augment.py):
    # the host train pipeline stops at geometry and ships uint8 (4x fewer
    # bytes through worker IPC and the H2D copy); horizontal flip +
    # brightness/contrast/saturation jitter + normalize run inside the
    # jitted step, seeded per sample from the same (seed, epoch, index)
    # streams. None = auto: ON for TPU backends, OFF elsewhere (parity with
    # pre-existing f32 CPU runs). True/False force the path.
    device_augment: Optional[bool] = None


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh layout. data = batch sharding; model = class-axis sharding of
    the GMM head / memory / EM (the TP analogue for this model family)."""

    data: int = -1  # -1: all devices on the data axis
    model: int = 1


@dataclasses.dataclass(frozen=True)
class Config:
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    em: EMConfig = dataclasses.field(default_factory=EMConfig)
    optim: OptimConfig = dataclasses.field(default_factory=OptimConfig)
    schedule: ScheduleConfig = dataclasses.field(default_factory=ScheduleConfig)
    loss: LossConfig = dataclasses.field(default_factory=LossConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    seed: int = 0
    model_dir: str = "./saved_models"

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def tiny_test_config(
    num_classes: int = 4,
    prototypes_per_class: int = 3,
    proto_dim: int = 8,
    img_size: int = 32,
    mem_capacity: int = 16,
    mine_T: int = 4,
    arch: str = "tiny",
) -> Config:
    """Small config for unit/integration tests and multi-chip dry runs."""
    return Config(
        model=ModelConfig(
            arch=arch,
            img_size=img_size,
            num_classes=num_classes,
            prototypes_per_class=prototypes_per_class,
            proto_dim=proto_dim,
            sz_embedding=8,
            mine_T=mine_T,
            mem_capacity=mem_capacity,
            pretrained=False,
        ),
        schedule=ScheduleConfig(
            num_train_epochs=2,
            mine_start=0,
            update_gmm_start=0,
            push_start=1,
            push_every=1,
            prune_top_m=2,
        ),
    )
