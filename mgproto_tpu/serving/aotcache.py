"""AOT executable cache: mmap-and-go cold start for serving replicas.

The serving plane (PR 7) proves zero *steady-state* recompiles, but every
replica start and blue/green swap still pays compile-everything warmup —
the largest latency cliff between "2 replicas on one host" and elastic
scale-out. Following the whole-program-compilation line of "Automatic Full
Compilation ... to Cloud TPUs" and the portable O(1) inference-caching
argument (PAPERS.md): compile once, SERIALIZE the executable, and make
every subsequent start a deserialization, not a compilation.

Each warmed bucket's compiled program (`jit(infer).lower(shape).compile()`)
is serialized via `jax.experimental.serialize_executable` into a
content-addressed entry keyed by everything that makes a compiled binary
valid to reuse:

    (program fingerprint, bucket shape, compute dtype, quant tag,
     device kind, topology (platform + device count),
     jax version, jaxlib version)

The quant axis (ISSUE 20, perf/quant.py) keeps an int8 weight-only
program and its f32 sibling from ever sharing an entry: the file
fingerprint usually separates them already, but live-state faces and any
future in-place requantization would not, so the tag is part of the key
unconditionally ("" = unquantized).

Any change to any component changes the digest, so a stale executable is
simply ABSENT (a miss → normal compile), never served. The entry file
additionally embeds its full key and a payload checksum: a digest-named
file whose header disagrees with the requested key (collision, tampering,
truncation) or whose payload fails its checksum / deserialization is a
counted REJECT and the engine falls back to compiling — fail-safe by
construction, a wrong or corrupt cache can only cost time, never serve a
wrong program.

Counters (serving/metrics.py, pre-registered):

    serving_aot_hit_total      warmups served from the cache (zero compiles)
    serving_aot_miss_total     key absent → normal compile (+ lazy store)
    serving_aot_reject_total   entry present but unusable, by reason
    serving_aot_store_total    store attempts by result (ok/unsupported/error)

The cache directory conventionally sits beside the `.mgproto` artifact
(`<artifact>.aotcache/` — see `default_cache_dir`) or wherever the operator
points `mgproto-serve --aot-cache`. Entries are written atomically
(tmp+rename, the checkpoint discipline), so concurrent replicas racing the
same key at worst both compile and one rename wins.

IMPORTANT key semantics: `program_fingerprint` must identify the FULL
program — weights included. The artifact face hashes the `.mgproto` file
itself (engine/export.py combines it with the gmm fingerprint); live-state
faces that only pass the gmm fingerprint must own the lifecycle of their
cache dir (the drill/bench pattern: a fresh dir per state).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
from typing import Any, Dict, Optional, Sequence, Tuple

from mgproto_tpu.serving import metrics as _m

_MAGIC = b"MGAOTX1\n"
_SUFFIX = ".aotx"

REJECT_KEY_MISMATCH = "key_mismatch"
REJECT_CORRUPT = "corrupt"
REJECT_DESERIALIZE = "deserialize"
REJECT_EXECUTE = "execute"

STORE_OK = "ok"
STORE_UNSUPPORTED = "unsupported"
STORE_ERROR = "error"


def environment_fingerprint() -> Dict[str, Any]:
    """The executable-validity half of the key: a compiled binary is only
    reusable on the same accelerator kind, the same local topology, and
    the same jax/jaxlib (which pins the XLA that produced it)."""
    import jax

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "")
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        jaxlib_version = ""
    devices = jax.devices()
    return {
        "device_kind": devices[0].device_kind if devices else "",
        "platform": jax.default_backend(),
        "device_count": len(devices),
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib_version,
    }


def cache_key(
    program_fingerprint: str,
    bucket_shape: Sequence[int],
    compute_dtype: str,
    quant: str = "",
    env: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The full cache key as a flat JSON-able dict. `env` is injectable so
    tests can simulate a jax upgrade / device change without one. `quant`
    is the artifact's quant tag (meta.json quant_config.tag, "" = f32) —
    present in every key so int8 and f32 programs can never collide."""
    key = {
        "format": "mgproto-aotx-v1",
        "program_fingerprint": str(program_fingerprint or ""),
        "bucket_shape": [int(d) for d in bucket_shape],
        "compute_dtype": str(compute_dtype or ""),
        "quant": str(quant or ""),
    }
    key.update(env if env is not None else environment_fingerprint())
    return key


def key_digest(key: Dict[str, Any]) -> str:
    blob = json.dumps(key, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def default_cache_dir(artifact_path: str) -> str:
    """Sidecar convention: the cache lives next to the artifact it caches."""
    return artifact_path + ".aotcache"


def file_fingerprint(path: str) -> str:
    """sha256 of a file — the artifact face's program fingerprint (weights
    and program identity in one hash; any re-export invalidates)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ExecutableCache:
    """Content-addressed store of serialized compiled executables.

    `load` returns a ready-to-call `jax.stages.Compiled` (or None on any
    miss/reject — the caller compiles); `store` serializes one. All
    failure modes are counted, none raise into the serving path.
    """

    def __init__(
        self, cache_dir: str, env: Optional[Dict[str, Any]] = None
    ):
        self.cache_dir = str(cache_dir)
        self._env = env  # None = the real environment, resolved per key

    # ------------------------------------------------------------------- keys
    def key(
        self,
        program_fingerprint: str,
        bucket_shape: Sequence[int],
        compute_dtype: str,
        quant: str = "",
    ) -> Dict[str, Any]:
        return cache_key(
            program_fingerprint, bucket_shape, compute_dtype,
            quant=quant, env=self._env,
        )

    def path_for(self, key: Dict[str, Any]) -> str:
        return os.path.join(self.cache_dir, key_digest(key) + _SUFFIX)

    # ------------------------------------------------------------------- load
    def load(self, key: Dict[str, Any]):
        """The deserialized executable for `key`, or None (counted as a
        miss when the entry is absent, a reject when present-but-unusable).
        Never raises.

        NOTE: deserializing is not yet serving — the HIT is counted by
        `note_hit()`, which the engine calls only after the executable
        passes its verification run. An entry that deserializes but fails
        verification is a `reject_loaded()` (and a compile), never a hit:
        the hit counter's meaning stays 'warmed with zero compiles'."""
        path = self.path_for(key)
        if not os.path.isfile(path):
            _m.counter(_m.AOT_MISSES).inc()
            return None
        try:
            with open(path, "rb") as f:
                raw = f.read()
            header, blob = self._parse(raw)
        except Exception:
            self._reject(REJECT_CORRUPT, path)
            return None
        if header.get("key") != key:
            # a digest-named entry whose embedded key disagrees with the
            # requested one: collision or tampering — never trust it
            self._reject(REJECT_KEY_MISMATCH, path)
            return None
        if hashlib.sha256(blob).hexdigest() != header.get("payload_sha256"):
            self._reject(REJECT_CORRUPT, path)
            return None
        try:
            from jax.experimental import serialize_executable as _se

            payload, in_tree, out_tree = pickle.loads(blob)
            compiled = _se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:
            self._reject(REJECT_DESERIALIZE, path)
            return None
        return compiled

    def note_hit(self) -> None:
        """Count one verified cache hit (see `load`)."""
        _m.counter(_m.AOT_HITS).inc()

    def reject_loaded(self, reason: str = REJECT_EXECUTE) -> None:
        """Count a post-load rejection (a deserialized executable that
        failed its verification run) — the engine's half of fail-safe."""
        _m.counter(_m.AOT_REJECTS).inc(reason=reason)

    @staticmethod
    def _reject(reason: str, path: str) -> None:
        _m.counter(_m.AOT_REJECTS).inc(reason=reason)

    # ------------------------------------------------------------------ store
    def store(self, key: Dict[str, Any], compiled) -> bool:
        """Serialize `compiled` under `key` (atomic tmp+rename). Returns
        True on success; failures are counted, never raised (a backend
        that cannot serialize still serves — it just stays cold)."""
        try:
            from jax.experimental import serialize_executable as _se

            payload, in_tree, out_tree = _se.serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
        except Exception as e:
            result = (
                STORE_UNSUPPORTED
                if isinstance(e, ValueError) else STORE_ERROR
            )
            _m.counter(_m.AOT_STORES).inc(result=result)
            return False
        header = {
            "key": key,
            "payload_sha256": hashlib.sha256(blob).hexdigest(),
            "payload_bytes": len(blob),
        }
        try:
            os.makedirs(self.cache_dir, exist_ok=True)
            head = json.dumps(header, sort_keys=True).encode()
            fd, tmp = tempfile.mkstemp(
                dir=self.cache_dir, suffix=_SUFFIX + ".tmp"
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(_MAGIC)
                    f.write(len(head).to_bytes(8, "big"))
                    f.write(head)
                    f.write(blob)
                os.replace(tmp, self.path_for(key))
            finally:
                if os.path.exists(tmp):  # replace failed; don't leak tmp
                    os.unlink(tmp)
        except OSError:
            _m.counter(_m.AOT_STORES).inc(result=STORE_ERROR)
            return False
        _m.counter(_m.AOT_STORES).inc(result=STORE_OK)
        return True

    # -------------------------------------------------------------- inventory
    def entries(self) -> Dict[str, Dict[str, Any]]:
        """{digest: header} of every parseable entry (operator surface:
        the README runbook's `python -c` one-liner and the tests)."""
        out: Dict[str, Dict[str, Any]] = {}
        try:
            names = sorted(os.listdir(self.cache_dir))
        except OSError:
            return out
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self.cache_dir, name)
            try:
                with open(path, "rb") as f:
                    header, _ = self._parse(f.read())
                out[name[: -len(_SUFFIX)]] = header
            except Exception:
                out[name[: -len(_SUFFIX)]] = {"unparseable": True}
        return out

    # -------------------------------------------------------------- internals
    @staticmethod
    def _parse(raw: bytes) -> Tuple[Dict[str, Any], bytes]:
        if raw[: len(_MAGIC)] != _MAGIC:
            raise ValueError("bad magic")
        off = len(_MAGIC)
        head_len = int.from_bytes(raw[off:off + 8], "big")
        off += 8
        header = json.loads(raw[off:off + head_len])
        blob = raw[off + head_len:]
        if len(blob) != int(header.get("payload_bytes", -1)):
            raise ValueError("truncated payload")
        return header, blob
