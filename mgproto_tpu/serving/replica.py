"""Replica supervision: N serving workers behind one admission boundary.

The fault-tolerance layer between the HTTP frontend and the engines, in the
production-serving spirit of the TensorFlow system paper (PAPERS.md): the
model is replicated, replicas fail, and the fleet's job is to keep every
request typed while survivors absorb the load.

  * ROUTING — round-robin over replicas whose `HealthProbe.readiness()` is
    true. A replica with an OPEN breaker, mid-warmup, or draining gets no
    new traffic. With zero ready replicas the request is answered with a
    typed shed (`no_replica`) — overload and total outage degrade to
    shed-rate telemetry, never to silence.
  * HEARTBEATS — every successful supervisor pass over a live replica beats
    it. A replica that stops beating (worker wedged on a device call, or
    the simulated process death the chaos harness injects) is detected when
    its heartbeat goes stale, DRAINED (its queued requests reroute to
    survivors, preserving each request's original deadline and enqueue
    time), and scheduled for restart on `resilience.retry`'s backoff
    schedule — the same pacing policy every other recovery path uses.
  * RESTARTS — a due replica rebuilds its engine from the factory and
    re-warms every bucket before rejoining the rotation (readiness stays
    false throughout, so the warmup compiles are never on a request's
    critical path). A failing factory re-enters backoff at the next longer
    delay.

Clock injectable throughout; nothing here sleeps or blocks (enforced by
scripts/check_no_blocking_sleep.py) — the supervisor is a `poll()` pump the
frontend (or the load harness) drives.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from mgproto_tpu.obs import reqtrace as _reqtrace
from mgproto_tpu.obs.flightrec import get_recorder
from mgproto_tpu.resilience import chaos as _chaos
from mgproto_tpu.resilience.retry import backoff_delays
from mgproto_tpu.serving import metrics as _m
from mgproto_tpu.serving.batcher import BatcherConfig, MicroBatcher
from mgproto_tpu.serving.health import HealthProbe
from mgproto_tpu.serving.response import (
    REASON_NO_REPLICA,
    REASON_REPLICA_LOST,
    REASON_SHUTDOWN,
    ServeResponse,
    shed_response,
)

STATE_READY = "ready"
STATE_BACKOFF = "backoff"  # failed; waiting for its scheduled restart

FAILURE_DEAD = "dead"  # stopped beating, process presumed gone
FAILURE_WEDGED = "wedged"  # stopped beating, process present but stuck


class Replica:
    """One supervised worker: engine + batcher + probe + heartbeat."""

    def __init__(
        self,
        name: str,
        factory: Callable[[], "object"],
        clock: Callable[[], float],
        batcher_config: Optional[BatcherConfig] = None,
        pre_dispatch: Optional[Callable[[], None]] = None,
        engine_prep: Optional[Callable[["object"], None]] = None,
    ):
        self.name = name
        self.factory = factory
        self.clock = clock
        self.batcher_config = batcher_config
        self.pre_dispatch = pre_dispatch
        self.engine_prep = engine_prep
        self.engine = None
        self.batcher: Optional[MicroBatcher] = None
        self.probe: Optional[HealthProbe] = None
        self.state = STATE_BACKOFF
        self.alive = True  # False = simulated process death (chaos kill)
        self.wedged = False  # True = present but unresponsive (chaos wedge)
        self.last_beat = 0.0
        self.restarts = 0  # restart ATTEMPTS performed (paces the backoff)
        self.restart_at = 0.0  # clock() time the next attempt is due

    # ----------------------------------------------------------------- lifecycle
    def start(self) -> int:
        """Build + warm the engine; returns warmup compile count. Raises on
        factory/warmup failure (the supervisor converts that into backoff).
        `engine_prep` runs between build and warmup — the per-replica HBM
        bucket-planning hook (serving/autoscale.py `hbm_bucket_prep`), so
        heterogeneous hardware gets heterogeneous bucket ladders BEFORE any
        bucket compiles."""
        self.engine = self.factory()
        if self.engine_prep is not None:
            self.engine_prep(self.engine)
        compiled = self.engine.warmup()
        self.batcher = MicroBatcher(
            self.engine,
            config=self.batcher_config,
            clock=self.clock,
            name=self.name,
            pre_dispatch=self.pre_dispatch,
        )
        self.probe = HealthProbe(self.engine)
        self.state = STATE_READY
        self.alive = True
        self.wedged = False
        self.last_beat = self.clock()
        return compiled

    def adopt(self, engine) -> None:
        """Install an already-warmed engine (the blue/green flip target);
        the replica keeps its identity, heartbeat history restarts."""
        self.engine = engine
        self.batcher = MicroBatcher(
            engine,
            config=self.batcher_config,
            clock=self.clock,
            name=self.name,
            pre_dispatch=self.pre_dispatch,
        )
        self.probe = HealthProbe(engine)
        self.state = STATE_READY
        self.alive = True
        self.wedged = False
        self.last_beat = self.clock()

    # ------------------------------------------------------------------- status
    def responsive(self) -> bool:
        """Can this replica do work RIGHT NOW (beat + dispatch)?"""
        return (
            self.state == STATE_READY
            and self.alive
            and not self.wedged
            and self.engine is not None
        )

    def routable(self) -> bool:
        """Should NEW traffic land here? Responsive + readiness contract."""
        return bool(
            self.responsive() and self.probe.readiness()["ready"]
        )

    def beat_stale(self, now: float, timeout_s: float) -> bool:
        return now - self.last_beat > timeout_s


class ReplicaSet:
    """The supervisor (see module docstring)."""

    def __init__(
        self,
        engine_factory: Callable[[], "object"],
        replicas: int = 2,
        clock: Callable[[], float] = time.monotonic,
        heartbeat_timeout_s: float = 1.0,
        restart_base_delay_s: float = 0.1,
        restart_max_delay_s: float = 5.0,
        batcher_config: Optional[BatcherConfig] = None,
        pre_dispatch: Optional[Callable[[], None]] = None,
        engine_prep: Optional[Callable[["object"], None]] = None,
    ):
        if replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        self.engine_factory = engine_factory
        self.clock = clock
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.restart_base_delay_s = float(restart_base_delay_s)
        self.restart_max_delay_s = float(restart_max_delay_s)
        self.batcher_config = batcher_config
        self.pre_dispatch = pre_dispatch
        self.engine_prep = engine_prep
        self._next_name = int(replicas)  # unique names across add/remove
        self.replicas: List[Replica] = [
            self._make_replica(f"r{i}") for i in range(int(replicas))
        ]
        self._rr = 0  # round-robin cursor
        self._admit_seq = 0  # global admitted-request index (chaos identity)
        self._started_at: Optional[float] = None
        self.steady_recompiles = 0  # accumulated post-warmup recompiles
        _m.gauge(_m.REPLICAS_TOTAL).set(float(len(self.replicas)))

    def _make_replica(self, name: str) -> Replica:
        return Replica(
            name,
            lambda: self.engine_factory(),  # late-bound: hot swap retargets
            self.clock,
            batcher_config=self.batcher_config,
            pre_dispatch=self.pre_dispatch,
            engine_prep=self.engine_prep,
        )

    # ----------------------------------------------------------------- lifecycle
    def start(self) -> int:
        """Build + warm every replica; returns total warmup compiles."""
        self._started_at = self.clock()
        compiled = 0
        for rep in self.replicas:
            compiled += rep.start()
        self._observe()
        return compiled

    # --------------------------------------------------------------- elasticity
    def add_replica(self) -> Replica:
        """Grow the set by one (the autoscaler's scale-up arm). The new
        replica enters as a due-now BACKOFF entry, so the NEXT supervisor
        `poll()` builds and warms it through the existing restart path —
        warmup (cheap through the AOT cache by construction) happens in
        the pump, never on a request's critical path, and a failing
        factory re-enters backoff like any other restart."""
        name = f"r{self._next_name}"
        self._next_name += 1
        rep = self._make_replica(name)
        rep.restart_at = self.clock()
        self.replicas.append(rep)
        _m.gauge(_m.REPLICAS_TOTAL).set(float(len(self.replicas)))
        get_recorder().record("replica_added", replica=name)
        _reqtrace.plane_event("replica_added", replica=name)
        self._observe()
        return rep

    def remove_replica(
        self, rep: Optional[Replica] = None
    ) -> List[ServeResponse]:
        """Shrink the set by one with ZERO dropped requests (the
        autoscaler's scale-down arm). The victim (default: a dead/backoff
        replica if one exists — free to remove — else the last ready one)
        is marked draining, its queued requests transfer to survivors via
        the same `drain_all`/`restore` path a heartbeat failure uses
        (deadlines + enqueue times intact); whatever the survivors cannot
        hold is answered THROUGH the victim's own device before it leaves
        (it is healthy — this is a shrink, not a failure), and only an
        unresponsive victim's leftovers shed typed. Returns every response
        produced. Refuses to empty the set."""
        if len(self.replicas) <= 1:
            raise ValueError("refusing to remove the last replica")
        if rep is None:
            idle = [r for r in self.replicas if r.engine is None]
            if idle:
                rep = idle[-1]
            else:
                ready = self.ready_replicas()
                rep = ready[-1] if ready else self.replicas[-1]
        if rep not in self.replicas:
            raise ValueError(f"{rep.name} is not in this set")
        out: List[ServeResponse] = []
        now = self.clock()
        stranded: List = []
        if rep.engine is not None:
            rep.engine.draining = True  # readiness false: no new routing
            stranded = rep.engine.queue.drain_all()
            stranded.extend(rep.engine.queue.drain_shed())
            survivors = [
                s for s in self.replicas
                if s is not rep and s.responsive()
            ]
            i = 0
            for req in stranded:
                placed = False
                for _ in range(len(survivors)):
                    target = survivors[i % len(survivors)]
                    i += 1
                    if target.engine.queue.restore(req):
                        placed = True
                        break
                if placed:
                    continue
                # survivors full: the victim itself answers before leaving
                if rep.responsive() and rep.engine.queue.restore(req):
                    continue
                out.append(
                    shed_response(
                        req.request_id, REASON_REPLICA_LOST,
                        latency_s=now - req.enqueued_at,
                    )
                )
            if rep.responsive() and len(rep.engine.queue):
                out.extend(rep.batcher.flush())
                self.steady_recompiles += rep.engine.monitor.check_recompiles()
        self.replicas.remove(rep)
        _m.gauge(_m.REPLICAS_TOTAL).set(float(len(self.replicas)))
        get_recorder().record(
            "replica_removed", replica=rep.name, drained=len(stranded),
        )
        _reqtrace.plane_event("replica_removed", replica=rep.name)
        self._observe()
        return out

    # ------------------------------------------------------------------ routing
    def ready_replicas(self) -> List[Replica]:
        return [rep for rep in self.replicas if rep.routable()]

    def _pick(self) -> Optional[Replica]:
        ready = self.ready_replicas()
        if not ready:
            return None
        rep = ready[self._rr % len(ready)]
        self._rr += 1
        return rep

    def submit(
        self,
        payload,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> List[ServeResponse]:
        """Route one request to a ready replica. Same contract as
        `ServingEngine.submit` (including the tenant id passthrough): the
        returned list holds any IMMEDIATE typed responses (reject/shed,
        for this or evicted requests); empty means queued, `poll()` will
        answer it."""
        seq = self._admit_seq
        self._admit_seq += 1
        rid = request_id or f"g{seq}"
        if _reqtrace.enabled():
            # frontend-less faces (batch driver, load harness) start the
            # request trace here; the HTTP frontend minted earlier and
            # this is then a no-op (first mint wins)
            _reqtrace.mint(rid, self.clock())
        target = self._pick()
        chaos = _chaos.get_active()
        if chaos is not None and target is not None:
            # simulated process death / wedge of the replica this request
            # would have landed on; the request itself reroutes
            if chaos.serve_replica_kill_due(seq):
                target.alive = False
                get_recorder().record(
                    "chaos_replica_kill", replica=target.name, request=rid
                )
                _reqtrace.plane_event("replica_kill", replica=target.name)
                target = self._pick()
            elif chaos.serve_replica_wedge_due(seq):
                target.wedged = True
                get_recorder().record(
                    "chaos_replica_wedge", replica=target.name, request=rid
                )
                _reqtrace.plane_event("replica_wedge", replica=target.name)
                target = self._pick()
        if target is None:
            return [shed_response(rid, REASON_NO_REPLICA, tenant=tenant)]
        return target.engine.submit(
            payload, request_id=rid, deadline_s=deadline_s, tenant=tenant
        )

    # ------------------------------------------------------------------- pumping
    def poll(self) -> List[ServeResponse]:
        """One supervisor pass: restart due replicas, detect stale
        heartbeats (drain + reroute + schedule restart), pump every
        responsive replica's batcher, refresh fleet gauges."""
        out: List[ServeResponse] = []
        now = self.clock()
        for rep in self.replicas:
            if rep.state == STATE_BACKOFF:
                if now >= rep.restart_at:
                    self._try_restart(rep)
                continue
            if rep.responsive():
                # an OPEN breaker takes the replica out of rotation, so no
                # traffic arrives to call allow() and perform the lazy
                # half-open transition; tick it here or the replica could
                # never rejoin after the cooldown
                rep.engine.breaker.tick()
                # a responsive worker beats by doing work; staleness is
                # only meaningful for one that CANNOT beat — so the check
                # stays independent of the supervisor's own pass cadence
                out.extend(rep.batcher.poll())
                rep.last_beat = self.clock()
                self.steady_recompiles += rep.engine.monitor.check_recompiles()
            elif rep.beat_stale(now, self.heartbeat_timeout_s):
                out.extend(self._fail(rep, now))
        self._observe()
        return out

    def flush(self) -> List[ServeResponse]:
        """Answer everything queued through the device WITHOUT leaving the
        rotation (batch drivers use this between submission waves; `drain`
        is the terminal, readiness-dropping variant)."""
        out: List[ServeResponse] = []
        for rep in self.replicas:
            if rep.responsive():
                out.extend(rep.batcher.flush())
                self.steady_recompiles += rep.engine.monitor.check_recompiles()
        self._observe()
        return out

    def shed_stranded(
        self, reason: str = REASON_REPLICA_LOST
    ) -> List[ServeResponse]:
        """Typed sheds for requests queued on replicas that cannot dispatch
        (killed/wedged but not yet heartbeat-detected). Batch drivers call
        this at exit so a fast batch cannot end with work stranded on a
        downed replica — the long-running faces let `poll()`'s detection
        reroute instead."""
        out: List[ServeResponse] = []
        now = self.clock()
        for rep in self.replicas:
            if rep.engine is None or rep.responsive():
                continue
            stranded = rep.engine.queue.drain_all()
            stranded.extend(rep.engine.queue.drain_shed())
            for req in stranded:
                out.append(
                    shed_response(
                        req.request_id, reason,
                        latency_s=now - req.enqueued_at,
                    )
                )
        self._observe()
        return out

    def drain(self, reason: str = REASON_SHUTDOWN) -> List[ServeResponse]:
        """Graceful shutdown: every queued request is ANSWERED (responsive
        replicas flush through the device) or SHED typed (unresponsive
        replicas' queues). Nothing is dropped; readiness goes false."""
        out: List[ServeResponse] = []
        for rep in self.replicas:
            if rep.engine is None:
                continue
            rep.engine.draining = True
            if rep.responsive():
                out.extend(rep.batcher.flush())
                self.steady_recompiles += rep.engine.monitor.check_recompiles()
            else:
                out.extend(rep.engine.drain(reason))
        self._observe()
        return out

    # ------------------------------------------------------------------ failure
    def _fail(self, rep: Replica, now: float) -> List[ServeResponse]:
        """Heartbeat-stale replica: account it, reroute its queue to
        survivors (original deadlines and enqueue times intact), schedule
        the restart on the retry-backoff schedule."""
        reason = FAILURE_WEDGED if rep.alive else FAILURE_DEAD
        _m.counter(_m.REPLICA_RESTARTS).inc(reason=reason)
        # flight recorder: a replica death is exactly the moment the recent
        # event ring is worth keeping — record it, then dump (when a
        # dump_dir is configured) so the post-mortem shows what the fleet
        # was doing in the seconds before the heartbeat went stale
        recorder = get_recorder()
        recorder.record(
            "replica_failure", replica=rep.name, reason=reason,
            queued=len(rep.engine.queue) if rep.engine else 0,
            restarts=rep.restarts,
        )
        _reqtrace.plane_event(
            "replica_fail_detected", replica=rep.name, reason=reason
        )
        out: List[ServeResponse] = []
        stranded = rep.engine.queue.drain_all() if rep.engine else []
        stranded.extend(rep.engine.queue.drain_shed() if rep.engine else [])
        survivors = [
            s for s in self.replicas if s is not rep and s.responsive()
        ]
        i = 0
        for req in stranded:
            placed = False
            for _ in range(len(survivors)):
                target = survivors[i % len(survivors)] if survivors else None
                i += 1
                if target is not None and target.engine.queue.restore(req):
                    placed = True
                    break
            if not placed:
                out.append(
                    shed_response(
                        req.request_id,
                        REASON_REPLICA_LOST,
                        latency_s=now - req.enqueued_at,
                    )
                )
        rep.engine = None
        rep.batcher = None
        rep.probe = None
        rep.state = STATE_BACKOFF
        rep.restart_at = now + self._restart_delay(rep.restarts)
        recorder.maybe_dump(f"replica_{reason}")
        return out

    def _restart_delay(self, attempts: int) -> float:
        """The (attempts+1)-th backoff delay from the shared retry
        schedule, jitter-free (deterministic recovery pacing — the same
        discipline CircuitBreaker._cooldown uses)."""
        delays = list(
            backoff_delays(
                attempts + 1,
                base_delay=self.restart_base_delay_s,
                max_delay=self.restart_max_delay_s,
                jitter=0.0,
            )
        )
        return delays[-1]

    def _try_restart(self, rep: Replica) -> None:
        rep.restarts += 1
        try:
            rep.start()
            get_recorder().record(
                "replica_restart", replica=rep.name, attempts=rep.restarts
            )
            _reqtrace.plane_event("replica_restart", replica=rep.name)
        except Exception:
            # the factory/warmup failed (artifact gone, device sick): stay
            # in backoff at the next longer delay; the fleet keeps serving
            rep.engine = None
            rep.batcher = None
            rep.probe = None
            rep.state = STATE_BACKOFF
            rep.restart_at = self.clock() + self._restart_delay(rep.restarts)

    # ------------------------------------------------------------------- gauges
    def _observe(self) -> None:
        now = self.clock()
        ready = len(self.ready_replicas())
        _m.gauge(_m.REPLICAS_READY).set(float(ready))
        depth = sum(
            len(rep.engine.queue)
            for rep in self.replicas
            if rep.engine is not None
        )
        _m.gauge(_m.QUEUE_DEPTH).set(float(depth))
        if self._started_at is not None:
            uptime = max(now - self._started_at, 0.0)
            _m.gauge(_m.UPTIME_SECONDS).set(uptime)
            open_s = sum(
                rep.engine.breaker.open_seconds(now)
                for rep in self.replicas
                if rep.engine is not None
            )
            denom = uptime * max(len(self.replicas), 1)
            _m.gauge(_m.BREAKER_OPEN_FRACTION).set(
                open_s / denom if denom > 0 else 0.0
            )
