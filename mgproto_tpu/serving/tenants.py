"""Multi-tenant serving: one shared trunk, many MGProto heads (ISSUE 17).

MGProto factors cleanly into a heavy TRUNK (backbone + prototype program —
the thing XLA compiles and the AOT cache serializes) and a light HEAD per
tenant: the calibration (quantile sketch, thresholds, per-class
temperatures), its TrustGate, and the tenant's online state (drift monitor,
trusted-capture reservoir). The directory here mounts and unmounts heads at
runtime against ONE engine fleet:

  * ZERO TRUNK COMPILES PER TENANT, BY CONSTRUCTION. The engine's AOT key
    is (trunk fingerprint, bucket shape, dtype) — see
    `ServingEngine._aot_key`. A head never touches `aot_fingerprint`, the
    jit handle, or the per-bucket executables, so mounting tenant N+1 costs
    head bytes + gate construction and nothing else. The load drill proves
    it the hard way: a mid-storm mount with the recompile detector watching
    must report a compile delta of exactly zero.
  * FAIR-SHARE ADMISSION. `quota_for` turns a tenant's weight into its
    share of the admission queue; the queue enforces it by shedding the
    tenant's OWN tail (typed `tenant_quota`, serving/admission.py) —
    deadline-aware within that share — so one tenant's storm cannot evict
    another tenant's queued work, and `pop_batch` round-robins batch slots
    across lanes so the storm cannot monopolize batch composition either.
  * TENANT-SCOPED BLUE/GREEN. `swap` stages a replacement head and verifies
    it through the same fail-closed contract as the fleet swap
    (serving/swap.py::verify_head): an uncalibrated or stale-fingerprint
    head is REJECTED for that one tenant while its old head — and every
    other tenant — keeps serving. The chaos knob
    MGPROTO_CHAOS_TENANT_BAD_SWAP drills exactly that.
  * PER-TENANT DRIFT + CAPTURE. Each head may carry its own DriftMonitor
    and TrustedCapture (tenant-labeled metrics): one tenant's traffic
    drifting breaches that tenant's monitor only — attribution, not a
    fleet-wide alarm.

The whole plane is opt-in: an engine built without a directory has
`tenants is None` and pays a single None-check (the reqtrace discipline);
responses, metrics, and the wire format are byte-identical to the
single-tenant build.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from mgproto_tpu.obs.flightrec import record_event
from mgproto_tpu.resilience import chaos as _chaos
from mgproto_tpu.serving import metrics as _m
from mgproto_tpu.serving.calibration import Calibration
from mgproto_tpu.serving.gate import TrustGate

# typed reject for traffic addressed at a tenant the directory does not
# hold (never silently served through the wrong head)
REASON_TENANT_UNMOUNTED = "tenant_unmounted"

SWAP_COMMITTED = "committed"
SWAP_REJECTED = "rejected"
REJECT_NOT_MOUNTED = "not_mounted"


def head_fingerprint(calibration: Optional[Calibration]) -> str:
    """Identity of a head: sha256 over the calibration payload. Two tenants
    serving the same trunk but different thresholds/temperatures have
    different heads; "" = no calibration (a degraded head)."""
    if calibration is None:
        return ""
    return hashlib.sha256(calibration.to_json().encode()).hexdigest()


def head_nbytes(calibration: Optional[Calibration]) -> int:
    """Resident bytes of a mounted head's trust data (float64 quantile
    sketch + per-class temperatures + percentile thresholds + operating
    point) — the marginal-cost-per-tenant numerator against the shared
    trunk. Deterministic (a function of the payload, not the allocator)."""
    if calibration is None:
        return 0
    return 8 * (
        len(calibration.quantile_log_px)
        + len(calibration.per_class_temperature)
        + len(calibration.thresholds)
        + 1  # threshold_log_px
    )


@dataclasses.dataclass
class TenantHead:
    """One tenant's mounted state: everything tenant-specific, nothing the
    trunk compiled. Mutable on purpose — `swap` replaces the trust data in
    place under the directory lock."""

    tenant: str
    calibration: Optional[Calibration]
    gate: TrustGate
    head_fingerprint: str
    head_bytes: int
    quota_weight: float
    mounted_at: float
    drift: Optional[Any] = None  # online.drift.DriftMonitor
    capture: Optional[Any] = None  # online.capture.TrustedCapture
    class_slots: Tuple[int, ...] = ()


@dataclasses.dataclass(frozen=True)
class MountReport:
    """What one mount cost — head bytes and seconds against a shared trunk
    (the trunk-compile count is the ENGINE's story: the drill reads the
    recompile monitor around the mount and asserts the delta is zero)."""

    tenant: str
    head_fingerprint: str
    head_bytes: int
    mount_seconds: float
    class_slots: Tuple[int, ...] = ()

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["class_slots"] = list(self.class_slots)
        return d


@dataclasses.dataclass(frozen=True)
class TenantSwapReport:
    """One tenant-scoped head swap attempt — always returned, never raised
    (a refused promotion is an outcome, the fleet-swap discipline)."""

    ok: bool
    tenant: str
    reason: str  # SWAP_COMMITTED or a swap.REJECT_* / REJECT_NOT_MOUNTED
    head_fingerprint: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class TenantDirectory:
    """The mounted heads, and every tenant-scoped operation over them.

    Thread-safe: mounts/swaps come from the operator path while the
    engine's dispatch loop reads gates and taps responses. Reads are
    dict lookups under the lock — never device work, never blocking."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        class_directory: Optional[Any] = None,
    ):
        self.clock = clock
        # optional PR-11 class-bucket machinery (online/classes.py): a
        # tenant mounting with class_names claims padded slots, so its
        # classes ride the SAME compiled width — zero trunk recompiles
        self.class_directory = class_directory
        self._lock = threading.Lock()
        self._heads: Dict[str, TenantHead] = {}

    # ------------------------------------------------------------- inventory
    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._heads)

    def head_for(self, tenant: str) -> Optional[TenantHead]:
        with self._lock:
            return self._heads.get(tenant)

    def gate_for(self, tenant: str) -> Optional[TrustGate]:
        head = self.head_for(tenant)
        return None if head is None else head.gate

    def __len__(self) -> int:
        with self._lock:
            return len(self._heads)

    # --------------------------------------------------------------- mounting
    def mount(
        self,
        tenant: str,
        calibration: Optional[Calibration],
        quota_weight: float = 1.0,
        class_names: Sequence[str] = (),
        expected_fingerprint: Optional[str] = None,
        expected_compute_dtype: Optional[str] = None,
        expected_quant: Optional[str] = None,
        percentile: Optional[float] = None,
        drift_config: Optional[Any] = None,
        capture_config: Optional[Any] = None,
        num_classes: Optional[int] = None,
    ) -> MountReport:
        """Mount one tenant head. Cost: head bytes + gate construction —
        the trunk is shared and NOT recompiled (see module docstring).

        `class_names` claims class-bucket slots through the PR-11
        directory (mount-once: slots stay claimed after unmount, because
        the compiled width they ride is a property of the trunk, not of
        the tenant). `drift_config`/`capture_config` attach per-tenant
        online state; capture needs `num_classes` for its reservoirs."""
        t0 = self.clock()
        if quota_weight <= 0.0:
            raise ValueError(
                f"tenant {tenant!r}: quota_weight must be > 0, "
                f"got {quota_weight}"
            )
        gate = TrustGate(
            calibration,
            expected_fingerprint=expected_fingerprint,
            percentile=percentile,
            expected_compute_dtype=expected_compute_dtype,
            expected_quant=expected_quant,
        )
        slots: List[int] = []
        if class_names:
            if self.class_directory is None:
                raise ValueError(
                    f"tenant {tenant!r} asks for class slots "
                    f"{list(class_names)} but the directory has no "
                    "class-bucket machinery attached"
                )
            for name in class_names:
                existing = self.class_directory.slot_of(str(name))
                slots.append(
                    existing if existing is not None
                    else self.class_directory.add_class(str(name))
                )
        drift = None
        if drift_config is not None:
            from mgproto_tpu.online.drift import DriftMonitor

            drift = DriftMonitor(
                calibration, config=drift_config, clock=self.clock,
                tenant=tenant,
            )
        capture = None
        if capture_config is not None:
            if num_classes is None:
                raise ValueError(
                    f"tenant {tenant!r}: capture_config needs num_classes"
                )
            from mgproto_tpu.online.capture import TrustedCapture

            capture = TrustedCapture(
                calibration, num_classes=int(num_classes),
                config=capture_config, tenant=tenant,
            )
        head = TenantHead(
            tenant=str(tenant),
            calibration=calibration,
            gate=gate,
            head_fingerprint=head_fingerprint(calibration),
            head_bytes=head_nbytes(calibration),
            quota_weight=float(quota_weight),
            mounted_at=t0,
            drift=drift,
            capture=capture,
            class_slots=tuple(slots),
        )
        with self._lock:
            if tenant in self._heads:
                raise ValueError(
                    f"tenant {tenant!r} is already mounted; use swap() to "
                    "replace its head"
                )
            self._heads[str(tenant)] = head
            count = len(self._heads)
        seconds = max(self.clock() - t0, 0.0)
        _m.counter(_m.TENANT_MOUNTS).inc(tenant=head.tenant)
        _m.gauge(_m.TENANTS_MOUNTED).set(float(count))
        _m.gauge(_m.TENANT_HEAD_BYTES).set(
            float(head.head_bytes), tenant=head.tenant
        )
        _m.histogram(_m.TENANT_MOUNT_SECONDS).observe(
            seconds, tenant=head.tenant
        )
        record_event(
            "tenant_mount", tenant=head.tenant,
            head_bytes=head.head_bytes, seconds=seconds,
        )
        return MountReport(
            tenant=head.tenant,
            head_fingerprint=head.head_fingerprint,
            head_bytes=head.head_bytes,
            mount_seconds=seconds,
            class_slots=head.class_slots,
        )

    def unmount(self, tenant: str) -> bool:
        """Drop a tenant's head (its claimed class slots stay claimed —
        the compiled width is trunk state, see `mount`). False when the
        tenant was not mounted."""
        with self._lock:
            head = self._heads.pop(tenant, None)
            count = len(self._heads)
        if head is None:
            return False
        _m.counter(_m.TENANT_UNMOUNTS).inc(tenant=str(tenant))
        _m.gauge(_m.TENANTS_MOUNTED).set(float(count))
        _m.gauge(_m.TENANT_HEAD_BYTES).set(0.0, tenant=str(tenant))
        record_event("tenant_unmount", tenant=str(tenant))
        return True

    # ------------------------------------------------------------- fair share
    def quota_for(self, tenant: str, capacity: int) -> Optional[int]:
        """The tenant's fair share of an admission queue: capacity split
        proportional to quota weights over the MOUNTED tenants, floor 1
        (every mounted tenant can always queue something). None for an
        unmounted tenant — the engine rejects those typed before quota
        ever applies."""
        with self._lock:
            head = self._heads.get(tenant)
            if head is None:
                return None
            total = sum(h.quota_weight for h in self._heads.values())
        share = head.quota_weight / total if total > 0 else 1.0
        return max(1, int(int(capacity) * share))

    # ----------------------------------------------------------- head swap
    def swap(
        self,
        tenant: str,
        calibration: Optional[Calibration],
        expected_fingerprint: Optional[str] = None,
        expected_compute_dtype: Optional[str] = None,
        expected_quant: Optional[str] = None,
        percentile: Optional[float] = None,
    ) -> TenantSwapReport:
        """Tenant-scoped blue/green: stage a replacement head, verify it
        through the fleet swap's fail-closed contract (swap.verify_head),
        and only then replace the mounted head atomically. A rejection —
        uncalibrated, stale fingerprint, quant-config mismatch against the
        served trunk, chaos-stripped — leaves the OLD head serving; no
        other tenant is touched either way. Note the head itself stays
        full-precision by construction whatever the trunk's quant config:
        head_nbytes counts host float64 sketch/temperature/threshold
        payload (perf/quant.py never sees a calibration)."""
        from mgproto_tpu.serving.swap import verify_head

        if self.head_for(tenant) is None:
            _m.counter(_m.TENANT_SWAPS).inc(
                tenant=str(tenant), result=SWAP_REJECTED
            )
            record_event(
                "tenant_swap_rejected", tenant=str(tenant),
                reason=REJECT_NOT_MOUNTED,
            )
            return TenantSwapReport(
                ok=False, tenant=str(tenant), reason=REJECT_NOT_MOUNTED
            )
        chaos = _chaos.get_active()
        if chaos is not None and chaos.tenant_bad_swap_due():
            # drill: the operator pushed a head with no trust data; the
            # verification below must refuse it exactly like the real thing
            calibration = None
        staged = TrustGate(
            calibration,
            expected_fingerprint=expected_fingerprint,
            percentile=percentile,
            expected_compute_dtype=expected_compute_dtype,
            expected_quant=expected_quant,
        )
        reason = verify_head(staged)
        if reason is not None:
            _m.counter(_m.TENANT_SWAPS).inc(
                tenant=str(tenant), result=SWAP_REJECTED
            )
            record_event(
                "tenant_swap_rejected", tenant=str(tenant), reason=reason
            )
            return TenantSwapReport(
                ok=False, tenant=str(tenant), reason=reason
            )
        with self._lock:
            head = self._heads.get(tenant)
            if head is None:  # unmounted between verify and commit
                return TenantSwapReport(
                    ok=False, tenant=str(tenant), reason=REJECT_NOT_MOUNTED
                )
            head.calibration = calibration
            head.gate = staged
            head.head_fingerprint = head_fingerprint(calibration)
            head.head_bytes = head_nbytes(calibration)
        if head.drift is not None:
            # the monitor now watches for drift away from the NEW head
            head.drift.rebase(calibration)
        if head.capture is not None:
            head.capture.retarget(calibration)
        _m.counter(_m.TENANT_SWAPS).inc(
            tenant=str(tenant), result=SWAP_COMMITTED
        )
        _m.gauge(_m.TENANT_HEAD_BYTES).set(
            float(head.head_bytes), tenant=str(tenant)
        )
        record_event("tenant_swap_committed", tenant=str(tenant))
        return TenantSwapReport(
            ok=True, tenant=str(tenant), reason=SWAP_COMMITTED,
            head_fingerprint=head.head_fingerprint,
        )

    # ------------------------------------------------------------ serve tap
    def on_response(self, payload: Any, resp: Any) -> None:
        """Per-response tenant tap, called by the engine POST-record: feed
        the tenant's drift window and trusted-capture reservoir. O(1) per
        response; never raises (the capture tap's own contract)."""
        tenant = getattr(resp, "tenant", None)
        if tenant is None:
            return
        head = self.head_for(tenant)
        if head is None:
            return
        if head.drift is not None:
            if resp.log_px is not None:
                head.drift.observe_px(resp.log_px)
            head.drift.evaluate()  # cadence-gated; no-op between intervals
        if head.capture is not None:
            head.capture.on_response(payload, resp)
