"""The one typed response shape every serving request is answered with.

Extracted from `serving/engine.py` (ISSUE 7) so the network plane —
batcher, replica supervisor, swap, HTTP frontend — can construct and
account typed responses without importing the engine (which pulls jax in):
a frontend host must be able to shed typed during an outage even if the
device stack is the thing that is down.

`record()` is the ONE metrics account for a response leaving the system
(requests-by-outcome counter, latency histogram, degraded counter); the
engine and every plane component route through it so a response can never
be double- or un-counted.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from mgproto_tpu.obs import reqtrace as _reqtrace
from mgproto_tpu.serving import metrics as _m

OUTCOME_PREDICT = "predict"
OUTCOME_ABSTAIN = "abstain"
OUTCOME_REJECT = "reject"
OUTCOME_SHED = "shed"

# reject/shed reasons minted by the plane (validation reasons come from
# serving/validate.py, admission reasons from serving/admission.py)
REASON_CIRCUIT_OPEN = "circuit_open"
REASON_DEVICE_ERROR = "device_error"
REASON_SHUTDOWN = "shutdown"  # graceful drain: answered typed, never dropped
REASON_NO_REPLICA = "no_replica"  # every replica dead/unready: typed shed
REASON_REPLICA_LOST = "replica_lost"  # rerouted off a dead replica, no room


@dataclasses.dataclass(frozen=True)
class ServeResponse:
    """The one shape every request is answered with — no other exit path."""

    request_id: str
    outcome: str  # predict | abstain | reject | shed
    prediction: Optional[int] = None
    log_px: Optional[float] = None
    trust: Optional[str] = None  # in_dist | abstain | ungated
    trust_score: Optional[float] = None  # calibrated ID-quantile of log_px
    confidence: Optional[float] = None  # temperature-calibrated max softmax
    degraded: bool = False
    reason: Optional[str] = None  # reject/shed cause
    latency_s: float = 0.0
    # opt-in per-request timing breakdown (obs/reqtrace.py with
    # include_timings=True): total_s / queue_s / device_s / pad_fraction /
    # replica. None — and absent from to_dict() — everywhere else, so the
    # wire format only grows for operators who asked for it.
    timings: Optional[Dict[str, Any]] = None
    # opt-in prototype explanation (ISSUE 15, ServingEngine explain=True):
    # the top activated prototypes behind a PREDICT outcome — per entry
    # class / k / mixture prior / peak log-density, plus nearest-training-
    # patch provenance when the artifact carries push metadata. None — and
    # absent from to_dict() — everywhere else (the timings discipline);
    # never populated on abstain/reject/shed.
    explain: Optional[Any] = None
    # multi-tenant serving (ISSUE 17): the tenant lane this response
    # belongs to. None — and absent from to_dict() — on the whole
    # single-tenant path (the timings discipline), so the wire format and
    # the metrics account are byte-identical when the tenant plane is off.
    tenant: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        for opt in ("timings", "explain", "tenant"):
            if d.get(opt) is None:
                d.pop(opt, None)
        return d


def record(resp: ServeResponse) -> ServeResponse:
    """Account a response leaving the system (see module docstring). ALSO
    the one request-tracing exit: when obs/reqtrace is enabled the stage
    spans + histograms are emitted here, and the opt-in timing breakdown is
    attached to the returned response — callers already use the return
    value, so the trace can never double- or un-finish a request."""
    _m.counter(_m.REQUESTS).inc(outcome=resp.outcome)
    _m.histogram(_m.REQUEST_SECONDS).observe(
        max(resp.latency_s, 0.0), outcome=resp.outcome
    )
    if resp.tenant is not None:
        # the per-tenant view rides a SEPARATE histogram family (see
        # serving/metrics.py): summarize merges label series per name, so
        # tenant labels inside REQUEST_SECONDS would double-count
        _m.counter(_m.TENANT_REQUESTS).inc(
            tenant=resp.tenant, outcome=resp.outcome
        )
        _m.histogram(_m.TENANT_REQUEST_SECONDS).observe(
            max(resp.latency_s, 0.0), tenant=resp.tenant, outcome=resp.outcome
        )
    if resp.degraded and resp.outcome == OUTCOME_PREDICT:
        _m.counter(_m.DEGRADED_REQUESTS).inc()
    if _reqtrace.enabled():
        timings = _reqtrace.finish(resp)
        if timings is not None:
            resp = dataclasses.replace(resp, timings=timings)
    return resp


def shed_response(
    request_id: str,
    reason: str,
    latency_s: float = 0.0,
    degraded: bool = False,
    tenant: Optional[str] = None,
) -> ServeResponse:
    """A recorded typed shed — the plane's answer when no engine can serve
    (dead replica with no survivors, graceful shutdown, lost reroute)."""
    _m.counter(_m.SHED).inc(reason=reason)
    if tenant is not None:
        _m.counter(_m.TENANT_SHED).inc(tenant=tenant, reason=reason)
    return record(
        ServeResponse(
            request_id=request_id,
            outcome=OUTCOME_SHED,
            reason=reason,
            degraded=degraded,
            latency_s=latency_s,
            tenant=tenant,
        )
    )
