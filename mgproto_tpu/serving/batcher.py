"""Continuous micro-batching over a ServingEngine's admission queue.

The compile-once substrate (bucketed warmup, PR 3/PR 6) makes batch
coalescing free of recompiles: any queue depth pads to the nearest WARMED
bucket. What is left is the scheduling question — when is waiting for a
fuller batch worth it? The batcher dispatches when either:

  * `bucket_full` — the LARGEST warmed bucket can be filled. More waiting
    cannot improve throughput (the program has no bigger shape), so go.
  * `deadline`    — the oldest queued request's latency-deadline slack has
    dropped to the measured dispatch cost (an EMA of recent dispatch wall
    time, seeded by `cost_prior_s`). Waiting any longer converts that
    request from served to shed; a partial batch padded up beats a typed
    shed.
  * `linger`      — the oldest request (deadline-less traffic) has waited
    `max_linger_s`. Bounded staleness for callers with no contract.
  * `drain`       — `flush()` was called (shutdown / blue-green flip):
    everything queued dispatches now, regardless of fill.

Host-side and jax-free: the engine's `process_pending` owns the device.
The clock is injectable (defaults to the engine's), so the chaos load test
drives deadline pressure deterministically — no real sleeps, matching
admission.py's discipline (enforced by scripts/check_no_blocking_sleep.py).

`pre_dispatch` is a test/bench-only hook that runs at the top of every
dispatch; the virtual-clock load harness (scripts/load_test.py) advances
its fake clock there to model device service time, which also feeds the
cost EMA the deadline trigger reads. Production leaves it None.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

from mgproto_tpu.obs import reqtrace as _reqtrace
from mgproto_tpu.obs.flightrec import record_event
from mgproto_tpu.serving import metrics as _m
from mgproto_tpu.serving.response import ServeResponse

TRIGGER_BUCKET_FULL = "bucket_full"
TRIGGER_DEADLINE = "deadline"
TRIGGER_LINGER = "linger"
TRIGGER_DRAIN = "drain"


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """Scheduling knobs (see module docstring for each trigger).

    `device_busy_s > 0` models the device as OCCUPIED for that long after
    each dispatch (on the injectable clock): `dispatch_due` holds further
    batches until the window passes, so the queue reflects true backlog
    instead of the host pump outrunning the device. This is what gives the
    virtual-clock load harness real queueing dynamics per replica (N
    replicas = N concurrent busy windows = N x capacity — the saturation
    the autoscaler drill measures); production leaves it 0 (off — the
    synchronous executor dispatch already paces the pump). `flush()`
    ignores the window: a drain answers everything regardless."""

    cost_prior_s: float = 0.002  # dispatch-cost estimate before any sample
    cost_ema_alpha: float = 0.2  # weight of the newest measured dispatch
    slack_safety: float = 1.0  # dispatch when slack <= cost * safety
    max_linger_s: float = 0.02  # deadline-less requests wait at most this
    device_busy_s: float = 0.0  # per-dispatch device occupancy model (off)


class MicroBatcher:
    """One batcher per engine; `poll()` is the only entry point the serving
    loop needs — it dispatches zero or more due batches and returns every
    typed response they produced."""

    def __init__(
        self,
        engine,
        config: Optional[BatcherConfig] = None,
        clock: Optional[Callable[[], float]] = None,
        name: Optional[str] = None,
        pre_dispatch: Optional[Callable[[], None]] = None,
    ):
        self.engine = engine
        self.config = config if config is not None else BatcherConfig()
        self.clock = clock if clock is not None else engine.clock
        self.name = name
        self.pre_dispatch = pre_dispatch
        self.dispatch_cost_s = float(self.config.cost_prior_s)
        self.dispatches = 0
        self._busy_until = 0.0  # device-occupancy model (device_busy_s)
        # tenant lanes whose depth gauge we have ever set (to zero drained
        # lanes, _observe_depth); empty forever on single-tenant engines
        self._tenant_lanes_seen: set = set()

    # ---------------------------------------------------------------- triggers
    def dispatch_due(self) -> Optional[str]:
        """The trigger that makes dispatching NOW the right call, or None to
        keep coalescing."""
        q = self.engine.queue
        depth = len(q)
        if depth == 0:
            return None
        now = self.clock()
        if now < self._busy_until:
            return None  # device occupied: backlog builds, honestly
        if depth >= self.engine.buckets[-1]:
            return TRIGGER_BUCKET_FULL
        oldest = q.peek_oldest()
        if oldest.deadline is not None:
            slack = oldest.deadline - now
            if slack <= self.dispatch_cost_s * self.config.slack_safety:
                return TRIGGER_DEADLINE
        if now - oldest.enqueued_at >= self.config.max_linger_s:
            return TRIGGER_LINGER
        return None

    # ---------------------------------------------------------------- serving
    def poll(self) -> List[ServeResponse]:
        """Dispatch every due batch (the queue strictly shrinks per
        dispatch, so this terminates) and update the queue-depth gauge."""
        out: List[ServeResponse] = []
        # bound by the entry depth: each dispatch pops >= 1 queued request,
        # so this can never loop past the work that existed when poll began
        for _ in range(len(self.engine.queue) + 1):
            trigger = self.dispatch_due()
            if trigger is None:
                break
            out.extend(self._dispatch(trigger))
        self._observe_depth()
        return out

    def flush(self) -> List[ServeResponse]:
        """Dispatch until the queue is empty (graceful drain: every queued
        request is ANSWERED, through the device, not shed)."""
        out: List[ServeResponse] = []
        while len(self.engine.queue):
            out.extend(self._dispatch(TRIGGER_DRAIN))
        self._observe_depth()
        return out

    # -------------------------------------------------------------- internals
    def _dispatch(self, trigger: str) -> List[ServeResponse]:
        _m.counter(_m.DISPATCHES).inc(trigger=trigger)
        self.dispatches += 1
        record_event(
            "dispatch", replica=self.name, trigger=trigger,
            depth=len(self.engine.queue),
        )
        t0 = self.clock()  # before the hook: its virtual service time is
        # exactly what the cost EMA must measure
        if _reqtrace.enabled():
            # request tracing: the engine's on_dispatch stamps the batch
            # with this replica lane, the trigger, and the t0 above — so
            # the trace's linger/device split matches the cost EMA's view
            _reqtrace.dispatch_context(self.name or "", trigger, t0)
        try:
            if self.pre_dispatch is not None:
                self.pre_dispatch()
            responses = self.engine.process_pending()
        finally:
            # a pump that never reached on_dispatch (breaker open, empty
            # pop, device error) must not leak its context into a later
            # context-less dispatch
            if _reqtrace.enabled():
                _reqtrace.clear_dispatch_context()
        dt = self.clock() - t0
        if dt > 0:  # a virtual clock that did not move leaves the prior
            a = self.config.cost_ema_alpha
            self.dispatch_cost_s = (1 - a) * self.dispatch_cost_s + a * dt
        if self.config.device_busy_s > 0:
            self._busy_until = self.clock() + self.config.device_busy_s
        return responses

    def _observe_depth(self) -> None:
        depth = float(len(self.engine.queue))
        if self.name is not None:
            _m.gauge(_m.QUEUE_DEPTH).set(depth, replica=self.name)
        else:
            _m.gauge(_m.QUEUE_DEPTH).set(depth)
        if getattr(self.engine, "tenants", None) is not None:
            # per-tenant lane depths (ISSUE 17): refreshed here — on the
            # same cadence as the fleet gauge — and zeroed for lanes that
            # drained, so a quiet tenant reads 0, not its last storm value
            depths = self.engine.queue.tenant_depths()
            for t in self._tenant_lanes_seen - set(depths):
                _m.gauge(_m.TENANT_QUEUE_DEPTH).set(0.0, tenant=t)
            for t, d in depths.items():
                _m.gauge(_m.TENANT_QUEUE_DEPTH).set(float(d), tenant=t)
            self._tenant_lanes_seen |= set(depths)
