"""Blue/green hot swap: promote a new artifact with zero dropped requests.

The serve-time half of the model lifecycle: a new `.mgproto` artifact (or
any engine factory) is staged into STANDBY engines, fully warmed, and
verified against the trust contract BEFORE any traffic moves. Verification
fails CLOSED — an artifact that cannot be trust-gated keeps the old model
serving:

  * `uncalibrated`          — no embedded calibration (and the caller did
    not explicitly allow degraded serving). The factory's own
    `UncalibratedArtifactError` is caught into this rejection too.
  * `fingerprint_mismatch`  — the calibration was measured under a
    different GMM than the artifact serves (the prune-then-serve regression
    the TrustGate exists to catch). Promoting it would silently misgate.
  * `quant_mismatch`        — the calibration was measured under a
    different quant config than the artifact serves (ISSUE 20: quantize-
    then-swap without recalibrating, or swapping an f32 artifact under an
    int8-stamped calibration). A quant-config change mid-swap is refused
    unless the staged artifact carries its own matching recalibration.
  * `stage_failed`          — the factory or bucket warmup raised: the
    artifact cannot even serve, let alone be promoted.

Only after EVERY standby verifies does traffic flip, one replica at a time:
the old engine is marked draining (readiness false — no new routing), its
queued requests transfer into the standby's queue with their original
deadlines and enqueue times intact (`AdmissionQueue.restore`), and the
replica adopts the standby. Queued work is never dropped and never shed by
the flip itself: the standby's queue starts empty and has the same
capacity, so every transfer fits by construction. The set's factory is
retargeted so later restarts build the NEW model.

The chaos knob MGPROTO_CHAOS_SERVE_SWAP_BAD_ARTIFACT simulates an operator
pushing an uncalibrated artifact (the staged engine's gate is stripped),
which must surface as a typed `uncalibrated` rejection — drilled by
scripts/load_test.py and the tier-1 chaos test.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from mgproto_tpu.obs import reqtrace as _reqtrace
from mgproto_tpu.obs.flightrec import record_event
from mgproto_tpu.resilience import chaos as _chaos
from mgproto_tpu.serving import metrics as _m
from mgproto_tpu.serving.replica import ReplicaSet

SWAP_COMMITTED = "committed"
SWAP_REJECTED = "rejected"

REJECT_UNCALIBRATED = "uncalibrated"
REJECT_FINGERPRINT = "fingerprint_mismatch"
REJECT_QUANT = "quant_mismatch"
REJECT_STAGE_FAILED = "stage_failed"
REJECT_NOT_WARMED = "not_warmed"


@dataclasses.dataclass(frozen=True)
class SwapReport:
    """What a swap attempt did — one record per attempt, always returned,
    never raised (a refused promotion is an outcome, not an error)."""

    ok: bool
    reason: str  # SWAP_COMMITTED or a REJECT_* cause
    replicas_swapped: int = 0
    transferred: int = 0  # queued requests moved old -> new
    detail: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def verify_head(gate, require_calibrated: bool = True) -> Optional[str]:
    """The trust half of the promotion gate: the verdicts that depend only
    on a TrustGate, shared by the fleet-level standby verification below
    and the per-tenant head swap (serving/tenants.py — a tenant's staged
    head passes or fails the SAME contract as a whole green fleet).
    Fingerprint mismatch outranks uncalibrated: the gate degrades itself on
    mismatch, and reporting that as 'uncalibrated' would hide the actual
    operator error (stale calibration, not missing one)."""
    if gate.fingerprint_mismatch:
        return REJECT_FINGERPRINT
    if getattr(gate, "quant_mismatch", False):
        # same precedence argument as fingerprint: the gate degraded
        # itself over a specific operator error (quantized without
        # recalibrating), and 'uncalibrated' would hide it
        return REJECT_QUANT
    if gate.degraded and require_calibrated:
        return REJECT_UNCALIBRATED
    return None


def verify_standby(engine, require_calibrated: bool = True) -> Optional[str]:
    """The promotion gate: None when the standby may take traffic, else
    the REJECT_* reason — an engine must be warmed AND trust-verified."""
    if not getattr(engine, "warmed_up", False):
        return REJECT_NOT_WARMED
    return verify_head(engine.gate, require_calibrated=require_calibrated)


def stage_standby(
    factory: Callable[[], Any], require_calibrated: bool = True
) -> Tuple[Optional[Any], Optional[str], str]:
    """Build + warm + verify one standby engine. Returns
    (engine, None, "") on success or (None, reject_reason, detail)."""
    from mgproto_tpu.serving.engine import UncalibratedArtifactError

    try:
        engine = factory()
        engine.warmup()
    except UncalibratedArtifactError as e:
        return None, REJECT_UNCALIBRATED, str(e)
    except Exception as e:  # artifact unreadable, warmup OOM, ...
        return None, REJECT_STAGE_FAILED, f"{type(e).__name__}: {e}"
    chaos = _chaos.get_active()
    if chaos is not None and chaos.serve_swap_bad_artifact_due():
        # drill: the operator pushed an artifact with no trust data; the
        # verification below must refuse it exactly like the real thing
        from mgproto_tpu.serving.gate import TrustGate

        engine.gate = TrustGate(None)
    reason = verify_standby(engine, require_calibrated=require_calibrated)
    if reason is not None:
        return None, reason, ""
    return engine, None, ""


def stage_fleet(
    count: int,
    standby_factory: Callable[[], Any],
    require_calibrated: bool = True,
) -> Tuple[List[Any], Optional[SwapReport]]:
    """Stage + verify `count` standby engines — the EXPENSIVE, trafficless
    half of a swap (artifact loads + bucket warmup compiles). It touches no
    live state, so callers that serialize ReplicaSet access through a pump
    (the HTTP frontend) may run it off-pump while traffic keeps flowing.
    Returns (standbys, None) or ([], rejection) — the whole green fleet
    stages BEFORE any traffic moves: a mid-flip stage failure would leave a
    mixed fleet, which is exactly the non-atomicity blue/green prevents."""
    standbys: List[Any] = []
    for _ in range(max(int(count), 1)):
        engine, reason, detail = stage_standby(
            standby_factory, require_calibrated=require_calibrated
        )
        if engine is None:
            _m.counter(_m.SWAPS).inc(result=SWAP_REJECTED, reason=reason)
            record_event("swap_rejected", reason=reason, detail=detail)
            _reqtrace.plane_event("swap_rejected", reason=reason)
            return [], SwapReport(ok=False, reason=reason, detail=detail)
        standbys.append(engine)
    return standbys, None


def flip_fleet(
    replica_set: ReplicaSet,
    standby_factory: Callable[[], Any],
    standbys: List[Any],
) -> SwapReport:
    """The CHEAP, atomic half: flip traffic replica-by-replica with queued
    work transferred, then retarget the set's factory. Must run where
    ReplicaSet access is serialized (the frontend's pump, or the single
    batch-driver thread). The live list is taken NOW — a replica that
    failed or restarted while standbys staged is handled, provided
    `standbys` covers every replica that might be live (callers stage one
    per replica slot)."""
    live = [rep for rep in replica_set.replicas if rep.engine is not None]
    transferred = 0
    swapped = 0
    for rep, standby in zip(live, standbys):
        old = rep.engine
        old.draining = True  # readiness false: no new routing to blue
        moved = old.queue.drain_all()
        for req in moved:
            # same capacity, empty target: restore cannot fail, but a
            # False here must still never lose the request
            if not standby.queue.restore(req):  # pragma: no cover
                raise RuntimeError(
                    "swap transfer overflowed the standby queue"
                )
        transferred += len(moved)
        rep.adopt(standby)
        swapped += 1
    replica_set.engine_factory = standby_factory
    _m.counter(_m.SWAPS).inc(result=SWAP_COMMITTED)
    _m.counter(_m.SWAP_TRANSFERRED).inc(float(transferred))
    record_event(
        "swap_committed", replicas=swapped, transferred=transferred
    )
    _reqtrace.plane_event(
        "swap_committed", replicas=swapped, transferred=transferred
    )
    return SwapReport(
        ok=True,
        reason=SWAP_COMMITTED,
        replicas_swapped=swapped,
        transferred=transferred,
    )


def hot_swap(
    replica_set: ReplicaSet,
    standby_factory: Callable[[], Any],
    require_calibrated: bool = True,
) -> SwapReport:
    """Stage a full green fleet, verify every engine, then flip traffic
    replica-by-replica with queued work transferred (see module docstring).
    Counts `serving_swap_total{result=...}`."""
    live = sum(
        1 for rep in replica_set.replicas if rep.engine is not None
    )
    standbys, rejection = stage_fleet(
        live, standby_factory, require_calibrated=require_calibrated
    )
    if rejection is not None:
        return rejection
    return flip_fleet(replica_set, standby_factory, standbys)
