"""ID-score calibration: the trust data a served model cannot run without.

MGProto's abstention signal is the generative score log p(x) (PAPER.md;
`core/mgproto.py:log_px`). Its absolute scale is a property of the TRAINED
mixture — it moves with every EM step, push projection, and especially
`prune_top_m` (which removes mixture mass; core/mgproto.py:334-338 warns
"recompute OoD thresholds afterwards"). A threshold is therefore only valid
for the exact GMM it was measured against, so a calibration carries:

  * percentile thresholds of the held-out ID set's log p(x) (the operating
    points; the serving default is the same 5th percentile the evaluation
    driver uses, engine/evaluate.py),
  * a quantile sketch (101 evenly spaced quantiles) of the ID log p(x)
    distribution — any other operating point can be interpolated at serve
    time without rescoring the ID set,
  * a per-class logit temperature (dispersion equalizer for confidence
    reporting),
  * `gmm_fingerprint`: sha256 over the GMM pytree the scores were measured
    under. The trust gate FAILS CLOSED on mismatch (serving/gate.py):
    prune-then-serve without recalibration is detected, not silently wrong.

Persisted as `calibration.json` inside the `.mgproto` export artifact
(engine/export.py) — the artifact either carries its trust data or the
engine refuses to gate with it.

Load path is numpy+stdlib only: a bare serving host must be able to read a
calibration without the model stack.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

CALIBRATION_FORMAT = "mgproto-calibration-v1"
DEFAULT_PERCENTILES: Tuple[float, ...] = (1.0, 5.0, 10.0)
DEFAULT_PERCENTILE = 5.0
_SKETCH_POINTS = 101  # quantiles at 0, 1, ..., 100


class CalibrationError(ValueError):
    """Malformed/missing/incompatible calibration payload."""


def gmm_fingerprint(gmm) -> str:
    """sha256 over the GMM pytree (means/sigmas/priors/keep — structure and
    exact leaf bytes). Any transform that moves the p(x) scale — EM, push,
    prune — changes it, which is exactly the invalidation we want."""
    from mgproto_tpu.utils.checkpoint import pytree_digest

    return pytree_digest(gmm)


@dataclasses.dataclass(frozen=True)
class Calibration:
    """Serve-time trust data (see module docstring for the fields' story)."""

    percentile: float  # the default operating point
    threshold_log_px: float  # ID log p(x) at `percentile`
    thresholds: Dict[str, float]  # percentile (as str) -> log p(x)
    quantile_log_px: Tuple[float, ...]  # sketch at 0..100, len 101
    per_class_temperature: Tuple[float, ...]
    gmm_fingerprint: str
    num_id_samples: int
    source: str = ""  # provenance: where the ID scores came from
    # compute dtype of the model the ID scores were measured under
    # (perf/precision.py): a bf16-measured threshold applied to an f32
    # serve (or vice versa) shifts the operating point the same way a
    # stale fingerprint does, so the gate fails closed on mismatch.
    # "" = unknown (pre-policy calibration): honored for back-compat.
    compute_dtype: str = ""
    # quant tag of the served weights the ID scores were measured under
    # (perf/quant.py quant_config "tag"; "" = unquantized/full precision).
    # Unlike compute_dtype, "" is not "unknown" — it is the f32 identity:
    # a quantized program refuses an empty-stamped calibration fail-closed
    # (serving/gate.py), because thresholds measured on unrounded weights
    # do not transfer to the rounded grid.
    quant_config: str = ""

    # ---------------------------------------------------------------- derive
    @staticmethod
    def from_scores(
        id_log_px: np.ndarray,
        id_logits: np.ndarray,
        fingerprint: str,
        percentile: float = DEFAULT_PERCENTILE,
        percentiles: Sequence[float] = DEFAULT_PERCENTILES,
        source: str = "",
        compute_dtype: str = "",
        quant_config: str = "",
    ) -> "Calibration":
        """Build from per-sample held-out ID scores (log p(x) [N] and class
        log-likelihoods [N, C]), host-side float64 like the eval driver."""
        scores = np.asarray(id_log_px, np.float64).ravel()
        if scores.size == 0:
            raise CalibrationError("cannot calibrate from zero ID samples")
        if not np.isfinite(scores).all():
            raise CalibrationError("non-finite ID log p(x) scores")
        pcts = sorted(set(float(p) for p in percentiles) | {float(percentile)})
        thresholds = {
            f"{p:g}": float(np.percentile(scores, p)) for p in pcts
        }
        sketch = tuple(
            float(v)
            for v in np.percentile(scores, np.linspace(0.0, 100.0, _SKETCH_POINTS))
        )
        logits = np.asarray(id_logits, np.float64)
        # dispersion equalizer: per-class std of log p(x|c), scaled so the
        # mean temperature is 1.0 (a pure reshape of confidence, never of
        # the abstention decision, which gates on log p(x) alone). Columns
        # with non-finite entries get temperature 1.0: padded class-bucket
        # slots (online/classes.py) legitimately emit -inf log p(x|c), and
        # an undefined dispersion must not poison the whole equalizer.
        finite_cols = np.isfinite(logits).all(axis=0)
        temps = np.ones(logits.shape[1], np.float64)
        if finite_cols.any():
            stds = np.maximum(logits[:, finite_cols].std(axis=0), 1e-6)
            temps[finite_cols] = stds / float(stds.mean())
        return Calibration(
            percentile=float(percentile),
            threshold_log_px=thresholds[f"{float(percentile):g}"],
            thresholds=thresholds,
            quantile_log_px=sketch,
            per_class_temperature=tuple(float(t) for t in temps),
            gmm_fingerprint=str(fingerprint),
            num_id_samples=int(scores.size),
            source=source,
            compute_dtype=str(compute_dtype),
            quant_config=str(quant_config),
        )

    # ---------------------------------------------------------------- lookup
    def threshold_for(self, percentile: float) -> float:
        """log p(x) threshold at any operating point, interpolated from the
        quantile sketch (exact at the persisted percentiles)."""
        key = f"{float(percentile):g}"
        if key in self.thresholds:
            return self.thresholds[key]
        if not 0.0 <= percentile <= 100.0:
            raise CalibrationError(
                f"percentile must be in [0, 100], got {percentile}"
            )
        q = np.linspace(0.0, 100.0, len(self.quantile_log_px))
        return float(np.interp(percentile, q, self.quantile_log_px))

    def id_quantile_of(self, log_px: float) -> float:
        """Where a score sits in the ID distribution (0..1) — the serving
        response's calibrated trust score."""
        q = np.linspace(0.0, 1.0, len(self.quantile_log_px))
        return float(np.interp(log_px, self.quantile_log_px, q))

    # ------------------------------------------------------------- serialize
    def to_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        d["format"] = CALIBRATION_FORMAT
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @staticmethod
    def from_dict(d: Dict) -> "Calibration":
        fmt = d.get("format")
        if fmt != CALIBRATION_FORMAT:
            raise CalibrationError(f"unknown calibration format {fmt!r}")
        try:
            return Calibration(
                percentile=float(d["percentile"]),
                threshold_log_px=float(d["threshold_log_px"]),
                thresholds={k: float(v) for k, v in d["thresholds"].items()},
                quantile_log_px=tuple(float(v) for v in d["quantile_log_px"]),
                per_class_temperature=tuple(
                    float(t) for t in d["per_class_temperature"]
                ),
                gmm_fingerprint=str(d["gmm_fingerprint"]),
                num_id_samples=int(d["num_id_samples"]),
                source=str(d.get("source", "")),
                # absent in pre-policy calibrations: "" = unknown, honored
                compute_dtype=str(d.get("compute_dtype", "")),
                # absent in pre-quant calibrations: "" = the f32 identity
                quant_config=str(d.get("quant_config", "")),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise CalibrationError(f"malformed calibration payload: {e}")

    @staticmethod
    def from_json(text: str) -> "Calibration":
        try:
            d = json.loads(text)
        except ValueError as e:
            raise CalibrationError(f"calibration is not valid JSON: {e}")
        return Calibration.from_dict(d)


def calibrate(
    trainer, state, id_batches: Iterable, percentile: float = DEFAULT_PERCENTILE,
    percentiles: Sequence[float] = DEFAULT_PERCENTILES, source: str = "",
    quant_config: str = "",
) -> Calibration:
    """Derive a Calibration from a held-out ID loader through the SAME eval
    step the engine serves with (`Trainer.eval_step` -> engine/evaluate.py's
    shared loop), so thresholds and served scores share one code path."""
    from mgproto_tpu.engine.evaluate import _run_eval

    id_log_px, _, _, _, id_logits = _run_eval(trainer, state, id_batches)
    return Calibration.from_scores(
        id_log_px,
        id_logits,
        fingerprint=gmm_fingerprint(state.gmm),
        percentile=percentile,
        percentiles=percentiles,
        source=source,
        # stamp the precision policy the scores were measured under: the
        # gate refuses to apply these thresholds to a different dtype.
        # quant_config flows in from the caller (mgproto-export --quantize
        # measures through the round-tripped weights and stamps their tag)
        compute_dtype=trainer.cfg.model.compute_dtype,
        quant_config=quant_config,
    )


def calibrate_from_config(
    cfg, trainer, state, percentile: float = DEFAULT_PERCENTILE,
    quant_config: str = "",
) -> Calibration:
    """CLI-facing wrapper: derive the calibration from the config's held-
    out ID loader (`cfg.data.test_dir`), with its provenance recorded. The
    ONE implementation behind both `mgproto-export --calibrate` and
    `mgproto-serve --calibrate`, so export-time and serve-time
    calibrations cannot drift."""
    from mgproto_tpu.data import build_pipelines

    _, _, test_loader, _ = build_pipelines(cfg)
    return calibrate(
        trainer, state, test_loader, percentile=percentile,
        source=f"test_dir={cfg.data.test_dir}",
        quant_config=quant_config,
    )
