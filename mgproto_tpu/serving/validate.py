"""Per-request input validation: a typed reject, never a device error.

The serving boundary is where arbitrary caller bytes meet a compiled XLA
program. Anything that would crash, retrace, or silently poison the device
computation is converted HERE into a `ValidationFailure` with a machine-
readable reason — shapes that don't match the artifact, dtypes that can't
losslessly become float32, NaN/Inf pixels, absurd value ranges. Host-side
numpy only: by the time an array reaches `jax.device_put` it is exactly
`float32 [H, W, 3]` with finite values.

The checks are ordered cheapest-first and the NaN scrub is LAST: a payload
can fail several ways, and the reported reason should be the structural one
(a string payload is "malformed", not "non-finite").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

# |pixel| bound AFTER normalization: ImageNet-normalized pixels live within
# ~[-3, 3]; 64 leaves headroom for exotic normalizations while still
# rejecting e.g. raw uint16 sensor dumps that would shift log p(x) scales
MAX_ABS_PIXEL = 64.0

REASON_MALFORMED = "malformed"
REASON_BAD_SHAPE = "bad_shape"
REASON_BAD_DTYPE = "bad_dtype"
REASON_NONFINITE = "nonfinite"
REASON_OUT_OF_RANGE = "out_of_range"


class ValidationFailure(ValueError):
    """Typed rejection: `reason` is one of the REASON_* constants."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason
        self.detail = detail


@dataclasses.dataclass(frozen=True)
class ValidationSpec:
    """What the compiled program accepts (from the artifact/model config)."""

    img_size: int
    channels: int = 3
    max_abs: float = MAX_ABS_PIXEL


def validate_image(payload: Any, spec: ValidationSpec) -> np.ndarray:
    """One request's payload -> a clean float32 [H, W, 3] array, or raise
    ValidationFailure. Accepts anything numpy can coerce to a numeric array
    of the right shape; never lets a bad payload reach the device."""
    try:
        arr = np.asarray(payload)
    except Exception as e:
        raise ValidationFailure(REASON_MALFORMED, f"not array-like: {e}")
    if arr.dtype == object or arr.dtype.kind in "USV":
        raise ValidationFailure(
            REASON_BAD_DTYPE, f"non-numeric dtype {arr.dtype}"
        )
    want = (spec.img_size, spec.img_size, spec.channels)
    if arr.shape != want:
        raise ValidationFailure(
            REASON_BAD_SHAPE, f"got {arr.shape}, artifact expects {want}"
        )
    if arr.dtype.kind not in "fiub":
        raise ValidationFailure(
            REASON_BAD_DTYPE, f"cannot serve dtype {arr.dtype}"
        )
    arr = arr.astype(np.float32)
    if not np.isfinite(arr).all():
        raise ValidationFailure(REASON_NONFINITE, "NaN/Inf pixels")
    peak = float(np.abs(arr).max()) if arr.size else 0.0
    if peak > spec.max_abs:
        raise ValidationFailure(
            REASON_OUT_OF_RANGE,
            f"|pixel| max {peak:.3g} exceeds {spec.max_abs:g}",
        )
    return arr


def validate_batch(
    payload: Any, spec: ValidationSpec, max_batch: Optional[int] = None
) -> np.ndarray:
    """A [N, H, W, 3] batch payload -> clean float32 array (same checks)."""
    try:
        arr = np.asarray(payload)
    except Exception as e:
        raise ValidationFailure(REASON_MALFORMED, f"not array-like: {e}")
    if arr.ndim != 4:
        raise ValidationFailure(
            REASON_BAD_SHAPE, f"batch must be 4-d, got ndim={arr.ndim}"
        )
    if max_batch is not None and arr.shape[0] > max_batch:
        raise ValidationFailure(
            REASON_BAD_SHAPE,
            f"batch of {arr.shape[0]} exceeds max {max_batch}",
        )
    rows = [validate_image(row, spec) for row in arr]
    return (
        np.stack(rows)
        if rows
        else np.zeros((0, spec.img_size, spec.img_size, spec.channels),
                      np.float32)
    )
