"""Observatory-driven autoscaler: grow/shrink the ReplicaSet under load.

The elastic half of ISSUE 13 (ROADMAP item 3): the serving plane already
EMITS everything a scaling decision needs — queue depth, shed counts,
per-request latency histograms, batch-fill fractions — into the telemetry
registry (PR 7/8). This module closes the loop: a pump-hook control policy
on the plane's injectable clock (no sleeps, no threads — the lint applies)
reads those signals over a sliding decision window and steers the replica
count within `[min_replicas, max_replicas]`:

  * SCALE UP when the fleet is saturated — queue depth per ready replica,
    windowed shed rate, or windowed p99 over their thresholds. The new
    replica is added through `ReplicaSet.add_replica()` (a due-now backoff
    entry: the next supervisor poll builds + warms it OFF any request's
    critical path), and warmup is cheap BY CONSTRUCTION when the engine
    factory carries an AOT executable cache (serving/aotcache.py): a
    scale-up is a deserialize, not a compile storm.
  * SCALE DOWN when the fleet has been calm for `down_patience`
    consecutive evaluations — near-empty queues, zero window sheds, thin
    batches. The victim drains through `ReplicaSet.remove_replica()`:
    queued requests transfer to survivors via the same `drain_all/restore`
    path a heartbeat failure uses, with zero dropped requests.

Every applied decision is counted (`autoscale_events_total{direction=}`),
steers the `autoscale_replicas_target` gauge, and lands on the flight
recorder WITH the triggering signal snapshot — a scale event in a
post-mortem always answers "what did the plane look like when you did
that?".

Windowed signals are COUNTER/HISTOGRAM DELTAS between evaluations (the
registry is cumulative): p99 comes from diffing the request-latency
histogram's bucket counts, so the decision sees the last window's tail,
not the run's whole history.

Per-replica bucket right-sizing: `hbm_bucket_prep` wraps the PR-6 HBM
planner (`perf/planner.plan_serve_buckets`) into a `ReplicaSet`
`engine_prep` hook — every engine a scale-up (or restart) builds gets its
warmup bucket ladder shrunk to ITS device's budget before anything
compiles, so heterogeneous hardware (v5e/v5p/GPU/CPU dev boxes) joins the
fleet with heterogeneous ladders instead of OOMing on a uniform one.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, Optional

from mgproto_tpu.obs.flightrec import record_event
from mgproto_tpu.serving import metrics as _m
from mgproto_tpu.serving.response import ServeResponse
from mgproto_tpu.telemetry.registry import (
    default_registry,
    percentile_from_buckets,
)

DIRECTION_UP = "up"
DIRECTION_DOWN = "down"


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Thresholds + pacing. Scale-up triggers are OR-ed (any saturation
    signal suffices); scale-down needs EVERY calm condition for
    `down_patience` consecutive evaluations (shrinking on a noisy window
    would thrash the fleet — the republisher's confirmation discipline)."""

    min_replicas: int = 1
    max_replicas: int = 4
    interval_s: float = 0.25  # decision cadence on the injected clock
    # -- scale-up saturation thresholds --
    up_queue_per_replica: float = 6.0  # queued requests per ready replica
    up_shed_rate: float = 0.02  # window sheds / window requests
    up_p99_s: float = 0.0  # windowed request p99 (0 = signal disabled)
    # -- scale-down calm thresholds --
    down_queue_per_replica: float = 1.0
    # windowed capacity utilization = window requests / (window dispatches
    # x largest bucket). NOT the batch-fill histogram: pad-to-smallest-
    # bucket makes per-dispatch fill ~1.0 by construction even at trickle
    # traffic — utilization against the LARGEST bucket is what actually
    # distinguishes a saturated fleet from an idle one
    down_utilization: float = 0.5
    down_patience: int = 3  # consecutive calm evaluations before shrink
    # -- pacing --
    up_cooldown_s: float = 0.5  # min spacing between scale-ups
    down_cooldown_s: float = 1.0  # min spacing between scale-downs


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    """One applied decision (tick returns None when nothing changed)."""

    t: float
    direction: str
    reason: str
    replicas_before: int
    replicas_after: int
    signals: Dict[str, Any]
    responses: List[ServeResponse] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "t": round(self.t, 6),
            "direction": self.direction,
            "reason": self.reason,
            "replicas_before": self.replicas_before,
            "replicas_after": self.replicas_after,
            "signals": self.signals,
        }


def _merged_hist(snapshot: Dict, name: str) -> Optional[Dict[str, Any]]:
    """One cumulative histogram series merged across label sets."""
    m = snapshot.get(name)
    if not m or m.get("type") != "histogram":
        return None
    merged: Optional[Dict[str, Any]] = None
    for s in m.get("series", []):
        if merged is None:
            merged = {
                "bounds": list(s["bounds"]),
                "bucket_counts": list(s["bucket_counts"]),
                "count": s["count"],
                "sum": s["sum"],
            }
        else:
            merged["bucket_counts"] = [
                a + b
                for a, b in zip(merged["bucket_counts"], s["bucket_counts"])
            ]
            merged["count"] += s["count"]
            merged["sum"] += s["sum"]
    return merged


def _counter_total(snapshot: Dict, name: str) -> float:
    m = snapshot.get(name) or {}
    return sum(
        s.get("value") or 0.0 for s in m.get("series", [])
    )


def _hist_delta(
    cur: Optional[Dict[str, Any]], prev: Optional[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    """cur - prev as a bucket dict (None when cur is absent/empty)."""
    if cur is None:
        return None
    if prev is None:
        return dict(cur)
    return {
        "bounds": cur["bounds"],
        "bucket_counts": [
            a - b
            for a, b in zip(cur["bucket_counts"], prev["bucket_counts"])
        ],
        "count": cur["count"] - prev["count"],
        "sum": cur["sum"] - prev["sum"],
    }


class Autoscaler:
    """`tick(now)` is the whole interface: call it from the pump that
    drives `ReplicaSet.poll()` (the HTTP frontend's executor step, the
    batch drivers' `on_pump`, the load harness's loop). Returns the
    applied `ScaleDecision` — whose `responses` the caller must surface,
    they are real typed answers from a scale-down drain — or None."""

    def __init__(
        self,
        replica_set,
        config: Optional[AutoscalerConfig] = None,
        clock: Optional[Callable[[], float]] = None,
        registry=None,
    ):
        self.rs = replica_set
        self.config = config if config is not None else AutoscalerConfig()
        if self.config.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.config.max_replicas < self.config.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.clock = clock if clock is not None else replica_set.clock
        self._registry = registry
        self._last_eval: Optional[float] = None
        self._last_up = -math.inf
        self._last_down = -math.inf
        self._calm_streak = 0
        self._prev: Dict[str, Any] = {}
        self.decisions: List[ScaleDecision] = []
        _m.gauge(_m.AUTOSCALE_TARGET).set(float(len(self.rs.replicas)))

    @property
    def registry(self):
        return (
            self._registry if self._registry is not None
            else default_registry()
        )

    # ---------------------------------------------------------------- signals
    def _signals(self, now: float) -> Dict[str, Any]:
        """One decision window's view of the observatory: counter and
        histogram DELTAS since the previous evaluation + instantaneous
        fleet state."""
        snapshot = self.registry.snapshot()
        ready = len(self.rs.ready_replicas())
        total = len(self.rs.replicas)
        depth = sum(
            len(rep.engine.queue)
            for rep in self.rs.replicas
            if rep.engine is not None
        )
        requests = _counter_total(snapshot, _m.REQUESTS)
        sheds = _counter_total(snapshot, _m.SHED)
        lat = _merged_hist(snapshot, _m.REQUEST_SECONDS)
        fill = _merged_hist(snapshot, _m.BATCH_FILL_HIST)
        w_requests = requests - self._prev.get("requests", 0.0)
        w_sheds = sheds - self._prev.get("sheds", 0.0)
        w_lat = _hist_delta(lat, self._prev.get("lat"))
        w_fill = _hist_delta(fill, self._prev.get("fill"))
        self._prev = {
            "requests": requests, "sheds": sheds, "lat": lat, "fill": fill,
        }
        p99 = None
        if w_lat and w_lat["count"] > 0:
            p99 = percentile_from_buckets(w_lat, 99.0)
        fill_mean = None
        w_dispatches = None
        if w_fill and w_fill["count"] > 0:
            fill_mean = w_fill["sum"] / w_fill["count"]
            w_dispatches = w_fill["count"]
        max_bucket = max(
            (rep.engine.buckets[-1]
             for rep in self.rs.replicas if rep.engine is not None),
            default=0,
        )
        utilization = None
        if w_dispatches and max_bucket:
            utilization = w_requests / (w_dispatches * max_bucket)
        return {
            "t": round(now, 6),
            "replicas": total,
            "replicas_ready": ready,
            "queue_depth": depth,
            "queue_per_replica": depth / max(ready, 1),
            "window_requests": w_requests,
            "window_sheds": w_sheds,
            "shed_rate": (w_sheds / w_requests) if w_requests > 0 else 0.0,
            "window_p99_s": p99,
            "window_batch_fill": fill_mean,
            "window_dispatches": w_dispatches,
            "window_utilization": utilization,
        }

    # --------------------------------------------------------------- decision
    def _saturation_reason(self, sig: Dict[str, Any]) -> Optional[str]:
        c = self.config
        if sig["queue_per_replica"] >= c.up_queue_per_replica:
            return "queue_depth"
        if (
            sig["window_requests"] > 0
            and sig["shed_rate"] >= c.up_shed_rate
        ):
            return "shed_rate"
        if (
            c.up_p99_s > 0
            and sig["window_p99_s"] is not None
            and sig["window_p99_s"] >= c.up_p99_s
        ):
            return "p99"
        return None

    def _calm(self, sig: Dict[str, Any]) -> bool:
        c = self.config
        if sig["window_sheds"] > 0:
            return False
        if sig["queue_per_replica"] > c.down_queue_per_replica:
            return False
        util = sig["window_utilization"]
        if util is not None and util > c.down_utilization:
            return False
        return True

    def tick(self, now: Optional[float] = None) -> Optional[ScaleDecision]:
        """Evaluate on cadence; apply at most one scale step. Consumes
        ZERO time itself (clock injectable; nothing blocks — the lint
        covers this module)."""
        now = self.clock() if now is None else now
        c = self.config
        if (
            self._last_eval is not None
            and now - self._last_eval < c.interval_s
        ):
            return None
        self._last_eval = now
        sig = self._signals(now)
        before = len(self.rs.replicas)
        reason = self._saturation_reason(sig)
        if (
            reason is not None
            and before < c.max_replicas
            and now - self._last_up >= c.up_cooldown_s
        ):
            self._calm_streak = 0
            self._last_up = now
            self.rs.add_replica()
            return self._applied(
                now, DIRECTION_UP, reason, before, sig, []
            )
        if reason is not None:
            # saturated but cannot grow (at max / cooling down): saturation
            # still resets the calm streak so a shrink cannot follow
            self._calm_streak = 0
            return None
        if not self._calm(sig):
            self._calm_streak = 0
            return None
        self._calm_streak += 1
        if (
            self._calm_streak >= c.down_patience
            and before > c.min_replicas
            and now - self._last_down >= c.down_cooldown_s
        ):
            self._calm_streak = 0
            self._last_down = now
            responses = self.rs.remove_replica()
            return self._applied(
                now, DIRECTION_DOWN, "calm", before, sig, responses
            )
        return None

    def _applied(
        self,
        now: float,
        direction: str,
        reason: str,
        before: int,
        sig: Dict[str, Any],
        responses: List[ServeResponse],
    ) -> ScaleDecision:
        after = len(self.rs.replicas)
        _m.counter(_m.AUTOSCALE_EVENTS).inc(direction=direction)
        _m.gauge(_m.AUTOSCALE_TARGET).set(float(after))
        record_event(
            f"autoscale_{direction}", reason=reason,
            replicas_before=before, replicas_after=after,
            **{k: v for k, v in sig.items() if k != "t"},
        )
        decision = ScaleDecision(
            t=now, direction=direction, reason=reason,
            replicas_before=before, replicas_after=after,
            signals=sig, responses=responses,
        )
        self.decisions.append(decision)
        return decision

    # ----------------------------------------------------------------- status
    def status(self) -> Dict[str, Any]:
        """Operator view (the frontend's GET /admin/autoscale)."""
        return {
            "min_replicas": self.config.min_replicas,
            "max_replicas": self.config.max_replicas,
            "replicas": len(self.rs.replicas),
            "replicas_ready": len(self.rs.ready_replicas()),
            "calm_streak": self._calm_streak,
            "decisions": len(self.decisions),
            "last_decision": (
                self.decisions[-1].to_dict() if self.decisions else None
            ),
        }


def hbm_bucket_prep(
    budget_bytes: Optional[int] = None,
    margin: Optional[float] = None,
    log: Optional[Callable[[str], None]] = None,
) -> Callable[[Any], None]:
    """An `engine_prep` hook (ReplicaSet) that right-sizes EVERY new
    engine's bucket ladder to its device's HBM budget via the PR-6 planner
    (`perf/planner.plan_serve_buckets`) before warmup compiles anything.
    Fail-closed like `mgproto-serve --auto_tune`: zero fitting buckets
    raises, sending the replica to backoff instead of warming a predicted
    OOM."""

    def prep(engine) -> None:
        from mgproto_tpu.perf.planner import plan_serve_buckets

        fitting, outcome = plan_serve_buckets(
            engine, budget_bytes=budget_bytes, margin=margin, log=log
        )
        if not fitting:
            raise RuntimeError(
                "hbm_bucket_prep: no warmup bucket fits the HBM budget "
                f"({outcome.budget_bytes} bytes, margin {outcome.margin})"
            )
        if tuple(fitting) != engine.buckets:
            engine.buckets = tuple(fitting)

    return prep
