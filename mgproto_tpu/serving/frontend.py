"""Asyncio HTTP frontend: the network boundary of the serving plane.

Stdlib only (asyncio + json) — no new dependencies. The frontend owns three
things and nothing else:

  * the SOCKETS — a minimal HTTP/1.1 server (`asyncio.start_server`), one
    JSON request/response per connection;
  * the PUMP — a single background task that feeds submitted requests into
    the `ReplicaSet`, drives its `poll()` (continuous micro-batching,
    replica supervision), and resolves each request's future when its typed
    response surfaces. ALL ReplicaSet access happens on the pump — one
    submission/poll/swap at a time, in order — so the plane needs no locks
    and chaos schedules stay deterministic. Engine work runs in the default
    executor, keeping the event loop responsive while XLA dispatches;
  * the DRAIN — on stop (explicit, or the preemption handler's
    SIGTERM/SIGINT flag), the pump stops admitting, answers or sheds every
    queued request typed (`ReplicaSet.drain`), resolves every outstanding
    future, and only then lets the process exit. No silently dropped
    requests — the same contract the batch driver honors.

Endpoints:

  POST /v1/predict   {"id"?, "image": nested lists, "deadline_ms"?,
                     "tenant"?}
                     -> one ServeResponse JSON. Status: 200 predict/abstain,
                     400 reject (503 when the cause is circuit_open/
                     device_error — retryable), 429 shed (503 on shutdown).
  GET  /healthz      liveness: 200 {"alive": true} while the pump runs.
  GET  /readyz       readiness: 200 when >= 1 replica is ready, else 503;
                     body carries per-replica probe detail.
  GET  /metrics      Prometheus text of the process-current registry.
  POST /admin/swap   {"artifact": path} -> blue/green hot swap (fail-closed;
                     see serving/swap.py). 200 committed, 409 rejected.
  GET  /admin/autoscale  autoscaler status (bounds, current/ready replicas,
                     last decision + its signal snapshot); 501 when no
                     autoscaler is configured (serving/autoscale.py).

`await asyncio.sleep` is the only waiting primitive here; `time.sleep` and
friends are banned from the serving path (scripts/check_no_blocking_sleep).
"""

from __future__ import annotations

import asyncio
import json
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from mgproto_tpu.obs import reqtrace as _reqtrace
from mgproto_tpu.serving.replica import ReplicaSet
from mgproto_tpu.serving.response import (
    OUTCOME_ABSTAIN,
    OUTCOME_PREDICT,
    OUTCOME_REJECT,
    OUTCOME_SHED,
    REASON_CIRCUIT_OPEN,
    REASON_DEVICE_ERROR,
    REASON_SHUTDOWN,
    ServeResponse,
    shed_response,
)

_RETRYABLE_REJECTS = (REASON_CIRCUIT_OPEN, REASON_DEVICE_ERROR)
_MAX_BODY_BYTES = 64 * 1024 * 1024  # a padded f32 518x518x3 is ~13MB of JSON
_MAX_HEAD_BYTES = 64 * 1024  # request line + headers, cumulative


def http_status_for(resp: ServeResponse) -> int:
    """The one outcome->status map (documented in the README runbook)."""
    if resp.outcome in (OUTCOME_PREDICT, OUTCOME_ABSTAIN):
        return 200
    if resp.outcome == OUTCOME_REJECT:
        return 503 if resp.reason in _RETRYABLE_REJECTS else 400
    # shed: overload backpressure, except shutdown which is going-away
    return 503 if resp.reason == REASON_SHUTDOWN else 429


class Frontend:
    def __init__(
        self,
        replicas: ReplicaSet,
        host: str = "127.0.0.1",
        port: int = 0,
        poll_interval_s: float = 0.002,
        request_timeout_s: float = 30.0,
        io_timeout_s: float = 10.0,
        max_head_bytes: int = _MAX_HEAD_BYTES,
        preemption_handler=None,
        swap_factory_builder: Optional[Callable[[str], Callable]] = None,
        require_calibrated_swap: bool = True,
        autoscaler=None,
    ):
        """`swap_factory_builder(path)` returns an engine factory for the
        artifact at `path` (the CLI wires the serve flags in); without it
        /admin/swap answers 501. `require_calibrated_swap=False` (the CLI
        sets it from --allow-uncalibrated) lets an operator who explicitly
        opted into degraded serving promote an uncalibrated artifact — the
        same policy the batch-face swap drill applies."""
        self.replicas = replicas
        self.host = host
        self.port = int(port)  # 0 = ephemeral; real port known after start
        self.poll_interval_s = float(poll_interval_s)
        self.request_timeout_s = float(request_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        self.max_head_bytes = int(max_head_bytes)
        self.preemption_handler = preemption_handler
        self.swap_factory_builder = swap_factory_builder
        self.require_calibrated_swap = bool(require_calibrated_swap)
        # autoscaler (serving/autoscale.py): ticked ON the pump, where all
        # ReplicaSet access already serializes — scale decisions can never
        # race a poll, and a scale-down's drain responses resolve futures
        # like any other pump output
        self.autoscaler = autoscaler
        self._server: Optional[asyncio.AbstractServer] = None
        self._pump_task: Optional[asyncio.Task] = None
        self._inbox: Deque[Tuple[Any, str, Optional[float]]] = deque()
        self._admin: Deque[Tuple[Callable[[], Any], asyncio.Future]] = deque()
        self._pending: Dict[str, asyncio.Future] = {}
        self._kick: Optional[asyncio.Event] = None
        self._swap_lock: Optional[asyncio.Lock] = None
        self._stop = False
        self._drained = False
        self._seq = 0
        self.outcomes: Dict[str, int] = {}  # resolved responses by outcome

    # ---------------------------------------------------------------- lifecycle
    async def start(self) -> None:
        self._kick = asyncio.Event()
        self._swap_lock = asyncio.Lock()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._pump_task = asyncio.create_task(self._pump())

    def request_stop(self) -> None:
        self._stop = True
        if self._kick is not None:
            self._kick.set()

    async def run_until_drained(self) -> None:
        """Serve until stopped (request_stop() or the preemption flag),
        then finish the graceful drain before returning."""
        if self._server is None:
            await self.start()
        await self._pump_task
        self._server.close()
        await self._server.wait_closed()

    # --------------------------------------------------------------------- pump
    def _stopping(self) -> bool:
        return self._stop or (
            self.preemption_handler is not None
            and self.preemption_handler.requested()
        )

    async def _pump(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._stopping():
            work = list(self._inbox)
            self._inbox.clear()
            admin = list(self._admin)
            self._admin.clear()

            def step():
                out: List[ServeResponse] = []
                admin_results = [fn() for fn, _fut in admin]
                for payload, rid, deadline_s, tenant in work:
                    out.extend(
                        self.replicas.submit(
                            payload, request_id=rid, deadline_s=deadline_s,
                            tenant=tenant,
                        )
                    )
                out.extend(self.replicas.poll())
                if self.autoscaler is not None:
                    decision = self.autoscaler.tick()
                    if decision is not None:
                        out.extend(decision.responses)
                return out, admin_results

            responses, admin_results = await loop.run_in_executor(None, step)
            for (_fn, fut), result in zip(admin, admin_results):
                if not fut.done():
                    fut.set_result(result)
            self._resolve(responses)
            if not work and not admin and not responses:
                self._kick.clear()
                try:
                    await asyncio.wait_for(
                        self._kick.wait(), timeout=self.poll_interval_s
                    )
                except asyncio.TimeoutError:
                    pass
        await self._graceful_drain(loop)

    async def _graceful_drain(self, loop) -> None:
        """Stop admitting; answer or shed EVERYTHING typed, then resolve
        any future the drain somehow missed (belt and braces: a pending
        future without a response would hang its connection)."""
        work = list(self._inbox)
        self._inbox.clear()
        admin = list(self._admin)
        self._admin.clear()

        def final():
            out: List[ServeResponse] = [
                shed_response(rid, REASON_SHUTDOWN, tenant=tenant)
                for _payload, rid, _deadline, tenant in work
            ]
            out.extend(self.replicas.drain(REASON_SHUTDOWN))
            return out

        self._resolve(await loop.run_in_executor(None, final))
        for _fn, fut in admin:
            if not fut.done():
                fut.set_result(
                    {"ok": False, "reason": REASON_SHUTDOWN}
                )
        for rid in list(self._pending):
            self._resolve([shed_response(rid, REASON_SHUTDOWN)])
        self._drained = True

    def _resolve(self, responses: List[ServeResponse]) -> None:
        for resp in responses:
            self.outcomes[resp.outcome] = (
                self.outcomes.get(resp.outcome, 0) + 1
            )
            fut = self._pending.pop(resp.request_id, None)
            if fut is not None and not fut.done():
                fut.set_result(resp)

    # ------------------------------------------------------------------ routing
    async def _handle(self, reader, writer) -> None:
        status, body, ctype = 400, b'{"error": "bad_request"}', None
        try:
            method, target, headers = await self._read_head(reader)
            length = int(headers.get("content-length", "0"))
            if length > _MAX_BODY_BYTES:
                raise ValueError("body too large")
            # same timeout as the head reads: a client that announces a
            # Content-Length and then stalls must not hold the handler
            # task and its socket open forever (slowloris)
            raw = (
                await asyncio.wait_for(
                    reader.readexactly(length), timeout=self.io_timeout_s
                )
                if length
                else b""
            )
            status, body, ctype = await self._route(method, target, raw)
        except (asyncio.IncompleteReadError, ValueError, UnicodeDecodeError):
            pass  # malformed HTTP: the 400 default answers
        except asyncio.TimeoutError:
            status, body = 408, b'{"error": "timeout"}'
        try:
            writer.write(
                b"HTTP/1.1 %d %s\r\n"
                b"Content-Type: %s\r\n"
                b"Content-Length: %d\r\n"
                b"Connection: close\r\n\r\n"
                % (
                    status,
                    {200: b"OK", 400: b"Bad Request", 404: b"Not Found",
                     408: b"Request Timeout", 409: b"Conflict",
                     429: b"Too Many Requests", 501: b"Not Implemented",
                     503: b"Service Unavailable"}.get(status, b"Status"),
                    ctype or b"application/json",
                    len(body),
                )
            )
            writer.write(body)
            await writer.drain()
        except (ConnectionError, OSError):
            pass  # client went away; its request still got accounted
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _read_head(self, reader):
        line = await asyncio.wait_for(
            reader.readline(), timeout=self.io_timeout_s
        )
        parts = line.decode("ascii").split()
        if len(parts) < 2:
            raise ValueError("bad request line")
        method, target = parts[0].upper(), parts[1]
        headers = {}
        head_bytes = 0
        while True:
            h = await asyncio.wait_for(
                reader.readline(), timeout=self.io_timeout_s
            )
            if h in (b"\r\n", b"\n", b""):
                break
            # cap the cumulative head size: a client drip-feeding headers
            # (each within io_timeout_s) must not hold the connection and
            # grow this dict forever
            head_bytes += len(h)
            if head_bytes > self.max_head_bytes:
                raise ValueError("request head too large")
            key, _, value = h.decode("latin-1").partition(":")
            headers[key.strip().lower()] = value.strip()
        return method, target, headers

    async def _route(self, method, target, raw):
        target = target.split("?", 1)[0]
        if method == "POST" and target == "/v1/predict":
            return await self._predict(raw)
        if method == "GET" and target == "/healthz":
            return 200, json.dumps(
                {"alive": True, "draining": self._stopping()}
            ).encode(), None
        if method == "GET" and target == "/readyz":
            return self._readyz()
        if method == "GET" and target == "/metrics":
            from mgproto_tpu.telemetry.registry import default_registry

            return 200, default_registry().to_prometheus().encode(), (
                b"text/plain; version=0.0.4"
            )
        if method == "POST" and target == "/admin/swap":
            return await self._swap(raw)
        if method == "GET" and target == "/admin/autoscale":
            if self.autoscaler is None:
                return 501, json.dumps(
                    {"error": "autoscaler_not_configured"}
                ).encode(), None
            return 200, json.dumps(
                self.autoscaler.status()
            ).encode(), None
        return 404, b'{"error": "not_found"}', None

    # ----------------------------------------------------------------- handlers
    async def _predict(self, raw: bytes):
        try:
            rec = json.loads(raw)
            payload = rec["image"]
            # multi-tenant serving (ISSUE 17): the tenant id on the wire.
            # Absent = the single-tenant path, byte-identical responses.
            tenant = rec.get("tenant")
            tenant = str(tenant) if tenant is not None else None
            deadline_ms = rec.get("deadline_ms")
            # parsed inside the guard: a non-numeric deadline_ms is a
            # malformed request (typed 400), not an unhandled handler crash
            deadline_s = (
                float(deadline_ms) / 1000.0
                if deadline_ms is not None
                else None
            )
        except (ValueError, KeyError, TypeError):
            return 400, json.dumps(
                {"outcome": OUTCOME_REJECT, "reason": "malformed"}
            ).encode(), None
        self._seq += 1
        rid = str(rec.get("id", f"h{self._seq}"))
        if rid in self._pending:  # duplicate in flight: keep ids unique
            rid = f"{rid}#{self._seq}"
        if _reqtrace.enabled():
            # request tracing starts at the HTTP boundary, stamped with the
            # PLANE's clock (the same one the replicas/batchers run on), so
            # the frontend span includes the pump/inbox wait the engine-side
            # stages cannot see
            _reqtrace.mint(rid, self.replicas.clock())
        if self._stopping():
            resp = shed_response(rid, REASON_SHUTDOWN)
            return http_status_for(resp), json.dumps(
                resp.to_dict()
            ).encode(), None
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending[rid] = fut
        self._inbox.append((payload, rid, deadline_s, tenant))
        self._kick.set()
        try:
            resp = await asyncio.wait_for(
                fut, timeout=self.request_timeout_s
            )
        except asyncio.TimeoutError:
            # contract backstop only: every admitted request is answered by
            # poll/drain; a lost one still must not hang the connection.
            # Deliberately NOT record()ed: the request may still be queued,
            # and its eventual real response is the one metrics account —
            # recording here would double-count the request
            self._pending.pop(rid, None)
            resp = ServeResponse(
                request_id=rid, outcome=OUTCOME_SHED, reason="timeout"
            )
        return http_status_for(resp), json.dumps(resp.to_dict()).encode(), None

    def _readyz(self):
        detail = []
        for rep in self.replicas.replicas:
            detail.append({
                "name": rep.name,
                "state": rep.state,
                "readiness": (
                    rep.probe.readiness() if rep.probe is not None else None
                ),
            })
        ready = bool(self.replicas.ready_replicas()) and not self._stopping()
        return (200 if ready else 503), json.dumps(
            {"ready": ready, "replicas": detail}
        ).encode(), None

    async def _swap(self, raw: bytes):
        if self.swap_factory_builder is None:
            return 501, json.dumps(
                {"ok": False, "reason": "swap_not_configured"}
            ).encode(), None
        try:
            rec = json.loads(raw)
            artifact = str(rec["artifact"])
        except (ValueError, KeyError, TypeError):
            return 400, json.dumps(
                {"ok": False, "reason": "malformed"}
            ).encode(), None
        from mgproto_tpu.serving.swap import flip_fleet, stage_fleet

        factory = self.swap_factory_builder(artifact)
        loop = asyncio.get_running_loop()
        async with self._swap_lock:  # one swap stages at a time
            if self._stopping():  # don't stage a fleet we cannot flip
                return 503, json.dumps(
                    {"ok": False, "reason": REASON_SHUTDOWN}
                ).encode(), None
            # STAGING (artifact loads + warmup compiles, the slow half)
            # runs OFF the pump in its own executor thread: it touches no
            # live state, so predict traffic keeps flowing while the green
            # fleet warms. One standby per replica SLOT (not per currently
            # live engine) so a replica that restarts mid-staging still
            # has a green engine waiting at flip time.
            slots = len(self.replicas.replicas)
            standbys, rejection = await loop.run_in_executor(
                None,
                lambda: stage_fleet(
                    slots, factory,
                    require_calibrated=self.require_calibrated_swap,
                ),
            )
            if rejection is not None:
                return 409, json.dumps(rejection.to_dict()).encode(), None
            if self._stopping():
                # stop arrived while the green fleet staged: the pump may
                # already have drained its admin inbox, so an append now
                # would never be consumed and this handler would hang on
                # its future. No await separates this check from the
                # append below, so the pump cannot drain in between; a
                # stop requested AFTER the append is resolved typed by
                # _graceful_drain's admin sweep.
                return 503, json.dumps(
                    {"ok": False, "reason": REASON_SHUTDOWN}
                ).encode(), None
            # only the FLIP (cheap: queue transfer + adopt) runs on the
            # pump, between traffic steps — atomic with respect to
            # submissions and polls by construction
            fut: asyncio.Future = loop.create_future()
            self._admin.append(
                (lambda: flip_fleet(self.replicas, factory, standbys), fut)
            )
            self._kick.set()
            report = await asyncio.wait_for(fut, timeout=600.0)
        if isinstance(report, dict):  # shutdown raced the swap
            return 503, json.dumps(report).encode(), None
        return (200 if report.ok else 409), json.dumps(
            report.to_dict()
        ).encode(), None
