"""Health/readiness probes reflecting warmup + breaker + gate state.

Two distinct questions, per the usual orchestration contract:

  * liveness  — "is the process wedged?" Always true while the engine
    object is intact; an orchestrator restarts on false/timeout.
  * readiness — "should traffic be routed here?" False until bucket warmup
    has compiled every serving shape (first-request compiles would blow the
    latency SLO), while the circuit breaker is OPEN (the backend is
    failing; routing more traffic in makes the outage worse), and while the
    engine is DRAINING (graceful shutdown or a blue/green flip in flight:
    queued work still answers, new work must go elsewhere). Half-open is
    READY: the breaker is probing its way back and the probe IS traffic.

Degraded mode is READY (classification still serves) but reported, so a
fleet can alert on trust-gating coverage without failing over.
"""

from __future__ import annotations

from typing import Any, Dict

from mgproto_tpu.serving.admission import BREAKER_OPEN


class HealthProbe:
    """Probe views over a ServingEngine (no references held to request
    payloads; safe to poll from any thread)."""

    def __init__(self, engine):
        self.engine = engine

    def liveness(self) -> Dict[str, Any]:
        return {"alive": True}

    def readiness(self) -> Dict[str, Any]:
        e = self.engine
        breaker_open = e.breaker.state == BREAKER_OPEN
        draining = bool(getattr(e, "draining", False))
        ready = e.warmed_up and not breaker_open and not draining
        return {
            "ready": ready,
            "warmed_up": e.warmed_up,
            "draining": draining,
            "buckets": list(e.buckets),
            "breaker_state": e.breaker.state,
            "degraded": e.gate.degraded,
            "fingerprint_mismatch": e.gate.fingerprint_mismatch,
            "queue_depth": len(e.queue),
            "queue_capacity": e.queue.capacity,
        }
