"""Admission control: bounded queue, deadline shedding, circuit breaker.

Under overload a serving process has exactly three honest moves: queue the
request (bounded — an unbounded queue converts overload into latency for
EVERYONE), shed it with a typed response, or stop accepting work while the
backend is failing. All three live here, host-side and jax-free.

  * `AdmissionQueue` — FIFO with a hard capacity and per-request deadlines.
    Shedding is deadline-aware: a full queue first sheds entries that are
    ALREADY past their deadline (oldest first — they can no longer be
    answered in time, so they are the cheapest work to drop), and only
    rejects the newcomer when everything queued is still viable. Batch
    draining re-checks deadlines at pop time: a request that expired while
    queued is shed, not served late.

  * `CircuitBreaker` — closed -> open after `failure_threshold` consecutive
    device failures; the open cooldown follows `resilience.retry`'s
    exponential backoff schedule (the SAME policy module training IO uses,
    so recovery pacing cannot drift between subsystems); after the cooldown
    a half-open probe admits one batch — success closes the breaker and
    resets the schedule, failure re-opens it at the next longer delay.

Clocks are injectable (`clock=`) so chaos tests drive deadline storms and
breaker recovery deterministically, without sleeping.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Any, Callable, Deque, List, Optional, Tuple

from mgproto_tpu.resilience.retry import backoff_delays
from mgproto_tpu.serving import metrics as _m

SHED_QUEUE_FULL = "queue_full"
SHED_DEADLINE = "deadline"

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

_STATE_GAUGE = {BREAKER_CLOSED: 0.0, BREAKER_HALF_OPEN: 0.5, BREAKER_OPEN: 1.0}


@dataclasses.dataclass
class ServeRequest:
    """One unit of admission: an opaque payload plus its latency contract.
    `deadline` is an absolute clock() time (None = no deadline)."""

    payload: Any
    request_id: str
    deadline: Optional[float] = None
    enqueued_at: float = 0.0

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


class AdmissionQueue:
    """Bounded FIFO with deadline-aware shedding (see module docstring)."""

    def __init__(
        self,
        capacity: int = 64,
        default_deadline_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.default_deadline_s = default_deadline_s
        self.clock = clock
        self._q: Deque[ServeRequest] = deque()
        self._ids = itertools.count()
        self.shed: List[ServeRequest] = []  # drained by the engine

    def __len__(self) -> int:
        return len(self._q)

    def _shed(self, req: ServeRequest, reason: str) -> None:
        _m.counter(_m.SHED).inc(reason=reason)
        self.shed.append(req)

    def submit(
        self,
        payload: Any,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ) -> Tuple[Optional[ServeRequest], Optional[str]]:
        """Admit a request; returns (request, None) on admission or
        (request, shed_reason) when it was shed instead. The shed request is
        ALSO recorded in `self.shed` so the engine answers it typed."""
        now = self.clock()
        rel = deadline_s if deadline_s is not None else self.default_deadline_s
        req = ServeRequest(
            payload=payload,
            request_id=request_id or f"r{next(self._ids)}",
            deadline=None if rel is None else now + rel,
            enqueued_at=now,
        )
        if req.expired(now):  # born dead (deadline storm): never queue it
            self._shed(req, SHED_DEADLINE)
            return req, SHED_DEADLINE
        if len(self._q) >= self.capacity:
            # shed already-expired entries first (oldest first, anywhere in
            # the queue — an expired entry behind a viable head is just as
            # unserveable); they free room without breaking anyone's
            # still-live latency contract
            keep: Deque[ServeRequest] = deque()
            for queued in self._q:
                if queued.expired(now):
                    self._shed(queued, SHED_DEADLINE)
                else:
                    keep.append(queued)
            self._q = keep
            if len(self._q) >= self.capacity:
                self._shed(req, SHED_QUEUE_FULL)
                return req, SHED_QUEUE_FULL
        self._q.append(req)
        return req, None

    def peek_oldest(self) -> Optional[ServeRequest]:
        """The request that has waited longest (None when empty). The
        micro-batcher's deadline-slack trigger reads its latency contract."""
        return self._q[0] if self._q else None

    def drain_all(self) -> List[ServeRequest]:
        """Remove and return EVERYTHING queued, unanswered and unaccounted —
        for transfer, not for shedding: the replica supervisor reroutes a
        dead replica's queue to survivors, and a blue/green swap moves the
        old engine's queue into the new one. The caller owns answering every
        drained request (typed) or restoring it somewhere."""
        out = list(self._q)
        self._q.clear()
        return out

    def restore(self, req: ServeRequest) -> bool:
        """Re-admit a transferred request PRESERVING its identity, deadline
        and original `enqueued_at` (latency accounting stays honest across a
        reroute/swap). Returns False at capacity — the caller must answer
        the request typed itself (it knows whether this is a reroute or a
        swap, and therefore the honest shed reason)."""
        if len(self._q) >= self.capacity:
            return False
        self._q.append(req)
        return True

    def pop_batch(self, max_size: int) -> List[ServeRequest]:
        """Up to `max_size` still-viable requests, FIFO; entries whose
        deadline passed while queued are shed here, not served late."""
        now = self.clock()
        out: List[ServeRequest] = []
        while self._q and len(out) < max_size:
            req = self._q.popleft()
            if req.expired(now):
                self._shed(req, SHED_DEADLINE)
                continue
            out.append(req)
        return out

    def drain_shed(self) -> List[ServeRequest]:
        """Hand the accumulated shed requests to the caller (clears them)."""
        out, self.shed = self.shed, []
        return out


class CircuitBreaker:
    """Consecutive-failure breaker with retry-policy-paced recovery."""

    def __init__(
        self,
        failure_threshold: int = 3,
        base_delay: float = 0.5,
        max_delay: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.failure_threshold = int(failure_threshold)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.clock = clock
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self._open_until = 0.0
        self._reopen_count = 0
        self._state_since = clock()
        self._open_seconds_total = 0.0
        _m.gauge(_m.BREAKER_STATE).set(_STATE_GAUGE[self.state])

    def _transition(self, new_state: str) -> None:
        if new_state == self.state:
            return
        now = self.clock()
        if self.state == BREAKER_OPEN:
            self._open_seconds_total += max(now - self._state_since, 0.0)
        self._state_since = now
        _m.counter(_m.BREAKER_TRANSITIONS).inc(
            edge=f"{self.state}->{new_state}"
        )
        from mgproto_tpu.obs.flightrec import record_event

        record_event(
            "breaker_transition", edge=f"{self.state}->{new_state}",
            consecutive_failures=self.consecutive_failures,
        )
        self.state = new_state
        _m.gauge(_m.BREAKER_STATE).set(_STATE_GAUGE[new_state])

    def open_seconds(self, now: Optional[float] = None) -> float:
        """Cumulative seconds spent OPEN (the outage time a fleet dashboard
        divides by uptime for the breaker open-time fraction). Includes the
        in-progress open period when the breaker is open right now."""
        total = self._open_seconds_total
        if self.state == BREAKER_OPEN:
            total += max((self.clock() if now is None else now)
                         - self._state_since, 0.0)
        return total

    def _cooldown(self) -> float:
        """The k-th open period's length: the retry module's backoff
        schedule, jitter-free (deterministic recovery pacing)."""
        delays = list(
            backoff_delays(
                self._reopen_count + 1,
                base_delay=self.base_delay,
                max_delay=self.max_delay,
                jitter=0.0,
            )
        )
        return delays[-1]

    def allow(self) -> bool:
        """May a batch be dispatched now? An elapsed cooldown moves the
        breaker to half-open and admits ONE probe batch."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_OPEN and self.clock() >= self._open_until:
            self._transition(BREAKER_HALF_OPEN)
            return True
        return self.state == BREAKER_HALF_OPEN

    def tick(self) -> None:
        """Advance the lazy OPEN -> HALF_OPEN transition without asking to
        dispatch. Readiness-gated routing starves an OPEN replica of
        traffic, so with an empty queue nothing ever calls `allow()` and
        the open state would outlive its cooldown forever; the supervisor
        ticks instead, letting readiness report half-open and the next
        routed batch serve as the probe."""
        if self.state == BREAKER_OPEN and self.clock() >= self._open_until:
            self._transition(BREAKER_HALF_OPEN)

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state != BREAKER_CLOSED:
            self._transition(BREAKER_CLOSED)
            self._reopen_count = 0

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == BREAKER_HALF_OPEN:
            # failed probe: back to open, next-longer cooldown
            self._reopen_count += 1
            self._open_until = self.clock() + self._cooldown()
            self._transition(BREAKER_OPEN)
        elif (
            self.state == BREAKER_CLOSED
            and self.consecutive_failures >= self.failure_threshold
        ):
            self._open_until = self.clock() + self._cooldown()
            self._transition(BREAKER_OPEN)
