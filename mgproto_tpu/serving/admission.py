"""Admission control: bounded queue, deadline shedding, circuit breaker.

Under overload a serving process has exactly three honest moves: queue the
request (bounded — an unbounded queue converts overload into latency for
EVERYONE), shed it with a typed response, or stop accepting work while the
backend is failing. All three live here, host-side and jax-free.

  * `AdmissionQueue` — FIFO with a hard capacity and per-request deadlines.
    Shedding is deadline-aware: a full queue first sheds entries that are
    ALREADY past their deadline (oldest first — they can no longer be
    answered in time, so they are the cheapest work to drop), and only
    rejects the newcomer when everything queued is still viable. Batch
    draining re-checks deadlines at pop time: a request that expired while
    queued is shed, not served late.

  * `CircuitBreaker` — closed -> open after `failure_threshold` consecutive
    device failures; the open cooldown follows `resilience.retry`'s
    exponential backoff schedule (the SAME policy module training IO uses,
    so recovery pacing cannot drift between subsystems); after the cooldown
    a half-open probe admits one batch — success closes the breaker and
    resets the schedule, failure re-opens it at the next longer delay.

Multi-tenant admission (ISSUE 17): a request may carry a `tenant` id, and
`submit` may carry that tenant's `quota` (its fair share of the queue,
computed by the TenantDirectory). A tenant at quota sheds ITS OWN tail —
deadline-aware within its share: its already-expired queued entries go
first, and only then the newcomer, typed `tenant_quota`. Another tenant's
entries are never touched, so one tenant's storm cannot evict anyone
else's queued work. `pop_batch` becomes fair-share only when the queue
actually holds more than one tenant lane: batch slots round-robin across
lanes (FIFO within each lane), so a storm tenant cannot monopolize batch
composition either. With zero or one lane the pop path is byte-for-byte
the original FIFO — the disabled tenant plane costs one set-membership
check.

Clocks are injectable (`clock=`) so chaos tests drive deadline storms and
breaker recovery deterministically, without sleeping.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from mgproto_tpu.resilience.retry import backoff_delays
from mgproto_tpu.serving import metrics as _m

SHED_QUEUE_FULL = "queue_full"
SHED_DEADLINE = "deadline"
SHED_TENANT_QUOTA = "tenant_quota"

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

_STATE_GAUGE = {BREAKER_CLOSED: 0.0, BREAKER_HALF_OPEN: 0.5, BREAKER_OPEN: 1.0}


@dataclasses.dataclass
class ServeRequest:
    """One unit of admission: an opaque payload plus its latency contract.
    `deadline` is an absolute clock() time (None = no deadline)."""

    payload: Any
    request_id: str
    deadline: Optional[float] = None
    enqueued_at: float = 0.0
    # multi-tenant serving (ISSUE 17): the tenant lane this request belongs
    # to. None (the default, and the whole single-tenant path) means "no
    # lane" — admission, popping and accounting behave exactly as before.
    tenant: Optional[str] = None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


class AdmissionQueue:
    """Bounded FIFO with deadline-aware shedding (see module docstring)."""

    def __init__(
        self,
        capacity: int = 64,
        default_deadline_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.default_deadline_s = default_deadline_s
        self.clock = clock
        self._q: Deque[ServeRequest] = deque()
        self._ids = itertools.count()
        self.shed: List[ServeRequest] = []  # drained by the engine

    def __len__(self) -> int:
        return len(self._q)

    def _shed(self, req: ServeRequest, reason: str) -> None:
        _m.counter(_m.SHED).inc(reason=reason)
        if req.tenant is not None:
            _m.counter(_m.TENANT_SHED).inc(tenant=req.tenant, reason=reason)
        self.shed.append(req)

    def tenant_depths(self) -> Dict[str, int]:
        """Queued entries per tenant lane (requests with no tenant are not
        listed) — the batcher's per-tenant depth gauge reads this."""
        out: Dict[str, int] = {}
        for req in self._q:
            if req.tenant is not None:
                out[req.tenant] = out.get(req.tenant, 0) + 1
        return out

    def submit(
        self,
        payload: Any,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        quota: Optional[int] = None,
    ) -> Tuple[Optional[ServeRequest], Optional[str]]:
        """Admit a request; returns (request, None) on admission or
        (request, shed_reason) when it was shed instead. The shed request is
        ALSO recorded in `self.shed` so the engine answers it typed.

        `quota` (with `tenant`) is the tenant's fair share of this queue:
        at quota the tenant sheds its own tail — its expired queued entries
        first, then the newcomer (`tenant_quota`) — never anyone else's."""
        now = self.clock()
        rel = deadline_s if deadline_s is not None else self.default_deadline_s
        req = ServeRequest(
            payload=payload,
            request_id=request_id or f"r{next(self._ids)}",
            deadline=None if rel is None else now + rel,
            enqueued_at=now,
            tenant=tenant,
        )
        if req.expired(now):  # born dead (deadline storm): never queue it
            self._shed(req, SHED_DEADLINE)
            return req, SHED_DEADLINE
        if tenant is not None and quota is not None:
            held = sum(1 for r in self._q if r.tenant == tenant)
            if held >= quota:
                # deadline-aware within the tenant's OWN share: its
                # already-expired entries free room first (they cannot be
                # answered in time anyway); other tenants' entries are
                # never candidates
                keep: Deque[ServeRequest] = deque()
                for queued in self._q:
                    if queued.tenant == tenant and queued.expired(now):
                        self._shed(queued, SHED_DEADLINE)
                        held -= 1
                    else:
                        keep.append(queued)
                self._q = keep
                if held >= quota:
                    self._shed(req, SHED_TENANT_QUOTA)
                    return req, SHED_TENANT_QUOTA
        if len(self._q) >= self.capacity:
            # shed already-expired entries first (oldest first, anywhere in
            # the queue — an expired entry behind a viable head is just as
            # unserveable); they free room without breaking anyone's
            # still-live latency contract
            keep: Deque[ServeRequest] = deque()
            for queued in self._q:
                if queued.expired(now):
                    self._shed(queued, SHED_DEADLINE)
                else:
                    keep.append(queued)
            self._q = keep
            if len(self._q) >= self.capacity:
                self._shed(req, SHED_QUEUE_FULL)
                return req, SHED_QUEUE_FULL
        self._q.append(req)
        return req, None

    def peek_oldest(self) -> Optional[ServeRequest]:
        """The request that has waited longest (None when empty). The
        micro-batcher's deadline-slack trigger reads its latency contract."""
        return self._q[0] if self._q else None

    def drain_all(self) -> List[ServeRequest]:
        """Remove and return EVERYTHING queued, unanswered and unaccounted —
        for transfer, not for shedding: the replica supervisor reroutes a
        dead replica's queue to survivors, and a blue/green swap moves the
        old engine's queue into the new one. The caller owns answering every
        drained request (typed) or restoring it somewhere."""
        out = list(self._q)
        self._q.clear()
        return out

    def restore(self, req: ServeRequest) -> bool:
        """Re-admit a transferred request PRESERVING its identity, deadline
        and original `enqueued_at` (latency accounting stays honest across a
        reroute/swap). Returns False at capacity — the caller must answer
        the request typed itself (it knows whether this is a reroute or a
        swap, and therefore the honest shed reason)."""
        if len(self._q) >= self.capacity:
            return False
        self._q.append(req)
        return True

    def pop_batch(self, max_size: int) -> List[ServeRequest]:
        """Up to `max_size` still-viable requests, FIFO; entries whose
        deadline passed while queued are shed here, not served late.

        When the queue holds more than one tenant lane, batch slots are
        filled round-robin across lanes (FIFO within each lane) so a storm
        tenant's backlog cannot monopolize batch composition; with zero or
        one lane this is exactly the original FIFO pop."""
        now = self.clock()
        if len({r.tenant for r in self._q}) > 1:
            return self._pop_batch_fair(max_size, now)
        out: List[ServeRequest] = []
        while self._q and len(out) < max_size:
            req = self._q.popleft()
            if req.expired(now):
                self._shed(req, SHED_DEADLINE)
                continue
            out.append(req)
        return out

    def _pop_batch_fair(self, max_size: int, now: float) -> List[ServeRequest]:
        """Round-robin pop across tenant lanes, lanes ordered by their
        oldest entry's arrival (so the longest-waiting lane leads each
        round); expired entries shed at pop exactly like the FIFO path."""
        lanes: Dict[Any, Deque[ServeRequest]] = {}
        order: List[Any] = []
        for req in self._q:
            if req.tenant not in lanes:
                lanes[req.tenant] = deque()
                order.append(req.tenant)
            lanes[req.tenant].append(req)
        out: List[ServeRequest] = []
        removed: List[ServeRequest] = []
        progressed = True
        while len(out) < max_size and progressed:
            progressed = False
            for t in order:
                if len(out) >= max_size:
                    break
                lane = lanes[t]
                while lane:
                    req = lane.popleft()
                    removed.append(req)
                    if req.expired(now):
                        self._shed(req, SHED_DEADLINE)
                        continue
                    out.append(req)
                    progressed = True
                    break
        gone = {id(r) for r in removed}
        self._q = deque(r for r in self._q if id(r) not in gone)
        return out

    def drain_shed(self) -> List[ServeRequest]:
        """Hand the accumulated shed requests to the caller (clears them)."""
        out, self.shed = self.shed, []
        return out


class CircuitBreaker:
    """Consecutive-failure breaker with retry-policy-paced recovery."""

    def __init__(
        self,
        failure_threshold: int = 3,
        base_delay: float = 0.5,
        max_delay: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.failure_threshold = int(failure_threshold)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.clock = clock
        self.state = BREAKER_CLOSED
        self.consecutive_failures = 0
        self._open_until = 0.0
        self._reopen_count = 0
        self._state_since = clock()
        self._open_seconds_total = 0.0
        _m.gauge(_m.BREAKER_STATE).set(_STATE_GAUGE[self.state])

    def _transition(self, new_state: str) -> None:
        if new_state == self.state:
            return
        now = self.clock()
        if self.state == BREAKER_OPEN:
            self._open_seconds_total += max(now - self._state_since, 0.0)
        self._state_since = now
        _m.counter(_m.BREAKER_TRANSITIONS).inc(
            edge=f"{self.state}->{new_state}"
        )
        from mgproto_tpu.obs.flightrec import record_event

        record_event(
            "breaker_transition", edge=f"{self.state}->{new_state}",
            consecutive_failures=self.consecutive_failures,
        )
        self.state = new_state
        _m.gauge(_m.BREAKER_STATE).set(_STATE_GAUGE[new_state])

    def open_seconds(self, now: Optional[float] = None) -> float:
        """Cumulative seconds spent OPEN (the outage time a fleet dashboard
        divides by uptime for the breaker open-time fraction). Includes the
        in-progress open period when the breaker is open right now."""
        total = self._open_seconds_total
        if self.state == BREAKER_OPEN:
            total += max((self.clock() if now is None else now)
                         - self._state_since, 0.0)
        return total

    def _cooldown(self) -> float:
        """The k-th open period's length: the retry module's backoff
        schedule, jitter-free (deterministic recovery pacing)."""
        delays = list(
            backoff_delays(
                self._reopen_count + 1,
                base_delay=self.base_delay,
                max_delay=self.max_delay,
                jitter=0.0,
            )
        )
        return delays[-1]

    def allow(self) -> bool:
        """May a batch be dispatched now? An elapsed cooldown moves the
        breaker to half-open and admits ONE probe batch."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_OPEN and self.clock() >= self._open_until:
            self._transition(BREAKER_HALF_OPEN)
            return True
        return self.state == BREAKER_HALF_OPEN

    def tick(self) -> None:
        """Advance the lazy OPEN -> HALF_OPEN transition without asking to
        dispatch. Readiness-gated routing starves an OPEN replica of
        traffic, so with an empty queue nothing ever calls `allow()` and
        the open state would outlive its cooldown forever; the supervisor
        ticks instead, letting readiness report half-open and the next
        routed batch serve as the probe."""
        if self.state == BREAKER_OPEN and self.clock() >= self._open_until:
            self._transition(BREAKER_HALF_OPEN)

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state != BREAKER_CLOSED:
            self._transition(BREAKER_CLOSED)
            self._reopen_count = 0

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == BREAKER_HALF_OPEN:
            # failed probe: back to open, next-longer cooldown
            self._reopen_count += 1
            self._open_until = self.clock() + self._cooldown()
            self._transition(BREAKER_OPEN)
        elif (
            self.state == BREAKER_CLOSED
            and self.consecutive_failures >= self.failure_threshold
        ):
            self._open_until = self.clock() + self._cooldown()
            self._transition(BREAKER_OPEN)
