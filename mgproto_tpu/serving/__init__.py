"""Trustworthy serving subsystem (ISSUE 3).

The inference boundary of the MGProto system: calibrated OoD abstention,
per-request validation, bucketed static-shape dispatch, admission control
with deadline shedding, a circuit breaker over device failures, and a
degraded mode that keeps classification up when trust gating cannot run.

Modules (import layering: everything except `engine` is importable without
jax — calibration files must be readable on a bare operator host):

  metrics     — serving counter/gauge/histogram names (jax-free).
  validate    — payload -> typed reject or clean float32 array (jax-free).
  calibration — ID-score calibration artifact + GMM fingerprint (numpy).
  gate        — TrustGate: in_dist / abstain / ungated decisions (numpy).
  admission   — AdmissionQueue + CircuitBreaker (jax-free).
  health      — liveness/readiness probes over an engine (jax-free).
  response    — the typed ServeResponse shape + its one metrics account
                (jax-free; shared by the engine and the network plane).
  engine      — ServingEngine (imports jax; loaded lazily through
                `__getattr__` so the package import stays jax-free).

The network serving plane (ISSUE 7) sits on top — all jax-free themselves
(engines arrive via factories):

  batcher     — continuous micro-batching with a latency-deadline cutoff.
  replica     — ReplicaSet: heartbeat supervision, reroute, backoff restart.
  swap        — blue/green hot swap, fail-closed on trust verification.
  frontend    — stdlib asyncio HTTP frontend + graceful drain.

See README "Serving & trust gating" + "Serving plane" for the operator
story.
"""

from mgproto_tpu.serving import metrics
from mgproto_tpu.serving.admission import (
    AdmissionQueue,
    CircuitBreaker,
    ServeRequest,
)
from mgproto_tpu.serving.calibration import (
    Calibration,
    CalibrationError,
    calibrate,
    gmm_fingerprint,
)
from mgproto_tpu.serving.batcher import BatcherConfig, MicroBatcher
from mgproto_tpu.serving.gate import TrustGate
from mgproto_tpu.serving.health import HealthProbe
from mgproto_tpu.serving.replica import Replica, ReplicaSet
from mgproto_tpu.serving.response import ServeResponse
from mgproto_tpu.serving.swap import (
    SwapReport,
    flip_fleet,
    hot_swap,
    stage_fleet,
)
from mgproto_tpu.serving.validate import (
    ValidationFailure,
    ValidationSpec,
    validate_batch,
    validate_image,
)

# engine imports jax, frontend imports asyncio machinery the batch drivers
# never need: both stay lazy so the package import is light
_LAZY = {
    "ServingEngine": "engine",
    "UncalibratedArtifactError": "engine",
    "Frontend": "frontend",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(
            f"mgproto_tpu.serving.{_LAZY[name]}"
        )
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "metrics",
    "AdmissionQueue",
    "CircuitBreaker",
    "ServeRequest",
    "Calibration",
    "CalibrationError",
    "calibrate",
    "gmm_fingerprint",
    "TrustGate",
    "HealthProbe",
    "ValidationFailure",
    "ValidationSpec",
    "validate_batch",
    "validate_image",
    "ServingEngine",
    "ServeResponse",
    "UncalibratedArtifactError",
    "BatcherConfig",
    "MicroBatcher",
    "Replica",
    "ReplicaSet",
    "SwapReport",
    "flip_fleet",
    "hot_swap",
    "stage_fleet",
    "Frontend",
]
