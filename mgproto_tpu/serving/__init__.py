"""Trustworthy serving subsystem (ISSUE 3).

The inference boundary of the MGProto system: calibrated OoD abstention,
per-request validation, bucketed static-shape dispatch, admission control
with deadline shedding, a circuit breaker over device failures, and a
degraded mode that keeps classification up when trust gating cannot run.

Modules (import layering: everything except `engine` is importable without
jax — calibration files must be readable on a bare operator host):

  metrics     — serving counter/gauge/histogram names (jax-free).
  validate    — payload -> typed reject or clean float32 array (jax-free).
  calibration — ID-score calibration artifact + GMM fingerprint (numpy).
  gate        — TrustGate: in_dist / abstain / ungated decisions (numpy).
  admission   — AdmissionQueue + CircuitBreaker (jax-free).
  health      — liveness/readiness probes over an engine (jax-free).
  engine      — ServingEngine (imports jax; loaded lazily through
                `__getattr__` so the package import stays jax-free).

See README "Serving & trust gating" for the operator-facing story.
"""

from mgproto_tpu.serving import metrics
from mgproto_tpu.serving.admission import (
    AdmissionQueue,
    CircuitBreaker,
    ServeRequest,
)
from mgproto_tpu.serving.calibration import (
    Calibration,
    CalibrationError,
    calibrate,
    gmm_fingerprint,
)
from mgproto_tpu.serving.gate import TrustGate
from mgproto_tpu.serving.health import HealthProbe
from mgproto_tpu.serving.validate import (
    ValidationFailure,
    ValidationSpec,
    validate_batch,
    validate_image,
)

_LAZY = ("ServingEngine", "ServeResponse", "UncalibratedArtifactError")


def __getattr__(name):
    if name in _LAZY:  # engine imports jax; keep the package import light
        from mgproto_tpu.serving import engine

        return getattr(engine, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "metrics",
    "AdmissionQueue",
    "CircuitBreaker",
    "ServeRequest",
    "Calibration",
    "CalibrationError",
    "calibrate",
    "gmm_fingerprint",
    "TrustGate",
    "HealthProbe",
    "ValidationFailure",
    "ValidationSpec",
    "validate_batch",
    "validate_image",
    "ServingEngine",
    "ServeResponse",
    "UncalibratedArtifactError",
]
