"""ServingEngine: bucketed, trust-gated inference over a frozen model.

The production serving loop the ROADMAP's "heavy traffic" north star needs,
applied to the inference boundary:

  * STATIC SHAPES ONLY. XLA recompiles per input shape ("Memory Safe
    Computations with XLA Compiler", PAPERS.md), so naive per-request
    shapes stall the fleet. The engine serves a fixed set of batch-size
    BUCKETS: requests are padded to the smallest fitting bucket, every
    bucket is compiled at warmup, and steady state performs ZERO further
    compiles — asserted in tier-1 via the telemetry StepMonitor's
    recompile detector watching the engine's jit handle.
  * TYPED RESPONSES, NEVER EXCEPTIONS. Payloads are validated host-side
    (serving/validate.py) into typed rejects; device failures are caught
    and answered as rejects while feeding the circuit breaker; overload is
    shed by the admission queue. `process_pending` cannot raise from a
    request's content.
  * TRUST GATING. Every served prediction carries log p(x) and a trust
    label from the calibrated gate (serving/gate.py); without a valid
    calibration the engine serves in DEGRADED mode — classification only,
    flagged per response — rather than inventing thresholds.

Two sources of truth for the model:

  * `from_live(trainer, state)` — a live TrainState; serves through the
    same jitted eval step training evaluates with.
  * `from_artifact(path)` — an exported `.mgproto` zip (engine/export.py):
    the StableHLO program plus its embedded calibration. Refuses an
    uncalibrated artifact unless `allow_uncalibrated=True` (which serves
    degraded), because a trust-gating engine without trust data is exactly
    the silent failure this subsystem exists to prevent.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mgproto_tpu.obs import reqtrace as _reqtrace
from mgproto_tpu.online import capture as _capture
from mgproto_tpu.resilience import chaos as _chaos
from mgproto_tpu.serving import metrics as _m
from mgproto_tpu.serving.admission import (
    AdmissionQueue,
    CircuitBreaker,
    ServeRequest,
)
from mgproto_tpu.serving.calibration import Calibration
from mgproto_tpu.serving.gate import (
    TRUST_ABSTAIN,
    TRUST_UNGATED,
    TrustGate,
)
from mgproto_tpu.serving.response import (
    OUTCOME_ABSTAIN,
    OUTCOME_PREDICT,
    OUTCOME_REJECT,
    OUTCOME_SHED,
    REASON_CIRCUIT_OPEN,
    REASON_DEVICE_ERROR,
    REASON_SHUTDOWN,
    ServeResponse,
    record as _record_response,
)
from mgproto_tpu.serving.tenants import REASON_TENANT_UNMOUNTED
from mgproto_tpu.serving.validate import (
    ValidationFailure,
    ValidationSpec,
    validate_image,
)
from mgproto_tpu.telemetry.monitor import StepMonitor

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8)


class UncalibratedArtifactError(RuntimeError):
    """Artifact has no embedded calibration and --allow-uncalibrated is off."""


class _ExplainContext:
    """Static prototype table behind the opt-in `explain` response field
    (ISSUE 15): per flat prototype index its class / within-class k /
    mixture prior, plus nearest-training-patch provenance when the
    push/export metadata carries it. Host-side numpy only; built once at
    engine construction, O(top_e) dict work per PREDICT response when
    enabled, and `engine._explain is None` is the ONE check the disabled
    path pays (the reqtrace discipline)."""

    def __init__(self, table: Dict[str, Any]):
        self.k_per_class = int(table["k_per_class"])
        self.priors = np.asarray(table["priors"], np.float64).ravel()
        prov = table.get("provenance") or None
        self._prov = None
        if prov is not None:
            self._prov = {
                "image_id": np.asarray(prov["image_id"], np.int64).ravel(),
                "spatial_idx": np.asarray(
                    prov["spatial_idx"], np.int64
                ).ravel(),
                "log_prob": np.asarray(prov["log_prob"], np.float64).ravel(),
            }

    def rows(
        self, proto_idx: np.ndarray, proto_logd: np.ndarray
    ) -> List[Dict[str, Any]]:
        """One response's explanation: the top activated prototypes, most
        activated first (the program already sorted them)."""
        out: List[Dict[str, Any]] = []
        for p, logd in zip(proto_idx, proto_logd):
            p = int(p)
            row: Dict[str, Any] = {
                "prototype": p,
                "class": p // self.k_per_class,
                "k": p % self.k_per_class,
                "prior": float(self.priors[p]),
                "log_density": float(logd),
            }
            if self._prov is not None and self._prov["image_id"][p] >= 0:
                row["source_patch"] = {
                    "image_id": int(self._prov["image_id"][p]),
                    "spatial_idx": int(self._prov["spatial_idx"][p]),
                    "log_prob": float(self._prov["log_prob"][p]),
                }
            out.append(row)
        return out


class ServingEngine:
    def __init__(
        self,
        infer_fn: Callable,
        img_size: int,
        num_classes: int,
        calibration: Optional[Calibration] = None,
        expected_fingerprint: Optional[str] = None,
        expected_compute_dtype: Optional[str] = None,
        expected_quant: Optional[str] = None,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        percentile: Optional[float] = None,
        queue_capacity: int = 64,
        default_deadline_s: Optional[float] = None,
        breaker: Optional[CircuitBreaker] = None,
        clock: Callable[[], float] = time.monotonic,
        monitor: Optional[StepMonitor] = None,
        aot_cache: Optional[Any] = None,
        aot_fingerprint: Optional[str] = None,
        explain_table: Optional[Dict[str, Any]] = None,
        tenants: Optional[Any] = None,
    ):
        """`infer_fn` maps float32 images [b, H, W, 3] to
        {"logits": [b, C], "log_px": [b]} and is jit-wrapped here so the
        recompile detector can watch its cache.

        `tenants` (serving/tenants.py TenantDirectory) turns on the
        multi-tenant plane: requests carrying a tenant id gate through
        that tenant's head, pay its fair-share quota, and feed its drift/
        capture state. None (the default) is the single-tenant engine,
        byte-identical to the pre-tenant build."""
        import jax

        if not buckets:
            raise ValueError("need at least one batch-size bucket")
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if self.buckets[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {self.buckets}")
        self.img_size = int(img_size)
        self.num_classes = int(num_classes)
        self.spec = ValidationSpec(img_size=self.img_size)
        self.clock = clock
        self._jit = jax.jit(infer_fn)
        self.gate = TrustGate(
            calibration,
            expected_fingerprint=expected_fingerprint,
            percentile=percentile,
            expected_compute_dtype=expected_compute_dtype,
            expected_quant=expected_quant,
        )
        self.queue = AdmissionQueue(
            capacity=queue_capacity,
            default_deadline_s=default_deadline_s,
            clock=clock,
        )
        # the default breaker must share the engine's (possibly virtual)
        # clock: cooldowns and open-seconds accounting on a different clock
        # would make chaos drills nondeterministic and the open-fraction
        # gauge meaningless
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            clock=clock
        )
        self.monitor = monitor if monitor is not None else StepMonitor(
            phase="serve"
        )
        self.monitor.watch(self._jit)
        # AOT executable cache (serving/aotcache.py): warmup consults it
        # FIRST and a hit deserializes the bucket's compiled program with
        # zero XLA compiles (mmap-and-go cold start). The key's program
        # half defaults to the gmm fingerprint; callers with a stronger
        # program identity (the artifact face hashes the .mgproto file)
        # pass `aot_fingerprint` explicitly.
        self.aot_cache = aot_cache
        self.aot_fingerprint = str(
            aot_fingerprint
            if aot_fingerprint is not None
            else (expected_fingerprint or "")
        )
        # opt-in explanations (ISSUE 15): when a prototype table rides
        # along, `infer_fn` is the EXPLAIN program (superset outputs:
        # proto_idx/proto_logd beside logits/log_px) and predict outcomes
        # carry an `explain` block. Disabled engines serve the plain
        # program untouched — the None-check below is the only cost.
        self._explain = (
            _ExplainContext(explain_table)
            if explain_table is not None else None
        )
        if self._explain is not None:
            # an explain program's executables must never collide with the
            # plain program's in the AOT cache (different output contract)
            self.aot_fingerprint += ":explain"
        self.compute_dtype = str(expected_compute_dtype or "")
        # quant identity of the served program (perf/quant.py tag, "" =
        # f32): an axis of the AOT cache key, so an int8 program can never
        # deserialize an f32 executable (or vice versa) — wrong-program
        # serves are structurally impossible, only counted misses
        self.quant_config = str(expected_quant or "")
        # multi-tenant plane (ISSUE 17): heads live in the directory, the
        # TRUNK lives here. A head never touches aot_fingerprint, _jit, or
        # _exec, so mounting a tenant can never cost a trunk compile.
        self.tenants = tenants
        # per-bucket compiled executables: populated by warmup (cache hit
        # or AOT compile); dispatch uses these, so the jit dispatch cache
        # stays empty in steady state and the recompile detector's zero
        # means literally zero compiles anywhere
        self._exec: Dict[int, Any] = {}
        # per-bucket warmup provenance: [{bucket, source, seconds}, ...]
        # (source: "cache" = deserialized hit, "compile" = AOT compile)
        self.warmup_report: List[Dict[str, Any]] = []
        self.warmed_up = False
        # readiness veto during a graceful drain or a blue/green flip: the
        # engine still ANSWERS (drains) but must not be routed new traffic
        self.draining = False
        self._request_seq = 0  # chaos injection index over admitted order
        self._dispatch_seq = 0  # chaos injection index over device dispatches

    # ------------------------------------------------------------ constructors
    @classmethod
    def from_live(
        cls, trainer, state, calibration: Optional[Calibration] = None,
        explain: bool = False, explain_top: int = 5,
        provenance: Optional[Dict[str, Any]] = None, **kw
    ) -> "ServingEngine":
        """Serve a live TrainState through the trainer's eval math. The
        expected fingerprint comes from the state's ACTUAL mixture, so a
        calibration measured before a prune/EM/push is refused (fail-closed
        into degraded mode) rather than silently misgating.

        `explain=True` serves the explain program instead (same logits/
        log_px math plus the top-`explain_top` activated prototypes per
        request); `provenance` is an optional push-metadata dict
        (engine/push.py::provenance_dict) for nearest-training-patch
        attribution."""
        from mgproto_tpu.serving.calibration import gmm_fingerprint

        if explain:
            from mgproto_tpu.engine.export import (
                explain_table as _explain_table,
                make_explain_fn,
            )

            kw["explain_table"] = _explain_table(
                state, provenance=provenance
            )
            infer = make_explain_fn(trainer, state, top_e=explain_top)
        else:
            def infer(images):
                out = trainer._eval(state, images, None)
                return {"logits": out.logits, "log_px": out.log_px}

        return cls(
            infer,
            img_size=trainer.cfg.model.img_size,
            num_classes=trainer.cfg.model.num_classes,
            calibration=calibration,
            expected_fingerprint=gmm_fingerprint(state.gmm),
            expected_compute_dtype=trainer.cfg.model.compute_dtype,
            # a live TrainState serves unrounded f32 weights by
            # construction — an int8-stamped calibration must fail closed
            expected_quant="",
            **kw,
        )

    @classmethod
    def from_artifact(
        cls, path: str, allow_uncalibrated: bool = False,
        explain: bool = False, **kw
    ) -> "ServingEngine":
        """Serve an exported `.mgproto` artifact (StableHLO + calibration).

        A static-batch artifact constrains the buckets to its pinned batch
        size; a dynamic-batch artifact serves every configured bucket (each
        bucket still compiles exactly once, at warmup).

        `explain=True` serves the artifact's embedded EXPLAIN program
        (`mgproto-export --explain` stages it beside the plain one) — the
        artifact then serves prototype explanations with push provenance
        and NO training run anywhere in sight. Refused loudly when the
        artifact predates --explain."""
        from mgproto_tpu.engine.export import (
            load_calibration,
            load_explain,
            load_exported,
        )

        exported, meta = load_exported(path)
        calibration = load_calibration(path)
        if explain:
            explain_exported, table = load_explain(path)
            if explain_exported is None:
                raise ValueError(
                    f"{path} carries no explain program; re-export with "
                    "mgproto-export --explain to serve explanations from "
                    "this artifact"
                )
            exported = explain_exported
            kw["explain_table"] = table
        if calibration is None and not allow_uncalibrated:
            raise UncalibratedArtifactError(
                f"{path} carries no calibration.json; re-export with "
                "--calibrate, or pass --allow-uncalibrated to serve "
                "classification WITHOUT OoD abstention (degraded mode)"
            )
        if not meta.get("dynamic_batch", True):
            # a static-batch program serves exactly one shape: any caller-
            # supplied bucket list would dispatch-fail on every batch.
            # Pre-`static_batch` metas recover the pin from the program's
            # own input aval instead of crashing at warmup.
            static = meta.get("static_batch") or int(
                exported.in_avals[0].shape[0]
            )
            kw["buckets"] = (int(static),)
        # the dtype the artifact's program actually computes in: the policy
        # block when present (post-ISSUE-12 exports), the bare meta field
        # otherwise — a calibration stamped with a DIFFERENT dtype fails
        # closed in the gate, exactly like a fingerprint mismatch
        policy = meta.get("precision_policy") or {}
        # the quant identity the artifact's program serves under
        # (meta.json quant_config.tag; "" for f32/pre-quant artifacts):
        # an int8 artifact whose calibration carries a different stamp —
        # including the empty pre-quant stamp — fails closed in the gate,
        # and the served program's resident weight bytes land on the
        # serving_quant_weight_bytes gauge for the planner/dashboards
        from mgproto_tpu.engine.export import quant_tag

        expected_quant = quant_tag(meta)
        qmeta = meta.get("quant_config") or {}
        if qmeta.get("total_weight_bytes"):
            _m.gauge(_m.QUANT_WEIGHT_BYTES).set(
                float(qmeta["total_weight_bytes"])
            )
        if kw.get("aot_cache") is not None and "aot_fingerprint" not in kw:
            # the artifact face's program identity is the FILE (weights and
            # program in one hash): a re-export — even with an unchanged
            # gmm fingerprint — misses the cache instead of serving a
            # stale executable. Factories that build many engines hoist
            # this (cli/serve computes it once and passes it explicitly).
            from mgproto_tpu.engine.export import artifact_aot_fingerprint

            kw["aot_fingerprint"] = artifact_aot_fingerprint(path)
        return cls(
            exported.call,
            img_size=int(meta["img_size"]),
            num_classes=int(meta["num_classes"]),
            calibration=calibration,
            expected_fingerprint=meta.get("gmm_fingerprint"),
            expected_compute_dtype=(
                policy.get("compute_dtype") or meta.get("compute_dtype")
            ),
            expected_quant=expected_quant,
            **kw,
        )

    # ----------------------------------------------------------------- warmup
    def _aot_key(self, bucket: int) -> Dict[str, Any]:
        return self.aot_cache.key(
            self.aot_fingerprint,
            (bucket, self.img_size, self.img_size, 3),
            self.compute_dtype,
            quant=self.quant_config,
        )

    def warmup(self) -> int:
        """Ready every bucket shape ahead of traffic; returns the number
        of XLA compiles performed. With an AOT cache (serving/aotcache.py)
        each bucket is CONSULTED FIRST: a hit deserializes the compiled
        executable (zero compiles — the mmap-and-go cold start); a miss or
        an unusable entry falls back to a normal compile, counted, and the
        fresh executable is stored for the next start. After this, any
        recompile the monitor sees in steady state is a bug (the tier-1
        chaos test asserts zero). `scripts/check_aot_warmup.py` lints that
        the cache consult precedes the compile (no silent bypass)."""
        compiled_count = 0
        self.warmup_report = []
        for b in self.buckets:
            zeros = np.zeros(
                (b, self.img_size, self.img_size, 3), np.float32
            )
            t0 = time.perf_counter()
            exe = None
            if self.aot_cache is not None:
                exe = self.aot_cache.load(self._aot_key(b))
                if exe is not None and not self._verify_exec(exe, zeros):
                    # deserialized but cannot run: counted reject, fall
                    # back to compiling — fail-safe, never fail-serve
                    self.aot_cache.reject_loaded()
                    exe = None
                elif exe is not None:
                    # hit = deserialized AND verified (zero compiles)
                    self.aot_cache.note_hit()
            source = "cache"
            if exe is None:
                exe = self._jit.lower(zeros).compile()
                self.monitor.note_compiles(1)
                compiled_count += 1
                source = "compile"
                out = exe(zeros)
                np.asarray(out["log_px"])  # block until executed
                if self.aot_cache is not None:
                    self.aot_cache.store(self._aot_key(b), exe)
            self._exec[b] = exe
            self.warmup_report.append({
                "bucket": int(b),
                "source": source,
                "seconds": time.perf_counter() - t0,
            })
        self.warmed_up = True
        # any dispatch-cache growth (an engine whose infer_fn was already
        # driven through self._jit before warmup) still folds in here
        return compiled_count + self.monitor.check_recompiles()

    @staticmethod
    def _verify_exec(exe, zeros: np.ndarray) -> bool:
        """One blocking verification run of a cache-loaded executable: the
        output contract must hold before it may serve traffic."""
        try:
            out = exe(zeros)
            return np.asarray(out["log_px"]).shape == (zeros.shape[0],)
        except Exception:
            return False

    def warmup_costs(self) -> Dict[str, Any]:
        """XLA cost analysis of the inference program at every bucket —
        the `--profile_warmup` off-TPU degrade (cli/serve.py writes this
        as the capture's cost_analysis.json, same schema family as
        obs/stall.step_costs). AOT-lowers each bucket shape, so it repeats
        warmup's compile work: call only when profiling asked for it."""
        import jax

        programs: Dict[str, Any] = {}
        for b in self.buckets:
            spec = jax.ShapeDtypeStruct(
                (b, self.img_size, self.img_size, 3), np.float32
            )
            ca = self._jit.lower(spec).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            ca = ca or {}
            programs[f"b{b}"] = {
                "flops": float(ca.get("flops") or 0.0),
                "bytes_accessed": float(
                    ca.get("bytes accessed", ca.get("bytes_accessed"))
                    or 0.0
                ),
            }
        return {
            "backend": jax.default_backend(),
            "buckets": [int(b) for b in self.buckets],
            "programs": programs,
            "flops": sum(p["flops"] for p in programs.values()),
            "bytes_accessed": sum(
                p["bytes_accessed"] for p in programs.values()
            ),
        }

    # ------------------------------------------------------------- admission
    def submit(
        self,
        payload: Any,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> List[ServeResponse]:
        """Validate + admit one request. Returns the immediate typed
        responses this submission produced: a validation reject, a shed
        response for THIS request, and/or shed responses for queued
        requests evicted past their deadline to make room. Empty list =
        queued; the response comes from `process_pending`.

        A `tenant` id routes the request through that tenant's mounted
        head: an unmounted tenant is REJECTED typed (never silently served
        through the wrong head), and admission enforces the tenant's
        fair-share quota."""
        t0 = self.clock()
        seq = self._request_seq
        self._request_seq += 1
        chaos = _chaos.get_active()
        if chaos is not None:
            payload = chaos.serve_corrupt_request(seq, payload)
            if chaos.serve_storm_due(seq):
                deadline_s = -1.0  # arrives already past its deadline
        if deadline_s is not None and deadline_s <= 0:
            # born dead: shedding is cheaper than validating, so a deadline
            # storm never spends host CPU on payloads nobody can wait for
            _m.counter(_m.SHED).inc(reason="deadline")
            if tenant is not None:
                _m.counter(_m.TENANT_SHED).inc(
                    tenant=tenant, reason="deadline"
                )
            return [
                self._respond(
                    ServeResponse(
                        request_id=request_id or f"v{seq}",
                        outcome=OUTCOME_SHED,
                        reason="deadline",
                        degraded=self.gate.degraded,
                        latency_s=0.0,
                        tenant=tenant,
                    )
                )
            ]
        quota = None
        if tenant is not None:
            quota = (
                self.tenants.quota_for(tenant, self.queue.capacity)
                if self.tenants is not None else None
            )
            if quota is None:
                # no directory, or the directory has no such head: typed
                # reject — traffic for an unmounted tenant must never be
                # gated through another tenant's (or the global) head
                return [
                    self._respond(
                        ServeResponse(
                            request_id=request_id or f"v{seq}",
                            outcome=OUTCOME_REJECT,
                            reason=REASON_TENANT_UNMOUNTED,
                            degraded=self.gate.degraded,
                            latency_s=self.clock() - t0,
                            tenant=tenant,
                        )
                    )
                ]
        try:
            clean = validate_image(payload, self.spec)
        except ValidationFailure as e:
            return [
                self._respond(
                    ServeResponse(
                        request_id=request_id or f"v{seq}",
                        outcome=OUTCOME_REJECT,
                        reason=e.reason,
                        degraded=self.gate.degraded,
                        latency_s=self.clock() - t0,
                        tenant=tenant,
                    )
                )
            ]
        req, shed_reason = self.queue.submit(
            clean, request_id=request_id, deadline_s=deadline_s,
            tenant=tenant, quota=quota,
        )
        if shed_reason is None and _reqtrace.enabled():
            # request tracing (obs/reqtrace.py): stamp admission. Mints
            # here too when no frontend/supervisor minted earlier (the
            # single-engine batch face), so every traced face gets spans.
            _reqtrace.on_enqueue(req.request_id, req.enqueued_at)
        out = []
        for shed in self.queue.drain_shed():
            reason = shed_reason if shed is req else "deadline"
            out.append(self._respond(self._shed_response(shed, reason)))
        return out

    def _shed_response(self, req: ServeRequest, reason: str) -> ServeResponse:
        return ServeResponse(
            request_id=req.request_id,
            outcome=OUTCOME_SHED,
            reason=reason,
            degraded=self.gate.degraded,
            latency_s=self.clock() - req.enqueued_at,
            tenant=req.tenant,
        )

    # ------------------------------------------------------------- processing
    def process_pending(self) -> List[ServeResponse]:
        """Serve one bucket's worth of queued requests (plus any typed
        responses for requests shed while queued). Never raises from
        request content or device failure."""
        responses: List[ServeResponse] = []
        t_pop = self.clock()  # dispatch-window fallback when no batcher set
        # a context (direct process_pending callers: serve_all, tests)
        batch = self.queue.pop_batch(self.buckets[-1])
        # requests shed at pop time (expired while queued) answer typed
        for req in self.queue.drain_shed():
            responses.append(
                self._respond(self._shed_response(req, "deadline"))
            )
        if not batch:
            return responses
        if not self.breaker.allow():
            # typed unavailability beats silent queue growth: the caller
            # sees REJECT/circuit_open and can retry against a replica
            for req in batch:
                responses.append(
                    self._respond(
                        ServeResponse(
                            request_id=req.request_id,
                            outcome=OUTCOME_REJECT,
                            reason=REASON_CIRCUIT_OPEN,
                            degraded=self.gate.degraded,
                            latency_s=self.clock() - req.enqueued_at,
                        )
                    )
                )
            return responses
        try:
            logits, log_px, extras = self._dispatch(
                np.stack([r.payload for r in batch])
            )
        except Exception:
            self.breaker.record_failure()
            _m.counter(_m.DEVICE_ERRORS).inc()
            for req in batch:
                responses.append(
                    self._respond(
                        ServeResponse(
                            request_id=req.request_id,
                            outcome=OUTCOME_REJECT,
                            reason=REASON_DEVICE_ERROR,
                            degraded=self.gate.degraded,
                            latency_s=self.clock() - req.enqueued_at,
                        )
                    )
                )
            return responses
        self.breaker.record_success()
        if _reqtrace.enabled():
            bucket = self._bucket_for(len(batch))
            _reqtrace.on_dispatch(
                [r.request_id for r in batch],
                bucket=bucket,
                fill=len(batch) / bucket,
                fallback_t0=t_pop,
            )
        responses.extend(
            self._gated_responses(batch, logits, log_px, extras)
        )
        return responses

    def drain(self, reason: str = REASON_SHUTDOWN) -> List[ServeResponse]:
        """Answer EVERYTHING still queued with a typed shed (plus any
        already-shed stragglers) — the no-silent-drops half of graceful
        shutdown and of replica teardown. Does not dispatch: a draining
        engine may be draining precisely because dispatching stopped being
        possible."""
        self.draining = True
        responses = []
        for req in self.queue.drain_all():
            _m.counter(_m.SHED).inc(reason=reason)
            if req.tenant is not None:
                _m.counter(_m.TENANT_SHED).inc(
                    tenant=req.tenant, reason=reason
                )
            responses.append(self._respond(self._shed_response(req, reason)))
        for req in self.queue.drain_shed():
            responses.append(
                self._respond(self._shed_response(req, "deadline"))
            )
        return responses

    def serve_all(self, payloads: Sequence[Any],
                  deadline_s: Optional[float] = None,
                  request_ids: Optional[Sequence[str]] = None,
                  should_stop: Optional[Callable[[], bool]] = None,
                  on_pump: Optional[Callable[[], None]] = None
                  ) -> List[ServeResponse]:
        """Batch driver (CLI / tests): submit everything, drain to
        completion, return responses in submission order. `should_stop`
        (e.g. the preemption handler's flag) turns the exit graceful:
        queued work is shed typed via `drain()` and never-submitted
        payloads answer typed too — every id gets exactly one response
        either way. `on_pump` runs between pump iterations — the hook the
        online consolidation cadence (cli/serve.py --online) ticks on,
        keeping background work off the dispatch path itself."""
        from mgproto_tpu.serving.response import shed_response

        ids = [
            request_ids[i] if request_ids is not None else f"req{i}"
            for i in range(len(payloads))
        ]
        order = {rid: i for i, rid in enumerate(ids)}
        responses: List[ServeResponse] = []
        unsubmitted: List[str] = []
        for i, payload in enumerate(payloads):
            if should_stop is not None and should_stop():
                unsubmitted = ids[i:]
                break
            responses.extend(
                self.submit(payload, request_id=ids[i], deadline_s=deadline_s)
            )
            if on_pump is not None:
                on_pump()
        # every pop either answers or sheds-with-answer, so this terminates
        # with zero requests left unanswered
        while len(self.queue):
            if should_stop is not None and should_stop():
                responses.extend(self.drain())
                break
            responses.extend(self.process_pending())
            if on_pump is not None:
                on_pump()
        responses.extend(
            shed_response(rid, REASON_SHUTDOWN) for rid in unsubmitted
        )
        return sorted(
            responses, key=lambda r: order.get(r.request_id, len(order))
        )

    # -------------------------------------------------------------- internals
    def _dispatch(
        self, images: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, Optional[Tuple]]:
        """Pad to bucket, run the compiled program, slice the padding off.
        Raises on (real or chaos-injected) device failure. The third
        element is None unless explanations are enabled (then the explain
        program's (proto_idx, proto_logd) rows ride along)."""
        from mgproto_tpu.telemetry.tracing import trace_span

        n = images.shape[0]
        bucket = self._bucket_for(n)
        seq = self._dispatch_seq
        self._dispatch_seq += 1
        chaos = _chaos.get_active()
        padded = images
        if bucket != n:
            padded = np.zeros(
                (bucket, self.img_size, self.img_size, 3), np.float32
            )
            padded[:n] = images
        _m.gauge(_m.BATCH_FILL).set(n / bucket)
        _m.histogram(_m.BATCH_FILL_HIST).observe(n / bucket)
        t0 = time.perf_counter()
        with trace_span("serve_dispatch", bucket=bucket, fill=n):
            if chaos is not None and chaos.serve_device_error_due(seq):
                raise _chaos.ChaosError(
                    f"chaos: simulated device failure at dispatch {seq}"
                )
            # the warmed per-bucket executable (cache hit or AOT compile);
            # an un-warmed bucket falls back to the jit dispatch path,
            # where the monitor counts the resulting compile — a silent
            # cache/warmup bypass is exactly what the detector flags
            exe = self._exec.get(bucket)
            out = exe(padded) if exe is not None else self._jit(padded)
            logits = np.asarray(out["logits"], np.float64)[:n]
            log_px = np.asarray(out["log_px"], np.float64)[:n]
            extras = None
            if self._explain is not None:
                extras = (
                    np.asarray(out["proto_idx"], np.int64)[:n],
                    np.asarray(out["proto_logd"], np.float64)[:n],
                )
        self.monitor.observe_step(n, time.perf_counter() - t0,
                                  transfer_bytes=int(padded.nbytes))
        return logits, log_px, extras

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _gated_responses(
        self, batch: List[ServeRequest], logits: np.ndarray,
        log_px: np.ndarray, extras: Optional[Tuple] = None,
    ) -> List[ServeResponse]:
        preds = np.argmax(logits, axis=-1)
        # per-request gate selection (ISSUE 17): a request carrying a
        # tenant id gates through that tenant's mounted head; everything
        # else — and every engine without a directory — uses the engine
        # gate exactly as before (the batch-level decide below is the
        # single-tenant fast path, untouched when tenants is None)
        gates = [self.gate] * len(batch)
        if self.tenants is not None:
            for i, req in enumerate(batch):
                if req.tenant is not None:
                    g = self.tenants.gate_for(req.tenant)
                    if g is not None:
                        gates[i] = g
        per_row = any(g is not self.gate for g in gates)
        if per_row:
            labels = []
            degraded_rows = []
            for g, score in zip(gates, log_px):
                try:
                    labels.append(g.decide([float(score)])[0])
                    degraded_rows.append(g.degraded)
                except Exception:
                    labels.append(TRUST_UNGATED)
                    degraded_rows.append(True)
        else:
            try:
                labels = self.gate.decide(log_px)
                degraded_rows = [self.gate.degraded] * len(batch)
            except Exception:
                # the gate itself erring must not take serving down:
                # degrade THIS batch to ungated classification, flagged
                # per response
                labels = [TRUST_UNGATED] * len(batch)
                degraded_rows = [True] * len(batch)
        # continual-learning tap (online/capture.py): disabled is ONE
        # module-global None-check per batch — the reqtrace discipline
        tap = _capture.get_active()
        out = []
        for i, (req, pred, row, score, label) in enumerate(zip(
            batch, preds, logits, log_px, labels
        )):
            outcome = (
                OUTCOME_ABSTAIN if label == TRUST_ABSTAIN else OUTCOME_PREDICT
            )
            explain_rows = None
            if self._explain is not None and outcome == OUTCOME_PREDICT:
                # populated ONLY on predict outcomes: an abstained request
                # has no served decision to explain
                explain_rows = self._explain.rows(
                    extras[0][i], extras[1][i]
                )
                _m.counter(_m.EXPLANATIONS).inc()
            gate = gates[i]
            resp = ServeResponse(
                request_id=req.request_id,
                outcome=outcome,
                prediction=int(pred),
                log_px=float(score),
                trust=label,
                trust_score=gate.trust_score(float(score)),
                confidence=gate.confidence(row),
                degraded=degraded_rows[i] or label == TRUST_UNGATED,
                latency_s=self.clock() - req.enqueued_at,
                explain=explain_rows,
                tenant=req.tenant,
            )
            resp = self._respond(resp)
            if tap is not None:
                # post-record(): stage trusted high-p(x) predictions for
                # background consolidation. O(1) reservoir append; never
                # raises (capture's own contract).
                tap.on_response(req.payload, resp)
            if self.tenants is not None:
                # the tenant tap (drift window + per-tenant capture) —
                # one None-check when the plane is off
                self.tenants.on_response(req.payload, resp)
            out.append(resp)
        return out

    def _respond(self, resp: ServeResponse) -> ServeResponse:
        return _record_response(resp)
