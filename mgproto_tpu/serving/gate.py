"""TrustGate: calibrated OoD abstention over served log p(x) scores.

The gate turns a raw generative score into a trust decision:

  * `in_dist`  — log p(x) strictly above the calibrated ID-percentile
                 threshold (the same `score > thresh` comparison
                 `evaluate_with_ood` uses, so serve-time decisions and the
                 eval driver agree even ON the boundary).
  * `abstain`  — at or below threshold: the model still reports its argmax (a
                 downstream fallback may want it) but flags the input as
                 out-of-distribution at the calibrated operating point.
  * `ungated`  — degraded mode: no valid calibration, so classification is
                 served WITHOUT an OoD decision, explicitly flagged.

Fail-closed fingerprint discipline (ISSUE 3 satellite): a calibration is
only honored when its `gmm_fingerprint` matches the mixture actually being
served. `prune_top_m` (or any EM/push) shifts the absolute p(x) scale —
core/mgproto.py:334-338 — so a stale calibration silently misgates; on
mismatch the gate drops to degraded mode and counts
`serving_fingerprint_mismatch_total`, rather than gating with wrong
thresholds.

Fail-closed PRECISION discipline (ISSUE 12): a calibration additionally
carries the compute dtype its ID scores were measured under
(perf/precision.py). Serving the same weights under a different trunk
dtype (bf16 vs f32) shifts the p(x) distribution the thresholds slice, so
a dtype mismatch is treated exactly like a fingerprint mismatch — degraded
mode plus `serving_precision_mismatch_total`. Calibrations with no dtype
stamp (pre-policy artifacts) are honored unchanged.

Fail-closed QUANT discipline (ISSUE 20): an int8 weight-only artifact
(perf/quant.py) serves weights rounded to a per-channel grid, which moves
the p(x) distribution just like a dtype change. The calibration carries the
quant tag its ID scores were measured under (`quant_config`, "" = f32);
when the served program's tag disagrees — including an int8 program paired
with an UNSTAMPED pre-quant calibration — the gate degrades and counts
`serving_quant_mismatch_total`. Unlike the dtype rule, an empty stamp does
NOT grandfather into a quantized program: "" is the f32 identity, so
"" vs "int8:..." is a real mismatch, while "" vs "" (f32 artifact,
pre-quant calibration) is honored unchanged.

The trailing abstain rate is exported as the `serving_abstain_rate` gauge —
the first dashboard signal that live traffic has drifted away from the
calibration set.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional, Sequence

import numpy as np

from mgproto_tpu.serving import metrics as _m
from mgproto_tpu.serving.calibration import Calibration

TRUST_IN_DIST = "in_dist"
TRUST_ABSTAIN = "abstain"
TRUST_UNGATED = "ungated"


class TrustGate:
    """Per-sample trust decisions from a Calibration (or None = degraded).

    `expected_fingerprint` is the GMM actually being served (live state's
    fingerprint, or the artifact's stamped one); when it disagrees with the
    calibration's, the gate installs itself in degraded mode.
    """

    def __init__(
        self,
        calibration: Optional[Calibration],
        expected_fingerprint: Optional[str] = None,
        percentile: Optional[float] = None,
        window: int = 256,
        expected_compute_dtype: Optional[str] = None,
        expected_quant: Optional[str] = None,
    ):
        self.fingerprint_mismatch = False
        self.precision_mismatch = False
        self.quant_mismatch = False
        if (
            calibration is not None
            and expected_fingerprint is not None
            and calibration.gmm_fingerprint != expected_fingerprint
        ):
            _m.counter(_m.FINGERPRINT_MISMATCHES).inc()
            self.fingerprint_mismatch = True
            calibration = None  # fail closed: degrade, don't misgate
        if (
            calibration is not None
            and expected_compute_dtype
            and calibration.compute_dtype
            and calibration.compute_dtype != expected_compute_dtype
        ):
            # precision-policy discipline (perf/precision.py): thresholds
            # measured under one compute dtype do not transfer to another —
            # the p(x) distribution shifts with the trunk's rounding. Same
            # fail-closed contract as a fingerprint mismatch. A calibration
            # with no dtype stamp ("" — pre-policy artifact) is honored.
            _m.counter(_m.PRECISION_MISMATCHES).inc()
            self.precision_mismatch = True
            calibration = None
        if (
            calibration is not None
            and expected_quant is not None
            and (calibration.quant_config or "") != (expected_quant or "")
        ):
            # quant discipline (perf/quant.py): strict equality, both
            # directions. expected_quant=None means "caller makes no quant
            # claim" (pre-ISSUE-20 construction sites) and checks nothing;
            # expected_quant="" is an explicit f32 claim that refuses an
            # int8-stamped calibration, and an int8 claim refuses both f32
            # stamps and the empty pre-quant stamp — thresholds measured
            # on unrounded weights do not transfer to the rounded grid.
            _m.counter(_m.QUANT_MISMATCHES).inc()
            self.quant_mismatch = True
            calibration = None
        self.calibration = calibration
        self.threshold: Optional[float] = None
        if calibration is not None:
            self.threshold = (
                calibration.threshold_log_px
                if percentile is None
                else calibration.threshold_for(percentile)
            )
        self._window: Deque[bool] = deque(maxlen=max(int(window), 1))

    @property
    def degraded(self) -> bool:
        """True when decisions are ungated (no/invalid calibration)."""
        return self.calibration is None

    # -------------------------------------------------------------- decisions
    def decide(self, log_px: Sequence[float]) -> List[str]:
        """Trust label per sample; updates the trailing abstain-rate gauge."""
        scores = np.asarray(log_px, np.float64).ravel()
        if self.calibration is None:
            return [TRUST_UNGATED] * scores.size
        labels = []
        for s in scores:
            # a non-finite score coming back from the device is by
            # definition not in-distribution — abstain, never compare NaN.
            # <=, not <: evaluate_with_ood flags in-distribution on
            # `score > thresh`, and the threshold is an ID percentile that
            # frequently EQUALS a real sample's score — the boundary must
            # decide the same way on both sides of the export seam
            abstain = (not np.isfinite(s)) or (s <= self.threshold)
            labels.append(TRUST_ABSTAIN if abstain else TRUST_IN_DIST)
            self._window.append(abstain)
        if self._window:
            _m.gauge(_m.ABSTAIN_RATE).set(
                sum(self._window) / len(self._window)
            )
        return labels

    def trust_score(self, log_px: float) -> Optional[float]:
        """Calibrated ID-quantile of a score (None in degraded mode)."""
        if self.calibration is None or not np.isfinite(log_px):
            return None
        return self.calibration.id_quantile_of(float(log_px))

    def confidence(self, logits_row: Sequence[float]) -> Optional[float]:
        """Calibrated class confidence: softmax over the per-class
        temperature-scaled log-likelihoods (the dispersion equalizer the
        calibration measured on held-out ID data), max over classes.
        None in degraded mode — an uncalibrated softmax would look like a
        probability without being one."""
        if self.calibration is None:
            return None
        try:
            z = np.asarray(logits_row, np.float64) / np.asarray(
                self.calibration.per_class_temperature, np.float64
            )
            # -inf is a legitimate "impossible class" (padded class-bucket
            # slots carry zero priors): exp(-inf)=0 drops out of the
            # softmax. NaN or +inf still means no confidence beats a wrong
            # one — as does an all-impossible row.
            if np.isnan(z).any() or np.isposinf(z).any():
                return None
            m = z.max()
            if not np.isfinite(m):
                return None
            p = np.exp(z - m)
            return float(p.max() / p.sum())
        except (ValueError, TypeError):
            # e.g. a calibration whose class count disagrees with the
            # served head: no confidence beats a wrong one
            return None

    @property
    def abstain_rate(self) -> Optional[float]:
        if not self._window:
            return None
        return sum(self._window) / len(self._window)
