"""Serving metric names + registration (jax-free).

Every serving-path event — request outcomes, abstentions, load shedding,
breaker transitions, calibration-fingerprint mismatches, degraded-mode
requests — lands in the telemetry registry as a labeled counter/gauge, so
`mgproto-telemetry summarize` renders the serving story next to throughput
and training health (companion to `resilience/metrics.py`).

Counters resolve through `default_registry()` on first use (they follow
whatever registry the live TelemetrySession installed), and
`register_serving_metrics` pre-registers the whole family so a clean run
reports explicit zeros instead of absent series.
"""

from __future__ import annotations

from mgproto_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    default_registry,
)

REQUESTS = "serving_requests_total"
REQUEST_SECONDS = "serving_request_seconds"
ABSTAIN_RATE = "serving_abstain_rate"
SHED = "serving_shed_total"
BREAKER_STATE = "serving_breaker_state"
BREAKER_TRANSITIONS = "serving_breaker_transitions_total"
FINGERPRINT_MISMATCHES = "serving_fingerprint_mismatch_total"
DEGRADED_REQUESTS = "serving_degraded_requests_total"
DEVICE_ERRORS = "serving_device_errors_total"
BATCH_FILL = "serving_batch_fill_ratio"

COUNTER_HELP = {
    REQUESTS: "requests by outcome (predict/abstain/reject/shed)",
    SHED: "requests shed by admission control (queue_full/deadline)",
    BREAKER_TRANSITIONS: "circuit breaker state transitions, by edge",
    FINGERPRINT_MISMATCHES:
        "calibrations rejected because the served GMM does not match the "
        "fingerprint the thresholds were derived from",
    DEGRADED_REQUESTS: "requests answered WITHOUT OoD gating (degraded mode)",
    DEVICE_ERRORS: "inference dispatches that raised a device error",
}

GAUGE_HELP = {
    ABSTAIN_RATE: "abstain fraction over the trailing decision window",
    BREAKER_STATE: "circuit breaker state (0=closed, 0.5=half-open, 1=open)",
    BATCH_FILL: "occupied fraction of the last padded serving batch",
}

HIST_HELP = {
    REQUEST_SECONDS: "per-request latency (admission to response), by outcome",
}

ALL_COUNTERS = tuple(COUNTER_HELP)
ALL_GAUGES = tuple(GAUGE_HELP)


def counter(name: str) -> Counter:
    """The named serving counter in the process-current registry."""
    return default_registry().counter(name, COUNTER_HELP.get(name, ""))


def gauge(name: str) -> Gauge:
    """The named serving gauge in the process-current registry."""
    return default_registry().gauge(name, GAUGE_HELP.get(name, ""))


def histogram(name: str) -> Histogram:
    """The named serving histogram in the process-current registry."""
    return default_registry().histogram(name, HIST_HELP.get(name, ""))


def register_serving_metrics(registry) -> None:
    """Pre-create the serving metric family with explicit zero-valued
    unlabeled series, so snapshots (and summarize) always carry the serving
    story, even when it is "nothing happened"."""
    for name in ALL_COUNTERS:
        registry.counter(name, COUNTER_HELP[name]).inc(0.0)
    for name in ALL_GAUGES:
        registry.gauge(name, GAUGE_HELP[name]).set(0.0)
    for name in HIST_HELP:
        registry.histogram(name, HIST_HELP[name])
