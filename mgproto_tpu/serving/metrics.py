"""Serving metric names + registration (jax-free).

Every serving-path event — request outcomes, abstentions, load shedding,
breaker transitions, calibration-fingerprint mismatches, degraded-mode
requests — lands in the telemetry registry as a labeled counter/gauge, so
`mgproto-telemetry summarize` renders the serving story next to throughput
and training health (companion to `resilience/metrics.py`).

Counters resolve through `default_registry()` on first use (they follow
whatever registry the live TelemetrySession installed), and
`register_serving_metrics` pre-registers the whole family so a clean run
reports explicit zeros instead of absent series.
"""

from __future__ import annotations

from mgproto_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    default_registry,
)

REQUESTS = "serving_requests_total"
REQUEST_SECONDS = "serving_request_seconds"
ABSTAIN_RATE = "serving_abstain_rate"
SHED = "serving_shed_total"
BREAKER_STATE = "serving_breaker_state"
BREAKER_TRANSITIONS = "serving_breaker_transitions_total"
FINGERPRINT_MISMATCHES = "serving_fingerprint_mismatch_total"
PRECISION_MISMATCHES = "serving_precision_mismatch_total"
# --- int8 weight-only serving (ISSUE 20) ---
QUANT_MISMATCHES = "serving_quant_mismatch_total"
QUANT_WEIGHT_BYTES = "serving_quant_weight_bytes"
DEGRADED_REQUESTS = "serving_degraded_requests_total"
DEVICE_ERRORS = "serving_device_errors_total"
BATCH_FILL = "serving_batch_fill_ratio"
# --- network serving plane (ISSUE 7) ---
BATCH_FILL_HIST = "serving_batch_fill_fraction"
DISPATCHES = "serving_batch_dispatch_total"
QUEUE_DEPTH = "serving_queue_depth"
REPLICA_RESTARTS = "serving_replica_restarts_total"
REPLICAS_READY = "serving_replicas_ready"
REPLICAS_TOTAL = "serving_replicas_total"
BREAKER_OPEN_FRACTION = "serving_breaker_open_fraction"
UPTIME_SECONDS = "serving_uptime_seconds"
SWAPS = "serving_swap_total"
SWAP_TRANSFERRED = "serving_swap_transferred_total"
# --- performance observatory (ISSUE 8): per-stage request latency ---
STAGE_SECONDS = "serving_stage_seconds"
# --- elastic serving (ISSUE 13): AOT executable cache + autoscaler ---
AOT_HITS = "serving_aot_hit_total"
AOT_MISSES = "serving_aot_miss_total"
AOT_REJECTS = "serving_aot_reject_total"
AOT_STORES = "serving_aot_store_total"
AUTOSCALE_TARGET = "autoscale_replicas_target"
AUTOSCALE_EVENTS = "autoscale_events_total"
# --- trust plane (ISSUE 15): explanations as a served product ---
EXPLANATIONS = "serving_explanations_total"
# --- multi-tenant serving (ISSUE 17): one fleet, many heads ---
# Per-tenant series are LABELED (tenant=<id>); the unlabeled zero is the
# pre-registration the registry lint demands. The per-tenant latency
# histogram is a SEPARATE family from REQUEST_SECONDS on purpose:
# summarize merges every label series of one histogram name, so tenant-
# labeled observations folded into the global family would double-count.
TENANT_REQUESTS = "tenant_requests_total"
TENANT_REQUEST_SECONDS = "tenant_request_seconds"
TENANT_SHED = "tenant_shed_total"
TENANT_MOUNTS = "tenant_mount_total"
TENANT_UNMOUNTS = "tenant_unmount_total"
TENANT_SWAPS = "tenant_swap_total"
TENANTS_MOUNTED = "tenants_mounted"
TENANT_QUEUE_DEPTH = "tenant_queue_depth"
TENANT_HEAD_BYTES = "tenant_head_bytes"
TENANT_MOUNT_SECONDS = "tenant_mount_seconds"

COUNTER_HELP = {
    REQUESTS: "requests by outcome (predict/abstain/reject/shed)",
    SHED: "requests shed by admission control (queue_full/deadline)",
    BREAKER_TRANSITIONS: "circuit breaker state transitions, by edge",
    FINGERPRINT_MISMATCHES:
        "calibrations rejected because the served GMM does not match the "
        "fingerprint the thresholds were derived from",
    PRECISION_MISMATCHES:
        "calibrations rejected because the served compute dtype does not "
        "match the precision policy the thresholds were measured under "
        "(perf/precision.py; a dtype change moves the p(x) scale)",
    QUANT_MISMATCHES:
        "calibrations rejected because the served quant config (meta.json "
        "quant_config.tag) does not match the one the thresholds were "
        "measured under (perf/quant.py; int8 weight rounding moves the "
        "p(x) scale the same way a dtype change does)",
    DEGRADED_REQUESTS: "requests answered WITHOUT OoD gating (degraded mode)",
    DEVICE_ERRORS: "inference dispatches that raised a device error",
    DISPATCHES:
        "micro-batch dispatches by trigger "
        "(bucket_full/deadline/linger/drain)",
    REPLICA_RESTARTS:
        "replica drain+restart cycles by detected failure (dead/wedged)",
    SWAPS: "blue/green hot-swap attempts by result (committed/rejected)",
    SWAP_TRANSFERRED:
        "queued requests transferred old->new engine during a hot swap "
        "(the zero-dropped-requests guarantee, made countable)",
    AOT_HITS:
        "bucket warmups served from the AOT executable cache "
        "(deserialize instead of compile — zero XLA compiles)",
    AOT_MISSES:
        "bucket warmups whose cache key was absent (normal compile, "
        "lazily stored for the next start)",
    AOT_REJECTS:
        "cache entries refused as unusable, by reason (key_mismatch/"
        "corrupt/deserialize/execute); every reject falls back to a "
        "normal compile — never a wrong-program serve",
    AOT_STORES:
        "executable serialization attempts by result (ok/unsupported/"
        "error)",
    AUTOSCALE_EVENTS:
        "autoscaler scale decisions applied, by direction (up/down)",
    EXPLANATIONS:
        "predict outcomes answered WITH a prototype explanation block "
        "(ServingEngine explain=True; abstain/reject/shed never explain)",
    TENANT_REQUESTS:
        "requests by tenant and outcome (labeled tenant=, outcome=; the "
        "per-tenant view of serving_requests_total)",
    TENANT_SHED:
        "requests shed by tenant and reason (labeled tenant=, reason=; "
        "tenant_quota = the tenant's own tail under fair-share admission)",
    TENANT_MOUNTS:
        "tenant heads mounted into the directory (labeled tenant=)",
    TENANT_UNMOUNTS:
        "tenant heads unmounted from the directory (labeled tenant=)",
    TENANT_SWAPS:
        "tenant-scoped head swap attempts by result (labeled tenant=, "
        "result=committed/rejected; a rejection is that tenant's TrustGate "
        "failing closed — other tenants keep serving)",
}

GAUGE_HELP = {
    ABSTAIN_RATE: "abstain fraction over the trailing decision window",
    BREAKER_STATE: "circuit breaker state (0=closed, 0.5=half-open, 1=open)",
    BATCH_FILL: "occupied fraction of the last padded serving batch",
    QUEUE_DEPTH: "admission queue depth (per replica, and unlabeled total)",
    REPLICAS_READY: "replicas currently passing the readiness probe",
    REPLICAS_TOTAL: "replicas the supervisor is responsible for",
    BREAKER_OPEN_FRACTION:
        "fraction of replica-seconds spent with the breaker OPEN",
    UPTIME_SECONDS: "seconds since the replica supervisor started",
    AUTOSCALE_TARGET:
        "replica count the autoscaler is currently steering toward "
        "(within its [min, max] bounds)",
    QUANT_WEIGHT_BYTES:
        "resident backbone weight bytes of the served program under its "
        "quant config (int8 tensors + scale vectors + untouched f32 "
        "leaves; 0 = unquantized or unknown — the per-replica HBM "
        "numerator perf/planner.py budgets with)",
    TENANTS_MOUNTED: "tenant heads currently mounted in the directory",
    TENANT_QUEUE_DEPTH:
        "admission-queue entries currently held per tenant (labeled "
        "tenant=; refreshed by the micro-batcher's depth observation)",
    TENANT_HEAD_BYTES:
        "resident bytes of a tenant's mounted head — calibration sketch, "
        "per-class temperatures, gate state (labeled tenant=; the "
        "marginal-cost-per-tenant numerator against the shared trunk)",
}

# batch fill is a fraction in (0, 1]; the default time buckets would dump
# every observation into one bin
FILL_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)

HIST_HELP = {
    REQUEST_SECONDS: "per-request latency (admission to response), by outcome",
    BATCH_FILL_HIST:
        "occupied fraction of each padded serving batch (per dispatch)",
    STAGE_SECONDS:
        "per-request stage latency by stage (queue=admission wait + "
        "batcher linger, device=dispatch time, total=arrival to response); "
        "populated only while request tracing (obs/reqtrace.py) is enabled",
    TENANT_REQUEST_SECONDS:
        "per-request latency by tenant (labeled tenant=, outcome=; "
        "observed only for requests that carry a tenant id)",
    TENANT_MOUNT_SECONDS:
        "wall seconds to mount one tenant head (directory-clock measured; "
        "the marginal-cost-per-tenant denominator — zero trunk compiles "
        "by construction, so this is head-bytes work only)",
}

HIST_BUCKETS = {
    BATCH_FILL_HIST: FILL_BUCKETS,
}

ALL_COUNTERS = tuple(COUNTER_HELP)
ALL_GAUGES = tuple(GAUGE_HELP)


def counter(name: str) -> Counter:
    """The named serving counter in the process-current registry."""
    return default_registry().counter(name, COUNTER_HELP.get(name, ""))


def gauge(name: str) -> Gauge:
    """The named serving gauge in the process-current registry."""
    return default_registry().gauge(name, GAUGE_HELP.get(name, ""))


def histogram(name: str) -> Histogram:
    """The named serving histogram in the process-current registry."""
    kw = {}
    if name in HIST_BUCKETS:
        kw["buckets"] = HIST_BUCKETS[name]
    return default_registry().histogram(name, HIST_HELP.get(name, ""), **kw)


def register_serving_metrics(registry) -> None:
    """Pre-create the serving metric family with explicit zero-valued
    unlabeled series, so snapshots (and summarize) always carry the serving
    story, even when it is "nothing happened"."""
    for name in ALL_COUNTERS:
        registry.counter(name, COUNTER_HELP[name]).inc(0.0)
    for name in ALL_GAUGES:
        registry.gauge(name, GAUGE_HELP[name]).set(0.0)
    for name in HIST_HELP:
        kw = {}
        if name in HIST_BUCKETS:
            kw["buckets"] = HIST_BUCKETS[name]
        registry.histogram(name, HIST_HELP[name], **kw)
