"""MGProto-TPU: a TPU-native (JAX/Flax/Pallas) framework for Mixture-of-Gaussian
prototype image recognition, with the capabilities of cwangrun/MGProto.

Brand-new design, not a port: the reference's mutable-module design
(/root/reference/model.py) becomes pure functions over an explicit functional
train state; per-patch Gaussian scoring runs as a single MXU matmul in log
domain; EM is vmapped over classes; distribution is expressed as GSPMD
shardings over a (data, model) mesh instead of torch DataParallel.

Subpackages:
  ops       — pure math kernels (gaussian density, pooling, RF arithmetic, Pallas)
  models    — Flax backbone zoo (ResNet / VGG / DenseNet) + torch weight converter
  core      — MGProto head, functional memory bank, EM, losses, train state
  engine    — train/eval/push/prune/OoD/interpretability drivers
  parallel  — mesh + sharding specs, multi-chip entry points
  data      — host-side input pipelines and dataset helpers
  utils     — logging, checkpointing, config
"""

__version__ = "0.1.0"
