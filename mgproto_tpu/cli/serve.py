"""Serving driver: trust-gated inference over an artifact or checkpoint.

`mgproto-serve` has two faces over the same `serving` subsystem:

  * BATCH/STDIN (default) — answer --images npy batches and/or --stdin
    JSONL requests, one JSON response line each plus a final summary line.
    `--replicas N` serves the batch through the replica-supervised plane;
    `--swap NEW.mgproto` performs a mid-batch blue/green hot swap drill
    (fail-closed: an unverifiable artifact is refused and the old model
    keeps serving; the report is printed as its own JSON line).
  * NETWORK (`--listen HOST:PORT`) — the asyncio HTTP frontend
    (serving/frontend.py): continuous micro-batching into the warmed
    buckets, `--replicas N` supervised workers, POST /v1/predict,
    /healthz, /readyz, /metrics, and POST /admin/swap for blue/green
    promotion. Stdlib only.

Both faces drain gracefully: SIGTERM/SIGINT (resilience/preemption.py's
`install_handlers`, the one permitted signal-handler site) stops admission
and answers or sheds EVERY queued request with a typed response before the
process exits — no silently dropped requests.

    # exported artifact (calibration embedded by `mgproto-export --calibrate`)
    mgproto-serve --artifact model.mgproto --images batch.npy

    # live checkpoint (same flags as mgproto-eval); calibrates on the fly
    mgproto-serve --checkpoint auto --model_dir runs/r1 --calibrate ...

    # stdin JSONL: {"id": "...", "image": [[[...]]]} per line
    mgproto-serve --artifact model.mgproto --stdin < requests.jsonl

    # network serving plane: 2 replicas behind an HTTP frontend
    mgproto-serve --artifact model.mgproto --listen 0.0.0.0:8000 --replicas 2

An artifact without calibration.json refuses to serve unless
`--allow-uncalibrated`, which drops to DEGRADED mode: classification
without OoD abstention, flagged on every response.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from mgproto_tpu.cli.common import add_train_args, config_from_args
from mgproto_tpu.serving.metrics import register_serving_metrics
from mgproto_tpu.telemetry import make_session
from mgproto_tpu.telemetry.monitor import StepMonitor


def _parse_buckets(raw: str):
    return tuple(int(b) for b in raw.split(",") if b.strip())


def _load_payloads(args):
    """(payloads, ids) from --images npy/npz files and/or --stdin JSONL."""
    payloads, ids = [], []
    for path in args.images:
        arr = np.load(path, allow_pickle=False)
        if isinstance(arr, np.lib.npyio.NpzFile):
            arr = arr[arr.files[0]]
        if arr.ndim == 3:
            arr = arr[None]
        for i, row in enumerate(arr):
            payloads.append(row)
            ids.append(f"{os.path.basename(path)}[{i}]")
    if args.stdin:
        for lineno, line in enumerate(sys.stdin):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                payloads.append(rec["image"])
                ids.append(str(rec.get("id", f"stdin[{lineno}]")))
            except (ValueError, KeyError, TypeError):
                payloads.append(None)  # typed reject, not a crash
                ids.append(f"stdin[{lineno}]")
    return payloads, ids


def _engine_kw(args, monitor: Optional[StepMonitor] = None):
    return dict(
        buckets=_parse_buckets(args.buckets),
        percentile=args.percentile,
        queue_capacity=args.queue_capacity,
        default_deadline_s=(
            args.deadline_ms / 1000.0 if args.deadline_ms > 0 else None
        ),
        monitor=monitor,
    )


def _resolve_aot_cache(args, cfg=None):
    """The ONE ExecutableCache for every engine this process builds (the
    cache is content-addressed, so sharing is safe), or None when
    --aot-cache is off. "auto" resolves the sidecar convention: next to
    the artifact, or under the live face's model_dir."""
    raw = getattr(args, "aot_cache", "") or ""
    if not raw:
        return None
    from mgproto_tpu.serving.aotcache import (
        ExecutableCache,
        default_cache_dir,
    )

    if raw != "auto":
        return ExecutableCache(raw)
    if args.artifact:
        return ExecutableCache(default_cache_dir(args.artifact))
    if cfg is not None and cfg.model_dir:
        return ExecutableCache(os.path.join(cfg.model_dir, "aotcache"))
    raise SystemExit(
        "--aot-cache auto needs --artifact or --model_dir to anchor the "
        "sidecar cache dir; pass an explicit directory instead"
    )


def make_engine_factory(
    args, monitor_factory: Optional[Callable[[], StepMonitor]] = None
) -> Callable:
    """An engine factory for the replica supervisor: each call builds an
    independent engine (own jit cache, queue, breaker) over SHARED heavy
    state — the artifact path, or the restored checkpoint + calibration
    loaded exactly once here."""
    from mgproto_tpu.serving.engine import ServingEngine

    def _kw():
        # read the serve knobs at CALL time, not factory-creation time:
        # --auto_tune shrinks args.buckets after the factory exists, and
        # every engine the factory builds (probe, fleet, restart) must
        # agree on the warmed bucket set
        kw = _engine_kw(args)
        kw.pop("monitor")
        return kw

    def _monitor():
        return monitor_factory() if monitor_factory is not None else None

    if args.artifact:
        path, allow = args.artifact, args.allow_uncalibrated
        cache = _resolve_aot_cache(args)
        aot_fp = None
        if cache is not None:
            # hash the artifact ONCE here, not per engine: every replica
            # (re)start would otherwise re-read the whole file
            from mgproto_tpu.engine.export import artifact_aot_fingerprint

            aot_fp = artifact_aot_fingerprint(path)

        def factory():
            return ServingEngine.from_artifact(
                path, allow_uncalibrated=allow, monitor=_monitor(),
                aot_cache=cache, aot_fingerprint=aot_fp,
                explain=args.explain, **_kw()
            )

        return factory

    import jax

    from mgproto_tpu.engine.train import Trainer
    from mgproto_tpu.utils import latest_checkpoint, restore_checkpoint
    from mgproto_tpu.utils.checkpoint import adopt_checkpoint_train_config

    cfg = config_from_args(args)
    path = (
        latest_checkpoint(cfg.model_dir)
        if args.checkpoint == "auto"
        else args.checkpoint
    )
    if not path:
        raise FileNotFoundError(f"no checkpoint found in {cfg.model_dir}")
    cfg = adopt_checkpoint_train_config(cfg, path, log=print)
    trainer = Trainer(cfg, steps_per_epoch=1)
    state = trainer.init_state(jax.random.PRNGKey(cfg.seed), for_restore=True)
    state = restore_checkpoint(path, state)
    calib = None
    if args.calibrate:
        from mgproto_tpu.serving.calibration import calibrate_from_config

        calib = calibrate_from_config(
            cfg, trainer, state,
            # explicit `is None`: --percentile 0 is a legitimate (gate
            # nothing out) operating point, not a request for the default
            percentile=5.0 if args.percentile is None else args.percentile,
        )
    elif not args.allow_uncalibrated:
        raise SystemExit(
            "live serving without calibration: pass --calibrate (derives "
            "thresholds from --test_dir) or --allow-uncalibrated "
            "(degraded mode, no OoD abstention)"
        )

    cache = _resolve_aot_cache(args, cfg)
    aot_fp = None
    if cache is not None:
        # the live face's program identity must cover the FULL restored
        # state, not just the mixture: pytree_digest hashes every leaf
        # (one pass at startup — the price of never serving a stale
        # executable for a touched-up checkpoint)
        from mgproto_tpu.utils.checkpoint import pytree_digest

        aot_fp = pytree_digest(state)

    provenance = None
    if args.explain:
        # nearest-training-patch table the run's push stage left behind
        # (cli/train.py); absent = explanations without source patches
        from mgproto_tpu.engine.push import load_push_provenance

        provenance = load_push_provenance(cfg.model_dir)

    def factory():
        return ServingEngine.from_live(
            trainer, state, calibration=calib, monitor=_monitor(),
            aot_cache=cache, aot_fingerprint=aot_fp,
            explain=args.explain, explain_top=args.explain_top,
            provenance=provenance, **_kw()
        )

    # the online plane (--online) needs the heavy live context the factory
    # closed over; exposed as an attribute so the return type stays a
    # plain callable for every existing caller
    factory.live_context = (trainer, state, calib)
    return factory


def build_engine(args, monitor: Optional[StepMonitor] = None):
    """One engine from --artifact, or from a checkpoint via the train
    flags (the single-engine batch path and the auto-tune probe)."""
    return make_engine_factory(
        args, monitor_factory=(lambda: monitor) if monitor else None
    )()


# --------------------------------------------------------------- batch faces
def drive_batch_engine(engine, payloads, ids, handler, on_pump=None) -> List:
    """Single-engine batch driver with graceful drain: `serve_all` owns
    the submit/pump/order invariant, the preemption flag turns its exit
    graceful (queued work shed typed, unsubmitted payloads answered too).
    `on_pump` runs between pump iterations (the --online cadence tick)."""
    return engine.serve_all(
        payloads,
        request_ids=ids,
        should_stop=handler.requested if handler is not None else None,
        on_pump=on_pump,
    )


def drive_batch_plane(
    replica_set, payloads, ids, handler,
    swap_at: Optional[int] = None, swap_factory: Optional[Callable] = None,
    require_calibrated: bool = True, on_pump: Optional[Callable] = None,
) -> Tuple[List, List]:
    """Replica-plane batch driver: (responses, swap_reports). The swap
    drill fires before request `swap_at` is submitted — queued requests
    transfer old->new with zero drops, or the swap is refused and the old
    fleet keeps answering. `on_pump` runs after each supervisor poll (the
    --online consolidation cadence tick)."""
    from mgproto_tpu.serving.response import shed_response
    from mgproto_tpu.serving.swap import hot_swap

    order = {rid: i for i, rid in enumerate(ids)}
    responses = []
    reports = []
    unsubmitted: List[str] = []
    for i, (payload, rid) in enumerate(zip(payloads, ids)):
        if handler is not None and handler.requested():
            unsubmitted = list(ids[i:])
            break
        if swap_at is not None and i == swap_at and swap_factory is not None:
            reports.append(hot_swap(
                replica_set, swap_factory,
                require_calibrated=require_calibrated,
            ))
        responses.extend(replica_set.submit(payload, request_id=rid))
        responses.extend(replica_set.poll())
        if on_pump is not None:
            on_pump()
    if handler is not None and handler.requested():
        responses.extend(replica_set.drain())
    else:
        responses.extend(replica_set.flush())
        # a replica killed/wedged by chaos mid-batch may still hold queued
        # requests that heartbeat detection never got to reroute (the batch
        # can finish inside the timeout): answer them typed, never drop
        responses.extend(replica_set.shed_stranded())
    responses.extend(shed_response(rid, "shutdown") for rid in unsubmitted)
    return (
        sorted(responses, key=lambda r: order.get(r.request_id, len(order))),
        reports,
    )


CHAOS_SERVE_ENV_HELP = """\
serving chaos-injection env knobs (fault drills; all off by default):
  MGPROTO_CHAOS_SEED                  seed for the deterministic schedule
  MGPROTO_CHAOS_SERVE_MALFORMED_RATE  fraction of requests made malformed
                                      (wrong shape -> typed reject)
  MGPROTO_CHAOS_SERVE_NAN_RATE        fraction NaN-poisoned (typed reject)
  MGPROTO_CHAOS_SERVE_DEVICE_ERRORS   comma-separated dispatch indices that
                                      raise a simulated device failure
                                      (feeds the circuit breaker)
  MGPROTO_CHAOS_SERVE_STORM_AT        first request index of a deadline
                                      storm (arrives already expired)
  MGPROTO_CHAOS_SERVE_STORM_LEN       number of storm requests
  MGPROTO_CHAOS_SERVE_REPLICA_KILL_AT admitted-request index at which the
                                      target replica dies (supervisor
                                      reroutes + restarts on backoff)
  MGPROTO_CHAOS_SERVE_WEDGE_AT        same, but the replica wedges
                                      (present yet unresponsive)
  MGPROTO_CHAOS_SERVE_SWAP_BAD_ARTIFACT
                                      poison the first N hot-swap attempts
                                      with a trust-stripped artifact (the
                                      swap must fail CLOSED)
  MGPROTO_CHAOS_TENANT_STORM_AT       from this request index the load drill
                                      floods ONE tenant over its fair-share
                                      quota (only its own tail may shed)
  MGPROTO_CHAOS_TENANT_BAD_SWAP       poison the first N tenant-scoped head
                                      swaps with a trust-stripped head (that
                                      tenant fails closed, others serve on)
  MGPROTO_CHAOS_TENANT_POISON_RATE    fraction of the storm tenant's traffic
                                      made OoD junk (its drift monitor must
                                      breach; quiet tenants stay flat)
"""


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(
        description="Serve an MGProto model with calibrated trust gating",
        epilog=CHAOS_SERVE_ENV_HELP,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    add_train_args(p)
    p.add_argument("--artifact", default="",
                   help=".mgproto artifact to serve (else --checkpoint + "
                        "model flags)")
    p.add_argument("--checkpoint", default="auto",
                   help="checkpoint path ('auto' = latest in --model_dir); "
                        "ignored when --artifact is given")
    p.add_argument("--images", action="append", default=[],
                   help="npy/npz of [N,H,W,3] (or [H,W,3]) normalized "
                        "float images (repeatable)")
    p.add_argument("--stdin", action="store_true",
                   help="also read JSONL requests from stdin: "
                        '{"id": ..., "image": nested lists}')
    p.add_argument("--buckets", default="1,2,4,8",
                   help="batch-size buckets compiled at warmup (requests "
                        "are padded up; no recompiles after warmup)")
    p.add_argument("--percentile", type=float, default=None,
                   help="abstention operating point (ID log p(x) "
                        "percentile); default: the calibration's own")
    p.add_argument("--deadline_ms", type=float, default=0.0,
                   help="per-request deadline; expired requests are shed "
                        "typed (0 = none)")
    p.add_argument("--queue_capacity", type=int, default=64,
                   help="admission queue bound (overflow sheds typed)")
    p.add_argument("--allow-uncalibrated", "--allow_uncalibrated",
                   dest="allow_uncalibrated", action="store_true",
                   help="serve WITHOUT calibration in degraded mode "
                        "(classification only, flagged per response)")
    p.add_argument("--calibrate", action="store_true",
                   help="live mode: derive calibration from the --test_dir "
                        "loader before serving")
    p.add_argument("--listen", default="",
                   help="HOST:PORT for the asyncio HTTP frontend (network "
                        "serving plane); empty = batch/stdin mode")
    p.add_argument("--replicas", type=int, default=1,
                   help="supervised serving workers behind the frontend "
                        "(or the batch plane)")
    p.add_argument("--swap", default="",
                   help="batch mode: blue/green hot-swap to this .mgproto "
                        "artifact midway through the batch (fail-closed "
                        "drill; network mode swaps via POST /admin/swap)")
    p.add_argument("--linger_ms", type=float, default=20.0,
                   help="micro-batcher: max wait before a deadline-less "
                        "request dispatches in a partial batch")
    p.add_argument("--heartbeat_timeout_s", type=float, default=2.0,
                   help="replica heartbeat staleness before the supervisor "
                        "drains + restarts it")
    # elastic serving (ISSUE 13): AOT executable cache + autoscaler
    p.add_argument("--aot-cache", "--aot_cache", dest="aot_cache",
                   default="",
                   help="AOT executable cache dir (serving/aotcache.py): "
                        "warmup deserializes cached bucket executables "
                        "instead of compiling (mmap-and-go cold start) and "
                        "lazily stores misses; 'auto' = the sidecar next "
                        "to --artifact (<artifact>.aotcache/) or "
                        "<model_dir>/aotcache. Empty = off")
    p.add_argument("--autoscale", default="",
                   help="MIN:MAX replica bounds for the observatory-driven "
                        "autoscaler (network face): the pump grows the "
                        "fleet on queue-depth/shed-rate/p99 saturation and "
                        "shrinks it after sustained calm with a zero-drop "
                        "drain (serving/autoscale.py). --replicas sets the "
                        "starting size (clamped into the bounds). Empty = "
                        "fixed fleet")
    p.add_argument("--autoscale_interval_s", type=float, default=0.25,
                   help="autoscaler decision cadence (pump-hook polling "
                        "on the plane's clock; never sleeps)")
    # performance observatory (ISSUE 8)
    p.add_argument("--explain", action="store_true",
                   help="serve prototype explanations: predict outcomes "
                        "gain an `explain` block (top activated "
                        "prototypes with class, mixture prior, peak "
                        "log-density, nearest-training-patch provenance). "
                        "Artifact face needs an --explain export; live "
                        "face reads push_provenance.json when present. "
                        "Off = the plain program, zero per-request cost.")
    p.add_argument("--explain_top", type=int, default=5,
                   help="live face: prototypes per explanation (most "
                        "activated first). The artifact face's depth is "
                        "baked into the explain program at export time "
                        "(mgproto-export --explain_top).")
    p.add_argument("--trace_requests", action="store_true",
                   help="end-to-end request tracing: frontend->batcher->"
                        "replica->engine stage spans in the telemetry "
                        "Chrome trace, serving_stage_seconds histograms in "
                        "/metrics, and a per-response 'timings' breakdown "
                        "(obs/reqtrace.py; zero per-request cost when off)")
    p.add_argument("--profile_warmup", default="",
                   help="capture a profiler trace of warmup compilation "
                        "into this dir (off-TPU: cost-analysis-only "
                        "capture — obs/profiler.py)")
    # online learning (ISSUE 11): continual capture + consolidation beside
    # the batch faces. Needs the LIVE checkpoint path (the artifact face
    # has no trainer to consolidate with).
    p.add_argument("--online", action="store_true",
                   help="stage trusted high-p(x) predictions (calibrated "
                        "capture gate) and consolidate them into the "
                        "memory banks via compact EM after the batch "
                        "drains — live-checkpoint faces only "
                        "(online/capture.py, online/consolidate.py)")
    p.add_argument("--online_capture_percentile", type=float, default=25.0,
                   help="calibration percentile a prediction's log p(x) "
                        "must clear to be captured")
    p.add_argument("--online_capture_capacity", type=int, default=64,
                   help="per-class staging reservoir bound")
    p.add_argument("--online_cadence_s", type=float, default=1.0,
                   help="consolidation cadence (poll-driven, injectable "
                        "clock — never sleeps)")
    # NB: add_train_args already contributes --auto_tune; here it sizes the
    # warmup bucket set instead of the train plan (perf/planner.py
    # plan_serve_buckets): over-budget buckets are dropped before warmup
    # compiles them, and the outcome lands in telemetry meta when enabled.
    args = p.parse_args(argv)
    if args.online and args.listen:
        raise SystemExit(
            "--online is wired into the batch faces (and the drift drill: "
            "mgproto-online drill); the network face's pump does not tick "
            "the consolidation cadence yet"
        )

    from mgproto_tpu.resilience import chaos as chaos_mod

    chaos_plan = chaos_mod.plan_from_env()
    if chaos_plan is not None:
        chaos_mod.install(chaos_plan)

    # graceful drain (both faces): first SIGTERM/SIGINT sets the flag, the
    # drivers answer/shed everything typed and exit; a second one kills
    from mgproto_tpu.resilience.preemption import get_handler, install_handlers

    uninstall = install_handlers()
    handler = get_handler()
    handler.reset()

    # unlike mgproto-train there is no default telemetry dir (a serve run
    # has no model_dir of its own): telemetry is on when --telemetry-dir is
    telem = make_session(args.telemetry_dir or "", not args.no_telemetry)
    monitor = None
    if telem:
        register_serving_metrics(telem.registry)
        monitor = StepMonitor(registry=telem.registry, phase="serve")

    # performance observatory: per-run flight recorder (dumps on replica
    # death when a telemetry dir gives it somewhere to write) + opt-in
    # end-to-end request tracing on the plane's production clock
    from mgproto_tpu.obs import reqtrace
    from mgproto_tpu.obs.flightrec import FlightRecorder, set_recorder

    prev_recorder = set_recorder(
        FlightRecorder(dump_dir=args.telemetry_dir or None)
    )
    if args.trace_requests:
        reqtrace.enable(
            tracer=telem.tracer if telem else None, include_timings=True
        )

    try:
        if args.listen:
            _main_listen(args, handler, telem)
        elif args.replicas > 1 or args.swap:
            _main_batch_plane(args, handler, telem)
        else:
            _main_batch_engine(args, handler, telem, monitor)
        if telem:
            telem.flush()
    finally:
        if args.trace_requests:
            reqtrace.disable()
        set_recorder(prev_recorder)
        uninstall()  # leave the embedding process's signal dispositions alone
        if telem:
            telem.close()


def _apply_auto_tune(args, engine, telem) -> None:
    """Shared --auto_tune step: shrink the warmup bucket set to the HBM
    budget (fail closed on an empty fit) before any bucket compiles."""
    from mgproto_tpu.perf.planner import plan_serve_buckets

    fitting, outcome = plan_serve_buckets(engine)
    print(json.dumps({
        "autotune": True,
        "buckets": list(fitting),
        "rejected": outcome.rejected,
        "budget_bytes": outcome.budget_bytes,
    }))
    if telem:
        telem.observe_autotune(outcome)
    if not fitting:
        # fail CLOSED: warming the rejected set would execute the
        # exact OOM the planner just predicted. Rerun without
        # --auto_tune (or raise the budget) to override.
        raise SystemExit(
            "auto_tune: no warmup bucket fits the HBM budget "
            f"({outcome.budget_bytes} bytes, margin "
            f"{outcome.margin}); refusing to warm an over-budget "
            "bucket set"
        )
    if tuple(fitting) != engine.buckets:
        engine.buckets = tuple(fitting)
    args.buckets = ",".join(str(b) for b in fitting)


def _swap_factory(args, path: str) -> Callable:
    """Engine factory for a swap target artifact, sharing the serve knobs
    (buckets/deadline/queue) with the running fleet. With --aot-cache the
    green fleet warms through the TARGET artifact's cache too (its own
    sidecar under 'auto', the shared content-addressed dir otherwise) —
    the cheap-swap story is precisely why the cache exists."""
    from mgproto_tpu.serving.engine import ServingEngine

    kw = _engine_kw(args)
    kw.pop("monitor")
    cache = None
    aot_fp = None
    if getattr(args, "aot_cache", ""):
        from mgproto_tpu.engine.export import artifact_aot_fingerprint
        from mgproto_tpu.serving.aotcache import (
            ExecutableCache,
            default_cache_dir,
        )

        cache = ExecutableCache(
            default_cache_dir(path) if args.aot_cache == "auto"
            else args.aot_cache
        )
        aot_fp = artifact_aot_fingerprint(path)  # hashed once, not per engine

    def factory():
        # a swap target must match the blue fleet's response contract: an
        # --explain fleet only accepts green artifacts that carry the
        # explain program (from_artifact refuses loudly otherwise)
        return ServingEngine.from_artifact(
            path, allow_uncalibrated=args.allow_uncalibrated,
            aot_cache=cache, aot_fingerprint=aot_fp,
            explain=getattr(args, "explain", False), **kw
        )

    return factory


def _summary_line(responses, compiled, steady, gate, readiness, extra=None):
    counts = {}
    for r in responses:
        counts[r.outcome] = counts.get(r.outcome, 0) + 1
    line = {
        "summary": True,
        "requests": len(responses),
        "outcomes": counts,
        "abstain_rate": gate.abstain_rate if gate is not None else None,
        "degraded": gate.degraded if gate is not None else None,
        "fingerprint_mismatch": (
            gate.fingerprint_mismatch if gate is not None else None
        ),
        "warmup_compiles": compiled,
        "steady_state_recompiles": steady,
        "readiness": readiness,
    }
    if extra:
        line.update(extra)
    print(json.dumps(line))


def _warmup_profile(args):
    """Context manager for --profile_warmup: a real device trace on
    TPU/GPU, a cost-analysis-only capture elsewhere (the cost analysis is
    written by `_write_warmup_costs` AFTER warmup, once the engine's
    compiled programs exist); nullcontext when unset."""
    import contextlib

    from mgproto_tpu.obs.profiler import profile_block

    if not args.profile_warmup:
        return contextlib.nullcontext()
    return profile_block(args.profile_warmup, reason="serve_warmup")


def _write_warmup_costs(capture_dir, engine) -> None:
    """The off-TPU --profile_warmup degrade: per-bucket XLA cost analysis
    of the warmed inference program into the capture dir (on TPU/GPU the
    real device trace already carries the op timeline)."""
    import os

    from mgproto_tpu.obs.profiler import COST_FILE, trace_supported

    if not capture_dir or engine is None or trace_supported():
        return
    try:
        costs = engine.warmup_costs()
    except Exception as e:  # profiling must never take the server down
        costs = {"error": f"{type(e).__name__}: {e}"}
    with open(os.path.join(capture_dir, COST_FILE), "w") as f:
        json.dump(costs, f, indent=2, sort_keys=True)


def _first_engine(rs):
    return next(
        (r.engine for r in rs.replicas if r.engine is not None), None
    )


def _setup_online(args, factory, telem):
    """--online wiring for the batch faces: install the capture tap and
    build the consolidator over the factory's live context. Returns
    (capture, consolidator) or (None, None) when --online is off. Fails
    loudly on the artifact face — there is no trainer to consolidate
    with (export a new artifact from a consolidated checkpoint instead)."""
    if not args.online:
        return None, None
    ctx = getattr(factory, "live_context", None)
    if ctx is None:
        raise SystemExit(
            "--online needs the live checkpoint face (--checkpoint + "
            "--calibrate): an exported artifact carries no trainer or "
            "memory bank to consolidate into"
        )
    trainer, state, calib = ctx
    if calib is None:
        raise SystemExit(
            "--online needs a calibration (--calibrate): the capture "
            "gate is a calibrated p(x) percentile"
        )
    from mgproto_tpu.online import capture as capture_mod
    from mgproto_tpu.online.capture import CaptureConfig, TrustedCapture
    from mgproto_tpu.online.consolidate import Consolidator, ConsolidatorConfig

    capture = TrustedCapture(
        calib, trainer.cfg.model.num_classes,
        CaptureConfig(
            percentile=args.online_capture_percentile,
            capacity_per_class=args.online_capture_capacity,
        ),
    )
    capture_mod.install(capture)
    cons = Consolidator(
        trainer, state, capture,
        ConsolidatorConfig(cadence_s=args.online_cadence_s),
    )
    # (online_*/drift_* metrics are pre-registered by TelemetrySession
    # itself — the registry-lint convention, like resilience's)
    return capture, cons


def _online_summary(capture, cons, forced=False):
    """The summary line's online block (None when --online off). The
    batch faces consolidate once after the pump drains (`forced`) — the
    cadence loop belongs to long-running faces."""
    if capture is None:
        return None
    if forced and cons is not None and capture.staged_count():
        cons.ingest(capture.drain())
    block = {"capture": capture.stats()}
    if cons is not None:
        block["consolidation"] = {
            "runs": cons.runs,
            "samples": cons.samples_consolidated,
            "em_active_max": max(
                (r.em_active_max for r in cons.reports), default=0
            ),
        }
    return block


def _main_batch_engine(args, handler, telem, monitor) -> None:
    """The original single-engine batch face (plus graceful drain)."""
    from mgproto_tpu.online import capture as capture_mod
    from mgproto_tpu.serving.health import HealthProbe

    factory = make_engine_factory(
        args, monitor_factory=(lambda: monitor) if monitor else None
    )
    capture, cons = _setup_online(args, factory, telem)
    try:
        engine = factory()
        if args.auto_tune:
            _apply_auto_tune(args, engine, telem)
        with _warmup_profile(args) as capture_dir:
            compiled = engine.warmup()
            _write_warmup_costs(capture_dir, engine)
        payloads, ids = _load_payloads(args)
        responses = drive_batch_engine(
            engine, payloads, ids, handler,
            on_pump=(lambda: cons.tick()) if cons is not None else None,
        )
        online = _online_summary(capture, cons, forced=True)
        for r in responses:
            print(json.dumps(r.to_dict()))
        extra = {"drained": handler.requested()}
        if online is not None:
            extra["online"] = online
        _summary_line(
            responses, compiled,
            engine.monitor.recompile_count - compiled,
            engine.gate, HealthProbe(engine).readiness(),
            extra=extra,
        )
    finally:
        if capture is not None:
            capture_mod.uninstall()


def _build_plane(args, telem):
    """The one ReplicaSet construction both plane faces share (auto-tune
    probe first, so warmup never compiles an over-budget bucket)."""
    from mgproto_tpu.serving.batcher import BatcherConfig
    from mgproto_tpu.serving.replica import ReplicaSet

    # ONE factory (the heavy state — artifact path or restored checkpoint +
    # calibration — loads exactly once); the auto-tune probe is its first
    # engine, and the factory reads the tuned bucket set late, so the fleet
    # and every restart agree with the plan
    factory = make_engine_factory(args)
    engine_prep = None
    if args.auto_tune:
        probe = factory()
        _apply_auto_tune(args, probe, telem)
        del probe
        # per-replica right-sizing: every engine a scale-up or restart
        # builds re-plans ITS bucket ladder against its own device budget
        # (heterogeneous hardware gets heterogeneous ladders; the probe
        # above already shrank the homogeneous baseline)
        from mgproto_tpu.serving.autoscale import hbm_bucket_prep

        engine_prep = hbm_bucket_prep()
    return ReplicaSet(
        factory,
        replicas=args.replicas,
        heartbeat_timeout_s=args.heartbeat_timeout_s,
        batcher_config=BatcherConfig(max_linger_s=args.linger_ms / 1000.0),
        engine_prep=engine_prep,
    )


def _parse_autoscale(raw: str):
    """'MIN:MAX' -> (min, max) or None when unset."""
    if not raw:
        return None
    mn, _, mx = raw.partition(":")
    try:
        bounds = (int(mn), int(mx))
    except ValueError:
        raise SystemExit(f"--autoscale must be MIN:MAX, got {raw!r}")
    if bounds[0] < 1 or bounds[1] < bounds[0]:
        raise SystemExit(f"--autoscale needs 1 <= MIN <= MAX, got {raw!r}")
    return bounds


def _main_batch_plane(args, handler, telem) -> None:
    """Batch face through the replica plane (--replicas > 1 or --swap)."""
    from mgproto_tpu.online import capture as capture_mod

    rs = _build_plane(args, telem)
    capture, cons = _setup_online(args, rs.engine_factory, telem)
    try:
        with _warmup_profile(args) as capture_dir:
            compiled = rs.start()
            _write_warmup_costs(capture_dir, _first_engine(rs))
        payloads, ids = _load_payloads(args)
        swap_at = len(payloads) // 2 if args.swap else None
        responses, reports = drive_batch_plane(
            rs, payloads, ids, handler,
            swap_at=swap_at,
            swap_factory=_swap_factory(args, args.swap) if args.swap else None,
            require_calibrated=not args.allow_uncalibrated,
            on_pump=(lambda: cons.tick()) if cons is not None else None,
        )
        online = _online_summary(capture, cons, forced=True)
        for r in responses:
            print(json.dumps(r.to_dict()))
        for rep in reports:
            print(json.dumps({"swap": True, **rep.to_dict()}))
        first = next((r for r in rs.replicas if r.engine is not None), None)
        extra = {
            "replicas": len(rs.replicas),
            "replicas_ready": len(rs.ready_replicas()),
            "swaps": [rep.to_dict() for rep in reports],
            "drained": handler.requested(),
        }
        if online is not None:
            extra["online"] = online
        _summary_line(
            responses, compiled, rs.steady_recompiles,
            first.engine.gate if first else None,
            first.probe.readiness() if first and first.probe else None,
            extra=extra,
        )
    finally:
        if capture is not None:
            capture_mod.uninstall()


def _main_listen(args, handler, telem) -> None:
    """The network face: replica plane behind the asyncio HTTP frontend."""
    import asyncio

    from mgproto_tpu.serving.frontend import Frontend

    host, _, port = args.listen.rpartition(":")
    if not host or not port:
        raise SystemExit(f"--listen must be HOST:PORT, got {args.listen!r}")
    bounds = _parse_autoscale(args.autoscale)
    if bounds is not None:
        # --replicas is the STARTING size, clamped into the bounds
        args.replicas = min(max(args.replicas, bounds[0]), bounds[1])
    rs = _build_plane(args, telem)
    with _warmup_profile(args) as capture_dir:
        compiled = rs.start()
        _write_warmup_costs(capture_dir, _first_engine(rs))
    autoscaler = None
    if bounds is not None:
        from mgproto_tpu.serving.autoscale import (
            Autoscaler,
            AutoscalerConfig,
        )

        autoscaler = Autoscaler(
            rs,
            AutoscalerConfig(
                min_replicas=bounds[0],
                max_replicas=bounds[1],
                interval_s=args.autoscale_interval_s,
            ),
        )
    frontend = Frontend(
        rs,
        host=host,
        port=int(port),
        preemption_handler=handler,
        swap_factory_builder=lambda path: _swap_factory(args, path),
        require_calibrated_swap=not args.allow_uncalibrated,
        autoscaler=autoscaler,
    )

    async def _run():
        await frontend.start()
        print(json.dumps({
            "listening": True,
            "host": host,
            "port": frontend.port,
            "replicas": args.replicas,
            "autoscale": args.autoscale or None,
            "buckets": _parse_buckets(args.buckets),
            "warmup_compiles": compiled,
        }), flush=True)
        await frontend.run_until_drained()

    started = time.monotonic()
    asyncio.run(_run())
    first = next((r for r in rs.replicas if r.engine is not None), None)
    print(json.dumps({
        "summary": True,
        "outcomes": frontend.outcomes,
        "requests": sum(frontend.outcomes.values()),
        "steady_state_recompiles": rs.steady_recompiles,
        "uptime_s": time.monotonic() - started,
        "degraded": first.engine.gate.degraded if first else None,
        "drained": True,
    }))


if __name__ == "__main__":
    main()
