"""Serving driver: trust-gated inference over an artifact or checkpoint.

`mgproto-serve` is the batch/stdin face of `serving.ServingEngine` — the
same engine a network frontend would embed, with zero network dependency
(tier-1 testable). One JSON line per request response, plus one final
summary line (counts by outcome, abstain rate, breaker/health state).

    # exported artifact (calibration embedded by `mgproto-export --calibrate`)
    mgproto-serve --artifact model.mgproto --images batch.npy

    # live checkpoint (same flags as mgproto-eval); calibrates on the fly
    mgproto-serve --checkpoint auto --model_dir runs/r1 --calibrate ...

    # stdin JSONL: {"id": "...", "image": [[[...]]]} per line
    mgproto-serve --artifact model.mgproto --stdin < requests.jsonl

An artifact without calibration.json refuses to serve unless
`--allow-uncalibrated`, which drops to DEGRADED mode: classification
without OoD abstention, flagged on every response.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

import numpy as np

from mgproto_tpu.cli.common import add_train_args, config_from_args
from mgproto_tpu.serving.metrics import register_serving_metrics
from mgproto_tpu.telemetry import make_session
from mgproto_tpu.telemetry.monitor import StepMonitor


def _parse_buckets(raw: str):
    return tuple(int(b) for b in raw.split(",") if b.strip())


def _load_payloads(args):
    """(payloads, ids) from --images npy/npz files and/or --stdin JSONL."""
    payloads, ids = [], []
    for path in args.images:
        arr = np.load(path, allow_pickle=False)
        if isinstance(arr, np.lib.npyio.NpzFile):
            arr = arr[arr.files[0]]
        if arr.ndim == 3:
            arr = arr[None]
        for i, row in enumerate(arr):
            payloads.append(row)
            ids.append(f"{os.path.basename(path)}[{i}]")
    if args.stdin:
        for lineno, line in enumerate(sys.stdin):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                payloads.append(rec["image"])
                ids.append(str(rec.get("id", f"stdin[{lineno}]")))
            except (ValueError, KeyError, TypeError):
                payloads.append(None)  # typed reject, not a crash
                ids.append(f"stdin[{lineno}]")
    return payloads, ids


def build_engine(args, monitor: Optional[StepMonitor] = None):
    """Engine from --artifact, or from a checkpoint via the train flags."""
    from mgproto_tpu.serving.engine import ServingEngine

    kw = dict(
        buckets=_parse_buckets(args.buckets),
        percentile=args.percentile,
        queue_capacity=args.queue_capacity,
        default_deadline_s=(
            args.deadline_ms / 1000.0 if args.deadline_ms > 0 else None
        ),
        monitor=monitor,
    )
    if args.artifact:
        return ServingEngine.from_artifact(
            args.artifact, allow_uncalibrated=args.allow_uncalibrated, **kw
        )

    import jax

    from mgproto_tpu.engine.train import Trainer
    from mgproto_tpu.utils import latest_checkpoint, restore_checkpoint
    from mgproto_tpu.utils.checkpoint import adopt_checkpoint_train_config

    cfg = config_from_args(args)
    path = (
        latest_checkpoint(cfg.model_dir)
        if args.checkpoint == "auto"
        else args.checkpoint
    )
    if not path:
        raise FileNotFoundError(f"no checkpoint found in {cfg.model_dir}")
    cfg = adopt_checkpoint_train_config(cfg, path, log=print)
    trainer = Trainer(cfg, steps_per_epoch=1)
    state = trainer.init_state(jax.random.PRNGKey(cfg.seed), for_restore=True)
    state = restore_checkpoint(path, state)
    calib = None
    if args.calibrate:
        from mgproto_tpu.serving.calibration import calibrate_from_config

        calib = calibrate_from_config(
            cfg, trainer, state,
            # explicit `is None`: --percentile 0 is a legitimate (gate
            # nothing out) operating point, not a request for the default
            percentile=5.0 if args.percentile is None else args.percentile,
        )
    elif not args.allow_uncalibrated:
        raise SystemExit(
            "live serving without calibration: pass --calibrate (derives "
            "thresholds from --test_dir) or --allow-uncalibrated "
            "(degraded mode, no OoD abstention)"
        )
    return ServingEngine.from_live(trainer, state, calibration=calib, **kw)


CHAOS_SERVE_ENV_HELP = """\
serving chaos-injection env knobs (fault drills; all off by default):
  MGPROTO_CHAOS_SEED                  seed for the deterministic schedule
  MGPROTO_CHAOS_SERVE_MALFORMED_RATE  fraction of requests made malformed
                                      (wrong shape -> typed reject)
  MGPROTO_CHAOS_SERVE_NAN_RATE        fraction NaN-poisoned (typed reject)
  MGPROTO_CHAOS_SERVE_DEVICE_ERRORS   comma-separated dispatch indices that
                                      raise a simulated device failure
                                      (feeds the circuit breaker)
  MGPROTO_CHAOS_SERVE_STORM_AT        first request index of a deadline
                                      storm (arrives already expired)
  MGPROTO_CHAOS_SERVE_STORM_LEN       number of storm requests
"""


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(
        description="Serve an MGProto model with calibrated trust gating",
        epilog=CHAOS_SERVE_ENV_HELP,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    add_train_args(p)
    p.add_argument("--artifact", default="",
                   help=".mgproto artifact to serve (else --checkpoint + "
                        "model flags)")
    p.add_argument("--checkpoint", default="auto",
                   help="checkpoint path ('auto' = latest in --model_dir); "
                        "ignored when --artifact is given")
    p.add_argument("--images", action="append", default=[],
                   help="npy/npz of [N,H,W,3] (or [H,W,3]) normalized "
                        "float images (repeatable)")
    p.add_argument("--stdin", action="store_true",
                   help="also read JSONL requests from stdin: "
                        '{"id": ..., "image": nested lists}')
    p.add_argument("--buckets", default="1,2,4,8",
                   help="batch-size buckets compiled at warmup (requests "
                        "are padded up; no recompiles after warmup)")
    p.add_argument("--percentile", type=float, default=None,
                   help="abstention operating point (ID log p(x) "
                        "percentile); default: the calibration's own")
    p.add_argument("--deadline_ms", type=float, default=0.0,
                   help="per-request deadline; expired requests are shed "
                        "typed (0 = none)")
    p.add_argument("--queue_capacity", type=int, default=64,
                   help="admission queue bound (overflow sheds typed)")
    p.add_argument("--allow-uncalibrated", "--allow_uncalibrated",
                   dest="allow_uncalibrated", action="store_true",
                   help="serve WITHOUT calibration in degraded mode "
                        "(classification only, flagged per response)")
    p.add_argument("--calibrate", action="store_true",
                   help="live mode: derive calibration from the --test_dir "
                        "loader before serving")
    # NB: add_train_args already contributes --auto_tune; here it sizes the
    # warmup bucket set instead of the train plan (perf/planner.py
    # plan_serve_buckets): over-budget buckets are dropped before warmup
    # compiles them, and the outcome lands in telemetry meta when enabled.
    args = p.parse_args(argv)

    from mgproto_tpu.resilience import chaos as chaos_mod

    chaos_plan = chaos_mod.plan_from_env()
    if chaos_plan is not None:
        chaos_mod.install(chaos_plan)

    # unlike mgproto-train there is no default telemetry dir (a serve run
    # has no model_dir of its own): telemetry is on when --telemetry-dir is
    telem = make_session(args.telemetry_dir or "", not args.no_telemetry)
    monitor = None
    if telem:
        register_serving_metrics(telem.registry)
        monitor = StepMonitor(registry=telem.registry, phase="serve")

    engine = build_engine(args, monitor=monitor)
    try:
        if args.auto_tune:
            from mgproto_tpu.perf.planner import plan_serve_buckets

            fitting, outcome = plan_serve_buckets(engine)
            print(json.dumps({
                "autotune": True,
                "buckets": list(fitting),
                "rejected": outcome.rejected,
                "budget_bytes": outcome.budget_bytes,
            }))
            if telem:
                telem.observe_autotune(outcome)
            if not fitting:
                # fail CLOSED: warming the rejected set would execute the
                # exact OOM the planner just predicted. Rerun without
                # --auto_tune (or raise the budget) to override.
                raise SystemExit(
                    "auto_tune: no warmup bucket fits the HBM budget "
                    f"({outcome.budget_bytes} bytes, margin "
                    f"{outcome.margin}); refusing to warm an over-budget "
                    "bucket set"
                )
            if tuple(fitting) != engine.buckets:
                engine.buckets = tuple(fitting)
        compiled = engine.warmup()
        payloads, ids = _load_payloads(args)
        responses = engine.serve_all(payloads, request_ids=ids)
        for r in responses:
            print(json.dumps(r.to_dict()))
        from mgproto_tpu.serving.health import HealthProbe

        counts = {}
        for r in responses:
            counts[r.outcome] = counts.get(r.outcome, 0) + 1
        print(json.dumps({
            "summary": True,
            "requests": len(responses),
            "outcomes": counts,
            "abstain_rate": engine.gate.abstain_rate,
            "degraded": engine.gate.degraded,
            "fingerprint_mismatch": engine.gate.fingerprint_mismatch,
            "warmup_compiles": compiled,
            "steady_state_recompiles": engine.monitor.recompile_count
            - compiled,
            "readiness": HealthProbe(engine).readiness(),
        }))
        if telem:
            telem.flush()
    finally:
        if telem:
            telem.close()


if __name__ == "__main__":
    main()
