"""Offline data-preparation CLI (reference preprocess_data/* scripts).

Subcommands:
  cub-crop   — bbox-crop CUB into train_cropped/test_cropped trees
  cub-masks  — bbox-crop CUB segmentation masks
  mask-fg    — binarize masks to foreground/background
  cars-crop  — bbox-crop Stanford Cars from cars_annos.mat
  pets       — build Oxford-IIIT Pets class folders
  augment    — 40x offline augmentation (rotate/skew/shear/distortion)
"""

from __future__ import annotations

import argparse
from typing import Optional

from mgproto_tpu.data import prep


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(description="MGProto-TPU dataset preparation")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("cub-crop")
    s.add_argument("--cub_root", required=True)
    s.add_argument("--out_root", required=True)

    s = sub.add_parser("cub-masks")
    s.add_argument("--cub_root", required=True)
    s.add_argument("--seg_root", required=True)
    s.add_argument("--out_root", required=True)

    s = sub.add_parser("mask-fg")
    s.add_argument("--src_root", required=True)
    s.add_argument("--dst_root", required=True)

    s = sub.add_parser("cars-crop")
    s.add_argument("--annos_mat", required=True)
    s.add_argument("--images_root", required=True)
    s.add_argument("--out_root", required=True)

    s = sub.add_parser("pets")
    s.add_argument("--img_dir", required=True)
    s.add_argument("--label_file", required=True)
    s.add_argument("--out_dir", required=True)

    s = sub.add_parser("augment")
    s.add_argument("--src_dir", required=True)
    s.add_argument("--dst_dir", required=True)
    s.add_argument("--copies_per_op", type=int, default=10)
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--ops", nargs="+", default=None,
                   choices=["rotate", "skew", "shear", "distortion"])

    args = p.parse_args(argv)
    if args.cmd == "cub-crop":
        n_train, n_test = prep.crop_cub(args.cub_root, args.out_root)
        print(f"cropped {n_train} train / {n_test} test images")
    elif args.cmd == "cub-masks":
        n = prep.crop_cub_masks(args.cub_root, args.seg_root, args.out_root)
        print(f"cropped {n} masks")
    elif args.cmd == "mask-fg":
        n = prep.binarize_masks(args.src_root, args.dst_root)
        print(f"binarized {n} masks")
    elif args.cmd == "cars-crop":
        n = prep.crop_cars(args.annos_mat, args.images_root, args.out_root)
        print(f"cropped {n} car images")
    elif args.cmd == "pets":
        n = prep.build_pets(args.img_dir, args.label_file, args.out_dir)
        print(f"copied {n} pet images")
    elif args.cmd == "augment":
        n = prep.augment_offline(
            args.src_dir, args.dst_dir,
            copies_per_op=args.copies_per_op, seed=args.seed, ops=args.ops,
        )
        print(f"wrote {n} augmented images")


if __name__ == "__main__":
    main()
