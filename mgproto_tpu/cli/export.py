"""Export driver: checkpoint -> self-contained StableHLO inference artifact.

Beyond the reference's deployment story (torch state_dicts that need the full
Python model code to reload, eval_purity.py:55): `mgproto-export` produces a
one-file program — weights baked in, symbolic batch — that any XLA backend
runs via `jax.export.deserialize` alone. See engine/export.py.

    mgproto-export --arch resnet34 --num_classes 200 \
        --model_dir saved_models --out mgproto_r34_cub.mgproto
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from typing import Optional

import jax

from mgproto_tpu.cli.common import add_train_args, config_from_args
from mgproto_tpu.engine.export import (
    artifact_meta,
    export_eval,
    save_artifact,
)
from mgproto_tpu.engine.train import Trainer
from mgproto_tpu.serving.calibration import (
    calibrate_from_config,
    gmm_fingerprint,
)
from mgproto_tpu.utils import latest_checkpoint, restore_checkpoint
from mgproto_tpu.utils.checkpoint import adopt_checkpoint_train_config


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(
        description="Export an MGProto-TPU checkpoint as a StableHLO artifact"
    )
    add_train_args(p)
    p.add_argument("--checkpoint", default="auto",
                   help="checkpoint path ('auto' = latest in --model_dir)")
    p.add_argument("--out", required=True,
                   help="artifact path to write (convention: *.mgproto)")
    p.add_argument("--static_batch", type=int, default=0,
                   help="pin the batch dimension to this size instead of "
                        "exporting a symbolic batch (some non-XLA StableHLO "
                        "consumers need static shapes); 0 = symbolic")
    p.add_argument("--calibrate", action="store_true",
                   help="derive the serving calibration (log p(x) "
                        "percentile thresholds, quantile sketch, per-class "
                        "temperatures; serving/calibration.py) from the "
                        "held-out ID loader at --test_dir and embed it as "
                        "calibration.json — mgproto-serve refuses "
                        "uncalibrated artifacts unless --allow-uncalibrated")
    p.add_argument("--calib_percentile", type=float, default=5.0,
                   help="ID percentile for the default abstention "
                        "operating point (matches evaluate_with_ood's "
                        "threshold convention)")
    p.add_argument("--explain", action="store_true",
                   help="stage the EXPLAIN program beside the plain one "
                        "(explain.stablehlo + explain.json: top activated "
                        "prototypes per request, mixture priors, and "
                        "nearest-training-patch provenance from the run's "
                        "push_provenance.json when present) — "
                        "mgproto-serve --explain then serves explanations "
                        "from the artifact with no training run")
    p.add_argument("--explain_top", type=int, default=5,
                   help="prototypes per explanation (most activated first)")
    p.add_argument("--aot-cache", "--aot_cache", dest="aot_cache",
                   action="store_true",
                   help="prebuild the AOT executable cache beside the "
                        "artifact (<out>.aotcache/): compile each "
                        "--aot_buckets serving bucket and serialize the "
                        "executable, so replica starts on matching "
                        "hardware warm with ZERO compiles "
                        "(serving/aotcache.py)")
    p.add_argument("--aot_buckets", default="1,2,4,8",
                   help="bucket sizes to precompile into the AOT cache")
    p.add_argument("--quantize", choices=("none", "int8"), default="none",
                   help="weight-only quantization of the backbone's conv/"
                        "dense kernels (perf/quant.py): 'int8' bakes int8 "
                        "tensors + per-output-channel f32 scales into the "
                        "program (dequantize-in-kernel — 1 byte/param "
                        "steady-state weight traffic), stamps quant_config "
                        "into meta.json + the calibration, and embeds the "
                        "dequantize-to-f32 debug program; 'none' (default) "
                        "writes today's f32 artifact byte-identically. The "
                        "GMM head, priors, log p(x) and calibration math "
                        "are never quantized")
    args = p.parse_args(argv)
    cfg = config_from_args(args)

    path = (
        latest_checkpoint(cfg.model_dir)
        if args.checkpoint == "auto"
        else args.checkpoint
    )
    if not path:
        raise FileNotFoundError(f"no checkpoint found in {cfg.model_dir}")
    cfg = adopt_checkpoint_train_config(cfg, path, log=print)
    # the exported program always uses the portable XLA scoring path
    # (engine/export.py); forcing it here avoids constructing a fused-path
    # Trainer on TPU hosts only for export_eval to rebuild a portable one
    cfg = cfg.replace(
        model=dataclasses.replace(cfg.model, fused_scoring=False)
    )

    trainer = Trainer(cfg, steps_per_epoch=1)
    state = trainer.init_state(jax.random.PRNGKey(cfg.seed), for_restore=True)
    state = restore_checkpoint(path, state)

    dynamic = args.static_batch <= 0
    qparams = None
    dequant = None
    if args.quantize != "none":
        from mgproto_tpu.perf.quant import (
            quantize_params,
            resolve_quant_policy,
        )

        qparams = quantize_params(
            state.params, resolve_quant_policy(args.quantize)
        )
        # calibration + the debug program both run on the ROUND-TRIPPED
        # weights: ID thresholds must be measured under exactly the grid
        # the int8 program serves, and the dequant blob is its f32 twin
        state = state.replace(params=qparams.materialize(barrier=False))
        dequant = export_eval(
            trainer, state, dynamic_batch=dynamic,
            static_batch=max(args.static_batch, 1),
        )
    exported = export_eval(
        trainer, state, dynamic_batch=dynamic,
        static_batch=max(args.static_batch, 1),
        quantized=qparams,
    )
    meta = artifact_meta(
        cfg, path, dynamic,
        gmm_fingerprint=gmm_fingerprint(state.gmm),
        static_batch=max(args.static_batch, 1),
        quant=qparams.quant_config() if qparams is not None else None,
    )
    calib = None
    if args.calibrate:
        calib = calibrate_from_config(
            cfg, trainer, state, percentile=args.calib_percentile,
            quant_config=(
                qparams.policy.tag if qparams is not None else ""
            ),
        )
    explain = None
    if args.explain:
        from mgproto_tpu.engine.export import (
            explain_table,
            export_explain,
        )

        from mgproto_tpu.engine.push import load_push_provenance

        provenance = load_push_provenance(cfg.model_dir)
        if provenance is not None:
            print(f"explain provenance: {cfg.model_dir}/push_provenance.json")
        else:
            print(
                "explain provenance: none (no push_provenance.json in "
                f"{cfg.model_dir}; explanations will carry prototype "
                "identity + prior + density but no source patches)"
            )
        explain = (
            export_explain(
                trainer, state, top_e=args.explain_top,
                dynamic_batch=dynamic,
                static_batch=max(args.static_batch, 1),
            ),
            explain_table(state, provenance=provenance),
        )
    save_artifact(
        args.out, exported, meta, calibration=calib, explain=explain,
        dequant=dequant,
    )
    line = {
        "artifact": args.out,
        "bytes": os.path.getsize(args.out),
        "calibrated": calib is not None,
        "explain": explain is not None,
        "quantize": args.quantize,
        **{k: meta[k] for k in ("arch", "num_classes", "img_size",
                                "dynamic_batch", "checkpoint",
                                "gmm_fingerprint")},
    }
    if args.aot_cache:
        from mgproto_tpu.engine.export import export_aot_cache

        line["aot_cache"] = export_aot_cache(
            args.out,
            buckets=tuple(
                int(b) for b in args.aot_buckets.split(",") if b.strip()
            ),
        )
    print(json.dumps(line))


if __name__ == "__main__":
    main()
