"""Evaluation driver: test accuracy + OoD metrics from a checkpoint.

Reference: the eval half of main.py plus the `_testing_with_OoD` path
(train_and_test.py:161-238). Interpretability metrics (consistency /
stability / purity) live in `mgproto_tpu.cli.interpret`.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
from typing import Optional

import jax

from mgproto_tpu.cli.common import (
    add_train_args,
    config_from_args,
    maybe_init_distributed,
)
from mgproto_tpu.cli.train import _test
from mgproto_tpu.data import build_pipelines
from mgproto_tpu.parallel import ShardedTrainer
from mgproto_tpu.telemetry import make_session
from mgproto_tpu.utils import latest_checkpoint, restore_checkpoint
from mgproto_tpu.utils.checkpoint import adopt_checkpoint_train_config


def main(argv: Optional[list] = None) -> None:
    p = argparse.ArgumentParser(
        description="Evaluate an MGProto-TPU checkpoint (test acc + OoD)"
    )
    add_train_args(p)
    p.add_argument(
        "--checkpoint",
        default="auto",
        help="checkpoint path ('auto' = latest in --model_dir)",
    )
    p.add_argument(
        "--ood_score", "--score_rule",
        dest="ood_score",
        default="sum",
        choices=["sum", "max", "paper"],
        help="OoD operating-point rule (alias: --score_rule, matching the "
             "engine's evaluate_with_ood parameter name): 'sum' = the "
             "reference's inherited "
             "sum_c p(x|c) threshold (with its C-fold asymmetry, kept for "
             "parity); 'max' = max_c p(x|c), which rescues broad-response "
             "near-OoD (evidence/README.md); 'paper' = log p(x) on BOTH "
             "sides (the paper's stated rule, and what the serving "
             "calibration gates with). AUROC for every rule is reported "
             "either way.",
    )
    args = p.parse_args(argv)
    maybe_init_distributed(args)
    cfg = config_from_args(args)

    _, _, test_loader, ood_loaders = build_pipelines(cfg)
    path = (
        latest_checkpoint(cfg.model_dir)
        if args.checkpoint == "auto"
        else args.checkpoint
    )
    if not path:
        raise FileNotFoundError(f"no checkpoint found in {cfg.model_dir}")
    cfg = adopt_checkpoint_train_config(cfg, path, log=print)

    trainer = ShardedTrainer(cfg, steps_per_epoch=1)
    state = trainer.init_state(jax.random.PRNGKey(cfg.seed), for_restore=True)
    state = trainer.prepare(restore_checkpoint(path, state))
    print(f"loaded {path}")

    # telemetry (eval-side): span + eval-step recompile watch + a health
    # record of the restored checkpoint, in <model_dir>/telemetry_eval so a
    # co-located training run's artifacts are never clobbered
    telem = make_session(
        args.telemetry_dir or os.path.join(cfg.model_dir, "telemetry_eval"),
        not args.no_telemetry,
    )
    if telem:
        telem.monitor.watch(lambda: trainer.jit_handles)

    try:
        with telem.span("evaluate", checkpoint=path) if telem else (
            contextlib.nullcontext()
        ):
            accu, results = _test(
                trainer, state, test_loader, ood_loaders, print,
                score_rule=args.ood_score,
            )
        if telem:
            telem.monitor.check_recompiles()
            telem.health.record(state)
            telem.flush()
    finally:
        if telem:
            telem.close()
    print(json.dumps({"checkpoint": path, "accuracy": accu, **results}))


if __name__ == "__main__":
    main()
