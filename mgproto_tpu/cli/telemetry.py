"""Run-summary driver: summarize a telemetry directory.

`mgproto-telemetry <dir>` (or `python -m mgproto_tpu.cli.telemetry <dir>`)
reads the artifacts a TelemetrySession wrote — metrics.jsonl (registry
snapshots), health.jsonl (per-epoch ModelHealth records), trace.json
(Chrome-trace spans) — and renders what a run operator asks first: how fast
were steps (final EMA + percentiles), did anything recompile mid-run, did
the model stay healthy (entropy / collapse / memory-fill trajectory), and
where did the wall time go (per-span totals). Accepts the run's model_dir
too (falls back to its telemetry/ subdirectory). `--json` emits the summary
as one JSON object for scripts; the default is an aligned text table.

Fleet view (ISSUE 10): `mgproto-telemetry fleet <dir>` merges host 0's
canonical stream with every `.h<pid>` sidecar (telemetry/session.py writes
one per process under multi-host) into a per-host table — img/s, step p99,
loader wait, barrier-wait fraction, arrival-skew fraction, heartbeat gaps,
restarts, per-chip allgather bytes, flight-recorder dumps — plus fleet
aggregates (slowest host, max skew, per-chip traffic: the weak-scaling
instrument panel). `check` gains fleet gate entries against a committed
baseline (`--write-baseline --fleet-gates`, e.g.
evidence/fleet_baseline.json from the two-process dryrun drill).

Host-side and jax-free: summarizing must work on a laptop with nothing but
the run directory.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Any, Dict, List, Optional

from mgproto_tpu.telemetry.registry import percentile_from_buckets
from mgproto_tpu.telemetry.session import (
    ALLGATHER_BYTES_COUNTER,
    AUTOTUNE_REJECTED_COUNTER,
    BANK_BYTES_GAUGE,
    BANK_OVERLAP_GAUGE,
    BARRIER_WAIT_HIST,
    COLLECTIVE_WAIT_HIST,
    DATA_SHM_SLABS_GAUGE,
    DATA_WAIT_GAUGE,
    EM_ACTIVE_GAUGE,
    EM_FALLBACK_COUNTER,
    HEALTH_FILE,
    HEARTBEAT_AGE_GAUGE,
    HOST_DEVICES_GAUGE,
    META_FILE,
    METRICS_FILE,
    OPT_BYTES_GAUGE,
    PROM_FILE,
    SKEW_GAUGE,
    STRAGGLER_COUNTER,
    TRACE_FILE,
)

STEP_PERCENTILES = (50.0, 90.0, 99.0)

# the health keys whose first->last trajectory the table shows
HEALTH_TRAJECTORY_KEYS = (
    "prior_entropy_mean",
    "min_interproto_dist",
    "collapse_frac",
    "memory_occupancy",
)


def _is_telemetry_dir(path: str) -> bool:
    """True when `path` holds TelemetrySession artifacts. A run's model_dir
    ALSO contains a metrics.jsonl (the MetricsWriter train-metrics stream,
    one scalar dict per step — no "metrics" key), so the jsonl name alone
    cannot identify a telemetry dir: check the unambiguous artifacts first,
    then the shape of the first parseable jsonl record."""
    for name in (PROM_FILE, HEALTH_FILE, TRACE_FILE):
        if os.path.isfile(os.path.join(path, name)):
            return True
    m = os.path.join(path, METRICS_FILE)
    if os.path.isfile(m):
        with open(m) as f:
            for line in f:
                try:
                    return "metrics" in json.loads(line)
                except ValueError:
                    continue
    return False


def resolve_dir(path: str) -> str:
    """Accept a telemetry dir directly or a run dir containing telemetry/."""
    if _is_telemetry_dir(path):
        return path
    sub = os.path.join(path, "telemetry")
    if os.path.isdir(sub):
        return sub
    return path


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    if not os.path.isfile(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # a torn tail line (killed run) is not an error
    return out


def _series_value(snapshot: Dict, name: str, default=None):
    """Latest-snapshot scalar: sums counters across label sets, takes the
    max-labeled single series otherwise (phase-labeled gauges have one)."""
    m = snapshot.get(name)
    if not m or not m.get("series"):
        return default
    vals = [s.get("value") for s in m["series"] if s.get("value") is not None]
    if not vals:
        return default
    if m.get("type") == "counter":
        return sum(vals)
    return vals[-1]


def _hist_series(snapshot: Dict, name: str) -> Optional[Dict]:
    """Merge a histogram's label series into one (same bounds by construction)."""
    m = snapshot.get(name)
    if not m or m.get("type") != "histogram" or not m.get("series"):
        return None
    merged: Optional[Dict[str, Any]] = None
    for s in m["series"]:
        if merged is None:
            merged = {
                "bounds": list(s["bounds"]),
                "bucket_counts": list(s["bucket_counts"]),
                "count": s["count"],
                "sum": s["sum"],
                "min": s["min"],
                "max": s["max"],
            }
        else:
            merged["bucket_counts"] = [
                a + b
                for a, b in zip(merged["bucket_counts"], s["bucket_counts"])
            ]
            merged["count"] += s["count"]
            merged["sum"] += s["sum"]
            for k, pick in (("min", min), ("max", max)):
                if s[k] is not None:
                    merged[k] = (
                        s[k] if merged[k] is None else pick(merged[k], s[k])
                    )
    return merged


def _series_by_label(
    snapshot: Dict, name: str, label_key: str
) -> Dict[str, float]:
    """Counter value per label (e.g. requests by outcome)."""
    m = snapshot.get(name)
    out: Dict[str, float] = {}
    if not m:
        return out
    for s in m.get("series", []):
        v = s.get("value")
        if v is None:
            continue
        key = s.get("labels", {}).get(label_key)
        if key is not None:
            out[key] = out.get(key, 0.0) + v
    return out


def _unlabeled_value(snapshot: Dict, name: str, default=None):
    """The explicitly-unlabeled series of a gauge that ALSO carries labeled
    series (e.g. serving_queue_depth: per-replica labels + the fleet total
    unlabeled) — _series_value's last-series pick would return whichever
    replica happened to flush last. Falls back to the labeled sum."""
    m = snapshot.get(name)
    if not m or not m.get("series"):
        return default
    labeled = []
    for s in m["series"]:
        if s.get("value") is None:
            continue
        if not s.get("labels"):
            return s["value"]
        labeled.append(s["value"])
    return sum(labeled) if labeled else default


def _stage_latency_section(
    snapshot: Dict, name: str
) -> Dict[str, Dict[str, float]]:
    """p50/p99/mean per `stage` label of the request-stage histogram."""
    m = snapshot.get(name)
    out: Dict[str, Dict[str, float]] = {}
    if not m or m.get("type") != "histogram":
        return out
    for s in m.get("series", []):
        stage = s.get("labels", {}).get("stage")
        if stage is None or not s.get("count"):
            continue
        out[stage] = {
            "count": s["count"],
            "mean": s["sum"] / s["count"],
            "p50": percentile_from_buckets(s, 50.0),
            "p99": percentile_from_buckets(s, 99.0),
        }
    return out


def _serving_section(last: Dict) -> Optional[Dict[str, Any]]:
    """Serving story: outcomes, PER-REASON shed counts (queue_full vs
    deadline vs shutdown...), latency percentiles, trust + breaker state
    including the open-time fraction, micro-batch fill histogram, replica
    supervision and hot-swap counters (None when this run never served —
    training-only telemetry)."""
    from mgproto_tpu.serving import metrics as sm  # jax-free

    if not any(name in last for name in sm.ALL_COUNTERS):
        return None
    section: Dict[str, Any] = {
        "requests_by_outcome": _series_by_label(
            last, sm.REQUESTS, "outcome"
        ),
        "shed_by_reason": _series_by_label(last, sm.SHED, "reason"),
        "abstain_rate": _series_value(last, sm.ABSTAIN_RATE),
        "degraded_requests": _series_value(last, sm.DEGRADED_REQUESTS),
        "fingerprint_mismatches": _series_value(
            last, sm.FINGERPRINT_MISMATCHES
        ),
        # int8 weight-only serving (ISSUE 20): mismatch counter is pre-
        # registered (explicit 0 = "no artifact/calibration quant skew"),
        # the weight-bytes gauge is nonzero only under a quantized artifact
        "quant_mismatches": _series_value(last, sm.QUANT_MISMATCHES),
        "quant_weight_bytes": _series_value(last, sm.QUANT_WEIGHT_BYTES),
        "device_errors": _series_value(last, sm.DEVICE_ERRORS),
        "breaker_state": _series_value(last, sm.BREAKER_STATE),
        "breaker_transitions": _series_by_label(
            last, sm.BREAKER_TRANSITIONS, "edge"
        ),
        "breaker_open_time_fraction": _series_value(
            last, sm.BREAKER_OPEN_FRACTION
        ),
    }
    hist = _hist_series(last, sm.REQUEST_SECONDS)
    if hist and hist["count"]:
        section["request_mean_seconds"] = hist["sum"] / hist["count"]
        for p in STEP_PERCENTILES:
            section[f"request_p{p:g}_seconds"] = percentile_from_buckets(
                hist, p
            )
        section["request_max_seconds"] = hist["max"]
    # per-stage request latency (obs/reqtrace.py: queue / device / total),
    # present only when request tracing ran
    stages = _stage_latency_section(last, sm.STAGE_SECONDS)
    if stages:
        section["stage_seconds"] = stages
    fill = _hist_series(last, sm.BATCH_FILL_HIST)
    if fill and fill["count"]:
        section["batch_fill"] = {
            "dispatches": fill["count"],
            "mean": fill["sum"] / fill["count"],
            "p50": percentile_from_buckets(fill, 50.0),
            "p90": percentile_from_buckets(fill, 90.0),
            "min": fill["min"],
        }
    # network-plane story, present only when the plane ran
    plane = {
        "dispatches_by_trigger": _series_by_label(
            last, sm.DISPATCHES, "trigger"
        ),
        "replica_restarts": _series_by_label(
            last, sm.REPLICA_RESTARTS, "reason"
        ),
        "replicas_ready": _series_value(last, sm.REPLICAS_READY),
        "replicas_total": _series_value(last, sm.REPLICAS_TOTAL),
        "queue_depth": _unlabeled_value(last, sm.QUEUE_DEPTH),
        "swaps_by_result": _series_by_label(last, sm.SWAPS, "result"),
        "swap_transferred": _series_value(last, sm.SWAP_TRANSFERRED),
    }
    for key, value in plane.items():
        if value not in (None, {}):
            section[key] = value
    return section


def _autoscale_section(last: Dict) -> Optional[Dict[str, Any]]:
    """Elastic-serving story (ISSUE 13): autoscaler decisions + the AOT
    executable cache's hit/miss/reject ledger. Present whenever the
    serving family is (pre-registered — explicit zeros mean "fixed fleet,
    cold compiles", which an operator should see, not infer); None only
    for telemetry dirs that never served."""
    from mgproto_tpu.serving import metrics as sm  # jax-free

    names = (
        sm.AUTOSCALE_TARGET, sm.AUTOSCALE_EVENTS,
        sm.AOT_HITS, sm.AOT_MISSES, sm.AOT_REJECTS,
    )
    if not any(name in last for name in names):
        return None
    return {
        "replicas_target": _series_value(last, sm.AUTOSCALE_TARGET),
        "events_by_direction": _series_by_label(
            last, sm.AUTOSCALE_EVENTS, "direction"
        ),
        "aot_hits": _series_value(last, sm.AOT_HITS),
        "aot_misses": _series_value(last, sm.AOT_MISSES),
        "aot_rejects_by_reason": _series_by_label(
            last, sm.AOT_REJECTS, "reason"
        ),
        "aot_stores_by_result": _series_by_label(
            last, sm.AOT_STORES, "result"
        ),
    }


def _tenant_nested(
    snapshot: Dict, name: str, inner_key: str
) -> Dict[str, Dict[str, float]]:
    """{tenant: {inner_label: count}} for a tenant-labeled counter."""
    out: Dict[str, Dict[str, float]] = {}
    for s in snapshot.get(name, {}).get("series", []):
        labels = s.get("labels", {})
        t, k = labels.get("tenant"), labels.get(inner_key)
        if t is not None and k is not None and s.get("value"):
            row = out.setdefault(t, {})
            row[k] = row.get(k, 0.0) + s["value"]
    return out


def _tenants_section(last: Dict) -> Optional[Dict[str, Any]]:
    """Multi-tenant serving story (ISSUE 17): heads mounted on the shared
    trunk, per-tenant request/shed/swap ledgers, head bytes, per-tenant
    latency. The family is pre-registered, so presence alone says nothing;
    the section renders only once a tenant has actually mounted or served
    — a single-tenant fleet stays a single-tenant summary."""
    from mgproto_tpu.serving import metrics as sm  # jax-free

    names = (
        sm.TENANTS_MOUNTED, sm.TENANT_MOUNTS, sm.TENANT_REQUESTS,
        sm.TENANT_SHED, sm.TENANT_SWAPS, sm.TENANT_HEAD_BYTES,
    )
    if not any(name in last for name in names):
        return None
    mounted = _series_value(last, sm.TENANTS_MOUNTED)
    mount_total = _series_value(last, sm.TENANT_MOUNTS)
    requests = _series_by_label(last, sm.TENANT_REQUESTS, "tenant")
    if not (mounted or mount_total or requests):
        return None
    head_bytes: Dict[str, float] = {}
    for s in last.get(sm.TENANT_HEAD_BYTES, {}).get("series", []):
        t = s.get("labels", {}).get("tenant")
        if t is not None and s.get("value") is not None:
            head_bytes[t] = s["value"]
    latency: Dict[str, Dict[str, Any]] = {}
    for s in last.get(sm.TENANT_REQUEST_SECONDS, {}).get("series", []):
        t = s.get("labels", {}).get("tenant")
        if t is None or not s.get("count"):
            continue
        row = latency.get(t)
        if row is None:
            latency[t] = {
                "bounds": list(s["bounds"]),
                "bucket_counts": list(s["bucket_counts"]),
                "count": s["count"],
                "sum": s["sum"],
                "min": s["min"],
                "max": s["max"],
            }
        else:
            row["bucket_counts"] = [
                a + b for a, b in
                zip(row["bucket_counts"], s["bucket_counts"])
            ]
            row["count"] += s["count"]
            row["sum"] += s["sum"]
            for k, pick in (("min", min), ("max", max)):
                if s[k] is not None:
                    row[k] = (
                        s[k] if row[k] is None else pick(row[k], s[k])
                    )
    latency_ms = {
        t: {
            "count": row["count"],
            "mean_ms": round(1e3 * row["sum"] / row["count"], 3),
            "p99_ms": round(
                1e3 * percentile_from_buckets(row, 99.0), 3
            ),
        }
        for t, row in latency.items()
    }
    try:
        from mgproto_tpu.online import metrics as om  # jax-free

        drift_breaches = _series_by_label(
            last, om.DRIFT_BREACHES, "tenant"
        )
    except Exception:
        drift_breaches = {}
    return {
        "mounted": mounted,
        "mount_total": mount_total,
        "unmount_total": _series_value(last, sm.TENANT_UNMOUNTS),
        "requests_by_tenant": requests,
        "outcomes_by_tenant": _tenant_nested(
            last, sm.TENANT_REQUESTS, "outcome"
        ),
        "shed_by_tenant": _tenant_nested(last, sm.TENANT_SHED, "reason"),
        "swaps_by_tenant": _tenant_nested(last, sm.TENANT_SWAPS, "result"),
        "head_bytes_by_tenant": head_bytes,
        "latency_by_tenant": latency_ms,
        "drift_breaches_by_tenant": drift_breaches,
    }


def _drift_section(last: Dict) -> Optional[Dict[str, Any]]:
    """Online-learning drift story (ISSUE 11): p(x) sketch divergence,
    per-class bank shift top-k, captures by outcome, consolidation +
    republish counts. Follows the resilience-section convention: the
    family is pre-registered by every TelemetrySession, so current runs
    always render it (all zeros = "no drift observed", which an operator
    should see, not infer); None only for pre-online telemetry dirs whose
    snapshots predate the family."""
    from mgproto_tpu.online import metrics as om  # jax-free

    if not any(
        name in last for name in om.ALL_COUNTERS + om.ALL_GAUGES
    ):
        return None
    # per-class shift top-k from the labeled gauge series
    shifts = []
    for s in last.get(om.DRIFT_CLASS_SHIFT, {}).get("series", []):
        cls = s.get("labels", {}).get("class")
        if cls is not None and s.get("value") is not None:
            shifts.append((cls, s["value"]))
    shifts.sort(key=lambda kv: -kv[1])
    section: Dict[str, Any] = {
        "px_divergence": _series_value(last, om.DRIFT_PX_DIVERGENCE),
        "mean_shift_max": _series_value(last, om.DRIFT_SHIFT_MAX),
        "cov_shift_max": _series_value(last, om.DRIFT_COV_SHIFT_MAX),
        "class_shift_topk": {cls: v for cls, v in shifts[:5]},
        "breaches_by_signal": _series_by_label(
            last, om.DRIFT_BREACHES, "signal"
        ),
        "captures_by_outcome": _series_by_label(
            last, om.CAPTURED, "outcome"
        ),
        "capture_evicted": _series_value(last, om.CAPTURE_EVICTED),
        "staged_samples": _series_value(last, om.STAGED),
        "consolidations_by_result": _series_by_label(
            last, om.CONSOLIDATIONS, "result"
        ),
        "consolidated_samples": _series_value(
            last, om.CONSOLIDATED_SAMPLES
        ),
        "class_additions": _series_value(last, om.CLASS_ADDITIONS),
        "active_classes": _series_value(last, om.ACTIVE_CLASSES),
        "republish_by_result": _series_by_label(
            last, om.REPUBLISH, "result"
        ),
    }
    return section


def _trust_section(last: Dict, d: str) -> Optional[Dict[str, Any]]:
    """Trust-verification story (ISSUE 15): matrix cells evaluated,
    per-pair AUROC, per-cell abstention/answered-accuracy extremes,
    calibration drift on the served sketch, sharded interpretability
    metric values, and the newest trust_report*.json's verdict tally.
    Present whenever the trust_* family is in the snapshot (pre-registered
    — explicit zeros mean "nothing verified this run", which an operator
    should see); None only for pre-trust telemetry dirs."""
    from mgproto_tpu.trust import metrics as tm  # jax-free

    if not any(
        name in last for name in tm.ALL_COUNTERS + tm.ALL_GAUGES
    ):
        return None
    aurocs = _series_by_label(last, tm.PAIR_AUROC, "pair")
    abst = _series_by_label(last, tm.ABSTENTION_RATE, "cell")
    acc = _series_by_label(last, tm.ANSWERED_ACCURACY, "cell")
    section: Dict[str, Any] = {
        "cells_by_kind": _series_by_label(last, tm.MATRIX_CELLS, "kind"),
        "pair_auroc": aurocs,
        "min_pair_auroc": min(aurocs.values()) if aurocs else None,
        "max_abstention_rate": max(abst.values()) if abst else None,
        "min_answered_accuracy": min(acc.values()) if acc else None,
        "px_divergence": _series_value(last, tm.PX_DIVERGENCE),
        "verdicts": _series_by_label(last, tm.VERDICTS, "result"),
        "interp_consistency": _series_value(last, tm.INTERP_CONSISTENCY),
        "interp_stability": _series_value(last, tm.INTERP_STABILITY),
        "interp_purity": _series_value(last, tm.INTERP_PURITY),
    }
    # the newest trust report living beside the metrics, reduced to its
    # verdict line (full rows stay in the report file / check --trust)
    import glob as _glob

    reports = sorted(
        _glob.glob(os.path.join(d, "trust_report*.json")),
        key=os.path.getmtime,
    )
    if reports:
        try:
            with open(reports[-1]) as f:
                rep = json.load(f)
        except (OSError, ValueError):
            rep = None
        if rep and rep.get("trust_report"):
            gates = rep.get("gates") or {}
            section["report"] = os.path.basename(reports[-1])
            section["report_gates"] = {
                "checked": gates.get("checked"),
                "failed": gates.get("failed"),
                "ok": gates.get("ok"),
            }
    return section


def summarize(telemetry_dir: str) -> Dict[str, Any]:
    """The whole summary as one JSON-able dict."""
    d = resolve_dir(telemetry_dir)
    snapshots = _read_jsonl(os.path.join(d, METRICS_FILE))
    health = _read_jsonl(os.path.join(d, HEALTH_FILE))
    last = snapshots[-1].get("metrics", {}) if snapshots else {}

    summary: Dict[str, Any] = {
        "telemetry_dir": os.path.abspath(d),
        "snapshots": len(snapshots),
        "artifacts": {
            name: os.path.isfile(os.path.join(d, name))
            for name in (METRICS_FILE, HEALTH_FILE, TRACE_FILE, PROM_FILE)
        },
    }

    steps: Dict[str, Any] = {
        "steps_total": _series_value(last, "steps_total"),
        "images_total": _series_value(last, "images_total"),
        "step_time_ema_seconds": _series_value(last, "step_time_ema_seconds"),
        "images_per_sec": _series_value(last, "images_per_sec"),
        "epoch_images_per_sec_global": _series_value(
            last, "epoch_images_per_sec_global"
        ),
        "host_transfer_bytes_total": _series_value(
            last, "host_transfer_bytes_total"
        ),
    }
    hist = _hist_series(last, "step_time_seconds")
    if hist:
        steps["step_time_mean_seconds"] = (
            hist["sum"] / hist["count"] if hist["count"] else None
        )
        for p in STEP_PERCENTILES:
            steps[f"step_time_p{p:g}_seconds"] = percentile_from_buckets(
                hist, p
            )
        steps["step_time_max_seconds"] = hist["max"]
    summary["steps"] = steps

    summary["recompiles"] = {
        "jit_recompiles_total": _series_value(last, "jit_recompiles_total"),
        "jit_cache_size": _series_value(last, "jit_cache_size"),
    }

    # EM fast path (compact dirty-class slab, core/em.py): how wide EM ran,
    # whether it ever overflowed the compact width into the dense branch,
    # how much of the epoch the async bank pipeline actually overlapped,
    # and whether the auto-tuner rejected over-budget plans on the way in
    em = {
        EM_ACTIVE_GAUGE: _series_value(last, EM_ACTIVE_GAUGE),
        EM_FALLBACK_COUNTER: _series_value(last, EM_FALLBACK_COUNTER),
        BANK_OVERLAP_GAUGE: _series_value(last, BANK_OVERLAP_GAUGE),
        AUTOTUNE_REJECTED_COUNTER: _series_value(
            last, AUTOTUNE_REJECTED_COUNTER
        ),
    }
    if any(v is not None for v in em.values()):
        summary["em"] = em

    # input pipeline (ISSUE 5 fast path): was the run input-bound, and did
    # the shm batch assembly / u8 wire carry it (wire dtype is in meta)
    data = {
        DATA_WAIT_GAUGE: _series_value(last, DATA_WAIT_GAUGE),
        DATA_SHM_SLABS_GAUGE: _series_value(last, DATA_SHM_SLABS_GAUGE),
        "host_transfer_bytes_total": _series_value(
            last, "host_transfer_bytes_total"
        ),
        "loader_sentinel_rows_total": _series_value(
            last, "loader_sentinel_rows_total"
        ),
    }
    if any(v is not None for v in data.values()):
        summary["data"] = data

    meta_path = os.path.join(d, META_FILE)
    if os.path.isfile(meta_path):
        try:
            with open(meta_path) as f:
                summary["meta"] = json.load(f)
        except ValueError:
            pass

    # perf: the newest stall-budget attribution report in the dir (written
    # by scripts/trace_report.py --out, or copied in beside the metrics) —
    # the bucket split and the byte-ranked fusion work list ride into the
    # one-pager next to the throughput they explain (ISSUE 12)
    perf = _perf_section(d)
    if perf is not None:
        summary["perf"] = perf

    # recovery events (resilience subsystem): retries, sentinel rows,
    # skipped non-finite steps, rollbacks, preemption saves, chaos faults
    from mgproto_tpu.resilience.metrics import ALL_COUNTERS

    resilience = {
        name: _series_value(last, name)
        for name in ALL_COUNTERS
    }
    # fleet health (ISSUE 10): heartbeat decay is visible here BEFORE a
    # barrier timeout kills the run, next to the skew/straggler story
    resilience[HEARTBEAT_AGE_GAUGE] = _series_value(last, HEARTBEAT_AGE_GAUGE)
    resilience[SKEW_GAUGE] = _series_value(last, SKEW_GAUGE)
    resilience[STRAGGLER_COUNTER] = _series_value(last, STRAGGLER_COUNTER)
    if any(v is not None for v in resilience.values()):
        summary["resilience"] = resilience

    serving = _serving_section(last)
    if serving is not None:
        summary["serving"] = serving

    autoscale = _autoscale_section(last)
    if autoscale is not None:
        summary["autoscale"] = autoscale

    tenants = _tenants_section(last)
    if tenants is not None:
        summary["tenants"] = tenants

    drift = _drift_section(last)
    if drift is not None:
        summary["drift"] = drift

    trust = _trust_section(last, d)
    if trust is not None:
        summary["trust"] = trust

    if health:
        traj = {}
        for key in HEALTH_TRAJECTORY_KEYS:
            vals = [r[key] for r in health if key in r]
            if vals:
                traj[key] = {"first": vals[0], "last": vals[-1]}
        summary["health"] = {
            "records": len(health),
            "first_epoch": health[0].get("epoch"),
            "last_epoch": health[-1].get("epoch"),
            "trajectory": traj,
            "last": {
                k: v
                for k, v in health[-1].items()
                if isinstance(v, (int, float)) and k not in ("time", "epoch")
            },
        }

    trace_path = os.path.join(d, TRACE_FILE)
    if os.path.isfile(trace_path):
        try:
            with open(trace_path) as f:
                events = json.load(f).get("traceEvents", [])
        except ValueError:
            events = None
        if events is not None:
            per_name: Dict[str, Dict[str, float]] = {}
            for e in events:
                s = per_name.setdefault(
                    e.get("name", "?"), {"count": 0, "total_s": 0.0}
                )
                s["count"] += 1
                s["total_s"] += e.get("dur", 0.0) / 1e6
            summary["spans"] = {
                name: {"count": s["count"], "total_s": round(s["total_s"], 4)}
                for name, s in sorted(
                    per_name.items(), key=lambda kv: -kv[1]["total_s"]
                )
            }
    return summary


def _perf_section(d: str) -> Optional[Dict[str, Any]]:
    """The newest `stall_report*.json` in the telemetry dir, reduced to the
    summarize one-pager: bucket fractions, MFU line items, byte source and
    the top byte movers (full rows stay in the report file / --json)."""
    import glob as _glob

    candidates = sorted(
        _glob.glob(os.path.join(d, "stall_report*.json")),
        key=os.path.getmtime,
    )
    if not candidates:
        return None
    path = candidates[-1]
    try:
        with open(path) as f:
            rep = json.load(f)
    except (OSError, ValueError):
        return None
    if not rep.get("stall_report"):
        return None
    out: Dict[str, Any] = {
        "stall_report": os.path.basename(path),
        "source": rep.get("source"),
        "byte_source": rep.get("byte_source"),
        "compute_dtype": rep.get("compute_dtype"),
        "step_time_s": rep.get("step_time_s"),
        "measured_mfu": rep.get("measured_mfu"),
        "attainable_mfu": rep.get("attainable_mfu"),
        "bytes_accessed": rep.get("bytes_accessed"),
    }
    for name, b in (rep.get("buckets") or {}).items():
        out[f"{name}_fraction"] = (b or {}).get("fraction")
    movers = (rep.get("top_byte_movers") or {}).get("rows") or []
    out["top_byte_movers"] = [
        {
            "name": r.get("name"),
            "bucket": r.get("bucket"),
            "bytes_accessed": r.get("bytes_accessed"),
            "bytes_fraction": r.get("bytes_fraction"),
        }
        for r in movers[:5]
    ]
    return out


def _fmt_gb(v: Any) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    return f"{v / 1e9:.2f}GB"


def _fmt_autotune(v: Dict[str, Any]) -> str:
    """One line for the meta table: the chosen plan, its predicted peak vs
    the budget, and the rejection count (full record stays in --json)."""
    plan = v.get("plan") or {}
    per_chip = ""
    if plan.get("bank_bytes_per_chip") is not None:
        per_chip = (
            f" bank/chip={_fmt_gb(plan.get('bank_bytes_per_chip'))}"
            f" opt/chip={_fmt_gb(plan.get('opt_bytes_per_chip'))}"
        )
    return (
        f"plan={plan.get('name', 'none')} "
        f"peak={_fmt_gb(plan.get('peak_bytes'))} "
        f"budget={_fmt_gb(v.get('budget_bytes'))} "
        f"margin={v.get('margin')} "
        f"rejected={v.get('rejected')}" + per_chip
    )


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        if v != 0 and (abs(v) < 1e-3 or abs(v) >= 1e6):
            return f"{v:.3e}"
        return f"{v:.4f}".rstrip("0").rstrip(".")
    return str(v)


def render_table(summary: Dict[str, Any]) -> str:
    rows: List = []

    def section(title: str):
        rows.append(None)
        rows.append((title, ""))

    rows.append(("telemetry dir", summary["telemetry_dir"]))
    rows.append(("snapshots", summary["snapshots"]))
    rows.append((
        "artifacts",
        " ".join(
            f"{n}{'' if ok else '(missing)'}"
            for n, ok in summary["artifacts"].items()
        ),
    ))

    section("steps")
    for k, v in summary.get("steps", {}).items():
        rows.append((k, v))
    section("recompiles")
    for k, v in summary.get("recompiles", {}).items():
        rows.append((k, v))
    if "em" in summary:
        section("em (compact dirty-class fast path)")
        for k, v in summary["em"].items():
            rows.append((k, v))
    if "data" in summary:
        section("data (input pipeline)")
        for k, v in summary["data"].items():
            rows.append((k, v))
    if "meta" in summary:
        section("meta")
        for k, v in sorted(summary["meta"].items()):
            if k == "autotune" and isinstance(v, dict):
                v = _fmt_autotune(v)
            rows.append((k, v))
    if "perf" in summary:
        section("perf (stall attribution + byte-ranked fusion targets)")
        for k, v in summary["perf"].items():
            if k == "top_byte_movers":
                for i, r in enumerate(v):
                    frac = r.get("bytes_fraction")
                    rows.append((
                        f"byte_mover_{i + 1}",
                        f"{r.get('name')} "
                        f"[{_fmt_gb(r.get('bytes_accessed'))}"
                        + (f", {frac:.1%} of step bytes]"
                           if isinstance(frac, float) else "]"),
                    ))
            else:
                rows.append((k, v))
    if "resilience" in summary:
        section("resilience (recovery events)")
        for k, v in summary["resilience"].items():
            rows.append((k, v))
    if "drift" in summary:
        section("drift (online learning)")
        for k, v in summary["drift"].items():
            if isinstance(v, dict):
                v = " ".join(
                    f"{kk}={_fmt(vv)}" for kk, vv in sorted(v.items())
                ) or "-"
            rows.append((k, v))
    if "trust" in summary:
        section("trust (robustness matrix + sharded interpretability)")
        for k, v in summary["trust"].items():
            if isinstance(v, dict):
                v = " ".join(
                    f"{kk}={_fmt(vv)}" for kk, vv in sorted(v.items())
                ) or "-"
            rows.append((k, v))
    if "autoscale" in summary:
        section("autoscale (elastic serving + AOT cache)")
        for k, v in summary["autoscale"].items():
            if isinstance(v, dict):
                v = " ".join(
                    f"{kk}={_fmt(vv)}" for kk, vv in sorted(v.items())
                ) or "-"
            rows.append((k, v))
    if "serving" in summary:
        section("serving")
        for k, v in summary["serving"].items():
            if isinstance(v, dict):
                parts = []
                for kk, vv in sorted(v.items()):
                    if isinstance(vv, dict):  # e.g. stage_seconds per stage
                        inner = ",".join(
                            f"{ik}={_fmt(iv)}" for ik, iv in sorted(vv.items())
                        )
                        parts.append(f"{kk}({inner})")
                    else:
                        parts.append(f"{kk}={_fmt(vv)}")
                v = " ".join(parts) or "-"
            rows.append((k, v))
    if "health" in summary:
        h = summary["health"]
        section(
            f"model health ({h['records']} records, epochs "
            f"{h.get('first_epoch')}..{h.get('last_epoch')})"
        )
        for k, t in h["trajectory"].items():
            rows.append((k, f"{_fmt(t['first'])} -> {_fmt(t['last'])}"))
        for k, v in h["last"].items():
            if k not in h["trajectory"]:
                rows.append((k, v))
    if "spans" in summary:
        section("tracing spans (total wall seconds)")
        for name, s in list(summary["spans"].items())[:12]:
            rows.append((name, f"{s['total_s']} ({s['count']}x)"))

    width = max(len(str(r[0])) for r in rows if r is not None)
    lines = []
    for r in rows:
        if r is None:
            lines.append("")
        else:
            k, v = r
            lines.append(f"{str(k):<{width}}  {_fmt(v)}" if v != "" else str(k))
    return "\n".join(lines)


# ----------------------------------------------------------------- fleet view
# `mgproto-telemetry fleet <dir>`: the pod-scale counterpart of summarize.
# Host 0 writes the canonical metrics.jsonl; every other process writes a
# `.h<pid>` sidecar into the SAME (shared-FS) telemetry dir. The fleet view
# joins them into one per-host table plus the aggregates ROADMAP item 1's
# weak-scaling runs are read through: who is slowest, how skewed are
# arrivals, how much barrier wait each host pays, and whether per-chip
# allgather traffic stays flat as the fleet grows.

def _host_metric_files(d: str) -> Dict[int, str]:
    """{host index: metrics stream path}: the unsuffixed host-0 file plus
    every `metrics.jsonl.h<pid>` sidecar."""
    out: Dict[int, str] = {}
    base = os.path.join(d, METRICS_FILE)
    if os.path.isfile(base):
        out[0] = base
    prefix = METRICS_FILE + ".h"
    try:
        names = os.listdir(d)
    except OSError:
        names = []
    for name in names:
        if name.startswith(prefix) and name[len(prefix):].isdigit():
            out[int(name[len(prefix):])] = os.path.join(d, name)
    return out


def _flightrec_dumps_by_host(d: str) -> Dict[int, List[str]]:
    """Flight-recorder dump files grouped by host (`flightrec_*.jsonl` is
    host 0's; `flightrec_*.h<pid>.jsonl` a sidecar's)."""
    out: Dict[int, List[str]] = {}
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("flightrec_") and name.endswith(".jsonl")):
            continue
        stem, host = name[: -len(".jsonl")], 0
        if ".h" in stem:
            tail = stem.rsplit(".h", 1)[1]
            if tail.isdigit():
                host = int(tail)
        out.setdefault(host, []).append(name)
    return out


def _hist_totals(last: Dict, name: str):
    """(sum_seconds, count) of a histogram merged across label sets."""
    h = _hist_series(last, name)
    if not h or not h["count"]:
        return 0.0, 0
    return float(h["sum"]), int(h["count"])


def _host_row(last: Dict) -> Dict[str, Any]:
    """One host's line of the fleet table, from its latest snapshot."""
    row: Dict[str, Any] = {
        "images_per_sec": _series_value(last, "images_per_sec"),
        "step_time_ema_seconds": _series_value(last, "step_time_ema_seconds"),
        "loader_wait_fraction": _series_value(last, DATA_WAIT_GAUGE),
        "host_step_skew_fraction": _series_value(last, SKEW_GAUGE),
        "peer_heartbeat_age_seconds": _series_value(
            last, HEARTBEAT_AGE_GAUGE
        ),
        "straggler_suspected": _series_value(last, STRAGGLER_COUNTER),
        "restarts": (
            (_series_value(last, "loader_worker_restarts_total") or 0.0)
            + (_series_value(last, "train_rollbacks_total") or 0.0)
        ),
    }
    hist = _hist_series(last, "step_time_seconds")
    step_wall = 0.0
    if hist and hist["count"]:
        row["step_time_p99_seconds"] = percentile_from_buckets(hist, 99.0)
        step_wall = float(hist["sum"])
    barrier_s, barrier_n = _hist_totals(last, BARRIER_WAIT_HIST)
    collective_s, _ = _hist_totals(last, COLLECTIVE_WAIT_HIST)
    row["barrier_wait_seconds_sum"] = barrier_s
    row["barrier_waits"] = barrier_n
    row["collective_wait_seconds_sum"] = collective_s
    # fraction of stepped wall time this host spent waiting at barriers —
    # high on the FAST hosts when one peer straggles
    row["barrier_wait_fraction"] = (
        min(1.0, barrier_s / step_wall) if step_wall > 0 else 0.0
    )
    # barrier-ADJUSTED step time ("self time"): a straggler's peers absorb
    # its delay as barrier wait inside their own step wall, so the raw
    # step EMAs of a skewed fleet converge to the same number — subtracting
    # each host's mean barrier wait per step is what actually ranks who is
    # slow (the slowest_host aggregate sorts by this)
    ema = row["step_time_ema_seconds"]
    steps = _series_value(last, "steps_total")
    if isinstance(ema, (int, float)):
        per_step_wait = barrier_s / steps if steps else 0.0
        row["self_step_time_seconds"] = max(float(ema) - per_step_wait, 0.0)
    ag_bytes = _series_value(last, ALLGATHER_BYTES_COUNTER) or 0.0
    devices = _series_value(last, HOST_DEVICES_GAUGE) or 1.0
    row["allgather_bytes_total"] = ag_bytes
    row["allgather_bytes_by_collective"] = _series_by_label(
        last, ALLGATHER_BYTES_COUNTER, "collective"
    )
    row["allgather_bytes_per_chip"] = ag_bytes / max(devices, 1.0)
    # weak-scaling per-chip memory (ISSUE 14): the planner-measured bank /
    # optimizer bytes one chip holds, next to the per-chip traffic above
    row["bank_bytes_per_chip"] = _series_value(last, BANK_BYTES_GAUGE)
    row["opt_bytes_per_chip"] = _series_value(last, OPT_BYTES_GAUGE)
    return row


def fleet_summary(telemetry_dir: str) -> Dict[str, Any]:
    """Per-host rows + fleet aggregates as one JSON-able dict."""
    d = resolve_dir(telemetry_dir)
    files = _host_metric_files(d)
    dumps = _flightrec_dumps_by_host(d)
    hosts: Dict[str, Dict[str, Any]] = {}
    for pid in sorted(files):
        snapshots = _read_jsonl(files[pid])
        last = snapshots[-1].get("metrics", {}) if snapshots else {}
        row = _host_row(last)
        row["snapshots"] = len(snapshots)
        row["flightrec_dumps"] = dumps.get(pid, [])
        hosts[str(pid)] = row

    def _vals(key):
        return [
            (pid, row[key]) for pid, row in hosts.items()
            if isinstance(row.get(key), (int, float))
        ]

    fleet: Dict[str, Any] = {"hosts": len(hosts)}
    emas = _vals("step_time_ema_seconds")
    if emas:
        fleet["slowest_step_time_ema_seconds"] = max(
            v for _, v in emas
        )
        fleet["fastest_step_time_ema_seconds"] = min(v for _, v in emas)
    # rank slowness by barrier-adjusted self time (see _host_row): the raw
    # EMAs of a skewed fleet all include waiting for the straggler
    selfs = _vals("self_step_time_seconds") or emas
    if selfs:
        fleet["slowest_host"] = int(max(selfs, key=lambda kv: kv[1])[0])
    for key, out in (
        ("host_step_skew_fraction", "max_skew_fraction"),
        ("barrier_wait_fraction", "max_barrier_wait_fraction"),
        ("peer_heartbeat_age_seconds", "max_heartbeat_age_seconds"),
        ("allgather_bytes_per_chip", "allgather_bytes_per_chip"),
    ):
        vals = [v for _, v in _vals(key)]
        if vals:
            fleet[out] = max(vals)
    straggler = sum(v for _, v in _vals("straggler_suspected"))
    fleet["straggler_suspected_total"] = straggler
    fleet["flightrec_dumps"] = sum(len(v) for v in dumps.values())
    return {
        "fleet_summary": True,
        "telemetry_dir": os.path.abspath(d),
        "hosts": hosts,
        "fleet": fleet,
    }


_FLEET_COLUMNS = (
    ("img/s", "images_per_sec"),
    ("step_ema", "step_time_ema_seconds"),
    ("step_p99", "step_time_p99_seconds"),
    ("loader_wait", "loader_wait_fraction"),
    ("barrier_wait", "barrier_wait_fraction"),
    ("skew", "host_step_skew_fraction"),
    ("hb_age", "peer_heartbeat_age_seconds"),
    ("restarts", "restarts"),
    ("straggler", "straggler_suspected"),
    ("ag_B/chip", "allgather_bytes_per_chip"),
    ("bank_B/chip", "bank_bytes_per_chip"),
    ("opt_B/chip", "opt_bytes_per_chip"),
)


def render_fleet_table(fs: Dict[str, Any]) -> str:
    lines = [f"telemetry dir  {fs['telemetry_dir']}"]
    header = ["host"] + [label for label, _ in _FLEET_COLUMNS] + ["dumps"]
    rows = [header]
    for pid in sorted(fs["hosts"], key=int):
        row = fs["hosts"][pid]
        rows.append(
            [pid]
            + [_fmt(row.get(key)) for _, key in _FLEET_COLUMNS]
            + [str(len(row.get("flightrec_dumps", [])))]
        )
    widths = [
        max(len(str(r[i])) for r in rows) for i in range(len(header))
    ]
    for r in rows:
        lines.append("  ".join(
            f"{str(v):>{w}}" for v, w in zip(r, widths)
        ))
    lines.append("")
    for k, v in sorted(fs["fleet"].items()):
        lines.append(f"fleet.{k:<32}  {_fmt(v)}")
    return "\n".join(lines)


def fleet_main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="mgproto-telemetry fleet",
        description="Merge host 0 + per-host telemetry sidecars into a "
                    "per-host table with fleet aggregates",
    )
    p.add_argument("dir", help="telemetry dir (or a run dir containing "
                               "telemetry/)")
    p.add_argument("--json", action="store_true",
                   help="emit the fleet summary as one JSON object")
    args = p.parse_args(argv)
    if not os.path.isdir(args.dir):
        raise SystemExit(f"not a directory: {args.dir}")
    fs = fleet_summary(args.dir)
    if not fs["hosts"]:
        raise SystemExit(
            f"no metrics.jsonl (or .h<pid> sidecars) under "
            f"{resolve_dir(args.dir)}"
        )
    if args.json:
        print(json.dumps(fs, indent=2))
    else:
        print(render_fleet_table(fs))
    return 0


# ---------------------------------------------------------- regression gate
# `mgproto-telemetry check <dir> --baseline FILE`: compare a run's
# summarized metrics against a committed baseline with tolerance bands and
# exit nonzero on regression — the observability loop's enforcement arm
# (BENCH/evidence numbers become CI gates instead of trivia). The baseline
# is generated from a known-good run (`--write-baseline`) and committed;
# its entries carry their own direction + tolerance so an operator can
# widen a band with a one-line edit, reviewed like any other change.

# default gate set for --write-baseline: (dotted summary key, direction,
# relative tolerance). direction 'higher' = regression when the new value
# drops below baseline*(1-tol); 'lower' = regression when it rises above
# baseline*(1+tol). Entries whose key is absent from the summary are
# skipped at write time; at CHECK time a missing key fails (a metric that
# vanished is itself a regression of the telemetry contract).
DEFAULT_GATES = (
    ("steps.images_per_sec", "higher", 0.20),
    ("steps.step_time_ema_seconds", "lower", 0.25),
    ("steps.step_time_p99_seconds", "lower", 0.30),
    ("recompiles.jit_recompiles_total", "lower", 0.0),
    ("serving.request_p99_seconds", "lower", 0.30),
    ("serving.breaker_open_time_fraction", "lower", 0.0),
)

# fleet gate set (ISSUE 10; written by `--write-baseline --fleet-gates`,
# committed as evidence/fleet_baseline.json from the two-process dryrun
# drill): entries are 4-tuples with an ABSOLUTE band because the gated
# values are machine-independent fractions/byte counts, and a clean
# baseline value near zero makes a purely relative band meaningless. A
# straggling host blows the skew/barrier-wait gates; per-chip allgather
# traffic must stay flat-within-tolerance as the fleet grows (the
# weak-scaling contract: 'equal', not 'lower' — silently LOSING traffic
# would mean the gather stopped covering the bank).
FLEET_GATES = (
    ("fleet.max_skew_fraction", "lower", 0.0, 0.35),
    ("fleet.max_barrier_wait_fraction", "lower", 0.0, 0.60),
    # abs_tol must stay well under the baseline VALUE or the equal band
    # could never catch traffic dropping to zero (it absorbs jitter near
    # zero, nothing more; at real scale the relative band dominates)
    ("fleet.allgather_bytes_per_chip", "equal", 0.25, 64.0),
)


def drift_drill_gates(record: Dict[str, Any]) -> Dict[str, Any]:
    """Gate a committed drift-drill record (evidence/drift_drill.json).

    The drill's acceptance criteria, re-derived from the record's RAW
    numbers (never from stored verdict booleans, which would gate nothing):
    the injected shift was detected via p(x) BEFORE the correction landed,
    the correction committed through the blue/green swap with zero dropped
    requests and zero steady-state recompiles (serving AND consolidation),
    poisoned traffic never became capture-eligible, and the served-accuracy
    curve actually dipped under drift and recovered after republish."""
    rows: List[Dict[str, Any]] = []

    def gate(key: str, ok: bool, why: str = "") -> None:
        rows.append({"key": key, "ok": bool(ok),
                     "why": "" if ok else why, "baseline": None,
                     "value": None, "direction": "drill"})

    o = record.get("online") or {}
    det = o.get("detection") or {}
    fb = det.get("first_breach") or None
    gate("drill.record", bool(o), "record has no 'online' section — not a "
                                  "drift-drill result")
    gate("drill.detected_via_px",
         bool(fb) and "px" in (fb.get("signals") or ()),
         "no p(x) drift breach recorded")
    commit_t = det.get("first_commit_t")
    gate("drill.detected_before_correction",
         bool(fb) and commit_t is not None
         and fb.get("t") is not None and fb["t"] <= commit_t,
         f"breach t={fb.get('t') if fb else None} vs commit t={commit_t}")
    committed = (o.get("republish_by_result") or {}).get("committed", 0)
    gate("drill.republish_committed", committed >= 1,
         "no republish committed through the blue/green swap")
    overall = record.get("overall") or {}
    gate("drill.zero_dropped", overall.get("zero_dropped") is True,
         "storm dropped requests")
    gate("drill.zero_steady_recompiles",
         record.get("steady_state_recompiles") == 0,
         f"serving recompiled in steady state: "
         f"{record.get('steady_state_recompiles')}")
    cons = o.get("consolidation") or {}
    gate("drill.consolidation_compiled_once",
         cons.get("steady_recompiles") == 0
         and 0 < (cons.get("compiles") or 0) <= 1,
         f"consolidation program compiles={cons.get('compiles')} "
         f"steady={cons.get('steady_recompiles')}")
    poison = o.get("poison") or {}
    gate("drill.poison_never_capture_eligible",
         (poison.get("capture_eligible") or 0) == 0,
         f"{poison.get('capture_eligible')} poisoned requests cleared the "
         "capture gate")
    windows = o.get("accuracy_windows") or []
    pre = [w for w in windows
           if (w.get("drifted_fraction") or 0) == 0
           and w.get("served_accuracy") is not None]
    drifted = [w for w in windows
               if (w.get("drifted_fraction") or 0) > 0.5
               and w.get("served_accuracy") is not None]
    if pre and len(drifted) >= 2:
        pre_acc = sum(w["served_accuracy"] for w in pre) / len(pre)
        dip = min(w["served_accuracy"] for w in drifted)
        post_acc = sum(
            w["served_accuracy"] for w in drifted[-2:]
        ) / 2.0
        detail = (f"pre={pre_acc:.3f} dip={dip:.3f} "
                  f"post={post_acc:.3f}")
        gate("drill.accuracy_dipped_under_drift",
             dip <= pre_acc - 0.05, detail)
        gate("drill.accuracy_recovered_after_republish",
             post_acc >= pre_acc - 0.15 and post_acc >= dip + 0.1,
             detail)
    else:
        gate("drill.accuracy_curves_present", False,
             "missing pre-drift/drifted accuracy windows")
    return {"ok": all(r["ok"] for r in rows), "checked": len(rows),
            "failed": sum(not r["ok"] for r in rows), "rows": rows}


def autoscale_gates(record: Dict[str, Any]) -> Dict[str, Any]:
    """Gate a committed autoscale load-test record (evidence/
    autoscale_baseline.json) — the elastic-serving acceptance criteria
    (ISSUE 13), re-derived from the record's RAW numbers:

      * the ramp past min-fleet capacity triggered scale-OUT (>= 1 up
        event, peak above the starting size, within [min, max]);
      * scale-up warmups went through the AOT cache (every post-cold
        warmup a hit, zero rejects) — cheap by construction, verified;
      * p99 stayed in the flat band: every phase's p99 under the request
        deadline, and the post-ramp calm phase within 1.5x of the
        pre-ramp calm phase (the fleet scaled back down AND latency
        recovered);
      * shed rate stayed bounded through the overrun (<= 20% in the
        storm phase, zero in the calm phases);
      * scale-DOWN followed the ramp (a down event after the last up,
        final size back at min) with ZERO dropped requests and zero
        steady-state recompiles."""
    rows: List[Dict[str, Any]] = []

    def gate(key: str, ok: bool, why: str = "") -> None:
        rows.append({"key": key, "ok": bool(ok),
                     "why": "" if ok else why, "baseline": None,
                     "value": None, "direction": "autoscale"})

    a = record.get("autoscale") or {}
    gate("autoscale.record", bool(a),
         "record has no 'autoscale' section — not an autoscale drill")
    events = a.get("events") or []
    ups = [e for e in events if e.get("direction") == "up"]
    downs = [e for e in events if e.get("direction") == "down"]
    start = a.get("start_replicas") or 0
    peak = a.get("replicas_peak") or 0
    gate("autoscale.scaled_out",
         len(ups) >= 1 and peak > start,
         f"ups={len(ups)} peak={peak} start={start}")
    gate("autoscale.bounded",
         (a.get("min") or 0) <= (a.get("replicas_final") or 0)
         and peak <= (a.get("max") or 0),
         f"final={a.get('replicas_final')} peak={peak} "
         f"bounds=[{a.get('min')},{a.get('max')}]")
    last_up_t = max((e.get("t") or 0 for e in ups), default=None)
    gate("autoscale.scaled_down_after_ramp",
         len(downs) >= 1 and last_up_t is not None
         and all((e.get("t") or 0) > last_up_t for e in downs)
         and a.get("replicas_final") == a.get("min"),
         f"downs={len(downs)} final={a.get('replicas_final')} "
         f"min={a.get('min')}")
    aot = a.get("aot") or {}
    nb = len((record.get("config") or {}).get("buckets") or [])
    gate("autoscale.scale_up_via_cache",
         not aot.get("rejects")
         and (aot.get("hits") or 0) >= len(ups) * nb > 0,
         f"hits={aot.get('hits')} expected>={len(ups) * nb} "
         f"rejects={aot.get('rejects')}")
    overall = record.get("overall") or {}
    gate("autoscale.zero_dropped", overall.get("zero_dropped") is True,
         "storm dropped requests")
    gate("autoscale.zero_steady_recompiles",
         record.get("steady_state_recompiles") == 0,
         f"recompiled in steady state: "
         f"{record.get('steady_state_recompiles')}")
    phases = record.get("phases") or []
    deadline_ms = (record.get("config") or {}).get("deadline_ms")
    if len(phases) >= 3 and isinstance(deadline_ms, (int, float)):
        rps = [p.get("rps") or 0 for p in phases]
        storm_i = rps.index(max(rps))
        storm = phases[storm_i]
        calm_before, calm_after = phases[0], phases[-1]
        p99s = [p.get("p99_ms") for p in phases]
        gate("autoscale.p99_under_deadline",
             all(isinstance(v, (int, float)) and v <= deadline_ms
                 for v in p99s),
             f"phase p99s {p99s} vs deadline {deadline_ms}")
        b, after = calm_before.get("p99_ms"), calm_after.get("p99_ms")
        gate("autoscale.p99_recovered",
             isinstance(b, (int, float)) and isinstance(after, (int, float))
             and after <= 1.5 * b,
             f"calm-after p99 {after} vs 1.5x calm-before {b}")
        gate("autoscale.shed_bounded",
             (storm.get("shed_rate") or 0) <= 0.20
             and (calm_before.get("shed_rate") or 0) == 0
             and (calm_after.get("shed_rate") or 0) == 0,
             f"storm shed {storm.get('shed_rate')}, calm "
             f"{calm_before.get('shed_rate')}/{calm_after.get('shed_rate')}")
    else:
        gate("autoscale.phases_present", False,
             "needs >= 3 phases (calm, storm, calm) and a deadline_ms")
    return {"ok": all(r["ok"] for r in rows), "checked": len(rows),
            "failed": sum(not r["ok"] for r in rows), "rows": rows}


def tenant_gates(
    record: Dict[str, Any], quiet_p99_tol: float = 2.0
) -> Dict[str, Any]:
    """Gate a committed multi-tenant isolation record (`load_test.py
    --tenants N` -> evidence/tenant_baseline.json). Every verdict is
    RE-DERIVED from the raw per-tenant ledgers — never from a stored
    summary verdict, which would gate nothing:

      * the per-tenant ledger balances: each tenant's submitted count
        equals the sum of its outcomes, typed sheds equal the shed
        outcome, and the tenant ledgers together cover ALL traffic in
        the overall ledger (nothing untagged slipped past the plane);
      * the quota storm stayed in its lane: the storm tenant shed with
        the typed `tenant_quota` reason, every quiet tenant shed ZERO,
        answered everything, and its in-storm p99 stayed within
        `quiet_p99_tol` x its calm p99 (only tenants observed in BOTH
        windows are compared; a mid-storm mount has no calm baseline);
      * the sabotaged swap failed closed for the storm tenant ONLY —
        quiet tenant's swap committed with a new head fingerprint while
        the storm raged;
      * the mid-storm mount cost head bytes and ZERO trunk compiles /
        AOT misses (heads live outside executable identity);
      * poisoned traffic breached ONLY the storm tenant's drift monitor
        — quiet monitors stayed silent on the same trunk;
      * warmup compiled at most buckets x replicas executables and
        steady state recompiled ZERO."""
    rows: List[Dict[str, Any]] = []

    def gate(key: str, ok: bool, why: str = "") -> None:
        rows.append({"key": key, "ok": bool(ok),
                     "why": "" if ok else why, "baseline": None,
                     "value": None, "direction": "tenants"})

    t = record.get("tenants") or {}
    gate("tenants.record", bool(t),
         "record has no 'tenants' section — not a multi-tenant drill")
    per = t.get("per_tenant") or {}
    storm = t.get("storm_tenant")
    gate("tenants.multi",
         (t.get("count") or 0) >= 3 and storm in per,
         f"count={t.get('count')} storm_tenant={storm!r} "
         f"tenants={sorted(per)}")
    overall = record.get("overall") or {}
    gate("tenants.zero_dropped", overall.get("zero_dropped") is True,
         "drill dropped requests")

    bad_ledgers = []
    bad_sheds = []
    for name, row in sorted(per.items()):
        outcomes = row.get("outcomes") or {}
        if row.get("submitted") != sum(outcomes.values()):
            bad_ledgers.append(
                f"{name}: submitted={row.get('submitted')} "
                f"outcomes_sum={sum(outcomes.values())}")
        shed_typed = sum((row.get("shed_by_reason") or {}).values())
        if shed_typed != (outcomes.get("shed") or 0):
            bad_sheds.append(
                f"{name}: typed={shed_typed} "
                f"outcome={outcomes.get('shed') or 0}")
    gate("tenants.ledger_consistent", bool(per) and not bad_ledgers,
         "; ".join(bad_ledgers) or "no per-tenant rows")
    gate("tenants.shed_ledger_consistent", bool(per) and not bad_sheds,
         "; ".join(bad_sheds) or "no per-tenant rows")
    tenant_sum = sum(row.get("submitted") or 0 for row in per.values())
    gate("tenants.covers_all_traffic",
         bool(per) and tenant_sum == overall.get("submitted"),
         f"tenant ledgers sum {tenant_sum} vs overall "
         f"{overall.get('submitted')}")

    storm_row = per.get(storm) or {}
    quiet = {n: r for n, r in per.items() if n != storm}
    storm_sheds = storm_row.get("shed_by_reason") or {}
    gate("tenants.storm_quota_shed",
         (storm_sheds.get("tenant_quota") or 0) > 0,
         f"storm tenant shed_by_reason={storm_sheds} — quota never bound")
    noisy = [n for n, r in sorted(quiet.items())
             if sum((r.get("shed_by_reason") or {}).values())
             or (r.get("outcomes") or {}).get("shed")]
    gate("tenants.quiet_zero_shed", bool(quiet) and not noisy,
         f"quiet tenants shed: {noisy}" if noisy else "no quiet tenants")
    unanswered = [
        n for n, r in sorted(quiet.items())
        if set(r.get("outcomes") or {}) - {"predict", "abstain"}
    ]
    gate("tenants.quiet_all_answered", bool(quiet) and not unanswered,
         f"quiet tenants with non-answer outcomes: {unanswered}"
         if unanswered else "no quiet tenants")
    compared = []
    slow = []
    for name, row in sorted(quiet.items()):
        calm = (row.get("calm") or {}).get("p99_ms")
        in_storm = (row.get("storm") or {}).get("p99_ms")
        if not isinstance(calm, (int, float)) or not isinstance(
            in_storm, (int, float)
        ):
            continue  # mounted mid-storm: no calm baseline to hold flat
        compared.append(name)
        if in_storm > quiet_p99_tol * calm:
            slow.append(f"{name}: storm p99 {in_storm} vs calm {calm}")
    gate("tenants.quiet_p99_flat", bool(compared) and not slow,
         "; ".join(slow) if slow
         else "no quiet tenant observed in both calm and storm windows")

    swaps = t.get("swaps") or []
    storm_swaps = [s for s in swaps if s.get("tenant") == storm]
    quiet_swaps = [s for s in swaps if s.get("tenant") != storm]
    gate("tenants.bad_swap_fail_closed",
         bool(storm_swaps)
         and all(s.get("ok") is False and s.get("reason")
                 for s in storm_swaps),
         f"storm tenant swaps: {storm_swaps}")
    gate("tenants.good_swap_committed",
         any(s.get("ok") is True and s.get("reason") == "committed"
             and s.get("head_fingerprint") for s in quiet_swaps),
         f"quiet tenant swaps: {quiet_swaps}")

    mounts = t.get("mounts") or []
    mid_storm = [m for m in mounts if m.get("during_storm")]
    gate("tenants.mid_storm_mount", bool(mid_storm),
         "no tenant was mounted while the storm raged")
    compiled = [
        f"{m.get('tenant')}: trunk={m.get('trunk_compiles_delta')} "
        f"aot_misses={m.get('aot_misses_delta')}"
        for m in mounts
        if m.get("trunk_compiles_delta") != 0
        or m.get("aot_misses_delta") != 0
    ]
    gate("tenants.mount_zero_trunk_compiles",
         bool(mounts) and not compiled,
         "; ".join(compiled) or "no mounts recorded")
    costless = [m.get("tenant") for m in mounts
                if not (m.get("head_bytes") or 0) > 0]
    gate("tenants.mount_head_cost_measured",
         bool(mounts) and not costless,
         f"mounts without measured head bytes: {costless}"
         if costless else "no mounts recorded")

    gate("tenants.storm_drift_breached",
         (t.get("poison_injected") or 0) > 0
         and (storm_row.get("drift_breaches") or 0) > 0,
         f"poison_injected={t.get('poison_injected')} storm breaches="
         f"{storm_row.get('drift_breaches')}")
    leaked = [n for n, r in sorted(quiet.items())
              if r.get("drift_breaches")]
    gate("tenants.quiet_drift_silent", bool(quiet) and not leaked,
         f"quiet tenants breached drift: {leaked}"
         if leaked else "no quiet tenants")

    cfg = record.get("config") or {}
    budget = len(cfg.get("buckets") or []) * (cfg.get("replicas") or 0)
    warm = record.get("warmup_compiles")
    gate("tenants.warmup_bounded",
         isinstance(warm, int) and 0 < warm <= budget,
         f"warmup_compiles={warm} budget={budget}")
    gate("tenants.zero_steady_recompiles",
         record.get("steady_state_recompiles") == 0,
         f"recompiled in steady state: "
         f"{record.get('steady_state_recompiles')}")
    return {"ok": all(r["ok"] for r in rows), "checked": len(rows),
            "failed": sum(not r["ok"] for r in rows), "rows": rows}


def weakscale_gates(
    record: Dict[str, Any],
    shrink_min_at_2: float = 1.8,
    shrink_rel_tol: float = 0.10,
    flat_rel_tol: float = 0.25,
    planner_rel_tol: float = 0.05,
) -> Dict[str, Any]:
    """Gate a committed weak-scaling record (`bench.py --measure
    weakscale` -> evidence/weakscale_bench.json). Every verdict is
    RE-DERIVED from the raw per-chip entries — never from stored summary
    ratios, which would gate nothing:

      * bank/optimizer bytes per chip shrink ~1/model_axis: >=
        `shrink_min_at_2` at model=2 vs model=1, and within
        `shrink_rel_tol` of the ideal 1/chips at every point;
      * the planner's shape-math prediction (the telemetry gauges'
        provenance) matches the LIVE shard-shape measurement;
      * per-chip collective traffic is bounded per scaling family. No
        single collective op may be bank-sized (max_op < bank_bytes_per_
        chip x chips — THE leaked-bank-gather detector; the probe config
        keeps the bank dominant over activation row-gathers). GATHER-
        family bytes (all-gather/reduce-scatter/all-to-all) per chip per
        GLOBAL BATCH ROW must not grow with chips — the scoring path
        legitimately gathers each row to the class shards, so per-chip
        gather bytes scale with the global batch; what must NOT happen is
        growth beyond it (a state-sized gather sneaking in). ALL-REDUCE-
        family bytes (all-reduce/collective-permute, per-chip result
        bytes ~constant in N) are gated flat RAW. A single chip must
        show ZERO collective bytes;
      * modeled img/s/chip never DEGRADES: no point drops more than
        `flat_rel_tol` below the 1-chip point or below any earlier point
        on the curve (improvement is expected — per-chip state shrinks,
        so the bytes-bound roofline rises — and never gated against);
      * per-chip flops stay flat within `flat_rel_tol` of the 1-chip
        point (the weak-scaling premise: per-chip work constant).
    """
    rows: List[Dict[str, Any]] = []

    def gate(key, ok, why="", baseline_v=None, value=None):
        rows.append({"key": key, "ok": bool(ok), "why": "" if ok else why,
                     "baseline": baseline_v, "value": value,
                     "direction": "weakscale"})

    entries = {
        e.get("chips"): e for e in (record.get("entries") or [])
        if isinstance(e.get("chips"), int)
    }
    gate("weakscale.schema",
         record.get("metric") == "weakscale" and len(entries) >= 3
         and 1 in entries and 2 in entries,
         f"need metric=weakscale with >=3 entries incl. chips 1 and 2; "
         f"got {sorted(entries)}")
    if not (1 in entries and 2 in entries):
        return {"ok": False, "checked": len(rows),
                "failed": sum(not r["ok"] for r in rows), "rows": rows}
    base = entries[1]
    multi = [entries[c] for c in sorted(entries) if c > 1]

    for field, label in (("bank_bytes_per_chip", "bank"),
                         ("opt_bytes_per_chip", "opt")):
        b1, b2 = base.get(field), entries[2].get(field)
        # a missing/null field is a FAILED gate row, never a crash: the
        # ratio (and everything derived from b1) is only computed once
        # both ends verified numeric
        numeric = (
            isinstance(b1, (int, float)) and not isinstance(b1, bool)
            and isinstance(b2, (int, float)) and b2 > 0
        )
        ratio = b1 / b2 if numeric else None
        gate(f"weakscale.{label}_reduction_at_2",
             numeric and ratio >= shrink_min_at_2,
             f"{field}: {b1} -> {b2} is "
             + (f"{ratio:.2f}x" if ratio is not None else "not derivable")
             + f" < {shrink_min_at_2}x",
             baseline_v=b1, value=b2)
        ideal_ok = numeric and all(
            isinstance(e.get(field), (int, float))
            and e[field] <= (b1 / e["chips"]) * (1.0 + shrink_rel_tol)
            for e in multi
        )
        gate(f"weakscale.{label}_scales_inverse_chips", ideal_ok,
             f"{field} missing or exceeding ideal bytes/chips by > "
             f"{shrink_rel_tol:.0%} somewhere on the curve")

    planner_ok, planner_why = True, ""
    for e in entries.values():
        for field in ("bank_bytes_per_chip", "opt_bytes_per_chip"):
            live = e.get(field)
            pred = (e.get("planner") or {}).get(field)
            if not (isinstance(live, (int, float))
                    and isinstance(pred, (int, float))) or live <= 0:
                planner_ok, planner_why = False, f"{field} missing"
                break
            if abs(pred - live) > planner_rel_tol * live:
                planner_ok = False
                planner_why = (
                    f"chips={e['chips']} {field}: planner {pred} vs "
                    f"live shard shapes {live}"
                )
                break
    gate("weakscale.planner_matches_live_shards", planner_ok, planner_why)

    single_total = (
        (base.get("collective_bytes_per_chip_per_step") or {}).get("total")
    )
    gate("weakscale.single_chip_zero_collectives", single_total == 0,
         f"1 chip moved {single_total} collective B")
    # THE leaked-bank-gather detector: the largest single collective
    # result must stay below the FULL bank (bank_bytes_per_chip x chips,
    # both raw numbers from the same entry — a gathered bank's result IS
    # full-bank-sized). The probe config keeps the bank dominant, so
    # ordinary scoring row-gathers sit well under this bound.
    op_ok, op_why = True, ""
    for e in multi:
        cmax = (e.get("collective_bytes_per_chip_per_step") or {}).get(
            "max_op"
        )
        bank_pc = e.get("bank_bytes_per_chip")
        bank_full = (
            bank_pc * e["chips"]
            if isinstance(bank_pc, (int, float)) else 0
        )
        if not isinstance(cmax, (int, float)) or bank_full <= 0:
            op_ok, op_why = False, f"chips={e.get('chips')}: max_op missing"
            break
        if cmax >= bank_full:
            op_ok = False
            op_why = (
                f"chips={e['chips']}: a collective op moves {cmax} B >= "
                f"the {bank_full} B bank — a shard gathers another's bank"
            )
            break
    gate("weakscale.max_collective_op_below_bank", op_ok, op_why)
    per_row = [
        e["gather_bytes_per_chip_per_step"] / e["global_batch"]
        for e in multi
        if isinstance(e.get("gather_bytes_per_chip_per_step"), (int, float))
        and e.get("global_batch")
    ]
    row_ok = len(per_row) == len(multi) and all(
        r <= per_row[0] * (1.0 + flat_rel_tol) for r in per_row
    )
    gate("weakscale.gather_bytes_per_row_bounded", row_ok,
         f"gather B/chip per global row {['%.0f' % r for r in per_row]} "
         f"grows > {flat_rel_tol:.0%} past the first multi-chip point — "
         "per-chip gather traffic is outpacing the global problem (a "
         "state-sized gather crept in)",
         value=[round(r) for r in per_row])
    ar = [
        e.get("allreduce_bytes_per_chip_per_step") for e in multi
        if isinstance(e.get("allreduce_bytes_per_chip_per_step"),
                      (int, float))
    ]
    ar_ok = len(ar) == len(multi) and (
        max(ar) == 0
        or max(ar) - min(ar) <= flat_rel_tol * max(ar)
    )
    gate("weakscale.allreduce_bytes_per_chip_flat", ar_ok,
         f"all-reduce-family bytes/chip {ar} drift > {flat_rel_tol:.0%} "
         "(per-chip reduction results should be ~constant in chips)",
         value=ar)

    v1 = base.get("modeled_img_per_sec_per_chip")
    vm = [
        e.get("modeled_img_per_sec_per_chip") for e in multi
        if isinstance(e.get("modeled_img_per_sec_per_chip"), (int, float))
    ]
    # degradation is the failure mode; improvement (per-chip state
    # shrinks -> the bytes-bound roofline rises) is the point of the PR
    running_max = v1 if isinstance(v1, (int, float)) else 0.0
    img_ok = isinstance(v1, (int, float)) and len(vm) == len(multi)
    for v in vm:
        if v < running_max * (1.0 - flat_rel_tol):
            img_ok = False
            break
        running_max = max(running_max, v)
    gate("weakscale.img_per_sec_per_chip_no_degradation", img_ok,
         f"1-chip {v1} then {vm}: throughput/chip drops more than "
         f"{flat_rel_tol:.0%} below an earlier point on the curve",
         baseline_v=v1, value=vm)

    f1 = base.get("flops_per_chip_per_step")
    flops_ok = isinstance(f1, (int, float)) and f1 > 0 and all(
        isinstance(e.get("flops_per_chip_per_step"), (int, float))
        and abs(e["flops_per_chip_per_step"] - f1) <= flat_rel_tol * f1
        for e in multi
    )
    gate("weakscale.flops_per_chip_flat", flops_ok,
         "per-chip flops drift with chip count — per-chip work is not "
         "constant, so the curve is not weak scaling", baseline_v=f1)
    return {"ok": all(r["ok"] for r in rows), "checked": len(rows),
            "failed": sum(not r["ok"] for r in rows), "rows": rows}


def trust_gates(record: Dict[str, Any]) -> Dict[str, Any]:
    """Gate a committed trust-matrix report (trust/matrix.py ->
    evidence/trust_baseline.json) — the graceful-degradation acceptance
    criteria (ISSUE 15), RE-DERIVED from the record's RAW numbers (outcome
    counts, correct-on-answered counts, per-sample served scores), never
    from stored rate/AUROC fields, which would gate nothing:

      * every ID x OoD pair's AUROC re-derived from the raw served
        log p(x) scores must match the recorded value (tamper bound) AND
        clear the report's own committed floor;
      * OoD traffic abstains at least as often as clean ID, per pair;
      * along every corruption family's severity ladder, abstention rises
        monotonically (within the report's monotone_tol) and ends above
        the clean-ID rate — coverage degrades GRACEFULLY, not chaotically;
      * accuracy over answered (predict) outcomes holds above the
        committed floor at EVERY severity (vacuously at full abstention);
      * the clean-ID served-score sketch sits on the calibration's own
        quantile sketch (px divergence under the limit) — the serving
        path and the calibration describe the same distribution;
      * zero dropped requests (submitted == returned in every cell), zero
        steady-state recompiles, gate not degraded."""
    rows: List[Dict[str, Any]] = []

    def gate(key, ok, why="", baseline_v=None, value=None):
        rows.append({"key": key, "ok": bool(ok), "why": "" if ok else why,
                     "baseline": baseline_v, "value": value,
                     "direction": "trust"})

    def abstain_rate(cell) -> Optional[float]:
        """Re-derive abstention over the GATED outcomes from raw counts."""
        oc = cell.get("outcomes") or {}
        gated = (oc.get("predict") or 0) + (oc.get("abstain") or 0)
        return (oc.get("abstain") or 0) / gated if gated else None

    cfg = record.get("config") or {}
    gate("trust.schema",
         bool(record.get("trust_report")) and bool(record.get("id"))
         and bool(record.get("pairs")) and bool(record.get("ladder")),
         "not a trust report (missing trust_report/id/pairs/ladder)")
    gate("trust.zero_steady_recompiles",
         record.get("steady_state_recompiles") == 0,
         f"serving recompiled in steady state: "
         f"{record.get('steady_state_recompiles')}")
    gate("trust.not_degraded", record.get("degraded") is False,
         "engine served in degraded mode — the matrix measured an ungated "
         "path")

    # zero dropped: every cell answered exactly what was submitted
    dropped = []
    id_cell = record.get("id") or {}
    all_cells = [("id", id_cell)]
    all_cells += [(f"ood:{p.get('pair')}", p)
                  for p in record.get("pairs") or []]
    for kind, rows_k in (record.get("ladder") or {}).items():
        all_cells += [(f"{kind}:{c.get('severity')}", c) for c in rows_k]
    for name, cell in all_cells:
        if not (cell.get("submitted") == cell.get("returned")
                == cell.get("n")) or not cell.get("n"):
            dropped.append(name)
    gate("trust.zero_dropped", not dropped,
         f"cells with submitted != returned (or empty): {dropped}")

    div = id_cell.get("px_divergence")
    limit = cfg.get("px_divergence_limit")
    gate("trust.calibration_matches_serving",
         isinstance(div, (int, float)) and isinstance(limit, (int, float))
         and div <= limit,
         f"clean-ID served-score divergence {div} vs limit {limit} — the "
         "serving path is not the distribution the calibration measured",
         baseline_v=limit, value=div)

    # per-pair AUROC: re-derive from raw scores (jax-free midrank AUROC)
    from mgproto_tpu.trust.auroc import binary_auroc as _auroc

    id_scores = id_cell.get("scores") or []
    rtol = cfg.get("auroc_rederive_tol", 1e-9)
    floor = cfg.get("auroc_floor")
    id_rate = abstain_rate(id_cell)
    for p in record.get("pairs") or []:
        name = p.get("pair")
        scores = p.get("scores") or []
        recorded = p.get("auroc")
        derived = (
            _auroc(id_scores, scores) if id_scores and scores else None
        )
        gate(f"trust.auroc_rederives[{name}]",
             isinstance(recorded, (int, float)) and derived is not None
             and abs(derived - recorded) <= rtol,
             f"recorded AUROC {recorded} vs re-derived {derived} — the "
             "stored value does not follow from the raw scores",
             baseline_v=recorded, value=derived)
        gate(f"trust.auroc_floor[{name}]",
             derived is not None and isinstance(floor, (int, float))
             and derived >= floor,
             f"re-derived AUROC {derived} < committed floor {floor}",
             baseline_v=floor, value=derived)
        ood_rate = abstain_rate(p)
        gate(f"trust.ood_abstains_more[{name}]",
             id_rate is not None and ood_rate is not None
             and ood_rate >= id_rate,
             f"OoD abstention {ood_rate} < ID abstention {id_rate}",
             baseline_v=id_rate, value=ood_rate)

    # corruption ladder: monotone abstention + answered-accuracy floor,
    # all from raw counts
    tol = cfg.get("monotone_tol", 0.0)
    acc_floor = cfg.get("answered_accuracy_floor")
    for kind, rows_k in sorted((record.get("ladder") or {}).items()):
        rates = [abstain_rate(c) for c in rows_k]
        mono = (
            bool(rates) and all(r is not None for r in rates)
            and id_rate is not None
            and all(b >= a - tol for a, b in zip(rates, rates[1:]))
            # the tol absorbs between-rung sampling noise only: the
            # heaviest rung must STRICTLY never abstain less than clean
            # traffic (the documented contract)
            and rates[-1] >= id_rate
        )
        gate(f"trust.abstention_monotone[{kind}]", mono,
             f"abstention along severities {rates} (clean ID {id_rate}) "
             f"is not monotone within tol {tol}, or the heaviest rung "
             "abstains LESS than clean traffic — degradation is not "
             "graceful",
             baseline_v=id_rate, value=rates)
        accs, acc_ok = [], bool(rows_k)
        for c in rows_k:
            answered = c.get("answered") or 0
            correct = c.get("correct_answered")
            if answered == 0:
                accs.append(None)  # full abstention: risk is vacuous
                continue
            if not isinstance(correct, (int, float)):
                acc_ok = False
                accs.append(None)
                continue
            acc = correct / answered
            accs.append(round(acc, 4))
            if not (isinstance(acc_floor, (int, float))
                    and acc >= acc_floor):
                acc_ok = False
        gate(f"trust.answered_accuracy_floor[{kind}]", acc_ok,
             f"accuracy-on-answered {accs} drops below the committed "
             f"floor {acc_floor} somewhere on the ladder",
             baseline_v=acc_floor, value=accs)
    return {"ok": all(r["ok"] for r in rows), "checked": len(rows),
            "failed": sum(not r["ok"] for r in rows), "rows": rows}


def quant_gates(record: Dict[str, Any]) -> Dict[str, Any]:
    """Gate a committed int8-serving record (bench.py --measure quant ->
    evidence/quant_bench.json) — the ISSUE 20 acceptance criteria,
    RE-DERIVED from the record's RAW numbers (per-leaf byte rows,
    per-sample parity deltas, per-bucket planner terms, the two embedded
    trust-matrix reports' raw scores and outcome counts), never from
    stored ratio/AUROC/fit fields, which would gate nothing:

      * backbone weight bytes re-summed from the per-leaf rows must match
        the recorded totals (tamper bound) AND the f32/int8 ratio must
        clear the committed reduction floor (>= 3x);
      * parity maxima re-derived from the per-sample delta arrays
        (per-logit and log p(x), int8 program vs its dequantize-to-f32
        debug twin) must match the recorded maxima and sit inside the
        committed tolerance;
      * the serve-bucket ladder re-derived from each bucket's
        program-peak + weight-resident terms vs the shared budget must
        match the recorded fit lists, and the int8 ladder must be
        STRICTLY longer than the f32 one — the 4x weight shrink has to
        buy real batch headroom, and the recorded per-replica HBM drop
        must equal the weight-resident difference;
      * between the f32 and int8 trust matrices: every ID x OoD pair's
        AUROC re-derived from raw served scores in BOTH reports (each
        also matching its own recorded value), with |delta| inside the
        committed limit; answered accuracy per corruption cell re-derived
        from raw counts with |delta| inside its limit; the int8 clean-ID
        sketch still sits on its calibration (px divergence under limit);
      * the mismatch drill fired: the quant-skewed calibration tripped
        serving_quant_mismatch_total, the gate degraded, and verify_head
        rejected the swap with 'quant_mismatch' — fail-closed, observed;
      * zero steady-state recompiles in both embedded matrices."""
    rows: List[Dict[str, Any]] = []

    def gate(key, ok, why="", baseline_v=None, value=None):
        rows.append({"key": key, "ok": bool(ok), "why": "" if ok else why,
                     "baseline": baseline_v, "value": value,
                     "direction": "quant"})

    floors = record.get("floors") or {}
    weights = record.get("weights") or {}
    parity = record.get("parity") or {}
    planner = record.get("planner") or {}
    trust = record.get("trust") or {}
    drill = record.get("drill") or {}
    gate("quant.schema",
         record.get("metric") == "quant" and bool(weights.get("rows"))
         and bool(parity) and bool(planner) and bool(floors)
         and bool(trust.get("f32")) and bool(trust.get("int8"))
         and bool(drill),
         "not a quant record (missing metric/weights/parity/planner/"
         "floors/trust.f32/trust.int8/drill)")

    # --- weight bytes: re-sum the per-leaf rows, then the reduction floor
    leaf_rows = weights.get("rows") or []
    f32_sum = sum(int(r.get("f32_bytes") or 0) for r in leaf_rows)
    int8_sum = sum(int(r.get("quant_bytes") or 0) for r in leaf_rows)
    gate("quant.weight_rows_resum",
         leaf_rows and f32_sum == weights.get("f32_total")
         and int8_sum == weights.get("int8_total"),
         f"per-leaf rows re-sum to f32={f32_sum} int8={int8_sum} but the "
         f"record claims f32={weights.get('f32_total')} "
         f"int8={weights.get('int8_total')}",
         baseline_v=(weights.get("f32_total"), weights.get("int8_total")),
         value=(f32_sum, int8_sum))
    floor = floors.get("weight_reduction_min")
    reduction = (f32_sum / int8_sum) if int8_sum else None
    gate("quant.weight_reduction_floor",
         reduction is not None and isinstance(floor, (int, float))
         and reduction >= floor,
         f"re-derived weight-bytes reduction {reduction} < committed "
         f"floor {floor}",
         baseline_v=floor,
         value=round(reduction, 3) if reduction else reduction)

    # --- parity: maxima re-derived from the per-sample arrays
    tol = floors.get("tolerance")
    for key, recorded_key in (("logit_delta_max_per_sample",
                               "max_logit_delta"),
                              ("log_px_delta", "max_log_px_delta")):
        deltas = parity.get(key) or []
        derived = max((abs(float(d)) for d in deltas), default=None)
        recorded = parity.get(recorded_key)
        gate(f"quant.parity_rederives[{key}]",
             derived is not None and isinstance(recorded, (int, float))
             and abs(derived - recorded) <= 1e-12,
             f"recorded {recorded_key}={recorded} does not follow from "
             f"the {len(deltas)} per-sample deltas (re-derived {derived})",
             baseline_v=recorded, value=derived)
        gate(f"quant.parity_tolerance[{key}]",
             derived is not None and isinstance(tol, (int, float))
             and derived <= tol,
             f"int8-vs-dequantized-f32 delta {derived} exceeds the "
             f"committed tolerance {tol}",
             baseline_v=tol, value=derived)

    # --- planner ladder: re-derive fits from the recorded raw terms
    budget = planner.get("budget_bytes")
    fits: Dict[str, List[int]] = {}
    for variant in ("f32", "int8"):
        vrows = (planner.get(variant) or {}).get("rows") or []
        derived_fit = []
        resum_ok = bool(vrows) and isinstance(budget, (int, float))
        for r in vrows:
            total = (int(r.get("program_peak_bytes") or 0)
                     + int(r.get("weight_resident_bytes") or 0))
            if total != r.get("total_bytes"):
                resum_ok = False
            if isinstance(budget, (int, float)) and total <= budget:
                derived_fit.append(int(r.get("batch")))
        fits[variant] = derived_fit
        recorded_fit = planner.get(f"{variant}_buckets_fit")
        gate(f"quant.ladder_rederives[{variant}]",
             resum_ok and derived_fit == recorded_fit,
             f"fit list re-derived from peak+weight terms vs budget "
             f"{budget} is {derived_fit}, record claims {recorded_fit} "
             "(or a row's total_bytes does not equal its terms)",
             baseline_v=recorded_fit, value=derived_fit)
    gate("quant.ladder_grows",
         len(fits.get("int8") or []) > len(fits.get("f32") or []),
         f"int8 serve-bucket ladder {fits.get('int8')} is not longer than "
         f"f32 {fits.get('f32')} — quantization bought no batch headroom "
         "under the shared budget",
         baseline_v=fits.get("f32"), value=fits.get("int8"))
    drop = planner.get("per_replica_hbm_drop_bytes")
    w_f32 = (planner.get("f32") or {}).get("weight_resident_bytes")
    w_int8 = (planner.get("int8") or {}).get("weight_resident_bytes")
    gate("quant.hbm_drop_rederives",
         isinstance(w_f32, int) and isinstance(w_int8, int)
         and drop == w_f32 - w_int8 and drop > 0,
         f"recorded per-replica HBM drop {drop} != f32 weight-resident "
         f"{w_f32} - int8 {w_int8} (or not positive)",
         baseline_v=drop,
         value=(w_f32 - w_int8) if isinstance(w_f32, int)
         and isinstance(w_int8, int) else None)

    # --- trust deltas: both matrices re-derived, then compared
    from mgproto_tpu.trust.auroc import binary_auroc as _auroc

    def acc(cell) -> Optional[float]:
        answered = cell.get("answered") or 0
        correct = cell.get("correct_answered")
        if not answered or not isinstance(correct, (int, float)):
            return None
        return correct / answered

    reports = {v: trust.get(v) or {} for v in ("f32", "int8")}
    for variant, rep in reports.items():
        gate(f"quant.zero_steady_recompiles[{variant}]",
             rep.get("steady_state_recompiles") == 0,
             f"{variant} matrix recompiled in steady state: "
             f"{rep.get('steady_state_recompiles')}")
    aurocs: Dict[str, Dict[str, float]] = {"f32": {}, "int8": {}}
    rtol = (record.get("config") or {}).get("auroc_rederive_tol", 1e-9)
    for variant, rep in reports.items():
        id_scores = (rep.get("id") or {}).get("scores") or []
        for p in rep.get("pairs") or []:
            name = p.get("pair")
            derived = (
                _auroc(id_scores, p.get("scores") or [])
                if id_scores and p.get("scores") else None
            )
            recorded = p.get("auroc")
            gate(f"quant.auroc_rederives[{variant}:{name}]",
                 derived is not None
                 and isinstance(recorded, (int, float))
                 and abs(derived - recorded) <= rtol,
                 f"{variant} recorded AUROC {recorded} does not follow "
                 f"from the raw scores (re-derived {derived})",
                 baseline_v=recorded, value=derived)
            if derived is not None:
                aurocs[variant][name] = derived
    limit = floors.get("auroc_delta_limit")
    for name in sorted(aurocs["f32"]):
        a, b = aurocs["f32"].get(name), aurocs["int8"].get(name)
        delta = abs(a - b) if a is not None and b is not None else None
        gate(f"quant.auroc_delta[{name}]",
             delta is not None and isinstance(limit, (int, float))
             and delta <= limit,
             f"int8 shifts OoD AUROC by {delta} (f32 {a} vs int8 {b}), "
             f"outside the committed limit {limit}",
             baseline_v=limit, value=delta)
    acc_limit = floors.get("answered_accuracy_delta_limit")
    f32_ladder = reports["f32"].get("ladder") or {}
    int8_ladder = reports["int8"].get("ladder") or {}
    for kind in sorted(f32_ladder):
        cells_a = {c.get("severity"): c for c in f32_ladder.get(kind) or []}
        cells_b = {c.get("severity"): c
                   for c in int8_ladder.get(kind) or []}
        for sev in sorted(cells_a):
            a, b = acc(cells_a[sev]), acc(cells_b.get(sev) or {})
            # full abstention on either side makes the risk vacuous — the
            # trust suite's own monotone/floor gates cover that cell
            if a is None or b is None:
                continue
            delta = abs(a - b)
            gate(f"quant.answered_accuracy_delta[{kind}:{sev}]",
                 isinstance(acc_limit, (int, float)) and delta <= acc_limit,
                 f"int8 shifts accuracy-on-answered by {delta} "
                 f"(f32 {a} vs int8 {b}) at {kind}:{sev}, outside the "
                 f"committed limit {acc_limit}",
                 baseline_v=acc_limit, value=round(delta, 4))
    div = (reports["int8"].get("id") or {}).get("px_divergence")
    div_limit = floors.get("px_divergence_limit")
    gate("quant.int8_calibration_matches_serving",
         isinstance(div, (int, float))
         and isinstance(div_limit, (int, float)) and div <= div_limit,
         f"int8 clean-ID served-score divergence {div} vs limit "
         f"{div_limit} — the int8 serving path is not the distribution "
         "its calibration measured",
         baseline_v=div_limit, value=div)

    # --- mismatch drill: fail-closed must have been OBSERVED, not assumed
    gate("quant.mismatch_drill_counted",
         (drill.get("quant_mismatch_total") or 0) >= 1,
         "the quant-skewed calibration never tripped "
         "serving_quant_mismatch_total",
         baseline_v=1, value=drill.get("quant_mismatch_total"))
    gate("quant.mismatch_drill_degraded", drill.get("degraded") is True,
         "the gate did not degrade on quant-config mismatch")
    gate("quant.mismatch_drill_swap_rejected",
         drill.get("swap_reject") == "quant_mismatch",
         f"verify_head returned {drill.get('swap_reject')!r}, expected "
         "'quant_mismatch'",
         baseline_v="quant_mismatch", value=drill.get("swap_reject"))
    return {"ok": all(r["ok"] for r in rows), "checked": len(rows),
            "failed": sum(not r["ok"] for r in rows), "rows": rows}


def stall_report_gates(
    record: Dict[str, Any],
    baseline: Optional[Dict[str, Any]] = None,
    bytes_rel_tol: float = 0.05,
    hbm_abs_tol: float = 0.02,
) -> Dict[str, Any]:
    """Gate a stall-budget report (scripts/trace_report.py) — schema sanity
    alone, or byte/stall regression against a committed baseline report.

    With a baseline, the two reports must share a byte source (comparing
    XLA cost-analysis bytes against the hlo_model would gate noise) AND a
    comparable step time (fractions are fractions OF the reported step —
    a slower window dilutes hbm_bound into bubble, so gating across step
    times would pass real regressions), the new report's `bytes_accessed`
    must not exceed the baseline's by more than `bytes_rel_tol` (THE
    byte-regression gate: a change that quietly re-materializes trunk
    traffic fails here before it ever reaches a TPU window), and the
    hbm_bound fraction must not grow past the baseline's by more than
    `hbm_abs_tol`."""
    rows: List[Dict[str, Any]] = []

    def gate(key, ok, why="", baseline_v=None, value=None):
        rows.append({"key": key, "ok": bool(ok), "why": "" if ok else why,
                     "baseline": baseline_v, "value": value,
                     "direction": "stall"})

    gate("stall.schema", bool(record.get("stall_report")),
         "not a stall report (missing stall_report marker)")
    frac_sum = record.get("fraction_sum")
    gate("stall.fractions_sum_to_one",
         isinstance(frac_sum, (int, float)) and abs(frac_sum - 1.0) < 1e-4,
         f"fraction_sum={frac_sum}")
    movers = (record.get("top_byte_movers") or {}).get("rows")
    gate("stall.top_byte_movers_present", bool(movers),
         "report carries no ranked top_byte_movers rows")
    if baseline is not None:
        b_src = (baseline.get("byte_source"), baseline.get("source"))
        n_src = (record.get("byte_source"), record.get("source"))
        gate("stall.byte_source_matches", b_src == n_src,
             f"baseline measured via {b_src}, new via {n_src}",
             baseline_v=str(b_src), value=str(n_src))
        b_t = baseline.get("step_time_s")
        n_t = record.get("step_time_s")
        if isinstance(b_t, (int, float)) and isinstance(n_t, (int, float)):
            gate("stall.step_time_comparable",
                 abs(n_t - b_t) <= 0.05 * abs(b_t),
                 f"step_time_s {n_t:.4g} vs baseline {b_t:.4g} — "
                 "fractions are not comparable across step times; "
                 "regenerate at the baseline's measured step",
                 baseline_v=b_t, value=n_t)
        else:
            gate("stall.step_time_comparable", False,
                 "step_time_s missing from report or baseline",
                 baseline_v=b_t, value=n_t)
        b_bytes = baseline.get("bytes_accessed")
        n_bytes = record.get("bytes_accessed")
        if isinstance(b_bytes, (int, float)) and isinstance(
                n_bytes, (int, float)):
            allowed = b_bytes * (1.0 + bytes_rel_tol)
            gate("stall.bytes_accessed", n_bytes <= allowed,
                 f"{n_bytes:.4g} > allowed {allowed:.4g}",
                 baseline_v=b_bytes, value=n_bytes)
        else:
            gate("stall.bytes_accessed", False,
                 "bytes_accessed missing from report or baseline",
                 baseline_v=b_bytes, value=n_bytes)
        b_hbm = ((baseline.get("buckets") or {}).get("hbm_bound")
                 or {}).get("fraction")
        n_hbm = ((record.get("buckets") or {}).get("hbm_bound")
                 or {}).get("fraction")
        if isinstance(b_hbm, (int, float)) and isinstance(
                n_hbm, (int, float)):
            gate("stall.hbm_bound_fraction", n_hbm <= b_hbm + hbm_abs_tol,
                 f"{n_hbm:.4f} > allowed {b_hbm + hbm_abs_tol:.4f}",
                 baseline_v=b_hbm, value=n_hbm)
        else:
            gate("stall.hbm_bound_fraction", False,
                 "hbm_bound fraction missing", baseline_v=b_hbm,
                 value=n_hbm)
    return {"ok": all(r["ok"] for r in rows), "checked": len(rows),
            "failed": sum(not r["ok"] for r in rows), "rows": rows}


def _lookup(summary: Dict[str, Any], dotted: str):
    node: Any = summary
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def build_baseline(summary: Dict[str, Any], gates=None) -> Dict[str, Any]:
    """A baseline record from a known-good run's summary: every gate whose
    key holds a number, frozen with its direction + band. Gate specs are
    (key, direction, rel_tol[, abs_tol]) tuples."""
    entries = []
    for spec in (DEFAULT_GATES if gates is None else gates):
        key, direction, rel_tol = spec[0], spec[1], spec[2]
        abs_tol = spec[3] if len(spec) > 3 else 0.0
        value = _lookup(summary, key)
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        entries.append({
            "key": key,
            "value": float(value),
            "direction": direction,
            "rel_tol": rel_tol,
            "abs_tol": abs_tol,
        })
    return {
        "telemetry_check_baseline": True,
        "telemetry_dir": summary.get("telemetry_dir"),
        "entries": entries,
    }


def check_entry(entry: Dict[str, Any], summary: Dict[str, Any]) -> Dict:
    """One gate: {key, baseline, value, allowed, ok, why}."""
    key = entry["key"]
    base = float(entry["value"])
    direction = entry.get("direction", "lower")
    rel = float(entry.get("rel_tol", 0.0))
    abs_tol = float(entry.get("abs_tol", 0.0))
    value = _lookup(summary, key)
    row = {"key": key, "baseline": base, "value": value,
           "direction": direction}
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        row.update(ok=False, why="metric missing from run summary")
        return row
    value = float(value)
    if direction == "higher":
        allowed = base * (1.0 - rel) - abs_tol
        ok = value >= allowed
        why = "" if ok else f"{value:.6g} < allowed {allowed:.6g}"
    elif direction == "lower":
        allowed = base * (1.0 + rel) + abs_tol
        ok = value <= allowed
        why = "" if ok else f"{value:.6g} > allowed {allowed:.6g}"
    elif direction == "equal":
        allowed = abs(base) * rel + abs_tol
        ok = abs(value - base) <= allowed
        why = "" if ok else f"|{value:.6g} - {base:.6g}| > {allowed:.6g}"
    else:
        row.update(ok=False, why=f"unknown direction {direction!r}")
        return row
    row.update(allowed=allowed, ok=ok, why=why)
    return row


def check(summary: Dict[str, Any], baseline: Dict[str, Any]) -> Dict:
    """Every baseline entry checked; {'ok': bool, 'rows': [...]}."""
    entries = baseline.get("entries", [])
    rows = [check_entry(e, summary) for e in entries]
    return {"ok": all(r["ok"] for r in rows), "checked": len(rows),
            "failed": sum(not r["ok"] for r in rows), "rows": rows}


def _print_gate_result(result: Dict[str, Any], json_mode: bool) -> None:
    """Render one gate-suite result ({ok, checked, failed, rows}) — the
    shared formatter of the stall-report and drift-drill branches."""
    if json_mode:
        print(json.dumps(result, indent=2))
        return
    width = max(len(r["key"]) for r in result["rows"])
    for r in result["rows"]:
        status = "ok  " if r["ok"] else "FAIL"
        detail = f" ({r['why']})" if r["why"] else ""
        print(f"{status} {r['key']:<{width}}{detail}")
    print(f"{result['checked']} checked, {result['failed']} failed")


def check_main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="mgproto-telemetry check",
        description="Gate a telemetry dir against a committed baseline "
                    "(exit 0 = within tolerance, 1 = regression)",
    )
    p.add_argument("dir", nargs="?", default=None,
                   help="telemetry dir (or a run dir containing "
                        "telemetry/); optional with --drift-drill")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON (generate with --write-baseline "
                        "from a known-good run, then commit it)")
    p.add_argument("--drift-drill", default=None, metavar="FILE",
                   help="gate a committed drift-drill record (e.g. "
                        "evidence/drift_drill.json): detection-before-"
                        "correction, zero drops/recompiles, poison "
                        "rejection, accuracy dip+recovery — exit 1 on any "
                        "failure")
    p.add_argument("--autoscale", default=None, metavar="FILE",
                   help="gate a committed autoscale load-test record "
                        "(e.g. evidence/autoscale_baseline.json): "
                        "scale-out under the ramp, AOT-cached scale-up "
                        "warmups, p99 flat band, bounded shed, zero-drop "
                        "scale-down — exit 1 on any failure")
    p.add_argument("--tenants", default=None, metavar="FILE",
                   help="gate a committed multi-tenant isolation record "
                        "(load_test.py --tenants N -> evidence/"
                        "tenant_baseline.json): quota storm sheds only "
                        "the storm tenant, quiet p99 flat, bad swap "
                        "fail-closed per tenant, mid-storm mount with "
                        "zero trunk compiles, drift isolation — exit 1 "
                        "on any failure")
    p.add_argument("--weakscale", default=None, metavar="FILE",
                   help="gate a committed weak-scaling record (bench.py "
                        "--measure weakscale -> evidence/weakscale_bench"
                        ".json): bank/optimizer bytes per chip shrink "
                        "~1/model_axis (>=1.8x at model=2), collective "
                        "bytes/chip and img/s/chip flat within tolerance, "
                        "planner prediction == live shard shapes — every "
                        "verdict re-derived from raw numbers; exit 1 on "
                        "any failure")
    p.add_argument("--trust", default=None, metavar="FILE",
                   help="gate a committed trust-matrix report (trust/"
                        "matrix.py -> evidence/trust_baseline.json): "
                        "per-pair OoD AUROC re-derived from raw scores "
                        ">= the committed floor, abstention monotone in "
                        "corruption severity, answered-accuracy >= floor "
                        "at every severity, calibration-vs-serving sketch "
                        "agreement, zero dropped requests, zero steady-"
                        "state recompiles — exit 1 on any failure")
    p.add_argument("--quant", default=None, metavar="FILE",
                   help="gate a committed int8-serving record (bench.py "
                        "--measure quant -> evidence/quant_bench.json): "
                        "backbone weight bytes re-summed from per-leaf "
                        "rows with >=3x reduction, int8-vs-dequantized "
                        "parity maxima re-derived inside tolerance, "
                        "serve-bucket ladder re-derived from raw peak+"
                        "weight terms and strictly longer under int8, "
                        "f32-vs-int8 trust-matrix AUROC/accuracy deltas "
                        "re-derived from raw scores inside committed "
                        "limits, quant-mismatch drill fail-closed, zero "
                        "steady-state recompiles — exit 1 on any failure")
    p.add_argument("--stall-report", default=None, metavar="FILE",
                   help="gate a stall-budget report (scripts/"
                        "trace_report.py output): schema sanity, and with "
                        "--stall-baseline the byte-regression gate — "
                        "bytes_accessed and the hbm_bound fraction must "
                        "not grow past the committed report's band")
    p.add_argument("--stall-baseline", default=None, metavar="FILE",
                   help="committed stall report to gate --stall-report "
                        "against (e.g. evidence/stall_report_b256_bf16"
                        ".json)")
    p.add_argument("--write-baseline", action="store_true",
                   help="summarize the dir and WRITE --baseline from it "
                        "(no checking)")
    p.add_argument("--fleet-gates", action="store_true",
                   help="with --write-baseline: freeze the FLEET gate set "
                        "(max skew / barrier-wait fraction, per-chip "
                        "allgather bytes) instead of the single-run "
                        "defaults — the evidence/fleet_baseline.json "
                        "workflow")
    p.add_argument("--json", action="store_true",
                   help="emit the check result as one JSON object")
    args = p.parse_args(argv)
    # `--json` must emit ONE JSON document however many gate suites run:
    # json-mode suite results are deferred into this dict and flushed once
    # at every exit point (a single suite prints its bare result object —
    # the pre-existing contract for `check DIR --baseline --json`)
    json_suites: Dict[str, Dict[str, Any]] = {}

    def _emit_suite(name: str, result: Dict[str, Any]) -> None:
        if args.json:
            json_suites[name] = result
        else:
            _print_gate_result(result, False)

    def _flush_json() -> None:
        if not args.json or not json_suites:
            return
        if len(json_suites) == 1:
            print(json.dumps(next(iter(json_suites.values())), indent=2))
        else:
            print(json.dumps(json_suites, indent=2))

    def _read_json(path, what):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise SystemExit(f"cannot read {what} {path}: {e}")

    suites_ok = True
    any_suite = False
    if args.stall_report:
        any_suite = True
        record = _read_json(args.stall_report, "stall report")
        baseline_rep = (
            _read_json(args.stall_baseline, "stall baseline")
            if args.stall_baseline else None
        )
        result = stall_report_gates(record, baseline_rep)
        _emit_suite("stall_report", result)
        suites_ok = suites_ok and result["ok"]
    if args.drift_drill:
        any_suite = True
        record = _read_json(args.drift_drill, "drift-drill record")
        result = drift_drill_gates(record)
        _emit_suite("drift_drill", result)
        suites_ok = suites_ok and result["ok"]
    if args.trust:
        any_suite = True
        record = _read_json(args.trust, "trust report")
        result = trust_gates(record)
        _emit_suite("trust", result)
        suites_ok = suites_ok and result["ok"]
    if args.autoscale:
        any_suite = True
        record = _read_json(args.autoscale, "autoscale record")
        result = autoscale_gates(record)
        _emit_suite("autoscale", result)
        suites_ok = suites_ok and result["ok"]
    if args.tenants:
        any_suite = True
        record = _read_json(args.tenants, "tenant record")
        result = tenant_gates(record)
        _emit_suite("tenants", result)
        suites_ok = suites_ok and result["ok"]
    if args.weakscale:
        any_suite = True
        record = _read_json(args.weakscale, "weakscale record")
        result = weakscale_gates(record)
        _emit_suite("weakscale", result)
        suites_ok = suites_ok and result["ok"]
    if args.quant:
        any_suite = True
        record = _read_json(args.quant, "quant record")
        result = quant_gates(record)
        _emit_suite("quant", result)
        suites_ok = suites_ok and result["ok"]
    if args.dir is None and any_suite:
        _flush_json()
        return 0 if suites_ok else 1
    if args.dir is None or args.baseline is None:
        raise SystemExit(
            "check needs a telemetry dir AND --baseline (or --drift-drill "
            "/ --stall-report / --autoscale / --tenants / --weakscale / "
            "--trust / --quant FILE alone)"
        )
    if not os.path.isdir(args.dir):
        raise SystemExit(f"not a directory: {args.dir}")
    summary = summarize(args.dir)
    # fleet aggregates ride along only when the dir shows an actual FLEET
    # (>= 2 host streams): a single-host run checked against a fleet
    # baseline then fails LOUDLY on every fleet.* key ("metric missing")
    # instead of passing vacuously on its pre-registered zeros. The cheap
    # file probe gates the full sidecar parse — an ordinary single-host
    # check never re-reads its metric stream for a fleet nobody has.
    if len(_host_metric_files(resolve_dir(args.dir))) > 1:
        summary["fleet"] = fleet_summary(args.dir)["fleet"]
    if args.write_baseline:
        baseline = build_baseline(
            summary, gates=FLEET_GATES if args.fleet_gates else None
        )
        if not baseline["entries"]:
            # an empty baseline would make every later check pass
            # vacuously ('checked: 0' is ok=True) — the fleet gate would
            # be silently disabled forever. Refuse instead.
            raise SystemExit(
                "refusing to write an EMPTY baseline: no gate key resolved "
                "to a number in this summary"
                + (" (fleet.* gates need >= 2 host metric streams — did "
                   "the drill write its sidecars into this dir?)"
                   if args.fleet_gates else "")
            )
        with open(args.baseline, "w") as f:
            json.dump(baseline, f, indent=2, sort_keys=True)
        print(f"wrote {len(baseline['entries'])} gate entries to "
              f"{args.baseline}")
        # writing a baseline skips the dir CHECK, but any gate suite that
        # already ran (--stall-report / --drift-drill) still decides the
        # exit code — and its deferred --json output still flushes
        _flush_json()
        return 0 if suites_ok else 1
    try:
        with open(args.baseline) as f:
            baseline = json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(f"cannot read baseline {args.baseline}: {e}")
    if not baseline.get("telemetry_check_baseline"):
        raise SystemExit(
            f"{args.baseline} is not a telemetry check baseline "
            "(generate one with --write-baseline)"
        )
    result = check(summary, baseline)
    if args.json:
        json_suites["baseline"] = result
        _flush_json()
    else:
        width = max((len(r["key"]) for r in result["rows"]), default=3)
        for r in result["rows"]:
            status = "ok  " if r["ok"] else "FAIL"
            detail = f" ({r['why']})" if r["why"] else ""
            print(f"{status} {r['key']:<{width}}  "
                  f"base={_fmt(r['baseline'])} new={_fmt(r['value'])}"
                  f"{detail}")
        print(f"{result['checked']} checked, {result['failed']} failed")
    return 0 if result["ok"] and suites_ok else 1


def main(argv: Optional[list] = None) -> Optional[int]:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    # subcommand dispatch with bare-directory back-compat:
    # `mgproto-telemetry <dir>` keeps meaning summarize
    if argv and argv[0] == "check":
        return check_main(argv[1:])
    if argv and argv[0] == "fleet":
        return fleet_main(argv[1:])
    if argv and argv[0] == "summarize":
        argv = argv[1:]
    p = argparse.ArgumentParser(
        description="Summarize an mgproto-tpu telemetry directory "
                    "(subcommands: summarize [default], fleet, check)"
    )
    p.add_argument("dir", help="telemetry dir (or a run dir containing "
                               "telemetry/)")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as one JSON object")
    args = p.parse_args(argv)
    if not os.path.isdir(args.dir):
        raise SystemExit(f"not a directory: {args.dir}")
    summary = summarize(args.dir)
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(render_table(summary))
    return None


if __name__ == "__main__":
    raise SystemExit(main())
