"""Trust-verification driver: the robustness matrix, sharded
interpretability, and the merged trust report (ISSUE 15).

    mgproto-trust matrix --synthetic --out evidence/trust_baseline.json
    mgproto-trust matrix --artifact model.mgproto --test_dir ... --ood_dir ...
    mgproto-trust interp --cub_root CUB_200_2011 --model_dir run/ --out interp.json
    mgproto-trust report trust_report.json            # render verdicts
    mgproto-trust report --matrix m.json --interp i.json --out merged.json

`matrix --synthetic` is the hermetic CPU drill (the committed
evidence/trust_baseline.json): a tiny model whose mixture is fitted
through the PRODUCTION consolidation path (no backprop — the online
drill's bootstrap idiom), calibrated through the production calibrate()
path, served through a warmed `ServingEngine` — so every number in the
committed record went through the exact code a production deployment
runs. Seeded and deterministic; no dataset, no network, no TPU.

Every verdict the matrix derives is RE-derived from the report's raw
numbers by `mgproto-telemetry check --trust` (cli/telemetry.py::
trust_gates) — the committed record gates regressions like every other
evidence file.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, Optional

import numpy as np


# ------------------------------------------------------------ hermetic drill
def _pattern(cls: int, img: int, drift: float = 0.0,
             channel: float = 1.0) -> np.ndarray:
    """Deterministic class texture (the load_test.py generator idiom):
    oriented wave + per-class channel balance. `drift` rotates the texture
    off the trained manifold; `channel=-2.0` is the measured off-manifold
    inversion this toy backbone's p(x) actually collapses on."""
    xx, yy = np.meshgrid(np.arange(img), np.arange(img), indexing="ij")
    ang = (cls * 45.0 + drift * 30.0) * np.pi / 180.0
    wave = np.cos(
        2.0 * np.pi * (cls + 1)
        * (xx * np.cos(ang) + yy * np.sin(ang)) / float(img)
    )
    base = np.repeat(wave[..., None].astype(np.float32), 3, axis=2)
    base[..., cls % 3] += channel
    base[..., (cls + 1) % 3] += drift * 0.6
    return base


def _samples(rng, cls: int, img: int, count: int, drift: float = 0.0,
             channel: float = 1.0, noise: float = 0.05) -> np.ndarray:
    base = _pattern(cls, img, drift, channel)
    return np.stack([
        base + rng.randn(img, img, 3).astype(np.float32) * noise
        for _ in range(count)
    ])


def run_synthetic_matrix(
    seed: int = 0,
    classes: int = 4,
    per_class: int = 16,
    bootstrap_epochs: int = 20,
    bootstrap_per_class: int = 8,
    percentile: float = 5.0,
    config_overrides: Optional[Dict] = None,
) -> Dict:
    """The hermetic drill as a report dict (trust_baseline.json schema:
    evidence/README.md). Importable — tests run the acceptance drill
    through this exact function."""
    import jax

    from mgproto_tpu.config import tiny_test_config
    from mgproto_tpu.engine.train import Trainer
    from mgproto_tpu.online.capture import CapturedSample
    from mgproto_tpu.online.consolidate import Consolidator, ConsolidatorConfig
    from mgproto_tpu.serving.calibration import calibrate
    from mgproto_tpu.serving.engine import ServingEngine
    from mgproto_tpu.trust.matrix import MatrixConfig, run_matrix

    import dataclasses as _dc

    cfg = tiny_test_config(num_classes=classes)
    # drill-scale EM mean step so the production consolidation path
    # converges in a few bootstrap passes (the load_test.py drill idiom)
    cfg = cfg.replace(em=_dc.replace(cfg.em, mean_lr=0.05))
    trainer = Trainer(cfg, steps_per_epoch=1)
    state = trainer.init_state(jax.random.PRNGKey(seed))
    img = cfg.model.img_size
    rng = np.random.RandomState(seed + 11)

    # hermetic bootstrap: labeled class textures through the PRODUCTION
    # consolidation program (memory_push + compact EM — no backprop), so
    # served accuracy below is real, not decorative
    cons = Consolidator(
        trainer, state,
        config=ConsolidatorConfig(cadence_s=1.0, batch_width=8),
        clock=lambda: 0.0,
    )
    for _ in range(int(bootstrap_epochs)):
        for c in range(classes):
            cons.ingest([
                CapturedSample(p, c, None, "bootstrap", True)
                for p in _samples(rng, c, img, bootstrap_per_class)
            ])
    state = cons.candidate_state(state)

    # calibration through the production path (same eval program serving
    # uses), on a held-out ID draw
    calib_batches = [
        (_samples(rng, c, img, 8), np.full((8,), c, np.int32))
        for c in range(classes) for _ in range(2)
    ]
    calib = calibrate(trainer, state, calib_batches,
                      percentile=percentile, source="trust-drill")

    engine = ServingEngine.from_live(
        trainer, state, calibration=calib, buckets=(1, 2, 4, 8),
    )
    engine.warmup()

    # evaluation sets: fresh ID draws + three OoD families
    id_parts, id_labels = [], []
    for c in range(classes):
        id_parts.append(_samples(rng, c, img, per_class))
        id_labels.append(np.full((per_class,), c, np.int32))
    id_images = np.concatenate(id_parts)
    id_labels = np.concatenate(id_labels)
    # OoD families chosen along the directions this toy's generative
    # score ACTUALLY collapses on — structural/channel departures from
    # the trained manifold. Additive uniform noise is deliberately NOT a
    # pair: a random untrained backbone scores pure noise HIGH p(x)
    # (measured in PR 11, which picked channel inversion as its poison
    # for the same reason), so it would gate the toy's blindness, not the
    # serving path.
    checker = np.tile(
        ((np.indices((img, img)).sum(0) % 2).astype(np.float32) * 2.0
         - 1.0)[..., None],
        (1, 1, 3),
    )
    ood_sets = {
        # channel inversion (far-OoD): the measured off-manifold direction
        "inverted": np.concatenate([
            _samples(rng, c, img, per_class // 2, channel=-2.0)
            for c in range(classes)
        ]),
        # class channel cue removed (near-OoD structural shift)
        "dimmed": np.concatenate([
            _samples(rng, c, img, per_class // 2, channel=0.0)
            for c in range(classes)
        ]),
        # alien periodic texture (far-OoD)
        "checker": np.stack([
            checker
            + rng.randn(img, img, 3).astype(np.float32) * 0.05
            for _ in range(classes * (per_class // 2))
        ]),
    }

    # drill bars: committed MEASURED properties of this seeded toy (a
    # random untrained backbone — chance accuracy 1/classes), not the
    # production defaults. A real trained model's report pins far higher
    # floors; what is gated here is the MACHINERY: every verdict below
    # re-derives from raw numbers and a tampered record fails.
    overrides = {
        "auroc_floor": 0.85,
        "answered_accuracy_floor": 0.30,
        "monotone_tol": 0.05,
        **(config_overrides or {}),
    }
    mc = MatrixConfig(seed=seed, **overrides)
    report = run_matrix(engine, id_images, id_labels, ood_sets, mc)
    report["synthetic_drill"] = {
        "seed": int(seed),
        "classes": int(classes),
        "per_class": int(per_class),
        "bootstrap_epochs": int(bootstrap_epochs),
        "arch": cfg.model.arch,
        "img_size": int(img),
    }
    return report


# --------------------------------------------------------------- real matrix
def _loader_arrays(loader, max_samples: int):
    """Drain a loader into bounded host arrays (images, labels|None),
    dropping padded sentinel rows (label -1)."""
    images, labels, have_labels = [], [], False
    n = 0
    for batch in loader:
        if isinstance(batch, tuple):
            imgs, lbls = batch[0], batch[1]
            have_labels = True
        else:
            imgs, lbls = batch, None
        imgs = np.asarray(imgs, np.float32)
        if lbls is not None:
            valid = np.asarray(lbls) >= 0
            imgs, lbls = imgs[valid], np.asarray(lbls)[valid]
            labels.append(lbls)
        images.append(imgs)
        n += len(imgs)
        if n >= max_samples:
            break
    imgs = np.concatenate(images)[:max_samples]
    lbls = (
        np.concatenate(labels)[:max_samples] if have_labels else None
    )
    return imgs, lbls


def matrix_main(argv=None) -> int:
    from mgproto_tpu.cli.common import add_train_args

    p = argparse.ArgumentParser(
        prog="mgproto-trust matrix",
        description="Serving-path robustness matrix: ID x OoD pairs + "
                    "corruption ladder through the calibrated engine",
    )
    add_train_args(p)
    p.add_argument("--synthetic", action="store_true",
                   help="hermetic CPU drill (tiny model, production "
                        "consolidation bootstrap, seeded) — the "
                        "evidence/trust_baseline.json generator")
    p.add_argument("--artifact", default="",
                   help="serve a calibrated .mgproto artifact instead of "
                        "a checkpoint")
    p.add_argument("--checkpoint", default="auto",
                   help="checkpoint path ('auto' = latest in --model_dir)")
    p.add_argument("--max_samples", type=int, default=512,
                   help="cap per matrix cell (bounded eval memory)")
    p.add_argument("--classes", type=int, default=4,
                   help="synthetic drill: generator classes")
    p.add_argument("--per_class", type=int, default=16,
                   help="synthetic drill: eval samples per class")
    p.add_argument("--percentile", type=float, default=5.0,
                   help="abstention operating point (ID percentile)")
    p.add_argument("--out", default="trust_report.json",
                   help="report path (telemetry dirs are summarized by "
                        "mgproto-telemetry; evidence/trust_baseline.json "
                        "is the committed drill)")
    args = p.parse_args(argv)

    if args.synthetic:
        report = run_synthetic_matrix(
            seed=args.seed, classes=args.classes,
            per_class=args.per_class, percentile=args.percentile,
        )
    else:
        import jax

        from mgproto_tpu.cli.common import config_from_args
        from mgproto_tpu.data import build_pipelines
        from mgproto_tpu.serving.engine import ServingEngine
        from mgproto_tpu.trust.matrix import MatrixConfig, run_matrix

        cfg = config_from_args(args)
        _, _, test_loader, ood_loaders = build_pipelines(cfg)
        id_images, id_labels = _loader_arrays(test_loader, args.max_samples)
        ood_sets = {}
        for i, ld in enumerate(ood_loaders, start=1):
            name = (
                os.path.basename(cfg.data.ood_dirs[i - 1].rstrip("/"))
                if i <= len(cfg.data.ood_dirs) else f"ood{i}"
            )
            ood_sets[name], _ = _loader_arrays(ld, args.max_samples)
        if not ood_sets:
            raise SystemExit(
                "no OoD sets: pass --ood_dir (repeatable) or --synthetic"
            )
        if args.artifact:
            engine = ServingEngine.from_artifact(args.artifact)
        else:
            from mgproto_tpu.engine.train import Trainer
            from mgproto_tpu.serving.calibration import calibrate
            from mgproto_tpu.utils import (
                latest_checkpoint,
                restore_checkpoint,
            )
            from mgproto_tpu.utils.checkpoint import (
                adopt_checkpoint_train_config,
            )

            path = (
                latest_checkpoint(cfg.model_dir)
                if args.checkpoint == "auto" else args.checkpoint
            )
            if not path:
                raise FileNotFoundError(
                    f"no checkpoint found in {cfg.model_dir}"
                )
            cfg = adopt_checkpoint_train_config(cfg, path, log=print)
            trainer = Trainer(cfg, steps_per_epoch=1)
            state = trainer.init_state(
                jax.random.PRNGKey(cfg.seed), for_restore=True
            )
            state = restore_checkpoint(path, state)
            calib = calibrate(
                trainer, state, test_loader, percentile=args.percentile,
                source=f"trust-matrix test_dir={cfg.data.test_dir}",
            )
            engine = ServingEngine.from_live(
                trainer, state, calibration=calib
            )
        report = run_matrix(
            engine, id_images, id_labels, ood_sets,
            MatrixConfig(seed=args.seed),
        )

    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    gates = report.get("gates") or {}
    print(json.dumps({
        "report": args.out,
        "pairs": {p["pair"]: round(p["auroc"], 4)
                  for p in report.get("pairs", [])},
        "steady_state_recompiles": report.get("steady_state_recompiles"),
        "gates_checked": gates.get("checked"),
        "gates_failed": gates.get("failed"),
    }))
    return 0 if gates.get("ok", False) else 1


# -------------------------------------------------------------------- interp
def interp_main(argv=None) -> int:
    from mgproto_tpu.cli.common import add_train_args

    p = argparse.ArgumentParser(
        prog="mgproto-trust interp",
        description="Sharded consistency/stability/purity over a "
                    "checkpoint + CUB-layout parts tree "
                    "(trust/interp_sharded.py)",
    )
    add_train_args(p)
    p.add_argument("--cub_root", required=True,
                   help="CUB_200_2011-layout root (images.txt, parts/)")
    p.add_argument("--checkpoint", default="auto")
    p.add_argument("--half_size", type=int, default=36)
    p.add_argument("--purity_half_size", type=int, default=16)
    p.add_argument("--top_k", type=int, default=10)
    p.add_argument("--noise_seed", type=int, default=0)
    p.add_argument("--out", default="trust_interp.json")
    args = p.parse_args(argv)

    import jax

    from mgproto_tpu.cli.common import config_from_args
    from mgproto_tpu.cli.interpret import build_eval_loader
    from mgproto_tpu.data.cub_parts import CubParts
    from mgproto_tpu.parallel import ShardedTrainer
    from mgproto_tpu.trust.interp_sharded import interp_metrics_sharded
    from mgproto_tpu.utils import latest_checkpoint, restore_checkpoint
    from mgproto_tpu.utils.checkpoint import adopt_checkpoint_train_config

    cfg = config_from_args(args)
    path = (
        latest_checkpoint(cfg.model_dir)
        if args.checkpoint == "auto" else args.checkpoint
    )
    if not path:
        raise FileNotFoundError(f"no checkpoint found in {cfg.model_dir}")
    cfg = adopt_checkpoint_train_config(cfg, path, log=print)
    trainer = ShardedTrainer(cfg, steps_per_epoch=1)
    state = trainer.init_state(jax.random.PRNGKey(cfg.seed), for_restore=True)
    state = trainer.prepare(restore_checkpoint(path, state))
    parts = CubParts(args.cub_root)
    loader_factory = (  # fresh iterator per metric pass
        lambda: iter(build_eval_loader(cfg, args.cub_root))
    )
    metrics = interp_metrics_sharded(
        trainer, state, loader_factory, parts, cfg.model.num_classes,
        consistency_half_size=args.half_size,
        purity_half_size=args.purity_half_size,
        top_k=args.top_k, noise_seed=args.noise_seed,
    )
    record = {
        "trust_interp": True,
        "checkpoint": path,
        **metrics,
    }
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
    print(json.dumps(record))
    return 0


# -------------------------------------------------------------------- report
def report_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="mgproto-trust report",
        description="Merge matrix + interp records into one trust report "
                    "(or render an existing one's verdicts)",
    )
    p.add_argument("report", nargs="?", default=None,
                   help="existing trust_report.json to render")
    p.add_argument("--matrix", default=None,
                   help="matrix record to merge")
    p.add_argument("--interp", default=None,
                   help="interp record to merge into the matrix record")
    p.add_argument("--out", default=None,
                   help="write the merged report here")
    args = p.parse_args(argv)

    if args.report and not (args.matrix or args.interp):
        with open(args.report) as f:
            record = json.load(f)
    elif args.matrix:
        with open(args.matrix) as f:
            record = json.load(f)
        if args.interp:
            with open(args.interp) as f:
                interp = json.load(f)
            record["interp"] = {
                k: v for k, v in interp.items() if k != "trust_interp"
            }
            # merged content invalidates the stored self-gate: re-derive
            from mgproto_tpu.cli.telemetry import trust_gates

            record["gates"] = trust_gates(record)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(record, f, indent=2, sort_keys=True)
    else:
        raise SystemExit("pass a report path, or --matrix [--interp]")

    from mgproto_tpu.cli.telemetry import _print_gate_result, trust_gates

    result = trust_gates(record)
    _print_gate_result(result, False)
    if record.get("interp"):
        print("interp: " + " ".join(
            f"{k}={v}" for k, v in sorted(record["interp"].items())
        ))
    return 0 if result["ok"] else 1


def main(argv: Optional[list] = None) -> Optional[int]:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "matrix":
        return matrix_main(argv[1:])
    if argv and argv[0] == "interp":
        return interp_main(argv[1:])
    if argv and argv[0] == "report":
        return report_main(argv[1:])
    print("usage: mgproto-trust {matrix|interp|report} [options]\n"
          "  matrix --synthetic --out evidence/trust_baseline.json\n"
          "  matrix --artifact M.mgproto --test_dir D --ood_dir O\n"
          "  interp --cub_root CUB --model_dir RUN --out interp.json\n"
          "  report trust_report.json")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
